"""Torch7 .t7 serialization tests (reference model: TorchFile round-trips
via TH.run in torch/ specs; here: self round-trip of the binary format +
model conversion fidelity)."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils import torch_file as t7
from bigdl_tpu.utils.torch_file import TorchObject


def test_primitive_roundtrip(tmp_path):
    p = str(tmp_path / "x.t7")
    for obj in [None, True, False, 3, 2.5, "hello"]:
        t7.save(p, obj)
        assert t7.load(p) == obj


def test_tensor_roundtrip(tmp_path):
    p = str(tmp_path / "t.t7")
    for dtype in (np.float32, np.float64, np.int64, np.int32, np.uint8):
        x = (np.random.rand(3, 4, 5) * 100).astype(dtype)
        t7.save(p, x)
        y = t7.load(p)
        assert y.dtype == dtype
        np.testing.assert_array_equal(x, y)


def test_table_roundtrip(tmp_path):
    p = str(tmp_path / "tab.t7")
    obj = {"a": 1, "b": [1.0, 2.0, "three"],
           "t": np.arange(6, dtype=np.float32).reshape(2, 3)}
    t7.save(p, obj)
    back = t7.load(p)
    assert back["a"] == 1
    assert back["b"][:2] == [1, 2]
    np.testing.assert_array_equal(back["t"], obj["t"])


def test_shared_object_identity(tmp_path):
    """Torch memoizes repeated objects; sharing must survive round-trip."""
    p = str(tmp_path / "shared.t7")
    w = np.random.rand(4, 4).astype(np.float32)
    obj = {"first": w, "second": w}
    t7.save(p, obj)
    back = t7.load(p)
    assert back["first"] is back["second"]


def test_torch_object_roundtrip(tmp_path):
    p = str(tmp_path / "obj.t7")
    lin = TorchObject("nn.Linear", {
        "weight": np.random.rand(3, 5).astype(np.float64),
        "bias": np.random.rand(3).astype(np.float64)})
    t7.save(p, lin)
    back = t7.load(p)
    assert back.torch_type == "nn.Linear"
    np.testing.assert_array_equal(back.state["weight"],
                                  lin.state["weight"])


def test_load_torch_model_mlp(tmp_path):
    """A torch-saved MLP (as torch.save would lay it out) converts to
    bigdl_tpu modules with identical forward."""
    p = str(tmp_path / "mlp.t7")
    w1 = np.random.randn(8, 4).astype(np.float64)
    b1 = np.random.randn(8).astype(np.float64)
    w2 = np.random.randn(2, 8).astype(np.float64)
    b2 = np.random.randn(2).astype(np.float64)
    model_t7 = TorchObject("nn.Sequential", {"modules": [
        TorchObject("nn.Linear", {"weight": w1, "bias": b1}),
        TorchObject("nn.ReLU", {}),
        TorchObject("nn.Linear", {"weight": w2, "bias": b2}),
        TorchObject("nn.LogSoftMax", {}),
    ]})
    t7.save(p, model_t7)
    model = t7.load_torch_model(p)
    x = np.random.randn(5, 4).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    # numpy reference
    h = np.maximum(x @ w1.T.astype(np.float32) + b1.astype(np.float32), 0)
    logits = h @ w2.T.astype(np.float32) + b2.astype(np.float32)
    ref = logits - np.log(np.exp(logits).sum(1, keepdims=True))
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_load_torch_model_convnet(tmp_path):
    p = str(tmp_path / "conv.t7")
    w = np.random.randn(6, 3, 5, 5).astype(np.float64) * 0.1
    b = np.zeros(6, np.float64)
    model_t7 = TorchObject("nn.Sequential", {"modules": [
        TorchObject("nn.SpatialConvolution", {
            "nInputPlane": 3, "nOutputPlane": 6, "kW": 5, "kH": 5,
            "dW": 1, "dH": 1, "padW": 2, "padH": 2,
            "weight": w, "bias": b}),
        TorchObject("nn.SpatialMaxPooling", {
            "kW": 2, "kH": 2, "dW": 2, "dH": 2, "padW": 0, "padH": 0}),
        TorchObject("nn.ReLU", {}),
    ]})
    t7.save(p, model_t7)
    model = t7.load_torch_model(p)
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    assert out.shape == (2, 6, 4, 4)
    assert np.isfinite(out).all()


def test_unsupported_module_raises(tmp_path):
    p = str(tmp_path / "bad.t7")
    t7.save(p, TorchObject("nn.ExoticLayer", {}))
    with pytest.raises(ValueError, match="unsupported torch module"):
        t7.load_torch_model(p)


def test_flattened_conv_weight(tmp_path):
    """Torch sometimes stores conv weight 2-D [nOut, nIn*kh*kw]."""
    p = str(tmp_path / "flat.t7")
    w4 = np.random.randn(4, 2, 3, 3).astype(np.float64)
    obj = TorchObject("nn.SpatialConvolution", {
        "nInputPlane": 2, "nOutputPlane": 4, "kW": 3, "kH": 3,
        "weight": w4.reshape(4, -1), "bias": np.zeros(4)})
    t7.save(p, obj)
    from bigdl_tpu.utils.torch_file import _to_module
    m = _to_module(t7.load(p))
    np.testing.assert_allclose(np.asarray(m.get_parameters()["weight"]),
                               w4.astype(np.float32), atol=1e-6)
