"""Graph control flow: Switch/Merge + IfThenElse (reference:
nn/ops/ControlOps.scala:69,91; nn/Scheduler.scala:118-130), including a
TF-imported v1 control-flow graph (utils/tf/ loaders Merge/Switch)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn


def _cond_graph():
    """x -> Switch(pred); false: x*2 ; true: x+10 ; Merge."""
    data = nn.Input()()
    pred = nn.Input()()
    sw = nn.SwitchOps()(data, pred)
    # 1-based branch outputs like the reference: 1=false, 2=true
    f_branch = nn.MulConstant(2.0)((sw, 1))
    t_branch = nn.AddConstant(10.0)((sw, 2))
    merge = nn.MergeOps()(f_branch, t_branch)
    return nn.Graph([data, pred], merge)


def test_graph_switch_merge_false_and_true():
    g = _cond_graph()
    x = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
    out_false = np.asarray(g.forward([x, np.asarray(False)]))
    np.testing.assert_allclose(out_false, x * 2)
    out_true = np.asarray(g.forward([x, np.asarray(True)]))
    np.testing.assert_allclose(out_true, x + 10)


def test_graph_switch_merge_under_jit():
    g = _cond_graph()
    g.ensure_initialized()
    params, state = g.get_parameters(), g.get_state()

    @jax.jit
    def fn(p, s, x, pred):
        out, _ = g.apply(p, s, [x, pred], training=False)
        return out

    x = np.ones((2, 2), np.float32)
    np.testing.assert_allclose(np.asarray(fn(params, state, x, True)),
                               x + 10)
    np.testing.assert_allclose(np.asarray(fn(params, state, x, False)),
                               x * 2)


def test_merge_requires_two_distinct_branches():
    data = nn.Input()()
    pred = nn.Input()()
    sw = nn.SwitchOps()(data, pred)
    b1 = nn.MulConstant(2.0)((sw, 1))
    b2 = nn.MulConstant(3.0)((sw, 1))  # same branch twice: invalid
    merge = nn.MergeOps()(b1, b2)
    with pytest.raises(ValueError, match="distinct branches"):
        nn.Graph([data, pred], merge)


def test_if_then_else_lax_cond():
    m = nn.IfThenElse(nn.Linear(4, 3), nn.Linear(4, 3))
    m.ensure_initialized()
    params, state = m.get_parameters(), m.get_state()
    x = np.random.RandomState(0).randn(2, 4).astype(np.float32)

    out_t, _ = m.apply(params, state, [np.asarray(True), x], training=False)
    out_f, _ = m.apply(params, state, [np.asarray(False), x],
                       training=False)
    # each branch has its own weights -> outputs differ
    assert not np.allclose(np.asarray(out_t), np.asarray(out_f))
    want_t = x @ np.asarray(params["then"]["weight"]).T \
        + np.asarray(params["then"]["bias"])
    np.testing.assert_allclose(np.asarray(out_t), want_t, atol=1e-5)

    @jax.jit
    def fn(p, s, pred, x):
        out, _ = m.apply(p, s, [pred, x], training=False)
        return out

    np.testing.assert_allclose(np.asarray(fn(params, state, True, x)),
                               np.asarray(out_t), atol=1e-6)


def test_tf_imported_cond_graph():
    tf = pytest.importorskip("tensorflow")
    from bigdl_tpu.utils.tf_loader import TFModule, parse_graphdef

    tf.compat.v1.disable_control_flow_v2()  # force Switch/Merge lowering
    with tf.compat.v1.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [2, 3], name="x")
        p = tf.compat.v1.placeholder(tf.bool, [], name="p")
        out = tf.cond(p, lambda: x + 10.0, lambda: x * 2.0)
        out = tf.identity(out, name="out")
        gd = g.as_graph_def()
    tf.compat.v1.enable_control_flow_v2()
    ops = {n.op for n in gd.node}
    assert "Switch" in ops and "Merge" in ops  # v1 lowering happened

    nodes = parse_graphdef(gd.SerializeToString())
    mod = TFModule(nodes, inputs=["x", "p"], outputs=["out"]).evaluate()
    xv = np.random.RandomState(1).randn(2, 3).astype(np.float32)
    with tf.compat.v1.Session(graph=g) as sess:
        want_t = sess.run("out:0", {"x:0": xv, "p:0": True})
        want_f = sess.run("out:0", {"x:0": xv, "p:0": False})
    got_t = np.asarray(mod.forward([xv, np.asarray(True)]))
    got_f = np.asarray(mod.forward([xv, np.asarray(False)]))
    np.testing.assert_allclose(got_t, want_t, atol=1e-5)
    np.testing.assert_allclose(got_f, want_f, atol=1e-5)


def test_nested_switch_merge_rejected():
    """Nested Switch/Merge conds resolve to different Switches — the
    nearest-Switch walk cannot select soundly, so Graph must refuse
    (IfThenElse nests safely instead)."""
    data = nn.Input()()
    p_out = nn.Input()()
    p_in = nn.Input()()
    sw_o = nn.SwitchOps()(data, p_out)
    sw_i = nn.SwitchOps()((sw_o, 1), p_in)
    inner_f = nn.MulConstant(2.0)((sw_i, 1))
    inner_t = nn.AddConstant(5.0)((sw_i, 2))
    inner_merge = nn.MergeOps()(inner_f, inner_t)
    outer_t = nn.AddConstant(10.0)((sw_o, 2))
    outer_merge = nn.MergeOps()(inner_merge, outer_t)
    with pytest.raises(ValueError, match="different"):
        nn.Graph([data, p_out, p_in], outer_merge)


def test_nested_if_then_else_works():
    m = nn.IfThenElse(nn.MulConstant(3.0), nn.MulConstant(5.0))
    m.ensure_initialized()
    p, s = m.get_parameters(), m.get_state()
    out, _ = m.apply(p, s, [np.asarray(True), np.ones((2,), np.float32)])
    np.testing.assert_allclose(np.asarray(out), 3.0)
