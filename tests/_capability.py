"""Environment capability gates for tier-1 (not itself a pytest file).

The two long-standing env exclusions — jax builds without
``jax.shard_map`` and CPU runtimes that cannot EXECUTE cross-process
collectives — used to surface as 35 identical crash-shaped failures.
They are environmental, not bugs, so they now route through the ONE
probe helper (``bigdl_tpu.elastic.capability``): each excluded test
skips with the precise, auditable reason, and a runtime that DOES
support the surface runs the real tests unchanged. The probe result is
cached per process, so the multiprocess probe's two-process gang runs
at most once per pytest session.
"""
import pytest

from bigdl_tpu.elastic.capability import (multiprocess_cpu,
                                          shard_map_available,
                                          shard_map_reason)

#: decorator for tests that compile through jax.shard_map (ring /
#: Ulysses sequence parallelism, pipeline parallelism)
shard_map_skip = pytest.mark.skipif(
    not shard_map_available(),
    reason=shard_map_reason() if not shard_map_available() else "")


def require_multiprocess_cpu() -> None:
    """Skip the calling test unless this runtime can execute
    cross-process collectives on the CPU backend (probed once per
    session by a real two-process reduction)."""
    ok, reason = multiprocess_cpu()
    if not ok:
        pytest.skip(reason)
