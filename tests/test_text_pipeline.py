"""Text pipeline tests (reference: dataset/text/ SentenceTokenizer.scala:35,
Dictionary.scala, TextToLabeledSentence.scala, LabeledSentenceToSample.scala;
PTB path of example/languagemodel/PTBWordLM.scala)."""
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import (
    DataSet, Dictionary, LabeledSentenceToSample, Sample, SampleToMiniBatch,
    SentenceBiPadding, SentenceSplitter, SentenceTokenizer,
    TextToLabeledSentence, load_ptb, ptb_arrays, tokenize,
    SENTENCE_START, SENTENCE_END)

CORPUS = """the quick brown fox jumps over the lazy dog .
the dog barks at the quick fox .
a lazy cat sleeps near the brown dog ."""


def test_tokenize_basic():
    assert tokenize("Don't stop, World!") == \
        ["don't", "stop", ",", "world", "!"]


def test_sentence_splitter_and_tokenizer():
    text = "First one. Second two!  Third three?"
    sents = list(SentenceSplitter().apply(iter([text])))
    assert len(sents) == 3
    toks = list(SentenceTokenizer().apply(iter(sents)))
    assert toks[0] == ["first", "one", "."]
    padded = list(SentenceBiPadding().apply(iter(toks)))
    assert padded[0][0] == SENTENCE_START
    assert padded[0][-1] == SENTENCE_END


def test_dictionary_vocab_limit_and_unk():
    sents = [tokenize(l) for l in CORPUS.splitlines()]
    d = Dictionary(sents, vocab_size=5)
    assert len(d.word2index) == 5
    # "the" is the most frequent word -> index 1
    assert d.get_index("the") == 1
    # out-of-vocab words share the single unk index = vocab_size
    assert d.get_index("zebra") == d.unk_index() == d.vocab_size()
    assert d.get_word(d.get_index("the")) == "the"


def test_dictionary_save_load(tmp_path):
    d = Dictionary([tokenize(l) for l in CORPUS.splitlines()])
    p = str(tmp_path / "dict.json")
    d.save(p)
    d2 = Dictionary.load(p)
    assert d2.word2index == d.word2index
    assert d2.get_word(d.get_index("fox")) == "fox"


def test_text_to_labeled_sentence_and_sample():
    sents = [tokenize(l) for l in CORPUS.splitlines()]
    d = Dictionary(sents)
    ls = list(TextToLabeledSentence(d).apply(iter(sents)))
    # label is data shifted by one
    np.testing.assert_array_equal(ls[0].data[1:], ls[0].label[:-1])
    samples = list(LabeledSentenceToSample(fixed_length=6).apply(iter(ls)))
    assert all(s.feature().shape == (6,) for s in samples)
    onehots = list(LabeledSentenceToSample(
        one_hot_size=d.vocab_size(), fixed_length=6).apply(iter(ls)))
    f = onehots[0].feature()
    assert f.shape == (6, d.vocab_size())
    np.testing.assert_allclose(f.sum(axis=1), 1.0)
    # one-hot position encodes the 1-based index
    assert np.argmax(f[0]) + 1 == ls[0].data[0]


def test_ptb_arrays_contiguity():
    # stream 1..25, batch 2, steps 3
    x, y = ptb_arrays(np.arange(1, 26, dtype=np.float32), 2, 3)
    assert x.shape == y.shape == (8, 3)
    np.testing.assert_array_equal(y, x + 1)  # next-word labels
    # row 0 of consecutive batches continues the same stream position
    np.testing.assert_array_equal(x[0], [1, 2, 3])
    np.testing.assert_array_equal(x[2], [4, 5, 6])  # continuation of row 0


def test_load_ptb_end_to_end_lm_training(tmp_path):
    """PTB LSTM trains end-to-end from raw text (BASELINE config 5 shape;
    PTBWordLM.scala) — loss (log-perplexity) must drop."""
    p = tmp_path / "ptb.train.txt"
    p.write_text("\n".join([CORPUS] * 8))
    splits, d = load_ptb(str(p), vocab_size=50)
    V = d.vocab_size()
    num_steps, batch = 5, 4
    x, y = ptb_arrays(splits["train"], batch, num_steps)
    samples = [Sample(x[i], y[i]) for i in range(len(x))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(batch))

    from bigdl_tpu.models import PTBModel
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_epoch

    model = PTBModel(V, 16, V, num_layers=1, keep_prob=2.0)
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
    model.ensure_initialized()
    out, _ = model.apply(model.get_parameters(), model.get_state(), x,
                         training=False)
    initial_loss = float(crit.apply(out, y))

    opt = LocalOptimizer(model, ds, crit, batch_size=batch)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_epoch(8))
    opt.optimize()
    final_loss = opt.driver_state["Loss"]
    assert final_loss < initial_loss  # perplexity exp(loss) improves
    assert np.exp(final_loss) < d.vocab_size()  # beats uniform guessing
