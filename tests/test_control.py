"""The fleet control plane (bigdl_tpu.fleet.control / admission /
deploy). Pins the subsystem's load-bearing claims — the autoscaler's
hysteresis band, cooldowns and min/max clamp suppress (and count)
every flap, actuators aborted by injected faults leave the fleet
untouched and retry next tick, spawn is warm-before-join, tenant
overload is always a typed counted shed (BudgetExhausted / fair-share
QueueFull), weighted-fair shares converge to the weight ratio under
saturation, priority preemption returns the victim's partial tokens,
and the deploy state machine lands done or rolled_back with the
incumbents never left mixed — resumable from its persisted state."""
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.fleet import (AdmissionController, Autoscaler,
                             BudgetExhausted, DeployPipeline, Preempted,
                             ScalePolicy)
from bigdl_tpu.fleet.deploy import STAGES
from bigdl_tpu.serving import QueueFull


# ------------------------------------------------------------- fakes

class _Stream:
    """Minimal FleetStream stand-in: a completion Future, a placement
    (`_replica`) and a TTFT — everything the control plane reads."""

    def __init__(self, replica=None, ttft_ms=1.0, err=None):
        self._replica = replica
        self.ttft_ms = ttft_ms
        self.completion = Future()
        if err is not None:
            self.completion.set_exception(err)
        else:
            self.completion.set_result("ok")

    def done(self):
        return self.completion.done()

    def result(self, timeout=None):
        return self.completion.result(timeout)


class _Rep:
    """Fake replica: name, state, a settable load, an event log."""

    def __init__(self, name, load=0.0):
        self.name = name
        self.state = "serving"
        self._load = load
        self.events = []

    def load(self):
        return self._load

    def accepting(self):
        return self.state == "serving"

    def submit(self, prompt, **kw):
        self.events.append("submit")
        return _Stream(self)

    def shutdown(self, drain=True):
        self.events.append("shutdown")


class _Router:
    """Fake FleetRouter: just the surface the autoscaler actuates."""

    def __init__(self, reps=()):
        self._reps = list(reps)
        self.metrics_registry = telemetry.MetricsRegistry()
        self.events = []

    def replicas(self):
        return list(self._reps)

    def add(self, rep):
        self.events.append(("add", rep.name))
        self._reps.append(rep)

    def drain(self, name):
        self.events.append(("drain", name))
        for r in self._reps:
            if r.name == name:
                r.state = "draining"

    def remove(self, name, drain=True):
        self.events.append(("remove", name))
        self._reps = [r for r in self._reps if r.name != name]

    def submit(self, prompt, **kw):
        return _Stream(None)


def _scaler(router, *, clock=None, **pol):
    defaults = dict(min_replicas=1, max_replicas=3, up_load=3.0,
                    down_load=1.0, up_cooldown_s=0.0,
                    down_cooldown_s=0.0)
    defaults.update(pol)
    kw = {"clock": clock} if clock is not None else {}
    return Autoscaler(router, lambda name: _Rep(name),
                      policy=ScalePolicy(**defaults),
                      metrics=router.metrics_registry, **kw)


def _counter(router, name):
    return router.metrics_registry.counter(name)


# -------------------------------------------------------- autoscaler

def test_scale_policy_validates_its_band_and_clamp():
    with pytest.raises(ValueError, match="min_replicas"):
        ScalePolicy(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        ScalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="hysteresis"):
        ScalePolicy(up_load=2.0, down_load=2.0)


def test_autoscaler_hysteresis_band_and_clamp():
    """Above up_load scales up, inside the band holds, at/below
    down_load scales down — and both clamps suppress WITH a counted
    impulse (a quiet autoscaler must be distinguishable from a dead
    one)."""
    router = _Router([_Rep("seed-1", load=5.0)])
    scaler = _scaler(router, max_replicas=2)
    sup = _counter(router, "fleet/control/suppressed")

    d = scaler.step()
    assert d.action == "up" and len(router.replicas()) == 2
    assert _counter(router, "fleet/control/scale_ups").total() == 1

    for r in router.replicas():        # still hot, but at max: clamp
        r._load = 5.0
    d = scaler.step()
    assert d.action == "hold" and "max_replicas" in d.reason
    assert sup.value(by="clamp") == 1 and len(router.replicas()) == 2

    for r in router.replicas():        # dead zone: no action at all
        r._load = 2.0
    d = scaler.step()
    assert d.action == "hold" and "inside band" in d.reason

    for r in router.replicas():        # idle: drain the autoscaled one
        r._load = 0.0
    d = scaler.step()
    assert d.action == "down" and len(router.replicas()) == 1
    assert router.replicas()[0].name == "seed-1"
    assert _counter(router, "fleet/control/scale_downs").total() == 1

    d = scaler.step()                  # at min: clamp, never below
    assert d.action == "hold" and "min_replicas" in d.reason
    assert sup.value(by="clamp") == 2 and len(router.replicas()) == 1


def test_autoscaler_cooldowns_gate_each_direction():
    """Per-direction cooldowns: an impulse inside the window is
    suppressed + counted; the same impulse actuates once the window
    elapses (driven by an injected clock — deterministic)."""
    now = [0.0]
    router = _Router([_Rep("seed-1", load=5.0)])
    scaler = _scaler(router, up_cooldown_s=10.0, down_cooldown_s=10.0,
                     clock=lambda: now[0])
    sup = _counter(router, "fleet/control/suppressed")

    assert scaler.step().action == "up"           # last_up = 0
    for r in router.replicas():
        r._load = 5.0
    d = scaler.step()
    assert d.action == "hold" and "up_cooldown" in d.reason
    assert sup.value(by="cooldown") == 1 and len(router.replicas()) == 2
    now[0] = 11.0
    assert scaler.step().action == "up"           # window elapsed
    assert len(router.replicas()) == 3

    for r in router.replicas():
        r._load = 0.0
    assert scaler.step().action == "down"         # last_down = 11
    d = scaler.step()
    assert d.action == "hold" and "down_cooldown" in d.reason
    assert sup.value(by="cooldown") == 2 and len(router.replicas()) == 2
    now[0] = 22.0
    assert scaler.step().action == "down"
    assert len(router.replicas()) == 1


def test_autoscaler_aborted_actuations_retry_next_tick():
    """An injected fleet/spawn or fleet/drain fault aborts the
    actuation with the fleet untouched, counts *_aborted, and the next
    tick retries — the recovery the chaos --control leg reconciles."""
    router = _Router([_Rep("seed-1", load=5.0)])
    scaler = _scaler(router)

    with faults.armed("fleet/spawn=nth:1,raise:RuntimeError"):
        d = scaler.step()
        assert d.action == "hold" and "spawn aborted" in d.reason
        assert len(router.replicas()) == 1        # fleet untouched
        assert _counter(
            router, "fleet/control/spawn_aborted").total() == 1
        assert scaler.step().action == "up"       # the retry lands
        assert len(router.replicas()) == 2

    for r in router.replicas():
        r._load = 0.0
    with faults.armed("fleet/drain=nth:1,raise:RuntimeError"):
        d = scaler.step()
        assert d.action == "hold" and "drain aborted" in d.reason
        assert len(router.replicas()) == 2
        assert _counter(
            router, "fleet/control/drain_aborted").total() == 1
        assert scaler.step().action == "down"
        assert len(router.replicas()) == 1


def test_spawn_is_warm_before_join_and_cleans_up_on_failure():
    """Warm prompts run against the replica BEFORE router.add (the
    router never sees a cold replica); a warm failure shuts the
    orphan down and leaves the fleet unchanged."""
    router = _Router([_Rep("seed-1", load=5.0)])
    prompts = [np.array([1, 2], np.int32)] * 2
    scaler = _scaler(router, warm_prompts=prompts)
    scaler.step()
    auto = next(r for r in router.replicas() if r.name == "auto-1")
    assert auto.events == ["submit", "submit"]    # warmed, then joined
    assert ("add", "auto-1") in router.events

    class _ColdRep(_Rep):
        def submit(self, prompt, **kw):
            self.events.append("submit")
            raise RuntimeError("warm prompt failed")

    orphans = []
    scaler.factory = lambda name: orphans.append(_ColdRep(name)) \
        or orphans[-1]
    for r in router.replicas():
        r._load = 5.0
    d = scaler.step()
    assert d.action == "hold" and "spawn aborted" in d.reason
    assert orphans[0].events[-1] == "shutdown"    # no orphan replica
    assert all(r.name != "auto-2" for r in router.replicas())


def test_empty_fleet_signals_infinite_load():
    router = _Router([])
    scaler = _scaler(router)
    assert scaler.signal() == float("inf")
    assert scaler.decide().action == "up"


# --------------------------------------------------------- admission

class _SatRouter(_Router):
    """Fake router whose replicas sit at a fixed load (drives the
    admission controller's saturation gate)."""

    def __init__(self, load):
        super().__init__([_Rep("r0", load=load), _Rep("r1", load=load)])


def test_token_budget_sheds_typed_counted_and_refills():
    now = [0.0]
    router = _SatRouter(load=0.0)
    adm = AdmissionController(router,
                              metrics=router.metrics_registry,
                              clock=lambda: now[0])
    adm.register("bronze", rate=1.0, burst=4.0)
    prompt = np.array([1, 2, 3], np.int32)

    adm.submit(prompt, tenant="bronze", max_new_tokens=4)
    with pytest.raises(BudgetExhausted) as ei:
        adm.submit(prompt, tenant="bronze", max_new_tokens=4)
    assert ei.value.tenant == "bronze"
    assert ei.value.retry_after_s == pytest.approx(4.0)
    shed = _counter(router, "fleet/admission/shed")
    assert shed.value(tenant="bronze", reason="budget") == 1

    now[0] = 4.0                                  # refilled: admits
    adm.submit(prompt, tenant="bronze", max_new_tokens=4)
    assert _counter(router, "fleet/admission/admitted").value(
        tenant="bronze") == 2

    with pytest.raises(KeyError, match="unknown tenant"):
        adm.submit(prompt, tenant="nobody")


def test_wfq_shares_converge_to_weight_ratio_under_saturation():
    """gold (weight 3) vs bronze (weight 1) hammering a saturated
    fleet: admitted shares converge to ~3:1, every bronze shed is a
    typed fair-share QueueFull counted under its own tenant label,
    and gold — never over its share — is never shed."""
    router = _SatRouter(load=9.0)
    adm = AdmissionController(router,
                              metrics=router.metrics_registry,
                              saturation_load=2.0, fairness_slack=2.0)
    adm.register("gold", weight=3.0)
    adm.register("bronze", weight=1.0)
    prompt = np.array([1, 2], np.int32)
    admits = {"gold": 0, "bronze": 0}
    sheds = {"gold": 0, "bronze": 0}
    for _ in range(300):
        for t in ("gold", "bronze"):
            try:
                adm.submit(prompt, tenant=t, max_new_tokens=1)
                admits[t] += 1
            except QueueFull:
                sheds[t] += 1
    assert admits["gold"] == 300 and sheds["gold"] == 0
    assert sheds["bronze"] > 0
    ratio = admits["gold"] / admits["bronze"]
    assert 2.5 <= ratio <= 3.5, (admits, sheds)
    shed = _counter(router, "fleet/admission/shed")
    assert shed.value(tenant="bronze",
                      reason="fair_share") == sheds["bronze"]


def test_wfq_is_work_conserving_below_saturation():
    """An idle fleet admits everyone, whatever their share — the
    fairness gate only bites under contention."""
    router = _SatRouter(load=0.0)
    adm = AdmissionController(router,
                              metrics=router.metrics_registry,
                              saturation_load=2.0, fairness_slack=2.0)
    adm.register("gold", weight=3.0)
    adm.register("bronze", weight=1.0)
    prompt = np.array([1, 2], np.int32)
    for _ in range(40):
        for t in ("gold", "bronze"):
            adm.submit(prompt, tenant=t, max_new_tokens=1)  # no raise


def test_priority_preemption_keeps_the_victims_partial_tokens():
    """A real one-replica fleet at capacity: a priority tenant's
    arrival preempts the bronze generation mid-decode — the victim
    resolves typed Preempted WITH the tokens it already produced
    (work done is returned, not discarded), and the preemptor's
    request lands in the freed capacity."""
    from bigdl_tpu.fleet import FleetRouter, Replica
    from bigdl_tpu.generation import GenerationConfig
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(42)
    model = TransformerLM(vocab_size=32, hidden_size=16, num_layers=1,
                          num_heads=2, max_len=64).evaluate()
    model.ensure_initialized()
    reg = telemetry.MetricsRegistry()
    rep = Replica("r0", model,
                  config=GenerationConfig(slots=1, max_len=64,
                                          length_buckets=(64,),
                                          prefill_rows=1, max_queue=1),
                  metrics=reg)
    router = FleetRouter([rep], metrics=reg)
    try:
        adm = AdmissionController(router, metrics=reg,
                                  preempt_wait_s=15.0)
        adm.register("bronze", priority=0)
        adm.register("gold", priority=1)
        prompt = np.array([1, 2, 3, 4], np.int32)

        victim = adm.submit(prompt, tenant="bronze",
                            max_new_tokens=48)
        victim.first(timeout=60)       # decoding, holding THE slot
        filler = adm.submit(prompt, tenant="bronze",
                            max_new_tokens=2)  # fills the queue
        gold = adm.submit(prompt, tenant="gold", max_new_tokens=2)

        with pytest.raises(Preempted) as ei:
            victim.result(timeout=30)
        assert ei.value.tenant == "bronze" and ei.value.by == "gold"
        assert 1 <= len(ei.value.tokens) < 48    # partial tokens KEPT
        assert list(ei.value.tokens) == list(victim.tokens())
        assert reg.counter("fleet/admission/preemptions").value(
            tenant="bronze") == 1
        assert gold.result(timeout=60) is not None
        filler.result(timeout=60)
    finally:
        router.shutdown(drain=False)


# ------------------------------------------------------------ deploy

class _Servable:
    def __init__(self, version, model):
        self.version = version
        self.model = model


class _FakeService:
    """Fake GenerationService registry: versioned current servable,
    load() activates a new version, swap() reverts to an old one."""

    def __init__(self, model):
        self._cur = _Servable(1, model)
        self.registry = self

    def current(self, name):
        return self._cur

    def load(self, name, model):
        self._cur = _Servable(self._cur.version + 1, model)

    def swap(self, name, version):
        self._cur = _Servable(version, self._cur.model)


class _DeployRep:
    def __init__(self, name, model):
        self.name = name
        self.state = "serving"
        self.service = _FakeService(model)

    def load(self):
        return 0

    def accepting(self):
        return True

    def shutdown(self, drain=True):
        self.state = "dead"


class _DeployRouter(_Router):
    """Deterministic canary split: with a split set, every second
    probe lands on the canary; `canary_fail=True` makes canary-placed
    probes fail typed (the poisoned-canary scenario)."""

    def __init__(self, reps):
        super().__init__(reps)
        self._split = None
        self._n = 0
        self.canary_fail = False

    def set_split(self, name, fraction, seed=0):
        self._split = name

    def clear_split(self):
        self._split = None

    @property
    def split(self):
        return self._split

    def submit(self, prompt, **kw):
        self._n += 1
        if self._split is not None and self._n % 2 == 0:
            rep = next(r for r in self._reps if r.name == self._split)
            err = RuntimeError("canary sick") if self.canary_fail \
                else None
            return _Stream(rep, ttft_ms=1.0, err=err)
        rep = next(r for r in self._reps
                   if self._split is None or r.name != self._split)
        return _Stream(rep, ttft_ms=1.0)


def _pipeline(router, trained, **kw):
    defaults = dict(
        train_fn=lambda: trained,
        replica_factory=lambda name, model: _DeployRep(name, model),
        canary_fraction=0.5, canary_requests=6,
        metrics=router.metrics_registry, seed=3)
    defaults.update(kw)
    return DeployPipeline(router, **defaults)


def test_deploy_happy_path_swaps_every_incumbent():
    router = _DeployRouter([_DeployRep("r0", "m0"),
                            _DeployRep("r1", "m0")])
    cand = object()
    report = _pipeline(router, cand).run()
    assert report["state"] == "done"
    assert report["history"] == list(STAGES)
    for rep in router.replicas():                 # fleet-wide swap
        assert rep.service.current(rep.name).model is cand
        assert rep.service.current(rep.name).version == 2
    assert len(router.replicas()) == 2            # canary retired
    assert router.split is None
    w = report["window"]
    assert w["canary_requests"] == 3 and w["incumbent_requests"] == 3
    assert w["canary_error_fraction"] == 0.0
    assert _counter(router, "fleet/deploy/completed").total() == 1
    assert _counter(router, "fleet/deploy/swaps").total() == 2


def test_deploy_gate_refusal_stages_nothing():
    from bigdl_tpu.precision.gate import AccuracyGateError

    class _RefusingGate:
        def check(self, reference, candidate, label=""):
            raise AccuracyGateError("delta 0.5 > 0.02")

    router = _DeployRouter([_DeployRep("r0", "m0")])
    report = _pipeline(router, object(), gate=_RefusingGate(),
                       gate_reference="m0").run()
    assert report["state"] == "rolled_back"
    assert "gate refused" in report["reason"]
    assert len(router.replicas()) == 1            # no canary ever built
    assert router.replicas()[0].service.current("r0").version == 1
    assert _counter(router, "fleet/deploy/gate_failures").total() == 1


def test_deploy_poisoned_canary_rolls_back_incumbent_untouched():
    router = _DeployRouter([_DeployRep("r0", "m0")])
    router.canary_fail = True
    report = _pipeline(router, object()).run()
    assert report["state"] == "rolled_back"
    assert "canary" in report["reason"]
    assert report["window"]["canary_error_fraction"] == 1.0
    rep = router.replicas()[0]
    assert rep.name == "r0"                       # canary removed
    assert rep.service.current("r0").model == "m0"  # untouched
    assert router.split is None
    assert _counter(router, "fleet/deploy/rollbacks").value(
        reason="canary") == 1


def test_deploy_swap_abort_reverts_the_already_swapped():
    """A fleet/canary_swap fault at the SECOND incumbent: the first —
    already swapped — is reverted to its previous version; the fleet
    is never left mixed."""
    router = _DeployRouter([_DeployRep("r0", "m0"),
                            _DeployRep("r1", "m0")])
    with faults.armed("fleet/canary_swap=nth:2,raise:RuntimeError"):
        report = _pipeline(router, object()).run()
    assert report["state"] == "rolled_back"
    assert "swap aborted" in report["reason"]
    for rep in router.replicas():
        assert rep.service.current(rep.name).version == 1
    assert _counter(router, "fleet/deploy/swap_aborted").total() == 1


def test_deploy_resumes_from_persisted_state(tmp_path):
    """A deploy killed after committing train+gate resumes from the
    persisted state file: committed stages are on record, artifact
    stages replay deterministically from the seeded train_fn, and the
    machine runs on to done. A re-run of a finished deploy is a
    no-op — nothing swaps twice."""
    path = str(tmp_path / "deploy.json")
    calls = []
    router1 = _DeployRouter([_DeployRep("r0", "m0")])
    p1 = _pipeline(router1, None,
                   train_fn=lambda: calls.append(1) or object(),
                   state_path=path)
    p1._stage_train()
    p1._commit("train")
    p1._stage_gate()
    p1._commit("gate")                 # ...and the process dies here

    router2 = _DeployRouter([_DeployRep("r0", "m0")])
    p2 = _pipeline(router2, None,
                   train_fn=lambda: calls.append(2) or object(),
                   state_path=path)
    assert p2.state["history"] == ["train", "gate"]  # state recovered
    report = p2.run()
    assert report["state"] == "done"
    assert 2 in calls                  # the artifact stage replayed
    assert router2.replicas()[0].service.current("r0").version == 2

    swaps = _counter(router2, "fleet/deploy/swaps").total()
    assert p2.run()["state"] == "done"               # idempotent
    assert _counter(router2, "fleet/deploy/swaps").total() == swaps
