"""Elastic preemption-tolerant training (bigdl_tpu/elastic): async
per-shard checkpointing behind a barriered format-3 manifest commit
(a not-yet-committed checkpoint is never visible, a torn commit is
quarantinable, the step-loop stall shrinks to the snapshot copy),
cross-mesh resume reassembling global arrays from the recorded
sharding metadata onto a different mesh/stage (resume matrix),
keep_last retention GC safe under an in-flight write, per-process
datapipe cursor re-splitting, SIGTERM grace, and the hardened
tools.launch typed exit reports + classified start retry."""
import os
import shutil
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import elastic, faults
from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet
from bigdl_tpu.optim import SGD, Optimizer, max_iteration
from bigdl_tpu.optim.trigger import several_iteration
from bigdl_tpu.parallel import ZeroConfig, make_mesh
from bigdl_tpu.parallel.zero import (entries_to_spec, shard_zero_tree,
                                     spec_to_entries)
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils.serialization import (CheckpointCorrupt,
                                           find_latest_checkpoint,
                                           host_value, load_checkpoint,
                                           quarantine_checkpoint,
                                           save_checkpoint,
                                           verify_checkpoint)


@pytest.fixture(scope="module")
def devices8():
    d = jax.devices()
    if len(d) < 8:
        pytest.skip("needs 8 virtual devices")
    return d[:8]


# ------------------------------------------------------ spec wire form

def test_spec_entries_roundtrip():
    for spec in (P(), P("data"), P(None, "data"), P("model", None),
                 P(("data", "model"), None)):
        assert entries_to_spec(spec_to_entries(spec)) == spec
    assert spec_to_entries(None) == []
    assert entries_to_spec([]) == P()


# ------------------------------------- per-shard snapshot + reassembly

def _sharded_state(mesh, stage=2):
    cfg = ZeroConfig(stage=stage)
    params = shard_zero_tree(
        {"w": jnp.arange(64.0).reshape(16, 4), "b": jnp.arange(3.0),
         "t": jnp.int32(7)}, mesh, cfg)
    opt = shard_zero_tree({"v": {"w": jnp.ones((16, 4)) * 2}}, mesh, cfg)
    mst = jax.device_put({"s": jnp.zeros((4,))},
                         NamedSharding(mesh, P()))
    return cfg, params, opt, mst


def test_format3_roundtrip_bitwise_and_manifest_metadata(devices8,
                                                         tmp_path):
    """Per-shard save -> reassembled load is BITWISE the gathered
    state, and the format-3 MANIFEST records the full sharding
    metadata contract: mesh shape, axis names, per-leaf PartitionSpec,
    ZeRO stage, precision policy, per-process cursors."""
    mesh = make_mesh([8], ["data"], devices8)
    cfg, params, opt, mst = _sharded_state(mesh)
    path = str(tmp_path / "checkpoint.4")
    from bigdl_tpu.precision import PrecisionPolicy
    meta = elastic.run_metadata(mesh=mesh, zero=cfg,
                                precision=PrecisionPolicy.named(
                                    "bf16_mixed"), process_count=1)
    elastic.save_checkpoint(
        path, params=params, opt_state=opt, model_state=mst,
        optim_host_state={"lr": 0.1},
        driver_state={"neval": 4, "epoch": 1}, run_meta=meta,
        cursor={"epoch": 0, "spos": 1, "offset": 5})
    verify_checkpoint(path)
    ck = load_checkpoint(path)
    np.testing.assert_array_equal(ck["params"]["w"],
                                  np.asarray(host_value(params["w"])))
    np.testing.assert_array_equal(ck["opt_state"]["v"]["w"],
                                  np.asarray(host_value(opt["v"]["w"])))
    assert int(ck["params"]["t"]) == 7
    sh = ck["sharding"]
    assert sh["mesh_shape"] == {"data": 8}
    assert sh["axis_names"] == ["data"]
    assert sh["zero_stage"] == 2
    assert sh["precision"] == "bf16_mixed"
    assert sh["process_count"] == 1
    assert sh["trees"]["params"]["w"]["spec"] == ["data", None]
    assert sh["trees"]["params"]["t"]["spec"] == []
    assert ck["cursors"] == {"0": {"epoch": 0, "spos": 1, "offset": 5}}
    assert ck["driver_state"]["neval"] == 4


def test_load_refuses_coverage_gap(devices8, tmp_path):
    """A lost part file must raise, never resume uninitialized
    memory as weights."""
    mesh = make_mesh([8], ["data"], devices8)
    cfg, params, opt, mst = _sharded_state(mesh)
    path = str(tmp_path / "checkpoint.2")
    elastic.save_checkpoint(path, params=params, opt_state=opt,
                            model_state=mst, optim_host_state={},
                            driver_state={"neval": 2},
                            run_meta=elastic.run_metadata(mesh=mesh,
                                                          zero=cfg))
    os.remove(os.path.join(path, "params.part0.npz"))
    with pytest.raises(CheckpointCorrupt):
        load_checkpoint(path)  # verify names the missing file
    with pytest.raises(CheckpointCorrupt):
        elastic.load_parts(path, verify=False)  # coverage check too


def test_load_for_mesh_reshards_onto_new_layout(devices8, tmp_path):
    mesh = make_mesh([8], ["data"], devices8)
    cfg, params, opt, mst = _sharded_state(mesh)
    path = str(tmp_path / "checkpoint.2")
    elastic.save_checkpoint(path, params=params, opt_state=opt,
                            model_state=mst, optim_host_state={},
                            driver_state={"neval": 2},
                            run_meta=elastic.run_metadata(mesh=mesh,
                                                          zero=cfg))
    mesh4 = make_mesh([4], ["data"], devices8[:4])
    ck = elastic.load_for_mesh(path, mesh=mesh4, zero=ZeroConfig(stage=3))
    assert ck["params"]["w"].sharding.mesh.shape["data"] == 4
    assert ck["params"]["w"].sharding.spec == P("data", None)
    np.testing.assert_array_equal(
        np.asarray(host_value(ck["params"]["w"])),
        np.asarray(host_value(params["w"])))


# ------------------------------------------- two-phase commit protocol

def _tiny_state():
    return ({"w": jnp.arange(8.0)}, {"v": jnp.ones((8,))},
            {"s": jnp.zeros((2,))})


def test_uncommitted_checkpoint_never_visible(tmp_path):
    """The async acceptance invariant: until process 0's MANIFEST
    lands, find_latest_checkpoint cannot select the write."""
    params, opt, mst = _tiny_state()
    writer = elastic.AsyncCheckpointWriter()
    path = str(tmp_path / "checkpoint.2")
    with faults.armed("ckpt/write_manifest=delay:600"):
        elastic.save_checkpoint(path, params=params, opt_state=opt,
                                model_state=mst, optim_host_state={},
                                driver_state={"neval": 2},
                                writer=writer)
        # the writer is mid-commit (held at the manifest faultpoint):
        # the checkpoint must not exist yet
        assert find_latest_checkpoint(str(tmp_path)) is None
        writer.flush()
    assert find_latest_checkpoint(str(tmp_path)) == path
    verify_checkpoint(path)


def test_torn_commit_invisible_and_quarantinable(tmp_path):
    """Death between the last part write and the manifest fsync
    (the ckpt/write_manifest faultpoint) leaves a staging dir that is
    invisible to find_latest_checkpoint, fails verify_checkpoint as a
    torn elastic commit, and is quarantinable — and the next save at
    the same path commits clean."""
    params, opt, mst = _tiny_state()
    writer = elastic.AsyncCheckpointWriter()
    path = str(tmp_path / "checkpoint.2")
    with faults.armed("ckpt/write_manifest=nth:1,raise:OSError"):
        elastic.save_checkpoint(path, params=params, opt_state=opt,
                                model_state=mst, optim_host_state={},
                                driver_state={"neval": 2},
                                writer=writer)
        with pytest.raises(OSError):
            writer.flush()  # the background failure surfaces typed
    staging = [n for n in os.listdir(tmp_path) if ".staging-" in n]
    assert staging, "torn commit left no staging dir"
    torn = str(tmp_path / staging[0])
    assert elastic.is_torn_commit(torn)
    assert find_latest_checkpoint(str(tmp_path)) is None
    with pytest.raises(CheckpointCorrupt):
        verify_checkpoint(torn)
    assert quarantine_checkpoint(torn) is not None
    elastic.save_checkpoint(path, params=params, opt_state=opt,
                            model_state=mst, optim_host_state={},
                            driver_state={"neval": 2}, writer=writer)
    writer.flush()
    assert find_latest_checkpoint(str(tmp_path)) == path
    verify_checkpoint(path)


def test_async_stall_excludes_write_tail(tmp_path):
    """train/checkpoint/save_s (the step-loop stall) must cover only
    the snapshot copy in async mode; the delayed commit lands in
    train/checkpoint/async_write_s."""
    params, opt, mst = _tiny_state()
    writer = elastic.AsyncCheckpointWriter()
    save_h = telemetry.histogram("train/checkpoint/save_s")
    tail_h = telemetry.histogram("train/checkpoint/async_write_s")
    s0, sc0 = save_h.sum(), save_h.count()
    t0, tc0 = tail_h.sum(), tail_h.count()
    with faults.armed("ckpt/write_manifest=delay:400"):
        elastic.save_checkpoint(str(tmp_path / "checkpoint.2"),
                                params=params, opt_state=opt,
                                model_state=mst, optim_host_state={},
                                driver_state={"neval": 2},
                                writer=writer)
        stall = save_h.sum() - s0
        assert save_h.count() == sc0 + 1
        writer.flush()
    tail = tail_h.sum() - t0
    assert tail_h.count() == tc0 + 1
    assert stall < 0.3, f"async save stalled the step loop {stall:.3f}s"
    assert tail >= 0.4, f"write tail {tail:.3f}s missed the delay"


def test_format2_checkpoints_still_load(tmp_path):
    """Back-compat: the gathered format-2 writer's checkpoints load
    through the same load_checkpoint entry point."""
    params, opt, mst = _tiny_state()
    path = str(tmp_path / "checkpoint.4")
    save_checkpoint(path, params=params, opt_state=opt, model_state=mst,
                    optim_host_state={"lr": 0.1},
                    driver_state={"neval": 4})
    verify_checkpoint(path)
    ck = load_checkpoint(path)
    np.testing.assert_array_equal(ck["params"]["w"], np.arange(8.0))
    assert "cursors" not in ck  # format-2 carries no elastic extras
    assert find_latest_checkpoint(str(tmp_path)) == path


# ------------------------------------------------------- GC / retention

def test_prune_keeps_newest_committed_and_skips_quarantines(tmp_path):
    params, opt, mst = _tiny_state()
    for neval in (2, 4, 6, 8):
        elastic.save_checkpoint(str(tmp_path / f"checkpoint.{neval}"),
                                params=params, opt_state=opt,
                                model_state=mst, optim_host_state={},
                                driver_state={"neval": neval})
    # a quarantined dir must be neither counted nor deleted
    shutil.copytree(str(tmp_path / "checkpoint.2"),
                    str(tmp_path / "checkpoint.9.corrupt-1"))
    deleted = elastic.prune_checkpoints(str(tmp_path), keep_last=2)
    assert sorted(os.path.basename(d) for d in deleted) == [
        "checkpoint.2", "checkpoint.4"]
    left = sorted(n for n in os.listdir(tmp_path))
    assert "checkpoint.6" in left and "checkpoint.8" in left
    assert "checkpoint.9.corrupt-1" in left
    # keep_last is clamped: the newest committed dir is never deleted
    assert elastic.prune_checkpoints(str(tmp_path), keep_last=0) == [
        str(tmp_path / "checkpoint.6")]
    assert find_latest_checkpoint(str(tmp_path)) is not None


def test_prune_safe_with_inflight_async_write(tmp_path):
    """GC during an in-flight write: the not-yet-committed staging dir
    is not a candidate (no MANIFEST = not committed), and the commit
    still lands after the prune."""
    params, opt, mst = _tiny_state()
    writer = elastic.AsyncCheckpointWriter()
    for neval in (2, 4):
        elastic.save_checkpoint(str(tmp_path / f"checkpoint.{neval}"),
                                params=params, opt_state=opt,
                                model_state=mst, optim_host_state={},
                                driver_state={"neval": neval})
    with faults.armed("ckpt/write_manifest=delay:500"):
        elastic.save_checkpoint(str(tmp_path / "checkpoint.6"),
                                params=params, opt_state=opt,
                                model_state=mst, optim_host_state={},
                                driver_state={"neval": 6},
                                writer=writer)
        assert writer.busy
        deleted = elastic.prune_checkpoints(str(tmp_path), keep_last=1)
        assert [os.path.basename(d) for d in deleted] == ["checkpoint.2"]
        assert any(".staging-" in n for n in os.listdir(tmp_path))
        writer.flush()
    assert find_latest_checkpoint(str(tmp_path)) == str(
        tmp_path / "checkpoint.6")


# ------------------------------------------------------ cursor re-split

def test_resplit_cursor_same_count_is_exact():
    cursors = {"0": {"epoch": 3, "spos": 2, "offset": 17},
               "1": {"epoch": 3, "spos": 1, "offset": 4}}
    assert elastic.resplit_cursor(cursors, 1, 2) == {
        "epoch": 3, "spos": 1, "offset": 4}


def test_resplit_cursor_changed_count_restarts_epoch():
    cursors = {"0": {"epoch": 3, "spos": 2, "offset": 17},
               "1": {"epoch": 2, "spos": 9, "offset": 1}}
    for pid in range(4):
        assert elastic.resplit_cursor(cursors, pid, 4) == {
            "epoch": 2, "spos": 0, "offset": 0}
    assert elastic.resplit_cursor({}, 0, 1) is None


# --------------------------------------- optimizer resume matrix (E2E)

def _run_optimizer_dev(mesh, stage, iters=8, ckpt=None, seed=7,
                       async_write=True, keep_last=None):
    """The chaos-exactness regime (epoch-exact device cache) under the
    ASYNC elastic writer — the resume-matrix harness."""
    RandomGenerator.set_seed(seed)
    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 255, (64, 1, 8, 8), np.uint8)
    labels = (rng.randint(0, 3, 64) + 1).astype(np.float32)
    ds = DeviceCachedArrayDataSet(
        imgs, labels, 16, crop=(8, 8), flip=False, mean=(0.0,),
        std=(255.0,), sharding=NamedSharding(mesh, P("data")))
    model = nn.Sequential().add(nn.Reshape([64])) \
        .add(nn.Linear(64, 3)).add(nn.LogSoftMax())
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16,
                    mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.05, momentum=0.9))
    opt.set_end_when(max_iteration(iters))
    if stage:
        opt.set_zero(ZeroConfig(stage=stage))
    if ckpt:
        opt.set_checkpoint(ckpt, several_iteration(4),
                           async_write=async_write, keep_last=keep_last)
    trained = opt.optimize()
    return [np.asarray(l)
            for l in jax.tree_util.tree_leaves(trained.get_parameters())]


def test_elastic_resume_matrix(devices8, tmp_path):
    """The supported cross-mesh elastic resume surface: one async
    (stage 2, 8-device) checkpoint resumes (a) same config —
    BIT-IDENTICAL to the uninterrupted run, (b) onto stage 3 over 4
    devices, (c) onto stage 0 over 2 devices — both within the
    documented 1e-5 tolerance (collective reduction order differs
    across mesh shapes, semantics do not)."""
    mesh8 = make_mesh([8], ["data"], devices8)
    d = str(tmp_path / "ckpt")
    _run_optimizer_dev(mesh8, 2, iters=4, ckpt=d)
    ref = _run_optimizer_dev(mesh8, 2, iters=8)

    same = _run_optimizer_dev(mesh8, 2, iters=8, ckpt=d, keep_last=2)
    for a, b in zip(ref, same):
        np.testing.assert_array_equal(a, b)
    # keep_last=2 retention held during the resumed leg
    committed = [p for _, p in elastic.committed_checkpoints(d)]
    assert len(committed) == 2

    matrix = [(3, make_mesh([4], ["data"], devices8[:4])),
              (0, make_mesh([2], ["data"], devices8[:2]))]
    for stage, mesh in matrix:
        shutil.rmtree(os.path.join(d, "checkpoint.8"), ignore_errors=True)
        crossed = _run_optimizer_dev(mesh, stage, iters=8, ckpt=d)
        err = max(float(np.abs(a - b).max())
                  for a, b in zip(ref, crossed))
        assert err < 1e-5, \
            f"stage {stage}/{mesh.shape} resume diverged: {err}"


def test_datapipe_cursor_rides_elastic_manifest(tmp_path):
    """A streaming pipeline's cursor checkpoints through the format-3
    manifest's per-process cursor map and restores bit-exactly on a
    same-world-size resume (the re-split path's exact branch)."""
    from bigdl_tpu import datapipe as dp

    def build():
        RandomGenerator.set_seed(11)
        rng = np.random.RandomState(5)
        X = rng.randn(64, 6).astype(np.float32)
        y = (np.arange(64) % 2 + 1).astype(np.float32)
        pipe = dp.Pipeline(dp.ArrayRecordReader(X, y, shard_size=16,
                                                seed=3)) \
            .batch(8, drop_remainder=True)
        ds = pipe.as_dataset(size=64, batch_size=8)
        model = nn.Sequential().add(nn.Linear(6, 2)) \
            .add(nn.LogSoftMax())
        opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=8)
        opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
        return opt

    d = str(tmp_path / "ckpt")
    opt = build()
    opt.set_end_when(max_iteration(6))
    opt.set_checkpoint(d, several_iteration(3), async_write=True)
    opt.optimize()
    ck = load_checkpoint(find_latest_checkpoint(d))
    assert ck["cursors"], "pipeline cursor missing from the manifest"

    ref_opt = build()
    ref_opt.set_end_when(max_iteration(12))
    ref = [np.asarray(l) for l in jax.tree_util.tree_leaves(
        ref_opt.optimize().get_parameters())]

    res_opt = build()
    res_opt.set_end_when(max_iteration(12))
    res_opt.set_checkpoint(d, several_iteration(3), async_write=True)
    resumed = [np.asarray(l) for l in jax.tree_util.tree_leaves(
        res_opt.optimize().get_parameters())]
    for a, b in zip(ref, resumed):
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------------- SIGTERM grace

def test_preempted_escapes_the_retry_classifier():
    """Preempted must be a BaseException: the classified retry loop
    catches Exception, and retrying a doomed process burns the grace
    window."""
    assert issubclass(elastic.Preempted, BaseException)
    assert not issubclass(elastic.Preempted, Exception)


def test_sigterm_grace_flushes_emergency_checkpoint(tmp_path):
    ck = str(tmp_path / "ckpt")
    fl = str(tmp_path / "flight")
    telemetry.flight.arm(fl)
    try:
        RandomGenerator.set_seed(7)
        rng = np.random.RandomState(3)
        imgs = rng.randint(0, 255, (64, 1, 8, 8), np.uint8)
        labels = (rng.randint(0, 3, 64) + 1).astype(np.float32)
        ds = DeviceCachedArrayDataSet(imgs, labels, 16, crop=(8, 8),
                                      flip=False, mean=(0.0,),
                                      std=(255.0,))
        model = nn.Sequential().add(nn.Reshape([64])) \
            .add(nn.Linear(64, 3)).add(nn.LogSoftMax())
        opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16)
        opt.set_optim_method(SGD(learning_rate=0.05))
        opt.set_end_when(max_iteration(500))
        opt.set_checkpoint(ck, several_iteration(1000),
                           async_write=True)
        opt.set_preemption_handler()
        pre = telemetry.counter("train/elastic/preemptions").value()
        t = threading.Timer(
            0.5, lambda: os.kill(os.getpid(), signal.SIGTERM))
        t.start()
        with pytest.raises(elastic.Preempted):
            opt.optimize()
        t.join()
        latest = find_latest_checkpoint(ck)
        assert latest is not None, "no emergency checkpoint flushed"
        saved = load_checkpoint(latest)
        assert saved["driver_state"]["neval"] >= 1
        assert saved["sharding"], "emergency save not format-3"
        assert os.listdir(fl), "no flight bundle dumped"
        assert telemetry.counter(
            "train/elastic/preemptions").value() == pre + 1
        # the handler was uninstalled on the way out
        assert signal.getsignal(signal.SIGTERM) in (
            signal.SIG_DFL, signal.default_int_handler)
    finally:
        telemetry.flight.disarm()


# ------------------------------------------- launcher typed exit reports

def _script(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


def test_run_gang_typed_ok_reports(tmp_path):
    from bigdl_tpu.tools import launch
    ok = _script(tmp_path, "ok.py",
                 "import os\nprint('hi', os.environ['JAX_PROCESS_ID'])\n")
    r = launch.run_gang(launch.build_args(ok, nproc=2))
    assert r.ok and r.restarts == 0
    assert [(p.rank, p.kind, p.returncode) for p in r.reports] == [
        (0, "ok", 0), (1, "ok", 0)]


def test_run_gang_runtime_failure_gang_restarts_then_reports(tmp_path):
    from bigdl_tpu.tools import launch
    bad = _script(tmp_path, "bad.py", "import sys\nsys.exit(3)\n")
    r = launch.run_gang(launch.build_args(bad, nproc=2, max_restarts=1,
                                          startup_grace=2.0))
    assert not r.ok and r.restarts == 1
    assert all(p.kind == "runtime" and p.returncode == 3
               for p in r.reports)
    assert r.failed()


def test_run_gang_startup_failure_retries_fresh_port(tmp_path):
    """A bring-up death with rendezvous-shaped output retries the gang
    start through faults.retry.retry_call (counted into
    io/retry/retries) and reports kind=startup when exhausted."""
    from bigdl_tpu.tools import launch
    startup = _script(
        tmp_path, "startup.py",
        "import os, sys\n"
        "print('jax.distributed.initialize: UNAVAILABLE: "
        "Failed to connect to', os.environ['JAX_COORDINATOR_ADDRESS'])\n"
        "sys.exit(1)\n")
    retries = telemetry.counter("io/retry/retries").value()
    r = launch.run_gang(launch.build_args(startup, nproc=1,
                                          start_retries=2,
                                          startup_grace=5.0))
    assert not r.ok
    assert r.start_retries == 3  # 1 initial + 2 retries, all classified
    assert telemetry.counter("io/retry/retries").value() == retries + 2
    assert all(p.kind == "startup" for p in r.reports)


def test_run_gang_fast_app_crash_is_not_a_startup_failure(tmp_path):
    """A worker that dies quickly WITHOUT rendezvous-shaped output is
    an application bug: no port-cycling start retry, straight to the
    runtime path."""
    from bigdl_tpu.tools import launch
    bad = _script(tmp_path, "appbug.py",
                  "raise KeyError('config')\n")
    r = launch.run_gang(launch.build_args(bad, nproc=1, start_retries=3,
                                          startup_grace=5.0))
    assert not r.ok and r.start_retries == 0
    assert r.reports[0].kind == "runtime"


def test_kill_gang_delivers_signal_and_reports_killed(tmp_path):
    from bigdl_tpu.tools import launch
    sleeper = _script(tmp_path, "sleep.py",
                      "import time\ntime.sleep(60)\n")

    def monitor(workers):
        launch.kill_gang(workers, sig=signal.SIGKILL)

    r = launch.run_gang(launch.build_args(sleeper, nproc=2,
                                          startup_grace=0.0),
                        monitor=monitor)
    assert not r.ok
    assert all(p.kind == "killed" and p.signal == "SIGKILL"
               for p in r.reports)


# ------------------------------------------- two-phase barrier (2 writers)

def test_two_writer_barrier_merges_parts_and_cursors(devices8, tmp_path):
    """The cross-process commit protocol, emulated with two writer
    calls against ONE shared staging dir (no collectives needed: the
    barrier is file-based by design). Process 1 lands its part first;
    process 0's commit must wait for it, merge both digest sets and
    cursors into the format-3 MANIFEST, and only then publish."""
    mesh = make_mesh([8], ["data"], devices8)
    cfg, params, opt, mst = _sharded_state(mesh)
    path = str(tmp_path / "checkpoint.2")
    meta = elastic.run_metadata(mesh=mesh, zero=cfg, process_count=2)
    # "process 1": writes its shards + PART-1.json, does NOT commit
    elastic.save_checkpoint(path, params=params, opt_state=opt,
                            model_state=mst, optim_host_state={},
                            driver_state={"neval": 2}, run_meta=meta,
                            cursor={"epoch": 1, "spos": 0, "offset": 3},
                            process_index=1, process_count=2)
    assert find_latest_checkpoint(str(tmp_path)) is None
    # "process 0": barriers on PART-1, merges, commits
    elastic.save_checkpoint(path, params=params, opt_state=opt,
                            model_state=mst, optim_host_state={},
                            driver_state={"neval": 2}, run_meta=meta,
                            cursor={"epoch": 1, "spos": 2, "offset": 7},
                            process_index=0, process_count=2,
                            commit_timeout_s=10.0)
    assert find_latest_checkpoint(str(tmp_path)) == path
    verify_checkpoint(path)
    ck = load_checkpoint(path)
    assert ck["cursors"] == {
        "0": {"epoch": 1, "spos": 2, "offset": 7},
        "1": {"epoch": 1, "spos": 0, "offset": 3}}
    assert ck["sharding"]["process_count"] == 2
    # both processes' part files are digest-verified by the manifest
    import json as _json
    with open(os.path.join(path, "MANIFEST.json")) as f:
        m = _json.load(f)
    assert "params.part0.npz" in m["sha256"]
    assert "params.part1.npz" in m["sha256"]
    assert "PART-0.json" in m["sha256"] and "PART-1.json" in m["sha256"]


def test_commit_barrier_times_out_without_all_parts(devices8, tmp_path):
    """A missing process's part must fail the commit (staging stays
    invisible), never publish a partial checkpoint."""
    mesh = make_mesh([8], ["data"], devices8)
    cfg, params, opt, mst = _sharded_state(mesh)
    path = str(tmp_path / "checkpoint.2")
    meta = elastic.run_metadata(mesh=mesh, zero=cfg, process_count=2)
    with pytest.raises(TimeoutError):
        elastic.save_checkpoint(path, params=params, opt_state=opt,
                                model_state=mst, optim_host_state={},
                                driver_state={"neval": 2}, run_meta=meta,
                                process_index=0, process_count=2,
                                commit_timeout_s=0.5)
    assert find_latest_checkpoint(str(tmp_path)) is None
