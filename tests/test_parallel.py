"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4 —
multi-node simulated in one process, like the reference's multi-partition
single-JVM DistriOptimizerSpec)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _capability import shard_map_skip
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.parallel import (make_mesh, ring_attention_sharded,
                                shard_params, spec_for, validate_rules)


@pytest.fixture(scope="module")
def devices8():
    d = jax.devices()
    if len(d) < 8:
        pytest.skip("needs 8 virtual devices")
    return d[:8]


@shard_map_skip
def test_ring_attention_matches_full(devices8):
    rng = np.random.RandomState(0)
    B, H, S, D = 2, 4, 64, 16
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    mesh = Mesh(np.array(devices8), ("seq",))
    for causal in (False, True):
        ref = dot_product_attention(q, k, v, causal=causal)
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


@shard_map_skip
def test_ring_attention_grad_matches(devices8):
    rng = np.random.RandomState(1)
    B, H, S, D = 1, 2, 32, 8
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    mesh = Mesh(np.array(devices8), ("seq",))
    g_ring = jax.grad(lambda q: ring_attention_sharded(
        q, k, v, mesh, causal=True).sum())(q)
    g_full = jax.grad(lambda q: dot_product_attention(
        q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               atol=2e-5)


def test_transformer_lm_forward():
    from bigdl_tpu.models import TransformerLM
    model = TransformerLM(vocab_size=100, hidden_size=32, num_layers=2,
                          num_heads=4, max_len=64).evaluate()
    tokens = np.random.randint(0, 100, (2, 16))
    logits = np.asarray(model.forward(tokens))
    assert logits.shape == (2, 16, 100)
    assert np.isfinite(logits).all()


def test_transformer_moe_aux_loss():
    from bigdl_tpu.models import TransformerLM
    model = TransformerLM(vocab_size=50, hidden_size=32, num_layers=2,
                          num_heads=4, max_len=32, moe_experts=4,
                          moe_every=2).training()
    tokens = np.random.randint(0, 50, (2, 8))
    model.forward(tokens)
    aux = float(model.aux_loss(model.get_state()))
    # balanced routing gives aux ~= 1.0 (E * sum f_e * P_e with f=P=1/E)
    assert 0.5 < aux < 4.0


def test_moe_routes_topk():
    m = nn.MoE(16, 32, num_experts=4, top_k=2)
    x = np.random.randn(2, 6, 16).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (2, 6, 16)
    assert np.isfinite(out).all()


def test_sharding_rules_engine(devices8):
    from bigdl_tpu.models import TransformerLM
    mesh = make_mesh([2, 4], ["data", "model"], devices8)
    model = TransformerLM(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=4, max_len=32)
    model.ensure_initialized()
    params = model.get_parameters()
    rules = model.sharding_rules()
    assert validate_rules(params, mesh, rules) == []
    sharded = shard_params(params, mesh, rules)
    wq = sharded["block_0"]["attn"]["wq"]
    assert wq.sharding.spec == P(None, "model")
    emb = sharded["embed"]
    assert emb.sharding.spec == P("model", None)
    ln = sharded["block_0"]["ln1"]["weight"]
    assert ln.sharding.spec == P()


def test_spec_rank_matching():
    rules = [("w_up", P("model", None, None)), ("w_up", P(None, "model"))]
    assert spec_for("block_0/mlp/w_up", 3, rules) == P("model", None, None)
    assert spec_for("block_0/mlp/w_up", 2, rules) == P(None, "model")
    assert spec_for("unmatched", 2, rules) == P()


def test_dp_tp_train_step(devices8):
    """Full train step: dp×tp mesh, sharded params, loss decreases."""
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step

    mesh = make_mesh([2, 4], ["data", "model"], devices8)
    model = TransformerLM(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=4, max_len=16).training()
    model.ensure_initialized()

    optim = SGD(learning_rate=0.1)
    params = shard_params(model.get_parameters(), mesh,
                          model.sharding_rules())
    opt_state = optim.init_state(params)
    mstate = jax.device_put(model.get_state(), NamedSharding(mesh, P()))
    bsh = NamedSharding(mesh, P("data"))
    tokens = jax.device_put(
        jnp.asarray(np.random.randint(0, 64, (8, 16))), bsh)
    targets = jax.device_put(
        jnp.asarray(np.random.randint(0, 64, (8, 16))), bsh)
    step = build_train_step(model, nn.SequenceCrossEntropyCriterion(),
                            optim)
    rng = jax.random.PRNGKey(0)
    losses = []
    for i in range(8):
        params, opt_state, mstate, loss = step(
            params, opt_state, mstate, rng, 0.1, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # param layout survived the step (XLA kept the TP sharding)
    assert params["block_0"]["attn"]["wq"].sharding.spec == P(None, "model")


@shard_map_skip
def test_sp_ring_train_step(devices8):
    """Sequence-parallel training: mesh (data=2, seq=4), ring attention
    inside shard_map, gradients match the unsharded reference."""
    from bigdl_tpu.models import TransformerLM

    mesh = make_mesh([2, 4], ["data", "seq"], devices8)
    model = TransformerLM(vocab_size=32, hidden_size=16, num_layers=1,
                          num_heads=2, max_len=32,
                          ring_axis="seq").evaluate()
    model.ensure_initialized()
    params = model.get_parameters()
    mstate = model.get_state()
    tokens = np.random.randint(0, 32, (4, 32))

    ref_model = TransformerLM(vocab_size=32, hidden_size=16, num_layers=1,
                              num_heads=2, max_len=32).evaluate()
    ref_model.set_parameters(params).set_state(mstate)
    ref = np.asarray(ref_model.forward(tokens))

    def fwd(p, tok_shard, pos0):
        # inside shard_map: positions are global; slice pos_embed by shard
        x = p["embed"][tok_shard.astype(jnp.int32)]
        s = tok_shard.shape[1]
        pos = jax.lax.dynamic_slice_in_dim(p["pos_embed"], pos0, s)
        x = x + pos[None]
        blk = model.blocks[0]
        x, _ = blk.apply(p["block_0"], {}, x)
        x = model.ln_f.forward_fn(p["ln_f"], x)
        return x @ p["embed"].T

    def sharded_fwd(p, tokens):
        def inner(p, tok):
            pos0 = jax.lax.axis_index("seq") * tok.shape[1]
            return fwd(p, tok, pos0)
        return jax.jit(jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P("data", "seq")),
            out_specs=P("data", "seq", None),
            check_vma=False))(p, tokens)

    out = np.asarray(sharded_fwd(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(out, ref, atol=3e-4)


def test_moe_topk_clamped_to_experts():
    m = nn.MoE(8, 16, num_experts=1, top_k=2)
    out = np.asarray(m.forward(np.random.randn(1, 4, 8).astype(np.float32)))
    assert out.shape == (1, 4, 8) and np.isfinite(out).all()


def test_moe_every_one_places_moe_in_all_layers():
    from bigdl_tpu.models import TransformerLM
    lm = TransformerLM(vocab_size=16, hidden_size=16, num_layers=2,
                       num_heads=2, max_len=8, moe_experts=2, moe_every=1)
    assert all(b.moe_experts == 2 for b in lm.blocks)


def test_pos_embed_rule_not_shadowed():
    from bigdl_tpu.models import TransformerLM
    lm = TransformerLM(vocab_size=16, hidden_size=16, num_layers=1,
                       num_heads=2, max_len=10)
    rules = lm.sharding_rules()
    assert spec_for("pos_embed", 2, rules) == P()
    assert spec_for("embed", 2, rules) == P("model", None)
    assert spec_for("momentum/embed", 2, rules) == P("model", None)


def test_untied_lm_head_uncorrelated_init():
    from bigdl_tpu.models import TransformerLM
    lm = TransformerLM(vocab_size=32, hidden_size=32, num_layers=1,
                       num_heads=2, max_len=8, tie_embeddings=False)
    p = lm.get_parameters()
    corr = np.corrcoef(np.asarray(p["embed"]).ravel(),
                       np.asarray(p["lm_head"]).T.ravel())[0, 1]
    assert abs(corr) < 0.1


def test_ring_axis_rejects_dropout():
    with pytest.raises(ValueError):
        nn.MultiHeadAttention(32, 4, dropout=0.1, ring_axis="seq")


def test_sequence_cross_entropy_criterion():
    logits = np.random.randn(2, 5, 7).astype(np.float32)
    targets = np.random.randint(0, 7, (2, 5))
    c = nn.SequenceCrossEntropyCriterion()
    loss = float(c.forward(logits, targets))
    # manual reference
    from scipy.special import log_softmax
    lp = log_softmax(logits, axis=-1)
    ref = -np.mean([lp[b, s, targets[b, s]] for b in range(2)
                    for s in range(5)])
    assert abs(loss - ref) < 1e-5


def test_zero1_helper_shards_dim0(devices8):
    from bigdl_tpu.parallel import shard_opt_state_zero1
    mesh = make_mesh([8], ["data"], devices8)
    tree = {"momentum": {"w": jnp.zeros((16, 4)), "b": jnp.zeros((3,))}}
    out = shard_opt_state_zero1(tree, mesh, "data")
    assert out["momentum"]["w"].sharding.spec == P("data", None)
    assert out["momentum"]["b"].sharding.spec == P()  # 3 not divisible by 8


def test_moe_aux_loss_produces_router_gradients():
    """Review regression: the load-balance loss must reach the router
    through build_train_step's objective."""
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step

    model = TransformerLM(vocab_size=32, hidden_size=16, num_layers=2,
                          num_heads=2, max_len=8, moe_experts=4,
                          moe_every=2).training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.0)  # lr 0: isolate gradient check
    params = model.get_parameters()
    opt_state = optim.init_state(params)
    mstate = model.get_state()
    step = build_train_step(model, nn.SequenceCrossEntropyCriterion(),
                            optim, aux_loss_weight=1.0)
    # compare grads with and without aux by direct jax.grad
    import jax as _jax

    def loss_with_aux(p):
        out, st = model.apply(p, mstate, jnp.zeros((2, 8), jnp.int32),
                              training=True, rng=_jax.random.PRNGKey(0))
        from bigdl_tpu.optim.optimizer import _collect_aux_losses
        return _collect_aux_losses(st)

    g = _jax.grad(loss_with_aux)(params)
    router_g = np.asarray(g["block_1"]["mlp"]["router"])
    assert np.abs(router_g).max() > 0.0


def test_sequence_ce_clamps_out_of_range():
    logits = np.random.randn(2, 3, 5).astype(np.float32)
    bad_targets = np.array([[0, 4, 7], [5, 1, 2]])  # 7 and 5 out of range
    loss = float(nn.SequenceCrossEntropyCriterion().forward(
        logits, bad_targets))
    assert np.isfinite(loss)


def test_pretrained_child_adopted_in_all_composites():
    """Pre-materialized child weights survive wrapping in any composite."""
    lin = nn.Linear(4, 4)
    w0 = np.asarray(lin.get_parameters()["weight"]).copy()
    seq = nn.Sequential().add(lin)
    np.testing.assert_array_equal(
        np.asarray(seq.get_parameters()["0"]["weight"]), w0)
    td = nn.TimeDistributed(nn.Linear(4, 4))
    inner = td.layer if hasattr(td, "layer") else None
    if inner is not None:
        wi = np.asarray(inner.get_parameters()["weight"]).copy()
        np.testing.assert_array_equal(
            np.asarray(td.get_parameters()["layer"]["weight"]), wi)


@shard_map_skip
def test_pipeline_parallel_matches_sequential(devices8):
    """GPipe pipeline over 4 stages == sequential layer application."""
    from bigdl_tpu.parallel import pipeline_forward

    mesh = make_mesh([4], ["pipe"], devices8[:4])
    L, D = 8, 16  # 8 layers, 2 per stage
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(L, D, D).astype(np.float32) * 0.2)
    bs = jnp.asarray(rng.randn(L, D).astype(np.float32) * 0.1)

    def block_fn(layer_params, x):
        w, b = layer_params
        return jnp.tanh(x @ w + b)

    x = jnp.asarray(rng.randn(16, D).astype(np.float32))
    got = pipeline_forward(block_fn, (ws, bs), x, mesh,
                           n_microbatches=4)
    ref = x
    for i in range(L):
        ref = jnp.tanh(ref @ ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


@shard_map_skip
def test_pipeline_parallel_grad_flows(devices8):
    from bigdl_tpu.parallel import pipeline_forward
    mesh = make_mesh([4], ["pipe"], devices8[:4])
    L, D = 4, 8
    rng = np.random.RandomState(1)
    ws = jnp.asarray(rng.randn(L, D, D).astype(np.float32) * 0.3)

    def block_fn(w, x):
        return jnp.tanh(x @ w)

    x = jnp.asarray(rng.randn(8, D).astype(np.float32))

    def loss(ws):
        return pipeline_forward(block_fn, ws, x, mesh,
                                n_microbatches=2).sum()

    g = jax.grad(loss)(ws)

    def ref_loss(ws):
        h = x
        for i in range(L):
            h = jnp.tanh(h @ ws[i])
        return h.sum()

    g_ref = jax.grad(ref_loss)(ws)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)


def test_flash_attention_path_matches_einsum_on_tpu():
    """When a real TPU is present, the pallas flash path must agree with
    the einsum reference; on CPU the flash path must cleanly bypass."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.attention import dot_product_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 1024, 128), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 1024, 128), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 1024, 128), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True, use_flash=False)
    got = dot_product_attention(q, k, v, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-2 if jax.devices()[0].platform
                               == "tpu" else 1e-6, rtol=1e-2)


def test_user_aux_loss_key_does_not_join_objective():
    """The aux-loss contract is namespaced (AUX_LOSS_KEY): a user state
    leaf coincidentally named "aux_loss" must NOT be added to the loss,
    while the reserved key must (VERDICT r2 weak #7)."""
    from bigdl_tpu.nn import AUX_LOSS_KEY
    from bigdl_tpu.optim.optimizer import _collect_aux_losses

    user_tree = {"layer": {"aux_loss": jnp.asarray(7.0)}}
    assert float(_collect_aux_losses(user_tree)) == 0.0

    opted_in = {"layer": {AUX_LOSS_KEY: jnp.asarray(3.0)},
                "other": {"aux_loss": jnp.asarray(7.0)}}
    assert float(_collect_aux_losses(opted_in)) == 3.0


def test_flash_routing_is_memory_keyed():
    """The pallas kernel is an HBM escape hatch, not a speedup (measured
    on v5e: XLA einsum wins wall-clock at every length it can compile) —
    routing keys on score-matrix bytes, not sequence length."""
    import jax.numpy as jnp

    from bigdl_tpu.nn.attention import _flash_eligible

    small = jnp.zeros((2, 8, 2048, 128), jnp.bfloat16)   # 128 MB scores
    big = jnp.zeros((1, 8, 32768, 128), jnp.bfloat16)    # 17 GB scores
    assert not _flash_eligible(small, None, 0.0, False)
    assert _flash_eligible(big, None, 0.0, False)
    # masks/dropout/untileable shapes stay on the einsum path
    assert not _flash_eligible(big, object(), 0.0, False)
    assert not _flash_eligible(big, None, 0.1, True)
    odd = jnp.zeros((1, 8, 32768, 96), jnp.bfloat16)
    assert not _flash_eligible(odd, None, 0.0, False)


@shard_map_skip
def test_ulysses_attention_matches_full():
    """All-to-all sequence parallelism: seq-sharded qkv re-shard to
    head-sharded, full attention per head group, shard back — exact
    equality with single-device attention (the second long-context
    layout next to ring attention)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from bigdl_tpu.nn.attention import dot_product_attention
    from bigdl_tpu.parallel import ulysses_attention_sharded

    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), ("seq",))
    rs = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rs.randn(2, 8, 64, 16).astype(np.float32))
               for _ in range(3)]
    for causal in (False, True):
        out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
        ref = dot_product_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from bigdl_tpu.parallel import ulysses_attention_sharded

    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    q = jnp.zeros((1, 4, 64, 16))  # 4 heads on an 8-way axis
    with np.testing.assert_raises(Exception):
        np.asarray(ulysses_attention_sharded(q, q, q, mesh))


@shard_map_skip
def test_pipeline_is_differentiable_for_training():
    """PP is training-capable, not a forward-only primitive: gradients
    through the microbatched ppermute pipeline match the dense stack's
    (a GPipe step is just jax.grad through pipeline_forward)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from bigdl_tpu.parallel import pipeline_forward

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("pipe",))
    rs = np.random.RandomState(0)
    L, D = 8, 6
    ws = jnp.asarray(rs.randn(L, D, D).astype(np.float32) * 0.3)
    x = jnp.asarray(rs.randn(8, D).astype(np.float32))
    y = jnp.asarray(rs.randn(8, D).astype(np.float32))

    def block(w, h):
        return jnp.tanh(h @ w)

    def pp_loss(ws):
        out = pipeline_forward(block, ws, x, mesh, n_microbatches=4)
        return jnp.mean((out - y) ** 2)

    def dense_loss(ws):
        h = x
        for i in range(L):
            h = block(ws[i], h)
        return jnp.mean((h - y) ** 2)

    g_pp = jax.jit(jax.grad(pp_loss))(ws)
    g_dense = jax.jit(jax.grad(dense_loss))(ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_dense),
                               atol=1e-5)
    # and one SGD step on pipeline grads lowers the pipeline loss
    ws2 = ws - 0.1 * g_pp
    assert float(pp_loss(ws2)) < float(pp_loss(ws))


@shard_map_skip
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_segments_match_dense(devices8, causal):
    """Packed segment masks survive the ring rotation: key-side ids
    travel with their K/V block, so cross-document attention stays
    zero exactly as in the dense segment-masked reference."""
    rng = np.random.RandomState(7)
    B, H, S, D = 2, 4, 64, 16
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    segs = jnp.asarray(np.sort(rng.randint(0, 3, (B, S))).astype(np.int32))
    mesh = Mesh(np.array(devices8), ("seq",))
    ref = dot_product_attention(q, k, v, causal=causal, segments=segs,
                                use_flash=False)
    out = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                 segments=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


@shard_map_skip
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_segments_match_dense(devices8, causal):
    """Ulysses all-gathers the id row after the head re-shard; the
    full-sequence mask it applies is the dense one."""
    from bigdl_tpu.parallel import ulysses_attention_sharded

    rng = np.random.RandomState(8)
    B, H, S, D = 2, 8, 64, 8
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    segs = jnp.asarray(np.sort(rng.randint(0, 3, (B, S))).astype(np.int32))
    mesh = Mesh(np.array(devices8), ("seq",))
    ref = dot_product_attention(q, k, v, causal=causal, segments=segs,
                                use_flash=False)
    out = ulysses_attention_sharded(q, k, v, mesh, causal=causal,
                                    segments=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


@shard_map_skip
def test_ring_segments_jit_grad_matches_dense(devices8):
    """jit(grad) through the segment-masked ring — the custom-VJP +
    ppermute composition the train step actually runs."""
    rng = np.random.RandomState(9)
    B, H, S, D = 1, 2, 32, 8
    q, k, v = [jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3)]
    segs = jnp.asarray(np.sort(rng.randint(0, 2, (B, S))).astype(np.int32))
    mesh = Mesh(np.array(devices8), ("seq",))
    g_ring = jax.jit(jax.grad(lambda q: ring_attention_sharded(
        q, k, v, mesh, causal=True, segments=segs).sum()))(q)
    g_full = jax.jit(jax.grad(lambda q: dot_product_attention(
        q, k, v, causal=True, segments=segs,
        use_flash=False).sum()))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               atol=2e-5)


@shard_map_skip
def test_mha_adopts_seq_parallel_policy(devices8):
    """A plain MHA (no ring_axis) adopts the installed train-step
    policy: under ``use_sequence_parallel`` on a live seq mesh the
    forward matches the dense module bitwise-tolerant and the policy
    resolves the mesh width as its degree."""
    from bigdl_tpu.parallel import (SeqParallelConfig,
                                    use_sequence_parallel)

    mesh = Mesh(np.array(devices8), ("seq",))
    mha = nn.MultiHeadAttention(64, 8, causal=True)  # 8 heads: ulysses
    params = mha.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(10)
                    .randn(2, 64, 64).astype(np.float32))
    dense = np.asarray(mha.forward_fn(params, x))
    for impl in ("ring", "ulysses"):
        cfg = SeqParallelConfig(axis="seq", impl=impl, mesh=mesh)
        with use_sequence_parallel(cfg):
            out = np.asarray(mha.forward_fn(params, x))
        np.testing.assert_allclose(out, dense, atol=2e-5)
        assert cfg.active_on(mesh) and cfg.degree() == 8
