"""Per-model Train/Test entry points (reference: models/*/Train.scala,
Test.scala mains) — each recipe must run end-to-end with --synthetic."""
import numpy as np
import pytest

from _capability import shard_map_skip


def test_lenet_train_cli(tmp_path):
    from bigdl_tpu.models.lenet.train import main
    model = main(["--synthetic", "64", "-b", "16", "--maxIterations", "6",
                  "--checkpoint", str(tmp_path)])
    assert model is not None
    assert any(tmp_path.iterdir())  # checkpoint written


def test_lenet_train_cli_graph_model():
    from bigdl_tpu.models.lenet.train import main
    assert main(["--synthetic", "32", "-b", "16", "--maxIterations",
                 "2", "-g"]) is not None


def test_lenet_test_cli(capsys):
    from bigdl_tpu.models.lenet.test import main
    results = main(["--synthetic", "48", "-b", "16"])
    out = capsys.readouterr().out
    assert "Top1Accuracy" in out and results


def test_vgg_train_cli():
    from bigdl_tpu.models.vgg.train import main
    assert main(["--synthetic", "32", "-b", "16",
                 "--maxIterations", "2"]) is not None


def test_resnet_train_cli():
    from bigdl_tpu.models.resnet.train import main
    assert main(["--synthetic", "32", "-b", "16", "--depth", "20",
                 "--maxIterations", "2"]) is not None


def test_resnet_cifar10_decay_schedule():
    from bigdl_tpu.models.resnet.train import cifar10_decay
    assert cifar10_decay(1) == 0.0
    assert cifar10_decay(81) == 1.0   # x0.1 (Train.scala:34)
    assert cifar10_decay(122) == 2.0  # x0.01


def test_inception_train_cli():
    from bigdl_tpu.models.inception.train import main
    assert main(["--synthetic", "8", "-b", "4", "--classNum", "10",
                 "--maxIterations", "2"]) is not None


def test_rnn_train_cli():
    from bigdl_tpu.models.rnn.train import main
    assert main(["--synthetic", "800", "-b", "8", "--vocabSize", "30",
                 "--numSteps", "5", "--maxIterations", "3"]) is not None


def test_rnn_train_cli_ptb_from_text(tmp_path):
    p = tmp_path / "train.txt"
    p.write_text("the cat sat on the mat\n" * 40)
    from bigdl_tpu.models.rnn.train import main
    assert main(["-f", str(p), "--vocabSize", "20", "-b", "4",
                 "--numSteps", "4", "--maxIterations", "3",
                 "--ptb"]) is not None


def test_autoencoder_train_cli():
    from bigdl_tpu.models.autoencoder.train import main
    assert main(["--synthetic", "64", "-b", "32",
                 "--maxIterations", "2"]) is not None


def test_snapshot_resume_flow(tmp_path):
    """Train, snapshot with save_module, resume via --model
    (Train.scala:48-56 modelSnapshot pattern)."""
    from bigdl_tpu.models.lenet.train import main
    from bigdl_tpu.utils.serialization import save_module

    model = main(["--synthetic", "32", "-b", "16", "--maxIterations", "2"])
    snap = str(tmp_path / "lenet_snapshot")
    save_module(snap, model)
    model2 = main(["--synthetic", "32", "-b", "16", "--maxIterations", "1",
                   "--model", snap])
    assert model2 is not None


def test_lenet_test_cli_quantized(capsys):
    """--quantize evaluates the int8-rewritten model (ModelValidator's
    quantized path, example/loadmodel)."""
    from bigdl_tpu.models.lenet.test import main
    results = main(["--synthetic", "32", "-b", "16", "--quantize"])
    out = capsys.readouterr().out
    assert "Top1Accuracy" in out and results


def test_rnn_test_cli_evaluate(capsys):
    """Evaluate branch of models/rnn/Test.scala:55-90 — Loss over a
    TimeDistributed CrossEntropy, perplexity printed."""
    from bigdl_tpu.models.rnn.test import main
    results = main(["--synthetic", "400", "-b", "4", "--vocabSize", "30",
                    "--numSteps", "5"])
    out = capsys.readouterr().out
    assert "Loss" in out and "perplexity" in out and results


def test_rnn_test_cli_generate():
    """Generation branch (Test.scala:91-137) — each step appends one
    predicted token."""
    from bigdl_tpu.models.rnn.test import main
    gen = main(["--synthetic", "200", "-b", "4", "--vocabSize", "30",
                "--numSteps", "5", "--numOfWords", "3"])
    assert gen.shape[1] == 5 + 3


def test_rnn_test_cli_from_snapshot(tmp_path):
    """Trained snapshot round-trips into the test main (the reference's
    Module.load path, Test.scala:52)."""
    from bigdl_tpu.models.rnn.test import main as test_main
    from bigdl_tpu.models.rnn.train import main as train_main
    from bigdl_tpu.utils.serialization import save_module

    model = train_main(["--synthetic", "400", "-b", "4", "--vocabSize",
                        "30", "--numSteps", "5", "--maxIterations", "2"])
    snap = str(tmp_path / "rnn_snap")
    save_module(snap, model)
    results = test_main(["--synthetic", "200", "-b", "4", "--vocabSize",
                         "30", "--numSteps", "5", "--model", snap])
    assert "Loss" in results


def test_inception_test_cli(capsys):
    from bigdl_tpu.models.inception.test import main
    results = main(["--synthetic", "8", "-b", "4", "--classNum", "10"])
    out = capsys.readouterr().out
    assert "Top1Accuracy" in out and "Top5Accuracy" in out and results


def test_autoencoder_test_cli(capsys):
    from bigdl_tpu.models.autoencoder.test import main
    results = main(["--synthetic", "32", "-b", "16"])
    out = capsys.readouterr().out
    assert "Loss" in out and results


def test_rnn_dictionary_roundtrip(tmp_path):
    """Train saves the vocabulary; test reloads it so words keep their
    training-time indices (Train.scala:90 vocab.save / Test.scala:52
    Dictionary(folder))."""
    import os
    from bigdl_tpu.models.rnn.test import main as test_main
    from bigdl_tpu.models.rnn.train import main as train_main

    txt = tmp_path / "train.txt"
    txt.write_text("the cat sat on the mat\n" * 30)
    ck = tmp_path / "ck"
    train_main(["-f", str(txt), "--vocabSize", "20", "-b", "4",
                "--numSteps", "4", "--maxIterations", "2",
                "--checkpoint", str(ck)])
    dict_path = ck / "dictionary.json"
    assert dict_path.exists()
    results = test_main(["-f", str(txt), "-b", "4", "--numSteps", "4",
                         "--dictionary", str(dict_path)])
    assert "Loss" in results


def test_resnet_imagenet_train_cli():
    """ImageNet branch: ResNet-18 recipe with the fb.resnet step
    schedule; jitter/lighting flags are parsed (folder path wires them
    into ImageFolderDataSet)."""
    from bigdl_tpu.models.resnet.train import imagenet_decay, main
    assert imagenet_decay(29) == 0.0
    assert imagenet_decay(30) == 1.0
    assert imagenet_decay(60) == 2.0
    assert main(["--synthetic", "8", "-b", "4", "--dataset", "imagenet",
                 "--depth", "18", "--classNum", "10",
                 "--maxIterations", "2"]) is not None


def test_resnet_imagenet_with_val_folder(tmp_path):
    """ImageNet recipe wires a val ImageFolder for per-epoch Top1/Top5
    (Train.scala:100 valSet); tiny real-JPEG folders end to end."""
    import os
    from PIL import Image

    rng = np.random.RandomState(0)
    for split, per in (("train", 3), ("val", 2)):
        for cls in ("a", "b"):
            d = tmp_path / split / cls
            os.makedirs(d)
            for i in range(per):
                Image.fromarray(rng.randint(
                    0, 255, (240, 260, 3), np.uint8)).save(d / f"{i}.jpg")

    from bigdl_tpu.models.resnet.train import main
    m = main(["-f", str(tmp_path / "train"), "--dataset", "imagenet",
              "--depth", "18", "--classNum", "2", "-b", "2",
              "--valFolder", str(tmp_path / "val"),
              "--maxIterations", "3"])
    assert m is not None


def test_transformer_train_cli():
    # data parallelism absorbs all devices by default, so the batch must
    # divide by the device count (8 on the virtual test mesh)
    from bigdl_tpu.models.transformer.train import main
    model = main(["--synthetic", "600", "-b", "8", "--vocabSize", "30",
                  "--hiddenSize", "16", "--layers", "2", "--heads", "2",
                  "--seqLen", "8", "--maxIterations", "3"])
    assert model is not None


@shard_map_skip
def test_transformer_train_cli_pp_tp():
    import jax
    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")
    from bigdl_tpu.models.transformer.train import main
    model = main(["--synthetic", "600", "-b", "8", "--vocabSize", "32",
                  "--hiddenSize", "16", "--layers", "4", "--heads", "2",
                  "--seqLen", "8", "--pp", "2", "--tp", "2",
                  "--maxIterations", "3"])
    assert model is not None


@shard_map_skip
def test_transformer_train_cli_sp_ring():
    import jax
    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")
    from bigdl_tpu.models.transformer.train import main
    model = main(["--synthetic", "600", "-b", "4", "--vocabSize", "32",
                  "--hiddenSize", "16", "--layers", "2", "--heads", "4",
                  "--seqLen", "16", "--sp", "ring", "--spSize", "4",
                  "--maxIterations", "3"])
    assert model is not None


def test_transformer_test_cli_perplexity(capsys):
    from bigdl_tpu.models.transformer.test import main
    ppl = main(["--synthetic", "600", "-b", "4", "--vocabSize", "30",
                "--hiddenSize", "16", "--layers", "2", "--heads", "2",
                "--seqLen", "8"])
    out = capsys.readouterr().out
    assert "perplexity" in out and ppl > 0
