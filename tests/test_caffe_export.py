"""Caffe export round-trip (reference: utils/caffe/CaffePersister.scala:47):
export through CaffePersister, re-import through the own CaffeLoader, and
check the rebuilt Graph computes the same function."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.caffe import load_caffe
from bigdl_tpu.utils.caffe_persister import save_caffe


def _roundtrip(model, x, tmp_path, input_shapes=None, train=False,
               atol=1e-4):
    model.ensure_initialized()
    want, _ = model.apply(model.get_parameters(), model.get_state(), x,
                          training=False)
    dp, mp = str(tmp_path / "net.prototxt"), str(tmp_path / "net.caffemodel")
    save_caffe(model, dp, mp, input_shapes=input_shapes or [list(x.shape)])
    back = load_caffe(def_path=dp, model_path=mp).evaluate()
    back.ensure_initialized()
    got, _ = back.apply(back.get_parameters(), back.get_state(), x,
                        training=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=atol, rtol=1e-4)
    return back


def test_conv_pool_relu_fc_roundtrip(tmp_path):
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 6, 3, 3, 1, 1, 1, 1).set_name("c1"))
         .add(nn.ReLU().set_name("r1"))
         .add(nn.SpatialMaxPooling(2, 2, 2, 2).set_name("p1"))
         .add(nn.InferReshape((0, -1)).set_name("fl"))
         .add(nn.Linear(6 * 4 * 4, 5).set_name("fc")))
    x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_lrn_power_abs_softmax_roundtrip(tmp_path):
    m = (nn.Sequential()
         .add(nn.SpatialCrossMapLRN(5, 1e-4, 0.75, 1.0).set_name("lrn"))
         .add(nn.Abs().set_name("abs"))
         .add(nn.Power(2.0, 1.5, 0.5).set_name("pw")))
    x = np.random.RandomState(1).randn(2, 4, 6, 6).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_batchnorm_scale_roundtrip(tmp_path):
    m = nn.Sequential().add(
        nn.SpatialBatchNormalization(4).set_name("bn"))
    m.ensure_initialized()
    # give running stats + affine params non-trivial values — through the
    # CONTAINER tree (child-level set wouldn't reach the adopted params)
    st = dict(m.get_state())
    st["0"] = dict(st["0"])
    st["0"]["running_mean"] = np.asarray([0.5, -0.5, 1.0, 0.0], np.float32)
    st["0"]["running_var"] = np.asarray([1.5, 0.5, 2.0, 1.0], np.float32)
    m.set_state(st)
    pp = dict(m.get_parameters())
    pp["0"] = dict(pp["0"])
    pp["0"]["weight"] = np.asarray([1.1, 0.9, 1.2, 0.8], np.float32)
    pp["0"]["bias"] = np.asarray([0.1, -0.1, 0.2, 0.0], np.float32)
    m.set_parameters(pp)
    m.evaluate()
    x = np.random.RandomState(2).randn(3, 4, 5, 5).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_graph_with_concat_and_eltwise_roundtrip(tmp_path):
    inp = nn.Input()()
    c1 = nn.SpatialConvolution(3, 4, 1, 1).set_name("b1")(inp)
    c2 = nn.SpatialConvolution(3, 4, 1, 1).set_name("b2")(inp)
    add = nn.CAddTable().set_name("sum")(c1, c2)
    g = nn.Graph(inp, add)
    x = np.random.RandomState(3).randn(2, 3, 5, 5).astype(np.float32)
    _roundtrip(g, x, tmp_path)


def test_deconv_roundtrip(tmp_path):
    m = nn.Sequential().add(
        nn.SpatialFullConvolution(3, 5, 3, 3, 2, 2, 1, 1).set_name("dc"))
    x = np.random.RandomState(4).randn(2, 3, 6, 6).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_convert_model_cli_bidirectional(tmp_path):
    """ConvertModel is now bidirectional for Caffe
    (utils/ConvertModel.scala:24)."""
    from bigdl_tpu.tools.convert_model import convert
    from bigdl_tpu.utils.serialization import save_module

    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1).set_name("c"))
         .add(nn.ReLU().set_name("r")))
    m.ensure_initialized()
    saved = str(tmp_path / "saved.bigdl")
    save_module(saved, m)
    out = convert("bigdl", "caffe", saved,
                  str(tmp_path / "net.prototxt") + ","
                  + str(tmp_path / "net.caffemodel"))
    assert "net.prototxt" in out
    back = load_caffe(def_path=str(tmp_path / "net.prototxt"),
                      model_path=str(tmp_path / "net.caffemodel"))
    x = np.random.RandomState(5).randn(1, 3, 6, 6).astype(np.float32)
    want, _ = m.apply(m.get_parameters(), m.get_state(), x, training=False)
    back.ensure_initialized()
    got, _ = back.apply(back.get_parameters(), back.get_state(), x,
                        training=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_unsupported_layer_raises(tmp_path):
    m = nn.Sequential().add(nn.GradientReversal())
    with pytest.raises(ValueError, match="cannot export"):
        save_caffe(m, str(tmp_path / "a.prototxt"),
                   str(tmp_path / "a.caffemodel"))


def test_all_caps_layer_name_is_quoted(tmp_path):
    """An all-caps layer name (e.g. BN1) must still emit quoted
    name/bottom/top strings — only enum parameter values (pool: MAX) are
    written bare (advisor r2, caffe_persister.py:44)."""
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)
              .set_name("CONV1"))
         .add(nn.ReLU().set_name("RELU1"))
         .add(nn.SpatialMaxPooling(2, 2, 2, 2).set_name("POOL1")))
    x = np.random.RandomState(0).randn(1, 3, 8, 8).astype(np.float32)
    _roundtrip(m, x, tmp_path)
    text = (tmp_path / "net.prototxt").read_text()
    assert 'name: "CONV1"' in text and 'top: "CONV1"' in text
    assert 'bottom: "CONV1"' in text
    assert "name: CONV1" not in text
    # enum values stay bare
    assert "pool: MAX" in text


def test_alexnet_roundtrip(tmp_path):
    """The load-model example's AlexNet (grouped convs + LRN) survives
    export->import bit-exact in function (ModelValidator's Caffe path).
    Exported up to the logits, the form Caffe AlexNets ship in (Caffe
    has no LogSoftmax layer; the reference persister had the same
    boundary)."""
    from bigdl_tpu.models import AlexNet
    full = AlexNet(10, has_dropout=False)
    full.ensure_initialized()
    m = nn.Sequential()
    for child in full.modules[:-1]:
        m.add(child)
    m.evaluate()
    x = np.random.RandomState(0).rand(1, 3, 227, 227).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_inception_v2_block_roundtrip(tmp_path):
    """A BN-Inception block (conv/bn triples, avg-pool branch, channel
    concat) round-trips through the BatchNorm+Scale pair encoding."""
    from bigdl_tpu.models.inception import Inception_Layer_v2
    from bigdl_tpu.utils.table import T
    m = nn.Sequential().add(
        Inception_Layer_v2(32, T(T(16), T(8, 16), T(8, 16), T("avg", 8)),
                           "i3a/")).evaluate()
    x = np.random.RandomState(1).rand(1, 32, 14, 14).astype(np.float32)
    # BN rsqrt recompute order differs between export/import forms;
    # differences are pure float noise (max ~6e-4)
    _roundtrip(m, x, tmp_path, atol=2e-3)
