"""Worker for the kill-and-resume fault-tolerance test (spawned by
``bigdl_tpu.tools.launch``; not itself a pytest file).

Trains a small deterministic model over a 2-process spanning mesh with
periodic checkpoints into ONE shared directory (single-writer: process 0
writes, both resume from it — the reference's driver-side checkpoint,
DistriOptimizer.scala:433-463). When ``kill_at > 0``, process 1 SIGKILLs
ITSELF right before that iteration — but only on the first incarnation
(``BIGDL_RESTART_ATTEMPT == 0``), the scripted-failure pattern of the
reference's ExceptionTest (test/.../utils/TestUtils.scala:103-131). The
relaunched gang resumes from the latest checkpoint; because the feed is
the epoch-exact device cache (a pure function of the iteration number),
the augmentation is deterministic, and the checkpoint captures
params + momentum + driver state, the final loss must equal an
uninterrupted run's bit-for-bit.

When ``crash_ckpt_at`` is given, the WRITER process instead dies MID
checkpoint-write at that neval (after the tree files, before the
MANIFEST — serialization._maybe_scripted_crash), leaving a torn tmp
dir; the restarted gang must resume from the previous INTACT
checkpoint and still reach the uninterrupted run's final loss.

argv: ckpt_root kill_at [crash_ckpt_at]
"""
import json
import os
import signal
import sys


def main():
    ckpt_root, kill_at = sys.argv[1], int(sys.argv[2])
    crash_ckpt_at = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    arm_crash = crash_ckpt_at and int(os.environ.get(
        "BIGDL_RESTART_ATTEMPT", "0")) == 0
    if arm_crash:
        # the mid-checkpoint-write SIGKILL (first incarnation only —
        # the resumed gang replays the same neval and must survive it);
        # armed explicitly below: the env var alone is inert
        os.environ["BIGDL_TEST_CRASH_IN_CHECKPOINT"] = str(crash_ckpt_at)

    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.extend.backend.clear_backends()
    except Exception:
        pass

    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bigdl_tpu.utils.engine import Engine

    Engine.init_distributed(initialization_timeout=60)
    pid = jax.process_index()
    attempt = int(os.environ.get("BIGDL_RESTART_ATTEMPT", "0"))

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet
    from bigdl_tpu.optim import SGD, max_iteration, several_iteration
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.utils.random import RandomGenerator

    if arm_crash:
        from bigdl_tpu.utils import serialization
        serialization.arm_scripted_crash()

    mesh = Mesh(np.array(jax.devices()), ("data",))
    sh = NamedSharding(mesh, P("data"))
    r = np.random.RandomState(100 + pid)
    imgs = r.randint(0, 255, (16, 3, 8, 8), np.uint8)
    lbls = (r.randint(0, 2, 16) + 1).astype(np.float32)
    # full-size crop + no flip: augmentation is deterministic, and the
    # epoch-exact Feistel walk makes every batch a pure function of the
    # iteration number — resume-exact by construction
    ds = DeviceCachedArrayDataSet(imgs, lbls, batch_size=8, flip=False,
                                  mean=(127,) * 3, std=(64,) * 3,
                                  sharding=sh, shuffle_seed=5)

    class KillingSGD(SGD):
        """SGD that scripts a worker death before iteration kill_at
        (first incarnation of process 1 only)."""

        def update_hyper_parameter(self):
            self.state["_it"] = self.state.get("_it", 0) + 1
            if (kill_at and pid == 1 and attempt == 0
                    and self.state["_it"] == kill_at):
                os.kill(os.getpid(), signal.SIGKILL)
            return super().update_hyper_parameter()

    RandomGenerator.set_seed(42)
    model = (nn.Sequential().add(nn.Reshape((3 * 8 * 8,)))
             .add(nn.Linear(3 * 8 * 8, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=8,
                    mesh=mesh)
    opt.set_optim_method(KillingSGD(learning_rate=0.2, momentum=0.9))
    # ONE shared checkpoint dir: process 0 writes (single-writer), both
    # ranks resume from it
    opt.set_checkpoint(ckpt_root, several_iteration(2))
    opt.set_end_when(max_iteration(8))
    opt.optimize()

    print(json.dumps({"ok": True, "pid": pid, "attempt": attempt,
                      "final_loss": opt.driver_state["Loss"],
                      "neval": opt.driver_state["neval"]}))


if __name__ == "__main__":
    main()
