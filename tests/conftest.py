"""Test config: force an 8-device virtual CPU platform BEFORE jax imports,
so sharding/mesh tests run anywhere (SURVEY.md §4 — the reference simulates
multi-node with multiple partitions in one JVM; we simulate a pod with
virtual CPU devices)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

# The container's sitecustomize imports jax (registering the TPU/axon
# backend) before this file runs, so env vars alone are too late; force the
# platform through the live config instead.
import jax

jax.config.update("jax_platforms", "cpu")

import threading
import time

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.utils.random import RandomGenerator
    RandomGenerator.set_seed(42)
    np.random.seed(42)
    yield


#: test modules exercising the package's thread-owning surfaces; each
#: must return the live non-daemon thread count to its baseline (the
#: PR 4 batcher-drain regression, generalized package-wide)
_THREAD_SURFACE_MODULES = ("tests.test_serving", "tests.test_generation",
                          "tests.test_fleet", "tests.test_elastic",
                          "test_serving", "test_generation",
                          "test_fleet", "test_elastic")


def _live_non_daemon():
    return {t for t in threading.enumerate()
            if t.is_alive() and not t.daemon
            and t is not threading.main_thread()}


@pytest.fixture(scope="module", autouse=True)
def _no_thread_leak(request):
    """A concurrency-surface test module must not leak non-daemon
    threads: every batcher/loop/replica/writer it starts must be shut
    down by module end (daemon workers are excluded — supervised
    worker threads are daemonized by design and die with the process).
    A short grace poll absorbs joins that are in flight at teardown."""
    name = request.module.__name__
    if not name.startswith(_THREAD_SURFACE_MODULES):
        yield
        return
    baseline = _live_non_daemon()
    yield
    deadline = time.monotonic() + 5.0
    while _live_non_daemon() - baseline \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    leaked = _live_non_daemon() - baseline
    assert not leaked, (
        f"{name} leaked non-daemon threads: "
        f"{sorted(t.name for t in leaked)}")
