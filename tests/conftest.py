"""Test config: force an 8-device virtual CPU platform BEFORE jax imports,
so sharding/mesh tests run anywhere (SURVEY.md §4 — the reference simulates
multi-node with multiple partitions in one JVM; we simulate a pod with
virtual CPU devices)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

# The container's sitecustomize imports jax (registering the TPU/axon
# backend) before this file runs, so env vars alone are too late; force the
# platform through the live config instead.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    from bigdl_tpu.utils.random import RandomGenerator
    RandomGenerator.set_seed(42)
    np.random.seed(42)
    yield
