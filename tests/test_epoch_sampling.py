"""Epoch-exact sampling semantics (reference: dataset/DataSet.scala:240
CachedDistriDataSet.shuffle, :110 LocalDataSet — a fresh permutation per
epoch, every sample visited exactly once per epoch) for BOTH feed paths:
the device-cached HBM feed and the threaded host ImageFolder pool."""
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet
from bigdl_tpu.dataset.imagenet import _IndexStream


def _make_ds(n, b, seed=0):
    rs = np.random.RandomState(0)
    imgs = rs.randint(0, 255, (n, 3, 8, 8), np.uint8)
    lbls = np.arange(n, dtype=np.float32)
    return DeviceCachedArrayDataSet(imgs, lbls, b, shuffle_seed=seed)


def test_device_feed_visits_each_index_once_per_epoch():
    n, b = 24, 6
    ds = _make_ds(n, b)
    fn = jax.jit(ds.sample_indices)
    idx = np.concatenate([np.asarray(fn(jnp.int32(s)))
                          for s in range(n // b)])
    assert sorted(idx.tolist()) == list(range(n))


def test_device_feed_epochs_are_distinct_permutations():
    n, b = 24, 6
    ds = _make_ds(n, b)
    fn = jax.jit(ds.sample_indices)
    ep0 = np.concatenate([np.asarray(fn(jnp.int32(s)))
                          for s in range(n // b)])
    ep1 = np.concatenate([np.asarray(fn(jnp.int32(s)))
                          for s in range(n // b, 2 * n // b)])
    assert sorted(ep1.tolist()) == list(range(n))
    assert ep0.tolist() != ep1.tolist()  # reshuffled between epochs


def test_device_feed_straddling_batches_stay_exact():
    """n not divisible by b: batches cross epoch boundaries, but every n
    consecutive samples of the stream still form a permutation."""
    n, b = 20, 6
    ds = _make_ds(n, b)
    fn = jax.jit(ds.sample_indices)
    stream = np.concatenate([np.asarray(fn(jnp.int32(s)))
                             for s in range(3 * n // b)])  # 60 = 3 epochs
    for e in range(3):
        chunk = stream[e * n:(e + 1) * n]
        assert sorted(chunk.tolist()) == list(range(n)), f"epoch {e}"


def test_device_feed_batch_matches_indices():
    """batch_fn(rng, step) must gather exactly sample_indices(step)."""
    n, b = 12, 4
    ds = _make_ds(n, b)
    idx = np.asarray(ds.sample_indices(jnp.int32(2)))
    _, y = ds.batch_fn(jax.random.PRNGKey(0), jnp.int32(2))
    np.testing.assert_array_equal(np.asarray(y), idx.astype(np.float32))


def test_device_feed_resume_is_deterministic():
    """The stream is a pure function of step: resuming from iteration k
    replays the identical visit order (checkpoint-resume semantics)."""
    ds = _make_ds(24, 6, seed=3)
    a = np.asarray(ds.sample_indices(jnp.int32(7)))
    ds2 = _make_ds(24, 6, seed=3)
    b = np.asarray(ds2.sample_indices(jnp.int32(7)))
    np.testing.assert_array_equal(a, b)


def test_index_stream_single_thread_exact():
    st = _IndexStream(13, seed=0)
    ep0 = st.next(13)
    assert sorted(ep0.tolist()) == list(range(13))
    ep1 = st.next(13)
    assert sorted(ep1.tolist()) == list(range(13))
    assert ep0.tolist() != ep1.tolist()


def test_index_stream_straddling_pulls():
    st = _IndexStream(10, seed=1)
    chunks = [st.next(4) for _ in range(5)]  # 20 = 2 epochs
    flat = np.concatenate(chunks)
    assert sorted(flat[:10].tolist()) == list(range(10))
    assert sorted(flat[10:].tolist()) == list(range(10))


def test_index_stream_concurrent_workers_exact():
    """4 threads pulling concurrently: over 8 epochs' worth of pulls the
    union contains every index exactly 8 times."""
    import threading
    n, k, pulls = 16, 4, 8  # 4 threads * 8 pulls * 4 = 128 = 8 epochs
    st = _IndexStream(n, seed=2)
    got = []
    lock = threading.Lock()

    def worker():
        local = []
        for _ in range(pulls):
            local.append(st.next(k))
        with lock:
            got.extend(local)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    flat = np.concatenate(got)
    assert len(flat) == 128
    counts = np.bincount(flat, minlength=n)
    assert (counts == 128 // n).all(), counts


def test_image_folder_pool_epoch_exact(tmp_path):
    """End-to-end through ImageFolderDataSet: 6 solid-color images, batch
    2 — the first 3 train batches decode to exactly the 6 colors."""
    from PIL import Image

    from bigdl_tpu.dataset.imagenet import ImageFolderDataSet
    colors = [15, 55, 95, 135, 175, 215]
    for i, v in enumerate(colors):
        cdir = tmp_path / f"class{i % 2}"
        cdir.mkdir(exist_ok=True)
        Image.fromarray(np.full((8, 8, 3), v, np.uint8)).save(
            cdir / f"img{i}.png")
    # one worker: batch DELIVERY order then matches the index stream
    # exactly (with several workers the multiset per epoch is still exact
    # — test_index_stream_concurrent_workers_exact — but a fast worker's
    # later batch can be dequeued before a slow worker's earlier one)
    ds = ImageFolderDataSet(str(tmp_path), batch_size=2, crop=8, scale=8,
                            mean=(0, 0, 0), std=(1, 1, 1), num_threads=1,
                            prefetch=2, seed=0)
    try:
        it = ds.data(train=True)
        seen = []
        for _ in range(3):
            batch = next(it)
            # solid color -> any pixel identifies the source image
            seen.extend(int(round(v))
                        for v in np.asarray(batch.input)[:, 0, 0, 0])
        assert sorted(seen) == sorted(colors), seen
    finally:
        ds.close()


def test_optimizer_device_feed_is_epoch_exact(tmp_path):
    """Through the real Optimizer loop: with a criterion that returns
    sum(labels) and labels = powers of two, per-epoch loss totals prove
    every sample was visited exactly once per epoch."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.module import Criterion
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration
    from bigdl_tpu.visualization import TrainSummary

    n, b = 8, 2
    rs = np.random.RandomState(0)
    imgs = rs.randint(0, 255, (n, 3, 4, 4), np.uint8)
    lbls = (2.0 ** np.arange(n)).astype(np.float32)  # unique bitmask ids
    ds = DeviceCachedArrayDataSet(imgs, lbls, b, shuffle_seed=1)

    class LabelSum(Criterion):
        def apply(self, output, target):
            return jnp.sum(target) + 0.0 * jnp.sum(output)

    model = (nn.Sequential().add(nn.InferReshape((0, -1)))
             .add(nn.Linear(48, 1)))
    steps = 2 * (n // b)  # two epochs
    summary = TrainSummary(str(tmp_path), "epoch_exact")
    opt = (LocalOptimizer(model, ds, LabelSum())
           .set_optim_method(SGD(learning_rate=0.0))
           .set_end_when(max_iteration(steps))
           .set_train_summary(summary))
    opt.optimize()
    losses = [v for _, v, _ in summary.read_scalar("Loss")]
    assert len(losses) == steps
    half = n // b
    # sum of one epoch's per-step label sums == sum of ALL unique labels
    assert sum(losses[:half]) == float(lbls.sum())
    assert sum(losses[half:]) == float(lbls.sum())
    # and the two epochs used different batch compositions (reshuffle)
    assert losses[:half] != losses[half:]


def test_optimizer_rollover_batch_larger_than_dataset(tmp_path):
    """batch_size > ds_size: one step consumes several epochs; the driver
    must advance epoch accordingly and keep the record counter bounded."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    n, b = 4, 10
    rs = np.random.RandomState(0)
    imgs = rs.randint(0, 255, (n, 3, 4, 4), np.uint8)
    lbls = np.ones(n, np.float32)
    ds = DeviceCachedArrayDataSet(imgs, lbls, b)
    model = (nn.Sequential().add(nn.InferReshape((0, -1)))
             .add(nn.Linear(48, 1)))
    opt = (LocalOptimizer(model, ds, nn.MSECriterion())
           .set_optim_method(SGD(learning_rate=0.0))
           .set_end_when(max_iteration(3)))
    opt.optimize()
    # 3 steps x 10 records = 30 = 7 full epochs of 4 + 2 leftover
    assert opt.driver_state["epoch"] == 1 + 30 // n
    assert opt.driver_state["recordsProcessedThisEpoch"] == 30 % n


def test_host_path_rollover_resets_counter(tmp_path):
    """Non-device feeds restart their iterator at a fresh permutation on
    rollover, so the overshoot is discarded (reset to 0), not carried —
    otherwise the tail of each new permutation would be skipped."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    n, b = 10, 4
    X = np.random.RandomState(0).randn(n, 4).astype(np.float32)
    Y = np.ones((n, 1), np.float32)
    ds = DataSet.array([Sample(X[i], Y[i]) for i in range(n)]) \
        .transform(SampleToMiniBatch(b))
    opt = (LocalOptimizer(nn.Linear(4, 1), ds, nn.MSECriterion())
           .set_optim_method(SGD(learning_rate=0.0))
           .set_end_when(max_iteration(5)))
    opt.optimize()
    # epoch 1: 3 batches = 12 records -> rollover resets to 0;
    # epoch 2: 2 more batches = 8 records, no rollover yet
    assert opt.driver_state["epoch"] == 2
    assert opt.driver_state["recordsProcessedThisEpoch"] == 8


def test_device_feed_exact_at_awkward_sizes():
    """Stress the Feistel cycle-walk: n just above a power of two (worst
    domain expansion) stays exactly-once-per-epoch."""
    for n, b in ((17, 4), (129, 8), (1000, 64)):
        ds = _make_ds(n, b)
        fn = jax.jit(ds.sample_indices)
        spe = -(-n * 1 // b)  # enough steps to cover one epoch
        stream = np.concatenate([np.asarray(fn(jnp.int32(s)))
                                 for s in range(spe + 1)])
        chunk = stream[:n]
        assert sorted(chunk.tolist()) == list(range(n)), (n, b)


def test_image_folder_rollover_carries_overshoot(tmp_path):
    """ImageFolderDataSet is a continuous stream (its _IndexStream never
    restarts), so the driver carries straddle overshoot across epochs
    instead of resetting — the epoch counter tracks the stream's true
    permutation boundaries."""
    import bigdl_tpu.nn as nn
    from PIL import Image

    from bigdl_tpu.dataset.imagenet import ImageFolderDataSet
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    for i in range(6):
        cdir = tmp_path / f"c{i % 2}"
        cdir.mkdir(exist_ok=True)
        Image.fromarray(np.full((6, 6, 3), 40 * i, np.uint8)).save(
            cdir / f"i{i}.png")
    ds = ImageFolderDataSet(str(tmp_path), batch_size=4, crop=6, scale=6,
                            mean=(0, 0, 0), std=(1, 1, 1), num_threads=1,
                            prefetch=2, seed=0)
    model = (nn.Sequential().add(nn.InferReshape((0, -1)))
             .add(nn.Linear(108, 1)))
    try:
        opt = (LocalOptimizer(model, ds, nn.MSECriterion())
               .set_optim_method(SGD(learning_rate=0.0))
               .set_end_when(max_iteration(3)))  # 12 records = 2 epochs
        opt.optimize()
        assert opt.driver_state["epoch"] == 3
        assert opt.driver_state["recordsProcessedThisEpoch"] == 0
    finally:
        ds.close()


def test_cursor_form_matches_step_form():
    """(epoch, pos) cursor (overflow-free long-run form) must produce the
    same indices as the equivalent global step."""
    n, b = 20, 6
    ds = _make_ds(n, b)
    for s in range(7):
        e, p = divmod(s * b, n)
        a = np.asarray(ds.sample_indices(jnp.int32(s)))
        c = np.asarray(ds.sample_indices(epoch=jnp.int32(e),
                                         pos=jnp.int32(p)))
        np.testing.assert_array_equal(a, c, err_msg=f"step {s}")


def test_continuous_stream_flag_survives_transform(tmp_path):
    """.transform() wrapping must forward continuous_stream, or the
    optimizer's rollover would wrongly reset the record counter for a
    wrapped ImageFolderDataSet."""
    from PIL import Image

    from bigdl_tpu.dataset.dataset import TransformedDataSet
    from bigdl_tpu.dataset.imagenet import ImageFolderDataSet
    from bigdl_tpu.dataset.transformer import Transformer

    cdir = tmp_path / "c0"
    cdir.mkdir()
    Image.fromarray(np.zeros((6, 6, 3), np.uint8)).save(cdir / "i.png")
    ds = ImageFolderDataSet(str(tmp_path), batch_size=1, crop=6, scale=6,
                            num_threads=1)

    class Identity(Transformer):
        def apply(self, it):
            return it

    wrapped = TransformedDataSet(ds, Identity())
    assert wrapped.continuous_stream is True
    ds.close()


def test_half_cursor_is_rejected():
    """Passing only half of the (epoch, pos) cursor must raise, not fall
    back to with-replacement sampling or fail opaquely."""
    ds = _make_ds(8, 4)
    key = jax.random.PRNGKey(0)
    with pytest.raises(ValueError, match="epoch and pos"):
        ds.batch_fn(key, pos=jnp.int32(0))
    with pytest.raises(ValueError, match="epoch and pos"):
        ds.batch_fn(key, epoch=jnp.int32(0))
    with pytest.raises(ValueError, match="epoch and pos"):
        ds.sample_indices(epoch=jnp.int32(0))
