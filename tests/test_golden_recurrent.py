"""Golden checks for the recurrent stack against real PyTorch RNN/LSTM/GRU
with COPIED weights (reference torch/ suite role, SURVEY.md §4.2):
sequence outputs must match step-for-step, not just shapes."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bigdl_tpu.nn as nn  # noqa: E402


def _x(shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def _run_recurrent(cell, x):
    m = nn.Recurrent(cell)
    m.ensure_initialized()
    p = m.get_parameters()
    out, _ = m.apply(p, m.get_state(), x, training=False)
    return np.asarray(out), {k: np.asarray(v)
                             for k, v in dict(p["cell"]).items()}


def test_rnn_cell_matches_torch_rnn():
    B, T, I, H = 2, 5, 3, 4
    x = _x((B, T, I))
    out, p = _run_recurrent(nn.RnnCell(I, H, nn.Tanh()), x)
    ref = torch.nn.RNN(I, H, batch_first=True)
    with torch.no_grad():
        ref.weight_ih_l0.copy_(torch.tensor(p["w_ih"]))
        ref.weight_hh_l0.copy_(torch.tensor(p["w_hh"]))
        ref.bias_ih_l0.copy_(torch.tensor(p["bias"]))
        ref.bias_hh_l0.zero_()
    want, _ = ref(torch.tensor(x))
    np.testing.assert_allclose(out, want.detach().numpy(), atol=1e-5)


def test_lstm_matches_torch_lstm():
    B, T, I, H = 2, 6, 3, 5
    x = _x((B, T, I), 1)
    out, p = _run_recurrent(nn.LSTM(I, H, 0.0), x)
    ref = torch.nn.LSTM(I, H, batch_first=True)
    with torch.no_grad():  # both use gate order (i, f, g, o)
        ref.weight_ih_l0.copy_(torch.tensor(p["w_ih"]))
        ref.weight_hh_l0.copy_(torch.tensor(p["w_hh"]))
        ref.bias_ih_l0.copy_(torch.tensor(p["bias"]))
        ref.bias_hh_l0.zero_()
    want, _ = ref(torch.tensor(x))
    np.testing.assert_allclose(out, want.detach().numpy(), atol=1e-5)


def test_gru_matches_torch_gru():
    B, T, I, H = 2, 5, 4, 3
    x = _x((B, T, I), 2)
    out, p = _run_recurrent(nn.GRU(I, H, 0.0), x)
    ref = torch.nn.GRU(I, H, batch_first=True)
    with torch.no_grad():  # torch packs (r, z, n); ours is (r,z) + n
        ref.weight_ih_l0.copy_(torch.tensor(
            np.concatenate([p["w_ih"], p["w_ih_n"]], axis=0)))
        ref.weight_hh_l0.copy_(torch.tensor(
            np.concatenate([p["w_hh"], p["w_hh_n"]], axis=0)))
        ref.bias_ih_l0.copy_(torch.tensor(
            np.concatenate([p["bias"], p["bias_n"]])))
        ref.bias_hh_l0.zero_()
    want, _ = ref(torch.tensor(x))
    np.testing.assert_allclose(out, want.detach().numpy(), atol=1e-5)


def test_lstm_peephole_manual_step():
    """No torch analogue: verify one step against the written-out math
    (LSTMPeephole.scala gate equations)."""
    I, H = 3, 4
    cell = nn.LSTMPeephole(I, H)
    cell.ensure_initialized()
    p = {k: np.asarray(v) for k, v in dict(cell.get_parameters()).items()}
    x = _x((2, I), 3)
    h = _x((2, H), 4) * 0.1
    c = _x((2, H), 5) * 0.1

    def sig(v):
        return 1 / (1 + np.exp(-v))

    gates = x @ p["w_ih"].T + h @ p["w_hh"].T + p["bias"]
    gi, gf, gg, go = np.split(gates, 4, axis=-1)
    i = sig(gi + p["w_ci"] * c)
    f = sig(gf + p["w_cf"] * c)
    g = np.tanh(gg)
    c2 = f * c + i * g
    o = sig(go + p["w_co"] * c2)
    want_h = o * np.tanh(c2)

    from bigdl_tpu.utils.table import T
    out, hid = cell.step(cell.get_parameters(), jnp.asarray(x),
                         T(jnp.asarray(h), jnp.asarray(c)))
    np.testing.assert_allclose(np.asarray(out), want_h, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hid[2]), c2, atol=1e-5)


def test_bi_recurrent_concat_of_directions():
    B, T, I, H = 2, 4, 3, 4
    x = _x((B, T, I), 6)
    m = nn.BiRecurrent().add(nn.RnnCell(I, H, nn.Tanh()))
    m.ensure_initialized()
    p = m.get_parameters()
    out, _ = m.apply(p, m.get_state(), x, training=False)
    out = np.asarray(out)
    assert out.shape == (B, T, 2 * H)
    # forward half equals a plain Recurrent with the fwd params
    fwd = nn.Recurrent(nn.RnnCell(I, H, nn.Tanh()))
    fwd.ensure_initialized()
    yf, _ = fwd.apply(p["fwd"], {}, x, training=False)
    np.testing.assert_allclose(out[:, :, :H], np.asarray(yf), atol=1e-5)
    # backward half equals running on the reversed sequence, reversed back
    yb, _ = fwd.apply(p["bwd"], {}, np.ascontiguousarray(x[:, ::-1]),
                      training=False)
    np.testing.assert_allclose(out[:, :, H:],
                               np.asarray(yb)[:, ::-1], atol=1e-5)


def test_recurrent_decoder_feeds_back_output():
    I = H = 3  # feedback needs out_dim == in_dim
    cell = nn.RnnCell(I, H, nn.Tanh())
    m = nn.RecurrentDecoder(4, cell)
    m.ensure_initialized()
    p = m.get_parameters()
    x0 = _x((2, I), 7)
    out, _ = m.apply(p, m.get_state(), x0, training=False)
    out = np.asarray(out)
    assert out.shape == (2, 4, H)
    # manual feedback loop
    pc = {k: np.asarray(v) for k, v in dict(p["cell"]).items()}
    h = np.zeros((2, H), np.float32)
    xin = x0
    for t in range(4):
        h = np.tanh(xin @ pc["w_ih"].T + h @ pc["w_hh"].T + pc["bias"])
        np.testing.assert_allclose(out[:, t], h, atol=1e-5)
        xin = h


def test_conv_lstm_peephole_shapes_and_state():
    B, T, C, Hh, Ww = 2, 3, 2, 5, 5
    x = _x((B, T, C, Hh, Ww), 8)
    m = nn.Recurrent(nn.ConvLSTMPeephole(C, 4, 3, 3, 1))
    m.ensure_initialized()
    out, _ = m.apply(m.get_parameters(), m.get_state(), x, training=False)
    out = np.asarray(out)
    assert out.shape == (B, T, 4, Hh, Ww)
    assert np.isfinite(out).all()
    # the sequence must actually depend on earlier frames (stateful)
    x2 = x.copy()
    x2[:, 0] += 1.0
    out2, _ = m.apply(m.get_parameters(), m.get_state(), x2,
                      training=False)
    assert not np.allclose(np.asarray(out2)[:, -1], out[:, -1])


def test_time_distributed_equals_per_step():
    B, T, F_ = 2, 5, 4
    x = _x((B, T, F_), 9)
    m = nn.TimeDistributed(nn.Linear(F_, 3))
    m.ensure_initialized()
    p = m.get_parameters()
    out, _ = m.apply(p, m.get_state(), x, training=False)
    w = np.asarray(p["layer"]["weight"])
    b = np.asarray(p["layer"]["bias"])
    want = x @ w.T + b
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)


def test_conv_lstm_peephole_3d_shapes():
    B, T, C, D, Hh, Ww = 1, 2, 2, 4, 4, 4
    x = _x((B, T, C, D, Hh, Ww), 10)
    m = nn.Recurrent(nn.ConvLSTMPeephole3D(C, 3, 3, 3, 1))
    m.ensure_initialized()
    out, _ = m.apply(m.get_parameters(), m.get_state(), x, training=False)
    out = np.asarray(out)
    assert out.shape == (B, T, 3, D, Hh, Ww)
    assert np.isfinite(out).all()


def test_custom_stochastic_cell_keeps_rng_via_uses_rng_flag():
    """ADVICE r5: rng-drop must key on the explicit Cell.uses_rng
    capability, not on the presence of a `p` attribute — a custom
    stochastic cell that doesn't follow the built-in dropout convention
    must still receive per-step keys."""
    from bigdl_tpu.nn.recurrent import Cell, Recurrent

    class NoisyCell(Cell):
        def __init__(self, size):
            super().__init__()
            self.hidden_size = size

        def init(self, rng):
            return {}

        def init_hidden(self, batch_size, dtype=None):
            return jnp.zeros((batch_size, self.hidden_size),
                             dtype or jnp.float32)

        def step(self, params, x, hidden, *, training=False, rng=None):
            if rng is not None:
                x = x + jax.random.normal(rng, x.shape)
            h = jnp.tanh(x + hidden)
            return h, h

    x = _x((2, 5, 4))

    def run(cell, seed):
        m = Recurrent(cell)
        m.ensure_initialized()
        out, _ = m.apply(m.get_parameters(), m.get_state(), x,
                         training=True, rng=jax.random.PRNGKey(seed))
        return np.asarray(out)

    # no flag, no `p`: the rng is dropped (scan-carry optimization) —
    # the cell runs deterministically and seeds don't matter
    assert np.allclose(run(NoisyCell(4), 0), run(NoisyCell(4), 1))

    noisy = NoisyCell(4)
    noisy.uses_rng = True  # explicit capability: keep per-step keys
    assert noisy.consumes_rng()
    a, b = run(noisy, 0), run(noisy, 1)
    assert not np.allclose(a, b)  # rng actually reached the cell
    np.testing.assert_allclose(a, run(noisy, 0), atol=1e-6)

    # built-in convention still derives the default from `p`
    assert nn.LSTM(4, 4, p=0.5).consumes_rng()
    assert not nn.LSTM(4, 4).consumes_rng()
