"""Pallas kernel layer (bigdl_tpu.kernels, ISSUE 12): interpret-mode
equivalence of all three kernels against the pure-jnp fallback on CPU
— the real kernel bodies execute in tier-1. Pins the load-bearing
claims: the flash forward is tolerance-bounded vs the einsum reference
and its backward passes a gradient check vs ``jax.grad`` of the
reference; the packed-slab segment-mask case is BIT-EXACT per token vs
the unpacked reference; the ragged decode kernel matches the
length-masked reference at EVERY length in a bucket (length 1 and
bucket max included); the int8 kernel is BITWISE equal to
dequantize-then-matmul; greedy decode through the service stays
token-bit-identical to full re-forward with kernels enabled; the
per-bucket compiled-program count stays <= 2 per version (kernel
variants add no program keys); and program profiles carry the
``kernel=pallas|reference`` label the bench KERNELS row compares."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import kernels
from bigdl_tpu.kernels.flash_attention import (blockwise_flash_attention,
                                               fit_block,
                                               flash_attention)
from bigdl_tpu.kernels.int8_gemm import pallas_quantized_matmul
from bigdl_tpu.kernels.ragged_decode import ragged_decode_attention
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.utils.random import RandomGenerator

ON = kernels.KernelConfig.all_on(interpret=True)
OFF = kernels.KernelConfig.off()


def _qkv(b=2, h=2, s=32, d=8, seed=0):
    r = np.random.default_rng(seed)
    return tuple(jnp.asarray(r.standard_normal((b, h, s, d))
                             .astype(np.float32)) for _ in range(3))


def _ref_attention(q, k, v, causal=False, mask=None):
    """The einsum reference — the exact fallback path
    ``nn.attention.dot_product_attention`` runs with kernels off."""
    from bigdl_tpu.nn.attention import dot_product_attention
    with kernels.use(OFF):
        return dot_product_attention(q, k, v, causal=causal, mask=mask,
                                     use_flash=False)


def _tiny_lm(vocab=50, seed=3):
    RandomGenerator.set_seed(seed)
    m = TransformerLM(vocab_size=vocab, hidden_size=16, num_layers=2,
                      num_heads=2, max_len=64).evaluate()
    m.ensure_initialized()
    return m


# ------------------------------------------------------------- config

class TestKernelConfig:
    def test_env_grammar(self):
        on = kernels.KernelConfig.from_env("1")
        assert on.flash_attention and on.decode_attention \
            and on.int8_matmul
        off = kernels.KernelConfig.from_env("off")
        assert not off.any_enabled
        subset = kernels.KernelConfig.from_env("flash,int8")
        assert subset.flash_attention and subset.int8_matmul
        assert not subset.decode_attention
        with pytest.raises(ValueError):
            kernels.KernelConfig.from_env("flash,warp")  # typo is loud

    def test_default_off_on_cpu_and_label(self):
        # tier-1 runs on CPU: the resolved default must be the
        # reference path ("defaulting off on CPU")
        kernels.configure(None)  # re-resolve the backend default
        assert not kernels.get_config().any_enabled
        assert kernels.active_label() == "reference"
        assert not kernels.enabled("flash")

    def test_use_scope_restores(self):
        before = kernels.get_config()
        with kernels.use(ON):
            assert kernels.enabled("decode")
            assert kernels.active_label() == "pallas"
        assert kernels.get_config() == before

    def test_unknown_op_raises(self):
        with pytest.raises(ValueError):
            kernels.enabled("warp")

    def test_interpret_auto_resolves_off_tpu(self):
        assert kernels.KernelConfig.all_on().resolve_interpret() is True
        assert kernels.KernelConfig.all_on(
            interpret=False).resolve_interpret() is False

    def test_fit_block(self):
        assert fit_block(256, 128) == 128
        assert fit_block(48, 128) == 48
        assert fit_block(48, 16) == 16
        assert fit_block(19, 16) == 1  # prime: one query per tile


# ----------------------------------------------------- flash attention

class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("s,block_q", [(32, 16), (48, 16), (19, 16)])
    def test_forward_matches_reference(self, causal, s, block_q):
        q, k, v = _qkv(s=s, seed=1)
        out = flash_attention(q, k, v, causal=causal, block_q=block_q,
                              interpret=True)
        ref = _ref_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=0)

    def test_segment_mask_matches_reference(self):
        q, k, v = _qkv(s=48, seed=2)
        r = np.random.default_rng(3)
        seg = jnp.asarray(r.integers(0, 3, (2, 48)).astype(np.int32))
        out = flash_attention(q, k, v, seg, causal=True, block_q=16,
                              interpret=True)
        mask = seg[:, None, :, None] == seg[:, None, None, :]
        ref = _ref_attention(q, k, v, causal=True, mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=0)
        assert np.isfinite(np.asarray(out)).all()

    def test_gradient_check_vs_reference(self):
        """The backward kernel vs jax.grad of the einsum reference —
        plain causal and segment-masked."""
        q, k, v = _qkv(s=32, seed=4)
        r = np.random.default_rng(5)
        seg = jnp.asarray(r.integers(1, 3, (2, 32)).astype(np.int32))
        mask = seg[:, None, :, None] == seg[:, None, None, :]

        for kern_loss, ref_loss in [
            (lambda q_, k_, v_: (flash_attention(
                q_, k_, v_, causal=True, block_q=16,
                interpret=True) ** 2).sum(),
             lambda q_, k_, v_: (_ref_attention(
                 q_, k_, v_, causal=True) ** 2).sum()),
            (lambda q_, k_, v_: (flash_attention(
                q_, k_, v_, seg, causal=True, block_q=16,
                interpret=True) ** 2).sum(),
             lambda q_, k_, v_: (_ref_attention(
                 q_, k_, v_, causal=True, mask=mask) ** 2).sum()),
        ]:
            gk = jax.grad(kern_loss, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
            for a, b in zip(gk, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=2e-4, rtol=1e-4)

    def test_grad_under_jit(self):
        """The custom-VJP kernel must survive the train-step shape:
        jit(grad(...)) — the compile path every real step takes."""
        q, k, v = _qkv(s=32, seed=6)

        @jax.jit
        def g(q_, k_, v_):
            return jax.grad(lambda t: (flash_attention(
                t, k_, v_, causal=True, block_q=16,
                interpret=True) ** 2).sum())(q_)

        ref = jax.grad(lambda t: (_ref_attention(
            t, k, v, causal=True) ** 2).sum())(q)
        np.testing.assert_allclose(np.asarray(g(q, k, v)),
                                   np.asarray(ref), atol=2e-4,
                                   rtol=1e-4)

    def test_packed_slab_bit_exact_vs_unpacked(self):
        """THE packed-slab contract with the kernel enabled: every
        document's logits in a packed slab are BIT-IDENTICAL to
        running that document alone through the same kernel — the
        datapipe guarantee (test_datapipe) survives the pallas path."""
        import bigdl_tpu.datapipe.packing as dp

        m = _tiny_lm()
        p, st = m.get_parameters(), m.get_state()
        r = np.random.RandomState(1)
        docs = [r.randint(1, 50, r.randint(4, 10)).astype(np.int32)
                for _ in range(7)]
        toks, segs, pos, _ = dp.pack_documents(docs, 16)
        with kernels.use(ON):
            packed = np.asarray(m.apply(p, st, [toks, segs, pos],
                                        training=False)[0])
            checked = 0
            for row in range(len(toks)):
                for sid in range(1, int(segs[row].max()) + 1):
                    at = np.flatnonzero(segs[row] == sid)
                    alone = np.asarray(m.apply(
                        p, st, toks[row, at][None].astype(np.int32),
                        training=False)[0])
                    assert np.array_equal(packed[row, at], alone[0]), \
                        f"row {row} seg {sid} leaked across documents"
                    checked += 1
        assert checked >= 7

    def test_packed_slab_content_independence_bitwise(self):
        """The leak-proof property at kernel level, robust to any
        block geometry: a document's output is bitwise UNCHANGED when
        every other segment's content is scrambled — masked lanes
        contribute exact zeros, so foreign content cannot perturb even
        the last ulp."""
        r = np.random.default_rng(7)
        h, d, s = 2, 8, 64
        l1, l2 = 25, 30  # doc boundaries straddle the 16-wide tiles
        seg = np.zeros((1, s), np.int32)
        seg[0, :l1], seg[0, l1:l1 + l2] = 1, 2
        q, k, v = _qkv(b=1, h=h, s=s, d=d, seed=8)
        out = np.asarray(flash_attention(q, k, v, jnp.asarray(seg),
                                         causal=True, block_q=16,
                                         interpret=True))
        scr = jnp.asarray(r.standard_normal((1, h, s, d))
                          .astype(np.float32))
        doc2 = (jnp.arange(s) >= l1) & (jnp.arange(s) < l1 + l2)
        sel = doc2[None, None, :, None]
        q2 = jnp.where(sel, q, scr)
        k2 = jnp.where(sel, k, scr)
        v2 = jnp.where(sel, v, scr)
        out2 = np.asarray(flash_attention(q2, k2, v2, jnp.asarray(seg),
                                          causal=True, block_q=16,
                                          interpret=True))
        assert np.array_equal(out[:, :, l1:l1 + l2, :],
                              out2[:, :, l1:l1 + l2, :])

    def test_dispatch_declines_off_and_ineligible(self):
        q, k, v = _qkv()
        with kernels.use(OFF):
            assert kernels.attention(q, k, v, causal=True) is None
        with kernels.use(ON):
            # rank-3 input is the einsum path's, not the kernel's
            assert kernels.attention(q[:, 0], k[:, 0], v[:, 0]) is None
            assert kernels.attention(q, k, v, causal=True) is not None

    def test_over_vmem_budget_routes_blockwise_or_declines(self):
        """Past the VMEM budget the dispatch routes to the BLOCKWISE
        long-context kernel (S=32K runs fused, no einsum fallback);
        with long_context switched off the historical decline→einsum
        escape hatch survives — Mosaic never sees an OOM shape."""
        from bigdl_tpu.kernels import dispatch, flash_attention as fa
        big = jax.ShapeDtypeStruct((1, 1, 32768, 128), jnp.bfloat16)
        cfg = kernels.KernelConfig.all_on(interpret=False)
        assert dispatch._flash_vmem_bytes(big, cfg.block_q) \
            > cfg.resolve_vmem_budget()
        with kernels.use(kernels.KernelConfig.all_on(
                interpret=False, long_context=False)):
            assert kernels.attention(big, big, big,
                                     causal=True) is None
        small = _qkv(s=512, d=64, seed=13)
        with kernels.use(ON):
            assert kernels.attention(*small, causal=True) is not None
        # a tiny budget steers a small shape down the blockwise path
        # (the same routing an over-budget shape takes on TPU) — and
        # the result stays tolerance-equal to the einsum reference
        routed = []
        real = fa.blockwise_flash_attention

        def spy(*a, **kw):
            routed.append(True)
            return real(*a, **kw)

        fa.blockwise_flash_attention = spy
        try:
            with kernels.use(kernels.KernelConfig.all_on(
                    interpret=True, vmem_budget_mb=1, block_q=64,
                    block_k=64)):
                q, k, v = _qkv(b=1, h=1, s=1024, d=16, seed=13)
                out = kernels.attention(q, k, v, causal=True)
        finally:
            fa.blockwise_flash_attention = real
        assert routed and out is not None
        ref = _ref_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=0)

    def test_vmem_budget_env_and_bounds(self):
        """BIGDL_VMEM_BUDGET_MB overrides the 12 MiB default; an
        explicit vmem_budget_mb wins over the env; nonsense values are
        loud."""
        import os
        cfg = kernels.KernelConfig.all_on()
        assert cfg.resolve_vmem_budget() == 12 * 1024 * 1024
        os.environ["BIGDL_VMEM_BUDGET_MB"] = "3"
        try:
            assert cfg.resolve_vmem_budget() == 3 * 1024 * 1024
            explicit = kernels.KernelConfig.all_on(vmem_budget_mb=5)
            assert explicit.resolve_vmem_budget() == 5 * 1024 * 1024
            os.environ["BIGDL_VMEM_BUDGET_MB"] = "lots"
            with pytest.raises(ValueError):
                cfg.resolve_vmem_budget()
        finally:
            del os.environ["BIGDL_VMEM_BUDGET_MB"]
        with pytest.raises(ValueError):
            kernels.KernelConfig.all_on(
                vmem_budget_mb=0).resolve_vmem_budget()

    def test_mask_and_segments_are_exclusive(self):
        """A free-form mask cannot ride the kernel, so passing both
        mask= and segments= raises instead of silently dropping what
        the mask adds beyond segment equality."""
        from bigdl_tpu.nn.attention import dot_product_attention
        q, k, v = _qkv(s=16, seed=14)
        seg = jnp.ones((2, 16), jnp.int32)
        mask = seg[:, None, :, None] == seg[:, None, None, :]
        with pytest.raises(ValueError, match="not both"):
            dot_product_attention(q, k, v, mask=mask, segments=seg)
        # segments alone derives the same-segment mask for the
        # fallback: kernels-off output == explicit-mask output bitwise
        with kernels.use(OFF):
            a = dot_product_attention(q, k, v, causal=True,
                                      segments=seg, use_flash=False)
            b = dot_product_attention(q, k, v, causal=True, mask=mask,
                                      use_flash=False)
        assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_model_forward_on_vs_off_tolerance(self):
        """The full TransformerLM forward with kernels on agrees with
        the reference forward at float32 reduction tolerance, and
        greedy argmax is unchanged."""
        m = _tiny_lm(seed=9)
        p, st = m.get_parameters(), m.get_state()
        toks = np.random.RandomState(2).randint(
            1, 50, (2, 16)).astype(np.int32)
        ref = np.asarray(m.apply(p, st, toks, training=False)[0])
        with kernels.use(ON):
            out = np.asarray(m.apply(p, st, toks, training=False)[0])
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=0)
        assert np.array_equal(out.argmax(-1), ref.argmax(-1))


# -------------------------------------------- blockwise (long-context)

class TestBlockwiseFlashAttention:
    """The online-softmax long-context path: VMEM working set
    independent of S. Tolerance contract (the rescale rounds per block
    boundary — flash_attention.py's section comment), checked against
    the same einsum reference at several block geometries, including
    boundaries that straddle documents and ragged tiles."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("block_k", [8, 16, 48])
    def test_forward_matches_reference(self, causal, block_k):
        q, k, v = _qkv(s=48, seed=30)
        out = blockwise_flash_attention(q, k, v, causal=causal,
                                        block_q=16, block_k=block_k,
                                        interpret=True)
        ref = _ref_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=0)

    def test_segment_mask_matches_reference(self):
        """Packed segment masks under the blockwise form — including
        key tiles that are FULLY masked for some query row (the
        all-masked-carry NaN hazard the exp guards exist for)."""
        q, k, v = _qkv(s=48, seed=31)
        r = np.random.default_rng(32)
        seg = jnp.asarray(r.integers(0, 3, (2, 48)).astype(np.int32))
        out = blockwise_flash_attention(q, k, v, seg, causal=True,
                                        block_q=16, block_k=16,
                                        interpret=True)
        mask = seg[:, None, :, None] == seg[:, None, None, :]
        ref = _ref_attention(q, k, v, causal=True, mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=0)
        assert np.isfinite(np.asarray(out)).all()

    def test_gradient_check_vs_reference(self):
        """The two-pass tiled backward vs jax.grad of the einsum
        reference — plain causal and segment-masked."""
        q, k, v = _qkv(s=32, seed=33)
        r = np.random.default_rng(34)
        seg = jnp.asarray(r.integers(1, 3, (2, 32)).astype(np.int32))
        mask = seg[:, None, :, None] == seg[:, None, None, :]

        for kern_loss, ref_loss in [
            (lambda q_, k_, v_: (blockwise_flash_attention(
                q_, k_, v_, causal=True, block_q=16, block_k=8,
                interpret=True) ** 2).sum(),
             lambda q_, k_, v_: (_ref_attention(
                 q_, k_, v_, causal=True) ** 2).sum()),
            (lambda q_, k_, v_: (blockwise_flash_attention(
                q_, k_, v_, seg, causal=True, block_q=16, block_k=8,
                interpret=True) ** 2).sum(),
             lambda q_, k_, v_: (_ref_attention(
                 q_, k_, v_, causal=True, mask=mask) ** 2).sum()),
        ]:
            gk = jax.grad(kern_loss, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
            for a, b in zip(gk, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=2e-4, rtol=1e-4)

    def test_grad_under_jit(self):
        """jit(grad(...)) — the train-step compile shape — over the
        blockwise custom VJP."""
        q, k, v = _qkv(s=32, seed=35)

        @jax.jit
        def g(q_, k_, v_):
            return jax.grad(lambda t: (blockwise_flash_attention(
                t, k_, v_, causal=True, block_q=16, block_k=16,
                interpret=True) ** 2).sum())(q_)

        ref = jax.grad(lambda t: (_ref_attention(
            t, k, v, causal=True) ** 2).sum())(q)
        np.testing.assert_allclose(np.asarray(g(q, k, v)),
                                   np.asarray(ref), atol=2e-4,
                                   rtol=1e-4)

    def test_matches_fullrow_kernel_tolerance(self):
        """The two kernel forms agree within float32 reduction
        tolerance — the property that makes the budget-based routing
        switch invisible to callers."""
        q, k, v = _qkv(s=64, seed=36)
        a = blockwise_flash_attention(q, k, v, causal=True, block_q=16,
                                      block_k=16, interpret=True)
        b = flash_attention(q, k, v, causal=True, block_q=16,
                            interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=0)


# ------------------------------------------------------- ragged decode

class TestRaggedDecode:
    def test_every_length_in_bucket(self):
        """The ragged kernel vs the length-masked reference at EVERY
        length of a 16-wide bucket — length 1 and bucket-max
        included."""
        slots, h, t, d = 4, 2, 16, 8
        r = np.random.default_rng(10)
        q = jnp.asarray(r.standard_normal((slots, h, d))
                        .astype(np.float32))
        k = jnp.asarray(r.standard_normal((slots, h, t, d))
                        .astype(np.float32))
        v = jnp.asarray(r.standard_normal((slots, h, t, d))
                        .astype(np.float32))
        for n in range(1, t + 1):
            lengths = jnp.full((slots,), n, jnp.int32)
            out = ragged_decode_attention(q, k, v, lengths, block_k=8,
                                          interpret=True)
            s = jnp.einsum("shd,shtd->sht", q, k,
                           preferred_element_type=jnp.float32) \
                / math.sqrt(d)
            s = jnp.where(jnp.arange(t)[None, None, :] < n, s, -jnp.inf)
            ref = jnp.einsum("sht,shtd->shd",
                             jax.nn.softmax(s, axis=-1), v)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=1e-5, rtol=0,
                                       err_msg=f"length {n}")

    def test_mixed_ragged_lengths(self):
        slots, h, t, d = 4, 2, 32, 8
        r = np.random.default_rng(11)
        q = jnp.asarray(r.standard_normal((slots, h, d))
                        .astype(np.float32))
        k = jnp.asarray(r.standard_normal((slots, h, t, d))
                        .astype(np.float32))
        v = jnp.asarray(r.standard_normal((slots, h, t, d))
                        .astype(np.float32))
        lengths = jnp.asarray(np.array([1, 7, 13, 32], np.int32))
        out = ragged_decode_attention(q, k, v, lengths, block_k=8,
                                      interpret=True)
        s = jnp.einsum("shd,shtd->sht", q, k,
                       preferred_element_type=jnp.float32) / math.sqrt(d)
        mask = jnp.arange(t)[None, None, :] < lengths[:, None, None]
        ref = jnp.einsum("sht,shtd->shd",
                         jax.nn.softmax(jnp.where(mask, s, -jnp.inf),
                                        axis=-1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=0)

    def test_dispatch_shapes_and_toggle(self):
        r = np.random.default_rng(12)
        q = jnp.asarray(r.standard_normal((2, 2, 8)).astype(np.float32))
        kv = jnp.asarray(r.standard_normal((2, 2, 16, 8))
                         .astype(np.float32))
        lengths = jnp.asarray(np.array([3, 9], np.int32))
        with kernels.use(OFF):
            assert kernels.decode_attention(q, kv, kv, lengths) is None
        with kernels.use(ON):
            out = kernels.decode_attention(q, kv, kv, lengths)
            assert out is not None and out.shape == (2, 2, 8)
            # a [B,H,S,D] query is the training shape, not decode's
            assert kernels.decode_attention(kv, kv, kv, lengths) is None


# ----------------------------------------------------------- int8 GEMM

class TestInt8Gemm:
    def _quantized(self, m=8, k=32, n=16, seed=0):
        from bigdl_tpu.ops.quant import quantize_symmetric
        r = np.random.default_rng(seed)
        x = r.standard_normal((m, k)).astype(np.float32)
        w = r.standard_normal((n, k)).astype(np.float32)
        w_q, w_scale = quantize_symmetric(w, axis=0)
        x_q, x_scale = quantize_symmetric(x, axis=0)
        return x, x_q, x_scale, w_q, np.asarray(w_scale).reshape(-1)

    @pytest.mark.parametrize("bk", [8, 16, 32])
    def test_bitwise_vs_dequantize_then_matmul(self, bk):
        """The kernel's split-K int32 accumulation + fused dequant is
        BITWISE equal to the reference dequantize-then-matmul at every
        K split."""
        from bigdl_tpu.ops.quant import quantized_linear
        x, x_q, x_scale, w_q, w_scale = self._quantized()
        out = pallas_quantized_matmul(
            jnp.asarray(x_q), jnp.asarray(w_q), jnp.asarray(x_scale),
            jnp.asarray(w_scale), bm=4, bn=8, bk=bk, interpret=True)
        ref = quantized_linear(x, np.asarray(w_q), w_scale, None)
        assert np.array_equal(np.asarray(out), np.asarray(ref))

    def test_dispatch_bitwise_with_bias(self):
        """Through the dispatch layer (bias added OUTSIDE the kernel —
        int8_gemm.py documents the FMA ulp the fused add would cost),
        the with-bias result is bitwise equal to the reference
        layer math."""
        from bigdl_tpu.ops.quant import quantized_linear
        x, x_q, x_scale, w_q, w_scale = self._quantized(seed=1)
        bias = np.random.default_rng(2).standard_normal(16) \
            .astype(np.float32)
        with kernels.use(ON):
            out = kernels.int8_matmul(
                jnp.asarray(x_q), jnp.asarray(w_q),
                jnp.asarray(x_scale), jnp.asarray(w_scale),
                jnp.asarray(bias))
        ref = quantized_linear(x, np.asarray(w_q), w_scale,
                               bias)
        assert np.array_equal(np.asarray(out), np.asarray(ref))

    def test_dispatch_toggle_and_alignment_gate(self):
        x, x_q, x_scale, w_q, w_scale = self._quantized()
        args = (jnp.asarray(x_q), jnp.asarray(w_q),
                jnp.asarray(x_scale), jnp.asarray(w_scale))
        with kernels.use(OFF):
            assert kernels.int8_matmul(*args) is None
        with kernels.use(kernels.KernelConfig.all_on(interpret=False)):
            # compiled mode demands MXU-aligned tiles; 8x32x16 is not
            assert kernels.int8_matmul(*args) is None
        with kernels.use(ON):
            assert kernels.int8_matmul(*args) is not None

    def test_quantized_linear_layer_bitwise_on_vs_off(self):
        """QuantizedLinear routes through the dispatch layer: kernels
        on (interpret) and off produce bitwise-identical layer
        outputs, dynamic AND calibrated activation scales."""
        from bigdl_tpu.nn.linear import Linear
        from bigdl_tpu.nn.quantized import QuantizedLinear
        RandomGenerator.set_seed(21)
        lin = Linear(12, 6)
        lin.ensure_initialized()
        x = jnp.asarray(np.random.RandomState(3)
                        .randn(5, 12).astype(np.float32))
        for act_scale in (None, 0.25):
            qm = QuantizedLinear.from_float(lin, lin.get_parameters(),
                                            act_scale)
            params = qm.init(None)
            with kernels.use(OFF):
                ref = np.asarray(qm.forward_fn(params, x))
            with kernels.use(ON):
                out = np.asarray(qm.forward_fn(params, x))
            assert np.array_equal(out, ref), f"act_scale={act_scale}"


# ---------------------------------------- generation with kernels on

def _greedy_reference(model, prompt, n, pad_to=16):
    @jax.jit
    def fwd(p, s, t):
        logits, _ = model.apply(p, s, t, training=False)
        return logits

    params, state = model.get_parameters(), model.get_state()
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        padded = np.zeros((1, pad_to), np.int32)
        padded[0, :len(toks)] = toks
        logits = np.asarray(fwd(params, state, padded))
        nxt = int(np.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def _gen_model(seed=42):
    RandomGenerator.set_seed(seed)
    m = TransformerLM(vocab_size=50, hidden_size=32, num_layers=2,
                      num_heads=4, max_len=32).evaluate()
    m.ensure_initialized()
    return m


class TestGenerationWithKernels:
    def test_greedy_decode_bit_identical_with_kernels_on(self):
        """The acceptance invariant with the ragged kernel live:
        greedy decode through the service is token-bit-identical to
        full-sequence re-forward — two prompt shapes."""
        from bigdl_tpu.generation import (GenerationConfig,
                                          GenerationService)
        model = _gen_model()
        with kernels.use(ON):
            svc = GenerationService(config=GenerationConfig(
                slots=4, max_len=16, length_buckets=(16,),
                prefill_rows=2))
            svc.load("lm", model)
            try:
                prompt = np.array([3, 7, 1, 4, 9], np.int32)
                out = svc.generate("lm", prompt,
                                   max_new_tokens=8).result(60)
                assert list(out) == _greedy_reference(model, prompt, 8)
                prompt2 = np.array([11, 2], np.int32)
                out2 = svc.generate("lm", prompt2,
                                    max_new_tokens=5).result(60)
                assert list(out2) == _greedy_reference(model, prompt2, 5)
            finally:
                svc.shutdown()

    def test_program_bound_holds_with_kernels_enabled(self):
        """Kernel variants must not multiply programs: a 2-rung ladder
        warms exactly <= 2 programs per rung with kernels on, and a
        decode burst across every bucket compiles nothing new."""
        from bigdl_tpu.generation.engine import DecodeEngine
        from bigdl_tpu.generation.kv_cache import KVCache
        from bigdl_tpu.serving.compile_cache import (BucketLadder,
                                                     CompileCache)
        from bigdl_tpu.serving.registry import ModelRegistry

        model = _gen_model()
        with kernels.use(ON):
            sv = ModelRegistry().load("m", model)
            ladder = BucketLadder(16, (8, 16))
            eng = DecodeEngine(CompileCache(), ladder, slots=4,
                               prefill_rows=2)
            kv = KVCache.for_model(model, 4, 16)
            compiled = eng.warmup(sv, kv)
            assert compiled <= 2 * len(ladder)
            before = eng.compile_count(sv)
            # a burst touching both rungs: no fresh compiles
            eng.prefill(sv, kv, [np.array([3, 7, 1], np.int32)], [0])
            for _ in range(9):  # crosses the 8 -> 16 rung boundary
                tokens = np.zeros((4,), np.int32)
                positions = kv.lengths.copy()
                active = np.zeros((4,), bool)
                active[0] = True
                eng.decode(sv, kv, tokens, positions, active)
                kv.lengths[0] += 1
            assert eng.compile_count(sv) == before

    def test_ragged_kernel_consumes_host_lengths_vector(self,
                                                        monkeypatch):
        """The decode-path seam: the decode program hands the host
        lengths vector (threaded as `positions`) straight to the
        ragged kernel as its per-slot bound — one [slots] int32
        operand, no re-bucketing inside."""
        from bigdl_tpu.generation.engine import DecodeEngine
        from bigdl_tpu.generation.kv_cache import KVCache
        from bigdl_tpu.serving.compile_cache import (BucketLadder,
                                                     CompileCache)
        from bigdl_tpu.serving.registry import ModelRegistry

        seen = []
        real = kernels.decode_attention

        def spy(q, k, v, lengths, **kw):
            seen.append((tuple(lengths.shape), str(lengths.dtype)))
            return real(q, k, v, lengths, **kw)

        monkeypatch.setattr(kernels, "decode_attention", spy)
        model = _gen_model()
        with kernels.use(ON):
            sv = ModelRegistry().load("m", model)
            eng = DecodeEngine(CompileCache(), BucketLadder(16, (16,)),
                               slots=4, prefill_rows=2)
            kv = KVCache.for_model(model, 4, 16)
            eng.prefill(sv, kv, [np.array([3, 7, 1], np.int32)], [0])
            tokens = np.zeros((4,), np.int32)
            active = np.zeros((4,), bool)
            active[0] = True
            eng.decode(sv, kv, tokens, kv.lengths.copy(), active)
        # one call per layer at trace time, each consuming the [slots]
        # int32 lengths operand
        assert len(seen) == model.num_layers
        assert all(s == ((4,), "int32") for s in seen)


# ------------------------------------------- telemetry kernel labels

class TestKernelProgramLabels:
    def test_explicit_labels_reach_gauges(self):
        import bigdl_tpu.telemetry as telemetry
        from bigdl_tpu.telemetry import programs

        r = telemetry.MetricsRegistry()
        reg = programs.ProgramRegistry(metrics=r)
        analysis = {"flops": 2.0e9, "bytes_accessed": 1e6,
                    "hbm_bytes": 5e6}
        prof = reg.register("kl/model/step", "train",
                            analysis=analysis, compile_s=0.5,
                            kernel="pallas")
        assert prof.kernel == "pallas"
        labels = {"program": "kl/model/step", "kernel": "pallas"}
        assert r.gauge("train/program/flops").value(**labels) == 2.0e9
        reg.record_rate("kl/model/step", 1000.0)
        assert r.gauge("train/program/mfu").value(**labels) > 0
        # explicit reference label: the side-by-side bench form
        prof2 = reg.register("kl/model/step_ref", "train",
                             analysis=analysis, compile_s=0.5,
                             kernel="reference")
        assert prof2.kernel == "reference"
        assert r.gauge("train/program/flops").value(
            program="kl/model/step_ref", kernel="reference") == 2.0e9

    def test_wrapped_site_labels_on_trace_evidence_only(self):
        """maybe_wrap_jitted earns kernel=pallas from the trace
        actually routing through a dispatch — a kernel-free program
        stays unlabeled even under an all-on config (the honest-label
        rule; a config-based guess would tag every TPU program)."""
        import bigdl_tpu.telemetry as telemetry
        from bigdl_tpu.nn.attention import dot_product_attention
        from bigdl_tpu.telemetry import programs

        r = telemetry.MetricsRegistry()
        reg = programs.ProgramRegistry(metrics=r)
        q, k, v = _qkv(s=16, seed=20)
        programs.enable()
        try:
            with kernels.use(ON):
                attn = programs.maybe_wrap_jitted(
                    "kl/evidence/attn", "serving",
                    jax.jit(lambda q_, k_, v_: dot_product_attention(
                        q_, k_, v_, causal=True)), prog_registry=reg)
                attn(q, k, v)
                plain = programs.maybe_wrap_jitted(
                    "kl/evidence/plain", "serving",
                    jax.jit(lambda x: x * 2.0), prog_registry=reg)
                plain(q)
        finally:
            programs.disable()
        assert reg.get("kl/evidence/attn").kernel == "pallas"
        assert reg.get("kl/evidence/plain").kernel is None

    def test_implicit_registration_keeps_unlabeled_series(self):
        """Registrations without explicit labels or trace evidence
        keep the pre-kernel single-label gauge identity — existing
        dashboards/series must not churn, whatever the config."""
        import bigdl_tpu.telemetry as telemetry
        from bigdl_tpu.telemetry import programs

        r = telemetry.MetricsRegistry()
        reg = programs.ProgramRegistry(metrics=r)
        with kernels.use(ON):  # even an all-on config must not leak in
            prof = reg.register("kl/off/step", "train",
                                analysis={"flops": 1.0}, compile_s=0.1)
        assert prof.kernel is None
        assert r.gauge("train/program/flops").value(
            program="kl/off/step") == 1.0

    def test_diagnose_device_rows_show_kernel(self):
        """The golden diagnose shape: device rows carry the kernel
        field and the text line tags it."""
        from bigdl_tpu.tools.diagnose import _device_lines, \
            device_summary

        rows = device_summary([
            {"name": "b/att/pallas", "kind": "serving",
             "kernel": "pallas", "mfu": 0.41, "achieved_tfs": 80.0,
             "flops": 1e12, "hbm_bytes": 2e9, "compile_s": 1.5},
            {"name": "b/att/ref", "kind": "serving",
             "kernel": "reference", "mfu": 0.3,
             "achieved_tfs": 60.0, "flops": 1e12, "hbm_bytes": 2e9,
             "compile_s": 1.0},
        ])
        assert [r["kernel"] for r in rows] == ["pallas", "reference"]
        lines = _device_lines(rows)
        assert "[pallas]" in lines[0] and "[reference]" in lines[1]

    def test_dispatch_counters_count_routing(self):
        import bigdl_tpu.telemetry as telemetry

        c_pallas = telemetry.registry().counter(
            "kernels/dispatch/pallas")
        c_ref = telemetry.registry().counter(
            "kernels/dispatch/reference")
        q, k, v = _qkv()
        before_p = c_pallas.value(op="flash")
        before_c = c_ref.value(op="flash", reason="config")
        before_s = c_ref.value(op="flash", reason="shape")
        before_v = c_ref.value(op="flash", reason="vmem")
        with kernels.use(ON):
            kernels.attention(q, k, v, causal=True)
        with kernels.use(OFF):
            assert kernels.attention(q, k, v, causal=True) is None
        with kernels.use(ON):
            # rank-3 input: declined for shape, attributably
            assert kernels.attention(q[:, 0], k[:, 0], v[:, 0]) is None
        big = jax.ShapeDtypeStruct((1, 1, 32768, 128), jnp.bfloat16)
        with kernels.use(kernels.KernelConfig.all_on(
                interpret=False, long_context=False)):
            assert kernels.attention(big, big, big) is None
        assert c_pallas.value(op="flash") == before_p + 1
        assert c_ref.value(op="flash", reason="config") == before_c + 1
        assert c_ref.value(op="flash", reason="shape") == before_s + 1
        assert c_ref.value(op="flash", reason="vmem") == before_v + 1
