"""The autotuner's contract: typed bounded spaces, ZERO-execution
static pruning (asserted via a backend_compile counter), per-candidate
failure isolation, deterministic byte-identical artifacts, and the
consumers (`perf --config`, the serving facade) actually applying the
winner."""
import json
import os

import pytest

from bigdl_tpu.autotune import (Candidate, Fingerprint,
                                FingerprintMismatchError, ServingSpace,
                                SpaceError, TrainSpace, TunedConfig,
                                TunedConfigError, enumerate_candidates,
                                load_tuned, save_tuned, static_prune)
from bigdl_tpu.autotune.defaults import (DEFAULT_TRAIN_CONFIG,
                                         INFEASIBLE_BATCH,
                                         SMOKE_HBM_BUDGET_BYTES,
                                         smoke_serving_space,
                                         smoke_train_space)
from bigdl_tpu.autotune.measure import measure_candidates
from bigdl_tpu.tools.autotune import run_autotune

# ----------------------------------------------------------- helpers

#: a foreign environment no CI host matches
_FOREIGN_FP = Fingerprint(device_kind="TPU v9", platform="tpu",
                          device_count=8, mesh_shape=(8,),
                          package_version="9.9.9")


def det_runner(cand, seed, iters):
    """Deterministic pseudo-measurement: stable across processes (no
    clocks, no RNG state) but sensitive to candidate, seed and iters."""
    h = sum(ord(c) * (i + 1) for i, c in enumerate(cand.cid))
    return float((h % 1000) + seed * 10 + iters)


def smoke_spaces():
    return {"train": smoke_train_space(),
            "serving": smoke_serving_space()}


# ------------------------------------------------------------- space

def test_space_bounds_raise_typed_errors():
    with pytest.raises(SpaceError):
        TrainSpace(steps_per_sync=(0,))
    with pytest.raises(SpaceError):
        TrainSpace(zero_stage=(4,))
    with pytest.raises(SpaceError):
        TrainSpace(precision=("f64",))
    with pytest.raises(SpaceError):
        TrainSpace(batch_size=(0,))
    with pytest.raises(SpaceError):  # ladder must ascend strictly
        ServingSpace(max_len=64, length_buckets=((64, 32),))
    with pytest.raises(SpaceError):  # top rung must equal max_len
        ServingSpace(max_len=64, length_buckets=((32,),))
    with pytest.raises(SpaceError):
        ServingSpace(speculation_k=(9,))


def test_enumeration_is_deterministic():
    a_valid, a_invalid = enumerate_candidates(smoke_train_space())
    b_valid, b_invalid = enumerate_candidates(smoke_train_space())
    assert [c.cid for c in a_valid] == [c.cid for c in b_valid]
    assert [(c.cid, r) for c, r in a_invalid] == \
        [(c.cid, r) for c, r in b_invalid]
    assert len(a_valid) + len(a_invalid) == 8  # the bounded smoke space
    # the hand-picked default point is IN the space, so the winner can
    # never lose to it on the same seeded windows
    assert any(all(c.config.get(k) == v
                   for k, v in DEFAULT_TRAIN_CONFIG.items())
               for c in a_valid)
    # every train candidate carries its model twin
    assert all(c.config["model"] == "mlp" for c in a_valid)


def test_constraints_reject_with_reasons():
    # flash on an attention-free model has nothing to dispatch
    valid, invalid = enumerate_candidates(
        TrainSpace(steps_per_sync=(1,), flash=(True,), model="mlp"))
    assert not valid and len(invalid) == 1
    assert "flash" in invalid[0][1]
    # ZeRO needs the batch to split across the data mesh
    valid, invalid = enumerate_candidates(
        TrainSpace(zero_stage=(2,), batch_size=(3,)), ndev=2)
    assert not valid and "divisible" in invalid[0][1]
    # speculation manages its own cache seeding
    valid, invalid = enumerate_candidates(ServingSpace(
        max_len=64, length_buckets=((64,),), speculation_k=(2,),
        prefix_cache_bytes=(1 << 20,)))
    assert not valid and "prefix_cache" in invalid[0][1]


# ------------------------------------------------------------- prune

def test_static_prune_rejects_infeasible_with_zero_compiles():
    """The footprint gate is eval_shape-only: the deliberately
    oversized smoke batch is rejected before ANY XLA compilation."""
    from jax._src import compiler
    valid, _ = enumerate_candidates(smoke_train_space())
    calls = []
    orig = compiler.backend_compile

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    compiler.backend_compile = counting
    try:
        report = static_prune(valid,
                              hbm_budget=SMOKE_HBM_BUDGET_BYTES,
                              contract_checks=False)
    finally:
        compiler.backend_compile = orig
    assert calls == [], f"static prune compiled {len(calls)} programs"
    assert {p.candidate.config["batch_size"] for p in report.pruned} \
        == {INFEASIBLE_BATCH}
    assert {c.config["batch_size"] for c in report.kept} == {16}
    # every drop is auditable: stage + a budget-bearing reason
    for p in report.pruned:
        assert p.stage == "hbm"
        assert str(SMOKE_HBM_BUDGET_BYTES) in p.reason


def test_contract_gate_passes_feasible_candidates():
    """Survivors are lowered and checked against the compiled-program
    contract (compiles happen; executions don't)."""
    valid, _ = enumerate_candidates(smoke_train_space())
    feasible = [c for c in valid
                if c.config["batch_size"] != INFEASIBLE_BATCH][:2]
    report = static_prune(feasible,
                          hbm_budget=SMOKE_HBM_BUDGET_BYTES)
    assert [c.cid for c in report.kept] == [c.cid for c in feasible]


# ----------------------------------------------------------- measure

def test_crashing_candidate_is_isolated():
    """One exploding window never takes down the sweep: the failure is
    classified (fatal fails fast, transient gets one retry) and every
    other candidate still gets measured."""
    valid, _ = enumerate_candidates(smoke_train_space())
    feasible = [c for c in valid
                if c.config["batch_size"] != INFEASIBLE_BATCH]
    bad_cid = feasible[0].cid
    attempts = {}

    def runner(cand, seed, iters):
        attempts[cand.cid] = attempts.get(cand.cid, 0) + 1
        if cand.cid == bad_cid:
            raise RuntimeError("window exploded")
        return det_runner(cand, seed, iters)

    results = measure_candidates(feasible, seed=0, iters=1,
                                 runner=runner)
    assert len(results) == len(feasible)
    by_cid = {r.candidate.cid: r for r in results}
    bad = by_cid[bad_cid]
    assert not bad.ok and bad.error_kind == "transient"
    assert "window exploded" in bad.error
    assert attempts[bad_cid] == 2  # transient => one retry
    assert all(r.ok for cid, r in by_cid.items() if cid != bad_cid)


def test_fatal_failure_is_not_retried():
    valid, _ = enumerate_candidates(smoke_train_space())
    cand = [c for c in valid if c.config["batch_size"] == 16][0]
    attempts = []

    def runner(c, seed, iters):
        attempts.append(1)
        raise ValueError("mis-wired candidate")  # FATAL_TYPES

    (res,) = measure_candidates([cand], runner=runner)
    assert not res.ok and res.error_kind == "fatal"
    assert len(attempts) == 1


# ---------------------------------------------- determinism + artifact

def test_same_seed_identical_leaderboard_and_bytes(tmp_path):
    """The acceptance bound: same seed + same (injected) runner =>
    identical leaderboard and byte-identical tuned.json."""
    logs = []
    kw = dict(seed=7, iters=2, spaces=smoke_spaces(),
              hbm_budget=SMOKE_HBM_BUDGET_BYTES, runner=det_runner,
              log=logs.append)
    a = run_autotune(("train", "serving"), **kw)
    b = run_autotune(("train", "serving"), **kw)
    assert a.leaderboard == b.leaderboard
    assert a.to_json() == b.to_json()
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    save_tuned(a, str(pa))
    save_tuned(b, str(pb))
    assert pa.read_bytes() == pb.read_bytes()
    # every dropped candidate was logged with its stage + reason
    pruned_lines = [l for l in logs if l.startswith("# pruned ")]
    assert len(pruned_lines) == len(a.pruned) + len(b.pruned)
    for line in pruned_lines:
        entry = json.loads(line[len("# pruned "):])
        assert entry["stage"] and entry["reason"]
    # round-trip: the loaded artifact reproduces the winners
    loaded = load_tuned(str(pa), fingerprint=a.fingerprint)
    assert set(loaded.winners) == {"train", "serving"}
    assert loaded.seed == 7


def test_winner_beats_default_on_same_seed():
    """The default config is a point in the smoke space, so the sweep's
    winner is >= it by construction on the same seeded windows."""
    cfg = run_autotune(("train",), seed=3, iters=1,
                       spaces=smoke_spaces(),
                       hbm_budget=SMOKE_HBM_BUDGET_BYTES,
                       runner=det_runner, log=lambda *_: None)
    ok = [e for e in cfg.leaderboard if e["ok"]]
    best = max(e["objective"] for e in ok)
    default = [e for e in ok
               if all(e["config"].get(k) == v
                      for k, v in DEFAULT_TRAIN_CONFIG.items())]
    assert default and best >= default[0]["objective"]
    assert cfg.winner("train")  # present and typed


def test_fingerprint_mismatch_is_typed(tmp_path):
    cfg = TunedConfig(fingerprint=_FOREIGN_FP, seed=0,
                      winners={"train": dict(DEFAULT_TRAIN_CONFIG)})
    path = str(tmp_path / "tuned.json")
    save_tuned(cfg, path)
    with pytest.raises(FingerprintMismatchError) as ei:
        load_tuned(path)
    # the typed error carries the per-field diff for the message
    assert "device_kind" in ei.value.mismatches
    # explicit escape hatches: inspect anyway, or pin the fingerprint
    assert load_tuned(path, allow_mismatch=True).winners["train"]
    assert load_tuned(path, fingerprint=_FOREIGN_FP).seed == 0


def test_unknown_schema_version_is_refused(tmp_path):
    cfg = TunedConfig(fingerprint=_FOREIGN_FP, seed=0,
                      winners={"train": {}})
    raw = json.loads(cfg.to_json())
    raw["schema_version"] = 99
    path = tmp_path / "tuned.json"
    path.write_text(json.dumps(raw))
    with pytest.raises(TunedConfigError, match="schema_version"):
        load_tuned(str(path), allow_mismatch=True)


def test_missing_regime_winner_is_typed():
    cfg = TunedConfig(fingerprint=_FOREIGN_FP, seed=0,
                      winners={"train": {}})
    with pytest.raises(TunedConfigError, match="serving"):
        cfg.winner("serving")


def test_save_is_atomic_no_tmp_left_behind(tmp_path):
    cfg = TunedConfig(fingerprint=_FOREIGN_FP, seed=0)
    path = str(tmp_path / "tuned.json")
    save_tuned(cfg, path)
    assert os.listdir(tmp_path) == ["tuned.json"]


# --------------------------------------------------------- consumers

def _tuned_artifact(tmp_path, train_winner=None, serving_winner=None):
    winners = {}
    if train_winner is not None:
        winners["train"] = train_winner
    if serving_winner is not None:
        winners["serving"] = serving_winner
    cfg = TunedConfig(fingerprint=Fingerprint.current(), seed=0,
                      winners=winners)
    path = str(tmp_path / "tuned.json")
    save_tuned(cfg, path)
    return path


def test_perf_config_applies_the_winner(tmp_path, capsys):
    """`perf --config tuned.json` applies K / precision / batch /
    kernels onto the run — spied through build_train_step and the JSON
    tail (the CLI flags all say otherwise)."""
    path = _tuned_artifact(tmp_path, train_winner={
        "steps_per_sync": 2, "zero_stage": 0,
        "precision": "bf16_mixed", "flash": False, "batch_size": 4,
        "model": "mlp"})
    from bigdl_tpu.optim import optimizer as opt_mod
    from bigdl_tpu.tools import perf
    from bigdl_tpu import kernels
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator
    seen = {}
    orig = opt_mod.build_train_step

    def spying(model, criterion, optim, **kw):
        seen.update(kw)
        return orig(model, criterion, optim, **kw)

    # perf.main mutates process globals by design (compute dtype, kernel
    # config, seed) — snapshot them so later tests see the defaults.
    saved_dtype = Engine.compute_dtype()
    saved_kernels = kernels.get_config()
    saved_seed = RandomGenerator.get_seed()
    opt_mod.build_train_step = spying
    try:
        perf.main(["--model", "lenet", "--batch-size", "32",
                   "--iterations", "1", "--warmup", "0",
                   "--config", path])
    finally:
        opt_mod.build_train_step = orig
        Engine.set_compute_dtype(saved_dtype)
        kernels.configure(saved_kernels)
        RandomGenerator.set_seed(saved_seed)
    assert seen["precision"] is not None  # bf16_mixed policy applied
    assert seen["zero"] is None
    tail = json.loads([l for l in capsys.readouterr().out.splitlines()
                       if l.startswith("{")][-1])
    assert tail["steps_per_sync"] == 2     # not the CLI default 1
    assert tail["batch_size"] == 4         # not the CLI's 32
    assert tail["dtype"] == "bf16_mixed"
    assert tail["kernels"] == "off"
    assert set(tail["tuned_applied"]) == {
        "steps_per_sync", "zero", "precision", "batch_size", "kernels"}


def test_serving_facade_applies_the_winner(tmp_path):
    from bigdl_tpu.generation import GenerationConfig, apply_tuned_config
    path = _tuned_artifact(tmp_path, serving_winner={
        "length_buckets": [32, 64], "slots": 2, "speculation_k": 0,
        "prefix_cache_bytes": 1 << 20})
    cfg = apply_tuned_config(path, base=GenerationConfig(max_queue=7))
    assert cfg.length_buckets == (32, 64)
    assert cfg.max_len == 64        # follows the ladder's top rung
    assert cfg.slots == 2
    assert cfg.prefix_cache_bytes == 1 << 20
    assert cfg.max_queue == 7       # untouched base fields survive


def test_serving_facade_refuses_speculative_winner(tmp_path):
    from bigdl_tpu.generation import apply_tuned_config
    path = _tuned_artifact(tmp_path, serving_winner={
        "length_buckets": [64], "slots": 4, "speculation_k": 2,
        "prefix_cache_bytes": 0})
    with pytest.raises(TunedConfigError, match="[Ss]pecul"):
        apply_tuned_config(path)


def test_apply_tuned_optimizer_goes_through_setters():
    from bigdl_tpu.autotune import apply_tuned_optimizer
    from bigdl_tpu.parallel import ZeroConfig

    calls = {}

    class FakeOpt:
        def set_steps_per_sync(self, k):
            calls["k"] = k

        def set_zero(self, z):
            calls["zero"] = z

        def set_precision(self, p):
            calls["precision"] = p

    cfg = TunedConfig(fingerprint=_FOREIGN_FP, seed=0, winners={
        "train": {"steps_per_sync": 8, "zero_stage": 2,
                  "precision": "f32"}})
    apply_tuned_optimizer(cfg, FakeOpt())
    assert calls["k"] == 8
    assert isinstance(calls["zero"], ZeroConfig) \
        and calls["zero"].stage == 2
    assert calls["precision"] is None  # f32 == no mixed policy


# ----------------------------------------------------------- wiring

def test_autotune_instruments_are_audited():
    """check --telemetry-audit sees the sweep's instruments via the
    same collector it audits everything else with."""
    from bigdl_tpu.tools.check import collect_instrument_names
    names = set(collect_instrument_names())
    assert {"autotune/sweep/candidates_total",
            "autotune/sweep/pruned_static",
            "autotune/sweep/measured",
            "autotune/sweep/best_objective"} <= names


def test_flash_decision_pairs_equal_configs():
    from bigdl_tpu.autotune.measure import MeasureResult
    from bigdl_tpu.tools.autotune import flash_decision

    def result(flash, obj):
        items = dict(DEFAULT_TRAIN_CONFIG, flash=flash,
                     model="transformer_lm")
        cand = Candidate("train", tuple(sorted(items.items())))
        return MeasureResult(cand, ok=True, objective=obj,
                             objective_name="train_steps_per_sec")

    d = flash_decision([result(True, 200.0), result(False, 100.0)])
    assert d["decision"] == "on"
    assert d["pairs"][0]["speedup"] == 2.0
    d = flash_decision([result(True, 50.0), result(False, 100.0)])
    assert d["decision"] == "off"
    assert flash_decision([])["decision"] == "no-evidence"
