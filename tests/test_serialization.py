"""Module topology + weight serialization round-trips (reference test model:
utils/serializer specs — save, load into a FRESH process-independent tree,
compare forward outputs)."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.module_serializer import (from_spec,
                                               register_module_class, to_spec)
from bigdl_tpu.utils.serialization import load_module, save_module


def _roundtrip_forward(model, x, tmp_path, atol=1e-6):
    model.evaluate()
    y0 = np.asarray(model.forward(x))
    save_module(str(tmp_path / "m"), model)
    loaded = load_module(str(tmp_path / "m")).evaluate()
    y1 = np.asarray(loaded.forward(x))
    np.testing.assert_allclose(y0, y1, atol=atol)
    return loaded


def test_sequential_lenet_roundtrip(tmp_path):
    from bigdl_tpu.models import LeNet5
    x = np.random.randn(2, 1, 28, 28).astype(np.float32)
    loaded = _roundtrip_forward(LeNet5(10), x, tmp_path)
    assert isinstance(loaded, nn.Sequential)


def test_graph_lenet_roundtrip(tmp_path):
    from bigdl_tpu.models.lenet import LeNet5_graph
    x = np.random.randn(2, 1, 28, 28).astype(np.float32)
    loaded = _roundtrip_forward(LeNet5_graph(10), x, tmp_path)
    assert isinstance(loaded, nn.Graph)


def test_resnet20_roundtrip(tmp_path):
    from bigdl_tpu.models import ResNet
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    _roundtrip_forward(ResNet(10, depth=20, dataset="CIFAR10"), x, tmp_path,
                       atol=1e-4)


def test_container_with_ctor_and_added_children(tmp_path):
    m = nn.Concat(2, nn.Linear(4, 3), nn.Linear(4, 5))
    m.add(nn.Linear(4, 2))
    x = np.random.randn(3, 4).astype(np.float32)
    y0 = np.asarray(m.forward(x))
    assert y0.shape == (3, 10)
    spec = to_spec(m)
    rebuilt = from_spec(spec)
    assert len(rebuilt.modules) == 3
    save_module(str(tmp_path / "c"), m)
    loaded = load_module(str(tmp_path / "c"))
    np.testing.assert_allclose(y0, np.asarray(loaded.forward(x)), atol=1e-6)


def test_metadata_preserved(tmp_path):
    m = nn.Sequential().add(
        nn.Linear(4, 4).set_name("proj").set_scale_w(0.5))
    m.forward(np.zeros((1, 4), np.float32))
    save_module(str(tmp_path / "meta"), m)
    loaded = load_module(str(tmp_path / "meta"))
    assert loaded[0].get_name() == "proj"
    assert loaded[0].scale_w == 0.5


def test_regularizer_arg_roundtrip(tmp_path):
    from bigdl_tpu.optim.regularizer import L2Regularizer
    m = nn.Linear(4, 4, w_regularizer=L2Regularizer(1e-4))
    x = np.random.randn(2, 4).astype(np.float32)
    _roundtrip_forward(m, x, tmp_path)
    loaded = load_module(str(tmp_path / "m"))
    p = loaded.get_parameters()
    assert float(loaded.regularization_loss(p)) > 0.0


def test_unknown_class_raises(tmp_path):
    with pytest.raises(KeyError):
        from_spec({"class": "DoesNotExist", "args": [], "kwargs": {}})


def test_custom_class_registration(tmp_path):
    class MyScale(nn.Module):
        def __init__(self, factor):
            super().__init__()
            self.factor = factor

        def forward_fn(self, params, input, *, training=False, rng=None):
            return input * self.factor

    register_module_class(MyScale)
    m = nn.Sequential().add(MyScale(3.0))
    x = np.ones((2, 2), np.float32)
    _roundtrip_forward(m, x, tmp_path)


def test_quantized_ctor_children_roundtrip(tmp_path):
    """Review regression: quantize() must repair captured ctor args so the
    quantized topology (not the stale float one) serializes."""
    from bigdl_tpu.utils.serialization import load_module, save_module
    m = nn.Concat(2, nn.Linear(4, 3), nn.Linear(4, 5)).evaluate()
    x = np.random.randn(3, 4).astype(np.float32)
    m.forward(x)
    q = m.quantize()
    ref = np.asarray(q.forward(x))
    save_module(str(tmp_path / "qc"), q)
    loaded = load_module(str(tmp_path / "qc"))
    assert isinstance(loaded[0], nn.QuantizedLinear)
    np.testing.assert_allclose(ref, np.asarray(loaded.forward(x)), atol=1e-5)


def test_self_building_subclass_no_double_children(tmp_path):
    from bigdl_tpu.utils.module_serializer import register_module_class
    from bigdl_tpu.utils.serialization import load_module, save_module

    class TinyNet(nn.Sequential):
        def __init__(self, n):
            super().__init__()
            self.add(nn.Linear(4, n)).add(nn.ReLU())

    register_module_class(TinyNet)
    m = TinyNet(3)
    x = np.random.randn(2, 4).astype(np.float32)
    y0 = np.asarray(m.forward(x))
    save_module(str(tmp_path / "t"), m)
    loaded = load_module(str(tmp_path / "t"))
    assert len(loaded.modules) == 2
    np.testing.assert_allclose(y0, np.asarray(loaded.forward(x)), atol=1e-6)


def test_graph_metadata_and_eval_mode(tmp_path):
    from bigdl_tpu.models.lenet import LeNet5_graph
    from bigdl_tpu.utils.serialization import load_module, save_module
    g = LeNet5_graph(10).set_name("lenet").evaluate()
    g.forward(np.random.randn(1, 1, 28, 28).astype(np.float32))
    save_module(str(tmp_path / "g"), g)
    loaded = load_module(str(tmp_path / "g"))
    assert loaded.get_name() == "lenet"
    assert loaded.is_training() is False


def test_quantized_conv_keeps_name():
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 4, 3, 3).set_name("conv1")))
    m.forward(np.random.randn(1, 3, 8, 8).astype(np.float32))
    q = m.quantize()
    assert q.find("conv1") is not None


def _save_ck(path, neval, val=1.0):
    import numpy as np

    from bigdl_tpu.utils.serialization import save_checkpoint
    save_checkpoint(str(path), params={"w": np.full(3, val, np.float32)},
                    opt_state={}, model_state={},
                    optim_host_state={}, driver_state={"neval": neval})


def test_checkpoint_atomic_write_and_manifest(tmp_path):
    """save_checkpoint commits via tmp-dir + MANIFEST-last + rename: the
    final dir always carries a MANIFEST and no staging debris remains."""
    import os

    from bigdl_tpu.utils.serialization import (MANIFEST,
                                               find_latest_checkpoint,
                                               load_checkpoint)

    _save_ck(tmp_path / "checkpoint.2", 2, 1.0)
    _save_ck(tmp_path / "checkpoint.4", 4, 2.0)
    assert (tmp_path / "checkpoint.4" / MANIFEST).exists()
    assert [n for n in os.listdir(tmp_path)
            if ".tmp-" in n or ".old-" in n] == []
    latest = find_latest_checkpoint(str(tmp_path))
    assert latest.endswith("checkpoint.4")
    assert load_checkpoint(latest)["params"]["w"][0] == 2.0


def test_find_latest_skips_torn_checkpoint(tmp_path):
    """A STAGING dir with tree files but NO MANIFEST (the real mid-write
    crash artifact: writes happen in .tmp-*, never at the final name) is
    never selected — resume lands on the previous intact checkpoint."""
    from bigdl_tpu.utils.serialization import (MANIFEST,
                                               find_latest_checkpoint)

    _save_ck(tmp_path / "checkpoint.2", 2)
    _save_ck(tmp_path / "checkpoint.6", 6)
    # simulate the torn write: a .tmp- staging dir whose MANIFEST was
    # never reached
    (tmp_path / "checkpoint.6" / MANIFEST).unlink()
    (tmp_path / "checkpoint.6").rename(tmp_path / "checkpoint.6.tmp-42")
    latest = find_latest_checkpoint(str(tmp_path))
    assert latest.endswith("checkpoint.2")


def test_find_latest_accepts_legacy_format0_checkpoint(tmp_path):
    """Back-compat: a properly-named pre-MANIFEST checkpoint (format 0
    — host_state.json was its completeness marker) still resumes."""
    from bigdl_tpu.utils.serialization import (MANIFEST,
                                               find_latest_checkpoint,
                                               load_checkpoint)

    _save_ck(tmp_path / "checkpoint.4", 4, 2.0)
    (tmp_path / "checkpoint.4" / MANIFEST).unlink()  # as written by r4
    latest = find_latest_checkpoint(str(tmp_path))
    assert latest is not None and latest.endswith("checkpoint.4")
    assert load_checkpoint(latest)["params"]["w"][0] == 2.0


def test_find_latest_recovers_stray_complete_tmp(tmp_path):
    """A COMPLETE staging dir (MANIFEST written, crash before the final
    rename) is still found: no crash point loses the newest state."""
    from bigdl_tpu.utils.serialization import (find_latest_checkpoint,
                                               load_checkpoint)

    _save_ck(tmp_path / "checkpoint.2", 2, 1.0)
    _save_ck(tmp_path / "checkpoint.6", 6, 3.0)
    (tmp_path / "checkpoint.6").rename(tmp_path / "checkpoint.6.tmp-999")
    latest = find_latest_checkpoint(str(tmp_path))
    assert ".tmp-999" in latest
    ck = load_checkpoint(latest)
    assert ck["driver_state"]["neval"] == 6
    assert ck["params"]["w"][0] == 3.0


def test_overwrite_checkpoint_transitions_complete_to_complete(tmp_path):
    """Re-saving the same fixed name (overwrite_checkpoint mode) swaps
    atomically: the dir is replaced, never torn, debris cleaned."""
    import os

    from bigdl_tpu.utils.serialization import (MANIFEST,
                                               find_latest_checkpoint,
                                               load_checkpoint)

    _save_ck(tmp_path / "checkpoint", 2, 1.0)
    _save_ck(tmp_path / "checkpoint", 9, 5.0)
    assert sorted(os.listdir(tmp_path)) == ["checkpoint"]
    assert (tmp_path / "checkpoint" / MANIFEST).exists()
    latest = find_latest_checkpoint(str(tmp_path))
    ck = load_checkpoint(latest)
    assert ck["driver_state"]["neval"] == 9
    assert ck["params"]["w"][0] == 5.0


def test_scripted_crash_in_checkpoint_leaves_previous_intact(tmp_path):
    """End-to-end torn-write: a subprocess SIGKILLs ITSELF mid-
    checkpoint-write (BIGDL_TEST_CRASH_IN_CHECKPOINT); the directory
    must still resolve to the previous intact checkpoint."""
    import os
    import subprocess
    import sys

    from bigdl_tpu.utils.serialization import find_latest_checkpoint

    code = (
        "import numpy as np\n"
        "from bigdl_tpu.utils import serialization\n"
        "from bigdl_tpu.utils.serialization import save_checkpoint\n"
        "import sys\n"
        "root = sys.argv[1]\n"
        "if sys.argv[2] == 'armed':\n"
        "    serialization.arm_scripted_crash()\n"
        "def sv(neval):\n"
        "    save_checkpoint(root + f'/checkpoint.{neval}',\n"
        "        params={'w': np.full(3, float(neval), np.float32)},\n"
        "        opt_state={}, model_state={}, optim_host_state={},\n"
        "        driver_state={'neval': neval})\n"
        "sv(2)\n"
        "sv(4)\n"  # BIGDL_TEST_CRASH_IN_CHECKPOINT=4 kills here
        "sv(6)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    env["BIGDL_TEST_CRASH_IN_CHECKPOINT"] = "4"
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([sys.executable, "-c", code, str(tmp_path), "armed"],
                       capture_output=True, text=True, timeout=120,
                       env=env)
    assert r.returncode == -9, (r.returncode, r.stderr[-500:])
    latest = find_latest_checkpoint(str(tmp_path))
    assert latest is not None and latest.endswith("checkpoint.2"), latest

    # ADVICE r5: the env var ALONE is inert — a stray
    # BIGDL_TEST_CRASH_IN_CHECKPOINT inherited by a real run must not
    # SIGKILL it; only a process that explicitly armed the hook dies
    unarmed = tmp_path / "unarmed"
    unarmed.mkdir()
    r = subprocess.run([sys.executable, "-c", code, str(unarmed),
                        "unarmed"],
                       capture_output=True, text=True, timeout=120,
                       env=env)
    assert r.returncode == 0, (r.returncode, r.stderr[-500:])
    latest = find_latest_checkpoint(str(unarmed))
    assert latest is not None and latest.endswith("checkpoint.6"), latest


def test_checkpoint_roundtrip_via_memory_filesystem():
    """Remote checkpoint IO (utils/File.scala HDFS/S3 role): fsspec's
    memory:// filesystem is the transport oracle."""
    pytest.importorskip("fsspec")
    import numpy as np

    from bigdl_tpu.utils.serialization import (find_latest_checkpoint,
                                               load_checkpoint,
                                               save_checkpoint)

    path = "memory://ckpts/checkpoint.3"
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    save_checkpoint(path, params=params, opt_state={},
                    model_state={"m": np.ones(2, np.float32)},
                    optim_host_state={"neval": 7},
                    driver_state={"epoch": 2, "neval": 7})
    latest = find_latest_checkpoint("memory://ckpts")
    assert latest is not None and latest.endswith("checkpoint.3")
    ck = load_checkpoint(latest)
    np.testing.assert_array_equal(ck["params"]["w"], params["w"])
    assert ck["driver_state"]["epoch"] == 2


# ---------------------------------------------- integrity (PR5 faults)

def test_manifest_records_sha256_and_verify_passes(tmp_path):
    """Format-2 checkpoints carry per-file digests; a clean dir
    verifies and loads."""
    import json

    from bigdl_tpu.utils.serialization import (MANIFEST, load_checkpoint,
                                               verify_checkpoint)
    _save_ck(tmp_path / "checkpoint.2", 2, 1.0)
    with open(tmp_path / "checkpoint.2" / MANIFEST) as f:
        manifest = json.load(f)
    assert manifest["format"] == 2
    assert sorted(manifest["sha256"]) == sorted(manifest["files"])
    verify_checkpoint(str(tmp_path / "checkpoint.2"))
    assert load_checkpoint(
        str(tmp_path / "checkpoint.2"))["params"]["w"][0] == 1.0


def test_corrupt_npz_behind_manifest_raises_and_skips_verify_off(tmp_path):
    """Bit rot AFTER the MANIFEST landed: completeness says done, the
    bytes say otherwise — only the digest check can catch it."""
    import os

    from bigdl_tpu.utils.serialization import (CheckpointCorrupt,
                                               load_checkpoint)
    _save_ck(tmp_path / "checkpoint.2", 2, 1.0)
    npz = tmp_path / "checkpoint.2" / "params.npz"
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)
    with pytest.raises(CheckpointCorrupt, match="params.npz"):
        load_checkpoint(str(tmp_path / "checkpoint.2"))


def test_missing_manifest_file_raises_corrupt(tmp_path):
    from bigdl_tpu.utils.serialization import (CheckpointCorrupt,
                                               verify_checkpoint)
    _save_ck(tmp_path / "checkpoint.2", 2)
    (tmp_path / "checkpoint.2" / "opt_state.npz").unlink()
    with pytest.raises(CheckpointCorrupt, match="opt_state.npz"):
        verify_checkpoint(str(tmp_path / "checkpoint.2"))


def test_format1_manifest_without_digests_still_loads(tmp_path):
    """Back-compat: a MANIFEST written before digests existed (format
    1: files listed, no sha256 map) passes verification on presence
    alone."""
    import json

    from bigdl_tpu.utils.serialization import (MANIFEST, load_checkpoint,
                                               verify_checkpoint)
    _save_ck(tmp_path / "checkpoint.2", 2, 3.0)
    mpath = tmp_path / "checkpoint.2" / MANIFEST
    with open(mpath) as f:
        manifest = json.load(f)
    del manifest["sha256"]
    manifest["format"] = 1
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    verify_checkpoint(str(tmp_path / "checkpoint.2"))
    assert load_checkpoint(
        str(tmp_path / "checkpoint.2"))["params"]["w"][0] == 3.0


def test_quarantined_dirs_are_never_selected(tmp_path):
    from bigdl_tpu.utils.serialization import (find_latest_checkpoint,
                                               quarantine_checkpoint)
    _save_ck(tmp_path / "checkpoint.2", 2, 1.0)
    _save_ck(tmp_path / "checkpoint.4", 4, 2.0)
    q = quarantine_checkpoint(str(tmp_path / "checkpoint.4"))
    assert q is not None and ".corrupt-" in q
    latest = find_latest_checkpoint(str(tmp_path))
    assert latest.endswith("checkpoint.2")


def test_try_resume_quarantines_corrupt_latest_and_walks_back(tmp_path):
    """The recovery contract the retry loop depends on: a corrupt
    LATEST checkpoint is quarantined and resume lands on the previous
    intact one — instead of re-raising on the same bad dir every
    retry (the satellite's truncate-params.npz-after-MANIFEST case)."""
    import os

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample
    from bigdl_tpu.optim.optimizer import Optimizer

    _save_ck(tmp_path / "checkpoint.2", 2, 1.0)
    _save_ck(tmp_path / "checkpoint.4", 4, 2.0)
    npz = tmp_path / "checkpoint.4" / "params.npz"
    with open(npz, "r+b") as f:
        f.truncate(os.path.getsize(npz) // 2)

    samples = [Sample(np.zeros(4, np.float32), np.float32(1.0))]
    opt = Optimizer(nn.Linear(4, 2), DataSet.array(samples),
                    nn.ClassNLLCriterion())
    opt.checkpoint_path = str(tmp_path)
    resumed = opt._try_resume()
    assert resumed is not None
    assert resumed["driver_state"]["neval"] == 2
    assert resumed["params"]["w"][0] == 1.0
    quarantined = [n for n in os.listdir(tmp_path) if ".corrupt-" in n]
    assert len(quarantined) == 1 and "checkpoint.4" in quarantined[0]
