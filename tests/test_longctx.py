"""Long-context stack tests (chunked prefill, paged decode, the
sequence-parallel train policy).

Chunked prefill must be BIT-identical to single-shot prefill — same
last-token logits, same KV rows — at every prompt length straddling a
chunk boundary, because chunking is a dispatch-shape decision, not a
numeric one. Paged decode must be token-identical to the contiguous
ragged kernel for any page table naming the same rows. The SP policy
(``SeqParallelConfig``) must be a quiet no-op wherever it cannot apply
(this CPU build has no ``jax.shard_map``), leaving the dense program
bit-identical; the sharded equivalence tests live in
tests/test_parallel.py behind ``shard_map_skip``.
"""
import numpy as np
import pytest

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.generation import GenerationConfig, GenerationService
from bigdl_tpu.generation.engine import DecodeEngine
from bigdl_tpu.generation.kv_cache import KVCache
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.serving import Servable
from bigdl_tpu.serving.compile_cache import BucketLadder, CompileCache
from bigdl_tpu.utils.random import RandomGenerator


def _model(vocab=50, hidden=32, layers=2, heads=4, max_len=64, seed=42):
    RandomGenerator.set_seed(seed)
    m = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                      num_layers=layers, num_heads=heads,
                      max_len=max_len).evaluate()
    m.ensure_initialized()
    return m


def _servable(model):
    return Servable("lm", 1, model, model.get_parameters(),
                    model.get_state())


def _engine(chunk=None, buckets=(16, 32, 64), slots=4):
    return DecodeEngine(CompileCache(), BucketLadder(max(buckets),
                                                     buckets=buckets),
                        slots=slots, prefill_rows=2,
                        prefill_chunk=chunk)


# ------------------------------------------------- chunked prefill

def test_chunked_prefill_bitwise_identical_at_every_chunk_boundary():
    """The acceptance invariant: a prompt prefilled in fixed 16-token
    chunks produces the SAME last-token logits and the SAME KV rows as
    the single-shot prefill, at every length straddling a chunk
    boundary (chunk-1 / chunk / chunk+1 / multiples / full rung)."""
    model = _model()
    sv = _servable(model)
    chunked, single = _engine(chunk=16), _engine(chunk=None)
    rng = np.random.RandomState(0)
    for plen in (15, 16, 17, 31, 32, 33, 48, 63, 64):
        prompt = rng.randint(1, 50, plen).astype(np.int32)
        kv_c = KVCache.for_model(model, 4, 64)
        kv_s = KVCache.for_model(model, 4, 64)
        out_c, bucket_c = chunked.prefill(sv, kv_c, [prompt], [1])
        out_s, bucket_s = single.prefill(sv, kv_s, [prompt], [1])
        assert bucket_c == bucket_s
        assert np.array_equal(out_c, out_s), f"logits differ at {plen}"
        # the written KV region is bitwise the single-shot one
        assert np.array_equal(np.asarray(kv_c.k)[:, 1, :, :plen],
                              np.asarray(kv_s.k)[:, 1, :, :plen]), plen
        assert np.array_equal(np.asarray(kv_c.v)[:, 1, :, :plen],
                              np.asarray(kv_s.v)[:, 1, :, :plen]), plen
        assert kv_c.lengths[1] == kv_s.lengths[1] == plen


def test_chunked_prefill_one_program_per_rung():
    """Chunking never mints extra programs: the chunk width is the
    rung's ONE token shape, so a chunked engine compiles exactly as
    many prefill programs as rungs it touched."""
    model = _model()
    sv = _servable(model)
    eng = _engine(chunk=16)
    kv = KVCache.for_model(model, 4, 64)
    rng = np.random.RandomState(1)
    for plen in (10, 20, 40, 60):  # rungs 16, 32, 64, 64
        eng.prefill(sv, kv, [rng.randint(1, 50, plen).astype(np.int32)],
                    [0])
    assert eng.compile_count(sv) == 3  # one per touched rung


def test_prefill_chunk_admission_and_start_validation():
    """The admission rule: the chunk must divide every larger rung
    (else chunk starts drift off the program's token grid), and a
    seeded ``start`` must be a chunk multiple below the prompt."""
    with pytest.raises(ValueError, match="divide"):
        _engine(chunk=12)  # 12 does not divide 16/32/64
    with pytest.raises(ValueError):
        _engine(chunk=0)
    eng = _engine(chunk=16)
    assert eng.chunk_for(16) == 16   # rung <= chunk: single-shot
    assert eng.chunk_for(64) == 16   # larger rungs fill chunkwise
    model = _model()
    sv = _servable(model)
    kv = KVCache.for_model(model, 4, 64)
    prompt = np.arange(1, 41, dtype=np.int32)  # rung 64
    with pytest.raises(ValueError, match="chunk multiple"):
        eng.prefill(sv, kv, [prompt], [0], start=[10])
    with pytest.raises(ValueError, match="chunk multiple"):
        eng.prefill(sv, kv, [prompt], [0], start=[48])  # >= len 40


def test_chunked_service_e2e_long_prompt_tokens_and_metrics():
    """A long prompt generates end-to-end through chunked prefill with
    the same greedy tokens as the unchunked service, the chunk counter
    reports every chunk dispatched, and the compile count stays inside
    the <= 2-programs-per-bucket bound."""
    model = _model()
    prompt = np.random.RandomState(3).randint(1, 50, 60).astype(np.int32)

    def run(chunk):
        svc = GenerationService(config=GenerationConfig(
            slots=2, max_len=64, length_buckets=(16, 32, 64),
            prefill_rows=2, prefill_chunk=chunk))
        svc.load("lm", model)
        try:
            out = list(svc.generate("lm", prompt,
                                    max_new_tokens=4).result(60))
            m = svc.metrics("lm")
        finally:
            svc.shutdown()
        return out, m

    chunked_out, m = run(16)
    single_out, _ = run(None)
    assert chunked_out == single_out
    assert m["prefill_chunks"] == -(-len(prompt) // 16)  # ceil(60/16)
    assert m["compile_count"] <= 2 * 3


# --------------------------------------------------- paged decode

def _decode_reference(q, k, v, lengths):
    """Length-masked dense decode attention in f32."""
    import jax.numpy as jnp
    slots, h, t, d = k.shape
    s = np.einsum("shd,shtd->sht", np.asarray(q, np.float32),
                  np.asarray(k, np.float32)) / np.sqrt(d)
    mask = np.arange(t)[None, None, :] < np.asarray(
        lengths).reshape(-1, 1, 1)
    s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("sht,shtd->shd", p, np.asarray(v, np.float32))


def test_paged_decode_token_identical_to_contiguous():
    """The paged kernel over an identity page view of a contiguous
    cache is BITWISE the contiguous ragged kernel's output (same tile
    width => same online-softmax accumulation order), and tight
    against the dense length-masked reference."""
    import jax
    from bigdl_tpu.kernels.paged_decode import (paged_decode_attention,
                                                paged_view)
    from bigdl_tpu.kernels.ragged_decode import ragged_decode_attention

    rng = np.random.RandomState(5)
    slots, h, t, d, page = 3, 2, 32, 8, 8
    q = np.asarray(rng.randn(slots, h, d), np.float32)
    k = np.asarray(rng.randn(slots, h, t, d), np.float32)
    v = np.asarray(rng.randn(slots, h, t, d), np.float32)
    lengths = np.array([5, 17, 32], np.int32)
    kp, vp, table = paged_view(jax.numpy.asarray(k),
                               jax.numpy.asarray(v), page)
    paged = np.asarray(paged_decode_attention(
        jax.numpy.asarray(q), kp, vp, table, jax.numpy.asarray(lengths),
        interpret=True))
    contig = np.asarray(ragged_decode_attention(
        jax.numpy.asarray(q), jax.numpy.asarray(k),
        jax.numpy.asarray(v), jax.numpy.asarray(lengths),
        block_k=page, interpret=True))
    assert np.array_equal(paged, contig)
    np.testing.assert_allclose(paged, _decode_reference(q, k, v, lengths),
                               atol=2e-6)


def test_paged_decode_shuffled_pool_matches_identity():
    """Physical page placement is invisible: permuting the pool and
    renaming the table gives the same output — the table IS the
    address space."""
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.kernels.paged_decode import (paged_decode_attention,
                                                paged_view)

    rng = np.random.RandomState(6)
    slots, h, t, d, page = 2, 2, 32, 8, 8
    q = jnp.asarray(rng.randn(slots, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(slots, h, t, d).astype(np.float32))
    v = jnp.asarray(rng.randn(slots, h, t, d).astype(np.float32))
    lengths = jnp.asarray(np.array([13, 32], np.int32))
    kp, vp, table = paged_view(k, v, page)
    base = np.asarray(paged_decode_attention(q, kp, vp, table, lengths,
                                             interpret=True))
    perm = rng.permutation(kp.shape[0])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    shuffled = np.asarray(paged_decode_attention(
        q, kp[perm], vp[perm], jnp.asarray(inv)[table], lengths,
        interpret=True))
    assert np.array_equal(base, shuffled)


def test_paged_dispatch_eligibility_and_decline():
    """The dispatch entry: paged decode runs under an enabled config
    with eligible shapes, declines (None) on config-off and on shape
    mismatches — the caller's contiguous-gather escape hatch."""
    import jax.numpy as jnp
    from bigdl_tpu import kernels
    from bigdl_tpu.kernels import dispatch
    from bigdl_tpu.kernels.paged_decode import paged_view

    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(2, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 2, 16, 8).astype(np.float32))
    lengths = jnp.asarray(np.array([4, 16], np.int32))
    kp, vp, table = paged_view(k, v, 8)
    with kernels.use(kernels.KernelConfig.all_on()):
        out = dispatch.paged_decode_attention(q, kp, vp, table, lengths)
        assert out is not None and out.shape == (2, 2, 8)
        # wrong table width (slots mismatch) -> shape decline
        assert dispatch.paged_decode_attention(
            q, kp, vp, table[:1], lengths) is None
        # int pools -> dtype decline
        assert dispatch.paged_decode_attention(
            q, kp.astype(jnp.int32), vp.astype(jnp.int32), table,
            lengths) is None
    with kernels.use(kernels.KernelConfig.off()):
        assert dispatch.paged_decode_attention(
            q, kp, vp, table, lengths) is None


# ------------------------------------- sequence-parallel policy

def test_seq_parallel_config_validation_and_context():
    from bigdl_tpu.parallel import (SeqParallelConfig,
                                    active_sequence_parallel,
                                    use_sequence_parallel)

    with pytest.raises(ValueError, match="ring.*ulysses"):
        SeqParallelConfig(impl="megatron")
    cfg = SeqParallelConfig(axis="seq", impl="ulysses")
    assert active_sequence_parallel() is None
    with use_sequence_parallel(cfg):
        assert active_sequence_parallel() is cfg
        with use_sequence_parallel(None):  # nested dense override
            assert active_sequence_parallel() is None
        assert active_sequence_parallel() is cfg
    assert active_sequence_parallel() is None


def test_seq_parallel_noop_without_shard_map_or_mesh():
    """Without ``jax.shard_map`` (this build) or a resolvable mesh the
    policy reports inactive and degree 1 — ``ZeroConfig.active_on``'s
    quiet-no-op contract."""
    import jax
    from bigdl_tpu.parallel import (SeqParallelConfig,
                                    sequence_parallel_available)

    cfg = SeqParallelConfig(axis="nonexistent_axis")
    assert not cfg.active_on(None)
    assert cfg.degree() == 1
    if not hasattr(jax, "shard_map"):
        assert not sequence_parallel_available()
        assert not SeqParallelConfig(axis="seq").active_on(None)


def test_build_train_step_seq_parallel_noop_is_bitwise_dense():
    """``build_train_step(seq_parallel=...)`` with an inapplicable
    policy runs the IDENTICAL dense program — losses bitwise equal —
    and the degree gauge reads 1 (the paid degree, not the asked-for
    one)."""
    import jax
    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.parallel import SeqParallelConfig

    model = _model(max_len=16)
    model.training()
    crit = nn.SequenceCrossEntropyCriterion()
    optim = SGD(learning_rate=0.1)
    rng = np.random.RandomState(11)
    x = rng.randint(1, 50, (2, 16)).astype(np.int32)
    y = np.roll(x, -1, axis=1)

    losses = []
    for sp in (None, SeqParallelConfig(axis="seq")):
        # fresh trees each run: the step donates its input buffers
        params = jax.tree_util.tree_map(np.asarray,
                                        model.get_parameters())
        opt_state = optim.init_state(params)
        mstate = model.get_state()
        step = build_train_step(model, crit, optim, seq_parallel=sp)
        _, _, _, loss = step(params, opt_state, mstate,
                             jax.random.PRNGKey(0), 0.1, x, y)
        losses.append(np.asarray(loss))
    assert np.array_equal(losses[0], losses[1])
    assert telemetry.gauge("train/seq_parallel/degree").value() == 1


def test_optimizer_set_sequence_parallel_typecheck():
    """The fluent setter: accepts a config or None (returns self for
    chaining), rejects anything else typed."""
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.parallel import SeqParallelConfig
    from bigdl_tpu.tools.chaos import _build_workload

    model, ds, crit = _build_workload("tiny", 42, 8)
    opt = Optimizer(model, ds, crit, batch_size=8)
    assert opt.set_sequence_parallel(
        SeqParallelConfig(axis="seq")) is opt
    assert opt.set_sequence_parallel(None) is opt
    with pytest.raises(TypeError):
        opt.set_sequence_parallel("ring")
