"""Distributed + device-cached inference (optim/Predictor.scala:35,
Evaluator.scala:37): the mesh path must score/predict identically to
the single-device path, batch-shard the forward over the data axis,
survive ragged final batches (fixed-shape padding), sweep device-cached
datasets off HBM, and honor TP sharding rules."""
import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import Top1Accuracy
from bigdl_tpu.optim.evaluator import Evaluator
from bigdl_tpu.optim.predictor import LocalPredictor, Predictor
from bigdl_tpu.parallel import make_mesh
from bigdl_tpu.utils.random import RandomGenerator


def _mlp(din=12, dout=3, seed=7):
    RandomGenerator.set_seed(seed)
    return (nn.Sequential().add(nn.Linear(din, 16)).add(nn.Tanh())
            .add(nn.Linear(16, dout)).add(nn.LogSoftMax()))


def _samples(n=22, din=12, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, din).astype(np.float32)
    ys = (rng.randint(0, 3, n) + 1).astype(np.float32)
    return [Sample(xs[i], ys[i]) for i in range(n)]


def test_mesh_predict_matches_local_incl_ragged_final_batch():
    samples = _samples(22)  # 22 % 8 != 0: ragged tail exercises padding
    model = _mlp()
    local = LocalPredictor(model).predict(DataSet.array(samples),
                                          batch_size=8)
    mesh = make_mesh([8], ["data"], jax.devices()[:8])
    dist = Predictor(model, mesh=mesh).predict(DataSet.array(samples),
                                               batch_size=8)
    assert len(local) == len(dist) == 22
    np.testing.assert_allclose(np.stack(dist), np.stack(local),
                               atol=1e-5)


def test_mesh_predict_class_and_module_surface():
    samples = _samples(16)
    model = _mlp()
    mesh = make_mesh([8], ["data"], jax.devices()[:8])
    pc_local = LocalPredictor(model).predict_class(
        DataSet.array(samples), batch_size=8)
    pc_mesh = Predictor(model, mesh=mesh).predict_class(
        DataSet.array(samples), batch_size=8)
    assert pc_local == pc_mesh
    # the Module-level one-liner takes a mesh too
    outs = model.predict(DataSet.array(samples), batch_size=8, mesh=mesh)
    np.testing.assert_allclose(
        np.stack(outs),
        np.stack(LocalPredictor(model).predict(DataSet.array(samples),
                                               batch_size=8)), atol=1e-5)


def test_mesh_evaluator_matches_local():
    samples = _samples(24)
    model = _mlp()
    ds = DataSet.array(samples)
    r_local = Evaluator(model).test(ds, [Top1Accuracy()], batch_size=8)
    mesh = make_mesh([8], ["data"], jax.devices()[:8])
    r_mesh = Evaluator(model, mesh=mesh).test(ds, [Top1Accuracy()],
                                              batch_size=8)
    (vl, _), (vm, _) = (r_local["Top1Accuracy"].result(),
                        r_mesh["Top1Accuracy"].result())
    assert vl == vm


def test_device_cached_predict_and_evaluate():
    """Forward sweep straight off the HBM cache: gather+normalize+model
    inside one jitted step, trimmed exactly at the dataset tail."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet

    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 255, (24, 3, 10, 10), np.uint8)
    lbls = (rng.randint(0, 2, 24) + 1).astype(np.float32)
    RandomGenerator.set_seed(11)
    model = (nn.Sequential().add(nn.Reshape((3 * 8 * 8,)))
             .add(nn.Linear(3 * 8 * 8, 2)).add(nn.LogSoftMax()))

    mesh = make_mesh([8], ["data"], jax.devices()[:8])
    sh = NamedSharding(mesh, P("data"))
    dcd = DeviceCachedArrayDataSet(imgs, lbls, 8, crop=(8, 8), pad=0,
                                   flip=False, mean=(127,) * 3,
                                   std=(64,) * 3, sharding=sh)
    preds = Predictor(model, mesh=mesh).predict(dcd)
    assert len(preds) == 24
    # oracle: the same deterministic eval batches through a local step
    res = Evaluator(model, mesh=mesh).test(dcd, [Top1Accuracy()])
    v, n = res["Top1Accuracy"].result()
    assert n == 24 and 0.0 <= v <= 1.0
    # prediction argmax must agree with the accuracy bookkeeping
    top1 = sum(int(np.argmax(p)) + 1 == int(l)
               for p, l in zip(preds, lbls)) / 24
    assert abs(top1 - v) < 1e-6


def test_tp_sharded_predict_matches_replicated():
    """sharding_rules lay the params out TP-style for the forward —
    the int8/serving layout story on a model-parallel mesh."""
    from bigdl_tpu.models import TransformerLM

    RandomGenerator.set_seed(5)
    lm = TransformerLM(vocab_size=32, hidden_size=16, num_layers=2,
                       num_heads=4, max_len=8)
    lm.ensure_initialized()
    rng = np.random.RandomState(1)
    toks = rng.randint(0, 32, (8, 8)).astype(np.int32)
    samples = [Sample(toks[i], np.float32(1.0)) for i in range(8)]
    local = LocalPredictor(lm).predict(DataSet.array(samples),
                                       batch_size=4)
    mesh = make_mesh([2, 4], ["data", "model"], jax.devices()[:8])
    dist = Predictor(lm, mesh=mesh,
                     sharding_rules=lm.sharding_rules(
                         model_axis="model")).predict(
        DataSet.array(samples), batch_size=4)
    np.testing.assert_allclose(np.stack(dist), np.stack(local),
                               atol=2e-4)


def test_mesh_path_rejects_table_and_multi_tensor_inputs():
    """ADVICE r5: the mesh sweep lays batches over the data axis, which
    only exists for a single dense ndarray — table/multi-tensor inputs
    must fail loudly, not become ragged object arrays."""
    from bigdl_tpu.dataset.sample import MiniBatch

    model = _mlp()
    mesh = make_mesh([8], ["data"], jax.devices()[:8])
    multi = [MiniBatch([np.zeros((8, 12), np.float32),
                        np.zeros((8, 3), np.float32)],
                       np.ones(8, np.float32))]
    with pytest.raises(TypeError, match="single-ndarray"):
        Predictor(model, mesh=mesh).predict(multi, batch_size=8)
    with pytest.raises(TypeError, match="single-ndarray"):
        Evaluator(model, mesh=mesh).test(multi, [Top1Accuracy()],
                                         batch_size=8)
    # the local path still serves them (that's the documented fallback)
    outs = LocalPredictor(model).predict(
        [MiniBatch(np.zeros((8, 12), np.float32))], batch_size=8)
    assert len(outs) == 8
