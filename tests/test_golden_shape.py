"""Golden numeric checks for the shape/structure family against
numpy/PyTorch references (reference torch/ suite role, SURVEY.md §4.2).
Dims are 1-based like the reference (Torch convention)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bigdl_tpu.nn as nn  # noqa: E402
from bigdl_tpu.utils.table import T  # noqa: E402


def _x(shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def _run(m, x, training=False):
    m.ensure_initialized()
    out, _ = m.apply(m.get_parameters(), m.get_state(), x,
                     training=training)
    return out


def test_view_and_reshape():
    x = _x((2, 3, 4))
    np.testing.assert_allclose(np.asarray(_run(nn.View(12), x)),
                               x.reshape(2, 12))
    np.testing.assert_allclose(
        np.asarray(_run(nn.Reshape((4, 3), batch_mode=True), x)),
        x.reshape(2, 4, 3))


def test_squeeze_unsqueeze():
    x = _x((2, 1, 3, 1))
    out = np.asarray(_run(nn.Squeeze(2, num_input_dims=3), x))
    assert out.shape == (2, 3, 1)   # 1-based dim 2 (after batch)
    x2 = _x((2, 3))
    # insert a new dim AT 1-based pos 2 (Unsqueeze.scala)
    out2 = np.asarray(_run(nn.Unsqueeze(2, num_input_dims=2), x2))
    assert out2.shape == (2, 1, 3)
    np.testing.assert_allclose(out2[:, 0, :], x2)
    # batched input: pos counts within the unbatched shape
    x3 = _x((4, 2, 3))
    out3 = np.asarray(_run(nn.Unsqueeze(2, num_input_dims=2), x3))
    assert out3.shape == (4, 2, 1, 3)


def test_transpose_contiguous():
    x = _x((2, 3, 4))
    out = np.asarray(_run(nn.Transpose([(2, 3)]), x))
    np.testing.assert_allclose(out, x.transpose(0, 2, 1))
    np.testing.assert_allclose(np.asarray(_run(nn.Contiguous(), x)), x)


def test_replicate():
    x = _x((2, 3))
    out = np.asarray(_run(nn.Replicate(4, dim=1), x))
    # replicate along a new dim (nn/Replicate.scala)
    assert out.shape[0] == 4 or out.shape[1] == 4
    flat_src = np.broadcast_to(x, out.shape) if out.shape[0] == 4 else None
    if flat_src is not None:
        np.testing.assert_allclose(out, flat_src)


def test_padding_and_spatial_zero_padding():
    x = _x((2, 3))
    out = np.asarray(_run(nn.Padding(2, 2, 2), x))
    assert out.shape == (2, 5)
    np.testing.assert_allclose(out[:, :3], x)
    np.testing.assert_allclose(out[:, 3:], 0)
    neg = np.asarray(_run(nn.Padding(2, -2, 2), x))
    assert neg.shape == (2, 5)
    np.testing.assert_allclose(neg[:, 2:], x)
    img = _x((1, 2, 3, 3))
    out2 = np.asarray(_run(nn.SpatialZeroPadding(1, 2, 1, 0), img))
    assert out2.shape == (1, 2, 4, 6)
    np.testing.assert_allclose(out2[:, :, 1:, 1:4], img)


def test_narrow_select_index():
    x = _x((4, 6))
    out = np.asarray(_run(nn.Narrow(2, 2, 3), x))
    np.testing.assert_allclose(out, x[:, 1:4])  # 1-based offset 2
    # negative offset counts from the end (Narrow.scala)
    out_neg = np.asarray(_run(nn.Narrow(2, -2, 2), x))
    np.testing.assert_allclose(out_neg, x[:, 4:6])
    sel = np.asarray(_run(nn.Select(1, 3), x))
    np.testing.assert_allclose(sel, x[2])
    sel_neg = np.asarray(_run(nn.Select(2, -1), x))
    np.testing.assert_allclose(sel_neg, x[:, -1])
    idx = np.asarray([1.0, 3.0, 1.0], np.float32)  # 1-based indices
    out_idx = np.asarray(_run(nn.Index(1), [x, idx]))
    np.testing.assert_allclose(out_idx, x[[0, 2, 0]])


def test_masked_select():
    x = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
    mask = np.asarray([[1.0, 0.0], [0.0, 1.0]], np.float32)
    out = np.asarray(_run(nn.MaskedSelect(), [x, mask]))
    np.testing.assert_allclose(np.sort(out.ravel())[:2], [1.0, 4.0])


def test_max_min_mean_sum():
    x = _x((3, 5))
    tx = torch.tensor(x)
    np.testing.assert_allclose(np.asarray(_run(nn.Max(2, 2), x)),
                               tx.max(dim=1).values.numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(_run(nn.Min(2, 2), x)),
                               tx.min(dim=1).values.numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(_run(nn.Mean(2, 2), x)),
                               x.mean(axis=1), atol=1e-6)
    np.testing.assert_allclose(np.asarray(_run(nn.Sum(2, 2), x)),
                               x.sum(axis=1), atol=1e-5)


def test_tile_pack_reverse():
    x = _x((2, 3))
    out = np.asarray(_run(nn.Tile(2, 3), x))
    np.testing.assert_allclose(out, np.tile(x, (1, 3)))
    a, b = _x((2, 3), 1), _x((2, 3), 2)
    packed = np.asarray(_run(nn.Pack(1), [a, b]))
    np.testing.assert_allclose(packed, np.stack([a, b], axis=0))
    rev = np.asarray(_run(nn.Reverse(1), x))
    np.testing.assert_allclose(rev, x[::-1])
    rev2 = np.asarray(_run(nn.Reverse(2), x))
    np.testing.assert_allclose(rev2, x[:, ::-1])


def test_split_join_bifurcate_flatten():
    x = _x((2, 4, 3))
    parts = _run(nn.SplitTable(2, 3), x)
    parts = list(parts)
    assert len(parts) == 4
    np.testing.assert_allclose(np.asarray(parts[0]), x[:, 0, :])
    joined = np.asarray(_run(nn.JoinTable(2, 2),
                             [x[:, :, 0], x[:, :, 1]]))
    np.testing.assert_allclose(
        joined, np.concatenate([x[:, :, 0], x[:, :, 1]], axis=1))
    l, r = list(_run(nn.BifurcateSplitTable(2), x))
    np.testing.assert_allclose(np.asarray(l), x[:, :2, :])
    np.testing.assert_allclose(np.asarray(r), x[:, 2:, :])
    nested = T(T(np.ones((2,)), np.zeros((2,))), np.full((2,), 2.0))
    flat = list(_run(nn.FlattenTable(), nested))
    assert len(flat) == 3


def test_select_table_narrow_table():
    a, b, c = (np.full((2, 2), v, np.float32) for v in (1, 2, 3))
    out = np.asarray(_run(nn.SelectTable(2), [a, b, c]))
    np.testing.assert_allclose(out, b)
    out_neg = np.asarray(_run(nn.SelectTable(-1), [a, b, c]))
    np.testing.assert_allclose(out_neg, c)
    nt = list(_run(nn.NarrowTable(2, 2), [a, b, c]))
    assert len(nt) == 2
    np.testing.assert_allclose(np.asarray(nt[0]), b)


def test_resize_bilinear_matches_tf_and_torch():
    x = _x((2, 3, 5, 7))
    # align_corners=True: same endpoint mapping as torch
    out_ac = np.asarray(_run(nn.ResizeBilinear(10, 14,
                                               align_corners=True), x))
    want_ac = torch.nn.functional.interpolate(
        torch.tensor(x), size=(10, 14), mode="bilinear",
        align_corners=True)
    np.testing.assert_allclose(out_ac, want_ac.numpy(), atol=1e-5)
    # align_corners=False: the reference wraps TF's legacy resize
    # (src = dst * scale), oracle is real TF
    tf = pytest.importorskip("tensorflow")
    out = np.asarray(_run(nn.ResizeBilinear(10, 14), x))
    want = tf.compat.v1.image.resize_bilinear(
        tf.constant(x.transpose(0, 2, 3, 1)), (10, 14),
        align_corners=False, half_pixel_centers=False).numpy()
    np.testing.assert_allclose(out, want.transpose(0, 3, 1, 2), atol=1e-5)


def test_nms_hand_computed():
    # three boxes: b0 and b1 heavily overlap; b2 is separate
    boxes = np.asarray([[0, 0, 10, 10],
                        [1, 1, 10.5, 10.5],
                        [20, 20, 30, 30]], np.float32)
    scores = np.asarray([0.9, 0.8, 0.7], np.float32)
    m = nn.Nms(iou_threshold=0.5, max_output=10)
    keep = np.asarray(m.forward([boxes, scores])).astype(int).ravel()
    kept = [k for k in keep.tolist() if k >= 0]
    assert 0 in [k - 1 for k in kept] or 0 in kept  # top box kept
    # the overlapping lower-score box must be suppressed
    as0 = set(k - min(kept) for k in kept)
    assert len(kept) == 2 and 1 not in as0


def test_scale_layer():
    m = nn.Scale((1, 3, 1, 1))
    m.ensure_initialized()
    p = dict(m.get_parameters())
    x = _x((2, 3, 4, 4))
    out = np.asarray(m.apply(p, m.get_state(), x, training=False)[0])
    w = np.asarray(p["cmul"]["weight"])   # CMul then CAdd (Scale.scala)
    b = np.asarray(p["cadd"]["bias"])
    np.testing.assert_allclose(out, x * w.reshape(1, 3, 1, 1)
                               + b.reshape(1, 3, 1, 1), atol=1e-5)
