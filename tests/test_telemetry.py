"""Telemetry subsystem tests (ISSUE 3): span tracer golden Chrome-trace
export + cross-thread nesting, disabled-mode overhead bound, metrics
registry semantics + thread safety under concurrent batcher traffic,
exporter agreement (TensorBoard/Prometheus/JSONL), and the acceptance
flow — instrumented LeNet training + concurrent serving burst producing
ONE schema-valid trace whose phase sums match Metrics.summary()."""
import json
import os
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import (Counter, MetricsRegistry, SpanTracer,
                                 parse_prometheus_text, prometheus_text,
                                 read_jsonl, scalarize)


@pytest.fixture(autouse=True)
def _telemetry_off():
    """Every test starts and ends with tracing disabled and an empty
    ring (the registry is cumulative by design; tests use deltas or
    private registries)."""
    telemetry.disable()
    telemetry.tracer().clear()
    yield
    telemetry.disable()
    telemetry.tracer().clear()


def validate_chrome_trace(events):
    """The trace-event schema the acceptance criterion names: every
    complete event carries ph/ts/dur/pid/tid/name with sane types.
    Flow events ("s"/"f" — the request-track links) carry an id."""
    assert events, "empty trace"
    for ev in events:
        assert ev["ph"] in ("X", "M", "s", "f"), ev
        if ev["ph"] in ("s", "f"):
            assert "id" in ev and "ts" in ev and "tid" in ev
            continue
        if ev["ph"] == "X":
            for k in ("ts", "dur", "pid", "tid", "name"):
                assert k in ev, (k, ev)
            assert isinstance(ev["ts"], (int, float))
            assert isinstance(ev["dur"], (int, float))
            assert ev["dur"] >= 0
            assert isinstance(ev["pid"], int)
            assert isinstance(ev["tid"], int)
            assert isinstance(ev["name"], str) and ev["name"]
            if "args" in ev:
                json.dumps(ev["args"])  # must be JSON-serializable


# ---------------------------------------------------------------- tracer

class TestSpanTracer:
    def test_golden_chrome_trace_fields_and_nesting(self, tmp_path):
        tr = SpanTracer()
        with tr.span("optimizer/step", {"step": 1}):
            with tr.span("optimizer/data_wait"):
                time.sleep(0.002)
            with tr.span("optimizer/compute"):
                time.sleep(0.002)
        path = str(tmp_path / "trace.json")
        # export via a process-tracer-independent writer
        events = tr.chrome_trace_events()
        validate_chrome_trace(events)
        xs = {e["name"]: e for e in events if e["ph"] == "X"}
        assert set(xs) == {"optimizer/step", "optimizer/data_wait",
                           "optimizer/compute"}
        parent = xs["optimizer/step"]
        assert parent["args"] == {"step": 1}
        for child in ("optimizer/data_wait", "optimizer/compute"):
            c = xs[child]
            # nesting: child interval inside parent interval, same tid
            assert c["tid"] == parent["tid"]
            assert c["ts"] >= parent["ts"] - 1e-3
            assert c["ts"] + c["dur"] <= parent["ts"] + parent["dur"] \
                + 1e-3
        # file form loads and carries the same events
        tr2 = SpanTracer()
        with tr2.span("x/y", None):
            pass
        n = tr2.export_chrome_trace(path)
        data = json.load(open(path))
        assert n == 1
        assert "traceEvents" in data
        validate_chrome_trace(data["traceEvents"])

    def test_nesting_preserved_across_threads(self):
        tr = SpanTracer()

        def work(tag):
            with tr.span(f"worker/{tag}/outer", None):
                with tr.span(f"worker/{tag}/inner", None):
                    time.sleep(0.002)

        threads = [threading.Thread(target=work, args=(t,),
                                    name=f"span-{t}")
                   for t in ("a", "b")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = tr.chrome_trace_events()
        validate_chrome_trace(events)
        xs = {e["name"]: e for e in events if e["ph"] == "X"}
        assert len(xs) == 4
        # each thread's inner nests in ITS OWN outer; tracks differ
        for tag in ("a", "b"):
            outer, inner = xs[f"worker/{tag}/outer"], \
                xs[f"worker/{tag}/inner"]
            assert inner["tid"] == outer["tid"]
            assert inner["ts"] >= outer["ts"] - 1e-3
            assert inner["ts"] + inner["dur"] <= \
                outer["ts"] + outer["dur"] + 1e-3
        assert xs["worker/a/outer"]["tid"] != xs["worker/b/outer"]["tid"]
        # thread_name metadata rows the two worker tracks
        meta = {e["args"]["name"] for e in events if e["ph"] == "M"}
        assert {"span-a", "span-b"} <= meta

    def test_ring_buffer_is_bounded(self):
        tr = SpanTracer(capacity=16)
        for i in range(100):
            with tr.span(f"s/{i}", None):
                pass
        spans = tr.spans()
        assert len(spans) == 16
        assert spans[-1].name == "s/99"  # newest kept, oldest rotated

    def test_record_pre_measured_interval(self):
        tr = SpanTracer()
        tr.record("optimizer/data_wait", 0.125, {"step": 3})
        (s,) = tr.spans()
        assert s.dur == 0.125
        assert s.args == {"step": 3}

    def test_span_args_always_jsonable(self):
        tr = SpanTracer()
        with tr.span("x/y", {"arr": np.float32(1.5), "o": object()}):
            pass
        (ev,) = [e for e in tr.chrome_trace_events() if e["ph"] == "X"]
        json.dumps(ev)  # numpy scalar coerced, object stringified
        assert ev["args"]["arr"] == 1.5


class TestDisabledMode:
    def test_disabled_span_overhead_bounded(self):
        """The no-op fast path: one flag check + a shared context
        manager. Budget is generous for CI noise; the real cost is
        ~0.2us."""
        assert not telemetry.enabled()
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with telemetry.span("optimizer/step"):
                pass
        per_span = (time.perf_counter() - t0) / n
        assert per_span < 5e-6, f"{per_span * 1e6:.2f}us per disabled span"

    def test_disabled_record_overhead_bounded(self):
        """record() is the optimizer hot loop's other entry point (the
        exact t_data/t_compute shipper); disabled it must be one flag
        check and return — no dict, no clock, no string work."""
        assert not telemetry.enabled()
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            telemetry.record("optimizer/data_wait", 0.001)
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, \
            f"{per_call * 1e6:.2f}us per disabled record"

    def test_disabled_creates_no_threads_files_or_spans(self, tmp_path):
        before_threads = set(threading.enumerate())
        cwd_before = sorted(os.listdir(tmp_path))
        for i in range(1000):
            with telemetry.span("a/b", step=i):
                pass
            telemetry.record("c/d", 0.1)
        assert set(threading.enumerate()) == before_threads
        assert sorted(os.listdir(tmp_path)) == cwd_before
        assert len(telemetry.tracer()) == 0  # nothing recorded

    def test_disabled_span_is_shared_noop(self):
        s1 = telemetry.span("a/b")
        s2 = telemetry.span("c/d", k=1)
        assert s1 is s2  # the singleton — no allocation per call

    def test_enable_capacity_honored_after_tracer_precreated(self):
        # tracer() pre-creates the ring; an explicit enable(capacity=)
        # must still re-bound it rather than silently dropping the ask
        old = telemetry.tracer().capacity
        try:
            telemetry.enable(capacity=8)
            assert telemetry.tracer().capacity == 8
            for i in range(20):
                with telemetry.span(f"s/{i}"):
                    pass
            assert len(telemetry.tracer()) == 8
            telemetry.disable()
            telemetry.enable()  # no capacity: keeps the current bound
            assert telemetry.tracer().capacity == 8
        finally:
            telemetry.tracer().set_capacity(old)

    def test_enable_disable_roundtrip(self):
        telemetry.enable()
        with telemetry.span("x/y"):
            pass
        assert len(telemetry.tracer()) == 1
        telemetry.disable()
        with telemetry.span("x/z"):
            pass
        assert len(telemetry.tracer()) == 1  # disabled span not recorded


# -------------------------------------------------------------- registry

class TestMetricsRegistry:
    def test_counter_gauge_histogram_basics(self):
        r = MetricsRegistry()
        c = r.counter("serving/batcher/requests", "reqs")
        c.inc()
        c.inc(2, model="a")
        assert c.value() == 1
        assert c.value(model="a") == 2
        assert c.total() == 3
        with pytest.raises(ValueError):
            c.inc(-1)
        g = r.gauge("data/prefetch/queue_depth")
        g.set(4)
        g.add(-1)
        assert g.value() == 3
        h = r.histogram("serving/batcher/latency_ms", reservoir_size=8)
        for v in range(20):
            h.observe(float(v))
        assert h.count() == 20
        assert h.sum() == sum(range(20))
        assert len(h.samples()) == 8  # bounded reservoir
        assert h.percentiles((50,))["p50"] == pytest.approx(15.5)

    def test_get_or_create_and_kind_conflict(self):
        r = MetricsRegistry()
        a = r.counter("a/b/c")
        assert r.counter("a/b/c") is a
        with pytest.raises(ValueError):
            r.gauge("a/b/c")

    def test_audit_names(self):
        r = MetricsRegistry()
        r.counter("serving/batcher/requests")
        r.counter("BadName")
        r.gauge("also/bad")
        assert telemetry.audit_names(r) == ["BadName", "also/bad"]

    def test_histogram_thread_safety(self):
        r = MetricsRegistry()
        h = r.histogram("x/y/z")
        c = r.counter("x/y/n")

        def work():
            for i in range(5000):
                h.observe(1.0, model="m")
                c.inc(model="m")

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count(model="m") == 40_000
        assert h.sum(model="m") == 40_000.0
        assert c.value(model="m") == 40_000


# -------------------------------------------------------------- exporters

class TestExporters:
    def _populated(self):
        r = MetricsRegistry()
        r.counter("serving/batcher/requests", "reqs").inc(7, model="m")
        r.counter("train/optimizer/steps", "steps").inc(3)
        r.gauge("data/prefetch/queue_depth", "depth").set(2)
        h = r.histogram("serving/batcher/latency_ms", "lat")
        for v in (1.0, 2.0, 3.0):
            h.observe(v, model="m")
        return r

    def test_prometheus_escaping_label_roundtrip(self):
        r = MetricsRegistry()
        c = r.counter("serving/batcher/requests", 'desc with "quotes"')
        nasty = 'a"b\\c\nd'
        c.inc(5, model=nasty)
        text = prometheus_text(r.snapshot())
        parsed = parse_prometheus_text(text)
        assert parsed[("serving_batcher_requests",
                       (("model", nasty),))] == 5.0

    def test_prometheus_histogram_summary_form(self):
        r = self._populated()
        parsed = parse_prometheus_text(prometheus_text(r.snapshot()))
        labels = (("model", "m"),)
        assert parsed[("serving_batcher_latency_ms_count", labels)] == 3
        assert parsed[("serving_batcher_latency_ms_sum", labels)] == 6.0
        assert parsed[("serving_batcher_latency_ms",
                       labels + (("quantile", "0.5"),))] == 2.0

    def test_prometheus_nonfinite_values_render(self):
        import math
        r = MetricsRegistry()
        r.gauge("a/b/inf").set(float("inf"))
        r.gauge("a/b/nan").set(float("nan"))
        parsed = parse_prometheus_text(prometheus_text(r.snapshot()))
        assert parsed[("a_b_inf", ())] == float("inf")
        assert math.isnan(parsed[("a_b_nan", ())])

    def test_write_prometheus_atomic_file(self, tmp_path):
        r = self._populated()
        path = str(tmp_path / "m.prom")
        text = telemetry.write_prometheus(r, path)
        assert open(path).read() == text
        assert not os.path.exists(path + ".part")

    def test_tensorboard_filereader_roundtrip(self, tmp_path):
        from bigdl_tpu.visualization.tensorboard import FileReader
        r = self._populated()
        log_dir = str(tmp_path / "tb")
        exp = telemetry.TensorBoardExporter(r, log_dir)
        exp.export(step=5)
        exp.close()
        rows = FileReader.read_scalar(log_dir, "train/optimizer/steps")
        assert [(s, v) for s, v, _ in rows] == [(5, 3.0)]
        rows = FileReader.read_scalar(
            log_dir, "serving/batcher/requests[model=m]")
        assert [(s, v) for s, v, _ in rows] == [(5, 7.0)]
        rows = FileReader.read_scalar(
            log_dir, "serving/batcher/latency_ms[model=m].sum")
        assert [(s, v) for s, v, _ in rows] == [(5, 6.0)]

    def test_jsonl_append_and_read(self, tmp_path):
        r = self._populated()
        path = str(tmp_path / "m.jsonl")
        exp = telemetry.JsonlExporter(r, path)
        exp.export(step=1, meta={"run": "a"})
        r.counter("train/optimizer/steps").inc()
        exp.export(step=2)
        recs = read_jsonl(path)
        assert len(recs) == 2
        assert recs[0]["step"] == 1 and recs[0]["meta"] == {"run": "a"}
        s1 = scalarize(recs[0]["metrics"])
        s2 = scalarize(recs[1]["metrics"])
        assert s1["train/optimizer/steps"] == 3.0
        assert s2["train/optimizer/steps"] == 4.0

    def test_three_exporters_agree_on_counter_totals(self, tmp_path):
        """The acceptance criterion: TensorBoard, Prometheus text and
        JSONL all report the same counter totals for the same run."""
        from bigdl_tpu.visualization.tensorboard import FileReader
        r = self._populated()
        counters = {
            "serving/batcher/requests[model=m]":
                ("serving_batcher_requests", (("model", "m"),)),
            "train/optimizer/steps": ("train_optimizer_steps", ()),
        }
        # 1. JSONL
        jsonl_path = str(tmp_path / "m.jsonl")
        telemetry.JsonlExporter(r, jsonl_path).export()
        jsonl_vals = scalarize(read_jsonl(jsonl_path)[0]["metrics"])
        # 2. Prometheus
        prom = parse_prometheus_text(
            telemetry.write_prometheus(r, str(tmp_path / "m.prom")))
        # 3. TensorBoard
        log_dir = str(tmp_path / "tb")
        exp = telemetry.TensorBoardExporter(r, log_dir)
        exp.export(step=1)
        exp.close()
        for tag, prom_key in counters.items():
            tb = FileReader.read_scalar(log_dir, tag)
            assert len(tb) == 1
            assert jsonl_vals[tag] == prom[prom_key] == tb[0][1], tag


# ------------------------------------------------- batcher/serving wiring

class TestServingIntegration:
    def test_registry_thread_safety_under_concurrent_batcher_traffic(
            self):
        """8 submitter threads against one MicroBatcher (pure-python
        runner): every admission outcome is accounted for exactly in
        the registry-backed stats."""
        from bigdl_tpu.serving.batcher import MicroBatcher, QueueFull
        from bigdl_tpu.serving.compile_cache import BucketLadder

        reg = MetricsRegistry()
        b = MicroBatcher(lambda x: x, BucketLadder(8),
                         max_wait_ms=0.5, max_queue=512, name="m",
                         metrics=reg)
        per_thread, threads_n = 100, 8
        admitted = []

        def work():
            ok = 0
            for i in range(per_thread):
                try:
                    b.submit(np.ones((1, 4), np.float32)).result(
                        timeout=30)
                    ok += 1
                except QueueFull:
                    pass
            admitted.append(ok)

        threads = [threading.Thread(target=work)
                   for _ in range(threads_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        b.shutdown(drain=True)
        total_ok = sum(admitted)
        st = b.stats
        assert st.requests == total_ok
        assert st.rows == total_ok
        assert st.rejected == per_thread * threads_n - total_ok
        assert st.errors == 0
        assert st.batched_rows == total_ok
        # the same numbers through the registry the exporters read
        assert reg.counter("serving/batcher/requests").value(
            model="m") == total_ok
        assert reg.histogram("serving/batcher/queue_wait_ms").count(
            model="m") == total_ok

    def test_service_metrics_shape_byte_compatible(self):
        """The pre-telemetry InferenceService.metrics() key set."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.serving import InferenceService, ServingConfig

        svc = InferenceService(config=ServingConfig(max_batch_size=4,
                                                    buckets=(4,)))
        m = nn.Sequential().add(nn.Linear(3, 2))
        m.ensure_initialized()
        svc.load("m", m)
        svc.predict_batch("m", np.ones((2, 3), np.float32))
        out = svc.metrics("m")
        svc.shutdown()
        assert {"request_count", "rows", "rejected", "timed_out",
                "errors", "batch_count", "batch_fill",
                "padded_row_ratio", "queue_depth",
                "compile_count"} <= set(out)
        assert out["request_count"] == 1 and out["rows"] == 2
        # and the service's registry carries the same series
        assert svc.metrics_registry.counter(
            "serving/batcher/requests").value(model="m") == 1
        assert svc.metrics_registry.counter(
            "serving/compile_cache/misses").value(model="m") == 1

    def test_two_services_do_not_mix_counts(self):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.serving import InferenceService, ServingConfig

        def mk():
            svc = InferenceService(config=ServingConfig(
                max_batch_size=4, buckets=(4,)))
            m = nn.Sequential().add(nn.Linear(3, 2))
            m.ensure_initialized()
            svc.load("m", m)
            return svc

        s1, s2 = mk(), mk()
        s1.predict_batch("m", np.ones((2, 3), np.float32))
        assert s1.metrics("m")["request_count"] == 1
        assert s2.metrics("m")["request_count"] == 0
        s1.shutdown()
        s2.shutdown()


# --------------------------------------------------- end-to-end / diagnose

class TestAcceptance:
    @pytest.fixture(scope="class")
    def workload(self, tmp_path_factory):
        """One instrumented LeNet run + concurrent serving burst,
        shared by the acceptance assertions (it carries a compile)."""
        from bigdl_tpu.tools.diagnose import run_workload
        trace_path = str(tmp_path_factory.mktemp("diag") / "trace.json")
        telemetry.tracer().clear()
        opt, events, snapshot = run_workload(
            steps=3, batch_size=16, serve=True, trace_path=trace_path)
        telemetry.disable()
        return opt, events, snapshot, trace_path

    def test_single_trace_loads_structurally(self, workload):
        _, _, _, trace_path = workload
        data = json.load(open(trace_path))
        validate_chrome_trace(data["traceEvents"])
        names = {e["name"] for e in data["traceEvents"]
                 if e["ph"] == "X"}
        # train AND serving phases in the ONE trace
        assert "optimizer/data_wait" in names
        assert "optimizer/compute" in names
        assert "serving/batch" in names
        # serving batches ran on their own thread track
        tids = {e["name"]: {ev["tid"] for ev in data["traceEvents"]
                            if ev["ph"] == "X" and ev["name"] == e["name"]}
                for e in data["traceEvents"] if e["ph"] == "X"}
        assert tids["serving/batch"].isdisjoint(
            tids["optimizer/compute"])

    def test_phase_sums_consistent_with_metrics_summary(self, workload):
        opt, events, _, _ = workload
        from bigdl_tpu.tools.diagnose import aggregate_spans
        agg = aggregate_spans(events)
        # the trace is fed the EXACT t_data/t_compute floats Metrics
        # records; only the us-rounding of the export separates them
        for span_name, metric in (("optimizer/data_wait", "data time"),
                                  ("optimizer/compute",
                                   "computing time")):
            assert agg[span_name]["count"] == 3
            assert agg[span_name]["total_s"] == pytest.approx(
                sum(opt.metrics.values[metric]), abs=1e-4)
        # and the registry histograms carry the same sums
        h = telemetry.registry().histogram(
            "train/optimizer/computing_time")
        assert agg["optimizer/compute"]["total_s"] == pytest.approx(
            sum(opt.metrics.values["computing time"]), abs=1e-4)
        assert h.sum() >= sum(opt.metrics.values["computing time"]) - 1e-6

    def test_diagnose_cli_ingests_the_trace(self, workload, capsys):
        from bigdl_tpu.tools.diagnose import main
        _, _, _, trace_path = workload
        assert main(["--trace", trace_path, "--json"]) == 0
        rows = json.loads(capsys.readouterr().out)["spans"]
        by_name = {r["name"]: r for r in rows}
        assert by_name["optimizer/compute"]["group"] == "train"
        assert by_name["serving/batch"]["group"] == "serving"
        assert abs(sum(r["share"] for r in rows) - 1.0) < 1e-6

    def test_diagnose_cli_ingests_jsonl(self, tmp_path, capsys):
        from bigdl_tpu.tools.diagnose import main
        r = MetricsRegistry()
        r.counter("train/optimizer/steps").inc(4)
        path = str(tmp_path / "m.jsonl")
        telemetry.JsonlExporter(r, path).export(step=4)
        phantom = str(tmp_path / "never_written.json")
        assert main(["--jsonl", path, "--out-trace", phantom]) == 0
        out = capsys.readouterr().out
        assert "train/optimizer/steps: 4" in out
        # ingest mode runs no workload: it must not claim a trace file
        # was written (none is)
        assert "chrome trace written" not in out
        assert not os.path.exists(phantom)

    def test_diagnose_cli_usage_errors(self, tmp_path):
        from bigdl_tpu.tools.diagnose import main
        assert main(["--trace", "a", "--jsonl", "b"]) == 2
        assert main(["--trace", str(tmp_path / "missing.json")]) == 2
        assert main(["--jsonl", str(tmp_path / "missing.jsonl")]) == 2


# ------------------------------------------------------- audit CLI wiring

class TestTelemetryAudit:
    def test_shipped_instruments_pass_the_audit(self, capsys):
        from bigdl_tpu.tools.check import main
        assert main(["--telemetry-audit"]) == 0
        out = capsys.readouterr().out
        assert "instrument names match family/component/metric" in out

    def test_audit_json_payload(self, capsys):
        from bigdl_tpu.tools.check import main
        assert main(["--telemetry-audit", "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)["telemetry"]
        assert payload["violations"] == []
        assert "serving/batcher/requests" in payload["instruments"]
        assert "train/optimizer/steps" in payload["instruments"]

    def test_audit_fails_on_bad_name(self, capsys):
        # a bad name in the DEFAULT registry must flip the exit code
        from bigdl_tpu.tools.check import main
        bad = telemetry.registry().counter("NotAValidName")
        try:
            assert main(["--telemetry-audit"]) == 1
            assert "FAIL NotAValidName" in capsys.readouterr().out
        finally:
            # registries have no public delete; scrub the test name so
            # later audits (and the shipped-clean test) stay green
            telemetry.registry()._instruments.pop("NotAValidName")
            del bad


# ----------------------------------------------------- optimizer Metrics

class TestOptimizerMetricsMigration:
    def test_metrics_summary_format_unchanged(self):
        from bigdl_tpu.optim.optimizer import Metrics
        m = Metrics(registry=MetricsRegistry())
        m.add("data time", 0.5)
        m.add("data time", 1.5)
        assert m.values["data time"] == [0.5, 1.5]
        assert m.summary() == "data time: avg 1.0000s over 2"

    def test_metrics_mirror_into_registry_histograms(self):
        from bigdl_tpu.optim.optimizer import Metrics
        r = MetricsRegistry()
        m = Metrics(registry=r)
        m.add("data time", 0.25)
        m.add("computing time", 0.75)
        assert r.histogram("train/optimizer/data_time").sum() == 0.25
        assert r.histogram(
            "train/optimizer/computing_time").sum() == 0.75
