"""Golden checks for the remaining layer families: LRN/normalization
variants, conv/pool stragglers, table elementwise ops, simple linear-family
layers, containers, dropout (reference torch/ suite role, SURVEY.md §4.2).
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bigdl_tpu.nn as nn  # noqa: E402


def _x(shape, seed=0, lo=-2.0, hi=2.0):
    return np.random.RandomState(seed).uniform(
        lo, hi, shape).astype(np.float32)


def _run(m, x, training=False, rng=None):
    m.ensure_initialized()
    out, _ = m.apply(m.get_parameters(), m.get_state(), x,
                     training=training, rng=rng)
    return out


# ----------------------------------------------------------- table ops

def test_table_elementwise_ops():
    a, b = _x((3, 4)), _x((3, 4), 1, lo=0.5, hi=2.0)
    np.testing.assert_allclose(np.asarray(_run(nn.CSubTable(), [a, b])),
                               a - b, atol=1e-6)
    np.testing.assert_allclose(np.asarray(_run(nn.CMulTable(), [a, b])),
                               a * b, atol=1e-6)
    np.testing.assert_allclose(np.asarray(_run(nn.CDivTable(), [a, b])),
                               a / b, atol=1e-5)
    np.testing.assert_allclose(np.asarray(_run(nn.CMaxTable(), [a, b])),
                               np.maximum(a, b), atol=1e-6)
    np.testing.assert_allclose(np.asarray(_run(nn.CMinTable(), [a, b])),
                               np.minimum(a, b), atol=1e-6)


# ------------------------------------------------------- linear family

def test_mul_add_layers():
    x = _x((2, 5))
    m = nn.Mul()
    m.ensure_initialized()
    p = dict(m.get_parameters())
    key = next(iter(p))
    w = float(np.asarray(p[key]).reshape(()))
    np.testing.assert_allclose(
        np.asarray(m.apply(p, m.get_state(), x)[0]), x * w, atol=1e-6)

    m2 = nn.Add(5)
    m2.ensure_initialized()
    p2 = dict(m2.get_parameters())
    key2 = next(iter(p2))
    b = np.asarray(p2[key2]).reshape(5)
    np.testing.assert_allclose(
        np.asarray(m2.apply(p2, m2.get_state(), x)[0]), x + b, atol=1e-6)


def test_cosine_euclidean_layers():
    """Cosine: per-output cosine similarity to weight rows; Euclidean:
    per-output L2 distance (nn/Cosine.scala, nn/Euclidean.scala)."""
    x = _x((3, 4))
    m = nn.Cosine(4, 6)
    m.ensure_initialized()
    p = dict(m.get_parameters())
    w = np.asarray(next(v for v in p.values()
                        if np.asarray(v).ndim == 2))
    out = np.asarray(m.apply(p, m.get_state(), x)[0])
    if w.shape == (6, 4):
        want = (x @ w.T) / (
            np.linalg.norm(x, axis=1, keepdims=True)
            * np.linalg.norm(w, axis=1)[None] + 1e-12)
    else:
        want = (x @ w) / (
            np.linalg.norm(x, axis=1, keepdims=True)
            * np.linalg.norm(w, axis=0)[None] + 1e-12)
    np.testing.assert_allclose(out, want, atol=1e-4)

    m2 = nn.Euclidean(4, 6)
    m2.ensure_initialized()
    p2 = dict(m2.get_parameters())
    w2 = np.asarray(next(v for v in p2.values()
                         if np.asarray(v).ndim == 2))
    out2 = np.asarray(m2.apply(p2, m2.get_state(), x)[0])
    wn = w2 if w2.shape == (6, 4) else w2.T
    want2 = np.stack([np.linalg.norm(x - wn[j][None], axis=1)
                      for j in range(6)], axis=1)
    np.testing.assert_allclose(out2, want2, atol=1e-4)


# ------------------------------------------------------------- norms

def test_spatial_within_channel_lrn():
    """y = x / (1 + alpha/n * window_mean_of_squares)^beta within each
    channel (SpatialWithinChannelLRN.scala)."""
    x = _x((1, 2, 5, 5), lo=0.1, hi=1.0)
    size, alpha, beta = 3, 1.0, 0.75
    out = np.asarray(_run(nn.SpatialWithinChannelLRN(size, alpha, beta), x))
    # direct reference computation: same-padded window sum of squares / n^2
    import scipy.signal as sig
    k = np.ones((size, size), np.float32)
    den = np.empty_like(x)
    for c in range(x.shape[1]):
        s = sig.convolve2d(x[0, c] ** 2, k, mode="same")
        den[0, c] = (1.0 + alpha / (size * size) * s) ** beta
    np.testing.assert_allclose(out, x / den, atol=1e-4)


def test_spatial_subtractive_and_divisive_normalization():
    x = _x((1, 1, 6, 6), lo=0.0, hi=1.0)
    import scipy.signal as sig
    k = np.ones((3, 3), np.float32) / 9.0
    # subtractive: x - local mean (same-padded, edge-corrected)
    out_s = np.asarray(_run(
        nn.SpatialSubtractiveNormalization(1, np.ones((3, 3))), x))
    assert out_s.shape == x.shape
    # the center region (away from borders) matches plain convolution
    mean = sig.convolve2d(x[0, 0], k, mode="same")
    np.testing.assert_allclose(out_s[0, 0, 2:-2, 2:-2],
                               (x[0, 0] - mean)[2:-2, 2:-2], atol=1e-3)
    out_d = np.asarray(_run(
        nn.SpatialDivisiveNormalization(1, np.ones((3, 3))), x))
    assert out_d.shape == x.shape
    out_c = np.asarray(_run(
        nn.SpatialContrastiveNormalization(1, np.ones((3, 3))), x))
    assert out_c.shape == x.shape


def test_normalize_layer():
    x = _x((3, 5))
    out = np.asarray(_run(nn.Normalize(2.0), x))
    want = F.normalize(torch.tensor(x), p=2.0, dim=1)
    np.testing.assert_allclose(out, want.numpy(), atol=1e-5)


def test_layer_norm_rms_norm_vs_torch():
    x = _x((4, 8))
    m = nn.LayerNorm(8)
    m.ensure_initialized()
    p = dict(m.get_parameters())
    out = np.asarray(m.apply(p, m.get_state(), x)[0])
    leaves = {k: np.asarray(v) for k, v in p.items()}
    wkey = [k for k in leaves if leaves[k].ndim == 1][0]
    want = F.layer_norm(torch.tensor(x), (8,))
    # fresh init: weight=1, bias=0 -> matches plain layer_norm
    np.testing.assert_allclose(out, want.numpy(), atol=1e-4)

    m2 = nn.RMSNorm(8)
    m2.ensure_initialized()
    out2 = np.asarray(m2.apply(m2.get_parameters(), m2.get_state(), x)[0])
    want2 = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out2, want2, atol=1e-4)


# --------------------------------------------------------- conv family

def test_spatial_share_convolution_equals_conv():
    x = _x((2, 3, 6, 6))
    m = nn.SpatialShareConvolution(3, 4, 3, 3, 1, 1, 1, 1)
    m.ensure_initialized()
    p = dict(m.get_parameters())
    out = np.asarray(m.apply(p, m.get_state(), x)[0])
    w = np.asarray(p["weight"])
    b = np.asarray(p.get("bias", np.zeros(4)))
    want = F.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                    padding=1)
    np.testing.assert_allclose(out, want.numpy(), atol=1e-4)


def test_volumetric_full_convolution_vs_torch():
    x = _x((1, 2, 3, 4, 4))
    m = nn.VolumetricFullConvolution(2, 3, 3, 3, 3, 2, 2, 2, 1, 1, 1)
    m.ensure_initialized()
    p = dict(m.get_parameters())
    out = np.asarray(m.apply(p, m.get_state(), x)[0])
    w = np.asarray(p["weight"])  # (in, out, kt, kh, kw)
    b = np.asarray(p.get("bias", np.zeros(3)))
    want = F.conv_transpose3d(torch.tensor(x), torch.tensor(w),
                              torch.tensor(b), stride=2, padding=1)
    np.testing.assert_allclose(out, want.numpy(), atol=1e-3)


def test_temporal_max_pooling_vs_torch():
    x = _x((2, 8, 3))  # (B, T, F)
    out = np.asarray(_run(nn.TemporalMaxPooling(2, 2), x))
    want = F.max_pool1d(torch.tensor(x).transpose(1, 2), 2, 2) \
        .transpose(1, 2)
    np.testing.assert_allclose(out, want.numpy(), atol=1e-6)


def test_roi_pooling_hand_case():
    """One 4x4 feature map, one ROI covering it, pooled 2x2
    (nn/RoiPooling.scala: rois are (batch_idx, x1, y1, x2, y2))."""
    fm = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.asarray([[0, 0, 0, 3, 3]], np.float32)
    out = np.asarray(_run(nn.RoiPooling(2, 2, 1.0), [fm, rois]))
    assert out.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_spatial_convolution_map_full_table_equals_conv():
    """A full connection table must reproduce a dense conv
    (SpatialConvolutionMap.scala fullConnTable)."""
    # full table: every input plane -> every output plane
    table = np.asarray([[i + 1, o + 1] for o in range(2)
                        for i in range(2)], np.float32)
    m = nn.SpatialConvolutionMap(table, 3, 3)
    m.ensure_initialized()
    x = _x((1, 2, 5, 5))
    out = np.asarray(m.apply(m.get_parameters(), m.get_state(), x)[0])
    assert out.shape[1] == 2  # two output planes, valid conv
    assert out.shape[2] == 3 and out.shape[3] == 3


# ----------------------------------------------------------- containers

def test_concat_table_parallel_table_map_table():
    x = _x((2, 4))
    ct = nn.ConcatTable().add(nn.MulConstant(2.0)).add(nn.AddConstant(1.0))
    outs = list(_run(ct, x))
    np.testing.assert_allclose(np.asarray(outs[0]), x * 2, atol=1e-6)
    np.testing.assert_allclose(np.asarray(outs[1]), x + 1, atol=1e-6)

    pt = nn.ParallelTable().add(nn.MulConstant(3.0)).add(nn.AddConstant(2.0))
    a, b = _x((2, 3)), _x((2, 3), 1)
    outs2 = list(_run(pt, [a, b]))
    np.testing.assert_allclose(np.asarray(outs2[0]), a * 3, atol=1e-6)
    np.testing.assert_allclose(np.asarray(outs2[1]), b + 2, atol=1e-6)

    mt = nn.MapTable(nn.MulConstant(5.0))
    outs3 = list(_run(mt, [a, b]))
    np.testing.assert_allclose(np.asarray(outs3[0]), a * 5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(outs3[1]), b * 5, atol=1e-6)


def test_bottle_and_mixture_table():
    """Bottle: flatten leading dims, apply, restore (nn/Bottle.scala)."""
    x = _x((2, 3, 4))
    m = nn.Bottle(nn.Linear(4, 5), 2, 2)
    m.ensure_initialized()
    out = np.asarray(m.apply(m.get_parameters(), m.get_state(), x)[0])
    assert out.shape == (2, 3, 5)
    # same result as applying the inner Linear to the flattened input
    inner = m.modules[0] if hasattr(m, "modules") else None
    # MixtureTable: gater weights alpha over expert outputs
    alpha = np.asarray([[0.3, 0.7], [0.6, 0.4]], np.float32)
    e1, e2 = _x((2, 4), 5), _x((2, 4), 6)
    experts = [e1, e2]
    mt = nn.MixtureTable()
    got = np.asarray(_run(mt, [alpha, experts]))
    want = alpha[:, :1] * e1 + alpha[:, 1:2] * e2
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_dropout_train_and_eval():
    x = np.ones((64, 64), np.float32)
    m = nn.Dropout(0.5)
    m.ensure_initialized()
    out = np.asarray(m.apply(m.get_parameters(), m.get_state(), x,
                             training=True, rng=jax.random.PRNGKey(0))[0])
    kept = out != 0
    assert 0.3 < kept.mean() < 0.7          # ~half kept
    np.testing.assert_allclose(out[kept], 2.0, atol=1e-6)  # inverted scale
    out_eval = np.asarray(_run(nn.Dropout(0.5), x, training=False))
    np.testing.assert_allclose(out_eval, x)  # identity at eval


def test_l1_penalty_forward_identity():
    x = _x((3, 4))
    m = nn.L1Penalty(0.1)
    np.testing.assert_allclose(np.asarray(_run(m, x)), x, atol=1e-6)
