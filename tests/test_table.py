"""Table pytree semantics (reference: utils/TableSpec)."""
import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.utils.table import Table, T


def test_t_constructor_positional():
    t = T(1, 2, 3)
    assert t[1] == 1 and t[3] == 3
    assert t.length() == 3
    assert list(t) == [1, 2, 3]


def test_insert_remove():
    t = T("a", "b")
    t.insert("c")
    assert t.length() == 3
    assert t.remove(2) == "b"
    assert t.to_list() == ["a", "c"]


def test_table_is_pytree():
    t = T(jnp.ones((2,)), jnp.zeros((3,)))
    leaves = jax.tree.leaves(t)
    assert len(leaves) == 2
    doubled = jax.tree.map(lambda x: x * 2, t)
    assert isinstance(doubled, Table)
    np.testing.assert_allclose(doubled[1], 2 * np.ones((2,)))


def test_table_through_jit():
    @jax.jit
    def f(t):
        return t[1] + t[2]

    out = f(T(jnp.ones((4,)), 2 * jnp.ones((4,))))
    np.testing.assert_allclose(out, 3 * np.ones((4,)))


def test_string_keys():
    t = T(1, 2, foo="bar")
    assert t["foo"] == "bar"
    assert t.length() == 2
