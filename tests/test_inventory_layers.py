"""Tests for the final inventory layers: SpatialConvolutionMap, Nms,
BinaryTreeLSTM (reference: nn/SpatialConvolutionMap.scala, nn/Nms.scala,
nn/BinaryTreeLSTM.scala) + the complete SURVEY §2.2 inventory check."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn


def test_full_inventory_present():
    names = """Sequential Container Graph Input Concat ConcatTable
    ParallelTable MapTable NarrowTable Bottle MixtureTable Linear
    SparseLinear Bilinear CMul CAdd Mul Add MulConstant AddConstant MM MV
    Cosine Euclidean DotProduct PairwiseDistance CosineDistance
    SpatialConvolution SpatialShareConvolution SpatialDilatedConvolution
    SpatialFullConvolution SpatialConvolutionMap TemporalConvolution
    VolumetricConvolution VolumetricFullConvolution LookupTable
    SpatialMaxPooling SpatialAveragePooling TemporalMaxPooling
    VolumetricMaxPooling RoiPooling BatchNormalization
    SpatialBatchNormalization SpatialCrossMapLRN SpatialWithinChannelLRN
    SpatialContrastiveNormalization SpatialDivisiveNormalization
    SpatialSubtractiveNormalization Normalize ReLU ReLU6 PReLU RReLU
    LeakyReLU ELU Tanh TanhShrink Sigmoid LogSigmoid SoftMax SoftMin
    LogSoftMax SoftPlus SoftSign SoftShrink HardShrink HardTanh Threshold
    BinaryThreshold Clamp Power Square Sqrt Log Exp Abs Negative
    GradientReversal GaussianSampler Reshape InferReshape View Squeeze
    Unsqueeze Transpose Contiguous Replicate Padding SpatialZeroPadding
    Narrow Select SelectTable MaskedSelect Index Max Min Mean Sum Scale
    Tile Pack Reverse SplitTable BifurcateSplitTable JoinTable
    SparseJoinTable FlattenTable DenseToSparse ResizeBilinear Nms
    CAddTable CSubTable CMulTable CDivTable CMaxTable CMinTable Dropout
    L1Penalty Recurrent RecurrentDecoder RnnCell LSTM LSTMPeephole GRU
    ConvLSTMPeephole ConvLSTMPeephole3D BiRecurrent TimeDistributed
    TreeLSTM BinaryTreeLSTM ClassNLLCriterion CrossEntropyCriterion
    BCECriterion MSECriterion AbsCriterion SmoothL1Criterion
    MarginCriterion MarginRankingCriterion MultiMarginCriterion
    MultiLabelMarginCriterion MultiLabelSoftMarginCriterion
    HingeEmbeddingCriterion L1HingeEmbeddingCriterion
    CosineEmbeddingCriterion CosineDistanceCriterion DistKLDivCriterion
    KLDCriterion GaussianCriterion ClassSimplexCriterion
    DiceCoefficientCriterion SoftmaxWithCriterion SoftMarginCriterion
    L1Cost ParallelCriterion MultiCriterion TimeDistributedCriterion
    MultiHeadAttention MoE LayerNorm RMSNorm QuantizedLinear
    QuantizedSpatialConvolution SequenceCrossEntropyCriterion"""
    missing = [n_ for n_ in names.split() if not hasattr(nn, n_)]
    assert missing == [], f"missing layers: {missing}"


def test_spatial_convolution_map():
    # LeNet-style partial connectivity: plane 1 -> out 1,2; plane 2 -> out 2
    table = [[1, 1], [1, 2], [2, 2]]
    m = nn.SpatialConvolutionMap(table, 3, 3, 1, 1, 1, 1)
    x = np.random.randn(2, 2, 6, 6).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (2, 2, 6, 6)
    # output 1 must NOT depend on input plane 2
    x2 = x.copy()
    x2[:, 1] += 10.0
    out2 = np.asarray(m.forward(x2))
    np.testing.assert_allclose(out[:, 0], out2[:, 0], atol=1e-5)
    assert np.abs(out[:, 1] - out2[:, 1]).max() > 0.1


def test_nms():
    boxes = np.array([[0, 0, 10, 10],
                      [1, 1, 11, 11],     # heavy overlap with 0
                      [20, 20, 30, 30],   # separate
                      [21, 21, 29, 29]],  # overlaps 2
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7, 0.95], np.float32)
    m = nn.Nms(iou_threshold=0.5, max_output=4)
    kept = np.asarray(m.forward([boxes, scores]))
    kept = kept[kept >= 0]
    # order by score: 3, 0, 1(suppressed by 0), 2(suppressed by 3)
    assert list(kept) == [3, 0]


def test_binary_tree_lstm():
    # tree: leaves 0,1 -> node 2; leaves 3 -> just a leaf; root 4 = (2, 3)
    emb = np.random.randn(5, 8).astype(np.float32)
    children = np.array([[-1, -1], [-1, -1], [0, 1], [-1, -1], [2, 3]],
                        np.int32)
    m = nn.BinaryTreeLSTM(8, 16)
    hs = np.asarray(m.forward([emb, children]))
    assert hs.shape == (5, 16)
    assert np.isfinite(hs).all()
    # root depends on leaf 0's embedding
    emb2 = emb.copy()
    emb2[0] += 1.0
    hs2 = np.asarray(m.forward([emb2, children]))
    assert np.abs(hs2[4] - hs[4]).max() > 1e-5
    # ...but node 3 (a leaf) does not
    np.testing.assert_allclose(hs[3], hs2[3], atol=1e-6)


def test_binary_tree_lstm_gradients():
    import jax
    emb = np.random.randn(3, 4).astype(np.float32)
    children = np.array([[-1, -1], [-1, -1], [0, 1]], np.int32)
    m = nn.BinaryTreeLSTM(4, 6)
    m.ensure_initialized()
    p = m.get_parameters()

    def loss(p):
        from bigdl_tpu.utils.table import T
        hs = m.forward_fn(p, T(np.asarray(emb), np.asarray(children)))
        return hs[-1].sum()

    g = jax.grad(loss)(p)
    assert float(np.abs(np.asarray(g["w_comp"])).max()) > 0
    assert float(np.abs(np.asarray(g["w_leaf"])).max()) > 0
