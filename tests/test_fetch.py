"""Dataset fetch/prep helpers against local fixtures (reference:
pyspark/bigdl/dataset/{mnist,news20,movielens}.py — download is
maybe_download-gated, parsers are pure and tested offline)."""
import gzip
import os
import struct

import numpy as np

from bigdl_tpu.dataset.fetch import (extract_mnist_images,
                                     extract_mnist_labels, maybe_download,
                                     mnist_read_data_sets,
                                     parse_glove_txt,
                                     parse_movielens_ratings,
                                     parse_news20_tree)


def _write_idx(tmp_path, rng):
    imgs = rng.randint(0, 255, (5, 28, 28), dtype=np.uint8)
    lbls = rng.randint(0, 10, 5, dtype=np.uint8)
    ip = tmp_path / "train-images-idx3-ubyte.gz"
    lp = tmp_path / "train-labels-idx1-ubyte.gz"
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 5, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 5))
        f.write(lbls.tobytes())
    return imgs, lbls, ip, lp


def test_mnist_idx_gzip_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    imgs, lbls, ip, lp = _write_idx(tmp_path, rng)
    np.testing.assert_array_equal(extract_mnist_images(str(ip)), imgs)
    np.testing.assert_array_equal(extract_mnist_labels(str(lp)), lbls)
    # read_data_sets finds the pre-seeded files without any network
    gi, gl = mnist_read_data_sets(str(tmp_path), "train")
    np.testing.assert_array_equal(gi, imgs)
    np.testing.assert_array_equal(gl, lbls)


def test_maybe_download_skips_existing(tmp_path):
    p = tmp_path / "cached.bin"
    p.write_bytes(b"seeded")
    # an invalid URL proves no network attempt happens for cached files
    got = maybe_download("cached.bin", str(tmp_path),
                         "http://invalid.invalid/cached.bin")
    assert got == str(p) and p.read_bytes() == b"seeded"


def test_news20_tree_parse(tmp_path):
    for ci, cat in enumerate(("alt.atheism", "sci.space")):
        d = tmp_path / cat
        d.mkdir()
        for j in range(2):
            (d / f"{j}").write_text(f"doc {cat} {j}")
    texts = parse_news20_tree(str(tmp_path))
    assert len(texts) == 4
    labels = sorted({lbl for _, lbl in texts})
    assert labels == [1, 2]  # 1-based, sorted category order
    assert any("sci.space" in t for t, lbl in texts if lbl == 2)


def test_glove_txt_parse(tmp_path):
    p = tmp_path / "glove.6B.50d.txt"
    p.write_text("the 0.1 0.2 0.3\ncat -1.0 2.0 3.5\n")
    w2v = parse_glove_txt(str(p))
    assert w2v["cat"] == [-1.0, 2.0, 3.5]
    assert len(w2v) == 2


def test_movielens_ratings_parse(tmp_path):
    p = tmp_path / "ratings.dat"
    p.write_text("1::1193::5::978300760\n2::661::3::978302109\n")
    arr = parse_movielens_ratings(str(p))
    assert arr.shape == (2, 4)
    assert arr[0].tolist() == [1, 1193, 5, 978300760]


def test_atomic_extract_failure_leaves_nothing(tmp_path):
    """An interrupted extraction must not pass the exists-skip guard
    (a half-populated corpus would silently train truncated)."""
    from bigdl_tpu.dataset.fetch import _atomic_extract

    final = tmp_path / "corpus"

    def boom(dst):
        os.makedirs(os.path.join(dst, "partial"))
        raise RuntimeError("disk full")

    try:
        _atomic_extract(str(final), boom)
    except RuntimeError:
        pass
    assert not final.exists()
    assert not any(p.name.startswith(".extract-")
                   for p in tmp_path.iterdir())

    def ok(dst):
        d = os.path.join(dst, "root")
        os.makedirs(d)
        with open(os.path.join(d, "f.txt"), "w") as f:
            f.write("x")

    _atomic_extract(str(final), ok)
    assert (final / "f.txt").read_text() == "x"


def test_news20_skips_non_article_files(tmp_path):
    from bigdl_tpu.dataset.fetch import parse_news20_tree

    d = tmp_path / "sci.space"
    d.mkdir()
    (d / "12345").write_text("real article")
    (d / ".DS_Store").write_text("junk")
    (d / "backup~").write_text("junk")
    texts = parse_news20_tree(str(tmp_path))
    assert texts == [("real article", 1)]


def test_lenet_cli_automaterializes_mnist(tmp_path, monkeypatch):
    """The zoo CLI runs from NOTHING (reference:
    pyspark/bigdl/models/lenet/lenet5.py:24-30): with -f pointing at an
    empty dir, mnist_arrays auto-downloads via fetch (file:// mirror
    stands in for the network) and the recipe proceeds."""
    import bigdl_tpu.dataset.fetch as fetch
    from bigdl_tpu.models._cli import mnist_arrays

    rng = np.random.RandomState(3)
    src = tmp_path / "mirror"
    src.mkdir()
    _write_idx(src, rng)
    # the mirror serves train-* under both prefixes
    for p in ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"):
        (src / p).write_bytes(
            (src / p.replace("t10k", "train")).read_bytes())
    monkeypatch.setattr(fetch, "MNIST_URL",
                        "file://" + str(src) + "/")
    dst = tmp_path / "data"
    xs, ys = mnist_arrays(str(dst), True)
    assert xs.shape == (5, 1, 28, 28) and xs.dtype == np.float32
    assert ys.min() >= 1 and ys.max() <= 10  # 1-based labels
    # second call reads the now-cached files, no URL involved
    monkeypatch.setattr(fetch, "MNIST_URL", "http://invalid.invalid/")
    xs2, _ = mnist_arrays(str(dst), True)
    np.testing.assert_array_equal(xs, xs2)


def test_rnn_cli_automaterializes_corpus(tmp_path, monkeypatch):
    """models/rnn (and transformer) auto-fetch their text corpus when
    -f has no train.txt; offline failure exits with a clear message."""
    import pytest

    import bigdl_tpu.dataset.fetch as fetch

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over the lazy dog . " * 30)
    monkeypatch.setattr(fetch, "SHAKESPEARE_URL",
                        "file://" + str(corpus))
    got = fetch.get_text_corpus(str(tmp_path / "data"))
    assert os.path.exists(got) and got.endswith("train.txt")

    # offline: the CLI must exit with guidance, not a stack trace
    from bigdl_tpu.models.rnn import train as rnn_train
    monkeypatch.setattr(fetch, "SHAKESPEARE_URL",
                        "file:///nonexistent/nowhere.txt")
    with pytest.raises(SystemExit, match="auto-download"):
        rnn_train.main(["-f", str(tmp_path / "empty"), "-e", "1"])


def _tiny_news20_tgz(path):
    """A minimal 20news-19997-shaped tarball: one root dir with one
    category holding one numeric-named article."""
    import io
    import tarfile

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as t:
        data = b"From: a@b\n\nhello serving"
        info = tarfile.TarInfo("20_newsgroups/alt.atheism/49960")
        info.size = len(data)
        t.addfile(info, io.BytesIO(data))
    with open(path, "wb") as f:
        f.write(buf.getvalue())


def test_news20_sha256_pin_is_live_at_call_site(tmp_path, monkeypatch):
    """ADVICE r5: get_news20 must PASS a digest pin into maybe_download
    (trust-on-first-use sidecar / env pin) so a re-download that doesn't
    match the recorded tarball fails loudly instead of landing."""
    import urllib.request

    import pytest

    import bigdl_tpu.dataset.fetch as fetch

    monkeypatch.delenv(fetch.NEWS20_SHA256_ENV, raising=False)
    good = tmp_path / "good.tar.gz"
    _tiny_news20_tgz(str(good))
    payload = {"bytes": good.read_bytes()}

    def fake_retrieve(url, dst):
        with open(dst, "wb") as f:
            f.write(payload["bytes"])

    monkeypatch.setattr(urllib.request, "urlretrieve", fake_retrieve)
    src = tmp_path / "news20"
    texts = fetch.get_news20(str(src) + os.sep)
    assert texts == [("From: a@b\n\nhello serving", 1)]
    tar = src / "20news-19997.tar.gz"
    sidecar = src / "20news-19997.tar.gz.sha256"
    assert sidecar.exists()  # first fetch recorded the pin
    recorded = sidecar.read_text().strip()

    # cache evicted + upstream swapped: the re-download must be refused
    # by the recorded pin, and nothing may land under the cache name
    tar.unlink()
    payload["bytes"] = b"not the archive that was pinned"
    with pytest.raises(IOError, match="sha256 mismatch"):
        fetch.get_news20(str(src) + os.sep)
    assert not tar.exists()

    # identical bytes re-download passes the same pin
    payload["bytes"] = good.read_bytes()
    assert fetch.get_news20(str(src) + os.sep) == texts
    assert sidecar.read_text().strip() == recorded

    # explicit env pin wins over the sidecar; "" disables checking
    tar.unlink()
    payload["bytes"] = b"rolled tarball, operator-approved"
    monkeypatch.setenv(fetch.NEWS20_SHA256_ENV, recorded)
    with pytest.raises(IOError, match="sha256 mismatch"):
        fetch.get_news20(str(src) + os.sep)


def test_maybe_download_sha256_verifies_before_landing(tmp_path,
                                                       monkeypatch):
    import hashlib
    import urllib.request

    import pytest

    import bigdl_tpu.dataset.fetch as fetch

    def fake_retrieve(url, dst):
        with open(dst, "wb") as f:
            f.write(b"payload")

    monkeypatch.setattr(urllib.request, "urlretrieve", fake_retrieve)
    want = hashlib.sha256(b"payload").hexdigest()
    got = fetch.maybe_download("a.bin", str(tmp_path), "http://x/a.bin",
                               sha256=want)
    assert open(got, "rb").read() == b"payload"
    with pytest.raises(IOError, match="sha256 mismatch"):
        fetch.maybe_download("b.bin", str(tmp_path), "http://x/b.bin",
                             sha256="0" * 64)
    assert not os.path.exists(os.path.join(str(tmp_path), "b.bin"))
