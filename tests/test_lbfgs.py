"""LBFGS + LineSearch and TreeNNAccuracy (reference: optim/LBFGS.scala:48,
optim/LineSearch.scala, optim/ValidationMethod.scala:118)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.optim import LBFGS, TreeNNAccuracy


def test_lbfgs_quadratic_converges():
    """f(x) = (x-c)'A(x-c): LBFGS must reach the exact minimum."""
    A = jnp.asarray(np.diag([1.0, 10.0, 100.0]), jnp.float32)
    c = jnp.asarray([1.0, -2.0, 3.0], jnp.float32)

    def f(x):
        d = x - c
        return d @ A @ d

    feval = jax.jit(jax.value_and_grad(f))
    opt = LBFGS(max_iter=50, max_eval=200)
    x0 = jnp.zeros(3, jnp.float32)
    x_star, f_hist = opt.optimize(feval, x0)
    assert f_hist[0] == pytest.approx(float(f(x0)), rel=1e-5)
    assert f_hist[-1] < f_hist[0]
    np.testing.assert_allclose(np.asarray(x_star), np.asarray(c), atol=1e-3)


def test_lbfgs_rosenbrock_converges():
    def rosen(x):
        return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                       + (1 - x[:-1]) ** 2)

    feval = jax.jit(jax.value_and_grad(rosen))
    opt = LBFGS(max_iter=200, max_eval=1000, tol_fun=1e-10, tol_x=1e-12)
    x0 = jnp.asarray([-1.2, 1.0, -1.2, 1.0], jnp.float32)
    x_star, f_hist = opt.optimize(feval, x0)
    assert f_hist[-1] < 1e-4
    np.testing.assert_allclose(np.asarray(x_star), 1.0, atol=2e-2)


def test_lbfgs_no_line_search_fixed_step():
    def f(x):
        return jnp.sum(x ** 2)

    feval = jax.jit(jax.value_and_grad(f))
    opt = LBFGS(max_iter=30, learning_rate=0.3, line_search=None)
    x_star, f_hist = opt.optimize(feval, jnp.ones(4, jnp.float32) * 3)
    assert f_hist[-1] < 1e-4


def test_lbfgs_pytree_params():
    """Pytree parameters (a tiny linear regression) are supported."""
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(32, 5), jnp.float32)
    w_true = jnp.asarray(rng.randn(5), jnp.float32)
    y = X @ w_true + 0.7

    def loss(p):
        pred = X @ p["w"] + p["b"]
        return jnp.mean((pred - y) ** 2)

    feval = jax.jit(jax.value_and_grad(loss))
    opt = LBFGS(max_iter=100, max_eval=500, tol_fun=1e-12)
    p0 = {"w": jnp.zeros(5, jnp.float32), "b": jnp.zeros((), jnp.float32)}
    p_star, f_hist = opt.optimize(feval, p0)
    assert f_hist[-1] < 1e-6
    np.testing.assert_allclose(np.asarray(p_star["w"]),
                               np.asarray(w_true), atol=1e-2)
    assert float(p_star["b"]) == pytest.approx(0.7, abs=1e-2)


def test_tree_nn_accuracy_hand_computed():
    """3-d case: only the root node (index 0 along dim 1) is scored."""
    # batch=3, nodes=2, classes=3
    out = np.zeros((3, 2, 3), np.float32)
    out[0, 0] = [0.9, 0.05, 0.05]   # root pred class 1
    out[1, 0] = [0.1, 0.8, 0.1]     # root pred class 2
    out[2, 0] = [0.2, 0.2, 0.6]     # root pred class 3
    out[:, 1] = [0, 0, 1]           # non-root nodes must be ignored
    target = np.asarray([[1, 9], [2, 9], [1, 9]], np.float32)
    r = TreeNNAccuracy()(out, target)
    value, count = r.result()
    assert count == 3
    assert value == pytest.approx(2 / 3)


def test_tree_nn_accuracy_binary_and_2d():
    # binary (classes == 1): threshold at 0.5 -> labels 0/1
    out = np.asarray([[[0.8], [0.0]], [[0.3], [0.0]]], np.float32)
    target = np.asarray([[1, 9], [0, 9]], np.float32)
    value, count = TreeNNAccuracy()(out, target).result()
    assert count == 2 and value == 1.0
    # 2-d single sample: first row is the root
    out2 = np.asarray([[0.1, 0.9], [0.9, 0.1]], np.float32)
    value2, count2 = TreeNNAccuracy()(out2, np.asarray([[2.0]])).result()
    assert count2 == 1 and value2 == 1.0


def test_lbfgs_reentry_matches_single_run():
    """Persisted-state re-entry: two optimize() calls of N iterations must
    follow the SAME trajectory as one call of 2N — requires the last
    line-search step length to be persisted (state["stepLen"]), since the
    first curvature pair on re-entry is s = d * t."""
    A = jnp.asarray(np.diag([1.0, 25.0, 400.0]), jnp.float32)
    c = jnp.asarray([0.5, -1.5, 2.0], jnp.float32)

    def f(x):
        d = x - c
        return d @ A @ d

    feval = jax.jit(jax.value_and_grad(f))
    x0 = jnp.asarray([4.0, 4.0, 4.0], jnp.float32)

    whole = LBFGS(max_iter=8, max_eval=400)
    x_whole, _ = whole.optimize(feval, x0)

    split = LBFGS(max_iter=4, max_eval=400)
    x_mid, _ = split.optimize(feval, x0)
    assert "stepLen" in split.state
    x_split, _ = split.optimize(feval, x_mid)

    np.testing.assert_allclose(np.asarray(x_split), np.asarray(x_whole),
                               atol=1e-5)
