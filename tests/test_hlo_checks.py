"""Static HLO verifier (analysis/hlo.py + analysis/checks/): parser
goldens (incl. the tuple-typed async -start collectives real TPU
schedules emit), each check's clean + seeded-mutant fixture, the
zero.py back-compat shims, and the zero-execution contract — program
verification lowers and compiles, never runs."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.analysis.hlo import (ProgramSpec, available_checks,
                                    collective_counts, format_findings,
                                    hbm_fit, parse_hlo,
                                    reduce_scatter_evidence, run_checks)
from bigdl_tpu.analysis import programs as progs
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim import SGD
from bigdl_tpu.optim.optimizer import build_train_step
from bigdl_tpu.utils.random import RandomGenerator


@pytest.fixture(scope="module")
def devices8():
    d = jax.devices()
    if len(d) < 8:
        pytest.skip("needs 8 virtual devices")
    return d[:8]


GOLDEN = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }, entry_computation_layout={(f32[16]{0})->f32[16]{0}}
%body (p: f32[16]) -> f32[16] {
  %ag = f32[16]{0} all-gather(%p), replica_groups={}
  %ar = f32[2]{0} all-reduce(%p), to_apply=%sum
  ROOT %ds = f32[2]{0} dynamic-slice(%ar, %i), dynamic_slice_sizes={2}
}
ENTRY %main (x: f32[16]) -> f32[16] {
  %g = f32[16]{0} all-gather(%x), replica_groups={}
  %p0 = f32[16]{0} parameter(0), sharding={replicated}
  ROOT %w = f32[16]{0} while(%x), body=%body, condition=%cond
}
"""

ASYNC = """\
HloModule jit_async, buffer_donor={ (1, {}), (3, {}) }
ENTRY %main (x: f32[2,4]) -> f32[16,4] {
  %ags = (f32[2,4]{1,0}, f32[16,4]{1,0}) all-gather-start(%x), dimensions={0}
  %agd = f32[16,4]{1,0} all-gather-done(%ags)
  %rss = ((f32[16]{0}), f32[2]{0}) reduce-scatter-start(%y), dimensions={0}
  ROOT %rsd = f32[2]{0} reduce-scatter-done(%rss)
}
"""


# ------------------------------------------------------------------ parser

def test_parser_structure_and_links():
    m = parse_hlo(GOLDEN)
    assert set(m.computations) == {"body", "main"}
    assert m.entry is m.computations["main"] and m.entry.is_entry
    assert not m.computations["body"].is_entry
    w = m.entry.op("w")
    assert w.is_root and w.opcode == "while"
    assert w.called == {"body": "body", "condition": "cond"}
    ag = m.computations["body"].op("ag")
    assert ag.opcode == "all-gather" and ag.operands == ["p"]
    assert ag.dtype == "f32" and ag.dims == (16,)
    assert ag.result_bytes() == 64
    p0 = m.entry.op("p0")
    assert p0.parameter_index == 0 and p0.sharding == "replicated" \
        and p0.replicated


def test_parser_alias_and_donor_tables():
    m = parse_hlo(GOLDEN)
    assert m.aliased_params == {0, 2}
    a = parse_hlo(ASYNC)
    assert a.donor_params == {1, 3}
    assert a.donated_params == {1, 3}


def test_parser_async_tuple_start_ops():
    m = parse_hlo(ASYNC)
    ags = m.entry.op("ags")
    assert ags.opcode == "all-gather-start"
    # both leaves of the tuple type parsed
    assert ags.shapes == (("f32", (2, 4)), ("f32", (16, 4)))
    counts = collective_counts(m)
    assert counts["all-gather"] == {"total": 1, "entry": 1}
    assert counts["reduce-scatter"] == {"total": 1, "entry": 1}


def test_collective_counts_and_zero_shim_agree():
    """The parallel.zero spellings are deprecated shims over the ONE
    structural parser — byte-identical results on the goldens."""
    from bigdl_tpu.parallel import zero
    for text in (GOLDEN, ASYNC):
        assert zero.collective_counts(text) == collective_counts(text)
    counts = collective_counts(GOLDEN)
    assert counts["all-gather"] == {"total": 2, "entry": 1}
    assert counts["all-reduce"] == {"total": 1, "entry": 0}
    assert reduce_scatter_evidence(counts)
    assert zero.reduce_scatter_evidence(counts)


def test_parser_lowered_bare_operands_def_use():
    """Lowered (pre-optimization) HLO writes operands without types —
    def-use edges must still resolve dtypes (the precision check's
    foundation)."""
    text = """\
HloModule jit_f
ENTRY main.4 {
  Arg_0.1 = bf16[4,8]{1,0} parameter(0)
  convert.2 = f32[4,8]{1,0} convert(Arg_0.1)
  multiply.3 = f32[4,8]{1,0} multiply(convert.2, convert.2)
  ROOT dot.4 = f32[4,4]{1,0} dot(multiply.3, convert.2), lhs_contracting_dims={1}, rhs_contracting_dims={1}
}
"""
    m = parse_hlo(text)
    dot = m.entry.op("dot.4")
    assert dot.operands == ["multiply.3", "convert.2"]
    assert m.entry.operand_dtypes(dot) == ["f32", "f32"]
    assert m.entry.operand_op(dot, 0).opcode == "multiply"


# --------------------------------------------------- donation fixtures

def _mlp():
    RandomGenerator.set_seed(7)
    m = nn.Sequential().add(nn.Linear(16, 32)).add(nn.Tanh()) \
        .add(nn.Linear(32, 4)).add(nn.LogSoftMax())
    m.training().ensure_initialized()
    return m


@pytest.fixture(scope="module")
def donation_specs():
    """The same train step lowered WITH donation (clean) and WITHOUT
    (the seeded mutant: declared donation that the compiled program
    cannot honor)."""
    model = _mlp()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params, opt_state, mstate = progs._train_abstract(model, optim)
    step = build_train_step(model, nn.ClassNLLCriterion(), optim)
    args = (params, opt_state, mstate, progs._key_struct(),
            progs._sds((), np.float32), progs._sds((8, 16), np.float32),
            progs._sds((8,), np.float32))
    clean = progs.spec_from_lowered("fixture/donated", step.lower(*args))

    def undonated(p, o, m, key, lr, x, y):  # the mutant: no donation
        return step(p, o, m, key, lr, x, y)

    mutant = progs.spec_from_lowered(
        "fixture/undonated", jax.jit(undonated).lower(*args),
        donated=clean.donated)  # contract says leaves SHOULD donate
    return clean, mutant


def test_donation_dropped_clean(donation_specs):
    clean, _ = donation_specs
    assert clean.donated > 0
    assert not run_checks([clean], checks=["donation-dropped"])


def test_donation_dropped_mutant(donation_specs):
    _, mutant = donation_specs
    findings = run_checks([mutant], checks=["donation-dropped"])
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "donation-dropped" and f.severity == "error"
    assert f"{mutant.donated} leaves declared donated but only 0" \
        in f.message


# ------------------------------------------------- windowed collectives

@pytest.fixture(scope="module")
def window_mutants(devices8):
    """An ENTRY-gather window (clean twin keeps the gather inside the
    scan) and an UNROLLED window pair (K=2, K=8) whose collective count
    scales with K."""
    from bigdl_tpu.parallel import make_mesh
    mesh = make_mesh([8], ["data"], devices8)
    repl = NamedSharding(mesh, P())
    shrd = NamedSharding(mesh, P("data"))

    def body_ops(c, x):
        g = jax.lax.with_sharding_constraint(x.mean(0) * c, shrd)
        c = jax.lax.with_sharding_constraint(c - g, repl)
        return c, g.sum()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def win_clean(p, xs):
        return jax.lax.scan(body_ops, p, xs)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def win_entry_gather(p, xs):
        # the mutant: the gather hoisted OUT of the scan to ENTRY
        p = jax.lax.with_sharding_constraint(p, repl)
        def body(c, x):
            return c - x.mean(0), c.sum()
        return jax.lax.scan(body, p, xs)

    p_sh = progs._sds((8,), np.float32, mesh, P("data"))
    p_re = progs._sds((8,), np.float32, mesh, P())

    def xs(k):
        return progs._sds((k, 16, 8), np.float32, mesh, P(None, "data"))

    clean = progs.spec_from_lowered(
        "fixture/window", win_clean.lower(p_re, xs(4)), window=True,
        scan_length=4)
    hoisted = progs.spec_from_lowered(
        "fixture/window-entry-gather",
        win_entry_gather.lower(p_sh, xs(4)), window=True, scan_length=4)

    def unrolled(k):
        @jax.jit
        def f(p, xs):
            for i in range(k):  # the mutant: K unrolled steps
                p, _ = body_ops(p, xs[i])
            return p
        return progs.spec_from_lowered(
            f"fixture/window-unrolled@k{k}", f.lower(p_re, xs(k)),
            window=True, scan_length=k)

    lo, hi = unrolled(2), unrolled(8)
    hi.companion = lo
    return clean, hoisted, hi


def test_entry_collective_clean(window_mutants):
    clean, _, _ = window_mutants
    assert not run_checks([clean], checks=["entry-collective"])


def test_entry_collective_mutant(window_mutants):
    _, hoisted, _ = window_mutants
    findings = run_checks([hoisted], checks=["entry-collective"])
    assert findings, "hoisted gather must trip entry-collective"
    assert findings[0].severity == "error"
    assert "ENTRY computation" in findings[0].message
    assert "all-gather" in findings[0].message


def test_scan_dispatch_ratio_clean(window_mutants):
    """A scanned window's body appears once whatever K — give the
    clean program a same-shape companion and the ratio check passes."""
    clean, _, _ = window_mutants
    companion = ProgramSpec(name="fixture/window@k2",
                            module=clean.module, window=True,
                            scan_length=2)
    spec = ProgramSpec(name="fixture/window@k4", module=clean.module,
                       window=True, scan_length=4, companion=companion)
    assert not run_checks([spec], checks=["scan-dispatch-ratio"])


def test_scan_dispatch_ratio_mutant(window_mutants):
    _, _, hi = window_mutants
    findings = run_checks([hi], checks=["scan-dispatch-ratio"])
    assert findings, "unrolled window must trip scan-dispatch-ratio"
    assert "grew with K" in findings[0].message


# ------------------------------------------- replicated large operand

@pytest.fixture(scope="module")
def zero_mutant(devices8):
    """A stage-2 step lowered with the optimizer state REPLICATED —
    the placement the ZeRO policy exists to prevent."""
    from bigdl_tpu.parallel import ZeroConfig, make_mesh
    mesh = make_mesh([8], ["data"], devices8)
    cfg = ZeroConfig(stage=2)
    model = _mlp()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params, opt_state, mstate = progs._train_abstract(model, optim)
    n_params = len(jax.tree.leaves(params))
    n_opt = len(jax.tree.leaves(opt_state))
    params = progs._with_sharding(params, mesh,
                                  jax.tree.map(lambda _: P(), params))
    opt_state = progs._with_sharding(  # the mutant: replicated
        opt_state, mesh, jax.tree.map(lambda _: P(), opt_state))
    mstate = progs._with_sharding(mstate, mesh,
                                  jax.tree.map(lambda _: P(), mstate))
    step = build_train_step(model, nn.ClassNLLCriterion(), optim,
                            zero=cfg, mesh=mesh)
    lowered = step.lower(
        params, opt_state, mstate, progs._key_struct(),
        progs._sds((), np.float32),
        progs._sds((16, 16), np.float32, mesh, P("data")),
        progs._sds((16,), np.float32, mesh, P("data")))
    return progs.spec_from_lowered(
        "fixture/zero2-replicated", lowered, zero_stage=2, ndev=8,
        sharded_params=tuple(range(n_params, n_params + n_opt)),
        large_bytes=1 << 10)


def test_replicated_large_operand_mutant(zero_mutant):
    findings = run_checks([zero_mutant],
                          checks=["replicated-large-operand"])
    assert findings, "replicated opt state must trip the check"
    f = findings[0]
    assert f.severity == "error" and "replicated" in f.message
    assert "8-device mesh" in f.message


def test_replicated_large_operand_needs_zero_context(zero_mutant):
    """Without a declared stage >= 2 context the same program is not a
    violation — replication is the stage-0 contract."""
    spec = ProgramSpec(name="fixture/stage0", module=zero_mutant.module,
                       lowered=zero_mutant.lowered, zero_stage=0,
                       ndev=8, sharded_params=zero_mutant.sharded_params,
                       large_bytes=1 << 10)
    assert not run_checks([spec], checks=["replicated-large-operand"])


# --------------------------------------------------------- precision

class _UpcastLayer(Module):
    """The seeded mutant: an activation-sized astype(f32) followed by
    f32 arithmetic mid-model — real compute escapes the policy."""

    def apply(self, params, state, x, training=False, rng=None):
        wide = x.astype(jnp.float32)  # bigdl: disable=implicit-upcast-in-trace
        return wide * 1.5 + 0.25, state


@pytest.fixture(scope="module")
def precision_specs():
    from bigdl_tpu.precision import PrecisionPolicy
    pol = PrecisionPolicy.bf16_mixed()
    optim = SGD(learning_rate=0.1)

    def build(with_leak):
        RandomGenerator.set_seed(7)
        m = nn.Sequential().add(nn.Linear(64, 64))
        if with_leak:
            m.add(_UpcastLayer())
        m.add(nn.Linear(64, 4)).add(nn.LogSoftMax())
        m.training().ensure_initialized()
        params, opt_state, mstate = progs._train_abstract(m, optim, pol)
        step = build_train_step(m, nn.ClassNLLCriterion(), optim,
                                precision=pol)
        lowered = step.lower(
            params, opt_state, mstate, progs._key_struct(),
            progs._sds((), np.float32),
            progs._sds((64, 64), np.float32),
            progs._sds((64,), np.float32))
        return progs.spec_from_lowered(
            "fixture/bf16" + ("-leak" if with_leak else ""), lowered,
            policy="bf16_mixed", compute_dtype="bf16")

    return build(False), build(True)


def test_precision_leak_clean(precision_specs):
    clean, _ = precision_specs
    assert not run_checks([clean], checks=["precision-leak"])


def test_precision_leak_mutant(precision_specs):
    _, leak = precision_specs
    findings = run_checks([leak], checks=["precision-leak"])
    assert findings, "astype(f32) before a matmul must trip the check"
    f = findings[0]
    assert f.severity == "error"
    assert "bf16_mixed policy" in f.message and "f32" in f.message


def test_precision_leak_ignores_f32_policy(precision_specs):
    _, leak = precision_specs
    spec = ProgramSpec(name="f32", module=leak.module,
                       lowered=leak.lowered, policy="f32",
                       compute_dtype=None)
    assert not run_checks([spec], checks=["precision-leak"])


# --------------------------------------------------------------- HBM

def test_hbm_over_budget(donation_specs):
    clean, _ = donation_specs
    assert clean.memory is not None
    ok = ProgramSpec(name="fits", memory=clean.memory,
                     hbm_budget=64 << 30)
    bad = ProgramSpec(name="oom", memory=clean.memory, hbm_budget=16)
    assert not run_checks([ok], checks=["hbm-over-budget"])
    findings = run_checks([bad], checks=["hbm-over-budget"])
    assert findings and "16-byte per-device budget" in findings[0].message


def test_hbm_fit_autotuner_api(donation_specs):
    """The autotuner-facing primitive: pure dict in, verdict out —
    prune infeasible candidate configs without compiling them twice or
    running anything."""
    clean, _ = donation_specs
    fit = hbm_fit(clean.memory, None)
    assert fit["fits"] and fit["budget_bytes"] is None
    fit = hbm_fit(clean.memory, 8)
    assert not fit["fits"]
    assert fit["total_bytes"] == int(sum(fit["breakdown"].values()))


# ----------------------------------------------------- engine behaviors

def test_findings_suppression_and_report(donation_specs):
    _, mutant = donation_specs
    spec = ProgramSpec(name=mutant.name, module=mutant.module,
                       donated=mutant.donated,
                       suppress=("donation-dropped",))
    findings = run_checks([spec], checks=["donation-dropped"])
    assert findings and findings[0].suppressed
    report = format_findings(findings, programs=1)
    assert "0 program findings (1 suppressed)" in report
    assert "(suppressed)" in findings[0].format()
    d = findings[0].to_dict()
    assert d["suppressed"] and d["check"] == "donation-dropped"


def test_available_checks_covers_the_six():
    names = {c.name for c in available_checks()}
    assert {"donation-dropped", "entry-collective",
            "replicated-large-operand", "precision-leak",
            "hbm-over-budget", "scan-dispatch-ratio"} <= names


def test_unknown_check_raises():
    with pytest.raises(KeyError):
        run_checks([ProgramSpec(name="x")], checks=["no-such-check"])


def test_verification_compiles_but_never_executes():
    """The acceptance contract: building a spec + running checks is
    lowering/AOT-compiling only — the execution path is never entered
    (asserted via the backend compile/execute counters)."""
    from jax._src import compiler
    from jax._src.interpreters import pxla

    model = _mlp()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params, opt_state, mstate = progs._train_abstract(model, optim)
    step = build_train_step(model, nn.ClassNLLCriterion(), optim)

    compiles, execs = [], []
    orig_compile = compiler.backend_compile
    orig_call = pxla.ExecuteReplicated.__call__

    def counting_compile(*a, **k):
        compiles.append(1)
        return orig_compile(*a, **k)

    def counting_call(self, *a, **k):
        execs.append(1)
        return orig_call(self, *a, **k)

    compiler.backend_compile = counting_compile
    pxla.ExecuteReplicated.__call__ = counting_call
    try:
        lowered = step.lower(
            params, opt_state, mstate, progs._key_struct(),
            progs._sds((), np.float32),
            progs._sds((8, 16), np.float32),
            progs._sds((8,), np.float32))
        spec = progs.spec_from_lowered("exec-proof/step", lowered)
        findings = run_checks([spec])
    finally:
        compiler.backend_compile = orig_compile
        pxla.ExecuteReplicated.__call__ = orig_call
    assert compiles, "verification must have AOT-compiled the program"
    assert execs == [], f"verification executed {len(execs)} programs"
    assert not [f for f in findings if not f.suppressed]


def test_check_compiled_program_and_profile_verdict(donation_specs):
    """The telemetry.programs integration: compile-site verification
    attaches a verdict to the profile, diagnose renders it, and
    ``to_dict`` ships it (the flight-recorder programs.json path)."""
    from bigdl_tpu.telemetry.programs import ProgramRegistry
    from bigdl_tpu.tools.diagnose import _device_lines, device_summary

    clean, mutant = donation_specs
    r = ProgramRegistry(metrics=__import__(
        "bigdl_tpu.telemetry", fromlist=["telemetry"]).MetricsRegistry())
    r.register("fixture/undonated", "train", analysis={})
    findings = run_checks([mutant], checks=["donation-dropped"])
    r.attach_checks("fixture/undonated", findings)
    prof = r.get("fixture/undonated")
    assert prof.checks is not None and not prof.checks["clean"]
    assert prof.checks["findings"][0]["check"] == "donation-dropped"
    assert prof.to_dict()["checks"] == prof.checks  # bundles ship it

    r.register("fixture/clean", "train", analysis={})
    r.attach_checks("fixture/clean", [])
    rows = device_summary([p.to_dict() for p in r.profiles()])
    lines = _device_lines(rows)
    joined = "\n".join(lines)
    assert "checks clean" in joined
    assert "1 finding [donation-dropped]" in joined


def test_compile_site_checks_attach_to_profile():
    """BIGDL_PROGRAM_CHECKS path: with profiling + checks enabled, a
    program compiled through maybe_wrap_jitted verifies itself at the
    compile site and carries the verdict on its profile (what diagnose
    prints and flight bundles ship)."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.telemetry import programs as tp

    model = _mlp()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    step = build_train_step(model, nn.ClassNLLCriterion(), optim)
    reg = tp.ProgramRegistry(metrics=telemetry.MetricsRegistry())
    wrapped = tp._ProfiledProgram(
        "selfcheck/step", "train", step,
        donation="params,opt_state,model_state", prog_registry=reg)
    params = model.get_parameters()
    opt_state = optim.init_state(params)
    x = np.zeros((8, 16), np.float32)
    y = np.ones((8,), np.float32)
    was = tp.checks_enabled()
    tp.enable_checks()
    try:
        wrapped(params, opt_state, model.get_state(),
                jax.random.PRNGKey(0), 0.1, x, y)
    finally:
        if not was:
            tp.disable_checks()
    prof = reg.get("selfcheck/step")
    assert prof is not None and prof.checks is not None
    assert prof.checks["clean"], prof.checks
    assert prof.to_dict()["checks"]["clean"]
