"""Profiling surface (reference: AbstractModule.getTimes :205 per-module
timing + the jax.profiler trace path for fused steps)."""
import os

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.profiling import module_times, trace


def test_module_times_per_child():
    m = (nn.Sequential()
         .add(nn.Linear(16, 32).set_name("fc1"))
         .add(nn.ReLU())
         .add(nn.Linear(32, 4).set_name("fc2")))
    x = np.random.RandomState(0).rand(8, 16).astype(np.float32)
    times = module_times(m, x)
    names = [n for n, _ in times]
    assert names[0] == "fc1" and names[-1] == "fc2"
    assert len(times) == 3
    assert all(t >= 0 for _, t in times)


def test_trace_writes_profile(tmp_path):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a):
        return (a @ a).sum()

    a = jnp.ones((64, 64))
    f(a).block_until_ready()  # compile outside the trace
    with trace(str(tmp_path)):
        f(a).block_until_ready()
    produced = []
    for root, _, files in os.walk(str(tmp_path)):
        produced.extend(files)
    assert produced  # a trace file landed


def test_engine_init_distributed_single_process():
    """Single-process bring-up through jax.distributed (the multi-host
    entry; topology of 1 process must behave like plain init)."""
    from bigdl_tpu.utils.engine import Engine

    try:
        Engine.reset()
        Engine.init_distributed(coordinator_address="localhost:12357",
                                num_processes=1, process_id=0)
    except RuntimeError:
        # jax.distributed must start before any computation; in a shared
        # pytest process other tests have already run — the API surface
        # is what's under test, topology falls back to plain init
        Engine.init()
    assert Engine.is_initialized()
    assert Engine.node_number() == 1
