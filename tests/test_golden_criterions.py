"""Golden checks for the criterion family against real PyTorch losses
(the reference torch/ suite role, SURVEY.md §4.2). Targets follow BigDL
conventions: class labels 1-based; hinge/margin labels ±1."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bigdl_tpu.nn as nn  # noqa: E402


def _r(shape, seed=0, lo=-2.0, hi=2.0):
    return np.random.RandomState(seed).uniform(
        lo, hi, shape).astype(np.float32)


def _loss(crit, out, tgt):
    return float(crit.apply(out, tgt))


def test_bce_criterion():
    p = _r((4, 3), lo=0.05, hi=0.95)
    t = (np.random.RandomState(1).rand(4, 3) > 0.5).astype(np.float32)
    got = _loss(nn.BCECriterion(), p, t)
    want = F.binary_cross_entropy(torch.tensor(p), torch.tensor(t))
    assert got == pytest.approx(float(want), rel=1e-5)


def test_abs_criterion():
    a, b = _r((4, 3)), _r((4, 3), 1)
    got = _loss(nn.AbsCriterion(), a, b)
    want = F.l1_loss(torch.tensor(a), torch.tensor(b))
    assert got == pytest.approx(float(want), rel=1e-5)


def test_smooth_l1():
    a, b = _r((4, 3)), _r((4, 3), 1)
    got = _loss(nn.SmoothL1Criterion(), a, b)
    want = F.smooth_l1_loss(torch.tensor(a), torch.tensor(b))
    assert got == pytest.approx(float(want), rel=1e-5)


def test_margin_criterion():
    """Hinge loss: mean(max(0, margin - y*x)) (MarginCriterion.scala)."""
    x = _r((6,))
    y = np.sign(_r((6,), 3)).astype(np.float32)
    got = _loss(nn.MarginCriterion(1.0), x, y)
    want = np.maximum(0.0, 1.0 - y * x).mean()
    assert got == pytest.approx(float(want), rel=1e-5)


def test_margin_ranking_criterion():
    x1, x2 = _r((5,)), _r((5,), 1)
    y = np.sign(_r((5,), 2)).astype(np.float32)
    got = _loss(nn.MarginRankingCriterion(0.5), [x1, x2], y)
    want = F.margin_ranking_loss(torch.tensor(x1), torch.tensor(x2),
                                 torch.tensor(y), margin=0.5)
    assert got == pytest.approx(float(want), rel=1e-4)


def test_multi_margin_criterion():
    x = _r((4, 5))
    t = np.asarray([1, 3, 5, 2], np.float32)  # 1-based
    got = _loss(nn.MultiMarginCriterion(1, margin=1.0), x, t)
    want = F.multi_margin_loss(torch.tensor(x),
                               torch.tensor(t).long() - 1, p=1, margin=1.0)
    assert got == pytest.approx(float(want), rel=1e-4)


def test_multi_label_margin_criterion():
    x = _r((2, 4))
    # 1-based label lists, 0-terminated (MultiLabelMarginCriterion.scala)
    t = np.asarray([[3, 1, 0, 0], [4, 0, 0, 0]], np.float32)
    got = _loss(nn.MultiLabelMarginCriterion(), x, t)
    tt = torch.tensor([[2, 0, -1, -1], [3, -1, -1, -1]])
    want = F.multilabel_margin_loss(torch.tensor(x), tt)
    assert got == pytest.approx(float(want), rel=1e-4)


def test_multi_label_soft_margin():
    x = _r((3, 4))
    t = (np.random.RandomState(5).rand(3, 4) > 0.5).astype(np.float32)
    got = _loss(nn.MultiLabelSoftMarginCriterion(), x, t)
    want = F.multilabel_soft_margin_loss(torch.tensor(x), torch.tensor(t))
    assert got == pytest.approx(float(want), rel=1e-4)


def test_soft_margin():
    x = _r((3, 4))
    y = np.sign(_r((3, 4), 7)).astype(np.float32)
    got = _loss(nn.SoftMarginCriterion(), x, y)
    want = F.soft_margin_loss(torch.tensor(x), torch.tensor(y))
    assert got == pytest.approx(float(want), rel=1e-4)


def test_hinge_embedding():
    x = _r((6,), lo=0.1, hi=2.0)
    y = np.asarray([1, -1, 1, -1, 1, -1], np.float32)
    got = _loss(nn.HingeEmbeddingCriterion(1.0), x, y)
    want = F.hinge_embedding_loss(torch.tensor(x), torch.tensor(y),
                                  margin=1.0)
    assert got == pytest.approx(float(want), rel=1e-4)


def test_l1_hinge_embedding():
    """L1 distance between pair, hinged for dissimilar
    (L1HingeEmbeddingCriterion.scala)."""
    a, b = _r((5,)), _r((5,), 1)
    d = float(np.abs(a - b).sum())
    got_sim = _loss(nn.L1HingeEmbeddingCriterion(2.0), [a, b],
                    np.asarray(1.0, np.float32))
    assert got_sim == pytest.approx(d, rel=1e-5)
    got_dis = _loss(nn.L1HingeEmbeddingCriterion(2.0), [a, b],
                    np.asarray(-1.0, np.float32))
    assert got_dis == pytest.approx(max(0.0, 2.0 - d), abs=1e-5)


def test_cosine_embedding():
    a, b = _r((4, 6)), _r((4, 6), 1)
    y = np.asarray([1, -1, 1, -1], np.float32)
    got = _loss(nn.CosineEmbeddingCriterion(0.3), [a, b], y)
    want = F.cosine_embedding_loss(torch.tensor(a), torch.tensor(b),
                                   torch.tensor(y), margin=0.3)
    assert got == pytest.approx(float(want), rel=1e-4)


def test_cosine_distance_criterion():
    a, b = _r((4, 6)), _r((4, 6), 1)
    got = _loss(nn.CosineDistanceCriterion(), a, b)
    cos = F.cosine_similarity(torch.tensor(a), torch.tensor(b))
    want = (1.0 - cos).mean()
    assert got == pytest.approx(float(want), rel=1e-4)


def test_dist_kl_div():
    logp = np.log(_r((3, 5), lo=0.05, hi=1.0))
    t = _r((3, 5), 1, lo=0.0, hi=1.0)
    t = t / t.sum(axis=1, keepdims=True)
    got = _loss(nn.DistKLDivCriterion(), logp, t)
    want = F.kl_div(torch.tensor(logp), torch.tensor(t),
                    reduction="batchmean")
    assert got == pytest.approx(float(want), rel=1e-4)


def test_kld_criterion_vae():
    """KL(q(z|x) || N(0,1)) from (mean, log_var) (KLDCriterion.scala)."""
    mean, logv = _r((4, 3)), _r((4, 3), 1, lo=-1, hi=1)
    got = _loss(nn.KLDCriterion(), [mean, logv], np.zeros((4, 3)))
    want = 0.5 * np.sum(mean ** 2 + np.exp(logv) - 1.0 - logv) / 4
    # the reference sums over latent dims and averages over batch OR sums;
    # accept either normalization
    want_sum = 0.5 * np.sum(mean ** 2 + np.exp(logv) - 1.0 - logv)
    assert got == pytest.approx(float(want), rel=1e-3) or \
        got == pytest.approx(float(want_sum), rel=1e-3)


def test_gaussian_criterion():
    """-log N(target; mean, exp(log_var)) (GaussianCriterion.scala)."""
    mean, logv = _r((4, 3)), _r((4, 3), 1, lo=-1, hi=1)
    t = _r((4, 3), 2)
    got = _loss(nn.GaussianCriterion(), [mean, logv], t)
    want = 0.5 * np.sum(np.log(2 * np.pi) + logv
                        + (t - mean) ** 2 / np.exp(logv))
    assert got == pytest.approx(float(want), rel=1e-3) or \
        got == pytest.approx(float(want) / 4, rel=1e-3)


def test_l1_cost():
    x = _r((4, 3))
    got = _loss(nn.L1Cost(), x, None)
    assert got == pytest.approx(float(np.abs(x).sum()), rel=1e-5)


def test_class_simplex_criterion():
    """MSE against simplex-embedded class targets
    (ClassSimplexCriterion.scala)."""
    x = _r((3, 4))
    t = np.asarray([1, 2, 4], np.float32)
    crit = nn.ClassSimplexCriterion(4)
    got = _loss(crit, x, t)
    assert np.isfinite(got) and got >= 0
    # perfect prediction of the simplex target gives ~0 loss
    # (recover the embedded targets through the criterion's own table)
    m = crit
    if hasattr(m, "simplex"):
        tgt = np.asarray(m.simplex)[[0, 1, 3]]
        assert _loss(crit, tgt, t) == pytest.approx(0.0, abs=1e-5)


def test_dice_coefficient():
    p = _r((2, 6), lo=0.0, hi=1.0)
    t = (np.random.RandomState(9).rand(2, 6) > 0.5).astype(np.float32)
    got = _loss(nn.DiceCoefficientCriterion(epsilon=1.0), p, t)
    eps = 1.0
    per = 1.0 - (2 * (p * t).sum(1) + eps) / (p.sum(1) + t.sum(1) + eps)
    assert got == pytest.approx(float(per.mean()), rel=1e-3)


def test_softmax_with_criterion():
    x = _r((2, 5))
    t = np.asarray([2, 4], np.float32)
    got = _loss(nn.SoftmaxWithCriterion(), x, t)
    want = F.cross_entropy(torch.tensor(x), torch.tensor(t).long() - 1)
    assert got == pytest.approx(float(want), rel=1e-4)


def test_parallel_and_multi_criterion():
    a, b = _r((3, 4)), _r((3, 4), 1)
    t1, t2 = _r((3, 4), 2), _r((3, 4), 3)
    pc = nn.ParallelCriterion()
    pc.add(nn.MSECriterion(), 0.5).add(nn.AbsCriterion(), 2.0)
    got = _loss(pc, [a, b], [t1, t2])
    want = 0.5 * float(F.mse_loss(torch.tensor(a), torch.tensor(t1))) \
        + 2.0 * float(F.l1_loss(torch.tensor(b), torch.tensor(t2)))
    assert got == pytest.approx(want, rel=1e-4)

    mc = nn.MultiCriterion()
    mc.add(nn.MSECriterion(), 1.0).add(nn.AbsCriterion(), 3.0)
    got2 = _loss(mc, a, t1)
    want2 = float(F.mse_loss(torch.tensor(a), torch.tensor(t1))) \
        + 3.0 * float(F.l1_loss(torch.tensor(a), torch.tensor(t1)))
    assert got2 == pytest.approx(want2, rel=1e-4)


def test_criterion_gradients_match_torch():
    """Spot-check backward for a few criterions via jax.grad vs torch."""
    cases = [
        (nn.BCECriterion(),
         _r((3, 4), lo=0.05, hi=0.95),
         (np.random.RandomState(2).rand(3, 4) > 0.5).astype(np.float32),
         lambda o, t: F.binary_cross_entropy(o, t)),
        (nn.SmoothL1Criterion(), _r((3, 4)), _r((3, 4), 1),
         lambda o, t: F.smooth_l1_loss(o, t)),
        (nn.SoftMarginCriterion(), _r((3, 4)),
         np.sign(_r((3, 4), 7)).astype(np.float32),
         lambda o, t: F.soft_margin_loss(o, t)),
    ]
    for crit, out, tgt, tfn in cases:
        g = jax.grad(lambda o: jnp.asarray(
            crit.apply(o, tgt)).reshape(()))(jnp.asarray(out))
        to = torch.tensor(out, requires_grad=True)
        tfn(to, torch.tensor(tgt)).backward()
        np.testing.assert_allclose(np.asarray(g), to.grad.numpy(),
                                   atol=1e-5, rtol=1e-4)
