"""Reference-equivalence harness for the optimizer (the reference's
strongest distributed-correctness oracle: RefDistriOptimizer.scala:1 — a
sequential reimplementation whose results the distributed optimizer must
match, used by DistriOptimizerSpec.scala:233-249).

Three oracles:
(a) one DP step on the 8-device mesh == the same step on a single device,
(b) Optimizer-driven SGD == a hand-written numpy SGD, iterate-for-iterate,
(c) ZeRO-1 sharded optimizer state == fully replicated optimizer state.
"""
import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import SGD, max_iteration
from bigdl_tpu.optim.optimizer import (DistriOptimizer, LocalOptimizer,
                                       Optimizer)
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random import RandomGenerator


def _toy(n, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    Y = (X @ w + 0.3).astype(np.float32)
    return X, Y


def _single_batch_ds(X, Y):
    """n == batch_size: each epoch is exactly one (identical) batch, so
    the two compared runs see byte-identical data regardless of shuffle
    (within-batch order does not change the mean gradient)."""
    samples = [Sample(X[i], Y[i]) for i in range(len(X))]
    return DataSet.array(samples).transform(SampleToMiniBatch(len(X)))


def _snapshot(model):
    return jax.tree.map(np.array, model.get_parameters())


def _run(optimizer_factory, model, params0, iters, seed=7):
    model.set_parameters(jax.tree.map(np.array, params0))
    RandomGenerator.set_seed(seed)
    opt = optimizer_factory(model)
    opt.set_end_when(max_iteration(iters))
    opt.optimize()
    return jax.tree.map(np.asarray, model.get_parameters())


def _build_model(d=4):
    RandomGenerator.set_seed(123)
    m = nn.Sequential().add(nn.Linear(d, 8)).add(nn.Tanh()) \
        .add(nn.Linear(8, 1))
    m.ensure_initialized()
    return m


def test_dp_step_equals_single_device_step():
    """(a) RefDistriOptimizer oracle: one synchronous DP step over the
    8-device mesh must produce the same parameters as the same step on an
    unsharded single device (DistriOptimizerSpec.scala:233-249)."""
    Engine.reset()
    Engine.init()
    assert Engine.device_count() == 8
    X, Y = _toy(64)
    model = _build_model()
    p0 = _snapshot(model)

    def local(m):
        return (LocalOptimizer(m, _single_batch_ds(X, Y),
                               nn.MSECriterion(), batch_size=64)
                .set_optim_method(SGD(learning_rate=0.1)))

    def distri(m):
        return (DistriOptimizer(m, _single_batch_ds(X, Y),
                                nn.MSECriterion(), batch_size=64)
                .set_optim_method(SGD(learning_rate=0.1)))

    p_local = _run(local, model, p0, iters=1)
    p_dp = _run(distri, model, p0, iters=1)
    for a, b in zip(jax.tree.leaves(p_local), jax.tree.leaves(p_dp)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_dp_multi_step_equals_single_device():
    """(a, extended) 5 DP steps == 5 single-device steps with momentum —
    accumulated optimizer state stays equivalent too."""
    Engine.reset()
    Engine.init()
    X, Y = _toy(64, seed=3)
    model = _build_model()
    p0 = _snapshot(model)

    def mk_sgd():
        return SGD(learning_rate=0.05, momentum=0.9, weight_decay=1e-4,
                   nesterov=True)

    def local(m):
        return (LocalOptimizer(m, _single_batch_ds(X, Y),
                               nn.MSECriterion(), batch_size=64)
                .set_optim_method(mk_sgd()))

    def distri(m):
        return (DistriOptimizer(m, _single_batch_ds(X, Y),
                                nn.MSECriterion(), batch_size=64)
                .set_optim_method(mk_sgd()))

    p_local = _run(local, model, p0, iters=5)
    p_dp = _run(distri, model, p0, iters=5)
    for a, b in zip(jax.tree.leaves(p_local), jax.tree.leaves(p_dp)):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_optimizer_sgd_equals_hand_numpy_sgd():
    """(b) Optimizer + SGD(momentum, wd, nesterov) on Linear+MSE must
    reproduce a from-scratch numpy implementation for 10 iterations."""
    d = 4
    X, Y = _toy(32, d=d, seed=1)
    RandomGenerator.set_seed(9)
    model = nn.Linear(d, 1)
    model.ensure_initialized()
    p0 = _snapshot(model)
    W0, b0 = p0["weight"].copy(), p0["bias"].copy()

    lr, mom, wd = 0.05, 0.9, 1e-4

    def factory(m):
        return (LocalOptimizer(m, _single_batch_ds(X, Y),
                               nn.MSECriterion(), batch_size=32)
                .set_optim_method(SGD(learning_rate=lr, momentum=mom,
                                      weight_decay=wd, nesterov=True)))

    p_opt = _run(factory, model, p0, iters=10)

    # ---- hand-rolled numpy: forward Linear (y = x W^T + b per the
    # torch/BigDL convention — weight stored [out, in]), MSE mean loss,
    # SGD.scala update: g += wd*p; v = mom*v + g; step = g + mom*v
    W, b = W0.copy(), b0.copy()
    vW, vb = np.zeros_like(W), np.zeros_like(b)
    B = len(X)
    for _ in range(10):
        pred = X @ W.T + b          # [B,1]
        dpred = 2.0 * (pred - Y) / (B * pred.shape[1])
        gW = dpred.T @ X            # [1,d]
        gb = dpred.sum(axis=0)
        gW = gW + wd * W
        gb = gb + wd * b
        vW = mom * vW + gW
        vb = mom * vb + gb
        sW = gW + mom * vW
        sb = gb + mom * vb
        W = W - lr * sW
        b = b - lr * sb
    np.testing.assert_allclose(p_opt["weight"], W, atol=1e-5)
    np.testing.assert_allclose(p_opt["bias"], b, atol=1e-5)


def test_zero1_equals_replicated_opt_state():
    """(c) ZeRO-1 (moment buffers sharded over the data axis —
    AllReduceParameter.scala:214-303's owned shards) must train
    identically to fully replicated optimizer state."""
    Engine.reset()
    Engine.init()
    X, Y = _toy(64, seed=5)
    model = _build_model()
    p0 = _snapshot(model)

    def mk(m, zero1):
        return (Optimizer(m, _single_batch_ds(X, Y), nn.MSECriterion(),
                          batch_size=64, mesh=Engine.mesh(), zero1=zero1)
                .set_optim_method(SGD(learning_rate=0.05, momentum=0.9)))

    p_rep = _run(lambda m: mk(m, False), model, p0, iters=5)
    p_z1 = _run(lambda m: mk(m, True), model, p0, iters=5)
    for a, b in zip(jax.tree.leaves(p_rep), jax.tree.leaves(p_z1)):
        np.testing.assert_allclose(a, b, atol=1e-6)
