"""Worker process for the two-process jax.distributed smoke test
(spawned by test_distributed_smoke.py; not itself a pytest file).

Brings up Engine.init_distributed (Engine.scala:100-103's executor
bring-up role), then exercises one cross-process psum and one tiny
data-parallel SGD step whose result must match the sequential update.
Prints one JSON line: {"ok": true, ...} on success, {"skip": reason}
when the runtime lacks cross-process CPU collectives.
"""
import json
import os
import sys


def _optimizer_mode(pid: int):
    """DistriOptimizer over a mesh spanning BOTH processes (4 virtual
    devices each -> 8 global): each process feeds its half of the global
    batch; prints the loss sequence, which the parent compares against a
    single-process 8-device run of the identical global batches
    (RefDistriOptimizer's oracle, lifted to real multi-host)."""
    import jax
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import (DistriOptimizer, SGD, Top1Accuracy,
                                 every_epoch, max_iteration)
    from bigdl_tpu.utils.random import RandomGenerator

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    rng = np.random.RandomState(7)
    xs = rng.randn(64, 10).astype(np.float32)
    ys = (rng.randint(0, 3, 64) + 1).astype(np.float32)
    lo, hi = pid * 32, pid * 32 + 32
    samples = [Sample(xs[i], ys[i]) for i in range(lo, hi)]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(8))

    RandomGenerator.set_seed(42)
    model = (nn.Sequential().add(nn.Linear(10, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
    from bigdl_tpu.optim.optimizer import Optimizer
    # ZeRO-1 across REAL processes: moment buffers shard dim 0 over the
    # spanning data axis; the update must stay identical to replicated
    # state (the single-process reference the parent compares against)
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(),
                    batch_size=8, mesh=mesh, zero1=True)
    opt.set_optim_method(SGD(learning_rate=0.2, momentum=0.9))
    # validation exercises the multi-host local-shard scoring path; a
    # DIFFERENT batch size than training proves the fixed-batch guard is
    # tracked per stream, not shared (it used to abort here)
    val = DataSet.array(samples[:16]).transform(SampleToMiniBatch(4))
    opt.set_validation(every_epoch(), val, [Top1Accuracy()],
                       batch_size=4)
    opt.set_end_when(max_iteration(4))  # exactly one local epoch:
    # stopping before the rollover keeps the data order deterministic
    # for the parent's single-process comparison
    opt.optimize()

    # checkpointing a cross-process ZeRO-1-sharded tree must reassemble
    # the full value on every host (serialization._host_leaf)
    import tempfile

    from bigdl_tpu.parallel import shard_opt_state_zero1
    from bigdl_tpu.utils.serialization import load_tree, save_tree

    w = np.arange(32, dtype=np.float32).reshape(8, 4)
    sharded = shard_opt_state_zero1({"momentum": {"w": w}}, mesh, "data")
    d = tempfile.mkdtemp()
    save_tree(d + "/ck", sharded)
    back = load_tree(d + "/ck")
    np.testing.assert_array_equal(np.asarray(back["momentum"]["w"]), w)

    print(json.dumps({"ok": True, "pid": pid,
                      "last_loss": opt.driver_state["Loss"],
                      "score": opt.driver_state.get("score"),
                      "neval": opt.driver_state["neval"]}))


def _imagefolder_mode(pid: int, folder: str):
    """Multi-host input parity: each process reads ITS shard of one
    image folder (process_index/process_count — the role Spark
    partitioning played for SeqFileFolder) and feeds the global
    DistriOptimizer batch from it."""
    import jax
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import ImageFolderDataSet
    from bigdl_tpu.optim import DistriOptimizer, SGD, max_iteration
    from bigdl_tpu.utils.random import RandomGenerator

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    ds = ImageFolderDataSet(folder, batch_size=4, crop=12, scale=16,
                            num_threads=1, process_index=pid,
                            process_count=2)
    assert ds.size() == 16 and ds.local_size() == 8

    RandomGenerator.set_seed(42)
    model = (nn.Sequential().add(nn.Reshape((3 * 12 * 12,)))
             .add(nn.Linear(3 * 12 * 12, 2)).add(nn.LogSoftMax()))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          batch_size=4, mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(3))
    opt.optimize()
    ds.close()
    print(json.dumps({"ok": True, "pid": pid,
                      "last_loss": opt.driver_state["Loss"]}))


def run_parallel_case(kind: str, devices, pid=None):
    """ONE definition of the TP/PP/EP/composed equivalence cases,
    imported by both the worker (spanning mesh over ``jax.devices()``)
    and the parent test's single-process oracle (local devices) —
    hyperparameters and data cannot drift between the two sides.
    Returns driver_state.

    tp: megatron TP on a [1, 4] ("data","model") mesh — the size-1
    data axis is what the flagship recipe's mesh builder emits when TP
    consumes every device, so batches must route down the replicated
    regime, not the per-process-concat DP branch.
    pp: GPipe on a [1, 4] ("data","pipe") mesh — the ppermute
    activation ring crosses whatever transport separates the devices.
    ep: MoE TransformerLM on a [1, 2] ("data","model") mesh with the
    EXPERT axis spanning the processes — routed-expert dispatch
    collectives cross the real transport.
    composed: PipelinedTransformerLM+MoE on a [2, 2, 2]
    ("data","pipe","model") mesh — data axis SPANS the two processes
    (sharded-batch regime: each side feeds its half) while pipe/model
    run within each process: the full DP×TP×PP×EP product on one
    spanning mesh behind one optimize() call. ``pid`` (composed only):
    None = oracle feeds interleaved per-process blocks, else this
    process's half.
    """
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.parallel import make_mesh
    from bigdl_tpu.utils.random import RandomGenerator

    if kind == "tp":
        from bigdl_tpu.models import TransformerLM
        mesh = make_mesh([1, 4], ["data", "model"], devices)
        seed = 11

        def build():
            lm = TransformerLM(vocab_size=32, hidden_size=16,
                               num_layers=2, num_heads=4, max_len=8)
            return lm, lm.sharding_rules(model_axis="model")
    elif kind == "ep":
        from bigdl_tpu.models import TransformerLM
        mesh = make_mesh([1, 2], ["data", "model"], devices)
        seed = 19

        def build():
            lm = TransformerLM(vocab_size=32, hidden_size=16,
                               num_layers=2, num_heads=4, max_len=8,
                               moe_experts=2, moe_every=1)
            return lm, lm.sharding_rules(model_axis="model",
                                         expert_axis="model")
    elif kind.startswith("composed"):
        from bigdl_tpu.models import PipelinedTransformerLM
        mesh = make_mesh([2, 2, 2], ["data", "pipe", "model"], devices)
        seed = 17
        # "composed" runs the interleaved schedule (virtual-stage
        # waiting-room queue + extra ring hops across the transport);
        # "composed_gpipe" keeps the gpipe product covered too
        sched = "gpipe" if kind == "composed_gpipe" else "interleaved"

        def build():
            lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                        num_layers=4, num_heads=2,
                                        max_len=8, n_microbatches=2,
                                        mesh=mesh, moe_experts=2,
                                        pp_schedule=sched, pp_rounds=2)
            return lm, lm.sharding_rules(model_axis="model",
                                         expert_axis="model")
    else:
        from bigdl_tpu.models import PipelinedTransformerLM
        mesh = make_mesh([1, 4], ["data", "pipe"], devices)
        seed = 13

        def build():
            lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                        num_layers=4, num_heads=2,
                                        max_len=8, n_microbatches=4,
                                        mesh=mesh)
            return lm, lm.sharding_rules()

    rng = np.random.RandomState(seed)
    toks = rng.randint(0, 32, (32, 9))
    all_samples = [Sample(toks[i, :-1].astype(np.int32),
                          toks[i, 1:].astype(np.int32)) for i in range(32)]
    if kind.startswith("composed"):
        # sharded-batch regime over the spanning data axis: global batch
        # i = concat(p0 batch i, p1 batch i)
        if pid is None:
            order = []
            for i in range(4):
                order += list(range(i * 4, i * 4 + 4))
                order += list(range(16 + i * 4, 16 + i * 4 + 4))
            samples, bs = [all_samples[i] for i in order], 8
        else:
            samples, bs = all_samples[pid * 16:pid * 16 + 16], 4
    else:
        # replicated-batch regime (no data axis > 1): all rows each side
        samples, bs = all_samples, 8

    RandomGenerator.set_seed(42)
    lm, rules = build()
    ds = DataSet.array(samples).transform(SampleToMiniBatch(bs))
    opt = Optimizer(lm, ds, nn.SequenceCrossEntropyCriterion(),
                    batch_size=bs, mesh=mesh, sharding_rules=rules)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(_step_marker(max_iteration(4)))
    opt.optimize()
    return opt.driver_state


def _step_marker(base_trigger):
    """Wrap an end trigger to print STEP_OK once the first training
    step completed — the harness uses it to tell a mid-run collective
    deadlock (FAIL) from a slow compile on a loaded host (skip)."""
    state_seen = {"printed": False}

    def trig(state):
        if state["neval"] > 1 and not state_seen["printed"]:
            print("STEP_OK", flush=True)
            state_seen["printed"] = True
        return base_trigger(state)
    return trig


def _tp_or_pp_mode(pid: int, kind: str):
    """TP/PP/EP/composed over a mesh spanning two OS processes (see
    run_parallel_case for the per-kind regime)."""
    import jax

    state = run_parallel_case(kind, jax.devices(),
                              pid if kind.startswith("composed") else None)
    print(json.dumps({"ok": True, "pid": pid,
                      "last_loss": state["Loss"],
                      "neval": state["neval"]}))


def run_sparse_case(pid_or_none, devices):
    """Shared sparse-feed case (SparseMiniBatch at multi-host): COO
    samples with FIXED-nnz padding feed SparseLinear over a spanning
    data mesh. Worker passes its process id (feeds its half of the
    global batch); the single-process oracle passes None (feeds all
    rows as interleaved per-process blocks). Returns driver_state."""
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import (DataSet, PaddingParam, Sample,
                                   SampleToMiniBatch, SparseFeature)
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.parallel import make_mesh
    from bigdl_tpu.utils.random import RandomGenerator

    mesh = make_mesh([len(devices)], ["data"], devices)
    rng = np.random.RandomState(17)
    dim = 32
    hots = [rng.choice(dim, size=rng.randint(1, 4), replace=False)
            for _ in range(32)]
    labels = [float(h[0] % 2 + 1) for h in hots]
    all_samples = [Sample(
        SparseFeature(h[:, None], np.ones(len(h), np.float32), (dim,)),
        labels[i]) for i, h in enumerate(hots)]
    if pid_or_none is None:
        # oracle: global batch i = concat(p0 batch i, p1 batch i)
        order = []
        for i in range(4):
            order += list(range(i * 4, i * 4 + 4))
            order += list(range(16 + i * 4, 16 + i * 4 + 4))
        samples, bs = [all_samples[i] for i in order], 8
    else:
        lo = pid_or_none * 16
        samples, bs = all_samples[lo:lo + 16], 4
    pad = PaddingParam(fixed_length=4)
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(bs, feature_padding=pad))

    RandomGenerator.set_seed(42)
    model = nn.Sequential().add(nn.SparseLinear(dim, 2)) \
        .add(nn.LogSoftMax())
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=bs,
                    mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(_step_marker(max_iteration(4)))
    opt.optimize()
    return opt.driver_state


def _sparse_mode(pid: int):
    """SparseMiniBatch feed over a mesh spanning two OS processes:
    fixed-nnz COO batches assemble into global BCOOs whose leaves shard
    over the cross-process data axis."""
    import jax

    state = run_sparse_case(pid, jax.devices())
    print(json.dumps({"ok": True, "pid": pid,
                      "last_loss": state["Loss"],
                      "neval": state["neval"]}))


def run_predict_case(pid_or_none, devices):
    """Shared distributed-inference case: Predictor/Evaluator over a
    spanning data mesh. Worker passes its process id (feeds its HALF of
    the dataset, gets back its rows' predictions); the single-process
    oracle passes None (all rows). Returns (preds ndarray, global
    Top1Accuracy)."""
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample
    from bigdl_tpu.optim import Top1Accuracy
    from bigdl_tpu.optim.evaluator import Evaluator
    from bigdl_tpu.optim.predictor import Predictor
    from bigdl_tpu.parallel import make_mesh
    from bigdl_tpu.utils.random import RandomGenerator

    mesh = make_mesh([len(devices)], ["data"], devices)
    rng = np.random.RandomState(23)
    xs = rng.randn(32, 10).astype(np.float32)
    ys = (rng.randint(0, 3, 32) + 1).astype(np.float32)
    # oracle feeds the GLOBAL batch (8 rows over 8 devices); each
    # worker feeds its 4-row half of it
    lo, hi, bs = (0, 32, 8) if pid_or_none is None \
        else (pid_or_none * 16, pid_or_none * 16 + 16, 4)
    samples = [Sample(xs[i], ys[i]) for i in range(lo, hi)]
    ds = DataSet.array(samples)

    RandomGenerator.set_seed(42)
    model = (nn.Sequential().add(nn.Linear(10, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
    preds = Predictor(model, mesh=mesh).predict(ds, batch_size=bs)
    res = Evaluator(model, mesh=mesh).test(ds, [Top1Accuracy()],
                                           batch_size=bs)
    score, n = res["Top1Accuracy"].result()
    return np.stack(preds), score, n


def _predict_mode(pid: int):
    """Distributed inference over a mesh spanning two OS processes:
    each process feeds ITS dataset shard and must get back exactly its
    rows' predictions; the evaluator reduces scores globally so both
    processes report the same accuracy over all 32 rows."""
    import jax

    preds, score, n = run_predict_case(pid, jax.devices())
    print(json.dumps({"ok": True, "pid": pid, "n": int(n),
                      "score": float(score),
                      "preds": preds.tolist()}))


def _rotate_mode(pid: int):
    """ShardRotator with slots sharded over a mesh SPANNING both
    processes: each process's provider returns its local shard rows,
    staging assembles global pieces, and a rotation is an argument
    rebind on the one compiled draw."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bigdl_tpu.dataset.device_dataset import ShardRotator

    mesh = Mesh(np.array(jax.devices()), ("data",))
    sh = NamedSharding(mesh, P("data"))
    local_m = 8  # global shard = 16

    def provider(i):
        # every sample carries a unique id in ALL pixels of channel 0
        # AND as its label, so any image/label row mispairing (e.g.
        # piecewise image staging vs whole-shard label layout) is
        # caught sample-exactly, not just on a per-shard aggregate
        ids = 100.0 * i + 10.0 * pid + np.arange(local_m)
        imgs = np.random.RandomState(1000 + 10 * i + pid) \
            .randint(0, 255, (local_m, 3, 8, 8), np.uint8)
        imgs[:, 0, :, :] = ids[:, None, None].astype(np.uint8)
        return imgs, ids.astype(np.float32)

    rot = ShardRotator(provider, 3, 8, crop=(6, 6),
                       shuffle_shards=False, sharding=sh,
                       chunk_bytes=2 * 3 * 8 * 8)
    assert rot.shard_size == 16, rot.shard_size
    tmpl = rot.template

    @jax.jit
    def label_mean(labels):
        return jnp.mean(labels)

    @jax.jit
    def draw(images, labels, key):
        x, y = tmpl.batch_fn_on(images, labels, key,
                                epoch=jnp.int32(0), pos=jnp.int32(0))
        # channel-0 pixel == sample id == label, crop/flip-invariant
        return jnp.max(jnp.abs(x[:, 0, 0, 0] - y)), y

    means = []
    for step in range(3):
        err, _ = draw(rot.images, rot.labels, jax.random.PRNGKey(step))
        assert float(err) == 0.0, f"image/label mispairing, err={err}"
        means.append(float(label_mean(rot.labels)))
        while not rot.staged:
            rot.pump()
        rot.rotate()
    assert draw._cache_size() == 1, "slot swap must not retrace"
    # shard k labels: {100k + 10p + r} -> global mean 100k + 8.5
    assert means == [8.5, 108.5, 208.5], means
    print(json.dumps({"ok": True, "pid": pid, "means": means}))


def main():
    port, pid = sys.argv[1], int(sys.argv[2])
    mode = sys.argv[3] if len(sys.argv) > 3 else "smoke"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count="
        + {"smoke": "1", "tp": "2", "pp": "2", "ep": "1"}.get(mode, "4"))

    import numpy as np

    try:
        import jax

        # the container's sitecustomize initializes backends at
        # interpreter startup; drop them so the distributed client is
        # wired into the fresh CPU client (same trick as conftest.py)
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.extend.backend.clear_backends()
        except Exception:
            pass

        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bigdl_tpu.utils.engine import Engine

        Engine.init_distributed(coordinator_address=f"127.0.0.1:{port}",
                                num_processes=2, process_id=pid,
                                initialization_timeout=60)
        assert jax.process_count() == 2, jax.process_count()
        assert Engine.node_number() == 2
        # the harness distinguishes "runtime lacks collectives" (no
        # marker -> skip) from "post-rendezvous deadlock" (marker then
        # timeout -> FAIL)
        print(f"RENDEZVOUS_OK {pid}", flush=True)
        if mode in ("optimizer", "imagefolder", "rotate", "tp", "pp",
                    "ep", "composed", "composed_gpipe", "sparse",
                    "predict"):
            # bring-up succeeded: failures past this point are REAL
            # regressions and must crash the worker (SystemExit bypasses
            # the skip-catch below), not print a skip
            try:
                if mode == "optimizer":
                    _optimizer_mode(pid)
                elif mode in ("tp", "pp", "ep", "composed",
                              "composed_gpipe"):
                    _tp_or_pp_mode(pid, mode)
                elif mode == "sparse":
                    _sparse_mode(pid)
                elif mode == "predict":
                    _predict_mode(pid)
                elif mode == "rotate":
                    _rotate_mode(pid)
                else:
                    _imagefolder_mode(pid, sys.argv[4])
                return
            except Exception:
                import traceback
                traceback.print_exc()
                sys.exit(3)
        mesh = Engine.mesh()
        assert mesh.devices.size == 2

        def replicated_value(arr):
            return np.asarray(
                jax.device_get(arr.addressable_shards[0].data))

        # 1. one psum: global sum of per-process contributions
        shard = NamedSharding(mesh, P("data"))
        repl = NamedSharding(mesh, P())
        local = np.array([float(pid + 1)], np.float32)
        garr = jax.make_array_from_process_local_data(shard, local, (2,))
        total = jax.jit(jnp.sum, out_shardings=repl)(garr)
        tval = float(replicated_value(total))
        assert tval == 3.0, tval

        # 2. one DP step on a global batch sharded across the processes
        xs = np.arange(1, 9, dtype=np.float32).reshape(8, 1)
        ys = 2.0 * xs
        gx = jax.make_array_from_process_local_data(
            shard, xs[pid * 4:(pid + 1) * 4], (8, 1))
        gy = jax.make_array_from_process_local_data(
            shard, ys[pid * 4:(pid + 1) * 4], (8, 1))
        w0 = jnp.zeros((1, 1), jnp.float32)

        @lambda f: jax.jit(f, out_shardings=repl)
        def step(w, x, y):
            g = jax.grad(lambda w: jnp.mean((x @ w - y) ** 2))(w)
            return w - 0.01 * g

        w1 = float(replicated_value(step(w0, gx, gy)))
        w_ref = float(-0.01 * (2.0 * (0.0 * xs - ys) * xs).mean())
        assert abs(w1 - w_ref) < 1e-6, (w1, w_ref)

        print(json.dumps({"ok": True, "psum": tval, "w1": w1}))
    except (AssertionError,):
        raise
    except Exception as e:  # runtime without cross-process CPU support
        print(json.dumps({"skip": f"{type(e).__name__}: {e}"}))


if __name__ == "__main__":
    main()
