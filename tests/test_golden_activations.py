"""Golden forward+gradient checks for the activation family against real
PyTorch (the role the reference's torch/ suite of 127 specs plays,
SURVEY.md §4.2). Every layer gets a numeric forward assertion and a
gradient assertion via jax.grad vs torch.autograd.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bigdl_tpu.nn as nn  # noqa: E402


def _x(shape=(3, 5), seed=0, lo=-2.0, hi=2.0):
    rng = np.random.RandomState(seed)
    return (rng.uniform(lo, hi, shape)).astype(np.float32)


# (name, build bigdl module, torch fn, input kwargs)
CASES = [
    ("ReLU", lambda: nn.ReLU(), lambda t: F.relu(t), {}),
    ("ReLU6", lambda: nn.ReLU6(), lambda t: F.relu6(t), {}),
    ("Tanh", lambda: nn.Tanh(), torch.tanh, {}),
    ("TanhShrink", lambda: nn.TanhShrink(),
     lambda t: t - torch.tanh(t), {}),
    ("Sigmoid", lambda: nn.Sigmoid(), torch.sigmoid, {}),
    ("LogSigmoid", lambda: nn.LogSigmoid(), F.logsigmoid, {}),
    ("SoftMax", lambda: nn.SoftMax(), lambda t: F.softmax(t, -1), {}),
    ("SoftMin", lambda: nn.SoftMin(), lambda t: F.softmin(t, -1), {}),
    ("LogSoftMax", lambda: nn.LogSoftMax(),
     lambda t: F.log_softmax(t, -1), {}),
    ("SoftPlus", lambda: nn.SoftPlus(), F.softplus, {}),
    ("SoftPlusBeta2", lambda: nn.SoftPlus(2.0),
     lambda t: F.softplus(t, beta=2.0), {}),
    ("SoftSign", lambda: nn.SoftSign(), F.softsign, {}),
    ("ELU", lambda: nn.ELU(), F.elu, {}),
    ("ELUAlpha", lambda: nn.ELU(0.5),
     lambda t: F.elu(t, alpha=0.5), {}),
    ("LeakyReLU", lambda: nn.LeakyReLU(0.1),
     lambda t: F.leaky_relu(t, 0.1), {}),
    ("SoftShrink", lambda: nn.SoftShrink(0.5),
     lambda t: F.softshrink(t, 0.5), {}),
    ("HardShrink", lambda: nn.HardShrink(0.5),
     lambda t: F.hardshrink(t, 0.5), {}),
    ("HardTanh", lambda: nn.HardTanh(-0.7, 1.2),
     lambda t: F.hardtanh(t, -0.7, 1.2), {}),
    ("Clamp", lambda: nn.Clamp(-1.0, 0.5),
     lambda t: torch.clamp(t, -1.0, 0.5), {}),
    ("Threshold", lambda: nn.Threshold(0.3, -7.0),
     lambda t: F.threshold(t, 0.3, -7.0), {}),
    ("Square", lambda: nn.Square(), lambda t: t * t, {}),
    ("Sqrt", lambda: nn.Sqrt(), torch.sqrt, {"lo": 0.1, "hi": 4.0}),
    ("Log", lambda: nn.Log(), torch.log, {"lo": 0.1, "hi": 4.0}),
    ("Log1p", lambda: nn.Log1p(), torch.log1p, {"lo": -0.5, "hi": 4.0}),
    ("Exp", lambda: nn.Exp(), torch.exp, {}),
    ("Abs", lambda: nn.Abs(), torch.abs, {}),
    ("Negative", lambda: nn.Negative(), torch.neg, {}),
    ("Power", lambda: nn.Power(2.0, 1.5, 0.1),
     lambda t: (0.1 + 1.5 * t) ** 2.0, {"lo": 0.1, "hi": 2.0}),
    ("HardSigmoid", lambda: nn.HardSigmoid(),
     lambda t: torch.clamp(0.2 * t + 0.5, 0.0, 1.0), {}),
]


@pytest.mark.parametrize("name,build,tfn,kw",
                         CASES, ids=[c[0] for c in CASES])
def test_activation_forward_and_grad(name, build, tfn, kw):
    x = _x(**kw)
    m = build().evaluate()
    m.ensure_initialized()
    params, state = m.get_parameters(), m.get_state()

    got = np.asarray(m.apply(params, state, x, training=False)[0])
    tx = torch.tensor(x, requires_grad=True)
    want = tfn(tx)
    np.testing.assert_allclose(got, want.detach().numpy(),
                               atol=1e-5, rtol=1e-5)

    # gradient of sum(output) wrt input
    g = jax.grad(lambda xx: jnp.sum(
        m.apply(params, state, xx, training=False)[0]))(jnp.asarray(x))
    want.sum().backward()
    np.testing.assert_allclose(np.asarray(g), tx.grad.numpy(),
                               atol=1e-5, rtol=1e-5)


def test_prelu_shared_and_per_channel():
    # shared single weight (n_output_plane=0)
    m = nn.PReLU().evaluate()
    m.ensure_initialized()
    p = dict(m.get_parameters())
    key = next(iter(p))
    p[key] = np.asarray(p[key]) * 0 + 0.3
    x = _x((2, 4))
    got = np.asarray(m.apply(p, m.get_state(), x, training=False)[0])
    want = F.prelu(torch.tensor(x), torch.tensor([0.3]))
    np.testing.assert_allclose(got, want.numpy(), atol=1e-6)
    # per-channel over NCHW
    m2 = nn.PReLU(3).evaluate()
    m2.ensure_initialized()
    p2 = dict(m2.get_parameters())
    key2 = next(iter(p2))
    w = np.asarray([0.1, 0.2, 0.3], np.float32)
    p2[key2] = w.reshape(np.asarray(p2[key2]).shape)
    x2 = _x((2, 3, 4, 4), seed=1)
    got2 = np.asarray(m2.apply(p2, m2.get_state(), x2, training=False)[0])
    want2 = F.prelu(torch.tensor(x2), torch.tensor(w))
    np.testing.assert_allclose(got2, want2.numpy(), atol=1e-6)


def test_binary_threshold():
    m = nn.BinaryThreshold(0.5)
    x = np.asarray([[0.2, 0.5, 0.7], [-1.0, 0.51, 2.0]], np.float32)
    got = np.asarray(m.forward(x))
    np.testing.assert_array_equal(got, (x > 0.5).astype(np.float32))


def test_rrelu_eval_matches_torch_and_train_bounds():
    lower, upper = 1 / 8, 1 / 3
    m = nn.RReLU(lower, upper)
    x = _x((4, 6), seed=2)
    # eval: deterministic slope (lower+upper)/2, torch semantics
    m.evaluate()
    m.ensure_initialized()
    got = np.asarray(m.apply(m.get_parameters(), m.get_state(), x,
                             training=False)[0])
    want = F.rrelu(torch.tensor(x), lower, upper, training=False)
    np.testing.assert_allclose(got, want.numpy(), atol=1e-6)
    # train: negatives scaled by a per-element slope within [lower, upper]
    out = np.asarray(m.apply(m.get_parameters(), m.get_state(), x,
                             training=True,
                             rng=jax.random.PRNGKey(0))[0])
    neg = x < 0
    slopes = out[neg] / x[neg]
    assert slopes.min() >= lower - 1e-6
    assert slopes.max() <= upper + 1e-6
    np.testing.assert_allclose(out[~neg], x[~neg], atol=1e-6)


def test_gradient_reversal():
    m = nn.GradientReversal(0.7)
    x = _x((3, 3))
    m.ensure_initialized()
    got = np.asarray(m.apply(m.get_parameters(), m.get_state(), x)[0])
    np.testing.assert_allclose(got, x)  # identity forward
    g = jax.grad(lambda xx: jnp.sum(
        m.apply(m.get_parameters(), m.get_state(), xx)[0] * 2.0))(
        jnp.asarray(x))
    # gradient is reversed and scaled by lambda (GradientReversal.scala)
    np.testing.assert_allclose(np.asarray(g), -0.7 * 2.0 * np.ones_like(x),
                               atol=1e-6)


def test_gaussian_sampler_statistics():
    m = nn.GaussianSampler()
    m.ensure_initialized()
    mean = np.full((2000, 2), 3.0, np.float32)
    logvar = np.full((2000, 2), np.log(0.25), np.float32)
    out, _ = m.apply(m.get_parameters(), m.get_state(), [mean, logvar],
                     training=True, rng=jax.random.PRNGKey(0))
    out = np.asarray(out)
    assert abs(out.mean() - 3.0) < 0.05
    assert abs(out.std() - 0.5) < 0.05
