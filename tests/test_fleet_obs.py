"""Fleet observability plane tests (ISSUE 18): cross-process snapshot
merge algebra (counter sums exact to the digit, associative and
order-independent; gauges keep per-source series; histogram count/sum
exact with percentiles from the merged reservoir), merged Chrome traces
(3 synthetic hosts, every span/flow pair preserved, ids namespaced),
snapshot-JSONL identity header back-compat, the SloSpec grammar /
evaluate / burn-rate engine, straggler detection, the snapshot shipper
(disabled = one flag check, micro-benchmark-asserted), and the
``diagnose --fleet`` / multi-bundle ``--postmortem`` CLI modes.
"""
import itertools
import json
import os
import time

import pytest

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import agg, slo
from bigdl_tpu.telemetry.metrics import MetricsRegistry
from bigdl_tpu.utils.profiling import percentile_summary


@pytest.fixture(autouse=True)
def _telemetry_off():
    telemetry.disable()
    telemetry.tracer().clear()
    yield
    agg.stop_shipping(final=False)
    telemetry.disable()
    telemetry.tracer().clear()


def _host_snapshot(host, counters=(), hist=(), gauges=()):
    """A (identity, rows) source built through the REAL registry."""
    r = MetricsRegistry()
    for name, vals in counters:
        c = r.counter(name, "test counter")
        for v in vals:
            c.inc(v)
    for name, vals in hist:
        h = r.histogram(name, "test histogram")
        for v in vals:
            h.observe(v)
    for name, v in gauges:
        r.gauge(name, "test gauge").set(v)
    return ({"host": host, "pid": 1000 + host},
            r.snapshot(include_samples=True))


# ---------------------------------------------------------- merge algebra

class TestMergeAlgebra:
    # values chosen so naive left-to-right float addition disagrees
    # between orders — fsum-over-sorted must not
    VALS = [0.1, 1e16, 0.2, 3.0, 7e-17, 0.3]

    def _sources(self):
        return [
            _host_snapshot(0, counters=[("train/x/events", self.VALS)],
                           hist=[("train/x/lat", [1.0, 2.0, 3.0])],
                           gauges=[("train/x/depth", 4.0)]),
            _host_snapshot(1, counters=[("train/x/events",
                                         self.VALS[::-1])],
                           hist=[("train/x/lat", [10.0, 20.0])],
                           gauges=[("train/x/depth", 9.0)]),
            _host_snapshot(2, counters=[("train/x/events", [5.0])],
                           hist=[("train/x/lat", [0.5])],
                           gauges=[("train/x/depth", 1.0)]),
        ]

    @staticmethod
    def _counter_total(merged, name):
        row = next(r for r in merged if r["name"] == name)
        return agg._fsum_sorted(s["value"] for s in row["series"])

    def test_counter_sums_to_the_digit(self):
        import math
        merged = agg.aggregate_snapshots(self._sources())
        want = math.fsum(sorted(
            self.VALS + self.VALS[::-1] + [5.0]))
        assert self._counter_total(merged, "train/x/events") == want

    def test_order_independent_across_all_permutations(self):
        sources = self._sources()
        reports = []
        for perm in itertools.permutations(sources):
            merged = agg.aggregate_snapshots(list(perm))
            reports.append((
                self._counter_total(merged, "train/x/events"),
                next(tuple(sorted(
                    (s["count"], s["sum"], s["p50"], s["p99"])
                    for s in r["series"]))
                    for r in merged if r["name"] == "train/x/lat")))
        assert len(set(reports)) == 1, reports

    def test_associative_via_remerge(self):
        """merge(merge(A,B), C) == merge(A, B, C): merged series carry
        their reservoirs, so a merged snapshot is itself a source."""
        a, b, c = self._sources()
        ab = agg.aggregate_snapshots([a, b])
        two_step = agg.aggregate_snapshots([({"host": 9}, ab), c])
        flat = agg.aggregate_snapshots([a, b, c])
        for name in ("train/x/events", "train/x/lat"):
            t = next(r for r in two_step if r["name"] == name)
            f = next(r for r in flat if r["name"] == name)
            assert t["kind"] == f["kind"]
            if t["kind"] == "counter":
                assert self._counter_total(two_step, name) == \
                    self._counter_total(flat, name)
            else:
                ts, fs = t["series"][0], f["series"][0]
                assert ts["count"] == fs["count"]
                assert ts["sum"] == fs["sum"]
                assert ts["p50"] == fs["p50"]
                assert ts["p99"] == fs["p99"]

    def test_gauges_keep_per_source_series(self):
        merged = agg.aggregate_snapshots(self._sources())
        row = next(r for r in merged if r["name"] == "train/x/depth")
        got = {tuple(sorted(s["labels"].items())): s["value"]
               for s in row["series"]}
        assert got == {(("host", "0"),): 4.0,
                       (("host", "1"),): 9.0,
                       (("host", "2"),): 1.0}

    def test_histogram_count_sum_exact_percentiles_from_union(self):
        merged = agg.aggregate_snapshots(self._sources())
        row = next(r for r in merged if r["name"] == "train/x/lat")
        s = row["series"][0]
        union = sorted([1.0, 2.0, 3.0, 10.0, 20.0, 0.5])
        assert s["count"] == 6
        assert s["sum"] == sum(union)
        want = percentile_summary(union, (50, 90, 99))
        assert s["p50"] == want["p50"]
        assert s["p99"] == want["p99"]
        assert sorted(s["samples"]) == union

    def test_merge_invariant_clean_and_detects_tamper(self):
        sources = self._sources()
        merged = agg.aggregate_snapshots(sources)
        assert agg.check_merge_invariant(sources, merged) == []
        row = next(r for r in merged if r["name"] == "train/x/events")
        # big enough to survive float spacing at the ~1e16 total
        row["series"][0]["value"] += 16.0
        bad = agg.check_merge_invariant(sources, merged)
        assert bad and "train/x/events" in bad[0]

    def test_kind_conflict_raises(self):
        a = _host_snapshot(0, counters=[("train/x/v", [1.0])])
        b = _host_snapshot(1, gauges=[("train/x/v", 2.0)])
        with pytest.raises(ValueError):
            agg.aggregate_snapshots([a, b])


# ------------------------------------------------------------ trace merge

class TestTraceMerge:
    def _host_events(self, host):
        base = 1000.0 * host
        return [
            {"ph": "X", "name": f"step{host}", "pid": 7, "tid": 1,
             "ts": base, "dur": 5.0},
            {"ph": "X", "name": "decode", "pid": 7,
             "tid": (1 << 48) + 3, "ts": base + 6, "dur": 2.0},
            {"ph": "s", "name": "req", "pid": 7, "tid": 1,
             "ts": base, "id": 42, "cat": "request"},
            {"ph": "f", "name": "req", "pid": 7, "tid": 1,
             "ts": base + 8, "id": 42, "cat": "request",
             "bp": "e"},
        ]

    def test_three_hosts_preserved_namespaced_no_collisions(self):
        sources = [({"host": h}, self._host_events(h))
                   for h in range(3)]
        merged = agg.merge_chrome_traces(sources)
        meta = [e for e in merged if e["ph"] == "M"]
        spans = [e for e in merged if e["ph"] == "X"]
        flows = [e for e in merged if e["ph"] in ("s", "f")]
        assert len(meta) == 3
        assert {m["args"]["name"] for m in meta} == \
            {"host0", "host1", "host2"}
        # every span preserved, one process track per host
        assert len(spans) == 6
        assert {e["pid"] for e in spans} == {1, 2, 3}
        # tids (incl. virtual tracks) verbatim
        assert {e["tid"] for e in spans} == {1, (1 << 48) + 3}
        # every flow PAIR preserved, ids namespaced per source — three
        # distinct pairs, no cross-host pairing
        ids = sorted(e["id"] for e in flows if e["ph"] == "s")
        assert ids == ["host0:42", "host1:42", "host2:42"]
        for s_ev in (e for e in flows if e["ph"] == "s"):
            f_ev = [e for e in flows if e["ph"] == "f"
                    and e["id"] == s_ev["id"]]
            assert len(f_ev) == 1 and f_ev[0]["pid"] == s_ev["pid"]

    def test_duplicate_tags_get_suffixes(self):
        sources = [("worker", [{"ph": "X", "name": "a", "pid": 1,
                                "tid": 1, "ts": 0, "dur": 1}])] * 2
        merged = agg.merge_chrome_traces(sources)
        names = {m["args"]["name"] for m in merged if m["ph"] == "M"}
        assert names == {"worker", "worker#1"}

    def test_write_and_file_merge_roundtrip(self, tmp_path):
        paths = []
        for h in range(2):
            p = tmp_path / f"host{h}-trace.json"
            with open(p, "w") as f:
                json.dump({"traceEvents": self._host_events(h)}, f)
            paths.append(str(p))
        merged = agg.merge_chrome_trace_files(paths)
        assert len([e for e in merged if e["ph"] == "X"]) == 4
        out = tmp_path / "merged.json"
        n = agg.write_merged_trace(
            str(out), [("a", self._host_events(0))])
        doc = json.loads(out.read_text())
        assert len(doc["traceEvents"]) == n


# ------------------------------------------- snapshot header back-compat

class TestSnapshotHeader:
    def test_new_files_carry_identity_header(self, tmp_path):
        r = MetricsRegistry()
        r.counter("train/x/events", "d").inc(3)
        path = str(tmp_path / "snap.jsonl")
        telemetry.JsonlExporter(
            r, path, identity={"host": 2, "pid": 77}).export()
        with open(path) as f:
            first = json.loads(f.readline())
        assert first["header"] == telemetry.SNAPSHOT_HEADER_FORMAT
        assert first["identity"] == {"host": 2, "pid": 77}
        ident, records = telemetry.read_jsonl_with_identity(path)
        assert ident == {"host": 2, "pid": 77}
        assert len(records) == 1
        # read_jsonl (the pre-header reader) still parses, skipping it
        assert len(telemetry.read_jsonl(path)) == 1

    def test_old_headerless_files_still_parse(self, tmp_path):
        path = str(tmp_path / "old.jsonl")
        rec = {"time": 1.0, "metrics": [
            {"name": "train/x/events", "kind": "counter",
             "description": "", "series": [{"labels": {},
                                            "value": 2.0}]}]}
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
        assert telemetry.read_jsonl(path) == [rec]
        ident, records = telemetry.read_jsonl_with_identity(path)
        assert ident is None and records == [rec]

    def test_tolerant_mode_skips_torn_tail(self, tmp_path):
        """A SIGKILL mid-write leaves a torn last line; the postmortem
        reader must keep every complete record."""
        r = MetricsRegistry()
        r.counter("train/x/events", "d").inc(1)
        path = str(tmp_path / "torn.jsonl")
        telemetry.JsonlExporter(r, path, identity={"pid": 1}).export()
        with open(path, "a") as f:
            f.write('{"time": 2.0, "metri')  # torn
        with pytest.raises(ValueError):
            telemetry.read_jsonl_with_identity(path)
        ident, records = telemetry.read_jsonl_with_identity(
            path, tolerant=True)
        assert ident == {"pid": 1} and len(records) == 1


# ------------------------------------------------------------- SLO engine

class TestSlo:
    def _snapshot(self):
        r = MetricsRegistry()
        r.counter("fleet/replica/evictions", "d").inc(2, replica="r0")
        r.counter("fleet/replica/evictions", "d").inc(1, replica="r1")
        h = r.histogram("serving/generation/ttft_ms", "d")
        for v in (10.0, 20.0, 300.0):
            h.observe(v, model="m")
        return r.snapshot(include_samples=True)

    def test_parse_grammar_and_roundtrip(self):
        spec = slo.SloSpec.parse(
            "p99: serving/generation/ttft_ms.p99 <= 250\n"
            "evictions: fleet/replica/evictions <= 0 default 0;"
            "goodput: goodput_tokens_per_sec >= 40 default 0")
        assert [o.name for o in spec.objectives] == \
            ["p99", "evictions", "goodput"]
        assert spec.objectives[1].default == 0.0
        with pytest.raises(ValueError):
            slo.SloSpec.parse("nonsense without colon")
        with pytest.raises(ValueError):
            slo.SloSpec.parse("a: x == 1")  # only <= / >=

    def test_evaluate_label_reduction_and_breach(self):
        spec = slo.SloSpec.parse(
            "evictions: fleet/replica/evictions <= 0 default 0;"
            "p99: serving/generation/ttft_ms.p99 <= 250")
        report = slo.evaluate(spec, self._snapshot())
        by = {v.objective.name: v for v in report.verdicts}
        # counter series SUM (2 + 1); histogram takes the worst series
        assert by["evictions"].value == 3.0
        assert by["p99"].value > 250.0
        assert report.breached == ["evictions", "p99"]
        with pytest.raises(slo.SloBreach) as ei:
            report.check()
        assert ei.value.report is report

    def test_missing_metric_default_vs_breach(self):
        ok = slo.evaluate(slo.SloSpec.parse(
            "evictions: fleet/replica/evictions <= 0 default 0"), [])
        assert ok.passed
        assert ok.verdicts[0].source == "default"
        bad = slo.evaluate(slo.SloSpec.parse(
            "evictions: fleet/replica/evictions <= 0"), [])
        assert not bad.passed
        assert bad.verdicts[0].source == "missing"
        assert bad.verdicts[0].value is None

    def test_observations_win_over_snapshot(self):
        spec = slo.SloSpec.parse(
            "evictions: fleet/replica/evictions <= 0")
        report = slo.evaluate(spec, self._snapshot(),
                              {"fleet/replica/evictions": 0.0})
        assert report.passed
        assert report.verdicts[0].source == "observation"

    def test_engine_multi_window_burn_rate(self):
        spec = slo.SloSpec.parse("evictions: x <= 0 default 0")
        eng = slo.SloEngine(spec, error_budget=0.5,
                            windows=(5.0, 100.0))
        t0 = 1000.0
        # clean for 5 evaluations, then breaching for 5 (1s apart)
        for i in range(10):
            obs = {"x": 1.0 if i >= 5 else 0.0}
            eng.evaluate(observations=obs, now=t0 + i)
        rates = eng.burn_rates(now=t0 + 9)
        # short window (ts > 1004): all 5 breach -> 1.0/0.5 = 2.0
        assert rates[5.0] == pytest.approx(2.0)
        # long window: 5/10 breach -> 0.5/0.5 = 1.0
        assert rates[100.0] == pytest.approx(1.0)
        assert not eng.burning(now=t0 + 9)  # long window not OVER 1.0
        eng.evaluate(observations={"x": 1.0}, now=t0 + 10)
        # 6/11 long-window breaches now burn past 1.0 -> page
        assert eng.burning(now=t0 + 10)


# ------------------------------------------------------------- stragglers

def test_detect_stragglers_flags_slow_host():
    sources = [
        _host_snapshot(0, hist=[("train/optimizer/computing_time",
                                 [0.10, 0.11, 0.10])]),
        _host_snapshot(1, hist=[("train/optimizer/computing_time",
                                 [0.10, 0.10, 0.12])]),
        _host_snapshot(2, hist=[("train/optimizer/computing_time",
                                 [0.50, 0.55, 0.52])]),
    ]
    out = agg.detect_stragglers(sources, threshold=1.5)
    assert set(out["per_source"]) == {"host0", "host1", "host2"}
    assert [s["source"] for s in out["stragglers"]] == ["host2"]
    assert out["stragglers"][0]["ratio"] > 1.5
    # all-even fleet: nobody flagged
    even = agg.detect_stragglers(sources[:2], threshold=1.5)
    assert even["stragglers"] == []


# ---------------------------------------------------------------- shipper

class TestShipper:
    def test_ship_and_read_roundtrip(self, tmp_path):
        r = MetricsRegistry()
        r.counter("train/x/events", "d").inc(7)
        d = str(tmp_path / "snaps")
        agg.start_shipping(d, interval_s=0.0, registry=r,
                           identity={"replica": "r0", "pid": 1})
        assert agg.maybe_ship() is not None
        r.counter("train/x/events", "d").inc(1)
        assert agg.maybe_ship(force=True) is not None
        agg.stop_shipping()
        sources = agg.read_snapshot_dir(d)
        assert len(sources) == 1
        ident, rows = sources[0]
        assert ident["replica"] == "r0"
        # read_snapshot_dir keeps the LAST (cumulative) record
        row = next(x for x in rows if x["name"] == "train/x/events")
        assert row["series"][0]["value"] == 8.0

    def test_interval_gate(self, tmp_path):
        r = MetricsRegistry()
        agg.start_shipping(str(tmp_path), interval_s=3600.0,
                           registry=r, identity={"pid": 1})
        assert agg.maybe_ship() is not None   # first ship is free
        assert agg.maybe_ship() is None       # gated
        assert agg.maybe_ship(force=True) is not None
        agg.stop_shipping(final=False)

    def test_disabled_maybe_ship_overhead_bounded(self):
        """Disarmed maybe_ship() must be ONE module-flag check — safe
        at optimizer-step cadence (same bound as disabled span())."""
        assert not agg.shipping()
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            agg.maybe_ship()
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, \
            f"{per_call * 1e6:.2f}us per disarmed maybe_ship"


# ------------------------------------------------------------- CLI modes

def _ship_fake_host(d, host, step_s):
    r = MetricsRegistry()
    c = r.counter("train/optimizer/steps", "steps")
    h = r.histogram("train/optimizer/computing_time", "step time")
    for v in step_s:
        c.inc()
        h.observe(v)
    telemetry.JsonlExporter(
        r, os.path.join(d, f"snap-host{host}.jsonl"),
        identity={"host": host, "pid": 100 + host},
        include_samples=True).export()


def test_diagnose_fleet_mode(tmp_path, capsys):
    from bigdl_tpu.tools import diagnose

    d = str(tmp_path)
    _ship_fake_host(d, 0, [0.1, 0.1])
    _ship_fake_host(d, 1, [0.1, 0.12])
    _ship_fake_host(d, 2, [0.9, 0.95])
    assert diagnose.main(["--fleet", d]) == 0
    out = capsys.readouterr().out
    assert "fleet:" in out
    assert "3 sources" in out
    assert "merged totals equal per-process sums (exact)" in out
    assert "STRAGGLER" in out
    assert "train/optimizer/steps: 6" in out

    # --json carries the typed sections
    assert diagnose.main(["--fleet", d, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fleet"]["violations"] == []
    strag = doc["fleet"]["stragglers"]["step_time"]
    assert [s["source"] for s in strag["stragglers"]] == ["host2"]


def test_diagnose_fleet_empty_dir_errors(tmp_path, capsys):
    from bigdl_tpu.tools import diagnose
    assert diagnose.main(["--fleet", str(tmp_path)]) == 2


def test_diagnose_postmortem_bundle_directory(tmp_path, capsys):
    """--postmortem on a directory OF bundles (what a killed gang
    leaves) merges traces and aggregates the registries."""
    from bigdl_tpu.telemetry import flight
    from bigdl_tpu.tools import diagnose

    r = MetricsRegistry()  # keep the shared registry out of it
    del r
    d = str(tmp_path)
    for i in range(2):
        telemetry.enable()
        with telemetry.span("optimizer/step", step=i):
            pass
        flight.arm(d)
        flight.note("checkpoint", step=i)
        assert flight.dump(f"test-{i}") is not None
        flight.disarm()
        telemetry.tracer().clear()
    bundles = [x for x in os.listdir(d) if x.startswith("postmortem-")]
    assert len(bundles) == 2
    assert diagnose.main(["--postmortem", d]) == 0
    out = capsys.readouterr().out
    assert "postmortem:" in out
    assert "test-0" in out and "test-1" in out


# ----------------------------------------------- ProcessReplica shipping

def test_process_replica_ships_snapshots_and_flight(tmp_path):
    """Subprocess replicas arm the flight recorder and ship serving
    snapshots into the router-owned directory; the router's
    fleet_snapshot() merges them with its own registry."""
    from bigdl_tpu.fleet.replica import ProcessReplica
    from bigdl_tpu.fleet.router import FleetRouter

    import numpy as np

    d = str(tmp_path / "fleet-telemetry")
    spec = dict(seed=42, vocab_size=32, hidden_size=16, num_layers=1,
                num_heads=2, max_len=16)
    router = None
    try:
        rep = ProcessReplica("p0", spec, slots=2, max_len=16,
                             telemetry_dir=d)
        router = FleetRouter([rep], telemetry_dir=d)
        s = router.submit(np.array([1, 2, 3], dtype=np.int32),
                          session="s0", max_new_tokens=3)
        assert len(s.result(timeout=120)) > 0
        # ships are interval-gated (0.2s): a second request after the
        # interval carries the serving counts into the shipped file
        deadline = time.time() + 60
        while True:
            time.sleep(0.3)
            s = router.submit(np.array([1, 2, 3], dtype=np.int32),
                              session="s0", max_new_tokens=3)
            assert len(s.result(timeout=120)) > 0
            merged = router.fleet_snapshot()
            ttft = next((row for row in merged
                         if row["name"] ==
                         "serving/generation/ttft_ms"), None)
            if ttft and sum(x["count"] for x in ttft["series"]) >= 1:
                break
            assert time.time() < deadline, \
                sorted({r["name"] for r in merged})
        # the shipped files themselves are postmortem-grade artifacts
        snaps = [f for f in os.listdir(d) if f.endswith(".jsonl")]
        assert snaps, "replica shipped no snapshot files"
    finally:
        if router is not None:
            router.shutdown(drain=False)


@pytest.mark.slow
def test_bench_slo_row_contract():
    """BENCH_SLO: fleet-soak goodput + p99 TTFT from the MERGED
    snapshot, keys named for the tools/regress direction rules, rides
    the schema-v2 record."""
    import importlib
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    bench = importlib.import_module("bench")
    from bigdl_tpu.tools.regress import (KNOWN_SCHEMA_VERSIONS,
                                         classify_key)

    row = bench._bench_slo()
    assert row["slo_goodput_tokens_per_sec"] > 0
    assert row["slo_ttft_ms_p99"] > 0
    assert row["slo_passed"] == 1
    assert bench.BENCH_SCHEMA_VERSION in KNOWN_SCHEMA_VERSIONS
    # regress gates the new keys with the right direction
    assert classify_key("slo_goodput_tokens_per_sec") == "higher"
    assert classify_key("slo_ttft_ms_p99") == "lower"
