"""Fleet serving (bigdl_tpu.fleet): prefix/KV reuse, speculative
decoding, replica router. Pins the subsystem's load-bearing claims —
a full-prefix hit skips prefill and stays bitwise identical to the
cold path, the refcounted cache never exceeds its budget and never
evicts a pinned entry, speculative greedy decode is token-bit-identical
to target-only decode with the per-(version, bucket) program bound at
3, the router places least-loaded with session stickiness, drains for
hot-swap, sheds typed, re-routes streams off dead replicas, and the
heavy-traffic soak holds its p99 budgets under QueueFull pressure."""
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.fleet import (FleetRouter, PrefixCache, Replica,
                             SpeculativeConfig, SpeculativeDecoder,
                             build_replicas, register_fleet_instruments,
                             run_fleet_soak)
from bigdl_tpu.generation import GenerationConfig, GenerationService
from bigdl_tpu.generation.sampling import SamplingParams
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.serving import Degraded, QueueFull, WorkerDied
from bigdl_tpu.utils.random import RandomGenerator


def _model(seed=42, vocab=50, hidden=32, layers=2, heads=4, max_len=32):
    RandomGenerator.set_seed(seed)
    m = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                      num_layers=layers, num_heads=heads,
                      max_len=max_len).evaluate()
    m.ensure_initialized()
    return m


def _service(model=None, **cfg):
    defaults = dict(slots=4, max_len=16, length_buckets=(16,),
                    prefill_rows=2)
    defaults.update(cfg)
    svc = GenerationService(config=GenerationConfig(**defaults))
    svc.load("lm", model if model is not None else _model())
    return svc


def _entry_args(n, length=4, layers=1, heads=2, rung=8, hd=4):
    """Device k/v blocks + logits for one synthetic prefix entry."""
    import jax.numpy as jnp
    k = jnp.full((layers, heads, rung, hd), float(n))
    return (k, k + 1.0, np.full((8,), float(n), np.float32))


# ------------------------------------------------------- prefix cache

def test_prefix_cache_refcount_lru_and_capacity():
    """LRU eviction over refcount-zero entries only; the byte budget
    is NEVER exceeded; an insert that cannot fit after evicting every
    unpinned entry is refused."""
    cache = PrefixCache(max_bytes=4 * 10_000,
                        metrics=telemetry.MetricsRegistry())
    vk = ("m", 1)
    one = _entry_args(0)[0].nbytes * 2 + 32  # ~one entry's bytes
    cache.max_bytes = 3 * one  # room for exactly 3 entries
    e = [cache.insert(vk, [i], *_entry_args(i)) for i in range(3)]
    assert all(x is not None for x in e) and len(cache) == 3
    assert cache.nbytes() <= cache.max_bytes
    # touch 0 so 1 becomes LRU; the next insert evicts exactly 1
    hit = cache.lookup(vk, [0])
    assert hit is e[0]
    cache.release(hit)
    assert cache.insert(vk, [3], *_entry_args(3)) is not None
    assert cache.lookup(vk, [1]) is None  # evicted (the LRU)
    assert cache.lookup(vk, [0]) is not None  # survived (recently used)
    assert cache.nbytes() <= cache.max_bytes
    # pin everything: a further insert is REFUSED, never over-budget
    pins = [cache.lookup(vk, [t]) for t in ([0], [2], [3])]
    assert all(p is not None for p in pins)
    assert cache.insert(vk, [9], *_entry_args(9)) is None
    assert len(cache) == 3 and cache.nbytes() <= cache.max_bytes
    for p in pins:
        cache.release(p)
    # unpinned again: the insert goes through (evicting the LRU)
    assert cache.insert(vk, [9], *_entry_args(9)) is not None
    assert cache.nbytes() <= cache.max_bytes


def test_prefix_eviction_never_frees_a_pinned_entry_under_stress():
    """Randomized reader/writer stress: entries pinned by live readers
    survive every eviction sweep; bytes stay bounded throughout."""
    rng = np.random.RandomState(0)
    cache = PrefixCache(max_bytes=5 * 300,
                        metrics=telemetry.MetricsRegistry())
    one = _entry_args(0, rung=2, hd=2)[0].nbytes * 2 + 32
    cache.max_bytes = 4 * one
    vk = ("m", 1)
    pinned = {}  # token -> entry (live readers)
    for step in range(400):
        t = int(rng.randint(0, 12))
        op = rng.rand()
        if op < 0.45:
            entry = cache.lookup(vk, [t])
            if entry is not None and t not in pinned:
                pinned[t] = entry
            elif entry is not None:
                cache.release(entry)
        elif op < 0.8:
            cache.insert(vk, [t], *_entry_args(t, rung=2, hd=2))
        elif pinned:
            t, entry = pinned.popitem()
            cache.release(entry)
        # invariants, every step
        assert cache.nbytes() <= cache.max_bytes
        for t_live, entry in pinned.items():
            assert entry.refs > 0
            again = cache.lookup(vk, [t_live])
            assert again is entry, \
                "a pinned entry was evicted under a live reader"
            cache.release(again)
    for entry in pinned.values():
        cache.release(entry)


def test_prefix_hit_skips_prefill_and_is_bitwise_identical():
    """A full-prompt hit runs NO prefill program (asserted via the
    engine's prefill-fill histogram) and yields the bit-identical
    greedy stream, sampled-path determinism included."""
    model = _model()
    svc = _service(model, prefix_cache_bytes=1 << 20)
    try:
        prompt = np.array([3, 7, 1, 4, 9], np.int32)
        cold = svc.generate("lm", prompt, max_new_tokens=6).result(60)
        fills_after_cold = len(svc.metrics_registry.histogram(
            "serving/generation/prefill_fill").samples(model="lm"))
        hot = svc.generate("lm", prompt, max_new_tokens=6).result(60)
        fills_after_hot = len(svc.metrics_registry.histogram(
            "serving/generation/prefill_fill").samples(model="lm"))
        assert np.array_equal(cold, hot)
        assert fills_after_hot == fills_after_cold, \
            "a full-prefix hit must not dispatch a prefill batch"
        m = svc.metrics("lm")
        assert m["prefix_hits"] == 1 and m["prefix_misses"] == 1
        # sampled requests seed from the same cached logits: same
        # seed => same stream, hit or miss
        a = svc.generate("lm", prompt, max_new_tokens=6,
                         temperature=0.9, top_k=5, seed=3).result(60)
        b = svc.generate("lm", prompt, max_new_tokens=6,
                         temperature=0.9, top_k=5, seed=3).result(60)
        assert np.array_equal(a, b)
        # reference without any prefix cache: identical bytes
        ref_svc = _service(model)
        try:
            ref = ref_svc.generate("lm", prompt,
                                   max_new_tokens=6).result(60)
        finally:
            ref_svc.shutdown()
        assert np.array_equal(cold, ref)
    finally:
        svc.shutdown()


def test_prefix_hit_ttft_beats_cold_prefill():
    """The latency claim at test scale: across a handful of
    identical-prompt requests, hit TTFT p50 is below cold p50 (the
    bench FLEET row pins the 2x-decode-step acceptance bound at
    measurement shapes)."""
    svc = _service(_model(max_len=64), max_len=64, length_buckets=(64,),
                   prefix_cache_bytes=16 << 20)
    try:
        r = np.random.RandomState(5)
        prompts = [r.randint(1, 50, 48).astype(np.int32)
                   for _ in range(6)]
        cold, hot = [], []
        for leg in (cold, hot):
            for p in prompts:
                s = svc.generate("lm", p, max_new_tokens=2)
                s.result(60)
                leg.append(s.ttft_ms)
        assert float(np.median(hot)) < float(np.median(cold)), \
            (cold, hot)
    finally:
        svc.shutdown()


def test_prefix_unload_drops_version_entries_pinned_ones_at_release():
    cache = PrefixCache(max_bytes=1 << 20,
                        metrics=telemetry.MetricsRegistry())
    v1, v2 = ("m", 1), ("m", 2)
    cache.insert(v1, [1], *_entry_args(1))
    cache.insert(v2, [1], *_entry_args(2))
    pinned = cache.lookup(v1, [1])
    assert pinned is not None
    assert cache.drop_version(v1) == 0  # pinned: doomed, not dropped
    assert cache.lookup(v1, [1]) is None  # doomed entries never hit
    assert cache.lookup(v2, [1]) is not None  # other versions untouched
    cache.release(pinned)  # last reader gone -> entry drops
    assert len(cache) == 1
    # keys are version-scoped: the same tokens under v2 still resolve
    e2 = cache.lookup(v2, [1])
    assert e2 is not None and e2.version_key == v2


# ------------------------------------------------------- speculative

def test_speculative_greedy_bitwise_identical_per_token():
    """The acceptance invariant: speculative greedy output equals
    target-only greedy decode token for token, whatever the draft
    proposes (two prompt shapes, a weak draft AND a strong draft)."""
    target = _model(42)
    weak_draft = _model(7, hidden=16, layers=1, heads=2)
    svc = _service(target, max_len=32, length_buckets=(32,))
    prompts = [np.array([3, 7, 1, 4, 9], np.int32),
               np.array([11, 2], np.int32)]
    try:
        refs = [list(svc.generate("lm", p, max_new_tokens=8).result(60))
                for p in prompts]
    finally:
        svc.shutdown()
    for draft in (weak_draft, target):
        dec = SpeculativeDecoder(target, draft, SpeculativeConfig(
            k=3, slots=4, max_len=32, length_buckets=(32,)))
        outs, stats = dec.generate(prompts, max_new_tokens=8)
        for out, ref in zip(outs, refs):
            assert list(out) == ref, (list(out), ref, stats)
    # the self-draft leg must accept EVERY proposal (p == q): the
    # accepted-token rate gauge is exact, not approximate
    assert stats["accept_rate"] == 1.0
    assert stats["macro_steps"] == 3  # 8 tokens: 1 prefill + 3*k


def test_speculative_seeded_sampling_deterministic():
    target = _model(42)
    draft = _model(7, hidden=16, layers=1, heads=2)
    dec = SpeculativeDecoder(target, draft, SpeculativeConfig(
        k=3, slots=2, max_len=32, length_buckets=(32,)))
    prompts = [np.array([3, 7, 1], np.int32)]
    sp = SamplingParams(temperature=0.8, top_k=5, seed=13)
    a, _ = dec.generate(prompts, max_new_tokens=8, sampling=sp)
    b, _ = dec.generate(prompts, max_new_tokens=8, sampling=sp)
    assert np.array_equal(a[0], b[0]), "same seed must replay exactly"
    c, _ = dec.generate(prompts, max_new_tokens=8,
                        sampling=SamplingParams(temperature=0.8,
                                                top_k=5, seed=14))
    # a different seed draws a different stream (overwhelmingly)
    assert len(c[0]) == 8


def test_speculative_program_bound_at_most_3_per_bucket():
    """K rungs x (prefill + decode + verify) for the target, (prefill
    + decode) for the draft — per (version, bucket) never more than
    3, asserted via the compile counter, and a repeat run compiles
    NOTHING new."""
    target = _model(42)
    draft = _model(7, hidden=16, layers=1, heads=2)
    buckets = (8, 16, 32)
    dec = SpeculativeDecoder(target, draft, SpeculativeConfig(
        k=2, slots=2, max_len=32, length_buckets=buckets))
    prompts = [np.array([3, 7, 1, 4], np.int32),
               np.array([5, 6], np.int32)]
    dec.generate(prompts, max_new_tokens=6)
    with dec.engine._lock:
        keys = {sv: set(ks) for sv, ks in dec.engine._keys.items()}
    for sv_key, ks in keys.items():
        per_bucket = {}
        for k in ks:
            per_bucket.setdefault(k[-1], set()).add(k[-2])
        bound = 3 if sv_key == dec.target.key else 2
        for bucket, kinds in per_bucket.items():
            assert len(kinds) <= bound, (sv_key, bucket, kinds)
    warm = dec.compile_count()
    assert warm <= 3 * len(buckets) + 2 * len(buckets)
    dec.generate(prompts, max_new_tokens=6)
    assert dec.compile_count() == warm, \
        "a repeat speculative run after warmup must never compile"


def test_speculative_rejects_oversized_requests_and_vocab_mismatch():
    target = _model(42)
    with pytest.raises(ValueError):
        SpeculativeDecoder(target, _model(7, vocab=49))
    dec = SpeculativeDecoder(target, _model(7, hidden=16, layers=1,
                                            heads=2),
                             SpeculativeConfig(k=4, slots=2, max_len=16,
                                               length_buckets=(16,)))
    with pytest.raises(ValueError):
        # 10 + 8 + 4 > 16: the verify write would overrun the cache
        dec.generate([np.arange(1, 11, dtype=np.int32)],
                     max_new_tokens=8)


# ------------------------------------------------------------ router

def _fleet(n=2, max_queue=8, **kw):
    metrics = telemetry.MetricsRegistry()
    router = FleetRouter(build_replicas(n, max_queue=max_queue,
                                        metrics=metrics, **kw),
                         metrics=metrics)
    return router, metrics


def test_router_least_loaded_placement_and_session_stickiness():
    router, _ = _fleet(2)
    try:
        prompt = np.array([3, 7, 1], np.int32)
        s1 = router.submit(prompt, session="u1", max_new_tokens=2)
        s1.result(60)
        pin = s1._replica.name
        for _ in range(3):
            s = router.submit(prompt, session="u1", max_new_tokens=2)
            s.result(60)
            assert s._replica.name == pin, "session must stick"
        # a session-less burst spreads: both replicas see traffic
        with faults.armed("serving/decode=delay:10,times:1000"):
            streams = [router.submit(prompt, max_new_tokens=2)
                       for _ in range(6)]
            placed = {s._replica.name for s in streams}
            for s in streams:
                s.result(60)
        assert len(placed) == 2, "least-loaded placement never spread"
    finally:
        router.shutdown()


def test_router_drain_rebalances_and_finishes_held_streams():
    router, _ = _fleet(2)
    try:
        prompt = np.array([3, 7, 1], np.int32)
        s0 = router.submit(prompt, session="u", max_new_tokens=2)
        s0.result(60)
        pin = s0._replica.name
        with faults.armed("serving/decode=delay:20,times:1000"):
            held = router.submit(prompt, session="u", max_new_tokens=8)
            held.first(30)
            router.drain(pin)  # hot-swap rebalance begins
            moved = router.submit(prompt, session="u", max_new_tokens=2)
            out_held = held.result(60)  # drained replica finishes it
            moved.result(60)
        assert held._replica.name == pin
        assert moved._replica.name != pin, \
            "a draining replica took a new session"
        assert len(out_held) == 8
        # resume returns it to rotation
        next(r for r in router.replicas() if r.name == pin).resume()
        assert any(r.accepting() and r.name == pin
                   for r in router.replicas())
    finally:
        router.shutdown()


def test_router_all_shedding_rejects_typed():
    router, _ = _fleet(2)
    try:
        prompt = np.array([3, 7], np.int32)
        for rep in router.replicas():
            for _ in range(rep.breaker.failures):
                rep.breaker.on_failure()
        with pytest.raises(Degraded):
            router.submit(prompt, max_new_tokens=2)
        assert router.metrics()["shed"] == 1
        # recovery: a success closes a breaker and routing resumes
        for rep in router.replicas():
            rep.breaker.on_success()
        assert len(router.submit(prompt,
                                 max_new_tokens=2).result(60)) == 2
    finally:
        router.shutdown()


def test_router_every_queue_full_rejects_typed():
    router, _ = _fleet(2, max_queue=1, slots=1)
    try:
        prompt = np.array([3, 7, 1], np.int32)
        with faults.armed("serving/decode=delay:40,times:1000"):
            streams = []
            with pytest.raises(QueueFull):
                for _ in range(12):  # overrun 2 slots + 2 queue seats
                    streams.append(router.submit(prompt,
                                                 max_new_tokens=8))
            for s in streams:
                s.result(60)
    finally:
        router.shutdown()


def test_router_replica_death_reroutes_bit_identical():
    """Mid-flight death: the stream re-places onto a healthy replica
    and the deduped deterministic replay matches the reference
    byte for byte; eviction counted exactly once."""
    router, metrics = _fleet(2)
    try:
        prompt = np.array([3, 7, 1], np.int32)
        ref = list(router.submit(prompt, max_new_tokens=8).result(60))
        with faults.armed("serving/decode=delay:25,times:1000"):
            router._sessions["x"] = "r0"
            s = router.submit(prompt, session="x", max_new_tokens=8)
            s.first(30)  # tokens already flowing
            next(r for r in router.replicas()
                 if r.name == "r0").kill()
            out = list(s.result(60))
        assert out == ref
        assert s._replica.name == "r1"
        m = router.metrics()
        assert m["evictions"] == 1 and m["reroutes"] == 1
        assert m["states"]["r0"] == "dead"
    finally:
        router.shutdown()


def test_router_injected_kills_reconcile_with_evictions():
    """The chaos contract in-process: every injected fleet/replica
    fault equals one router eviction, counter for counter, and the
    killed replica's requests land elsewhere."""
    router, metrics = _fleet(3)
    try:
        prompt = np.array([3, 7, 1], np.int32)
        ref = list(router.submit(prompt, max_new_tokens=4).result(60))
        with faults.armed(
                "fleet/replica=nth:2,raise:RuntimeError,"
                "match:replica=r1") as sched:
            # pin one session to r1 so its nth:2 submit deterministically
            # reaches the scheduled kill
            router._sessions["doomed"] = "r1"
            outs = []
            for i in range(8):
                s = router.submit(prompt, session="doomed",
                                  max_new_tokens=4)
                outs.append(list(s.result(60)))
            assert all(o == ref for o in outs)
            injected = sched.fired().get("fleet/replica", 0)
        assert injected == 1
        assert router.metrics()["evictions"] == injected
        assert router.metrics()["states"]["r1"] == "dead"
    finally:
        router.shutdown()


def test_fleet_soak_smoke_p99_under_budget_with_breaker_open():
    """The soak invariant at smoke scale: QueueFull pressure reached,
    one replica's breaker open the whole time, every accepted stream
    resolves, p99 TTFT/token under (generous CPU) budgets."""
    report = run_fleet_soak(replicas=2, requests=16, threads=3,
                            max_queue=2, open_breaker_on="r0",
                            ttft_budget_ms=30_000.0,
                            token_budget_ms=10_000.0)
    assert report["passed"], report["violations"]
    assert report["resolved"]["hung"] == 0
    assert report["resolved"]["ok"] > 0
    assert report["breaker_open"] == "r0"
    assert report["ttft_ms_p99"] <= 30_000.0


# --------------------------------------------------------- telemetry

def test_fleet_instruments_pass_the_telemetry_audit():
    r = telemetry.MetricsRegistry()
    inst = register_fleet_instruments(r)
    assert telemetry.audit_names(r) == []
    assert {"hits", "misses", "inserts", "evictions", "requests",
            "shed", "reroutes", "proposed", "accepted",
            "accept_rate"} <= set(inst)
    # a live prefix-enabled service registers only scheme-clean names
    svc = _service(prefix_cache_bytes=1 << 20)
    try:
        svc.generate("lm", [1, 2, 3], max_new_tokens=2).result(60)
        assert telemetry.audit_names(svc.metrics_registry) == []
    finally:
        svc.shutdown()


# ---------------------------------------------------- process replica

@pytest.mark.slow
def test_process_replica_serves_and_dies_typed():
    """The process-hosted replica: same router-facing surface, tokens
    over the pipe; a SIGKILLed process fails its streams TYPED, and
    the router re-routes onto the surviving thread-hosted peer."""
    from bigdl_tpu.fleet import ProcessReplica

    spec = dict(seed=42, vocab_size=32, hidden_size=16, num_layers=1,
                num_heads=2, max_len=16)
    proc = ProcessReplica("p0", spec, slots=2, max_len=16)
    try:
        prompt = np.array([3, 7, 1], np.int32)
        out = proc.submit(prompt, max_new_tokens=4).result(120)
        assert len(out) == 4
        # the same seeded model thread-hosted produces the same bytes
        metrics = telemetry.MetricsRegistry()
        twin = build_replicas(1, seed=42, vocab=32, hidden=16,
                              layers=1, heads=2, max_len=16,
                              metrics=metrics)[0]
        try:
            ref = twin.submit(prompt, max_new_tokens=4).result(60)
            assert np.array_equal(out, ref)
            router = FleetRouter([proc, twin], metrics=metrics)
            s = proc.submit(prompt, max_new_tokens=4)
            proc.kill()
            with pytest.raises(WorkerDied):
                s.result(30)
            # the router routes around the dead process replica
            via = router.submit(prompt, max_new_tokens=4)
            assert np.array_equal(via.result(60), ref)
            assert via._replica.name == "r0"
        finally:
            twin.shutdown()
    finally:
        proc.shutdown(drain=False)


def test_fleet_faultpoints_surface_typed():
    """The new faultpoints: fleet/route fires at the router's submit
    edge (before placement), fleet/verify inside the speculative
    macro step — both surface as typed exceptions, and the decoder's
    slots are released for the next call."""
    router, _ = _fleet(1)
    try:
        with faults.armed("fleet/route=nth:1,raise:OSError"):
            with pytest.raises(OSError):
                router.submit(np.array([1, 2], np.int32),
                              max_new_tokens=2)
        # disarmed: the same submit serves
        assert len(router.submit(np.array([1, 2], np.int32),
                                 max_new_tokens=2).result(60)) == 2
    finally:
        router.shutdown()
    target = _model(42)
    dec = SpeculativeDecoder(target, _model(7, hidden=16, layers=1,
                                            heads=2),
                             SpeculativeConfig(k=2, slots=2, max_len=32,
                                               length_buckets=(32,)))
    prompts = [np.array([3, 7, 1], np.int32)]
    with faults.armed("fleet/verify=nth:1,raise:RuntimeError"):
        with pytest.raises(RuntimeError):
            dec.generate(prompts, max_new_tokens=6)
    outs, _ = dec.generate(prompts, max_new_tokens=6)
    assert len(outs[0]) == 6  # slots were released by the failed run
