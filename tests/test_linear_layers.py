"""Golden tests for linear-algebra layers against numpy/torch references
(the reference's torch/ golden-spec strategy, SURVEY.md §4.2)."""
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.table import T


def test_linear_forward_matches_numpy():
    layer = nn.Linear(5, 3)
    x = np.random.randn(4, 5).astype(np.float32)
    out = np.asarray(layer.forward(x))
    p = layer.get_parameters()
    expect = x @ np.asarray(p["weight"]).T + np.asarray(p["bias"])
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_linear_matches_torch():
    torch = pytest.importorskip("torch")
    layer = nn.Linear(6, 4)
    x = np.random.randn(3, 6).astype(np.float32)
    out = np.asarray(layer.forward(x))
    p = layer.get_parameters()
    tl = torch.nn.Linear(6, 4)
    with torch.no_grad():
        tl.weight.copy_(torch.from_numpy(np.asarray(p["weight"])))
        tl.bias.copy_(torch.from_numpy(np.asarray(p["bias"])))
        expect = tl(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


def test_linear_backward_gradinput():
    layer = nn.Linear(5, 3)
    x = np.random.randn(4, 5).astype(np.float32)
    layer.forward(x)
    grad_out = np.ones((4, 3), np.float32)
    grad_in = np.asarray(layer.backward(x, grad_out))
    p = layer.get_parameters()
    expect = grad_out @ np.asarray(p["weight"])
    np.testing.assert_allclose(grad_in, expect, rtol=1e-5)
    # accumulated param grads
    g = layer.get_grad_parameters()
    np.testing.assert_allclose(np.asarray(g["bias"]), grad_out.sum(0),
                               rtol=1e-5)


def test_bilinear():
    layer = nn.Bilinear(3, 4, 2)
    x1 = np.random.randn(5, 3).astype(np.float32)
    x2 = np.random.randn(5, 4).astype(np.float32)
    out = np.asarray(layer.forward(T(x1, x2)))
    p = layer.get_parameters()
    w, b = np.asarray(p["weight"]), np.asarray(p["bias"])
    expect = np.einsum("bi,kij,bj->bk", x1, w, x2) + b
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_cmul_cadd_broadcast():
    cmul = nn.CMul((1, 4))
    x = np.random.randn(2, 4).astype(np.float32)
    out = np.asarray(cmul.forward(x))
    w = np.asarray(cmul.get_parameters()["weight"])
    np.testing.assert_allclose(out, x * w, rtol=1e-6)

    cadd = nn.CAdd((1, 4))
    out2 = np.asarray(cadd.forward(x))
    b = np.asarray(cadd.get_parameters()["bias"])
    np.testing.assert_allclose(out2, x + b, rtol=1e-6)


def test_mm_mv_dot():
    a = np.random.randn(2, 3, 4).astype(np.float32)
    b = np.random.randn(2, 4, 5).astype(np.float32)
    out = np.asarray(nn.MM().forward(T(a, b)))
    np.testing.assert_allclose(out, a @ b, rtol=1e-5)

    m = np.random.randn(2, 3, 4).astype(np.float32)
    v = np.random.randn(2, 4).astype(np.float32)
    out = np.asarray(nn.MV().forward(T(m, v)))
    np.testing.assert_allclose(out, np.einsum("bij,bj->bi", m, v), rtol=1e-5)

    x = np.random.randn(4, 7).astype(np.float32)
    y = np.random.randn(4, 7).astype(np.float32)
    out = np.asarray(nn.DotProduct().forward(T(x, y)))
    np.testing.assert_allclose(out, (x * y).sum(-1), rtol=1e-5)


def test_cosine_distance_pairwise():
    x = np.random.randn(4, 7).astype(np.float32)
    y = np.random.randn(4, 7).astype(np.float32)
    out = np.asarray(nn.CosineDistance().forward(T(x, y)))
    expect = (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                * np.linalg.norm(y, axis=-1))
    np.testing.assert_allclose(out, expect, rtol=1e-5)

    out2 = np.asarray(nn.PairwiseDistance(2).forward(T(x, y)))
    np.testing.assert_allclose(out2, np.linalg.norm(x - y, axis=-1),
                               rtol=1e-5)


def test_mul_add_constants():
    x = np.random.randn(3, 3).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(nn.MulConstant(2.5).forward(x)), x * 2.5, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(nn.AddConstant(1.5).forward(x)), x + 1.5, rtol=1e-6)


def test_freeze_scales():
    layer = nn.Linear(5, 3).freeze()
    layer.ensure_initialized()
    scales = layer.param_scales(layer.get_parameters())
    assert all(s == 0.0 for s in np.asarray(
        [scales["weight"], scales["bias"]], dtype=object).ravel())
