"""Golden tests for conv/pool/norm against torch (reference torch/ specs)."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn

torch = pytest.importorskip("torch")


def test_spatial_convolution_matches_torch():
    layer = nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1)
    x = np.random.randn(2, 3, 9, 9).astype(np.float32)
    out = np.asarray(layer.forward(x))
    p = layer.get_parameters()
    tconv = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1)
    with torch.no_grad():
        tconv.weight.copy_(torch.from_numpy(np.asarray(p["weight"]).copy()))
        tconv.bias.copy_(torch.from_numpy(np.asarray(p["bias"]).copy()))
        expect = tconv(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_grouped_convolution():
    layer = nn.SpatialConvolution(4, 8, 3, 3, 1, 1, 1, 1, n_group=2)
    x = np.random.randn(2, 4, 5, 5).astype(np.float32)
    out = np.asarray(layer.forward(x))
    p = layer.get_parameters()
    tconv = torch.nn.Conv2d(4, 8, 3, padding=1, groups=2)
    with torch.no_grad():
        tconv.weight.copy_(torch.from_numpy(np.asarray(p["weight"]).copy()))
        tconv.bias.copy_(torch.from_numpy(np.asarray(p["bias"]).copy()))
        expect = tconv(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_dilated_convolution():
    layer = nn.SpatialDilatedConvolution(3, 6, 3, 3, 1, 1, 2, 2, 2, 2)
    x = np.random.randn(1, 3, 10, 10).astype(np.float32)
    out = np.asarray(layer.forward(x))
    p = layer.get_parameters()
    tconv = torch.nn.Conv2d(3, 6, 3, padding=2, dilation=2)
    with torch.no_grad():
        tconv.weight.copy_(torch.from_numpy(np.asarray(p["weight"]).copy()))
        tconv.bias.copy_(torch.from_numpy(np.asarray(p["bias"]).copy()))
        expect = tconv(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_full_convolution_matches_torch_convtranspose():
    layer = nn.SpatialFullConvolution(4, 6, 3, 3, 2, 2, 1, 1, 1, 1)
    x = np.random.randn(2, 4, 5, 5).astype(np.float32)
    out = np.asarray(layer.forward(x))
    p = layer.get_parameters()
    t = torch.nn.ConvTranspose2d(4, 6, 3, stride=2, padding=1,
                                 output_padding=1)
    with torch.no_grad():
        t.weight.copy_(torch.from_numpy(np.asarray(p["weight"]).copy()))
        t.bias.copy_(torch.from_numpy(np.asarray(p["bias"]).copy()))
        expect = t(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_temporal_convolution():
    layer = nn.TemporalConvolution(5, 7, 3, 1)
    x = np.random.randn(2, 9, 5).astype(np.float32)
    out = np.asarray(layer.forward(x))
    assert out.shape == (2, 7, 7)
    p = layer.get_parameters()
    t = torch.nn.Conv1d(5, 7, 3)
    with torch.no_grad():
        t.weight.copy_(torch.from_numpy(np.asarray(p["weight"]).copy()))
        t.bias.copy_(torch.from_numpy(np.asarray(p["bias"]).copy()))
        expect = t(torch.from_numpy(x).transpose(1, 2)).transpose(1, 2).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_max_pool_floor_and_ceil():
    x = np.random.randn(1, 2, 7, 7).astype(np.float32)
    out_floor = np.asarray(nn.SpatialMaxPooling(2, 2, 2, 2).forward(x))
    assert out_floor.shape == (1, 2, 3, 3)
    expect = torch.nn.functional.max_pool2d(torch.from_numpy(x), 2).numpy()
    np.testing.assert_allclose(out_floor, expect, rtol=1e-6)

    out_ceil = np.asarray(nn.SpatialMaxPooling(2, 2, 2, 2).ceil().forward(x))
    assert out_ceil.shape == (1, 2, 4, 4)
    expect_c = torch.nn.functional.max_pool2d(torch.from_numpy(x), 2,
                                              ceil_mode=True).numpy()
    np.testing.assert_allclose(out_ceil, expect_c, rtol=1e-6)


def test_avg_pool_matches_torch():
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    out = np.asarray(nn.SpatialAveragePooling(3, 3, 2, 2, 1, 1).forward(x))
    expect = torch.nn.functional.avg_pool2d(
        torch.from_numpy(x), 3, stride=2, padding=1).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_volumetric_pool_and_conv_shapes():
    x = np.random.randn(1, 2, 6, 8, 8).astype(np.float32)
    out = np.asarray(nn.VolumetricMaxPooling(2, 2, 2).forward(x))
    assert out.shape == (1, 2, 3, 4, 4)
    conv = nn.VolumetricConvolution(2, 4, 3, 3, 3, 1, 1, 1, 1, 1, 1)
    out2 = np.asarray(conv.forward(x))
    assert out2.shape == (1, 4, 6, 8, 8)


def test_batchnorm_train_eval():
    bn = nn.BatchNormalization(4, eps=1e-5, momentum=0.1)
    x = np.random.randn(16, 4).astype(np.float32) * 3 + 1
    bn.training()
    out = np.asarray(bn.forward(x))
    p = bn.get_parameters()
    tbn = torch.nn.BatchNorm1d(4, eps=1e-5, momentum=0.1)
    with torch.no_grad():
        tbn.weight.copy_(torch.from_numpy(np.asarray(p["weight"]).copy()))
        tbn.bias.copy_(torch.from_numpy(np.asarray(p["bias"]).copy()))
    tbn.train()
    expect = tbn(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-3, atol=1e-4)
    # running stats updated like torch
    st = bn.get_state()
    np.testing.assert_allclose(np.asarray(st["running_mean"]),
                               tbn.running_mean.numpy(), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(st["running_var"]),
                               tbn.running_var.numpy(), rtol=1e-3, atol=1e-4)
    # eval mode uses running stats
    bn.evaluate()
    tbn.eval()
    out_e = np.asarray(bn.forward(x))
    expect_e = tbn(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(out_e, expect_e, rtol=1e-3, atol=1e-4)


def test_spatial_batchnorm():
    bn = nn.SpatialBatchNormalization(3)
    x = np.random.randn(4, 3, 5, 5).astype(np.float32)
    out = np.asarray(bn.forward(x))
    p = bn.get_parameters()
    tbn = torch.nn.BatchNorm2d(3)
    with torch.no_grad():
        tbn.weight.copy_(torch.from_numpy(np.asarray(p["weight"]).copy()))
        tbn.bias.copy_(torch.from_numpy(np.asarray(p["bias"]).copy()))
    tbn.train()
    expect = tbn(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-3, atol=1e-4)


def test_cross_map_lrn_matches_torch():
    lrn = nn.SpatialCrossMapLRN(5, 0.0001, 0.75, 1.0)
    x = np.random.rand(2, 7, 4, 4).astype(np.float32)
    out = np.asarray(lrn.forward(x))
    t = torch.nn.LocalResponseNorm(5, alpha=0.0001, beta=0.75, k=1.0)
    expect = t(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_normalize():
    x = np.random.randn(3, 6).astype(np.float32)
    out = np.asarray(nn.Normalize(2).forward(x))
    expect = x / np.linalg.norm(x, axis=1, keepdims=True)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_lookup_table():
    lt = nn.LookupTable(10, 4)
    idx = np.array([[1, 3, 5], [2, 4, 10]], np.float32)
    out = np.asarray(lt.forward(idx))
    w = np.asarray(lt.get_parameters()["weight"])
    np.testing.assert_allclose(out[0, 0], w[0], rtol=1e-6)
    np.testing.assert_allclose(out[1, 2], w[9], rtol=1e-6)
