"""bench.py is the driver's scoreboard — a broken bench is a silent
zero. Smoke-run it at tiny shapes on CPU and check the one-line JSON
contract ({"metric", "value", "unit", "vs_baseline"})."""
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(mode, extra=None):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "BENCH_BATCH": "2",
                "BENCH_SCAN": "1", "BENCH_ITERS": "1",
                "BENCH_WARMUP": "1", "BENCH_MODE": mode,
                "BENCH_FED_POOL": "8", "BENCH_CHUNK_MB": "1",
                "PYTHONPATH": _ROOT + os.pathsep
                + env.get("PYTHONPATH", "")})
    env.update(extra or {})
    r = subprocess.run([sys.executable, os.path.join(_ROOT, "bench.py")],
                       capture_output=True, text=True, timeout=540,
                       env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    return json.loads(line)


@pytest.mark.slow
def test_bench_synthetic_contract():
    out = _run_bench("synthetic")
    assert {"metric", "value", "unit", "vs_baseline"} <= set(out)
    assert out["value"] > 0 and out["unit"] == "images/sec"
    # the regression sentinel's schema handshake: a known version the
    # sentinel accepts (tools/regress.KNOWN_SCHEMA_VERSIONS)
    from bigdl_tpu.tools.regress import KNOWN_SCHEMA_VERSIONS
    assert out["schema_version"] in KNOWN_SCHEMA_VERSIONS


@pytest.mark.slow
def test_bench_programs_row_contract_and_sentinel_accepts_it():
    """The PROGRAMS row: per-model HBM bytes / flops / compile time
    (and MFU once a rate exists) from XLA's own analyses — and the
    regression sentinel must accept the fresh line as a candidate
    against the checked-in trajectory."""
    out = _run_bench("synthetic", {"BENCH_PROGRAMS": "1"})
    assert out["programs_resnet50_train_hbm_bytes"] > 0
    assert out["programs_resnet50_train_flops_per_img"] > 0
    assert out["programs_resnet50_train_compile_s"] > 0
    assert out["programs_resnet50_eval_hbm_bytes"] > 0
    assert out["programs_resnet50_train_mfu"] >= 0
    # train holds grads+opt state: strictly more resident bytes than
    # the eval forward
    assert out["programs_resnet50_train_hbm_bytes"] > \
        out["programs_resnet50_eval_hbm_bytes"]
    # a tiny-shape CPU smoke value regresses hugely vs the banked TPU
    # trajectory by construction, so only the SCHEMA path is asserted
    # here: the sentinel must parse the row and not refuse it
    from bigdl_tpu.tools.regress import extract_metrics
    metrics = extract_metrics(out, "bench-line")
    assert "programs_resnet50_train_hbm_bytes" in metrics


@pytest.mark.slow
def test_bench_rotate_contract():
    out = _run_bench("rotate", {"BENCH_ROTATE_SHARDS": "4"})
    assert out["value"] > 0
    assert out["pool_images"] == 8 and out["hbm_budget_images"] == 4


@pytest.mark.slow
def test_bench_generation_row_contract():
    """The GENERATION row: tokens/sec plus p50/p99 TTFT and per-token
    latency for the TransformerLM decode engine, with the compile
    count carried for the 2K bound."""
    out = _run_bench("synthetic", {
        "BENCH_GEN": "1", "BENCH_GEN_VOCAB": "64",
        "BENCH_GEN_HIDDEN": "32", "BENCH_GEN_LAYERS": "1",
        "BENCH_GEN_LEN": "32", "BENCH_GEN_SLOTS": "2",
        "BENCH_GEN_REQS": "4", "BENCH_GEN_NEW": "4"})
    assert out["transformerlm_generation_tokens_per_sec_per_chip"] > 0
    for key in ("generation_ttft_ms_p50", "generation_ttft_ms_p99",
                "generation_token_ms_p50", "generation_token_ms_p99"):
        assert out[key] >= 0
    # K length-buckets (powers of two up to BENCH_GEN_LEN) => <= 2K
    assert out["generation_compiles"] <= 2 * 6


@pytest.mark.slow
def test_bench_data_row_contract():
    """The DATA row: host-feed vs device-feed steps/sec through the
    datapipe staged windows, TransformerLM packed-vs-padded real
    tokens/sec, and the padding-efficiency pair."""
    out = _run_bench("synthetic", {
        "BENCH_DATA": "1", "BENCH_DATA_K": "2",
        "BENCH_DATA_BATCH": "16", "BENCH_DATA_SEQ": "32",
        "BENCH_DATA_VOCAB": "64", "BENCH_DATA_ROWS": "4"})
    assert out["data_window_k"] == 2
    assert out["data_lenet_devfeed_steps_per_sec"] > 0
    assert out["data_lenet_hostfeed_steps_per_sec"] > 0
    assert out["data_hostfeed_fraction_of_devfeed"] > 0
    assert out["data_tlm_packed_tokens_per_sec"] > 0
    assert out["data_tlm_padded_tokens_per_sec"] > 0
    # packing must beat pad-to-max on slab utilization
    assert out["data_padding_efficiency_packed"] > \
        out["data_padding_efficiency_padded"]


@pytest.mark.slow
def test_bench_precision_row_contract():
    """The PRECISION row: ResNet f32 vs bf16_mixed train imgs/sec,
    TransformerLM tokens/sec both regimes, and f32 vs calibrated-int8
    serving with the accuracy delta the registry gate would enforce.
    On CPU the bf16 ratio is reported, not asserted (bf16 emulates
    slowly off-accelerator); the int8 delta must sit under its gate."""
    out = _run_bench("synthetic", {
        "BENCH_PRECISION": "1", "BENCH_PREC_DEPTH": "8",
        "BENCH_PREC_BATCH": "8", "BENCH_PREC_VOCAB": "64",
        "BENCH_PREC_HIDDEN": "32", "BENCH_PREC_LAYERS": "1",
        "BENCH_PREC_SEQ": "16", "BENCH_PREC_LM_BATCH": "2",
        "BENCH_PREC_GATE_N": "16"})
    for key in ("precision_resnet_f32_imgs_per_sec",
                "precision_resnet_bf16_imgs_per_sec",
                "precision_tlm_f32_tokens_per_sec",
                "precision_tlm_bf16_tokens_per_sec",
                "precision_serving_f32_imgs_per_sec",
                "precision_serving_int8_imgs_per_sec"):
        assert out[key] > 0
    assert out["precision_resnet_bf16_speedup"] > 0
    # the asserted accuracy contract: calibrated int8 top-1 agreement
    # with the float reference stays under the serving gate's bound
    assert out["precision_int8_accuracy_delta"] <= \
        out["precision_int8_gate_max_delta"]


@pytest.mark.slow
def test_bench_zero_row_contract():
    """The ZERO row: imgs/sec and opt_state_bytes_per_chip at ZeRO
    stage 0 vs 2 vs 3 over the data mesh of every device — the stage-2
    bytes must show a real reduction whenever the mesh has more than
    one device (trivially 1.0 on a single-device smoke host)."""
    out = _run_bench("synthetic", {
        "BENCH_ZERO": "1", "BENCH_ZERO_BATCH": "8",
        "BENCH_ZERO_DEPTH": "8",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert out["zero_devices"] >= 1
    for stage in (0, 2, 3):
        assert out[f"zero_stage{stage}_imgs_per_sec"] > 0
        assert out[f"zero_stage{stage}_opt_state_bytes_per_chip"] > 0
    if out["zero_devices"] >= 8:
        assert out["zero_opt_state_reduction_stage2"] >= 4
        assert out["zero_stage3_opt_state_bytes_per_chip"] <= \
            out["zero_stage0_opt_state_bytes_per_chip"] // 4


@pytest.mark.slow
def test_bench_kernels_row_contract_and_sentinel_accepts_it():
    """The KERNELS row: attention-program throughput/MFU and decode
    tokens/sec with the pallas kernel layer on vs off, plus the
    speedup ratio — and the regression sentinel must parse the fresh
    line without refusing it. On CPU the on-legs run the pallas
    interpreter, so only sign/shape is asserted, never a win."""
    out = _run_bench("synthetic", {
        "BENCH_KERNELS": "1", "BENCH_KERNELS_BATCH": "1",
        "BENCH_KERNELS_HEADS": "2", "BENCH_KERNELS_SEQ": "32",
        "BENCH_KERNELS_HEAD_DIM": "8", "BENCH_KERNELS_VOCAB": "64",
        "BENCH_KERNELS_HIDDEN": "32", "BENCH_KERNELS_LAYERS": "1",
        "BENCH_KERNELS_LEN": "32", "BENCH_KERNELS_SLOTS": "2",
        "BENCH_KERNELS_REQS": "4", "BENCH_KERNELS_NEW": "4"})
    for key in ("kernels_attention_tokens_per_sec_on",
                "kernels_attention_tokens_per_sec_off",
                "kernels_decode_tokens_per_sec_on",
                "kernels_decode_tokens_per_sec_off"):
        assert out[key] > 0
    assert out["kernels_decode_speedup"] > 0
    assert out["kernels_attention_mfu_on"] >= 0
    assert out["kernels_attention_mfu_off"] >= 0
    # schema_version=2 stamped => the sentinel parses the row as a
    # candidate instead of refusing it
    from bigdl_tpu.tools.regress import extract_metrics
    metrics = extract_metrics(out, "bench-line")
    assert "kernels_decode_tokens_per_sec_on" in metrics
    assert "kernels_attention_mfu_on" in metrics


@pytest.mark.slow
def test_bench_elastic_row_contract_and_sentinel_accepts_it():
    """The ELASTIC row: checkpoint step-loop stall sync vs async (the
    async stall is the snapshot copy alone), the hidden async write
    tail, and resume-to-first-step seconds — all lower-is-better keys
    the sentinel classifies by its documented suffix rules."""
    out = _run_bench("synthetic", {"BENCH_ELASTIC": "1",
                                   "BENCH_ELASTIC_STEPS": "6"})
    for key in ("elastic_ckpt_stall_ms_sync",
                "elastic_ckpt_stall_ms_async",
                "elastic_ckpt_async_write_ms",
                "elastic_resume_to_first_step_s"):
        assert out[key] > 0, key
    from bigdl_tpu.tools.regress import classify_key, extract_metrics
    metrics = extract_metrics(out, "bench-line")
    for key in ("elastic_ckpt_stall_ms_sync",
                "elastic_ckpt_stall_ms_async",
                "elastic_resume_to_first_step_s"):
        assert key in metrics
        assert classify_key(key) == "lower"


@pytest.mark.slow
def test_bench_fleet_row_contract_and_sentinel_accepts_it():
    """The FLEET row (bigdl_tpu.fleet): goodput-under-load for 1 vs N
    replicas at a fixed p99 TTFT budget, prefix-cache hit vs cold
    TTFT p50 (the acceptance bound: a full-prefix hit costs at most
    2x one decode step — the prefill is GONE), and speculative
    accepted-token rate + tokens/sec on vs off — and the regression
    sentinel accepts the row as a schema_version=2 candidate."""
    out = _run_bench("synthetic", {"BENCH_FLEET": "1",
                                   "BENCH_FLEET_REQS": "12"})
    for key in ("fleet_goodput_tokens_per_sec_1r",
                "fleet_goodput_tokens_per_sec_nr",
                "fleet_prefix_cold_ttft_ms_p50",
                "fleet_prefix_hit_ttft_ms_p50",
                "fleet_token_ms_p50",
                "fleet_spec_tokens_per_sec_on",
                "fleet_spec_tokens_per_sec_off"):
        assert out[key] > 0, key
    assert 0.0 <= out["fleet_spec_accept_rate"] <= 1.0
    # the prefix acceptance bound: a full-prefix hit pays the seed
    # splice + sampling from cached logits — at most 2x one decode
    # step, and strictly cheaper than the cold prefill it replaced
    assert out["fleet_prefix_hit_ttft_ms_p50"] <= \
        2.0 * out["fleet_token_ms_p50"], out
    assert out["fleet_prefix_hit_ttft_ms_p50"] < \
        out["fleet_prefix_cold_ttft_ms_p50"], out
    from bigdl_tpu.tools.regress import KNOWN_SCHEMA_VERSIONS, \
        extract_metrics
    assert out["schema_version"] in KNOWN_SCHEMA_VERSIONS
    metrics = extract_metrics(out, "bench-line")
    for key in ("fleet_goodput_tokens_per_sec_nr",
                "fleet_spec_accept_rate"):
        assert key in metrics


@pytest.mark.slow
def test_bench_longctx_row_contract_and_regress_accepts_it(tmp_path):
    """The LONGCTX row: per-S blockwise-flash vs einsum train-step
    tokens/sec + MFU and chunked-prefill TTFT both ways. The 1 MiB
    VMEM budget makes the smoke shapes over-budget by construction, so
    flash_taken=1 proves the BLOCKWISE route ran (full-row flash is
    not eligible past the budget; declining would have run einsum and
    left the counter flat). The fresh line must ride tools/regress end
    to end: judged against a trajectory of itself it exits 0."""
    chunk, new = 64, 4
    out = _run_bench("synthetic", {
        "BENCH_LONGCTX": "1", "BENCH_LONGCTX_SEQS": "512,1024",
        "BENCH_LONGCTX_BATCH": "1", "BENCH_LONGCTX_HEADS": "2",
        "BENCH_LONGCTX_HEAD_DIM": "8",
        "BENCH_LONGCTX_EINSUM_MAX": "1024",
        "BENCH_LONGCTX_CHUNK": str(chunk), "BENCH_LONGCTX_VOCAB": "64",
        "BENCH_LONGCTX_HIDDEN": "32", "BENCH_LONGCTX_LAYERS": "1",
        "BENCH_LONGCTX_NEW": str(new), "BIGDL_VMEM_BUDGET_MB": "1"})
    for s in (512, 1024):
        # over the 1 MiB budget at these shapes -> blockwise, fused
        assert out[f"longctx_s{s}_flash_taken"] == 1, out
        for key in (f"longctx_s{s}_tokens_per_sec_blockwise",
                    f"longctx_s{s}_tokens_per_sec_einsum",
                    f"longctx_s{s}_blockwise_speedup",
                    f"longctx_s{s}_ttft_ms",
                    f"longctx_s{s}_ttft_ms_einsum"):
            assert out[key] > 0, key
        assert out[f"longctx_s{s}_mfu_blockwise"] >= 0
        # every chunk the prompt needs went through the engine
        prompt_len = s - new
        assert out[f"longctx_s{s}_prefill_chunks"] == \
            -(-prompt_len // chunk), out
    # direction rules: throughput/MFU higher-is-better, TTFT lower
    from bigdl_tpu.tools.regress import classify_key, extract_metrics
    metrics = extract_metrics(out, "bench-line")
    assert classify_key("longctx_s512_tokens_per_sec_blockwise") == \
        "higher"
    assert classify_key("longctx_s512_mfu_blockwise") == "higher"
    assert classify_key("longctx_s512_ttft_ms") == "lower"
    assert "longctx_s1024_blockwise_speedup" in metrics
    # the sentinel gate itself: a 2-point trajectory of this same row
    # plus the row as candidate judges every tracked key ok (exit 0)
    import json as _json

    from bigdl_tpu.tools.regress import main as regress_main
    for i in (1, 2):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            _json.dumps({"parsed": out}))
    cand = tmp_path / "candidate.json"
    cand.write_text(_json.dumps(out))
    rc = regress_main([str(tmp_path / "BENCH_r01.json"),
                       str(tmp_path / "BENCH_r02.json"),
                       "--candidate", str(cand)])
    assert rc == 0


@pytest.mark.slow
def test_bench_tuned_row_contract_and_sentinel_accepts_it():
    """The TUNED row: the autotuner's winner vs the hand-picked
    defaults from ONE prune-then-measure sweep over the bounded smoke
    spaces. The default config is a point IN those spaces, so the
    winner can never lose to it on the same seeded windows — the
    speedup keys are >= 1 by construction, and the regression sentinel
    accepts the fresh line as a schema_version=2 candidate."""
    out = _run_bench("synthetic", {"BENCH_TUNED": "1",
                                   "BENCH_ITERS": "2"})
    for key in ("tuned_train_steps_per_sec",
                "default_train_steps_per_sec",
                "tuned_decode_tokens_per_sec",
                "default_decode_tokens_per_sec"):
        assert out[key] > 0, key
    assert out["tuned_vs_default_train_speedup"] >= 1.0, out
    assert out["tuned_vs_default_serving_speedup"] >= 1.0, out
    from bigdl_tpu.tools.regress import KNOWN_SCHEMA_VERSIONS, \
        extract_metrics
    assert out["schema_version"] in KNOWN_SCHEMA_VERSIONS
    # "per_sec"/"speedup" keys classify higher-is-better in the
    # sentinel's documented suffix rules
    metrics = extract_metrics(out, "bench-line")
    for key in ("tuned_train_steps_per_sec",
                "tuned_vs_default_train_speedup"):
        assert key in metrics


@pytest.mark.slow
def test_bench_control_row_contract_and_regress_accepts_it(tmp_path):
    """The CONTROL row: the chaos ``--control`` ramp leg run
    fault-free — goodput and p99 TTFT while the autoscaler takes the
    fleet 1->N->1 under the two-tenant burst, scale-up reaction time,
    and per-tenant shed fractions. control_passed carries the leg's
    own invariants (typed-only sheds, zero hangs, ramp reached N,
    drained back to 1). The fresh line must ride tools/regress end to
    end: schema_version=2 accepted, goodput classified higher, the
    latencies lower, the shed fractions deliberately unclassified
    (_frac_ spelling — context, not a regression), and judged against
    a trajectory of itself the sentinel exits 0."""
    out = _run_bench("synthetic", {"BENCH_CONTROL": "1",
                                   "BENCH_CONTROL_REPLICAS": "2"})
    assert out["control_passed"] == 1, out
    assert out["control_goodput_tokens_per_sec"] > 0
    assert out["control_ttft_ms_p99"] > 0
    assert out["control_scaleup_reaction_ms"] > 0
    for t in ("gold", "bronze"):
        assert 0.0 <= out[f"control_shed_frac_{t}"] <= 1.0, out
    from bigdl_tpu.tools.regress import (KNOWN_SCHEMA_VERSIONS,
                                         classify_key, extract_metrics)
    assert out["schema_version"] == 2
    assert out["schema_version"] in KNOWN_SCHEMA_VERSIONS
    metrics = extract_metrics(out, "bench-line")
    assert "control_goodput_tokens_per_sec" in metrics
    assert classify_key("control_goodput_tokens_per_sec") == "higher"
    assert classify_key("control_ttft_ms_p99") == "lower"
    assert classify_key("control_scaleup_reaction_ms") == "lower"
    assert classify_key("control_shed_frac_gold") is None
    # the sentinel gate itself: a 2-point trajectory of this same row
    # plus the row as candidate judges every tracked key ok (exit 0)
    from bigdl_tpu.tools.regress import main as regress_main
    for i in (1, 2):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"parsed": out}))
    cand = tmp_path / "candidate.json"
    cand.write_text(json.dumps(out))
    rc = regress_main([str(tmp_path / "BENCH_r01.json"),
                       str(tmp_path / "BENCH_r02.json"),
                       "--candidate", str(cand)])
    assert rc == 0
