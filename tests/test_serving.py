"""Online inference subsystem (bigdl_tpu/serving): micro-batch
coalescing, bucket-padding correctness (pad rows never leak), the
K-bucket compile bound under randomized request sizes, hot-swap
atomicity mid-traffic, admission control (timeout/rejection/drain), a
quantized-model serve smoke test, and serving metrics landing on the
TensorBoard summary path. Everything runs on the conftest's virtual-CPU
platform — threads + queues, no TPU-only APIs."""
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.serving import (BucketLadder, CompileCache, DeadlineExceeded,
                               InferenceService, MicroBatcher, ModelRegistry,
                               QueueFull, ServingConfig)
from bigdl_tpu.utils.random import RandomGenerator


def _mlp(din=12, dout=3, seed=7):
    RandomGenerator.set_seed(seed)
    return (nn.Sequential().add(nn.Linear(din, 16)).add(nn.Tanh())
            .add(nn.Linear(16, dout)).add(nn.LogSoftMax()))


def _const_model(v: float):
    """Shape-preserving model whose every output element is ``v`` — the
    rows of a response identify which model version served it."""
    return (nn.Sequential().add(nn.MulConstant(0.0))
            .add(nn.AddConstant(float(v))))


# ------------------------------------------------------------- ladder

def test_bucket_ladder_powers_of_two_and_custom():
    assert list(BucketLadder(32)) == [1, 2, 4, 8, 16, 32]
    assert list(BucketLadder(24)) == [1, 2, 4, 8, 16, 24]  # max is a rung
    assert list(BucketLadder(1)) == [1]
    custom = BucketLadder(0, buckets=[8, 2, 8, 5])
    assert list(custom) == [2, 5, 8] and custom.max_batch_size == 8
    assert custom.bucket_for(1) == 2 and custom.bucket_for(3) == 5
    assert custom.bucket_for(8) == 8
    with pytest.raises(ValueError):
        custom.bucket_for(9)
    with pytest.raises(ValueError):
        BucketLadder(0)
    with pytest.raises(ValueError):
        BucketLadder(0, buckets=[0, 4])


# -------------------------------------------------- coalescing/padding

def test_single_requests_coalesce_into_few_batches():
    svc = InferenceService(config=ServingConfig(max_batch_size=16,
                                                max_wait_ms=20.0))
    model = _mlp()
    svc.load("m", model, warmup_shape=(12,))
    try:
        xs = np.random.RandomState(0).randn(40, 12).astype(np.float32)
        futs = [svc.predict_async("m", xs[i]) for i in range(40)]
        outs = np.stack([f.result(timeout=30) for f in futs])
        ref = np.asarray(model.forward(xs))
        np.testing.assert_allclose(outs, ref, atol=1e-5)
        m = svc.metrics("m")
        assert m["request_count"] == 40
        # the whole point of the batcher: far fewer forwards than requests
        assert 1 <= m["batch_count"] <= 10
        assert m["batch_fill"] > 0.5
    finally:
        svc.shutdown()


def test_bucket_padding_rows_never_leak_into_results():
    """Randomized request sizes land on padded buckets; every response
    must contain exactly the forward of its own rows."""
    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=1.0))
    model = _mlp(din=6, dout=4)
    svc.load("m", model, warmup_shape=(6,))
    try:
        rng = np.random.RandomState(1)
        reqs = [rng.randn(int(n), 6).astype(np.float32)
                for n in rng.randint(1, 9, size=30)]
        futs = [svc.predict_batch_async("m", x) for x in reqs]
        for x, f in zip(reqs, futs):
            out = f.result(timeout=30)
            assert out.shape[0] == x.shape[0]
            np.testing.assert_allclose(out, np.asarray(model.forward(x)),
                                       atol=1e-5)
    finally:
        svc.shutdown()


def test_oversized_and_empty_requests_rejected():
    svc = InferenceService(config=ServingConfig(max_batch_size=4))
    svc.load("m", _mlp(din=6), warmup_shape=(6,))
    try:
        with pytest.raises(ValueError, match="max_batch_size"):
            svc.predict_batch("m", np.zeros((5, 6), np.float32))
        with pytest.raises(ValueError, match="rows"):
            svc.predict_batch("m", np.zeros((0, 6), np.float32))
        with pytest.raises(KeyError):
            svc.predict("nope", np.zeros(6, np.float32))
    finally:
        svc.shutdown()


# ------------------------------------------------------- compile bound

def test_compile_count_bounded_by_ladder_under_random_sizes():
    """Acceptance: K buckets => at most K compiled programs per model,
    no matter how many distinct request sizes arrive (N >= 100)."""
    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=1.0))
    model = _mlp(din=5, dout=2)
    svc.load("m", model)  # no warmup: compiles happen under traffic
    k = len(svc.ladder)
    try:
        rng = np.random.RandomState(2)
        futs = [svc.predict_batch_async(
                    "m", rng.randn(int(n), 5).astype(np.float32))
                for n in rng.randint(1, 9, size=120)]
        for f in futs:
            f.result(timeout=60)
        assert svc.metrics("m")["request_count"] == 120
        assert 1 <= svc.compile_count("m") <= k
    finally:
        svc.shutdown()


def test_warmup_precompiles_every_bucket():
    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=1.0))
    model = _mlp(din=5, dout=2)
    svc.load("m", model)
    k = len(svc.ladder)
    assert svc.warmup("m", feature_shape=(5,)) == k
    assert svc.compile_count("m") == k
    try:
        rng = np.random.RandomState(3)
        futs = [svc.predict_batch_async(
                    "m", rng.randn(int(n), 5).astype(np.float32))
                for n in rng.randint(1, 9, size=50)]
        for f in futs:
            f.result(timeout=60)
        # warm cache: traffic added ZERO compiles
        assert svc.compile_count("m") == k
        # warming again is free
        assert svc.warmup("m", feature_shape=(5,)) == 0
    finally:
        svc.shutdown()


def test_compile_cache_keys_isolate_versions_and_drop():
    cache = CompileCache()
    model = _mlp(din=4, dout=2)
    params, state = model.get_parameters(), model.get_state()
    ladder = BucketLadder(4)
    assert cache.warmup(("m", 1), model, params, state, (4,),
                        ladder) == len(ladder)
    assert cache.compile_count(("m", 1)) == len(ladder)
    assert cache.compile_count(("m", 2)) == 0  # other versions untouched
    cache.drop(("m", 1))
    assert cache.compile_count(("m", 1)) == 0
    assert cache.compile_count() == 0


# ----------------------------------------------------------- hot swap

def test_hot_swap_atomic_no_mixed_or_dropped_responses():
    """Swap mid-traffic: every response comes wholly from one version,
    requests submitted after the swap see only the new version, and
    request count in == response count out."""
    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=1.0))
    svc.load("m", _const_model(1.0), warmup_shape=(3,))
    swapped = threading.Event()
    stop = threading.Event()
    results, errors = [], []
    lock = threading.Lock()

    def worker(seed):
        rng = np.random.RandomState(seed)
        while not stop.is_set():
            after = swapped.is_set()
            x = rng.randn(int(rng.randint(1, 4)), 3).astype(np.float32)
            try:
                out = svc.predict_batch("m", x, timeout_ms=None)
            except Exception as e:  # noqa: BLE001 — recorded for assert
                with lock:
                    errors.append(e)
                return
            with lock:
                results.append((after, np.asarray(out)))

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    svc.load("m", _const_model(2.0), warmup_shape=(3,))  # atomic swap
    swapped.set()
    time.sleep(0.15)
    stop.set()
    for t in threads:
        t.join()
    svc.shutdown()

    assert not errors, errors  # zero dropped/failed responses
    assert len(results) > 20
    saw = set()
    for after, out in results:
        vals = np.unique(out)
        assert vals.size == 1, f"mixed-version response: {vals}"
        v = float(vals[0])
        assert v in (1.0, 2.0)
        saw.add(v)
        if after:
            # submitted after the swap returned: new version only
            assert v == 2.0
    assert saw == {1.0, 2.0}  # traffic really straddled the swap


def test_registry_swap_back_and_unload_rules():
    reg = ModelRegistry()
    s1 = reg.load("m", _const_model(1.0))
    s2 = reg.load("m", _const_model(2.0))
    assert (s1.version, s2.version) == (1, 2)
    assert reg.current("m") is s2
    assert reg.swap("m", 1) is s1  # roll back
    with pytest.raises(KeyError):
        reg.swap("m", 9)
    with pytest.raises(ValueError, match="current"):
        reg.unload("m", 1)  # serving version is protected
    assert reg.unload("m", 2) == [("m", 2)]
    assert reg.versions("m") == [1]
    desc = reg.describe("m")
    assert desc["current_version"] == 1 and desc["versions"] == [1]
    assert reg.unload("m") == [("m", 1)]  # whole name
    with pytest.raises(KeyError):
        reg.current("m")
    with pytest.raises(ValueError, match="exactly one"):
        reg.load("m")


# --------------------------------------------------- admission control

def test_deadline_exceeded_while_batcher_is_busy():
    release = threading.Event()
    entered = threading.Event()

    def slow_run(x):
        entered.set()
        release.wait(timeout=30)
        return x

    b = MicroBatcher(slow_run, BucketLadder(4), max_wait_ms=1.0,
                     name="slow")
    try:
        f1 = b.submit(np.zeros((1, 2), np.float32))
        assert entered.wait(timeout=10)  # dispatch thread is busy now
        f2 = b.submit(np.zeros((1, 2), np.float32), timeout_ms=30.0)
        time.sleep(0.1)  # f2's deadline passes while slow_run blocks
        release.set()
        assert f1.result(timeout=10).shape == (1, 2)
        with pytest.raises(DeadlineExceeded):
            f2.result(timeout=10)
        with b.stats.lock:
            assert b.stats.timed_out == 1
    finally:
        release.set()
        b.shutdown()


def test_queue_full_rejection():
    release = threading.Event()
    entered = threading.Event()

    def slow_run(x):
        entered.set()
        release.wait(timeout=30)
        return x

    b = MicroBatcher(slow_run, BucketLadder(4), max_wait_ms=1.0,
                     max_queue=1, name="full")
    try:
        f1 = b.submit(np.zeros((1, 2), np.float32))
        assert entered.wait(timeout=10)
        deadline = time.monotonic() + 10
        while b.queue_depth() and time.monotonic() < deadline:
            time.sleep(0.001)  # f1 popped: the queue is drained
        f2 = b.submit(np.zeros((1, 2), np.float32))  # fills the queue
        with pytest.raises(QueueFull):
            b.submit(np.zeros((1, 2), np.float32))
        with b.stats.lock:
            assert b.stats.rejected == 1
        release.set()
        assert f1.result(timeout=10) is not None
        assert f2.result(timeout=10) is not None
    finally:
        release.set()
        b.shutdown()


def test_shutdown_drains_queued_requests():
    calls = []

    def run(x):
        time.sleep(0.02)
        calls.append(x.shape[0])
        return x * 2.0

    b = MicroBatcher(run, BucketLadder(2), max_wait_ms=50.0, name="drain")
    futs = [b.submit(np.full((1, 2), i, np.float32)) for i in range(6)]
    b.shutdown(drain=True)  # flushes everything already queued
    for i, f in enumerate(futs):
        np.testing.assert_allclose(f.result(timeout=0.1),
                                   np.full((1, 2), 2.0 * i))
    with pytest.raises(RuntimeError, match="shut down"):
        b.submit(np.zeros((1, 2), np.float32))


def test_shutdown_without_drain_fails_queued_requests():
    release = threading.Event()
    entered = threading.Event()

    def slow_run(x):
        entered.set()
        release.wait(timeout=30)
        return x

    b = MicroBatcher(slow_run, BucketLadder(1), max_wait_ms=1.0,
                     name="nodrain")
    f1 = b.submit(np.zeros((1, 2), np.float32))
    assert entered.wait(timeout=10)
    f2 = b.submit(np.zeros((1, 2), np.float32))

    def _shutdown():
        b.shutdown(drain=False)

    t = threading.Thread(target=_shutdown)
    t.start()
    time.sleep(0.05)
    release.set()
    t.join(timeout=10)
    assert not t.is_alive()
    assert f1.result(timeout=10) is not None  # in-flight still finishes
    with pytest.raises(RuntimeError, match="shut down"):
        f2.result(timeout=10)


def test_run_batch_errors_propagate_to_futures():
    def boom(x):
        raise RuntimeError("kaboom")

    b = MicroBatcher(boom, BucketLadder(4), max_wait_ms=1.0, name="err")
    try:
        f = b.submit(np.zeros((2, 2), np.float32))
        with pytest.raises(RuntimeError, match="kaboom"):
            f.result(timeout=10)
        with b.stats.lock:
            assert b.stats.errors == 1
    finally:
        b.shutdown()


# ------------------------------------------------- quantized/checkpoint

def test_quantized_model_serves_identically():
    model = _mlp(din=8, dout=4)
    model.evaluate()
    x = np.random.RandomState(4).randn(10, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    svc = InferenceService(config=ServingConfig(max_batch_size=4,
                                                max_wait_ms=1.0))
    svc.load("q", model, quantize=True, warmup_shape=(8,))
    try:
        out = np.stack([svc.predict("q", x[i]) for i in range(10)])
        assert out.shape == ref.shape
        # int8 path: same surface, near-float accuracy
        assert (out.argmax(1) == ref.argmax(1)).mean() >= 0.8
        assert 1 <= svc.compile_count("q") <= len(svc.ladder)
    finally:
        svc.shutdown()


def test_serve_from_saved_checkpoint(tmp_path):
    from bigdl_tpu.utils.serialization import save_module

    model = _mlp(din=6, dout=3)
    model.ensure_initialized()
    save_module(str(tmp_path / "ckpt"), model)
    svc = InferenceService()
    svc.load("m", path=str(tmp_path / "ckpt"), warmup_shape=(6,))
    try:
        x = np.random.RandomState(5).randn(6).astype(np.float32)
        np.testing.assert_allclose(
            svc.predict("m", x), np.asarray(model.forward(x[None]))[0],
            atol=1e-5)
    finally:
        svc.shutdown()


def test_unload_releases_compiled_programs():
    svc = InferenceService(config=ServingConfig(max_batch_size=2,
                                                max_wait_ms=1.0))
    s = svc.load("m", _mlp(din=4, dout=2), warmup_shape=(4,))
    assert svc.cache.compile_count(s.key) == len(svc.ladder)
    svc.unload("m")
    assert svc.cache.compile_count(s.key) == 0
    with pytest.raises(KeyError):
        svc.predict("m", np.zeros(4, np.float32))


# ------------------------------------------------------------- metrics

def test_serving_metrics_land_on_tensorboard_path(tmp_path):
    from bigdl_tpu.visualization import ServingSummary

    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=1.0))
    model = _mlp(din=5, dout=2)
    svc.load("mnist", model, warmup_shape=(5,))
    try:
        rng = np.random.RandomState(6)
        futs = [svc.predict_batch_async(
                    "mnist", rng.randn(int(n), 5).astype(np.float32))
                for n in rng.randint(1, 9, size=25)]
        for f in futs:
            f.result(timeout=30)
        summary = ServingSummary(str(tmp_path), "app")
        svc.export_metrics(summary, step=1)
        svc.export_metrics(summary, step=2)
        for tag in ("serving/mnist/request_count",
                    "serving/mnist/queue_depth",
                    "serving/mnist/batch_fill",
                    "serving/mnist/compile_count",
                    "serving/mnist/latency_ms_p50",
                    "serving/mnist/latency_ms_p99"):
            vals = summary.read_scalar(tag)
            assert [s for s, _, _ in vals] == [1, 2], tag
        (_, reqs, _) = summary.read_scalar(
            "serving/mnist/request_count")[-1]
        assert reqs == 25.0
        (_, fill, _) = summary.read_scalar("serving/mnist/batch_fill")[-1]
        assert 0.0 < fill <= 1.0
        # the serving run dir sits beside train/validation runs
        assert (tmp_path / "app" / "serving").is_dir()
        summary.close()
    finally:
        svc.shutdown()


def test_percentile_summary_shape():
    from bigdl_tpu.utils.profiling import percentile_summary

    assert percentile_summary([]) == {}
    d = percentile_summary([1.0, 2.0, 3.0], (50, 99))
    assert set(d) == {"p50", "p99"} and d["p50"] == 2.0


# -------------------------------------------------- review hardening

def test_mismatched_signature_rejected_at_admission():
    """One malformed request must be rejected at submit — never fail
    the well-formed requests it would have been batched with (and a
    stray dtype must not upcast the batch past the compile bound)."""
    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=20.0))
    model = _mlp(din=6, dout=3)
    svc.load("m", model, warmup_shape=(6,))
    try:
        good = np.zeros((1, 6), np.float32)
        f1 = svc.predict_batch_async("m", good)
        with pytest.raises(ValueError, match="signature"):
            svc.predict_batch("m", np.zeros((1, 4), np.float32))
        with pytest.raises(ValueError, match="signature"):
            svc.predict_batch("m", np.zeros((1, 6), np.float64))
        # the co-batched good request is unharmed
        np.testing.assert_allclose(f1.result(timeout=30),
                                   np.asarray(model.forward(good)),
                                   atol=1e-5)
        assert svc.compile_count("m") == len(svc.ladder)
    finally:
        svc.shutdown()


def test_hot_swap_warms_new_version_before_activation():
    """A hot-swap load must compile every bucket of the NEW version
    before repointing the name — live traffic never hits a cold
    bucket — and activate=False stages a version without serving it."""
    svc = InferenceService(config=ServingConfig(max_batch_size=4,
                                                max_wait_ms=1.0))
    k = len(svc.ladder)
    svc.load("m", _const_model(1.0), warmup_shape=(3,))
    try:
        staged = svc.load("m", _const_model(2.0), activate=False,
                          warmup_shape=(3,))
        assert svc.cache.compile_count(staged.key) == k  # fully warm
        assert svc.registry.current("m").version == 1    # not serving
        assert float(svc.predict("m", np.zeros(3, np.float32))[0]) == 1.0
        svc.swap("m", staged.version)
        assert float(svc.predict("m", np.zeros(3, np.float32))[0]) == 2.0
        # the activate=True path also warms before repointing
        v3 = svc.load("m", _const_model(3.0), warmup_shape=(3,))
        assert svc.cache.compile_count(v3.key) == k
        assert svc.registry.current("m").version == v3.version
    finally:
        svc.shutdown()


def test_concurrent_first_predicts_create_one_batcher():
    """The per-name MicroBatcher owns a dispatch thread: racing first
    requests must not leak extra batchers/threads."""
    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=1.0))
    model = _mlp(din=4, dout=2)
    svc.load("m", model, warmup_shape=(4,))
    start = threading.Barrier(8)
    outs = []

    def first_predict():
        start.wait()
        outs.append(svc.predict("m", np.zeros(4, np.float32)))

    threads = [threading.Thread(target=first_predict) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert len(outs) == 8
        dispatchers = [t for t in threading.enumerate()
                       if t.name == "serving-batcher-m"]
        assert len(dispatchers) == 1, dispatchers
    finally:
        svc.shutdown()


def test_row_reducing_run_batch_fails_loudly():
    """run_batch must return one output row per padded input row — a
    batch-reducing model yields a loud error, not silently empty
    per-request slices."""
    b = MicroBatcher(lambda x: x.sum(axis=0), BucketLadder(4),
                     max_wait_ms=1.0, name="reduce")
    try:
        f = b.submit(np.ones((2, 3), np.float32))
        with pytest.raises(ValueError, match="one output row"):
            f.result(timeout=10)
    finally:
        b.shutdown()


def test_registry_load_does_not_flip_live_module_to_eval():
    """Registering a live module for serving must not mutate it — a
    model still training eagerly elsewhere keeps its train mode (the
    serving step forces training=False on its own)."""
    model = _mlp(din=4, dout=2)
    model.training()
    reg = ModelRegistry()
    reg.load("m", model)
    assert model.train_mode  # caller's module untouched


def test_short_timeout_is_served_on_idle_batcher():
    """A request with timeout_ms <= max_wait_ms must be SERVED on an
    idle service — the dispatch window closes at the deadline exactly
    to serve it, not to expire it."""
    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                max_wait_ms=50.0))
    model = _mlp(din=4, dout=2)
    svc.load("m", model, warmup_shape=(4,))
    try:
        x = np.zeros(4, np.float32)
        out = svc.predict("m", x, timeout_ms=5.0)  # << max_wait_ms
        np.testing.assert_allclose(out, np.asarray(model.forward(x[None]))[0],
                                   atol=1e-5)
        assert svc.metrics("m")["timed_out"] == 0
    finally:
        svc.shutdown()


def test_malformed_first_request_does_not_brick_the_name():
    """The signature is only CONFIRMED by a successful dispatch: a bad
    lone first request fails its own forward and later well-formed
    requests establish theirs and serve normally."""
    svc = InferenceService(config=ServingConfig(max_batch_size=4,
                                                max_wait_ms=1.0))
    model = _mlp(din=6, dout=3)
    svc.load("m", model, warmup_shape=(6,))
    try:
        bad = svc.predict_batch_async("m", np.zeros((1, 4), np.float32))
        with pytest.raises(Exception):
            bad.result(timeout=30)  # its own forward fails...
        good = np.zeros((1, 6), np.float32)
        np.testing.assert_allclose(  # ...but the name still serves
            svc.predict_batch("m", good),
            np.asarray(model.forward(good)), atol=1e-5)
        with pytest.raises(ValueError, match="signature"):
            svc.predict_batch("m", np.zeros((1, 4), np.float32))
    finally:
        svc.shutdown()


def test_registry_activate_false_stages_even_first_version():
    reg = ModelRegistry()
    reg.load("m", _const_model(1.0), activate=False)
    with pytest.raises(KeyError, match="ACTIVE"):
        reg.current("m")
    reg.swap("m", 1)
    assert reg.current("m").version == 1


# ------------------------------------- supervision + breaker (PR5 faults)

def test_worker_death_fails_pending_futures_typed_not_hang():
    """The silent-hang regression: a crash in _take_batch_locked (i.e.
    in the batching machinery, OUTSIDE _dispatch's error handling) used
    to kill the daemon thread and leave every queued future pending
    forever. Supervision must fail them with WorkerDied within the
    deadline — and restart the loop so the batcher keeps serving."""
    from concurrent.futures import TimeoutError as FutTimeout

    from bigdl_tpu import faults
    from bigdl_tpu.serving import WorkerDied

    b = MicroBatcher(lambda x: x, BucketLadder(8), max_wait_ms=20.0,
                     name="sup")
    try:
        with faults.armed("serving/take_batch=nth:1,raise:RuntimeError"):
            futs = [b.submit(np.ones((1, 4), np.float32))
                    for _ in range(3)]
            died = 0
            for f in futs:
                try:
                    f.result(timeout=5)  # a post-restart round may
                    # legitimately serve a late-queued submitter
                except WorkerDied as e:
                    assert "sup" in str(e)
                    died += 1
                except FutTimeout:
                    raise AssertionError(
                        "future hung past deadline — supervision failed")
            # the crashing round's submitters fail typed, never hang
            assert died >= 1
        assert b.stats.worker_restarts == 1
        assert b.stats.worker_failed == died
        # the restarted loop serves new traffic
        out = b.submit(np.ones((2, 4), np.float32)).result(timeout=5)
        assert out.shape == (2, 4)
    finally:
        faults.disarm()
        b.shutdown(drain=False)


def test_circuit_breaker_state_machine_with_fake_clock():
    from bigdl_tpu.serving import CircuitBreaker

    now = [0.0]
    br = CircuitBreaker(failures=3, cooldown_ms=1000.0,
                        clock=lambda: now[0])
    assert br.state == "closed" and br.allow()
    br.on_failure()
    br.on_failure()
    assert br.state == "closed"  # 2 < 3: still closed
    br.on_success()
    br.on_failure()
    br.on_failure()
    br.on_failure()  # 3 consecutive -> open
    assert br.state == "open"
    assert not br.allow()
    now[0] += 0.5
    assert not br.allow()  # cooldown not elapsed
    now[0] += 0.6
    assert br.allow()  # the half-open probe
    assert br.state == "half-open"
    assert not br.allow()  # one probe at a time
    br.on_failure()  # probe failed -> re-open
    assert br.state == "open"
    now[0] += 1.1
    assert br.allow()
    br.on_success()  # probe succeeded -> closed, counters reset
    assert br.state == "closed"
    assert br.allow()


def test_circuit_breaker_rearms_probe_when_outcome_never_arrives():
    """A half-open probe can die before dispatch (queue-full, deadline
    expiry, worker death clearing the queue) — neither on_success nor
    on_failure ever fires. The breaker must admit a fresh probe after
    a cooldown instead of shedding forever."""
    from bigdl_tpu.serving import CircuitBreaker

    now = [0.0]
    br = CircuitBreaker(failures=1, cooldown_ms=1000.0,
                        clock=lambda: now[0])
    br.on_failure()
    assert br.state == "open"
    now[0] += 1.1
    assert br.allow()  # probe admitted... and then vanishes
    assert not br.allow()
    now[0] += 1.1  # a full cooldown with no probe outcome
    assert br.allow()  # re-armed, not permanently Degraded
    br.on_success()
    assert br.state == "closed"


def test_circuit_breaker_half_open_probe_is_single_flight():
    """Two submits racing the open->half-open edge on the SAME clock
    reading must admit exactly ONE probe (regression: the transition
    used to admit without claiming the probe slot, so both racers got
    through and half-open ran two concurrent probes). A zero cooldown
    is the worst case — the vanished-probe re-arm check sees
    now - probe_at == cooldown on the racing thread."""
    from bigdl_tpu.serving import CircuitBreaker

    br = CircuitBreaker(failures=1, cooldown_ms=0.0,
                        clock=lambda: 7.0)  # frozen: a perfect race
    for _ in range(50):
        br.on_failure()  # open; the next allow() half-opens
        admitted = []
        barrier = threading.Barrier(2)

        def racer():
            barrier.wait()
            admitted.append(br.allow())

        threads = [threading.Thread(target=racer) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(admitted) == 1, admitted  # THE probe, exactly once
        br.on_success()  # resolve the probe; next round re-opens


def test_service_sheds_load_when_breaker_opens_and_recovers():
    """End to end: K consecutive dispatch failures open the breaker,
    submits fast-reject with Degraded (counted as shed), and a healthy
    dispatch after the cooldown closes it again."""
    from bigdl_tpu import faults
    from bigdl_tpu.serving import Degraded

    svc = InferenceService(config=ServingConfig(
        max_batch_size=8, max_wait_ms=1.0, buckets=(8,),
        breaker_failures=2, breaker_cooldown_ms=80.0))
    try:
        svc.load("brk", _const_model(1.0))
        x = np.ones((2, 4), np.float32)
        with faults.armed("serving/dispatch=nth:1-2,raise:RuntimeError"):
            # two serial failing batches (submit->resolve each so they
            # cannot coalesce) trip the breaker
            for _ in range(2):
                with pytest.raises(RuntimeError):
                    svc.predict_batch("brk", x, timeout_ms=2000)
            assert svc.breaker_state("brk") == "open"
            with pytest.raises(Degraded):
                svc.predict_batch("brk", x)
        m = svc.metrics("brk")
        assert m["failed_batches"] == 2
        assert m["shed"] == 1
        time.sleep(0.1)  # past the cooldown: half-open probe admitted
        out = svc.predict_batch("brk", x, timeout_ms=2000)
        np.testing.assert_allclose(np.asarray(out), 1.0)
        assert svc.breaker_state("brk") == "closed"
    finally:
        faults.disarm()
        svc.shutdown(drain=False)


def test_swap_faultpoint_failure_leaves_old_version_serving():
    from bigdl_tpu import faults

    svc = InferenceService(config=ServingConfig(max_batch_size=8,
                                                buckets=(8,)))
    try:
        svc.load("m", _const_model(1.0))
        svc.load("m", _const_model(2.0), activate=False)
        x = np.ones((1, 4), np.float32)
        with faults.armed("serving/swap=nth:1,raise:RuntimeError"):
            with pytest.raises(RuntimeError):
                svc.swap("m", 2)
        np.testing.assert_allclose(
            np.asarray(svc.predict_batch("m", x, timeout_ms=2000)), 1.0)
        svc.swap("m", 2)  # disarmed: the swap completes
        np.testing.assert_allclose(
            np.asarray(svc.predict_batch("m", x, timeout_ms=2000)), 2.0)
    finally:
        faults.disarm()
        svc.shutdown(drain=False)
