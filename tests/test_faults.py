"""Fault-injection framework: schedule semantics (nth / seeded prob /
match / times / delay / first-rule-wins determinism), the disarmed
fast-path overhead bound, classified retry + backoff, and the
integration faultpoints (fetch retry, prefetch error channel, batcher
supervision sites are covered in their own suites)."""
import time

import pytest

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.faults import (FaultRule, FaultSchedule, InjectedFault,
                              backoff_delay, classify, parse_schedule,
                              retry_call)


@pytest.fixture(autouse=True)
def _disarmed():
    faults.disarm()
    yield
    faults.disarm()


# ------------------------------------------------------------ schedules

def test_nth_fires_exactly_on_the_nth_call():
    with faults.armed("p/x=nth:3,raise:RuntimeError") as s:
        faults.point("p/x")
        faults.point("p/x")
        with pytest.raises(RuntimeError):
            faults.point("p/x")
        faults.point("p/x")  # past nth: silent again
    assert s.fired() == {"p/x": 1}


def test_nth_range_fires_on_each_call_in_range():
    with faults.armed("p/x=nth:2-3,raise:OSError") as s:
        faults.point("p/x")
        with pytest.raises(OSError):
            faults.point("p/x")
        with pytest.raises(OSError):
            faults.point("p/x")
        faults.point("p/x")
    assert s.total_fired() == 2


def test_seeded_probability_is_deterministic_and_times_capped():
    def run():
        hits = []
        with faults.armed("p/x=prob:0.5,seed:7,times:3"):
            for i in range(30):
                try:
                    faults.point("p/x")
                    hits.append(0)
                except InjectedFault:
                    hits.append(1)
        return hits

    a, b = run(), run()
    assert a == b  # same seed, same schedule -> same injections
    assert sum(a) == 3  # times cap


def test_match_keys_gate_on_call_context():
    with faults.armed("p/x=match:neval=4,raise") as s:
        faults.point("p/x", neval=3)
        with pytest.raises(InjectedFault):
            faults.point("p/x", neval=4)
        faults.point("p/x", neval=5)
    assert s.total_fired() == 1


def test_sibling_rules_on_one_point_count_calls_independently():
    # two nth rules on the same point: each observes EVERY call, so
    # their nth positions are absolute call numbers, not order-dependent
    s = FaultSchedule([
        FaultRule("p/x", nth=2, exc=RuntimeError),
        FaultRule("p/x", nth=4, exc=OSError),
    ])
    with faults.armed(s):
        faults.point("p/x")
        with pytest.raises(RuntimeError):
            faults.point("p/x")
        faults.point("p/x")
        with pytest.raises(OSError):
            faults.point("p/x")
    assert [r.fired for r in s.rules] == [1, 1]


def test_delay_rule_injects_latency_without_raising():
    with faults.armed("p/x=delay:30,times:1") as s:
        t0 = time.perf_counter()
        faults.point("p/x")
        assert time.perf_counter() - t0 >= 0.025
        t0 = time.perf_counter()
        faults.point("p/x")  # times exhausted: no delay
        assert time.perf_counter() - t0 < 0.02
    assert s.total_fired() == 1


def test_injected_counter_labels_by_point():
    c = telemetry.counter("faults/point/injected")
    before = c.value(point="p/ctr")
    with faults.armed("p/ctr=nth:1-2,raise"):
        for _ in range(2):
            with pytest.raises(InjectedFault):
                faults.point("p/ctr")
    assert c.value(point="p/ctr") - before == 2


def test_parse_rejects_malformed_schedules():
    for bad in ("", "p/x", "p/x=wat:1", "p/x=raise:NoSuchError"):
        with pytest.raises(ValueError):
            parse_schedule(bad)


def test_points_are_noops_when_disarmed():
    assert not faults.is_armed()
    faults.point("p/x", neval=1)  # nothing raises, nothing counts


def test_disarmed_point_overhead_bounded():
    """The production contract: a disarmed faultpoint is one module
    flag check (same budget as a disabled telemetry span; real cost
    ~0.2us, bound generous for CI noise)."""
    assert not faults.is_armed()
    n = 50_000
    t0 = time.perf_counter()
    for i in range(n):
        faults.point("train/step", neval=i)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 5e-6, f"{per_call * 1e6:.2f}us per disarmed point"


# ---------------------------------------------------- classified retry

def test_classify_fatal_beats_transient_supertypes():
    assert classify(TypeError("x")) == "fatal"
    assert classify(ValueError("shape")) == "fatal"
    # NotImplementedError IS a RuntimeError; it must still be fatal
    assert classify(NotImplementedError()) == "fatal"
    assert classify(OSError("io")) == "transient"
    assert classify(RuntimeError("xla")) == "transient"
    assert classify(InjectedFault("chaos")) == "transient"
    assert classify(Exception("unknown")) == "transient"


def test_classify_honors_the_bigdl_fatal_marker():
    # CheckpointCorrupt only ESCAPES resume when quarantine is
    # impossible — retrying re-hashes the same corrupt dir, so it must
    # fail fast despite subclassing RuntimeError
    from bigdl_tpu.utils.serialization import CheckpointCorrupt
    assert classify(CheckpointCorrupt("bad digest")) == "fatal"


def test_backoff_doubles_to_cap_with_equal_jitter():
    import random
    rng = random.Random(0)
    ds = [backoff_delay(a, 1.0, 8.0, rng) for a in range(6)]
    for a, d in enumerate(ds):
        full = min(1.0 * 2 ** a, 8.0)
        assert full / 2 <= d <= full
    # deterministic under a seeded rng
    rng2 = random.Random(0)
    assert ds == [backoff_delay(a, 1.0, 8.0, rng2) for a in range(6)]


def test_retry_call_retries_transient_and_counts():
    c = telemetry.counter("io/retry/retries")
    before = c.value()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("blip")
        return "ok"

    slept = []
    assert retry_call(flaky, attempts=4, base_delay_s=0.01,
                      sleep=slept.append) == "ok"
    assert len(calls) == 3 and len(slept) == 2
    assert c.value() - before == 2


def test_retry_call_fails_fast_on_fatal():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("shape mismatch")

    with pytest.raises(ValueError):
        retry_call(broken, attempts=5, base_delay_s=0.01,
                   sleep=lambda s: None)
    assert len(calls) == 1  # no second attempt


def test_retry_call_exhausts_attempts_then_reraises():
    calls = []

    def always():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, attempts=3, base_delay_s=0.01,
                   sleep=lambda s: None)
    assert len(calls) == 3


# ------------------------------------------------- integration points

def test_fetch_download_retries_through_faultpoint(tmp_path):
    """maybe_download survives two injected transient failures and
    removes a stale .part from a prior crashed run (the satellite
    contract)."""
    from bigdl_tpu.dataset.fetch import maybe_download
    src = tmp_path / "payload.bin"
    src.write_bytes(b"corpus-bytes")
    work = tmp_path / "cache"
    work.mkdir()
    stale = work / "got.bin.part"
    stale.write_bytes(b"half-written garbage from a dead process")
    with faults.armed("fetch/download=nth:1-2,raise:OSError") as s:
        out = maybe_download("got.bin", str(work), src.as_uri())
    assert s.total_fired() == 2
    assert open(out, "rb").read() == b"corpus-bytes"
    assert not stale.exists()


def test_fetch_download_exhausted_attempts_raise(tmp_path):
    from bigdl_tpu.dataset.fetch import maybe_download
    src = tmp_path / "payload.bin"
    src.write_bytes(b"x")
    with faults.armed("fetch/download=nth:1-9,raise:OSError"):
        with pytest.raises(OSError):
            maybe_download("got.bin", str(tmp_path / "c"), src.as_uri(),
                           attempts=3)
    assert not (tmp_path / "c" / "got.bin").exists()


def test_prefetch_stage_fault_propagates_to_consumer():
    """An injected staging-thread failure must surface as the
    consumer's exception, never a silent end-of-dataset."""
    import numpy as np

    from bigdl_tpu.dataset.prefetch import device_prefetch
    from bigdl_tpu.dataset.sample import MiniBatch

    batches = [MiniBatch(np.ones((2, 3), np.float32), None)
               for _ in range(4)]
    with faults.armed("prefetch/stage=nth:2,raise:RuntimeError"):
        it = device_prefetch(iter(batches), size=1)
        got = [next(it)]
        with pytest.raises(RuntimeError, match="injected"):
            for b in it:
                got.append(b)
    assert len(got) >= 1


def test_known_points_table_matches_call_sites_exactly():
    """faults.KNOWN_POINTS is the registry docs/robustness.md mirrors:
    every `faults.point("name", ...)` call site in the package must be
    a table entry (no undeclared points), and every table entry must
    have a live call site (no stale rows)."""
    import os
    import re

    import bigdl_tpu

    pkg = os.path.dirname(bigdl_tpu.__file__)
    pat = re.compile(r'faults\.point\(\s*"([a-z0-9_/]+)"')
    found = set()
    for root, _dirs, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                found.update(pat.findall(f.read()))
    declared = set(faults.KNOWN_POINTS)
    assert found - declared == set(), \
        f"faults.point call sites missing from KNOWN_POINTS: " \
        f"{sorted(found - declared)}"
    assert declared - found == set(), \
        f"stale KNOWN_POINTS entries with no call site: " \
        f"{sorted(declared - found)}"
    for name, site in faults.KNOWN_POINTS.items():
        assert "/" in name and site.strip(), name
