"""Hadoop SequenceFile wire compat (reference: dataset/DataSet.scala:
470-552 SeqFileFolder + models/utils/ImageNetSeqFileGenerator.scala —
the interop format for datasets already packed for the reference)."""
import numpy as np
import pytest

from bigdl_tpu.dataset.seqfile import (SequenceFileWriter, _read_vint,
                                       _write_vint, read_seq_image_records,
                                       read_sequence_file,
                                       write_seq_image_shards)


def test_hadoop_vint_wire_vectors():
    """Known WritableUtils.writeVInt encodings (the Hadoop spec)."""
    cases = {
        0: b"\x00", 1: b"\x01", 127: b"\x7f", -112: b"\x90",
        -1: b"\xff",
        128: b"\x8f\x80",          # 1-byte positive: marker -113
        150: b"\x8f\x96",
        255: b"\x8f\xff",
        256: b"\x8e\x01\x00",      # 2-byte positive: marker -114
        65536: b"\x8d\x01\x00\x00",
        -150: b"\x87\x95",         # 1-byte negative: marker -121
    }
    for val, wire in cases.items():
        assert _write_vint(val) == wire, (val, _write_vint(val), wire)
        got, pos = _read_vint(wire, 0)
        assert got == val and pos == len(wire)


def test_sequence_file_roundtrip_with_syncs(tmp_path):
    """Write >2KB of records so sync escapes appear mid-stream, then
    read every record back exactly."""
    path = str(tmp_path / "a.seq")
    rng = np.random.RandomState(0)
    records = [(f"key-{i}".encode(), rng.bytes(rng.randint(10, 400)))
               for i in range(64)]
    with SequenceFileWriter(path) as w:
        for k, v in records:
            w.append(k, v)
    back = list(read_sequence_file(path))
    assert back == records
    # sync escapes really exist (total payload is way past the interval)
    with open(path, "rb") as f:
        raw = f.read()
    assert raw.count(b"\xff\xff\xff\xff") >= 1


def test_sequence_file_header_checks(tmp_path):
    p = tmp_path / "bad.seq"
    p.write_bytes(b"NOTASEQFILE")
    with pytest.raises(ValueError, match="SEQ magic"):
        list(read_sequence_file(str(p)))


def test_imagenet_seq_convention_and_imagefolder_training(tmp_path):
    """Pack a tiny ImageFolder tree into .seq shards, read it back via
    the reference's name\\nlabel convention, and TRAIN from the shards
    through the stock threaded pipeline (ImageFolder-equivalent)."""
    from PIL import Image

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import ImageFolderDataSet
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    rng = np.random.RandomState(0)
    src = tmp_path / "imgs"
    for cls in ("a", "b"):
        d = src / cls
        d.mkdir(parents=True)
        for i in range(6):
            Image.fromarray(rng.randint(0, 255, (20, 20, 3), np.uint8)) \
                .save(d / f"{i}.jpg")

    shards = write_seq_image_shards(str(src), str(tmp_path / "seq"),
                                    num_shards=2)
    assert len(shards) == 2 and all(s.endswith(".seq") for s in shards)

    recs = [r for s in shards for r in read_seq_image_records(s)]
    assert len(recs) == 12
    names = {name for _, _, name in recs}
    labels = {lbl for _, lbl, _ in recs}
    assert labels == {1.0, 2.0}
    assert all(n.endswith(".jpg") for n in names)
    # values are the original JPEG bytes, decodable
    from bigdl_tpu.dataset import decode_image
    img = decode_image(recs[0][0], scale=16)
    assert img.shape[2] == 3

    ds = ImageFolderDataSet(seq_files=shards, batch_size=4, crop=12,
                            scale=16, num_threads=1)
    assert ds.size() == 12
    model = (nn.Sequential().add(nn.Reshape((3 * 12 * 12,)))
             .add(nn.Linear(3 * 12 * 12, 2)).add(nn.LogSoftMax()))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=4)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(3))
    opt.optimize()
    ds.close()
    assert np.isfinite(opt.driver_state["Loss"])


def test_label_only_keys_read():
    """The reference also writes keys that are just the label
    (readLabel's single-part branch, DataSet.scala:499)."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = d + "/x.seq"
        with SequenceFileWriter(path) as w:
            w.append(b"7", b"payload")
        (data, label, name), = read_seq_image_records(path)
        assert (data, label, name) == (b"payload", 7.0, "")
