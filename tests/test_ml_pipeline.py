"""ML-pipeline estimator tests (reference model: DLEstimatorSpec /
DLClassifierSpec + pyspark test_dl_classifier.py)."""
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.ml import DLClassifier, DLEstimator


def _toy_data(n=200, d=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, :3].sum(1) > X[:, 3:].sum(1)).astype(np.float32) + 1.0
    return X, y


def test_dl_classifier_fit_predict_score():
    X, y = _toy_data()
    model = (nn.Sequential().add(nn.Linear(6, 24)).add(nn.ReLU())
             .add(nn.Linear(24, 2)).add(nn.LogSoftMax()))
    clf = DLClassifier(model, nn.ClassNLLCriterion(), batch_size=32,
                       max_epoch=30, learning_rate=0.1)
    fitted = clf.fit(X, y)
    acc = fitted.score(X, y)
    assert acc > 0.8, f"train accuracy only {acc}"
    preds = fitted.predict(X[:5])
    assert set(preds).issubset({1, 2})
    proba = fitted.predict_proba(X[:5])
    np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-5)


def test_dl_estimator_regression():
    rng = np.random.RandomState(1)
    X = rng.randn(128, 4).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    y = (X @ w).reshape(-1, 1)
    model = nn.Sequential().add(nn.Linear(4, 1))
    est = DLEstimator(model, nn.MSECriterion(), batch_size=32,
                      max_epoch=60, learning_rate=0.05,
                      label_size=[1])
    fitted = est.fit(X, y)
    pred = fitted.transform(X)
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.05, f"MSE {mse}"


def test_sklearn_params_contract():
    model = nn.Sequential().add(nn.Linear(2, 2))
    est = DLEstimator(model, nn.MSECriterion())
    params = est.get_params()
    assert params["batch_size"] == 32
    est.set_params(batch_size=64)
    assert est.batch_size == 64


def test_vector_assembler_and_column_fit():
    """VectorAssembler-style column handling (reference ML-pipeline
    featuresCol/labelCol params, DLEstimator.scala:54)."""
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.ml import DLClassifier, VectorAssembler

    rng = np.random.RandomState(0)
    n = 96
    data = {
        "age": rng.rand(n).astype(np.float32),
        "income": rng.rand(n, 2).astype(np.float32),  # multi-dim column
    }
    # label depends on the assembled features
    feats = VectorAssembler(["age", "income"]).transform(data)
    assert feats.shape == (n, 3)
    label = 1.0 + (feats.sum(axis=1) > 1.5).astype(np.float32)
    data["label"] = label

    model = nn.Sequential().add(nn.Linear(3, 2)).add(nn.LogSoftMax())
    est = DLClassifier(model, nn.ClassNLLCriterion(),
                       feature_cols=["age", "income"], label_col="label",
                       batch_size=16, max_epoch=30, learning_rate=0.5)
    fitted = est.fit(data)  # label pulled from the label_col
    acc = fitted.score(feats, label)
    assert acc > 0.85
    # the fitted model accepts the SAME column-wise input
    acc2 = fitted.score(data, label)
    assert acc2 == acc
