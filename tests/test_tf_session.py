"""TF Session training: train an imported (unfrozen) GraphDef with
Variables (reference: utils/tf/Session.scala:53,104-110 BigDLSessionImpl
— Variables become trainable weights, the graph's loss node is
minimized)."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import SGD
from bigdl_tpu.utils.tf_loader import Session, TFModule, parse_graphdef


def _linear_graph():
    """v1 graph: loss = mean((x @ W + b - y)^2) with Variable W, b."""
    with tf.compat.v1.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [32, 4], name="x")
        y = tf.compat.v1.placeholder(tf.float32, [32, 1], name="y")
        W = tf.compat.v1.get_variable(
            "W", initializer=tf.constant(np.zeros((4, 1), np.float32)))
        b = tf.compat.v1.get_variable(
            "b", initializer=tf.constant(np.zeros((1,), np.float32)))
        pred = tf.add(tf.matmul(x, W), b, name="pred")
        tf.reduce_mean(tf.square(pred - y), name="loss")
        return g.as_graph_def().SerializeToString()


def _toy_data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4).astype(np.float32)
    w_true = np.asarray([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    Y = X @ w_true + 0.7
    return X, Y, w_true


def test_session_imports_variables():
    nodes = parse_graphdef(_linear_graph())
    mod = TFModule(nodes, inputs=["x", "y"], outputs=["loss"])
    assert set(mod.variable_init) == {"W", "b"}
    assert mod.variable_init["W"].shape == (4, 1)


def test_session_trains_imported_graph_to_lower_loss():
    X, Y, w_true = _toy_data()
    sess = Session(_linear_graph(), inputs=["x", "y"], loss="loss")

    def batches():
        while True:
            for i in range(0, len(X), 32):
                yield MiniBatch(X[i:i + 32], Y[i:i + 32])

    mod = sess.train(batches(), SGD(learning_rate=0.1),
                     max_iterations=200)
    assert sess.last_loss is not None and sess.last_loss < 1e-2
    # learned weights approach the generating ones
    W = np.asarray(mod.get_parameters()["W"])
    np.testing.assert_allclose(W, w_true, atol=0.05)
    b = float(np.asarray(mod.get_parameters()["b"]).reshape(()))
    assert b == pytest.approx(0.7, abs=0.05)


def test_trained_graph_predicts_through_pred_node():
    X, Y, _ = _toy_data()
    sess = Session(_linear_graph(), inputs=["x", "y"], loss="loss")

    def batches():
        while True:
            for i in range(0, len(X), 32):
                yield MiniBatch(X[i:i + 32], Y[i:i + 32])

    sess.train(batches(), SGD(learning_rate=0.1), max_iterations=200)
    # rebuild an inference view on the SAME trained params
    infer = TFModule(parse_graphdef(_linear_graph()), inputs=["x"],
                     outputs=["pred"])
    infer.set_parameters(sess.module.get_parameters())
    infer.ensure_initialized()
    pred = np.asarray(infer.forward([X[:32], np.zeros((32, 1), np.float32)]))
    np.testing.assert_allclose(pred, Y[:32], atol=0.1)


def test_session_rejects_frozen_graph():
    @tf.function
    def f(x):
        return x * 2.0

    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    conc = f.get_concrete_function(tf.TensorSpec([2], tf.float32))
    gd = convert_variables_to_constants_v2(conc).graph.as_graph_def()
    with pytest.raises(ValueError, match="no Variables"):
        Session(gd.SerializeToString(), inputs=["x"], loss="Identity")


def test_random_initializer_is_evaluated_not_zeroed():
    """tf.truncated_normal initializers must produce non-zero inits (a
    silent zeros fallback would make training fail symmetrically)."""
    with tf.compat.v1.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [8, 4], name="x")
        W = tf.compat.v1.get_variable(
            "W", initializer=tf.random.truncated_normal([4, 3],
                                                        stddev=0.5))
        tf.matmul(x, W, name="out")
        gd = g.as_graph_def().SerializeToString()
    mod = TFModule(parse_graphdef(gd), inputs=["x"], outputs=["out"])
    W0 = mod.variable_init["W"]
    assert W0.shape == (4, 3)
    assert np.abs(W0).max() > 0  # not the zeros fallback


def test_session_epoch_size_enables_epoch_trigger():
    from bigdl_tpu.optim import max_epoch

    X, Y, _ = _toy_data(64)
    sess = Session(_linear_graph(), inputs=["x", "y"], loss="loss")

    def batches():
        while True:
            for i in range(0, len(X), 32):
                yield MiniBatch(X[i:i + 32], Y[i:i + 32])

    sess.train(batches(), SGD(learning_rate=0.05),
               end_trigger=max_epoch(3), epoch_size=2)
    # 2 iters/epoch * 3 epochs = 6 steps, then the trigger fires
    assert sess.module is not None


def test_while_loop_cycle_raises():
    tf.compat.v1.disable_control_flow_v2()
    with tf.compat.v1.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [], name="x")
        tf.while_loop(lambda v: v < 10.0, lambda v: v + 1.0, [x],
                      name="loop")
        gd = g.as_graph_def().SerializeToString()
    tf.compat.v1.enable_control_flow_v2()
    nodes = parse_graphdef(gd)
    out = [n.name for n in nodes if n.op == "Exit"][0]
    mod = TFModule(nodes, inputs=["x"], outputs=[out]).evaluate()
    with pytest.raises(ValueError, match="cycle|Merge"):
        mod.forward(np.asarray(0.0, np.float32))


def test_same_shape_variables_get_distinct_random_inits():
    """Initializer seeding must hash the FULL node name: layer1/kernel vs
    layer2/kernel share their last path component, and suffix-byte seeding
    made them train symmetrically (advisor r2, tf_loader.py:430)."""
    with tf.compat.v1.Graph().as_default() as g:
        tf.compat.v1.placeholder(tf.float32, [8, 4], name="x")
        k1 = tf.compat.v1.get_variable(
            "layer1/kernel", shape=[4, 4],
            initializer=tf.compat.v1.truncated_normal_initializer())
        k2 = tf.compat.v1.get_variable(
            "layer2/kernel", shape=[4, 4],
            initializer=tf.compat.v1.truncated_normal_initializer())
        tf.add(k1, k2, name="out")
        data = g.as_graph_def().SerializeToString()
    mod = TFModule(parse_graphdef(data), inputs=["x"], outputs=["out"])
    v1 = mod.variable_init["layer1/kernel"]
    v2 = mod.variable_init["layer2/kernel"]
    assert v1.shape == v2.shape == (4, 4)
    assert not np.allclose(v1, v2), \
        "same-shape variables received identical random inits"
