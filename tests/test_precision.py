"""Mixed precision as a policy (bigdl_tpu/precision): preset semantics,
the loss-scaler overflow state machine, bf16_mixed short-run loss parity
vs f32, f16 skip-step + master-weights behavior inside the compiled
step, K=1 vs K=8 bit-consistency with the scaler riding the scan carry,
ZeRO stage-2 + bf16 within the documented bound of f32 stage-0, the ONE
int8 calibration path, the registry accuracy gate actually refusing a
bad quantized swap, and shapecheck diagnostics carrying the policy's
dtypes."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import SGD, Optimizer, max_iteration
from bigdl_tpu.optim.optimizer import build_eval_step, build_train_step
from bigdl_tpu.precision import (MASTER_KEY, SCALER_KEY, AccuracyGate,
                                 AccuracyGateError, DynamicLossScaler,
                                 PrecisionPolicy, cast_floating,
                                 matmul_accum_dtype)
from bigdl_tpu.utils.random import RandomGenerator


@pytest.fixture(scope="module")
def devices8():
    d = jax.devices()
    if len(d) < 8:
        pytest.skip("needs 8 virtual devices")
    return d[:8]


# ------------------------------------------------------------- helpers

def _mlp(d_in=8, hidden=16, classes=2):
    return nn.Sequential().add(nn.Linear(d_in, hidden)).add(nn.Tanh()) \
        .add(nn.Linear(hidden, classes)).add(nn.LogSoftMax())


def _batch(n=16, d=8, classes=2, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    y = (rng.randint(0, classes, n) + 1).astype(np.float32)
    return x, y


def _setup_step(policy, scaler=None, seed=3):
    """build_train_step under ``policy`` with the optimizer-state keys
    seeded the way Optimizer.set_precision does it."""
    RandomGenerator.set_seed(seed)
    model = _mlp().training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params = model.get_parameters()
    opt_state = optim.init_state(params)
    if policy.needs_master:
        opt_state[MASTER_KEY] = params
        params = policy.cast_to_param(params)
    if scaler is None and policy.needs_loss_scaling:
        scaler = DynamicLossScaler()
    if scaler is not None and policy.needs_loss_scaling:
        opt_state[SCALER_KEY] = scaler.init_state()
    step = build_train_step(model, nn.ClassNLLCriterion(), optim,
                            precision=policy, loss_scaler=scaler)
    return model, step, params, opt_state, model.get_state()


def _run_steps(policy, steps=12, scaler=None):
    _, step, params, opt, ms = _setup_step(policy, scaler)
    x, y = _batch()
    losses = []
    for i in range(steps):
        params, opt, ms, loss = step(params, opt, ms,
                                     jax.random.PRNGKey(i), 0.1, x, y)
        losses.append(float(loss))
    return losses, params, opt


def _leaves_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


# ------------------------------------------------------ policy object

def test_presets_and_named():
    assert PrecisionPolicy.f32().is_noop
    bf16 = PrecisionPolicy.named("bf16_mixed")
    assert bf16 == PrecisionPolicy.bf16_mixed()
    assert bf16.compute_dtype == jnp.dtype(jnp.bfloat16)
    assert bf16.param_dtype == jnp.dtype(jnp.float32)
    assert not bf16.needs_master and not bf16.needs_loss_scaling
    f16 = PrecisionPolicy.named("f16_mixed")
    assert f16.needs_master and f16.needs_loss_scaling
    assert f16.name == "f16_mixed" and bf16.name == "bf16_mixed"
    with pytest.raises(ValueError, match="unknown precision preset"):
        PrecisionPolicy.named("int4_wishful")


def test_accum_dtype_pinned_to_f32():
    with pytest.raises(ValueError, match="accum_dtype must stay float32"):
        PrecisionPolicy(accum_dtype=jnp.bfloat16)


def test_explicit_loss_scaling_flag_wins():
    assert PrecisionPolicy(compute_dtype=jnp.bfloat16,
                           loss_scaling=True).needs_loss_scaling
    assert not PrecisionPolicy(param_dtype=jnp.float16,
                               compute_dtype=jnp.float16,
                               loss_scaling=False).needs_loss_scaling


def test_cast_floating_skips_non_float_leaves():
    tree = {"w": jnp.ones((2,), jnp.float32),
            "ids": jnp.ones((2,), jnp.int32),
            "flag": jnp.ones((2,), bool)}
    out = cast_floating(tree, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["ids"].dtype == jnp.int32
    assert out["flag"].dtype == jnp.dtype(bool)


def test_matmul_accum_dtype():
    assert matmul_accum_dtype(jnp.bfloat16) == jnp.float32
    assert matmul_accum_dtype(jnp.float16) == jnp.float32
    assert matmul_accum_dtype(jnp.float32) == jnp.dtype(jnp.float32)
    assert matmul_accum_dtype(jnp.float64) == jnp.dtype(jnp.float64)


def test_apply_module_casts_entry_and_exit():
    RandomGenerator.set_seed(1)
    model = nn.Linear(4, 3)
    model.ensure_initialized()
    policy = PrecisionPolicy.bf16_mixed()
    x = jnp.ones((2, 4), jnp.float32)
    out, _ = policy.apply_module(model, model.get_parameters(),
                                 model.get_state(), x)
    # cast-on-exit hands the loss output_dtype (f32) activations
    assert out.dtype == jnp.float32


# ------------------------------------------------- loss-scaler machine

def test_scaler_validates_config():
    with pytest.raises(ValueError):
        DynamicLossScaler(growth_factor=1.0)
    with pytest.raises(ValueError):
        DynamicLossScaler(backoff_factor=1.5)
    with pytest.raises(ValueError):
        DynamicLossScaler(growth_interval=0)


def test_scaler_grows_after_interval_and_resets_counter():
    sc = DynamicLossScaler(init_scale=1024.0, growth_interval=2)
    s = sc.init_state()
    s = sc.next_state(s, jnp.bool_(True))
    assert float(s["scale"]) == 1024.0 and int(s["good_steps"]) == 1
    s = sc.next_state(s, jnp.bool_(True))   # hits the interval: doubles
    assert float(s["scale"]) == 2048.0 and int(s["good_steps"]) == 0
    assert int(s["skipped"]) == 0


def test_scaler_backoff_resets_counter_and_counts_skip():
    sc = DynamicLossScaler(init_scale=1024.0, growth_interval=4)
    s = sc.init_state()
    s = sc.next_state(s, jnp.bool_(True))
    s = sc.next_state(s, jnp.bool_(False))  # overflow: halve, reset
    assert float(s["scale"]) == 512.0
    assert int(s["good_steps"]) == 0
    assert int(s["skipped"]) == 1


def test_scaler_clamps_to_min_and_max():
    sc = DynamicLossScaler(init_scale=2.0, growth_interval=1,
                           min_scale=1.0, max_scale=4.0)
    s = sc.init_state()
    s = sc.next_state(s, jnp.bool_(True))
    s = sc.next_state(s, jnp.bool_(True))
    assert float(s["scale"]) == 4.0      # max clamp
    for _ in range(4):
        s = sc.next_state(s, jnp.bool_(False))
    assert float(s["scale"]) == 1.0      # min clamp


def test_all_finite_probe():
    good = {"a": jnp.ones((3,)), "b": jnp.zeros((2,), jnp.int32)}
    assert bool(DynamicLossScaler.all_finite(good))
    bad = {"a": jnp.asarray([1.0, np.inf]), "b": jnp.ones((2,))}
    assert not bool(DynamicLossScaler.all_finite(bad))
    nan = {"a": jnp.asarray([np.nan])}
    assert not bool(DynamicLossScaler.all_finite(nan))
    assert bool(DynamicLossScaler.all_finite({"i": jnp.ones((2,),
                                                       jnp.int32)}))


def test_scale_and_unscale_roundtrip():
    sc = DynamicLossScaler(init_scale=512.0)
    s = sc.init_state()
    loss = jnp.float32(3.0)
    assert float(sc.scale_loss(loss, s)) == 3.0 * 512.0
    grads = {"w": jnp.full((2,), 512.0 * 0.25)}
    un = sc.unscale(grads, s)
    np.testing.assert_allclose(np.asarray(un["w"]), 0.25)


# -------------------------------------------- compiled-step integration

def test_bf16_mixed_short_run_loss_parity_vs_f32():
    """Seeded 12-step run: bf16_mixed tracks the f32 loss trajectory
    within rounding noise (bf16 shares f32's exponent; the f32 islands
    keep the reductions exact)."""
    l32, p32, _ = _run_steps(PrecisionPolicy.f32())
    lbf, pbf, _ = _run_steps(PrecisionPolicy.bf16_mixed())
    assert abs(l32[-1] - lbf[-1]) < 2e-2
    assert np.mean([abs(a - b) for a, b in zip(l32, lbf)]) < 2e-2
    # params stay f32 at rest under bf16_mixed (no master copy)
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(pbf))


def test_f32_policy_matches_engine_default_bitwise():
    """PrecisionPolicy.f32() compiles the exact pre-policy program: a
    step built with precision=None (the legacy Engine dtype knobs, f32
    in tests) is bit-identical to one built with the explicit f32
    policy."""
    RandomGenerator.set_seed(3)
    model = _mlp().training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params = model.get_parameters()
    x, y = _batch()

    def run(precision):
        # fresh copies: the compiled step DONATES its carry buffers
        p = jax.tree.map(jnp.array, params)
        opt = optim.init_state(p)
        ms = jax.tree.map(jnp.array, model.get_state())
        step = build_train_step(model, nn.ClassNLLCriterion(), optim,
                                precision=precision)
        losses = []
        for i in range(4):
            p, opt, ms, loss = step(p, opt, ms, jax.random.PRNGKey(i),
                                    0.1, x, y)
            losses.append(float(loss))
        return losses, p

    l_legacy, p_legacy = run(None)
    l_f32, p_f32 = run(PrecisionPolicy.f32())
    assert l_legacy == l_f32
    assert _leaves_equal(p_legacy, p_f32)


def test_legacy_engine_low_precision_path_needs_no_master_or_scaler():
    """Regression (review finding): Engine.set_default_dtype(bf16) is
    the PRE-policy configuration surface — precision=None must keep
    training directly on the low-precision params, with no master copy,
    no scaler, and the update running in param dtype."""
    from bigdl_tpu.utils.engine import Engine
    old_d, old_c = Engine.default_dtype(), Engine.compute_dtype()
    try:
        Engine.set_default_dtype(jnp.bfloat16)
        Engine.set_compute_dtype(jnp.bfloat16)
        legacy = PrecisionPolicy.from_engine()
        assert not legacy.needs_master and not legacy.needs_loss_scaling
        RandomGenerator.set_seed(3)
        model = _mlp().training()
        model.ensure_initialized()
        optim = SGD(learning_rate=0.1, momentum=0.9)
        params = model.get_parameters()
        opt_state = optim.init_state(params)  # no dunder keys seeded
        step = build_train_step(model, nn.ClassNLLCriterion(), optim)
        x, y = _batch()
        params, opt_state, ms, loss = step(params, opt_state,
                                           model.get_state(),
                                           jax.random.PRNGKey(0), 0.1,
                                           x, y)
        assert np.isfinite(float(loss))
        for leaf in jax.tree.leaves(params):
            assert leaf.dtype == jnp.bfloat16   # updated in place,
        assert MASTER_KEY not in opt_state      # no f32 master grew
        assert SCALER_KEY not in opt_state
    finally:
        Engine.set_default_dtype(old_d)
        Engine.set_compute_dtype(old_c)


def test_f16_skip_step_on_overflow_backs_off_inside_step():
    """A step with non-finite gradients is SKIPPED inside the compiled
    step: params/opt buffers keep their previous values, the scale
    halves, the growth counter resets, skipped increments."""
    sc = DynamicLossScaler(init_scale=2.0 ** 24, growth_interval=3)
    _, step, params, opt, ms = _setup_step(PrecisionPolicy.f16_mixed(),
                                           sc)
    x, y = _batch()
    before = jax.tree.map(np.asarray, params)
    master_before = jax.tree.map(np.asarray, opt[MASTER_KEY])
    v_before = jax.tree.map(np.asarray, opt["v"])
    params, opt, ms, _ = step(params, opt, ms, jax.random.PRNGKey(0),
                              0.1, x, y)
    ss = opt[SCALER_KEY]
    assert float(ss["scale"]) == 2.0 ** 23        # halved
    assert int(ss["good_steps"]) == 0             # counter reset
    assert int(ss["skipped"]) == 1
    assert _leaves_equal(before, params)          # step skipped
    assert _leaves_equal(master_before, opt[MASTER_KEY])
    assert _leaves_equal(v_before, opt["v"])      # moments skipped too


def test_f16_master_copy_updates_and_casts_down():
    """Finite f16 steps: the f32 master copy advances and the at-rest
    f16 params are exactly the master cast down."""
    sc = DynamicLossScaler(init_scale=128.0, growth_interval=50)
    _, step, params, opt, ms = _setup_step(PrecisionPolicy.f16_mixed(),
                                           sc)
    x, y = _batch()
    before = jax.tree.map(np.asarray, opt[MASTER_KEY])
    for i in range(3):
        params, opt, ms, loss = step(params, opt, ms,
                                     jax.random.PRNGKey(i), 0.1, x, y)
    assert np.isfinite(float(loss))
    assert int(opt[SCALER_KEY]["skipped"]) == 0
    assert not _leaves_equal(before, opt[MASTER_KEY])
    for p, m in zip(jax.tree.leaves(params),
                    jax.tree.leaves(opt[MASTER_KEY])):
        assert p.dtype == jnp.float16
        assert m.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(p),
                                      np.asarray(m, np.float16))


def test_missing_scaler_or_master_state_raises():
    """Direct build_train_step users get a clear trace-time error when
    the policy needs state they did not seed."""
    RandomGenerator.set_seed(3)
    model = _mlp().training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.1)
    params = model.get_parameters()
    opt_state = optim.init_state(params)  # no SCALER_KEY / MASTER_KEY
    step = build_train_step(model, nn.ClassNLLCriterion(), optim,
                            precision=PrecisionPolicy.f16_mixed())
    x, y = _batch()
    with pytest.raises(ValueError, match="scaler state"):
        step(params, opt_state, model.get_state(),
             jax.random.PRNGKey(0), 0.1, x, y)


def test_eval_step_runs_compute_dtype_casts_output():
    RandomGenerator.set_seed(3)
    model = _mlp().evaluate()
    model.ensure_initialized()
    ev = build_eval_step(model, precision=PrecisionPolicy.bf16_mixed())
    x, _ = _batch()
    out = ev(model.get_parameters(), model.get_state(), x)
    assert out.dtype == jnp.float32   # output_dtype — what scoring sees


# -------------------------------------------------- Optimizer surface

def _toy_ds(n=256, d=16, classes=4, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d).astype(np.float32) * 3
    X = np.stack([centers[i % classes]
                  + rng.randn(d).astype(np.float32) * 0.5
                  for i in range(n)])
    y = np.array([i % classes + 1 for i in range(n)], np.float32)
    return DataSet.array([Sample(X[i], y[i]) for i in range(n)]) \
        .transform(SampleToMiniBatch(batch))


def _mlp16():
    return nn.Sequential().add(nn.Linear(16, 32)).add(nn.Tanh()) \
        .add(nn.Linear(32, 4)).add(nn.LogSoftMax())


def _run_optimizer(k=1, precision=None, scaler=None, zero=None,
                   mesh=None, iters=8, seed=7):
    RandomGenerator.set_seed(seed)
    opt = Optimizer(_mlp16(), _toy_ds(), nn.ClassNLLCriterion(),
                    batch_size=32, mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
    opt.set_end_when(max_iteration(iters))
    opt.set_steps_per_sync(k)
    if precision is not None:
        opt.set_precision(precision, scaler)
    if zero is not None:
        from bigdl_tpu.parallel import ZeroConfig
        opt.set_zero(ZeroConfig(stage=zero))
    model = opt.optimize()
    return [np.asarray(l)
            for l in jax.tree_util.tree_leaves(model.get_parameters())]


def test_set_precision_validates_inputs():
    opt = Optimizer(_mlp16(), _toy_ds(), nn.ClassNLLCriterion())
    with pytest.raises(ValueError, match="unknown precision preset"):
        opt.set_precision("fp4")
    with pytest.raises(TypeError, match="PrecisionPolicy"):
        opt.set_precision(16)
    with pytest.raises(TypeError, match="DynamicLossScaler"):
        opt.set_precision("f16_mixed", scaler="big")
    assert opt.set_precision("bf16_mixed") is opt     # fluent
    assert opt.set_precision(None) is opt             # revert


def test_k1_vs_k8_bit_identical_with_scaler_in_carry():
    """set_precision composes with set_steps_per_sync: the f16 loss
    scaler's state rides the donated scan carry, and the K=8 fused
    window is bit-identical to the per-step loop — overflow/backoff
    transitions included."""
    sc = DynamicLossScaler(init_scale=256.0, growth_interval=4)
    p1 = _run_optimizer(k=1, precision="f16_mixed", scaler=sc)
    p8 = _run_optimizer(k=8, precision="f16_mixed", scaler=sc)
    for a, b in zip(p1, p8):
        np.testing.assert_array_equal(a, b)


def test_zero2_bf16_within_bound_of_f32_stage0(devices8):
    """set_precision composes with set_zero: stage-2 bf16 gradients
    reduce-scatter in bf16 and the f32-accumulated update lands within
    the documented 5e-3 short-run bound of the f32 stage-0 reference
    (docs/precision.md — measured ~2e-4 at this scale)."""
    from bigdl_tpu.parallel import make_mesh
    mesh = make_mesh([8], ["data"], devices8)
    p0 = _run_optimizer(mesh=mesh)
    pz = _run_optimizer(mesh=mesh, precision="bf16_mixed", zero=2)
    err = max(float(np.abs(a - b).max()) for a, b in zip(p0, pz))
    assert err < 5e-3, f"zero2+bf16 err {err}"


def test_precision_gauges_exported():
    """train/precision/* gauges carry the policy, the scale and the
    skip count after an f16 run (loss-scale trajectory is host-visible
    at every sync)."""
    sc = DynamicLossScaler(init_scale=256.0, growth_interval=4)
    _run_optimizer(k=2, precision="f16_mixed", scaler=sc, iters=4)
    g = telemetry.gauge("train/precision/policy_info")
    assert g.value(policy="f16_mixed", param="float16",
                   compute="float16", accum="float32") == 1.0
    assert telemetry.gauge("train/precision/loss_scale").value() > 0
    assert telemetry.gauge("train/precision/skipped_steps").value() >= 0
    # the f32-equivalent "before" bytes: params are f16 at rest, so the
    # counterfactual f32 layout must cost ~2x the measured one
    f32b = telemetry.gauge(
        "train/precision/params_f32_bytes_per_chip").value()
    realb = telemetry.gauge(
        "train/memory/params_bytes_per_chip").value()
    assert f32b > realb


# ------------------------------------- calibration + serving int8 gate

def test_scale_estimation_single_path():
    """ops/quant.quantize_symmetric == scale_from_amax +
    quantize_with_scale — the ONE max-abs rule every consumer shares."""
    from bigdl_tpu.ops.quant import (quantize_symmetric,
                                     quantize_with_scale, scale_from_amax)
    w = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    q, scale = quantize_symmetric(w, axis=0)
    amax = np.max(np.abs(w), axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(scale),
                               np.asarray(scale_from_amax(amax)),
                               rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(q), np.asarray(quantize_with_scale(w, scale)))


def test_collect_activation_scales_records_input_peaks():
    from bigdl_tpu.ops.quant import scale_from_amax
    from bigdl_tpu.precision.calibrate import collect_activation_scales
    RandomGenerator.set_seed(5)
    lin = nn.Linear(4, 3)
    model = nn.Sequential().add(lin)
    model.evaluate()
    model.ensure_initialized()
    b1 = np.full((2, 4), 2.0, np.float32)
    b2 = np.full((2, 4), -5.0, np.float32)
    scales = collect_activation_scales(model, [b1, b2])
    assert set(scales) == {id(lin)}
    np.testing.assert_allclose(scales[id(lin)],
                               float(np.asarray(scale_from_amax(5.0))),
                               rtol=1e-6)
    # the transient recording wrapper must be gone afterwards
    assert "apply" not in lin.__dict__


def test_collect_activation_scales_validates():
    from bigdl_tpu.precision.calibrate import collect_activation_scales
    model = nn.Sequential().add(nn.Tanh())
    with pytest.raises(ValueError, match="no quantizable layers"):
        collect_activation_scales(model, [np.ones((1, 4), np.float32)])
    lin_model = nn.Sequential().add(nn.Linear(4, 3))
    lin_model.ensure_initialized()
    with pytest.raises(ValueError, match="at least one batch"):
        collect_activation_scales(lin_model, [])


def test_quantized_linear_calibrated_close_to_dynamic():
    """A representative static activation scale reproduces the dynamic
    per-batch estimate within quantization noise — and skips the amax
    reduce on the hot path."""
    from bigdl_tpu.ops.quant import quantized_linear, quantize_symmetric
    rng = np.random.RandomState(2)
    x = rng.randn(8, 16).astype(np.float32)
    w = rng.randn(4, 16).astype(np.float32) * 0.1
    w_q, w_s = quantize_symmetric(w, axis=0)
    dyn = np.asarray(quantized_linear(x, w_q, w_s))
    static_scale = float(np.max(np.abs(x))) / 127.0
    cal = np.asarray(quantized_linear(x, w_q, w_s, x_scale=static_scale))
    ref = x @ w.T
    assert np.abs(cal - ref).max() < 0.1
    assert np.abs(cal - dyn).max() < 0.1


def test_registry_calibrated_gated_load_passes_and_records_delta():
    from bigdl_tpu.serving.registry import ModelRegistry
    RandomGenerator.set_seed(5)
    model = nn.Sequential().add(nn.Linear(8, 32)).add(nn.ReLU()) \
        .add(nn.Linear(32, 4))
    model.evaluate()
    model.ensure_initialized()
    rng = np.random.RandomState(1)
    calib = [rng.randn(16, 8).astype(np.float32) for _ in range(2)]
    gate = AccuracyGate(inputs=rng.randn(64, 8).astype(np.float32),
                        max_delta=0.05)
    reg = ModelRegistry()
    sv = reg.load("prec_ok", model, quantize=True, calibration=calib,
                  accuracy_gate=gate)
    assert reg.current("prec_ok").version == sv.version
    # delta gauge recorded (near-misses visible on dashboards too)
    assert telemetry.gauge("serving/precision/accuracy_delta") \
        .value(model="prec_ok") <= 0.05


def test_registry_refuses_swap_when_gate_trips():
    """The acceptance-criteria path: a quantized candidate calibrated on
    unrepresentative batches (activations clip hard at serve range)
    exceeds the gate bound — the load raises, nothing is registered,
    the old state keeps serving."""
    from bigdl_tpu.serving.registry import ModelRegistry
    RandomGenerator.set_seed(5)
    model = nn.Sequential().add(nn.Linear(8, 32)).add(nn.ReLU()) \
        .add(nn.Linear(32, 4))
    model.evaluate()
    model.ensure_initialized()
    rng = np.random.RandomState(1)
    bad_calib = [rng.randn(16, 8).astype(np.float32) * 1e-4
                 for _ in range(2)]
    gate = AccuracyGate(inputs=rng.randn(64, 8).astype(np.float32) * 50,
                        max_delta=0.02)
    reg = ModelRegistry()
    with pytest.raises(AccuracyGateError, match="exceeds the gate"):
        reg.load("prec_bad", model, quantize=True,
                 calibration=bad_calib, accuracy_gate=gate)
    assert "prec_bad" not in reg.names()     # nothing staged
    # the near-miss delta still lands in the gauge
    assert telemetry.gauge("serving/precision/accuracy_delta") \
        .value(model="prec_bad") > 0.02


def test_registry_gate_requires_quantize():
    from bigdl_tpu.serving.registry import ModelRegistry
    model = nn.Linear(4, 2)
    with pytest.raises(ValueError, match="quantize=True"):
        ModelRegistry().load("f", model, calibration=[np.ones((1, 4))])


def test_diagnose_precision_section():
    """tools/diagnose renders the precision section from the registry
    snapshot: policy dtypes, loss-scale (with trajectory from snapshot
    history), skipped steps, and the params/opt bytes before/after."""
    from bigdl_tpu.tools.diagnose import (_precision_lines,
                                          precision_summary)
    sc = DynamicLossScaler(init_scale=256.0, growth_interval=4)
    _run_optimizer(k=2, precision="f16_mixed", scaler=sc, iters=4)
    snap = telemetry.registry().snapshot()
    prec = precision_summary(snap, history=[snap])
    assert prec["policy"]["policy"] == "f16_mixed"
    assert prec["policy"]["compute"] == "float16"
    assert prec["loss_scale"] > 0
    assert len(prec["loss_scale_trajectory"]) == 2
    assert prec["skipped_steps"] >= 0
    assert prec["params_bytes_ratio_vs_f32"] < 1.0  # f16 at rest
    lines = "\n".join(_precision_lines(prec))
    assert "policy: f16_mixed" in lines
    assert "loss_scale:" in lines and "trajectory" in lines
    assert "bytes/chip" in lines


# ------------------------------------------------- shapecheck surface

def test_shapecheck_diagnostics_carry_policy_dtypes():
    from bigdl_tpu.analysis import spec
    bad = nn.Sequential().add(nn.Linear(16, 32)).add(nn.Linear(8, 4))
    report = bad.check(spec((None, 16), np.float32),
                       raise_on_error=False,
                       policy=PrecisionPolicy.bf16_mixed())
    assert not report.ok
    d = report.diagnostics[0]
    assert d.policy and "bf16_mixed" in d.policy
    assert "compute=bfloat16" in d.policy
    assert "[policy:" in str(d)
    # the traced input really was compute dtype
    assert "bfloat16" in (d.input_shapes or "")


def test_shapecheck_ok_model_traces_under_policy():
    from bigdl_tpu.analysis import spec
    ok = nn.Sequential().add(nn.Linear(16, 32)).add(nn.Tanh()) \
        .add(nn.Linear(32, 4))
    report = ok.check(spec((None, 16), np.float32),
                      policy=PrecisionPolicy.bf16_mixed())
    assert report.ok
