"""ZeRO-2/3 weight-update sharding (parallel/zero.py) on the 8-device
virtual mesh: seeded stage-0 vs stage-1/2/3 runs match within the
grad_err bound (DP and DP×TP), the donated scan carry holds the SHARDED
optimizer state with K=1 vs K=8 bit-consistency, the compiled window
places every collective inside the scan body (HLO counts), the
per-chip memory gauges show the n-fold reduction, and a checkpoint
written under ZeRO resumes — same config bit-identically, and onto a
different stage or mesh width."""
import os
import shutil
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import SGD, Adam, Optimizer, max_iteration
from bigdl_tpu.optim.optimizer import build_train_step
from bigdl_tpu.optim.trigger import several_iteration
from bigdl_tpu.parallel import (ZeroConfig, collective_counts, make_mesh,
                                place_zero_state, reduce_scatter_evidence,
                                shard_zero_tree, tree_bytes_per_chip,
                                tree_zero_specs, window_collectives)
from bigdl_tpu.parallel.zero import extend_spec
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils.serialization import host_value


@pytest.fixture(scope="module")
def devices8():
    d = jax.devices()
    if len(d) < 8:
        pytest.skip("needs 8 virtual devices")
    return d[:8]


# ------------------------------------------------------------- helpers

def _tree_err(a, b):
    return max(float(np.abs(np.asarray(x) - np.asarray(y)).max())
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _lm(seed=3):
    from bigdl_tpu.models import TransformerLM
    RandomGenerator.set_seed(seed)
    m = TransformerLM(vocab_size=64, hidden_size=32, num_layers=1,
                      num_heads=4, max_len=16).training()
    m.ensure_initialized()
    return m


def _lm_batch(dp_rows=16):
    tok = np.random.RandomState(0).randint(0, 64, (dp_rows, 16))
    tgt = np.random.RandomState(1).randint(0, 64, (dp_rows, 16))
    return tok, tgt


#: (stage, with_rules, optim_cls) -> (host params, opt_state, losses);
#: each seeded run compiles once and several tests read it, so the
#: module stays inside the tier-1 time budget
_RUN_CACHE = {}


def _run_lm_cached(mesh, stage, rules=None, optim_cls=SGD):
    key = (stage, rules is not None, optim_cls)
    if key not in _RUN_CACHE:
        _RUN_CACHE[key] = _run_lm_steps(mesh, stage, rules=rules,
                                        optim_cls=optim_cls)
    return _RUN_CACHE[key]


def _run_lm_steps(mesh, stage, rules=None, optim_cls=SGD, steps=2):
    """Seeded TransformerLM training at one ZeRO stage; returns
    (host params, placed opt_state, per-step losses)."""
    model = _lm()
    if optim_cls is SGD:
        optim = SGD(learning_rate=0.1, momentum=0.9)
    else:
        optim = optim_cls(learning_rate=0.01)
    cfg = ZeroConfig(stage=stage) if stage else None
    params = model.get_parameters()
    opt_state = optim.init_state(params)
    repl = NamedSharding(mesh, P())
    params, opt_state = place_zero_state(params, opt_state, mesh, cfg,
                                         rules)
    mstate = jax.device_put(model.get_state(), repl)
    dp = mesh.shape["data"]
    tok, tgt = _lm_batch(2 * dp)
    bsh = NamedSharding(mesh, P("data"))
    x = jax.device_put(jnp.asarray(tok), bsh)
    y = jax.device_put(jnp.asarray(tgt), bsh)
    step = build_train_step(model, nn.SequenceCrossEntropyCriterion(),
                            optim, zero=cfg, mesh=mesh,
                            sharding_rules=rules)
    losses = []
    for i in range(steps):
        params, opt_state, mstate, loss = step(
            params, opt_state, mstate, jax.random.PRNGKey(i), 0.1, x, y)
        losses.append(float(loss))
    return jax.tree.map(host_value, params), opt_state, losses


# ------------------------------------------------- config + spec engine

def test_zero_config_validates_stage():
    with pytest.raises(ValueError):
        ZeroConfig(stage=4)
    assert ZeroConfig(stage=2).data_axis == "data"


def test_zero_config_active_on(devices8):
    mesh = make_mesh([8], ["data"], devices8)
    tp_only = make_mesh([1, 8], ["data", "model"], devices8)
    assert ZeroConfig(stage=2).active_on(mesh)
    assert not ZeroConfig(stage=0).active_on(mesh)
    assert not ZeroConfig(stage=2).active_on(None)
    assert not ZeroConfig(stage=2).active_on(tp_only)  # data axis is 1


def test_extend_spec_takes_first_free_divisible_dim():
    assert extend_spec(P(), (16, 4), 8, "data") == P("data", None)
    assert extend_spec(P(), (3, 8), 8, "data") == P(None, "data")
    assert extend_spec(P(), (3,), 8, "data") == P()          # indivisible
    assert extend_spec(P(), (), 8, "data") == P()            # scalar
    # TP already consumed a dim: ZeRO takes the next free one
    assert extend_spec(P("model", None), (16, 8), 8, "data") \
        == P("model", "data")
    # TP rules already using the data axis are left alone
    assert extend_spec(P("data", None), (16, 8), 8, "data") \
        == P("data", None)


def test_tree_zero_specs_every_leaf_explicit(devices8):
    mesh = make_mesh([8], ["data"], devices8)
    tree = {"m": {"w": jnp.zeros((16, 4)), "b": jnp.zeros((3,))},
            "t": jnp.zeros((), jnp.int32)}
    specs = tree_zero_specs(tree, mesh, ZeroConfig(stage=2))
    assert specs["m"]["w"] == P("data", None)
    assert specs["m"]["b"] == P()
    assert specs["t"] == P()  # scalar step counter: explicit, replicated


def test_shard_zero_tree_annotates_every_leaf(devices8):
    mesh = make_mesh([8], ["data"], devices8)
    tree = {"v": {"w": jnp.zeros((16, 4))}, "t": jnp.zeros((), jnp.int32)}
    out = shard_zero_tree(tree, mesh, ZeroConfig(stage=1))
    for leaf in jax.tree.leaves(out):
        assert isinstance(leaf.sharding, NamedSharding)
    assert out["v"]["w"].sharding.spec == P("data", None)
    assert out["t"].sharding.spec == P()


# -------------------------------------------------- stage equivalence

def test_stage_equivalence_dp(devices8):
    """Seeded stage-0 vs stage-1/2/3 DP runs match within the grad_err
    bound — the update math is identical, only collective reduction
    order differs."""
    mesh = make_mesh([8], ["data"], devices8)
    p0, o0, l0 = _run_lm_cached(mesh, 0)
    bytes0 = tree_bytes_per_chip(o0)
    for stage in (1, 2, 3):
        p, o, losses = _run_lm_cached(mesh, stage)
        err = _tree_err(p0, p)
        assert err < 1e-6, f"stage {stage} params err {err}"
        np.testing.assert_allclose(l0, losses, atol=1e-5)
        # n-fold optimizer-state reduction (every LM leaf divides by 8)
        assert tree_bytes_per_chip(o) * 8 == bytes0


def test_stage_equivalence_dp_tp(devices8):
    """DP×TP composition: ZeRO shards the dims the TP rules leave
    free; stage-2 matches the stage-0 TP run within the bound."""
    mesh = make_mesh([4, 2], ["data", "model"], devices8)
    rules = _lm().sharding_rules()
    p0, o0, _ = _run_lm_cached(mesh, 0, rules=rules)
    p2, o2, _ = _run_lm_cached(mesh, 2, rules=rules)
    assert _tree_err(p0, p2) < 1e-6
    assert tree_bytes_per_chip(o2) * 2 <= tree_bytes_per_chip(o0)


def test_stage_equivalence_adam(devices8):
    """The non-SGD slot layout (m/v buffers + scalar step counter)
    updates shard-locally to the same result."""
    mesh = make_mesh([8], ["data"], devices8)
    p0, _, _ = _run_lm_cached(mesh, 0, optim_cls=Adam)
    p2, o2, _ = _run_lm_cached(mesh, 2, optim_cls=Adam)
    assert _tree_err(p0, p2) < 1e-6
    assert o2["t"].sharding.spec == P()


def test_set_zero_reconciles_data_axis(devices8):
    """A ZeroConfig carrying the default 'data' axis must follow the
    Optimizer's own data_axis — otherwise a renamed mesh axis would
    silently deactivate the policy."""
    mesh = make_mesh([8], ["dp"], devices8)
    opt = Optimizer(_mlp(), _toy_ds(), nn.ClassNLLCriterion(),
                    batch_size=32, mesh=mesh, data_axis="dp")
    opt.set_zero(ZeroConfig(stage=2))  # default data_axis="data"
    assert opt.zero_config.data_axis == "dp"
    assert opt._active_zero() is not None


# --------------------------- sharding persistence (satellite regression)

def test_opt_state_sharding_survives_donated_updates(devices8):
    """Regression: every opt-state leaf — moment buffers AND non-float
    step counters — carries an EXPLICIT sharding through donated jitted
    updates, so jit out-shardings never silently re-replicate a shard
    after the first step (Momentum + Adam trees)."""
    mesh = make_mesh([8], ["data"], devices8)
    for optim_cls in (SGD, Adam):
        _, opt_state, _ = _run_lm_cached(mesh, 2, optim_cls=optim_cls)
        flat, _ = jax.tree_util.tree_flatten_with_path(opt_state)
        for path, leaf in flat:
            assert isinstance(leaf.sharding, NamedSharding), path
            if leaf.ndim >= 1 and leaf.shape[0] % 8 == 0:
                assert "data" in jax.tree.leaves(tuple(
                    leaf.sharding.spec)), \
                    f"{path} re-replicated: {leaf.sharding.spec}"


def test_params_stay_sharded_at_rest_stage3(devices8):
    mesh = make_mesh([8], ["data"], devices8)
    model = _lm()
    cfg = ZeroConfig(stage=3)
    params = shard_zero_tree(model.get_parameters(), mesh, cfg)
    optim = SGD(learning_rate=0.1, momentum=0.9)
    opt_state = shard_zero_tree(optim.init_state(
        model.get_parameters()), mesh, cfg)
    mstate = jax.device_put(model.get_state(), NamedSharding(mesh, P()))
    tok, tgt = _lm_batch(16)
    bsh = NamedSharding(mesh, P("data"))
    step = build_train_step(model, nn.SequenceCrossEntropyCriterion(),
                            optim, zero=cfg, mesh=mesh)
    x, y = (jax.device_put(jnp.asarray(tok), bsh),
            jax.device_put(jnp.asarray(tgt), bsh))
    for i in range(2):
        params, opt_state, mstate, _ = step(
            params, opt_state, mstate, jax.random.PRNGKey(i), 0.1, x, y)
    # params per chip are 1/8 of the model: larger-than-chip regime
    full = sum(np.asarray(l).nbytes
               for l in jax.tree.leaves(model.get_parameters()))
    assert tree_bytes_per_chip(params) * 8 == full


# ------------------------------------------------ windowed scan carry

def _toy_ds(n=512, d=16, classes=4, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d).astype(np.float32) * 3
    X = np.stack([centers[i % classes]
                  + rng.randn(d).astype(np.float32) * 0.5
                  for i in range(n)])
    y = np.array([i % classes + 1 for i in range(n)], np.float32)
    return DataSet.array([Sample(X[i], y[i]) for i in range(n)]) \
        .transform(SampleToMiniBatch(batch))


def _mlp():
    return nn.Sequential().add(nn.Linear(16, 32)).add(nn.Tanh()) \
        .add(nn.Linear(32, 4)).add(nn.LogSoftMax())


def _run_optimizer(mesh, stage, k=1, iters=8, ckpt=None, seed=7):
    RandomGenerator.set_seed(seed)
    opt = Optimizer(_mlp(), _toy_ds(), nn.ClassNLLCriterion(),
                    batch_size=32, mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
    opt.set_end_when(max_iteration(iters))
    opt.set_steps_per_sync(k)
    if stage:
        opt.set_zero(ZeroConfig(stage=stage))
    if ckpt:
        opt.set_checkpoint(ckpt, several_iteration(4))
    model = opt.optimize()
    return [np.asarray(l)
            for l in jax.tree_util.tree_leaves(model.get_parameters())]


def test_sharded_carry_k1_vs_k8_bit_identical(devices8):
    """set_zero composes with set_steps_per_sync: the donated scan
    carry holds the SHARDED opt state and the K=8 fused window is
    bit-identical to the per-step loop."""
    mesh = make_mesh([8], ["data"], devices8)
    p1 = _run_optimizer(mesh, 2, k=1)
    p8 = _run_optimizer(mesh, 2, k=8)
    for a, b in zip(p1, p8):
        np.testing.assert_array_equal(a, b)


def test_optimizer_stage_sweep_matches_stage0(devices8):
    mesh = make_mesh([8], ["data"], devices8)
    p0 = _run_optimizer(mesh, 0)
    for stage in (2, 3):
        p = _run_optimizer(mesh, stage)
        err = max(float(np.abs(a - b).max()) for a, b in zip(p0, p))
        assert err < 1e-6, f"stage {stage} err {err}"


def test_memory_gauges_report_n_fold_reduction(devices8):
    """train/memory/*_bytes_per_chip gauges export the placed shard
    sizes; under stage 2 the opt-state gauge shows the ~n-fold drop."""
    mesh = make_mesh([8], ["data"], devices8)
    g_opt = telemetry.gauge("train/memory/opt_state_bytes_per_chip")
    g_par = telemetry.gauge("train/memory/params_bytes_per_chip")
    _run_optimizer(mesh, 0, iters=2)
    full_opt, full_par = g_opt.value(), g_par.value()
    _run_optimizer(mesh, 2, iters=2)
    assert g_par.value() == full_par          # stage 2: params replicated
    assert g_opt.value() * 4 <= full_opt      # MLP: most dims divide by 8
    _run_optimizer(mesh, 3, iters=2)
    assert g_par.value() * 4 <= full_par      # stage 3: params sharded too


# ------------------------------------------------------- HLO placement

def test_window_hlo_collectives_inside_scan_body(devices8):
    """The compiled K-step stage-2 window reduce-scatters and
    all-gathers INSIDE the scan body: zero collectives at the ENTRY
    (host dispatch) boundary, the all-gather count is positive, and
    the reduce-scatter evidence holds (a literal reduce-scatter on
    TPU; all-reduce + dynamic-slice under XLA CPU's lowering)."""
    import functools

    from jax import lax

    mesh = make_mesh([8], ["data"], devices8)
    model = _lm()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    cfg = ZeroConfig(stage=2)
    params = jax.device_put(model.get_parameters(),
                            NamedSharding(mesh, P()))
    opt_state = shard_zero_tree(optim.init_state(model.get_parameters()),
                                mesh, cfg)
    mstate = jax.device_put(model.get_state(), NamedSharding(mesh, P()))
    step = build_train_step(model, nn.SequenceCrossEntropyCriterion(),
                            optim, zero=cfg, mesh=mesh)
    K = 4
    rs = np.random.RandomState(5)
    bsh = NamedSharding(mesh, P(None, "data"))
    xs = jax.device_put(jnp.asarray(rs.randint(0, 64, (K, 8, 16))), bsh)
    ys = jax.device_put(jnp.asarray(rs.randint(0, 64, (K, 8, 16))), bsh)
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(K)])
    lrs = jnp.full((K,), 0.1, jnp.float32)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def window(p, o, m, keys, lrs, xs, ys):
        def body(carry, sl):
            p, o, m = carry
            key, lr, x, y = sl
            p, o, m, loss = step(p, o, m, key, lr, x, y)
            return (p, o, m), loss
        (p, o, m), losses = lax.scan(body, (p, o, m),
                                     (keys, lrs, xs, ys))
        return p, o, m, losses

    counts = window_collectives(
        window.lower(params, opt_state, mstate, keys, lrs, xs,
                     ys).compile())
    for op in ("all-gather", "all-reduce", "reduce-scatter"):
        assert counts[op]["entry"] == 0, \
            f"{op} escaped the scan body to ENTRY: {counts}"
    assert counts["all-gather"]["total"] >= 1, counts
    assert reduce_scatter_evidence(counts), counts
    # the carry keeps the sharded layout window over window
    p, o, m, losses = window(params, opt_state, mstate, keys, lrs, xs,
                             ys)
    assert np.isfinite(np.asarray(losses)).all()
    assert o["v"]["embed"].sharding.spec[0] == "data"


def test_collective_counts_parser():
    text = """\
%body (p: f32[16]) -> f32[16] {
  %ag = f32[16]{0} all-gather(%p), replica_groups={}
  %ar = f32[2]{0} all-reduce(%p), to_apply=%sum
  ROOT %ds = f32[2]{0} dynamic-slice(%ar, %i), dynamic_slice_sizes={2}
}
ENTRY %main (x: f32[16]) -> f32[16] {
  %g = f32[16]{0} all-gather(%x), replica_groups={}
  ROOT %w = f32[16]{0} while(%x), body=%body
}
"""
    counts = collective_counts(text)
    assert counts["all-gather"] == {"total": 2, "entry": 1}
    assert counts["all-reduce"] == {"total": 1, "entry": 0}
    assert counts["dynamic-slice"]["total"] == 1
    assert reduce_scatter_evidence(counts)


def test_collective_counts_async_tuple_types():
    """Real TPU schedules emit async collectives whose result TYPE is a
    tuple with spaces; the -start op must count once (the -done twin
    never matches) even though the type is not a single token."""
    text = """\
ENTRY %main (x: f32[2,4]) -> f32[16,4] {
  %ags = (f32[2,4]{1,0}, f32[16,4]{1,0}) all-gather-start(%x), dimensions={0}
  %agd = f32[16,4]{1,0} all-gather-done(%ags)
  %rss = ((f32[16]{0}), f32[2]{0}) reduce-scatter-start(%y), dimensions={0}
  ROOT %rsd = f32[2]{0} reduce-scatter-done(%rss)
}
"""
    counts = collective_counts(text)
    assert counts["all-gather"] == {"total": 1, "entry": 1}
    assert counts["reduce-scatter"] == {"total": 1, "entry": 1}


# ------------------------------------------------------ resume roundtrip

def _run_optimizer_dev(mesh, stage, iters=8, ckpt=None, seed=7):
    """Device-cached feed (batch position derives from neval, no
    augmentation randomness): the resume-exactness regime the chaos
    soak uses — a resumed run replays the identical batch sequence."""
    from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet
    RandomGenerator.set_seed(seed)
    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 255, (64, 1, 8, 8), np.uint8)
    labels = (rng.randint(0, 3, 64) + 1).astype(np.float32)
    ds = DeviceCachedArrayDataSet(
        imgs, labels, 16, crop=(8, 8), flip=False, mean=(0.0,),
        std=(255.0,), sharding=NamedSharding(mesh, P("data")))
    model = nn.Sequential().add(nn.Reshape([64])) \
        .add(nn.Linear(64, 3)).add(nn.LogSoftMax())
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16,
                    mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.05, momentum=0.9))
    opt.set_end_when(max_iteration(iters))
    if stage:
        opt.set_zero(ZeroConfig(stage=stage))
    if ckpt:
        opt.set_checkpoint(ckpt, several_iteration(4))
    trained = opt.optimize()
    return [np.asarray(l)
            for l in jax.tree_util.tree_leaves(trained.get_parameters())]


def test_zero_resume_roundtrip(devices8, tmp_path):
    """tools.chaos-style contract under ZeRO: the checkpoint saves the
    gathered, unsharded-equivalent state behind the sha256 MANIFEST, so
    (a) a same-config stage-2 resume reproduces the uninterrupted run
    BIT-IDENTICALLY, and (b) the same checkpoint restores onto a
    different stage AND a narrower mesh (stage 3, 4 devices), resharded
    on load, matching within float tolerance."""
    mesh = make_mesh([8], ["data"], devices8)
    d = str(tmp_path / "ckpt")
    # interrupted leg: 4 iters, checkpoint written at iter 4
    _run_optimizer_dev(mesh, 2, iters=4, ckpt=d)
    # uninterrupted reference: full 8 iters, no resume
    ref = _run_optimizer_dev(mesh, 2, iters=8)
    # same-config resume: picks up at iter 5, finishes 8
    resumed = _run_optimizer_dev(mesh, 2, iters=8, ckpt=d)
    for a, b in zip(ref, resumed):
        np.testing.assert_array_equal(a, b)
    # cross-stage + cross-mesh-width restore: stage 3 on 4 devices
    shutil.rmtree(os.path.join(d, "checkpoint.8"))
    mesh4 = make_mesh([4], ["data"], devices8[:4])
    crossed = _run_optimizer_dev(mesh4, 3, iters=8, ckpt=d)
    err = max(float(np.abs(a - b).max()) for a, b in zip(ref, crossed))
    assert err < 1e-5, f"cross-stage/mesh resume diverged: {err}"
