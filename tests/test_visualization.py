"""Visualization tests (reference: visualization/* specs — write scalars/
histograms, read them back through FileReader like the Python API does)."""
import glob
import os
import struct

import numpy as np
import pytest

from bigdl_tpu.visualization import (FileReader, FileWriter, TrainSummary,
                                     ValidationSummary)
from bigdl_tpu.visualization.crc32c import crc32c, masked_crc32c, unmask


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"") == 0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43
    assert crc32c(bytes(range(32))) == 0x46DD794E
    assert crc32c(b"123456789") == 0xE3069283


def test_masked_crc_roundtrip():
    data = b"hello tensorboard"
    assert unmask(masked_crc32c(data)) == crc32c(data)


def test_filewriter_scalar_roundtrip(tmp_path):
    d = str(tmp_path / "logs")
    w = FileWriter(d)
    for i in range(10):
        w.add_scalar("Loss", 1.0 / (i + 1), i)
    w.close()
    vals = FileReader.read_scalar(d, "Loss")
    assert len(vals) == 10
    steps = [s for s, _, _ in vals]
    assert steps == list(range(10))
    np.testing.assert_allclose([v for _, v, _ in vals],
                               [1.0 / (i + 1) for i in range(10)], rtol=1e-6)


def test_filewriter_histogram(tmp_path):
    d = str(tmp_path / "logs")
    w = FileWriter(d)
    w.add_histogram("weights", np.random.randn(1000), 1)
    w.close()
    # histograms aren't scalars; read_scalar must not see them
    assert FileReader.read_scalar(d, "weights") == []
    # but the file must be a valid record stream (crc-checked on read)
    from bigdl_tpu.visualization.tensorboard import _iter_records
    files = FileReader.list_event_files(d)
    assert len(files) == 1
    recs = list(_iter_records(files[0]))
    assert len(recs) == 2  # file_version + histogram


def test_train_validation_summary(tmp_path):
    from bigdl_tpu.optim.trigger import several_iteration
    ts = TrainSummary(str(tmp_path), "app")
    ts.set_summary_trigger("Parameters", several_iteration(10))
    assert ts.get_summary_trigger("Parameters") is not None
    with pytest.raises(ValueError):
        ts.set_summary_trigger("bogus", several_iteration(1))
    ts.add_scalar("Loss", 0.5, 1)
    assert ts.read_scalar("Loss")[0][1] == pytest.approx(0.5)
    vs = ValidationSummary(str(tmp_path), "app")
    vs.add_scalar("Top1Accuracy", 0.9, 1)
    assert vs.read_scalar("Top1Accuracy")[0][1] == pytest.approx(0.9)
    ts.close()
    vs.close()
    assert os.path.isdir(str(tmp_path / "app" / "train"))
    assert os.path.isdir(str(tmp_path / "app" / "validation"))


def test_optimizer_writes_summaries(tmp_path):
    """End-to-end: train a tiny model with summaries attached."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import max_iteration, several_iteration

    xs = np.random.randn(64, 4).astype(np.float32)
    ys = (xs.sum(axis=1) > 0).astype(np.float32) + 1.0
    samples = [Sample(x, y) for x, y in zip(xs, ys)]
    model = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(
        nn.Linear(8, 2)).add(nn.LogSoftMax())
    ds = DataSet.array(samples).transform(SampleToMiniBatch(16))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16)
    ts = TrainSummary(str(tmp_path), "e2e")
    ts.set_summary_trigger("Parameters", several_iteration(2))
    opt.set_train_summary(ts)
    opt.set_end_when(max_iteration(5))
    opt.optimize()
    losses = ts.read_scalar("Loss")
    assert len(losses) == 5
    thr = ts.read_scalar("Throughput")
    assert len(thr) == 5
    ts.close()
