"""In-graph TF input pipelines: queue runners + ParseExample executed on
host, device graph trained from the boundary tensors (reference:
nn/ops/ParseExample.scala, nn/ops/DecodeImage.scala,
utils/tf/Session.scala:104-110 — BigDLSessionImpl trains straight off
queue-runner input graphs)."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from bigdl_tpu.utils.tf_loader import Session, TFNode, parse_graphdef
from bigdl_tpu.utils.tf_input import (HostInputGraph, find_boundary_refs,
                                      has_input_pipeline)


def _write_tfrecord(path, n=64, seed=0):
    """Linear data y = x.w + 1, serialized by REAL TF (adversarial oracle
    for the host-side parse)."""
    rng = np.random.RandomState(seed)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    xs = rng.randn(n, 4).astype(np.float32)
    ys = xs @ w + 1.0
    with tf.io.TFRecordWriter(str(path)) as wr:
        for i in range(n):
            ex = tf.train.Example(features=tf.train.Features(feature={
                "x": tf.train.Feature(float_list=tf.train.FloatList(
                    value=xs[i])),
                "y": tf.train.Feature(float_list=tf.train.FloatList(
                    value=ys[i])),
            }))
            wr.write(ex.SerializeToString())
    return xs, ys


def _queue_runner_graph(record_path, batch_size=8):
    """string_input_producer -> TFRecordReader -> tf.train.batch ->
    parse_example -> linear model -> MSE loss (the classic v1 export)."""
    tfv1 = tf.compat.v1
    g = tfv1.Graph()
    with g.as_default():
        tfv1.set_random_seed(7)
        fq = tfv1.train.string_input_producer([str(record_path)])
        reader = tfv1.TFRecordReader()
        _, serialized = reader.read(fq)
        batch = tfv1.train.batch([serialized], batch_size=batch_size)
        feats = tfv1.parse_example(batch, {
            "x": tfv1.FixedLenFeature([4], tf.float32),
            "y": tfv1.FixedLenFeature([1], tf.float32)})
        w = tfv1.Variable(tfv1.random.truncated_normal([4, 1],
                                                       stddev=0.1),
                          name="w")
        b = tfv1.Variable(tfv1.zeros([1]), name="b")
        pred = tfv1.matmul(feats["x"], w) + b
        tfv1.reduce_mean(tfv1.square(pred - feats["y"]), name="loss")
    return g.as_graph_def().SerializeToString()


def test_session_trains_queue_runner_graph(tmp_path):
    """The verdict's done-bar: import a TF-exported graph containing
    ParseExample and train it to lower loss from a .tfrecord."""
    from bigdl_tpu.optim import SGD

    rec = tmp_path / "train.tfrecord"
    _write_tfrecord(rec)
    graph_bytes = _queue_runner_graph(rec)

    sess = Session(graph_bytes, loss="loss")
    assert sess.pipeline is not None
    first = Session(graph_bytes, loss="loss")
    # sanity: pipeline auto-feeds; 40 SGD steps on a linear problem
    m = sess.train(optim_method=SGD(learning_rate=0.05),
                   max_iterations=40)
    assert sess.last_loss is not None

    # loss after training is far below the first-step loss
    m0 = first.train(optim_method=SGD(learning_rate=0.05),
                     max_iterations=1)
    assert sess.last_loss < 0.25 * first.last_loss
    # learned weights approach the generating w=[1,-2,.5,3], b=1
    w = np.asarray(m.get_parameters()["w"]).ravel()
    np.testing.assert_allclose(w, [1.0, -2.0, 0.5, 3.0], atol=0.35)
    del m0


def test_session_record_files_override(tmp_path):
    """The graph bakes in the exporting machine's path; record_files
    substitutes a local one (reader nodes resolve to a host iterator)."""
    from bigdl_tpu.optim import SGD

    rec = tmp_path / "local.tfrecord"
    _write_tfrecord(rec)
    graph_bytes = _queue_runner_graph("/nonexistent/exported.tfrecord")

    sess = Session(graph_bytes, loss="loss",
                   record_files=[str(rec)])
    sess.train(optim_method=SGD(learning_rate=0.05), max_iterations=5)
    assert np.isfinite(sess.last_loss)


def test_boundary_detection(tmp_path):
    rec = tmp_path / "b.tfrecord"
    _write_tfrecord(rec, n=8)
    nodes = parse_graphdef(_queue_runner_graph(rec))
    by_name = {n.name: n for n in nodes}
    assert has_input_pipeline(nodes)
    refs = find_boundary_refs(nodes, by_name, ["loss"])
    # exactly the two ParseExample dense outputs cross the boundary
    assert [r.split(":")[0] for r in refs] == \
        ["ParseExample/ParseExampleV2"] * 2


def test_host_graph_epochs_cycle_over_file(tmp_path):
    """The filename queue cycles: more batches than one file pass."""
    rec = tmp_path / "c.tfrecord"
    _write_tfrecord(rec, n=16)  # 2 batches of 8 per pass
    nodes = parse_graphdef(_queue_runner_graph(rec))
    by_name = {n.name: n for n in nodes}
    refs = find_boundary_refs(nodes, by_name, ["loss"])
    host = HostInputGraph(nodes)
    it = host.batches(refs)
    seen = [next(it) for _ in range(5)]  # 40 records from a 16-row file
    for xs in seen:
        assert xs[0].shape == (8, 4) and xs[1].shape == (8, 1)


def test_parse_example_v1_layout():
    """The pre-V2 op layout: Nsparse/Ndense attrs with per-key Const
    inputs (nn/ops/ParseExample.scala:1 handles this form)."""
    from bigdl_tpu.utils.tfrecord import encode_example

    recs = [encode_example({"a": np.array([1.0, 2.0], np.float32),
                            "b": np.array([7.0], np.float32)})
            for _ in range(3)]
    serialized = np.empty(3, object)
    serialized[:] = recs

    def const(name, val):
        return TFNode(name, "Const", [], {"value": val})

    key_a = np.empty((), object)
    key_a[()] = b"a"
    key_b = np.empty((), object)
    key_b[()] = b"b"
    nodes = [
        const("keys/a", key_a), const("keys/b", key_b),
        const("names", np.empty(0, object)),
        const("default/a", np.zeros(0, np.float32)),
        const("default/b", np.zeros(0, np.float32)),
        TFNode("parse", "ParseExample",
               ["serialized", "names", "keys/a", "keys/b",
                "default/a", "default/b"],
               {"Nsparse": 0, "Ndense": 2,
                "Tdense": [np.float32, np.float32],
                "dense_shapes": [[2], [1]]}),
        TFNode("serialized", "Placeholder", [], {}),
    ]
    host = HostInputGraph(nodes)
    cache = {"serialized": serialized}
    a = host.eval_ref("parse:0", cache)
    b = host.eval_ref("parse:1", cache)
    np.testing.assert_allclose(a, [[1, 2]] * 3)
    np.testing.assert_allclose(b, [[7.0]] * 3)


def test_decode_raw_in_pipeline(tmp_path):
    """String features + DecodeRaw: raw float32 bytes parsed on host
    (nn/ops/DecodeImage.scala's DecodeRaw sibling)."""
    from bigdl_tpu.optim import SGD

    rng = np.random.RandomState(3)
    xs = rng.randn(32, 4).astype(np.float32)
    ys = (xs @ np.array([[2.0], [0.0], [-1.0], [1.0]],
                        np.float32)).astype(np.float32)
    rec = tmp_path / "raw.tfrecord"
    with tf.io.TFRecordWriter(str(rec)) as wr:
        for i in range(len(xs)):
            ex = tf.train.Example(features=tf.train.Features(feature={
                "x_raw": tf.train.Feature(bytes_list=tf.train.BytesList(
                    value=[xs[i].tobytes()])),
                "y": tf.train.Feature(float_list=tf.train.FloatList(
                    value=ys[i]))}))
            wr.write(ex.SerializeToString())

    tfv1 = tf.compat.v1
    g = tfv1.Graph()
    with g.as_default():
        fq = tfv1.train.string_input_producer([str(rec)])
        reader = tfv1.TFRecordReader()
        _, serialized = reader.read(fq)
        batch = tfv1.train.batch([serialized], batch_size=8)
        feats = tfv1.parse_example(batch, {
            "x_raw": tfv1.FixedLenFeature([], tf.string),
            "y": tfv1.FixedLenFeature([1], tf.float32)})
        x = tfv1.reshape(tfv1.decode_raw(feats["x_raw"], tf.float32),
                         [8, 4])
        w = tfv1.Variable(tfv1.zeros([4, 1]), name="w")
        pred = tfv1.matmul(x, w)
        tfv1.reduce_mean(tfv1.square(pred - feats["y"]), name="loss")

    sess = Session(g.as_graph_def().SerializeToString(), loss="loss")
    sess.train(optim_method=SGD(learning_rate=0.05), max_iterations=30)
    w_l = np.asarray(sess.module.get_parameters()["w"]).ravel()
    np.testing.assert_allclose(w_l, [2.0, 0.0, -1.0, 1.0], atol=0.3)


def test_parse_single_example_v1_layout():
    """TF1 frozen-graph ParseSingleExample: keys in attrs, scalar
    serialized input, unbatched dense outputs."""
    from bigdl_tpu.utils.tfrecord import encode_example

    rec = encode_example({"x": np.array([1.5, 2.5], np.float32)})
    ser = np.empty((), object)
    ser[()] = rec
    nodes = [
        TFNode("ser", "Placeholder", [], {}),
        TFNode("default/x", "Const",
               [], {"value": np.zeros(0, np.float32)}),
        TFNode("parse", "ParseSingleExample", ["ser", "default/x"],
               {"sparse_keys": [], "dense_keys": ["x"],
                "Tdense": [np.float32], "dense_shapes": [[2]],
                "num_sparse": 0}),
    ]
    host = HostInputGraph(nodes)
    cache = {"ser": ser}
    out = host.eval_ref("parse", cache)
    np.testing.assert_allclose(out, [1.5, 2.5])
    assert out.shape == (2,)  # unbatched

    # the modern lowering (ParseExampleV2 with scalar input) agrees
    import tensorflow.compat.v1 as tfv1
    g = tfv1.Graph()
    with g.as_default():
        s = tfv1.placeholder(tf.string, [], name="ser2")
        tfv1.io.parse_single_example(
            s, {"x": tfv1.FixedLenFeature([2], tf.float32)})
    nodes2 = parse_graphdef(g.as_graph_def().SerializeToString())
    host2 = HostInputGraph(nodes2)
    cache2 = {"ser2": ser}
    pe = [n.name for n in nodes2 if n.op == "ParseExampleV2"][0]
    out2 = host2.eval_ref(pe, cache2)
    np.testing.assert_allclose(out2, [1.5, 2.5])
    assert np.asarray(out2).shape == (2,)
