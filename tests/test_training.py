"""End-to-end training tests (reference: optim/DistriOptimizerSpec,
LocalOptimizerSpec — convergence on toy problems, SURVEY.md §4.3)."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import (Adam, DistriOptimizer, Evaluator, LocalOptimizer,
                             SGD, Top1Accuracy, max_epoch, max_iteration)
from bigdl_tpu.utils.engine import Engine


def _toy_classification(n=256, d=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d).astype(np.float32) * 3
    X, y = [], []
    for i in range(n):
        c = i % classes
        X.append(centers[c] + rng.randn(d).astype(np.float32) * 0.5)
        y.append(c + 1)  # 1-based labels
    return np.stack(X), np.array(y, np.float32)


def test_local_optimizer_converges_mlp():
    X, y = _toy_classification()
    samples = [Sample(X[i], y[i]) for i in range(len(X))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32))

    model = nn.Sequential() \
        .add(nn.Linear(8, 16)) \
        .add(nn.Tanh()) \
        .add(nn.Linear(16, 3)) \
        .add(nn.LogSoftMax())
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_epoch(15))
    trained = opt.optimize()

    res = Evaluator(trained).test(
        DataSet.array([Sample(X[i], y[i]) for i in range(len(X))]),
        [Top1Accuracy()], batch_size=64)
    acc, _ = res["Top1Accuracy"].result()
    assert acc > 0.95, f"accuracy {acc}"


def test_distri_optimizer_8dev_mesh_converges():
    import jax
    Engine.reset()
    Engine.init()  # 8 virtual CPU devices from conftest
    assert Engine.device_count() == 8
    X, y = _toy_classification(n=512)
    samples = [Sample(X[i], y[i]) for i in range(len(X))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(64))

    model = nn.Sequential() \
        .add(nn.Linear(8, 16)) \
        .add(nn.ReLU()) \
        .add(nn.Linear(16, 3)) \
        .add(nn.LogSoftMax())
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64)
    opt.set_optim_method(Adam(learning_rate=0.05))
    opt.set_end_when(max_iteration(120))
    trained = opt.optimize()

    res = Evaluator(trained).test(DataSet.array(samples), [Top1Accuracy()],
                                  batch_size=64)
    acc, _ = res["Top1Accuracy"].result()
    assert acc > 0.9, f"accuracy {acc}"


def test_lenet_trains_and_checkpoint_resume(tmp_path):
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import several_iteration
    rng = np.random.RandomState(1)
    # synthetic 28x28 "digits": class = which quadrant is bright
    X = rng.rand(128, 28, 28).astype(np.float32) * 0.1
    y = np.zeros(128, np.float32)
    for i in range(128):
        c = i % 4
        r, col = divmod(c, 2)
        X[i, r * 14:(r + 1) * 14, col * 14:(col + 1) * 14] += 0.9
        y[i] = c + 1
    samples = [Sample(X[i], y[i]) for i in range(128)]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32))

    model = LeNet5(10)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.05, momentum=0.9))
    opt.set_end_when(max_iteration(40))
    opt.set_checkpoint(str(tmp_path / "ckpt"), several_iteration(20))
    trained = opt.optimize()

    res = Evaluator(trained).test(DataSet.array(samples), [Top1Accuracy()],
                                  batch_size=64)
    acc, _ = res["Top1Accuracy"].result()
    assert acc > 0.9, f"accuracy {acc}"

    # checkpoint exists and can be loaded
    from bigdl_tpu.utils.serialization import (find_latest_checkpoint,
                                               load_checkpoint)
    latest = find_latest_checkpoint(str(tmp_path / "ckpt"))
    assert latest is not None
    ck = load_checkpoint(latest)
    assert "params" in ck and "driver_state" in ck


def test_validation_and_triggers():
    X, y = _toy_classification(n=128)
    samples = [Sample(X[i], y[i]) for i in range(len(X))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32))
    val = DataSet.array(samples)

    from bigdl_tpu.optim import every_epoch
    model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_epoch(3))
    opt.set_validation(every_epoch(), val, [Top1Accuracy()])
    opt.optimize()
    assert "score" in opt.driver_state


def test_validation_score_uses_first_method():
    """driver_state['score'] must be the FIRST validation method's result
    (DistriOptimizer.scala:382-397 uses head) — not a max() across
    heterogeneous methods, which with Loss in the set would exceed any
    accuracy and corrupt maxScore/Plateau decisions."""
    from bigdl_tpu.optim import Loss, every_epoch

    X, y = _toy_classification(n=128)
    samples = [Sample(X[i], y[i]) for i in range(len(X))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32))
    val = DataSet.array(samples)

    model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_epoch(1))
    # First method is Top1 (<=1.0); Loss of an untrained 3-class model is
    # ~ln(3) > 1, so max() across both would pick the Loss value.
    opt.set_validation(every_epoch(), val,
                       [Top1Accuracy(), Loss(nn.ClassNLLCriterion())])
    opt.optimize()
    assert opt.driver_state["score"] <= 1.0


def test_failure_retry_from_checkpoint(tmp_path):
    """Fault injection (reference ExceptionTest / DistriOptimizerSpec:461):
    a layer that throws at a scripted iteration; training must resume from
    checkpoint and complete."""
    X, y = _toy_classification(n=64)
    samples = [Sample(X[i], y[i]) for i in range(len(X))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32))

    calls = {"n": 0, "thrown": False}

    class ExceptionLayer(nn.Module):
        def forward_fn(self, params, input, *, training=False, rng=None):
            return input

        def init(self, rng):
            return {}

    model = nn.Sequential().add(ExceptionLayer()) \
        .add(nn.Linear(8, 3)).add(nn.LogSoftMax())

    from bigdl_tpu.optim import several_iteration
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(30))
    opt.set_checkpoint(str(tmp_path / "ck"), several_iteration(5))
    opt.retry_interval_s = 0.0

    real_impl = opt._optimize_impl

    def flaky_impl():
        calls["n"] += 1
        if not calls["thrown"] and opt.driver_state["neval"] > 1:
            pass
        return real_impl()

    # inject: throw once at iteration 12 via a wrapped step
    orig_put = opt._prep_io

    def flaky_prep(batch):
        if opt.driver_state["neval"] == 12 and not calls["thrown"]:
            calls["thrown"] = True
            raise RuntimeError("injected failure at iteration 12")
        return orig_put(batch)

    opt._prep_io = flaky_prep
    trained = opt.optimize()
    assert calls["thrown"], "failure was not injected"
    assert opt.driver_state["neval"] > 30


def test_convergence_dataset_is_a_learnable_split():
    """tools/convergence's prototype task: the class prototypes are the
    TASK and must be identical across splits (a train/val mismatch here
    silently turns the 99.9% on-chip result into chance-level — the bug
    class this guards). The full run is on-chip only (BASELINE.md r3:
    99.85% held-out top-1 in 20 epochs); it is far too slow for 1-vCPU
    CI."""
    from bigdl_tpu.tools.convergence import make_dataset

    xs_a, ys_a = make_dataset(600, seed=0)
    xs_b, ys_b = make_dataset(600, seed=1)
    assert xs_a.shape == (600, 3, 32, 32) and xs_a.dtype == np.uint8
    assert set(np.unique(ys_a)).issubset(set(np.arange(1, 11.0)))
    # different seeds draw different samples...
    assert not np.array_equal(xs_a, xs_b)
    # ...of the SAME task: per-class pixel means across splits correlate
    # (the +-3px translation of white-noise prototypes smears alignment,
    # so r lands ~0.4; DISTINCT prototype sets give r ~ 0 +- 0.02, which
    # is exactly the train/val-mismatch bug this guards against)
    for c in (1.0, 2.0):
        ma = xs_a[ys_a == c].mean(0).astype(np.float32).ravel()
        mb = xs_b[ys_b == c].mean(0).astype(np.float32).ravel()
        r = np.corrcoef(ma, mb)[0, 1]
        assert r > 0.2, f"class {c} prototypes differ across splits: r={r}"
    # same seed reproduces exactly (checkpoint/resume replays the data)
    xs_c, ys_c = make_dataset(600, seed=0)
    np.testing.assert_array_equal(xs_a, xs_c)
    np.testing.assert_array_equal(ys_a, ys_c)


def test_freeze_and_layerwise_scale_through_training():
    """setScaleW/setScaleB/freeze flow through the compiled step
    (DistriOptimizer.scala:768 isLayerwiseScaled): a frozen layer's
    params are bit-identical after training; a 0.5-scaled weight moves
    exactly half as far as an unscaled clone on the same batch."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration
    from bigdl_tpu.utils.random import RandomGenerator

    rng = np.random.RandomState(0)
    xs = rng.randn(32, 6).astype(np.float32)
    ys = (rng.randint(0, 2, 32) + 1).astype(np.float32)
    ds = DataSet.array([Sample(xs[i], ys[i]) for i in range(32)]) \
        .transform(SampleToMiniBatch(32))

    def build():
        RandomGenerator.set_seed(5)
        return (nn.Sequential()
                .add(nn.Linear(6, 8).set_name("frozen").freeze())
                .add(nn.Tanh())
                .add(nn.Linear(8, 2).set_name("head"))
                .add(nn.LogSoftMax()))

    m = build()
    m.ensure_initialized()
    before = np.asarray(m.get_parameters()["0"]["weight"]).copy()
    head_before = np.asarray(m.get_parameters()["2"]["weight"]).copy()
    opt = LocalOptimizer(m, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(1))
    opt.optimize()
    after = np.asarray(m.get_parameters()["0"]["weight"])
    head_after = np.asarray(m.get_parameters()["2"]["weight"])
    np.testing.assert_array_equal(before, after)     # frozen: untouched
    assert np.abs(head_after - head_before).max() > 0  # head trained

    # scale 0.5 halves the update exactly (same data, same init)
    m_full = build()
    opt = LocalOptimizer(m_full, ds, nn.ClassNLLCriterion(),
                         batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(1))
    opt.optimize()
    delta_full = np.asarray(m_full.get_parameters()["2"]["weight"]) \
        - head_before

    m_half = build()
    m_half.modules[2].set_scale_w(0.5).set_scale_b(0.5)
    opt = LocalOptimizer(m_half, ds, nn.ClassNLLCriterion(),
                         batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(1))
    opt.optimize()
    delta_half = np.asarray(m_half.get_parameters()["2"]["weight"]) \
        - head_before
    np.testing.assert_allclose(delta_half, 0.5 * delta_full,
                               atol=1e-6)
