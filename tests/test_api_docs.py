"""API-reference completeness gate (the reference shipped a full
per-layer APIGuide, docs/docs/APIGuide/, and per-model READMEs,
models/resnet/README.md:25-56 — this suite asserts our generated
equivalent can never silently rot)."""
import os

import pytest

from bigdl_tpu.tools.gen_api_docs import (FAMILIES, generate,
                                          generate_family, undocumented)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_every_public_symbol_documented():
    missing = undocumented()
    assert missing == [], (
        f"{len(missing)} undocumented public symbols (add docstrings "
        f"or fix __all__): {missing[:20]}")


def test_family_pages_generate_with_content():
    for fam in FAMILIES:
        page = generate_family(fam)
        # each page indexes at least a handful of symbols
        assert page.count("- **`") >= 3, (fam, page[:500])


def test_api_index_links_family_pages():
    idx = generate()
    for fam in FAMILIES:
        assert f"api/{fam}.md" in idx


def test_generated_docs_are_committed_and_current():
    """docs/api.md + per-family pages exist in the tree; the index
    must mention every module the generator covers (regenerate with
    `python -m bigdl_tpu.tools.gen_api_docs` after API changes)."""
    idx_path = os.path.join(REPO, "docs", "api.md")
    assert os.path.exists(idx_path)
    with open(idx_path) as f:
        committed = f.read()
    from bigdl_tpu.tools.gen_api_docs import MODULES
    for m in MODULES:
        assert f"`{m}`" in committed, f"docs/api.md is stale: missing {m}"
    for fam in FAMILIES:
        assert os.path.exists(os.path.join(REPO, "docs", "api",
                                           fam + ".md"))


def test_every_zoo_family_has_readme():
    """Per-model READMEs, like the reference's models/*/README.md."""
    zoo = os.path.join(REPO, "bigdl_tpu", "models")
    fams = [d for d in os.listdir(zoo)
            if os.path.isdir(os.path.join(zoo, d))
            and not d.startswith("_")]
    assert len(fams) >= 8
    for fam in fams:
        readme = os.path.join(zoo, fam, "README.md")
        assert os.path.exists(readme), f"missing {readme}"
        with open(readme) as f:
            text = f.read()
        assert "train" in text and "python -m" in text, readme
