"""The dogfood gate: the shipped ``bigdl_tpu`` tree must stay clean under
its own linter and CLI — tier-1 itself is the lint gate, so a PR that
introduces a JAX pitfall (or breaks a rule's precision) fails here."""
import os
import subprocess
import sys

import bigdl_tpu
from bigdl_tpu.analysis import format_text, lint_paths

PKG_DIR = os.path.dirname(os.path.abspath(bigdl_tpu.__file__))
REPO = os.path.dirname(PKG_DIR)


def test_package_lints_clean_in_process():
    findings = lint_paths([PKG_DIR])
    active = [f for f in findings if not f.suppressed]
    assert active == [], (
        "unsuppressed lint findings in bigdl_tpu (fix them or add an "
        "explicit `# bigdl: disable=RULE`):\n"
        + format_text(findings))


def test_parse_clean_no_parse_errors():
    findings = lint_paths([PKG_DIR])
    assert not any(f.rule == "parse-error" for f in findings)


def test_check_cli_lint_pass_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "bigdl_tpu",
         "--lint-only"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 findings" in proc.stdout


def test_check_cli_exit_code_on_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    try:\n        pass\n"
                   "    except:\n        pass\n")
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", str(bad),
         "--lint-only"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1
    assert "bare-except" in proc.stdout


def test_check_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0
    for r in ("host-sync", "traced-branch", "jit-static-args",
              "apply-mutates-self", "bare-except"):
        assert r in proc.stdout


def test_full_check_cli_self_run_clean():
    """The acceptance gate: `python -m bigdl_tpu.tools.check bigdl_tpu`
    (lint + whole-zoo shape pass) exits 0 on the shipped tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "bigdl_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "12/12 zoo models clean" in proc.stdout
