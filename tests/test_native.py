"""Native C++ runtime tests (crc32c + data loader), skipped when no
compiler. The Python crc32c is the cross-check."""
import numpy as np
import pytest

from bigdl_tpu import native


pytestmark = pytest.mark.skipif(not native.native_available(),
                                reason="native lib unavailable")


def test_native_crc32c_matches_python():
    from bigdl_tpu.visualization.crc32c import _crc_py
    rng = np.random.RandomState(0)
    for size in (0, 1, 7, 8, 9, 63, 1024, 65537):
        data = rng.bytes(size)
        assert native.native_crc32c(data) == _crc_py(data), size


def test_crc32c_module_uses_native():
    """crc32c.py should have picked up the native impl."""
    from bigdl_tpu.visualization import crc32c as c
    c._try_native()
    assert c._crc_impl is not c._crc_py
    # masked crc stays consistent through the swap
    data = b"tensorboard record"
    assert c.unmask(c.masked_crc32c(data)) == c.crc32c(data)


def test_parse_idx():
    import struct
    arr = np.random.randint(0, 256, (5, 4, 3), dtype=np.uint8)
    buf = struct.pack(">BBBB", 0, 0, 0x08, 3)
    buf += struct.pack(">III", 5, 4, 3)
    buf += arr.tobytes()
    out = native.parse_idx(buf)
    assert out.shape == (5, 4, 3)
    np.testing.assert_array_equal(out, arr.astype(np.float32))


def test_parse_idx_bad_magic():
    with pytest.raises(ValueError):
        native.parse_idx(b"\x01\x00\x08\x01\x00\x00\x00\x01x")


def test_parse_cifar():
    rng = np.random.RandomState(1)
    n = 7
    recs = b""
    labels, imgs = [], []
    for i in range(n):
        lab = rng.randint(0, 10)
        px = rng.randint(0, 256, 3 * 32 * 32, dtype=np.uint8)
        labels.append(lab + 1)
        imgs.append(px.reshape(3, 32, 32))
        recs += bytes([lab]) + px.tobytes()
    got_imgs, got_lbls = native.parse_cifar(recs)
    assert got_imgs.shape == (n, 3, 32, 32)
    np.testing.assert_array_equal(got_lbls, np.asarray(labels, np.float32))
    np.testing.assert_array_equal(got_imgs[3], imgs[3].astype(np.float32))


def test_batch_loader_eval_mode_deterministic():
    rng = np.random.RandomState(2)
    images = rng.rand(32, 3, 8, 8).astype(np.float32)
    labels = np.arange(1, 33, dtype=np.float32)
    ld = native.NativeBatchLoader(images, labels, batch_size=8,
                                  train=False, flip=False, num_threads=1,
                                  prefetch=1)
    imgs, lbls = ld.next_batch()
    assert imgs.shape == (8, 3, 8, 8)
    # eval mode walks the dataset in order
    np.testing.assert_array_equal(lbls, labels[:8])
    np.testing.assert_allclose(imgs, images[:8], atol=1e-6)
    ld.close()


def test_batch_loader_train_augment_and_normalize():
    rng = np.random.RandomState(3)
    images = rng.rand(64, 3, 12, 12).astype(np.float32)
    labels = np.ones(64, np.float32)
    mean = [0.5, 0.5, 0.5]
    std = [0.25, 0.25, 0.25]
    ld = native.NativeBatchLoader(images, labels, batch_size=16,
                                  crop=(8, 8), pad=2, flip=True,
                                  train=True, mean=mean, std=std,
                                  num_threads=2, prefetch=3, seed=7)
    seen = []
    for _ in range(5):
        imgs, lbls = ld.next_batch()
        assert imgs.shape == (16, 3, 8, 8)
        assert np.isfinite(imgs).all()
        seen.append(imgs.copy())
    ld.close()
    # augmentation actually varies batches
    assert not np.allclose(seen[0], seen[1])
    # normalization applied: values centered near 0 at scale ~2
    allv = np.concatenate([s.ravel() for s in seen])
    assert -2.5 < allv.mean() < 2.5


def test_native_dataset_trains_a_model():
    """End-to-end: native loader feeding the Optimizer."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import NativeArrayDataSet
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import max_iteration

    rng = np.random.RandomState(5)
    images = rng.rand(128, 1, 8, 8).astype(np.float32)
    labels = (images.mean((1, 2, 3)) > 0.5).astype(np.float32) + 1.0
    ds = NativeArrayDataSet(images, labels, batch_size=32, num_threads=2)
    model = (nn.Sequential().add(nn.Reshape((64,)))
             .add(nn.Linear(64, 2)).add(nn.LogSoftMax()))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), 32)
    opt.set_end_when(max_iteration(10))
    opt.optimize()
    ds.close()
    out = np.asarray(model.evaluate().forward(images[:8]))
    assert out.shape == (8, 2)


def test_eval_sweep_no_duplicates():
    """Review regression: eval iteration covers each sample exactly once
    even when n % batch_size != 0."""
    from bigdl_tpu.dataset import NativeArrayDataSet
    images = np.random.rand(10, 1, 4, 4).astype(np.float32)
    labels = np.arange(1, 11, dtype=np.float32)
    ds = NativeArrayDataSet(images, labels, batch_size=4, num_threads=1)
    seen = []
    for mb in ds.data(train=False):
        seen.extend(np.asarray(mb.get_target()).tolist())
    ds.close()
    assert sorted(seen) == list(range(1, 11))


def test_empty_dataset_raises_not_crashes():
    from bigdl_tpu import native
    with pytest.raises(ValueError):
        native.NativeBatchLoader(np.empty((0, 3, 8, 8), np.float32),
                                 np.empty(0, np.float32), 4)


def test_too_many_channels_raises():
    from bigdl_tpu import native
    with pytest.raises(ValueError):
        native.NativeBatchLoader(np.zeros((4, 16, 2, 2), np.float32),
                                 np.ones(4, np.float32), 2)
