"""Device-cached dataset with on-device augmentation (TPU-native form of
the reference's decoded-image executor cache, DataSet.scala
CachedDistriDataSet:240)."""
import jax
import numpy as np
import pytest

from bigdl_tpu.dataset import DeviceCachedArrayDataSet


def _data(n=20, c=3, h=8, w=8, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randint(0, 255, (n, c, h, w), np.uint8),
            rng.randint(1, 11, n).astype(np.float32))


def test_train_batch_shapes_and_normalization():
    imgs, lbls = _data()
    ds = DeviceCachedArrayDataSet(imgs, lbls, 6, crop=(6, 6), pad=0,
                                  flip=False, mean=(10, 20, 30),
                                  std=(2, 4, 8))
    x, y = jax.jit(ds.batch_fn)(jax.random.PRNGKey(0))
    x, y = np.asarray(x), np.asarray(y)
    assert x.shape == (6, 3, 6, 6) and y.shape == (6,)
    # every crop pixel must denormalize back to a source uint8 value
    denorm = x * np.array([2, 4, 8]).reshape(1, 3, 1, 1) \
        + np.array([10, 20, 30]).reshape(1, 3, 1, 1)
    assert np.allclose(denorm, np.round(denorm), atol=1e-3)
    assert denorm.min() >= 0 and denorm.max() <= 255
    assert set(y).issubset(set(lbls))


def test_batches_vary_with_rng_and_are_deterministic():
    imgs, lbls = _data()
    ds = DeviceCachedArrayDataSet(imgs, lbls, 4, crop=(6, 6), pad=2)
    f = jax.jit(ds.batch_fn)
    x1, _ = f(jax.random.PRNGKey(1))
    x1b, _ = f(jax.random.PRNGKey(1))
    x2, _ = f(jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x1b))
    assert not np.array_equal(np.asarray(x1), np.asarray(x2))


def test_eval_batch_center_crop_exact():
    imgs, lbls = _data()
    ds = DeviceCachedArrayDataSet(imgs, lbls, 5, crop=(6, 6), pad=0,
                                  flip=False)
    x, y = jax.jit(ds.eval_batch_fn)(0)
    want = imgs[:5, :, 1:7, 1:7].astype(np.float32)
    np.testing.assert_allclose(np.asarray(x), want)
    np.testing.assert_array_equal(np.asarray(y), lbls[:5])
    # wraps modulo n at the tail
    x2, y2 = jax.jit(ds.eval_batch_fn)(18)
    np.testing.assert_array_equal(np.asarray(y2),
                                  lbls[(18 + np.arange(5)) % 20])


def test_pad_then_crop_covers_borders():
    imgs, lbls = _data(h=6, w=6)
    ds = DeviceCachedArrayDataSet(imgs, lbls, 8, crop=(6, 6), pad=2)
    # with pad 2 some crops include zero border; all values still valid
    x, _ = jax.jit(ds.batch_fn)(jax.random.PRNGKey(3))
    x = np.asarray(x)
    assert x.min() >= 0 and x.max() <= 255


def test_rejects_bad_config():
    imgs, lbls = _data()
    with pytest.raises(ValueError, match="crop larger"):
        DeviceCachedArrayDataSet(imgs, lbls, 4, crop=(20, 20), pad=0)
    with pytest.raises(ValueError, match="labels shorter"):
        DeviceCachedArrayDataSet(imgs, lbls[:5], 4)


def test_trains_a_model_end_to_end():
    """Full jitted train loop with on-device batches: loss decreases."""
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step

    rng = np.random.RandomState(0)
    # learnable: label = 1 + (channel-0 mean > 127)
    imgs = rng.randint(0, 255, (64, 3, 8, 8), np.uint8)
    lbls = 1.0 + (imgs[:, 0].mean(axis=(1, 2)) > 127).astype(np.float32)
    ds = DeviceCachedArrayDataSet(imgs, lbls, 16, crop=(8, 8), pad=1,
                                  mean=(127, 127, 127), std=(64, 64, 64))
    model = (nn.Sequential()
             .add(nn.Reshape((3 * 8 * 8,)))
             .add(nn.Linear(3 * 8 * 8, 2))
             .add(nn.LogSoftMax()))
    model.ensure_initialized()
    crit = nn.ClassNLLCriterion()
    optim = SGD(learning_rate=0.1)
    step = build_train_step(model, crit, optim)
    params = model.get_parameters()
    mstate = model.get_state()
    ostate = optim.init_state(params)

    @jax.jit
    def train_step(p, o, m, key):
        kb, kr = jax.random.split(key)
        x, y = ds.batch_fn(kb)
        return step(p, o, m, kr, 0.1, x, y)

    losses = []
    key = jax.random.PRNGKey(0)
    for i in range(30):
        key, k = jax.random.split(key)
        params, ostate, mstate, loss = train_step(params, ostate, mstate, k)
        losses.append(float(loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_local_optimizer_accepts_device_cached_dataset():
    """LocalOptimizer with a DeviceCachedArrayDataSet runs the fully-fused
    step (batch sampled+augmented inside jit) and converges."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_epoch

    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 255, (64, 3, 8, 8), np.uint8)
    lbls = 1.0 + (imgs[:, 0].mean(axis=(1, 2)) > 127).astype(np.float32)
    ds = DeviceCachedArrayDataSet(imgs, lbls, 16, crop=(8, 8), pad=1,
                                  mean=(127,) * 3, std=(64,) * 3)
    model = (nn.Sequential()
             .add(nn.Reshape((3 * 8 * 8,)))
             .add(nn.Linear(3 * 8 * 8, 2))
             .add(nn.LogSoftMax()))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_epoch(12))
    opt.optimize()
    # assert on the full-dataset eval loss, not the (noisy) last-batch
    # train loss — with epoch-exact ordering the final batch is arbitrary
    crit = nn.ClassNLLCriterion()
    total = 0.0
    for s in range(0, 64, 16):
        x, y = ds.eval_batch_fn(s)
        out, _ = model.apply(model.get_parameters(), model.get_state(), x,
                             training=False)
        total += float(crit.apply(out, y)) * 16
    assert total / 64 < 0.5, total / 64
    assert opt.driver_state["epoch"] > 1  # epoch accounting still works


class TestShardRotator:
    """HBM shard rotation (DataSet.scala:470-552's cluster-rate IO,
    recast as double-buffered device slots)."""

    @staticmethod
    def _provider(n_shards=4, m=16):
        def provider(i):
            rng = np.random.RandomState(100 + i)
            imgs = rng.randint(0, 255, (m, 3, 8, 8), np.uint8)
            lbls = np.full(m, float(i + 1), np.float32)
            return imgs, lbls
        return provider

    def _make(self, **kw):
        from bigdl_tpu.dataset.device_dataset import ShardRotator
        kw.setdefault("chunk_bytes", 4 * 3 * 8 * 8)  # 4 rows per pump
        return ShardRotator(self._provider(), 4, 4, crop=(6, 6),
                            shuffle_shards=False, **kw)

    def test_pump_is_bounded_and_rotate_swaps_slot(self):
        import jax
        import jax.numpy as jnp

        rot = self._make()
        tmpl = rot.template

        @jax.jit
        def draw(images, labels, key):
            return tmpl.batch_fn_on(images, labels, key,
                                    epoch=jnp.int32(0), pos=jnp.int32(0))

        _, y0 = draw(rot.images, rot.labels, jax.random.PRNGKey(0))
        assert set(np.asarray(y0).tolist()) == {1.0}
        pumps = 1
        while not rot.pump():
            pumps += 1
        assert pumps == 4  # 16 rows / 4 rows-per-chunk
        rot.rotate()
        _, y1 = draw(rot.images, rot.labels, jax.random.PRNGKey(1))
        assert set(np.asarray(y1).tolist()) == {2.0}
        # swapping slots was an argument change, not a recompile
        assert draw._cache_size() == 1

    def test_full_cycle_visits_every_shard_exactly_once(self):
        rot = self._make()
        seen = []
        for _ in range(4):
            seen.append(float(np.asarray(rot.labels)[0]))
            while not rot.staged:
                rot.pump()
            rot.rotate()
        assert sorted(seen) == [1.0, 2.0, 3.0, 4.0]
        # next cycle starts over in the same fixed order
        assert float(np.asarray(rot.labels)[0]) == seen[0]

    def test_rotated_slot_content_matches_provider(self):
        rot = self._make()
        while not rot.staged:
            rot.pump()
        rot.rotate()
        imgs, lbls = self._provider()(1)
        np.testing.assert_array_equal(np.asarray(rot.images), imgs)
        np.testing.assert_array_equal(np.asarray(rot.labels), lbls)

    def test_rotate_before_staged_raises(self):
        rot = self._make()
        with np.testing.assert_raises(RuntimeError):
            rot.rotate()

    def test_epoch_exact_sampling_within_shard(self):
        import jax
        import jax.numpy as jnp

        rot = self._make()
        tmpl = rot.template
        idxs = []
        for it in range(4):  # 4 batches of 4 = one shard epoch
            idx = tmpl.sample_indices(epoch=jnp.int32(0),
                                      pos=jnp.int32(it * 4))
            idxs.extend(np.asarray(idx).tolist())
        assert sorted(idxs) == list(range(16))


def test_shard_rotator_sharded_slots_on_mesh():
    """Rotation with slots sharded over a data mesh (the v5e-8 ImageNet
    layout: each chip holds 1/n of both slots); swapping stays an
    argument rebind on the same compiled step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bigdl_tpu.dataset.device_dataset import ShardRotator

    devs = jax.devices()[:8]
    mesh = Mesh(np.array(devs), ("data",))
    sh = NamedSharding(mesh, P("data"))
    m = 16

    def provider(i):
        r = np.random.RandomState(10 + i)
        return (r.randint(0, 255, (m, 3, 8, 8), np.uint8),
                np.full(m, float(i + 1), np.float32))

    rot = ShardRotator(provider, 3, 8, crop=(6, 6), shuffle_shards=False,
                       chunk_bytes=3 * 3 * 8 * 8, sharding=sh)
    assert rot.images.sharding.spec == P("data")
    tmpl = rot.template

    @jax.jit
    def draw(images, labels, key):
        return tmpl.batch_fn_on(images, labels, key,
                                epoch=jnp.int32(0), pos=jnp.int32(0))

    _, y0 = draw(rot.images, rot.labels, jax.random.PRNGKey(0))
    assert set(np.asarray(y0).tolist()) == {1.0}
    while not rot.pump():
        pass
    rot.rotate()
    assert rot.images.sharding.spec == P("data")
    _, y1 = draw(rot.images, rot.labels, jax.random.PRNGKey(1))
    assert set(np.asarray(y1).tolist()) == {2.0}
    assert draw._cache_size() == 1
    # staged content identical to the provider's shard
    imgs1, _ = provider(1)
    np.testing.assert_array_equal(np.asarray(rot.images), imgs1)


def test_optimizer_trains_from_rotating_dataset():
    """The Optimizer drives a RotatingDeviceDataSet end to end: slot
    arrays are step ARGUMENTS (each rotation rebinds, never retraces),
    after_step pumps/rotates at shard boundaries, and epoch accounting
    spans the full dataset."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import RotatingDeviceDataSet, ShardRotator
    from bigdl_tpu.optim import Optimizer, SGD, max_iteration

    m_per = 16   # shard size; batch 8 -> 2 iters per shard
    protos = np.random.RandomState(42).randn(4, 3, 8, 8)

    def provider(i):
        r = np.random.RandomState(50 + i)
        xs = np.clip(protos[i % 4] * 40 + 128 +
                     r.randn(m_per, 3, 8, 8) * 10, 0, 255)
        return xs.astype(np.uint8), np.full(m_per, float(i % 4 + 1),
                                            np.float32)

    rot = ShardRotator(provider, 4, 8, crop=(8, 8), flip=False,
                       mean=(128,) * 3, std=(64,) * 3,
                       chunk_bytes=8 * 3 * 8 * 8, shuffle_shards=False)
    ds = RotatingDeviceDataSet(rot)
    assert ds.size() == 64

    model = (nn.Sequential().add(nn.Reshape((3 * 8 * 8,)))
             .add(nn.Linear(3 * 8 * 8, 4)).add(nn.LogSoftMax()))
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=8)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(17))  # 2+ full cycles of 8 iters
    trained = opt.optimize()
    assert np.isfinite(opt.driver_state["Loss"])
    # 16 iterations consumed exactly 2 full dataset epochs
    assert opt.driver_state["epoch"] >= 3
    assert ds._consumed_shards == 8
    # each shard's class is separable from its prototype: the trained
    # model must beat chance decisively on clean prototypes
    xs = np.clip(protos * 40 + 128, 0, 255).astype(np.float32)
    xs = (xs - 128.0) / 64.0
    preds = np.asarray(trained.evaluate().forward(
        xs.astype(np.float32))).argmax(-1) + 1
    assert (preds == np.arange(1, 5)).mean() >= 0.75


def test_set_validation_accepts_device_cached_dataset():
    """Trigger-driven validation rides the HBM cache directly (the
    fastest eval path is reachable from the Optimizer: the device form
    of DistriOptimizer.scala:607-686 validating on the cached
    distributed dataset). Scores must equal the host-fed Sample path."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import (LocalOptimizer, SGD, Top1Accuracy,
                                 every_epoch, max_iteration)
    from bigdl_tpu.utils.random import RandomGenerator

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, (32, 3, 8, 8), np.uint8)
    lbls = (rng.randint(0, 2, 32) + 1).astype(np.float32)
    train = DeviceCachedArrayDataSet(imgs, lbls, 8, flip=False,
                                     mean=(127,) * 3, std=(64,) * 3)
    vimgs = rng.randint(0, 255, (20, 3, 8, 8), np.uint8)
    vlbls = (rng.randint(0, 2, 20) + 1).astype(np.float32)
    # batch 8 over 20 rows: exercises the wrapped-tail trim too
    val_dev = DeviceCachedArrayDataSet(vimgs, vlbls, 8, flip=False,
                                       mean=(127,) * 3, std=(64,) * 3)

    def build():
        RandomGenerator.set_seed(4)
        return (nn.Sequential().add(nn.Reshape((3 * 8 * 8,)))
                .add(nn.Linear(3 * 8 * 8, 2)).add(nn.LogSoftMax()))

    scores = {}
    for kind in ("device", "host"):
        model = build()
        opt = LocalOptimizer(model, train, nn.ClassNLLCriterion(),
                             batch_size=8)
        opt.set_optim_method(SGD(learning_rate=0.1))
        if kind == "device":
            opt.set_validation(every_epoch(), val_dev, [Top1Accuracy()])
        else:
            x_norm = ((vimgs.astype(np.float32) - 127.0) / 64.0)
            vs = [Sample(x_norm[i], vlbls[i]) for i in range(20)]
            opt.set_validation(
                every_epoch(), DataSet.array(vs).transform(
                    SampleToMiniBatch(8)), [Top1Accuracy()])
        opt.set_end_when(max_iteration(4))
        opt.optimize()
        scores[kind] = opt.driver_state["score"]
    assert scores["device"] == scores["host"], scores
