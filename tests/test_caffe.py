"""Caffe importer tests (reference model: CaffeLoaderSpec against tiny
prototxt/caffemodel fixtures, test/resources/caffe). Fixtures here are
generated with the same wire codec the importer decodes with, using the
public caffe.proto field numbers."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils import proto
from bigdl_tpu.utils.caffe import (CaffeLoader, load_caffe, parse_caffemodel,
                                   parse_prototxt)

PROTOTXT = """
name: "TinyNet"
# a comment
layer {
  name: "data" type: "Input" top: "data"
  input_param { shape { dim: 1 dim: 3 dim: 8 dim: 8 } }
}
layer {
  name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1 }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer {
  name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "fc" type: "InnerProduct" bottom: "pool1" top: "fc"
  inner_product_param { num_output: 10 }
}
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""


def test_parse_prototxt():
    net = parse_prototxt(PROTOTXT)
    assert net["name"] == ["TinyNet"]
    layers = net["layer"]
    assert len(layers) == 6
    conv = layers[1]
    assert conv["type"] == ["Convolution"]
    cp = conv["convolution_param"][0]
    assert cp["num_output"] == [4]
    assert cp["kernel_size"] == [3]
    pool = layers[3]
    assert pool["pooling_param"][0]["pool"] == ["MAX"]


def test_prototxt_topology_build():
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "net.prototxt")
        with open(p, "w") as f:
            f.write(PROTOTXT)
        model = load_caffe(def_path=p)
    x = np.random.randn(1, 3, 8, 8).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    assert out.shape == (1, 10)
    np.testing.assert_allclose(out.sum(), 1.0, atol=1e-5)  # softmax
    assert model.find("conv1") is not None


# ---------------------------------------------------- binary caffemodel

def _blob(arr: np.ndarray) -> bytes:
    shape_msg = b"".join(proto.encode_field(1, int(d), wire_type=0)
                         for d in arr.shape)
    payload = np.asarray(arr, "<f4").tobytes()
    return (proto.encode_message(7, shape_msg) +
            proto.encode_field(5, payload, wire_type=2))


def _layer_v2(name, type_, bottoms, tops, blobs=(), param_field=None,
              param_payload=b"") -> bytes:
    msg = proto.encode_field(1, name) + proto.encode_field(2, type_)
    for b in bottoms:
        msg += proto.encode_field(3, b)
    for t in tops:
        msg += proto.encode_field(4, t)
    for bl in blobs:
        msg += proto.encode_message(7, _blob(bl))
    if param_field:
        msg += proto.encode_message(param_field, param_payload)
    return msg


def _make_binary_net(w, b, wfc, bfc) -> bytes:
    conv_param = (proto.encode_field(1, 2, wire_type=0) +    # num_output=2
                  proto.encode_field(4, 3, wire_type=0) +    # kernel=3
                  proto.encode_field(6, 1, wire_type=0) +    # stride=1
                  proto.encode_field(3, 1, wire_type=0))     # pad=1
    ip_param = proto.encode_field(1, 5, wire_type=0)         # num_output=5
    net = proto.encode_field(1, "BinNet")
    net += proto.encode_message(100, _layer_v2(
        "conv", "Convolution", ["data"], ["conv"], [w, b], 106, conv_param))
    net += proto.encode_message(100, _layer_v2(
        "relu", "ReLU", ["conv"], ["conv"]))
    net += proto.encode_message(100, _layer_v2(
        "fc", "InnerProduct", ["conv"], ["fc"], [wfc, bfc], 117, ip_param))
    return net


def test_binary_caffemodel_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    w = rng.randn(2, 3, 3, 3).astype(np.float32) * 0.2
    b = rng.randn(2).astype(np.float32)
    wfc = rng.randn(5, 2 * 4 * 4).astype(np.float32) * 0.1
    bfc = rng.randn(5).astype(np.float32)
    path = tmp_path / "net.caffemodel"
    path.write_bytes(_make_binary_net(w, b, wfc, bfc))

    name, layers, _ = parse_caffemodel(path.read_bytes())
    assert name == "BinNet"
    assert [l.name for l in layers] == ["conv", "relu", "fc"]
    np.testing.assert_allclose(layers[0].blobs[0], w)

    model = load_caffe(model_path=str(path))
    x = rng.randn(1, 3, 4, 4).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    # numpy reference: conv(pad1) -> relu -> flatten -> fc
    import jax
    import jax.numpy as jnp
    ref_conv = jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ref = np.maximum(np.asarray(ref_conv) + b.reshape(1, -1, 1, 1), 0)
    ref = ref.reshape(1, -1) @ wfc.T + bfc
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_prototxt_plus_caffemodel_weights(tmp_path):
    """Text topology + binary weights matched by layer name (the
    CaffeLoader.load(defPath, modelPath) path)."""
    rng = np.random.RandomState(1)
    w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.2
    b = rng.randn(4).astype(np.float32)
    wfc = rng.randn(10, 4 * 4 * 4).astype(np.float32) * 0.1
    bfc = rng.randn(10).astype(np.float32)
    conv_param = (proto.encode_field(1, 4, wire_type=0) +
                  proto.encode_field(4, 3, wire_type=0) +
                  proto.encode_field(3, 1, wire_type=0))
    ip_param = proto.encode_field(1, 10, wire_type=0)
    net = proto.encode_message(100, _layer_v2(
        "conv1", "Convolution", ["data"], ["conv1"], [w, b], 106,
        conv_param))
    net += proto.encode_message(100, _layer_v2(
        "fc", "InnerProduct", ["pool1"], ["fc"], [wfc, bfc], 117, ip_param))
    mp = tmp_path / "weights.caffemodel"
    mp.write_bytes(net)
    dp = tmp_path / "net.prototxt"
    dp.write_text(PROTOTXT)
    model = CaffeLoader(str(dp), str(mp)).load()
    x = np.random.randn(1, 3, 8, 8).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    assert out.shape == (1, 10)
    # conv1 weights came from the binary net
    conv1 = model.find("conv1")
    np.testing.assert_allclose(np.asarray(conv1.get_parameters()["weight"]),
                               w, atol=1e-6)


def test_inplace_layers_chain():
    """top == bottom chains (caffe in-place ReLU/Dropout) must thread
    through the graph in order."""
    txt = """
layer { name: "data" type: "Input" top: "d"
  input_param { shape { dim: 1 dim: 2 } } }
layer { name: "ip" type: "InnerProduct" bottom: "d" top: "ip"
  inner_product_param { num_output: 3 } }
layer { name: "r1" type: "ReLU" bottom: "ip" top: "ip" }
layer { name: "s" type: "Sigmoid" bottom: "ip" top: "out" }
"""
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "x.prototxt")
        open(p, "w").write(txt)
        model = load_caffe(def_path=p)
    x = np.random.randn(2, 2).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    assert out.shape == (2, 3)
    assert (out > 0).all() and (out < 1).all()  # sigmoid output


def test_concat_and_eltwise():
    txt = """
layer { name: "data" type: "Input" top: "d"
  input_param { shape { dim: 1 dim: 2 dim: 4 dim: 4 } } }
layer { name: "c1" type: "Convolution" bottom: "d" top: "c1"
  convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "c2" type: "Convolution" bottom: "d" top: "c2"
  convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "cat" type: "Concat" bottom: "c1" bottom: "c2" top: "cat"
  concat_param { axis: 1 } }
layer { name: "sum" type: "Eltwise" bottom: "c1" bottom: "c2" top: "sum"
  eltwise_param { operation: SUM } }
"""
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "x.prototxt")
        open(p, "w").write(txt)
        model = load_caffe(def_path=p)
    x = np.random.randn(1, 2, 4, 4).astype(np.float32)
    out = model.evaluate().forward(x)
    # two sinks: cat [1,4,4,4] and sum [1,2,4,4]
    outs = list(out)
    shapes = sorted(np.asarray(o).shape for o in outs)
    assert shapes == [(1, 2, 4, 4), (1, 4, 4, 4)]


def test_deconvolution_layer():
    """Review regression: Deconvolution imports as transposed conv with
    upsampling shape semantics and caffe's [in, out/g, kh, kw] blob."""
    rng = np.random.RandomState(2)
    w = rng.randn(3, 4, 2, 2).astype(np.float32) * 0.3  # [in, out, 2, 2]
    b = np.zeros(4, np.float32)
    deconv_param = (proto.encode_field(1, 4, wire_type=0) +   # num_output
                    proto.encode_field(4, 2, wire_type=0) +   # kernel 2
                    proto.encode_field(6, 2, wire_type=0))    # stride 2
    net = proto.encode_message(100, _layer_v2(
        "up", "Deconvolution", ["data"], ["up"], [w, b], 106, deconv_param))
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "d.caffemodel")
        open(p, "wb").write(net)
        model = load_caffe(model_path=p)
    x = np.random.randn(1, 3, 5, 5).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    assert out.shape == (1, 4, 10, 10)  # 2x upsample


def test_rectangular_kernel_repeated_field():
    """'kernel_size: 1 kernel_size: 7' (Inception-v3 1x7 conv)."""
    txt = """
layer { name: "data" type: "Input" top: "d"
  input_param { shape { dim: 1 dim: 2 dim: 9 dim: 9 } } }
layer { name: "c" type: "Convolution" bottom: "d" top: "c"
  convolution_param { num_output: 3 kernel_size: 1 kernel_size: 7 } }
"""
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "x.prototxt")
        open(p, "w").write(txt)
        model = load_caffe(def_path=p)
    x = np.random.randn(1, 2, 9, 9).astype(np.float32)
    out = np.asarray(model.evaluate().forward(x))
    assert out.shape == (1, 3, 9, 3)  # kh=1, kw=7
