"""Per-rule lint fixtures: every shipped rule fires on a purpose-built
positive case AND honors a `# bigdl: disable=RULE` suppression, plus
engine-level behaviors (file suppressions, precision exemptions, JSON)."""
import json

import pytest

from bigdl_tpu.analysis import (available_rules, format_text, lint_source,
                                to_json)

HEADER = """\
import functools
import random
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
"""


def names(findings, only_active=True):
    return [f.rule for f in findings
            if not (only_active and f.suppressed)]


def run(body):
    return lint_source(HEADER + body, "fixture.py")


# One (positive, suppressed) source pair per rule. The suppressed variant
# is the same pitfall with an explicit `# bigdl: disable=<rule>`.
CASES = {
    "host-sync": (
        """
@jax.jit
def f(x):
    y = jnp.sum(x)
    return float(y)
""",
        """
@jax.jit
def f(x):
    y = jnp.sum(x)
    return float(y)  # bigdl: disable=host-sync
""",
    ),
    "traced-branch": (
        """
@jax.jit
def f(x):
    y = jnp.sum(x)
    if y > 0:
        return y
    return -y
""",
        """
@jax.jit
def f(x):
    y = jnp.sum(x)
    if y > 0:  # bigdl: disable=traced-branch
        return y
    return -y
""",
    ),
    "jnp-in-host-loop": (
        """
def feed(batches):
    out = []
    for b in batches:
        out.append(jnp.zeros((128, 128)))
    return out
""",
        """
def feed(batches):
    out = []
    for b in batches:
        # bigdl: disable=jnp-in-host-loop
        out.append(jnp.zeros((128, 128)))
    return out
""",
    ),
    "growing-concat-in-loop": (
        """
def decode(step, tok):
    out = jnp.zeros((1, 4))
    for t in range(16):
        out = jnp.concatenate([out, step(tok)])
    return out
""",
        """
def decode(step, tok):
    out = jnp.zeros((1, 4))
    for t in range(16):
        # bigdl: disable=growing-concat-in-loop
        out = jnp.concatenate([out, step(tok)])
    return out
""",
    ),
    "gather-in-step-loop": (
        """
def train(ref_params, step):
    state = 0
    for i in range(100):
        full = lax.all_gather(ref_params, "data")
        state = step(state, full)
    return state
""",
        """
def train(ref_params, step):
    state = 0
    for i in range(100):
        # bigdl: disable=gather-in-step-loop
        full = lax.all_gather(ref_params, "data")
        state = step(state, full)
    return state
""",
    ),
    "jit-static-args": (
        """
def g(x, mode):
    if mode:
        return x * 2
    return x

f = jax.jit(g)
""",
        """
def g(x, mode):
    if mode:  # bigdl: disable=jit-static-args
        return x * 2
    return x

f = jax.jit(g)
""",
    ),
    "use-after-donate": (
        """
def train(params, grads):
    step = jax.jit(lambda p, g: p - g, donate_argnums=(0,))
    new_params = step(params, grads)
    norm = jnp.sum(params)
    return new_params, norm
""",
        """
def train(params, grads):
    step = jax.jit(lambda p, g: p - g, donate_argnums=(0,))
    new_params = step(params, grads)
    norm = jnp.sum(params)  # bigdl: disable=use-after-donate
    return new_params, norm
""",
    ),
    "apply-mutates-self": (
        """
class Layer:
    def apply(self, params, state, input, *, training=False, rng=None):
        self.cache = input
        return input, state
""",
        """
class Layer:
    def apply(self, params, state, input, *, training=False, rng=None):
        self.cache = input  # bigdl: disable=apply-mutates-self
        return input, state
""",
    ),
    "host-state-in-trace": (
        """
@jax.jit
def f(x):
    return x * time.time()
""",
        """
@jax.jit
def f(x):
    return x * time.time()  # bigdl: disable=host-state-in-trace
""",
    ),
    "global-rng": (
        """
def sample(n):
    return np.random.rand(n)
""",
        """
def sample(n):
    return np.random.rand(n)  # bigdl: disable=global-rng
""",
    ),
    "bare-except": (
        """
def f():
    try:
        return 1
    except:
        return 2
""",
        """
def f():
    try:
        return 1
    except:  # bigdl: disable=bare-except
        return 2
""",
    ),
    "telemetry-in-trace": (
        """
from bigdl_tpu import telemetry

@jax.jit
def f(x):
    with telemetry.span("optimizer/step"):
        return x * 2
""",
        """
from bigdl_tpu import telemetry

@jax.jit
def f(x):
    with telemetry.span("optimizer/step"):  # bigdl: disable=telemetry-in-trace
        return x * 2
""",
    ),
    "sync-in-loop": (
        """
def train(step, params, batches):
    for x in batches:
        params, loss = step(params, x)
        jax.block_until_ready(params)
        print(float(loss))
""",
        """
def train(step, params, batches):
    for x in batches:
        params, loss = step(params, x)
        jax.block_until_ready(params)  # bigdl: disable=sync-in-loop
        print(float(loss))  # bigdl: disable=sync-in-loop
""",
    ),
    "hardcoded-tuned-constant": (
        """
steps_per_sync = 4

def serve(svc, opt):
    svc.configure(length_buckets=(16, 32),
                  prefix_cache_bytes=256 << 20)
    opt.set_steps_per_sync(8)
""",
        """
steps_per_sync = 4  # bigdl: disable=hardcoded-tuned-constant

def serve(svc, opt):
    svc.configure(length_buckets=(16, 32),  # bigdl: disable=hardcoded-tuned-constant
                  prefix_cache_bytes=256 << 20)  # bigdl: disable=hardcoded-tuned-constant
    opt.set_steps_per_sync(8)  # bigdl: disable=hardcoded-tuned-constant
""",
    ),
    "retry-no-backoff": (
        """
def run(fn):
    for attempt in range(5):
        try:
            return fn()
        except Exception:
            time.sleep(1.0)
""",
        """
def run(fn):
    for attempt in range(5):
        try:
            return fn()
        except Exception:
            time.sleep(1.0)  # bigdl: disable=retry-no-backoff
""",
    ),
    "implicit-upcast-in-trace": (
        """
class Layer(Module):
    def forward_fn(self, params, input, *, training=False, rng=None):
        h = input * params["w"]
        return h.astype(jnp.float32)
""",
        """
class Layer(Module):
    def forward_fn(self, params, input, *, training=False, rng=None):
        h = input * params["w"]
        return h.astype(jnp.float32)  # bigdl: disable=implicit-upcast-in-trace
""",
    ),
    "unseeded-shuffle": (
        """
def epoch_order(records):
    rng = np.random.default_rng()
    rng.shuffle(records)
    return records
""",
        """
def epoch_order(records):
    rng = np.random.default_rng()
    rng.shuffle(records)  # bigdl: disable=unseeded-shuffle
    return records
""",
    ),
    "raw-pallas-call": (
        """
from jax.experimental import pallas as pl

def double(x, kern):
    return pl.pallas_call(kern, out_shape=None)(x)
""",
        """
from jax.experimental import pallas as pl

def double(x, kern):
    return pl.pallas_call(kern, out_shape=None)(x)  # bigdl: disable=raw-pallas-call
""",
    ),
    "blocking-copy-in-checkpoint": (
        """
from bigdl_tpu.utils.serialization import save_checkpoint
def snapshot(leaves, step):
    out = {}
    for key in leaves:
        shard = step(leaves[key])
        out[key] = np.asarray(shard)
    return out
""",
        """
from bigdl_tpu.utils.serialization import save_checkpoint
def snapshot(leaves, step):
    out = {}
    for key in leaves:
        shard = step(leaves[key])
        out[key] = np.asarray(shard)  # bigdl: disable=blocking-copy-in-checkpoint
    return out
""",
    ),
    "metric-label-cardinality": (
        """
import bigdl_tpu.telemetry as telemetry
reqs = telemetry.counter("serving/x/requests", "d")
def handle(batch):
    for i, r in enumerate(batch):
        reqs.inc(req=f"req-{i}")
""",
        """
import bigdl_tpu.telemetry as telemetry
reqs = telemetry.counter("serving/x/requests", "d")
def handle(batch):
    for i, r in enumerate(batch):
        reqs.inc(req=f"req-{i}")  # bigdl: disable=metric-label-cardinality
""",
    ),
    "unbounded-cache-growth": (
        """
import bigdl_tpu.serving

class ResponseCache:
    def __init__(self):
        self._seen = {}

    def put(self, key, value):
        self._seen[key] = value
""",
        """
import bigdl_tpu.serving

class ResponseCache:
    def __init__(self):
        self._seen = {}

    def put(self, key, value):
        self._seen[key] = value  # bigdl: disable=unbounded-cache-growth
""",
    ),
}


def test_blocking_copy_skips_files_off_the_checkpoint_surface():
    # the same loop WITHOUT a serialization/elastic import is ordinary
    # host code (scoring, plotting) — not the checkpoint hot path
    src = HEADER + """
def snapshot(leaves, step):
    out = {}
    for key in leaves:
        shard = step(leaves[key])
        out[key] = np.asarray(shard)
    return out
"""
    assert "blocking-copy-in-checkpoint" not in names(
        lint_source(src, "fixture.py"))


def test_blocking_copy_flags_device_get_in_loop():
    src = HEADER + """
from bigdl_tpu.elastic import save_checkpoint
def fetch_all(tree):
    host = []
    for leaf in tree:
        host.append(jax.device_get(leaf))
    return host
"""
    assert "blocking-copy-in-checkpoint" in names(
        lint_source(src, "fixture.py"))


def test_blocking_copy_ignores_host_asarray_in_loop():
    # np.asarray over plain host values (no device-ish producer in the
    # loop) is list/parsing work, not a device fetch
    src = HEADER + """
from bigdl_tpu.utils.serialization import load_checkpoint
def widen(rows):
    out = []
    for r in rows:
        out.append(np.asarray(r))
    return out
"""
    assert "blocking-copy-in-checkpoint" not in names(
        lint_source(src, "fixture.py"))


def test_retry_no_backoff_flags_fixed_attribute_interval():
    # the exact shape this rule was written to remove from
    # optimizer.py: except Exception + sleep(self.retry_interval_s)
    src = HEADER + """
class Driver:
    def optimize(self):
        while True:
            try:
                return self._impl()
            except Exception:
                time.sleep(self.retry_interval_s)
"""
    findings = lint_source(src, "fixture.py")
    assert "retry-no-backoff" in names(findings)


def test_retry_no_backoff_passes_computed_backoff():
    # a delay assigned in the handler grows across attempts — the
    # sanctioned pattern must not be flagged
    src = HEADER + """
def run(fn, backoff):
    for attempt in range(5):
        try:
            return fn()
        except Exception:
            delay = backoff(attempt)
            time.sleep(delay)
"""
    findings = lint_source(src, "fixture.py")
    assert "retry-no-backoff" not in names(findings, only_active=False)


def test_retry_no_backoff_passes_growing_attribute_backoff():
    # an attribute the loop rebinds (self.delay *= 2) IS a backoff —
    # only never-reassigned attributes (config knobs) count as fixed
    src = HEADER + """
class Driver:
    def run(self, fn):
        while True:
            try:
                return fn()
            except Exception:
                self.delay *= 2
                time.sleep(self.delay)
"""
    findings = lint_source(src, "fixture.py")
    assert "retry-no-backoff" not in names(findings, only_active=False)


def test_retry_no_backoff_ignores_narrow_excepts():
    # a narrow except (one concrete error) is a deliberate recovery
    # path, not a blanket retry — out of scope for this rule
    src = HEADER + """
def run(fn):
    for attempt in range(5):
        try:
            return fn()
        except ConnectionResetError:
            time.sleep(1.0)
"""
    findings = lint_source(src, "fixture.py")
    assert "retry-no-backoff" not in names(findings, only_active=False)


def test_metric_label_cardinality_flags_str_of_request_id():
    # per-request identity stringified into a label value: one fresh
    # series per request — the cardinality explosion the rule exists
    # to catch (trace_id goes in SPAN ARGS, never labels)
    src = HEADER + """
import bigdl_tpu.telemetry as telemetry
lat = telemetry.histogram("serving/x/latency_ms", "d")
def done(trace_id, ms):
    lat.observe(ms, trace=str(trace_id))
"""
    findings = lint_source(src, "fixture.py")
    assert "metric-label-cardinality" in names(findings)


def test_metric_label_cardinality_flags_bare_request_id_name():
    # the id itself (no f-string needed) is already one series per
    # request; instruments tracked through self-attribute bindings too
    src = HEADER + """
import bigdl_tpu.telemetry as telemetry
class Stats:
    def __init__(self, r):
        self._g = r.gauge("serving/x/depth", "d")
    def on_req(self, request_id, d):
        self._g.set(d, request=request_id)
"""
    findings = lint_source(src, "fixture.py")
    assert "metric-label-cardinality" in names(findings)


def test_metric_label_cardinality_passes_bounded_labels_and_spans():
    # a model-name label is a small fixed vocabulary; trace_id in SPAN
    # args is the sanctioned home; .add on a plain set is not an
    # instrument update (receiver tracking, not method-name matching)
    src = HEADER + """
import bigdl_tpu.telemetry as telemetry
reqs = telemetry.counter("serving/x/requests", "d")
def handle(model_name, trace_id, items):
    reqs.inc(model=model_name)
    seen = set()
    for i in items:
        seen.add(i)
    with telemetry.span("serving/request", trace_id=trace_id):
        pass
"""
    findings = lint_source(src, "fixture.py")
    assert "metric-label-cardinality" not in names(findings,
                                                  only_active=False)


def test_unseeded_shuffle_passes_seeded_generators():
    # the sanctioned pattern: an explicit seed (any expression) makes
    # the order a pure function of it — nothing to flag
    src = HEADER + """
def epoch_order(records, seed, epoch):
    rng = np.random.default_rng((seed, epoch))
    rng.shuffle(records)
    old = np.random.RandomState(seed)
    return old.permutation(len(records))
"""
    findings = lint_source(src, "fixture.py")
    assert "unseeded-shuffle" not in names(findings, only_active=False)


def test_unseeded_shuffle_flags_self_attribute_and_wrapper():
    # self._rng bound to an unseeded wrapper (Generator(PCG64())) is the
    # sneaky form: construction and use sit in different methods
    src = HEADER + """
class Feed:
    def __init__(self):
        self._rng = np.random.Generator(np.random.PCG64())

    def shuffle(self, xs):
        self._rng.shuffle(xs)
"""
    findings = lint_source(src, "fixture.py")
    assert "unseeded-shuffle" in names(findings)


def test_unseeded_shuffle_scoping_no_cross_function_taint():
    # an unseeded `rng` in one function (used for non-shuffle draws)
    # must not taint a seeded `rng` in a DIFFERENT function; and a
    # seeded rebinding in the same scope exonerates
    src = HEADER + """
def jitter(xs):
    rng = np.random.default_rng()
    return xs + rng.normal()

def epoch_order(xs, seed):
    rng = np.random.default_rng(seed)
    rng.shuffle(xs)
    return xs

def rebound(xs, seed):
    rng = np.random.default_rng()
    rng = np.random.default_rng(seed)
    rng.shuffle(xs)
"""
    findings = lint_source(src, "fixture.py")
    assert "unseeded-shuffle" not in names(findings, only_active=False)


def test_unseeded_shuffle_module_level_binding_reaches_functions():
    src = HEADER + """
rng = np.random.default_rng()

def epoch_order(xs):
    rng.shuffle(xs)
"""
    findings = lint_source(src, "fixture.py")
    assert "unseeded-shuffle" in names(findings)


def test_unseeded_shuffle_flags_global_numpy_permutation():
    src = HEADER + """
def order(n):
    return np.random.permutation(n)
"""
    findings = lint_source(src, "fixture.py")
    assert "unseeded-shuffle" in names(findings)


def test_implicit_upcast_skips_files_off_the_precision_surface():
    # a plain jax utility file (no Module-ish class, no
    # bigdl_tpu.precision import) never runs under a policy's compute
    # dtype — its f32 casts are its own business
    src = HEADER + """
@jax.jit
def f(x):
    return x.astype(jnp.float32)
"""
    findings = lint_source(src, "fixture.py")
    assert "implicit-upcast-in-trace" not in names(findings,
                                                   only_active=False)


def test_implicit_upcast_fires_via_precision_import():
    # importing bigdl_tpu.precision marks the file as a policy consumer
    # even without a Module class (e.g. the optimizer's step builder)
    src = HEADER + """
from bigdl_tpu.precision import PrecisionPolicy

@jax.jit
def step(g):
    h = jnp.tanh(g)
    eps = jnp.float32(1e-6)   # host literal: trace-time folding, fine
    return jnp.float32(h) + eps
"""
    findings = lint_source(src, "fixture.py")
    hits = [f for f in findings if f.rule == "implicit-upcast-in-trace"]
    assert len(hits) == 1 and hits[0].line == HEADER.count("\n") + 8


def test_implicit_upcast_ignores_host_side_code_in_layer_files():
    # a host-side helper (not apply/forward_fn, not jitted) in a Module
    # file quantizes weights AT REST — no trace, no finding
    src = HEADER + """
class Layer(Module):
    def forward_fn(self, params, input, *, training=False, rng=None):
        return input * params["w"]

    def export_weights(self):
        return np.asarray(self.w).astype(np.float32)
"""
    findings = lint_source(src, "fixture.py")
    assert "implicit-upcast-in-trace" not in names(findings,
                                                   only_active=False)


def test_implicit_upcast_asarray_traced_vs_host_constant():
    # dtype-less asarray is flagged only over traced values; a host
    # constant folds at trace time, and dtype= is always sanctioned
    src = HEADER + """
class Layer(Module):
    def forward_fn(self, params, input, *, training=False, rng=None):
        table = jnp.asarray([0.5, 1.5])          # host constant: fine
        h = jnp.tanh(input)
        h = jnp.asarray(h)                       # traced: flagged
        y = jnp.asarray(h, dtype=h.dtype)        # explicit: fine
        return h * y * table[0]
"""
    findings = lint_source(src, "fixture.py")
    hits = [f for f in findings
            if f.rule == "implicit-upcast-in-trace" and not f.suppressed]
    assert len(hits) == 1


def test_sync_in_loop_skips_files_without_jax():
    # .item()/float() in a numpy-only file touch no device; the rule
    # must not fire where jax is never imported
    src = """
import numpy as np

def f(cols):
    out = []
    for c in cols:
        out.append(c.item())
        out.append(float(np.sum(c)))
    return out
"""
    findings = lint_source(src, "fixture.py")
    assert "sync-in-loop" not in names(findings, only_active=False)


def test_sync_in_loop_flags_inner_loop_once():
    src = HEADER + """
def train(step, params, epochs, batches):
    for e in range(epochs):
        for x in batches:
            params, loss = step(params, x)
            jax.block_until_ready(params)
"""
    findings = lint_source(src, "fixture.py")
    hits = [f for f in findings if f.rule == "sync-in-loop"]
    assert len(hits) == 1  # the inner loop's finding, not doubled


def test_sync_in_loop_ignores_float_of_host_values():
    src = HEADER + """
def summarize(xs):
    total = 0.0
    for x in xs:
        total += float(x)  # plain python value, never assigned from a call
    return total
"""
    findings = lint_source(src, "fixture.py")
    assert "sync-in-loop" not in names(findings, only_active=False)


def test_sync_in_loop_ignores_host_parsing_method_calls():
    # method calls on arbitrary objects (string/regex parsing) are host
    # work even in a jax-importing file — float() over them is fine
    src = HEADER + """
def parse(fh):
    total = 0.0
    for line in fh:
        parts = line.split(",")
        total += float(parts[0])
    return total
"""
    findings = lint_source(src, "fixture.py")
    assert "sync-in-loop" not in names(findings, only_active=False)


def test_sync_in_loop_ignores_float_of_host_builtins():
    src = HEADER + """
def count(rows):
    total = 0.0
    for row in rows:
        n = len(row)
        total += float(n)  # host integer, not a device fetch
    return total
"""
    findings = lint_source(src, "fixture.py")
    assert "sync-in-loop" not in names(findings, only_active=False)


def test_sync_in_loop_flags_module_level_script_loop():
    # script-style top-level training loops are the classic per-step
    # sync offender; module level is NOT exempt for this rule
    src = HEADER + """
params = init()
for i in range(1000):
    params, loss = step(params)
    jax.block_until_ready(params)
"""
    findings = lint_source(src, "fixture.py")
    assert "sync-in-loop" in names(findings)


def test_gather_in_step_loop_allows_loop_variant_tree():
    # a REAL train loop re-gathers the params it just updated — the
    # operand changes per iteration, so this is not the pitfall
    body = """
def train(params, step):
    for i in range(100):
        full = lax.all_gather(params, "data")
        params = step(full)
    return params
"""
    assert "gather-in-step-loop" not in names(run(body))


def test_gather_in_step_loop_flags_psum():
    body = """
def train(ref_grads, apply):
    out = []
    for i in range(10):
        g = jax.lax.psum(ref_grads, "data")
        out.append(apply(g))
    return out
"""
    assert "gather-in-step-loop" in names(run(body))


def test_gather_in_step_loop_skips_traced_loops():
    # inside jit, loop-invariant collectives are XLA's to hoist
    body = """
@jax.jit
def f(x):
    for i in range(4):
        y = lax.all_gather(x, "data")
    return y
"""
    assert "gather-in-step-loop" not in names(run(body))


def test_hardcoded_tuned_constant_path_scope():
    # tools/bench files are choice sites; library modules and the
    # sanctioned defaults module are definition sites
    src = HEADER + CASES["hardcoded-tuned-constant"][0]
    assert "hardcoded-tuned-constant" in names(
        lint_source(src, "fixture.py"))
    assert "hardcoded-tuned-constant" in names(
        lint_source(src, "bigdl_tpu/tools/perf.py"))
    assert "hardcoded-tuned-constant" not in names(
        lint_source(src, "bigdl_tpu/optim/optimizer.py"),
        only_active=False)
    assert "hardcoded-tuned-constant" not in names(
        lint_source(src, "bigdl_tpu/autotune/defaults.py"),
        only_active=False)


def test_hardcoded_tuned_constant_exempts_class_defaults():
    # dataclass/class-body defaults are the knob DEFINITIONS
    body = """
class Config:
    steps_per_sync = 4
    length_buckets = (16, 32)
"""
    assert "hardcoded-tuned-constant" not in names(run(body))


def test_hardcoded_tuned_constant_ignores_computed_values():
    # values flowed in from args / a tuned artifact are the point
    body = """
def main(args, svc, tuned):
    steps_per_sync = args.steps_per_sync
    svc.configure(length_buckets=tuple(tuned["length_buckets"]),
                  prefix_cache_bytes=args.cache_bytes)
"""
    assert "hardcoded-tuned-constant" not in names(run(body))


def test_hardcoded_tuned_constant_flags_arithmetic_literals():
    # 256 << 20 is still a hand-picked number
    body = """
def main(svc):
    svc.configure(prefix_cache_bytes=256 << 20)
"""
    assert "hardcoded-tuned-constant" in names(run(body))


def test_raw_pallas_call_exempts_the_kernels_package():
    # the kernel layer is the sanctioned home: the SAME source that
    # fires elsewhere is clean under bigdl_tpu/kernels/
    src = HEADER + CASES["raw-pallas-call"][0]
    assert "raw-pallas-call" in names(lint_source(src, "fixture.py"))
    clean = lint_source(src, "bigdl_tpu/kernels/flashy.py")
    assert "raw-pallas-call" not in names(clean)
    clean2 = lint_source(
        src, "/site-packages/bigdl_tpu/kernels/int8_gemm.py")
    assert "raw-pallas-call" not in names(clean2)


def test_raw_pallas_call_flags_from_import_spelling():
    body = """
from jax.experimental.pallas import pallas_call

def f(x, kern):
    return pallas_call(kern, out_shape=None)(x)
"""
    assert "raw-pallas-call" in names(run(body))


def test_raw_pallas_call_ignores_dispatch_layer_calls():
    # routing through bigdl_tpu.kernels is the sanctioned idiom
    body = """
from bigdl_tpu import kernels

def f(q, k, v):
    out = kernels.attention(q, k, v, causal=True)
    return out if out is not None else q
"""
    assert "raw-pallas-call" not in names(run(body))


def test_case_table_covers_every_shipped_rule():
    assert {r.name for r in available_rules()} == set(CASES)


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_fires_on_positive_fixture(rule):
    positive, _ = CASES[rule]
    findings = run(positive)
    assert rule in names(findings), \
        f"{rule} missed its positive fixture: {format_text(findings)}"


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_suppression_is_honored(rule):
    _, suppressed = CASES[rule]
    findings = run(suppressed)
    assert rule not in names(findings), \
        f"{rule} ignored its suppression: {format_text(findings)}"
    # the finding is retained as suppressed, not silently dropped
    assert rule in names(findings, only_active=False)


def test_file_level_suppression():
    _, _ = CASES["bare-except"]
    src = "# bigdl: disable-file=bare-except\n" + HEADER + CASES[
        "bare-except"][0]
    findings = lint_source(src, "fixture.py")
    assert "bare-except" not in names(findings)
    assert "bare-except" in names(findings, only_active=False)


def test_standalone_comment_suppresses_next_line():
    body = """
def f():
    try:
        return 1
    # bigdl: disable=bare-except
    except:
        return 2
"""
    assert "bare-except" not in names(run(body))


# ------------------------------------------------- precision exemptions

def test_static_shape_branch_not_flagged():
    body = """
@jax.jit
def f(x):
    y = jnp.sum(x, axis=-1)
    if y.ndim == 1:
        y = y[None]
    if x.shape[0] > 4:
        y = y * 2
    return y
"""
    assert names(run(body)) == []


def test_is_none_and_membership_not_flagged():
    body = """
@jax.jit
def f(x, rng=None):
    cache = {}
    y = jnp.sum(x)
    cache["k"] = y
    if rng is None:
        return y
    if "k" in cache:
        return y * 2
    return y
"""
    assert names(run(body)) == []


def test_per_item_loop_construction_not_flagged():
    body = """
def stage(chunks):
    return [jnp.asarray(c) for c in chunks]

def stage2(chunks):
    out = []
    for c in chunks:
        out.append(jnp.asarray(c))
    return out
"""
    assert names(run(body)) == []


def test_dataset_transformer_apply_is_not_trace_surface():
    body = """
class Normalizer(Transformer):
    def apply(self, it):
        for s in it:
            yield np.asarray(s, np.float32) / 255.0
"""
    assert names(run(body)) == []


def test_moduleish_subclass_chain_is_trace_surface():
    body = """
class Cell(Module):
    pass

class LSTM(Cell):
    def apply(self, params, state, input, *, training=False, rng=None):
        self.h = input
        return input, state
"""
    assert names(run(body)) == ["apply-mutates-self"]


def test_intra_class_helper_called_from_apply_is_traced():
    body = """
class Layer(Module):
    def apply(self, params, state, input, *, training=False, rng=None):
        return self._go(input), state

    def _go(self, x):
        y = jnp.sum(x)
        return float(y)
"""
    assert names(run(body)) == ["host-sync"]


def test_unhashable_static_argument_at_call_site():
    body = """
def g(x, shape):
    return x.reshape(shape)

f = jax.jit(g, static_argnums=(1,))
y = f(jnp.zeros((4,)), [2, 2])
"""
    assert "jit-static-args" in names(run(body))


def test_out_of_range_static_argnums():
    body = """
def g(x):
    return x

f = jax.jit(g, static_argnums=(3,))
"""
    fs = run(body)
    assert any(f.rule == "jit-static-args" and "out of range"
               in f.message for f in fs)


def test_json_output_is_stable():
    findings = run(CASES["bare-except"][0])
    data = json.loads(to_json(findings))
    assert any(d["rule"] == "bare-except" for d in data)
    assert {"rule", "path", "line", "col", "message",
            "suppressed"} <= set(data[0])


def test_parse_error_is_reported_not_raised():
    fs = lint_source("def broken(:\n", "bad.py")
    assert [f.rule for f in fs] == ["parse-error"]


def test_instrument_update_in_traced_code_flagged():
    """Module-level instruments (telemetry.counter idiom) are telemetry
    surface: their .inc/.observe inside traced code advances once per
    COMPILE, not per execution."""
    body = """
from bigdl_tpu import telemetry
STEPS = telemetry.counter("train/loop/steps")

@jax.jit
def f(x):
    STEPS.inc()
    return x * 2
"""
    assert "telemetry-in-trace" in names(run(body))


def test_instrument_update_on_host_not_flagged():
    body = """
from bigdl_tpu import telemetry
STEPS = telemetry.counter("train/loop/steps")

def host_loop(x):
    STEPS.inc()
    return x
"""
    assert "telemetry-in-trace" not in names(run(body))


def test_telemetry_record_in_scanned_fn_flagged():
    """The rule covers trace entries beyond jit: a lax.scan body is
    traced too."""
    body = """
import bigdl_tpu.telemetry as telemetry

def outer(xs):
    def body(c, x):
        telemetry.record("phase/x/y", 0.1)
        return c + x, x
    return lax.scan(body, 0.0, xs)
"""
    assert "telemetry-in-trace" in names(run(body))


# ------------------------------------------------------ use-after-donate

def test_use_after_donate_rebind_exonerates():
    """Rebinding the donated name to the call's result — the
    Optimizer's own pattern — is the sanctioned shape."""
    body = """
def train(p, o, g):
    step = jax.jit(lambda p, o, g: (p - g, o), donate_argnums=(0, 1))
    p, o = step(p, o, g)
    return jnp.sum(p) + jnp.sum(o["v"])
"""
    assert "use-after-donate" not in names(run(body))


def test_use_after_donate_intervening_store_exonerates():
    """A fresh assignment between the call and the later read makes
    the read fine — the name no longer aliases the donated buffer."""
    body = """
def train(params, grads):
    step = jax.jit(lambda p, g: p - g, donate_argnums=(0,))
    out = step(params, grads)
    params = out
    return jnp.sum(params)
"""
    assert "use-after-donate" not in names(run(body))


def test_use_after_donate_only_donated_positions_flagged():
    """Reading a NON-donated argument after the call is fine; only the
    donated positions invalidate their buffers."""
    body = """
def train(params, grads):
    step = jax.jit(lambda p, g: p - g, donate_argnums=(0,))
    out = step(params, grads)
    norm = jnp.sum(grads)
    return out, norm
"""
    assert "use-after-donate" not in names(run(body))


def test_use_after_donate_multiline_call_args_not_flagged():
    """A donated call wrapped across lines must not flag its OWN
    continuation-line arguments as post-call reads (reads past the
    call's end_lineno only)."""
    body = """
def train(params, grads):
    step = jax.jit(lambda p, g: p - g, donate_argnums=(0,))
    new = step(
        params, grads)
    return new
"""
    assert "use-after-donate" not in names(run(body))


def test_unbounded_cache_growth_eviction_lifecycle_passes():
    """The sanctioned shape (the fleet prefix cache's): grow sites
    paired with pop/del eviction in the same class pass clean — as
    does a deque bounded by construction."""
    body = """
import bigdl_tpu.generation

class BoundedCache:
    def __init__(self):
        self._entries = {}
        self._ring = deque(maxlen=64)

    def put(self, key, value):
        while len(self._entries) > 32:
            victim = next(iter(self._entries))
            self._entries.pop(victim)
        self._entries[key] = value
        self._ring.append(key)
"""
    body = "from collections import deque\n" + body
    assert "unbounded-cache-growth" not in names(run(body))


def test_unbounded_cache_growth_skips_non_serving_files():
    """The identical grow-only dict OFF the serving surface (no
    serving/generation/fleet import, path outside those dirs) is
    ordinary bookkeeping — not flagged."""
    body = """
class Memo:
    def __init__(self):
        self._seen = {}

    def put(self, key, value):
        self._seen[key] = value
"""
    assert "unbounded-cache-growth" not in names(run(body))
    # the same source UNDER a serving dir is on-surface by path alone
    from bigdl_tpu.analysis import lint_source
    flagged = lint_source(HEADER + body,
                          "bigdl_tpu/generation/widget.py")
    assert "unbounded-cache-growth" in names(flagged)


def test_unbounded_cache_growth_module_dict_and_append_sites():
    """Module-level grow-only dicts and .append-grown lists are
    flagged too; a del site anywhere in the scope exonerates."""
    grow_only = """
import bigdl_tpu.fleet

_RESPONSES = {}

def remember(key, value):
    _RESPONSES[key] = value
"""
    assert "unbounded-cache-growth" in names(run(grow_only))
    with_del = grow_only + """

def forget(key):
    del _RESPONSES[key]
"""
    assert "unbounded-cache-growth" not in names(run(with_del))
    append_only = """
import bigdl_tpu.serving

class Log:
    def __init__(self):
        self._rows = []

    def record(self, row):
        self._rows.append(row)
"""
    assert "unbounded-cache-growth" in names(run(append_only))
    # `+=` is the same growth as .append, not a rebind-reset
    aug_only = append_only.replace("self._rows.append(row)",
                                   "self._rows += [row]")
    assert "unbounded-cache-growth" in names(run(aug_only))
