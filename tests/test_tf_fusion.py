"""TF pattern fusion -> structured modules, against REAL TensorFlow as
the numeric oracle (reference: utils/tf/TensorflowToBigDL.scala:1 — the
fusion table that turns imported GraphDefs into first-class layers).

The fused model must (a) equal the TF graph numerically, (b) read as
layers, (c) survive quantize(), (d) round-trip the module serializer —
the four things an op-soup TFModule import cannot do."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.tf_fusion import fuse_tf_graph


def _freeze(fn, *specs):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] for t in frozen.outputs]
    return gd.SerializeToString(), in_names, out_names


def _convnet_graph():
    """A classic TF1-style conv net: conv+bias+relu, BN, pool, flatten,
    dense+relu, dense+softmax."""
    rs = np.random.RandomState(0)
    k1 = tf.constant(rs.randn(3, 3, 3, 8).astype(np.float32) * 0.3)
    b1 = tf.constant(rs.randn(8).astype(np.float32) * 0.1)
    scale = tf.constant(rs.rand(8).astype(np.float32) + 0.5)
    offset = tf.constant(rs.randn(8).astype(np.float32) * 0.1)
    mean = tf.constant(rs.randn(8).astype(np.float32) * 0.1)
    var = tf.constant(rs.rand(8).astype(np.float32) + 0.5)
    w1 = tf.constant(rs.randn(8 * 4 * 4, 16).astype(np.float32) * 0.2)
    c1 = tf.constant(rs.randn(16).astype(np.float32) * 0.1)
    w2 = tf.constant(rs.randn(16, 5).astype(np.float32) * 0.3)
    c2 = tf.constant(rs.randn(5).astype(np.float32) * 0.1)

    def fn(x):
        y = tf.nn.conv2d(x, k1, strides=[1, 1, 1, 1], padding="SAME")
        y = tf.nn.bias_add(y, b1)
        y = tf.nn.relu(y)
        y = tf.raw_ops.FusedBatchNormV3(
            x=y, scale=scale, offset=offset, mean=mean, variance=var,
            epsilon=1e-3, is_training=False).y
        y = tf.nn.max_pool2d(y, 2, 2, "VALID")
        y = tf.reshape(y, [-1, 8 * 4 * 4])
        y = tf.nn.relu(tf.matmul(y, w1) + c1)
        y = tf.matmul(y, w2) + c2
        return tf.nn.softmax(y)

    return fn


def test_fused_convnet_matches_tf_and_reads_as_layers():
    fn = _convnet_graph()
    x = np.random.RandomState(1).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    got = np.asarray(model.forward(x))
    want = np.asarray(fn(tf.constant(x)))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)
    # reads as layers, not op soup
    kinds = [type(m).__name__ for m in model.modules]
    assert "SpatialConvolution" in kinds and "Linear" in kinds
    assert "SpatialBatchNormalization" in kinds
    assert "SpatialMaxPooling" in kinds


def test_fused_convnet_survives_quantize():
    from bigdl_tpu.nn.quantized import quantize

    fn = _convnet_graph()
    x = np.random.RandomState(2).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    q = quantize(model)
    ref = np.asarray(model.forward(x))
    got = np.asarray(q.forward(x))
    # int8 path keeps the prediction, not the exact numbers
    assert got.shape == ref.shape
    assert (got.argmax(-1) == ref.argmax(-1)).all()


def test_fused_convnet_roundtrips_serializer(tmp_path):
    from bigdl_tpu.utils.serialization import load_module, save_module

    fn = _convnet_graph()
    x = np.random.RandomState(3).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    save_module(str(tmp_path / "m"), model)
    back = load_module(str(tmp_path / "m")).evaluate()
    np.testing.assert_allclose(np.asarray(back.forward(x)),
                               np.asarray(model.forward(x)), atol=1e-6)


def test_fusion_rejects_unknown_ops_with_name():
    def fn(x):
        return tf.nn.elu(x)

    data, ins, outs = _freeze(fn, tf.TensorSpec([2, 4], tf.float32))
    with pytest.raises(ValueError, match="Elu"):
        fuse_tf_graph(data, inputs=ins, outputs=outs)


def test_fused_mlp_trains():
    """The fused model is a real module tree: it trains through the
    Optimizer like any native model."""
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    rs = np.random.RandomState(4)
    w1 = tf.constant(rs.randn(6, 12).astype(np.float32) * 0.4)
    b1 = tf.constant(np.zeros(12, np.float32))
    w2 = tf.constant(rs.randn(12, 2).astype(np.float32) * 0.4)

    def fn(x):
        return tf.matmul(tf.nn.relu(tf.matmul(x, w1) + b1), w2)

    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 6], tf.float32))
    fused = fuse_tf_graph(data, inputs=ins, outputs=outs)
    model = nn.Sequential().add(fused).add(nn.LogSoftMax()).training()

    xs = rs.randn(64, 6).astype(np.float32)
    ys = ((xs.sum(1) > 0) + 1).astype(np.float32)
    ds = DataSet.array([Sample(xs[i], ys[i]) for i in range(64)]) \
        .transform(SampleToMiniBatch(16))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(12))
    opt.optimize()
    assert opt.driver_state["Loss"] < 0.4
