"""TF pattern fusion -> structured modules, against REAL TensorFlow as
the numeric oracle (reference: utils/tf/TensorflowToBigDL.scala:1 — the
fusion table that turns imported GraphDefs into first-class layers).

The fused model must (a) equal the TF graph numerically, (b) read as
layers, (c) survive quantize(), (d) round-trip the module serializer —
the four things an op-soup TFModule import cannot do."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.tf_fusion import fuse_tf_graph


def _freeze(fn, *specs):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    conc = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name.split(":")[0] for t in frozen.outputs]
    return gd.SerializeToString(), in_names, out_names


def _convnet_graph():
    """A classic TF1-style conv net: conv+bias+relu, BN, pool, flatten,
    dense+relu, dense+softmax."""
    rs = np.random.RandomState(0)
    k1 = tf.constant(rs.randn(3, 3, 3, 8).astype(np.float32) * 0.3)
    b1 = tf.constant(rs.randn(8).astype(np.float32) * 0.1)
    scale = tf.constant(rs.rand(8).astype(np.float32) + 0.5)
    offset = tf.constant(rs.randn(8).astype(np.float32) * 0.1)
    mean = tf.constant(rs.randn(8).astype(np.float32) * 0.1)
    var = tf.constant(rs.rand(8).astype(np.float32) + 0.5)
    w1 = tf.constant(rs.randn(8 * 4 * 4, 16).astype(np.float32) * 0.2)
    c1 = tf.constant(rs.randn(16).astype(np.float32) * 0.1)
    w2 = tf.constant(rs.randn(16, 5).astype(np.float32) * 0.3)
    c2 = tf.constant(rs.randn(5).astype(np.float32) * 0.1)

    def fn(x):
        y = tf.nn.conv2d(x, k1, strides=[1, 1, 1, 1], padding="SAME")
        y = tf.nn.bias_add(y, b1)
        y = tf.nn.relu(y)
        y = tf.raw_ops.FusedBatchNormV3(
            x=y, scale=scale, offset=offset, mean=mean, variance=var,
            epsilon=1e-3, is_training=False).y
        y = tf.nn.max_pool2d(y, 2, 2, "VALID")
        y = tf.reshape(y, [-1, 8 * 4 * 4])
        y = tf.nn.relu(tf.matmul(y, w1) + c1)
        y = tf.matmul(y, w2) + c2
        return tf.nn.softmax(y)

    return fn


def test_fused_convnet_matches_tf_and_reads_as_layers():
    fn = _convnet_graph()
    x = np.random.RandomState(1).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    got = np.asarray(model.forward(x))
    want = np.asarray(fn(tf.constant(x)))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)
    # reads as layers, not op soup
    kinds = [type(m).__name__ for m in model.modules]
    assert "SpatialConvolution" in kinds and "Linear" in kinds
    assert "SpatialBatchNormalization" in kinds
    assert "SpatialMaxPooling" in kinds


def test_fused_convnet_survives_quantize():
    from bigdl_tpu.nn.quantized import quantize

    fn = _convnet_graph()
    x = np.random.RandomState(2).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    q = quantize(model)
    ref = np.asarray(model.forward(x))
    got = np.asarray(q.forward(x))
    # int8 path keeps the prediction, not the exact numbers
    assert got.shape == ref.shape
    assert (got.argmax(-1) == ref.argmax(-1)).all()


def test_fused_convnet_roundtrips_serializer(tmp_path):
    from bigdl_tpu.utils.serialization import load_module, save_module

    fn = _convnet_graph()
    x = np.random.RandomState(3).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    save_module(str(tmp_path / "m"), model)
    back = load_module(str(tmp_path / "m")).evaluate()
    np.testing.assert_allclose(np.asarray(back.forward(x)),
                               np.asarray(model.forward(x)), atol=1e-6)


def test_fusion_rejects_unknown_ops_with_name():
    def fn(x):
        return tf.nn.elu(x)

    data, ins, outs = _freeze(fn, tf.TensorSpec([2, 4], tf.float32))
    with pytest.raises(ValueError, match="Elu"):
        fuse_tf_graph(data, inputs=ins, outputs=outs)


def _inception_graph():
    """A tiny Inception-style branchy net: stem conv -> three parallel
    branches (1x1 conv / 3x3 conv / maxpool+1x1 conv) -> channel concat
    -> relu -> flatten -> dense -> softmax — the branch-and-concat
    topology the reference's fusion table existed for."""
    rs = np.random.RandomState(7)

    def cw(kh, kw, ci, co):
        return tf.constant(rs.randn(kh, kw, ci, co).astype(np.float32)
                           * 0.25)

    k0 = cw(3, 3, 3, 8)
    b0 = tf.constant(rs.randn(8).astype(np.float32) * 0.1)
    k1 = cw(1, 1, 8, 4)
    k3 = cw(3, 3, 8, 6)
    b3 = tf.constant(rs.randn(6).astype(np.float32) * 0.1)
    kp = cw(1, 1, 8, 4)
    wd = tf.constant(rs.randn(14 * 8 * 8, 5).astype(np.float32) * 0.1)
    bd = tf.constant(rs.randn(5).astype(np.float32) * 0.1)

    def fn(x):
        stem = tf.nn.relu(tf.nn.bias_add(
            tf.nn.conv2d(x, k0, 1, "SAME"), b0))
        br1 = tf.nn.conv2d(stem, k1, 1, "SAME")
        br3 = tf.nn.relu(tf.nn.bias_add(
            tf.nn.conv2d(stem, k3, 1, "SAME"), b3))
        brp = tf.nn.conv2d(tf.nn.max_pool2d(stem, 3, 1, "SAME"), kp, 1,
                           "SAME")
        y = tf.nn.relu(tf.concat([br1, br3, brp], axis=3))
        y = tf.reshape(y, [-1, 14 * 8 * 8])
        return tf.nn.softmax(tf.matmul(y, wd) + bd)

    return fn


def test_branchy_inception_fusion_matches_tf():
    fn = _inception_graph()
    x = np.random.RandomState(11).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    got = np.asarray(model.forward(x))
    want = np.asarray(fn(tf.constant(x)))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)
    # a branchy import fuses to a Graph of REAL layers incl. the join
    kinds = [type(m).__name__ for m in model.modules]
    assert type(model).__name__ == "Graph"
    assert kinds.count("SpatialConvolution") == 4
    assert "JoinTable" in kinds and "SpatialMaxPooling" in kinds


def test_branchy_fusion_quantizes_and_serializes(tmp_path):
    """The whole point of fusion for the Inception model class: the
    branchy import survives quantize() AND the module serializer."""
    from bigdl_tpu.nn.quantized import quantize
    from bigdl_tpu.utils.serialization import load_module, save_module

    fn = _inception_graph()
    x = np.random.RandomState(12).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    ref = np.asarray(model.forward(x))
    q = quantize(model)
    got = np.asarray(q.forward(x))
    assert got.shape == ref.shape
    assert (got.argmax(-1) == ref.argmax(-1)).all()
    save_module(str(tmp_path / "m"), model)
    back = load_module(str(tmp_path / "m")).evaluate()
    np.testing.assert_allclose(np.asarray(back.forward(x)), ref,
                               atol=1e-6)


def test_residual_add_fuses_to_caddtable():
    rs = np.random.RandomState(9)
    k = tf.constant(rs.randn(3, 3, 4, 4).astype(np.float32) * 0.2)

    def fn(x):
        y = tf.nn.relu(tf.nn.conv2d(x, k, 1, "SAME"))
        return x + y  # residual

    x = np.random.RandomState(13).randn(2, 6, 6, 4).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 6, 6, 4],
                                                tf.float32))
    model = fuse_tf_graph(data, inputs=ins, outputs=outs)
    np.testing.assert_allclose(np.asarray(model.forward(x)),
                               np.asarray(fn(tf.constant(x))),
                               atol=2e-4, rtol=1e-4)
    kinds = [type(m).__name__ for m in model.modules]
    assert "CAddTable" in kinds


def test_mixed_mode_islands_unsupported_op():
    """mixed=True keeps the structure around an exotic node: Elu
    becomes a one-op TFModule island, everything else real layers —
    and the result still matches TF and serializes."""
    rs = np.random.RandomState(10)
    k = tf.constant(rs.randn(3, 3, 3, 4).astype(np.float32) * 0.3)
    w = tf.constant(rs.randn(4 * 4 * 4, 3).astype(np.float32) * 0.2)

    def fn(x):
        y = tf.nn.conv2d(x, k, 1, "SAME")
        y = tf.nn.elu(y)  # not in the fusion table
        y = tf.nn.max_pool2d(y, 2, 2, "VALID")
        y = tf.reshape(y, [-1, 4 * 4 * 4])
        return tf.matmul(y, w)

    x = np.random.RandomState(14).randn(2, 8, 8, 3).astype(np.float32)
    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 8, 8, 3],
                                                tf.float32))
    with pytest.raises(ValueError, match="Elu"):
        fuse_tf_graph(data, inputs=ins, outputs=outs)
    model = fuse_tf_graph(data, inputs=ins, outputs=outs, mixed=True)
    assert len(model.fused_islands) == 1 and \
        model.fused_islands[0].endswith(":Elu")
    kinds = [type(m).__name__ for m in model.modules]
    assert "SpatialConvolution" in kinds and "Linear" in kinds
    assert "TFModule" in kinds
    np.testing.assert_allclose(np.asarray(model.forward(x)),
                               np.asarray(fn(tf.constant(x))),
                               atol=2e-4, rtol=1e-4)
    # islands are rebuilt from raw NodeDef bytes: still serializable
    from bigdl_tpu.utils.serialization import load_module, save_module
    import tempfile
    d = tempfile.mkdtemp()
    save_module(d + "/m", model)
    back = load_module(d + "/m").evaluate()
    np.testing.assert_allclose(np.asarray(back.forward(x)),
                               np.asarray(model.forward(x)), atol=1e-6)


def test_fused_mlp_trains():
    """The fused model is a real module tree: it trains through the
    Optimizer like any native model."""
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    rs = np.random.RandomState(4)
    w1 = tf.constant(rs.randn(6, 12).astype(np.float32) * 0.4)
    b1 = tf.constant(np.zeros(12, np.float32))
    w2 = tf.constant(rs.randn(12, 2).astype(np.float32) * 0.4)

    def fn(x):
        return tf.matmul(tf.nn.relu(tf.matmul(x, w1) + b1), w2)

    data, ins, outs = _freeze(fn, tf.TensorSpec([None, 6], tf.float32))
    fused = fuse_tf_graph(data, inputs=ins, outputs=outs)
    model = nn.Sequential().add(fused).add(nn.LogSoftMax()).training()

    xs = rs.randn(64, 6).astype(np.float32)
    ys = ((xs.sum(1) > 0) + 1).astype(np.float32)
    ds = DataSet.array([Sample(xs[i], ys[i]) for i in range(64)]) \
        .transform(SampleToMiniBatch(16))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(12))
    opt.optimize()
    assert opt.driver_state["Loss"] < 0.4
