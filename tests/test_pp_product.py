"""PP/SP as first-class Optimizer product surface (the reference's
parallelism was reachable from Optimizer(...).optimize() —
optim/DistriOptimizer.scala:728; these tests hold the net-new pipeline
and sequence parallelism to the same bar)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from _capability import shard_map_skip
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.models import PipelinedTransformerLM, TransformerLM
from bigdl_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def devices8():
    d = jax.devices()
    if len(d) < 8:
        pytest.skip("needs 8 virtual devices")
    return d[:8]


def _token_dataset(n, seq, vocab, batch_size, seed=0):
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    rng = np.random.RandomState(seed)
    toks = rng.randint(0, vocab, (n, seq + 1))
    samples = [Sample(toks[i, :-1].astype(np.int32),
                      toks[i, 1:].astype(np.int32)) for i in range(n)]
    return DataSet.array(samples).transform(SampleToMiniBatch(batch_size))


def _loss_on_first_batch(model, n, seq, vocab, batch_size, seed=0):
    """Initial-params loss on the dataset's first batch — the oracle the
    trained loss must beat (same generator as _token_dataset)."""
    rng = np.random.RandomState(seed)
    toks = rng.randint(0, vocab, (n, seq + 1))
    x = jnp.asarray(toks[:batch_size, :-1].astype(np.int32))
    y = jnp.asarray(toks[:batch_size, 1:].astype(np.int32))
    crit = nn.SequenceCrossEntropyCriterion()
    out, _ = model.apply(model.get_parameters(), model.get_state(), x)
    return float(crit.apply(out, y))


def test_pipelined_lm_dense_fallback_forward():
    lm = PipelinedTransformerLM(vocab_size=50, hidden_size=16,
                                num_layers=2, num_heads=2,
                                max_len=8).evaluate()
    logits = np.asarray(lm.forward(np.random.randint(0, 50, (2, 8))))
    assert logits.shape == (2, 8, 50)
    assert np.isfinite(logits).all()


@shard_map_skip
def test_pipelined_lm_pp_matches_dense(devices8):
    """Pipelined forward AND grads must equal the sequential-scan path
    on identical params — PP changes the schedule, never the math."""
    mesh = make_mesh([4], ["pipe"], devices8[:4])
    lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                num_layers=4, num_heads=2, max_len=8,
                                n_microbatches=4, mesh=mesh).training()
    lm.ensure_initialized()
    params = lm.get_parameters()
    dense = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                   num_layers=4, num_heads=2, max_len=8,
                                   n_microbatches=4, mesh=None).training()
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (8, 8)))
    tgts = jnp.asarray(np.random.RandomState(1).randint(0, 32, (8, 8)))
    crit = nn.SequenceCrossEntropyCriterion()

    def loss(model, p):
        out = model.forward_fn(p, toks)
        return crit.apply(out, tgts)

    lp, gp = jax.value_and_grad(lambda p: loss(lm, p))(params)
    ld, gd = jax.value_and_grad(lambda p: loss(dense, p))(params)
    assert abs(float(lp) - float(ld)) < 1e-5
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


@shard_map_skip
def test_optimizer_trains_dp_tp_pp_composed(devices8):
    """THE product bar: one Optimizer call trains a pipelined model on a
    (data x pipe x model) mesh with composed DP+TP+PP shardings."""
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import Optimizer

    mesh = make_mesh([2, 2, 2], ["data", "pipe", "model"], devices8)
    lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                num_layers=4, num_heads=2, max_len=8,
                                n_microbatches=2, mesh=mesh)
    ds = _token_dataset(32, 8, 32, batch_size=8)
    opt = Optimizer(lm, ds, nn.SequenceCrossEntropyCriterion(),
                    batch_size=8, mesh=mesh,
                    sharding_rules=lm.sharding_rules(model_axis="model"))
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(8))
    lm.ensure_initialized()
    init_loss = _loss_on_first_batch(lm, 32, 8, 32, batch_size=8)
    opt.optimize()
    final = opt.driver_state["Loss"]
    assert np.isfinite(final)
    # layout really is composed: block weights carry pipe AND model axes
    p = lm.get_parameters()
    assert p["blocks"]["wq"].shape == (4, 16, 16)
    assert final < init_loss - 0.3, \
        f"composed training did not move the loss: {init_loss} -> {final}"


@shard_map_skip
def test_sp_ring_reaches_optimizer(devices8):
    """TransformerLM(ring_axis=...) trains through the plain Optimizer on
    a (data x seq) mesh — attention auto-wraps in shard_map over seq."""
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import Optimizer

    mesh = make_mesh([2, 4], ["data", "seq"], devices8)
    lm = TransformerLM(vocab_size=32, hidden_size=16, num_layers=2,
                       num_heads=4, max_len=16, ring_axis="seq",
                       mesh=mesh)
    ds = _token_dataset(16, 16, 32, batch_size=4)
    opt = Optimizer(lm, ds, nn.SequenceCrossEntropyCriterion(),
                    batch_size=4, mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(6))
    lm.ensure_initialized()
    init_loss = _loss_on_first_batch(lm, 16, 16, 32, batch_size=4)
    opt.optimize()
    final = opt.driver_state["Loss"]
    assert np.isfinite(final)
    assert final < init_loss - 0.3, \
        f"SP training did not move the loss: {init_loss} -> {final}"


@shard_map_skip
def test_sp_ulysses_matches_local_forward(devices8):
    """sp_impl='ulysses': the auto-wrapped SP forward equals the local
    (single-device) forward on identical params."""
    mesh = make_mesh([4], ["seq"], devices8[:4])
    lm = TransformerLM(vocab_size=32, hidden_size=16, num_layers=2,
                       num_heads=4, max_len=16, ring_axis="seq",
                       sp_impl="ulysses", mesh=mesh).evaluate()
    lm.ensure_initialized()
    params = lm.get_parameters()
    local = TransformerLM(vocab_size=32, hidden_size=16, num_layers=2,
                          num_heads=4, max_len=16).evaluate()
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 16)))
    out_sp, _ = lm.apply(params, lm.get_state(), toks)
    out_lc, _ = local.apply(params, local.get_state(), toks)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_lc),
                               atol=2e-5)


@shard_map_skip
def test_sp_ring_matches_local_forward(devices8):
    mesh = make_mesh([4], ["seq"], devices8[:4])
    lm = TransformerLM(vocab_size=32, hidden_size=16, num_layers=2,
                       num_heads=4, max_len=16, ring_axis="seq",
                       sp_impl="ring", mesh=mesh).evaluate()
    lm.ensure_initialized()
    params = lm.get_parameters()
    local = TransformerLM(vocab_size=32, hidden_size=16, num_layers=2,
                          num_heads=4, max_len=16).evaluate()
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 16)))
    out_sp, _ = lm.apply(params, lm.get_state(), toks)
    out_lc, _ = local.apply(params, local.get_state(), toks)
    np.testing.assert_allclose(np.asarray(out_sp), np.asarray(out_lc),
                               atol=2e-5)


@shard_map_skip
def test_mesh_bearing_model_snapshot_roundtrip(tmp_path, devices8):
    """A mesh is runtime placement, not model identity: snapshots of
    mesh-constructed models must save and load on any topology."""
    from bigdl_tpu.utils.serialization import load_module, save_module

    mesh = make_mesh([4], ["pipe"], devices8[:4])
    lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                num_layers=4, num_heads=2, max_len=8,
                                n_microbatches=2, mesh=mesh)
    lm.ensure_initialized()
    path = str(tmp_path / "pp_snap")
    save_module(path, lm)
    back = load_module(path)
    assert back.mesh is None  # reattach on the load topology
    toks = np.random.RandomState(0).randint(0, 32, (2, 8))
    a = np.asarray(back.evaluate().forward(toks))
    b = np.asarray(lm.evaluate().forward(toks))
    np.testing.assert_allclose(a, b, atol=2e-5)


@shard_map_skip
def test_interleaved_schedule_matches_dense(devices8):
    """The interleaved (virtual-stage) schedule shrinks the pipeline
    bubble from (S-1)/(M+S-1) to (S-1)/(V*M+S-1); it must remain a pure
    re-scheduling — forward and grads equal the sequential scan."""
    mesh = make_mesh([4], ["pipe"], devices8[:4])
    lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                num_layers=8, num_heads=2, max_len=8,
                                n_microbatches=4, mesh=mesh,
                                pp_schedule="interleaved",
                                pp_rounds=2).training()
    lm.ensure_initialized()
    params = lm.get_parameters()
    dense = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                   num_layers=8, num_heads=2, max_len=8,
                                   n_microbatches=4, mesh=None).training()
    toks = jnp.asarray(np.random.RandomState(3).randint(0, 32, (8, 8)))
    tgts = jnp.asarray(np.random.RandomState(4).randint(0, 32, (8, 8)))
    crit = nn.SequenceCrossEntropyCriterion()

    def loss(model, p):
        return crit.apply(model.forward_fn(p, toks), tgts)

    lp, gp = jax.jit(jax.value_and_grad(
        lambda p: loss(lm, p)))(params)
    ld, gd = jax.value_and_grad(lambda p: loss(dense, p))(params)
    assert abs(float(lp) - float(ld)) < 1e-5
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4)


@shard_map_skip
def test_interleaved_trains_through_optimizer(devices8):
    """--ppSchedule interleaved is product surface: the stock Optimizer
    trains it on a (data x pipe) mesh."""
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import Optimizer

    mesh = make_mesh([2, 4], ["data", "pipe"], devices8)
    lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                num_layers=8, num_heads=2, max_len=8,
                                n_microbatches=4, mesh=mesh,
                                pp_schedule="interleaved", pp_rounds=2)
    ds = _token_dataset(32, 8, 32, batch_size=8)
    opt = Optimizer(lm, ds, nn.SequenceCrossEntropyCriterion(),
                    batch_size=8, mesh=mesh,
                    sharding_rules=lm.sharding_rules())
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(8))
    lm.ensure_initialized()
    init_loss = _loss_on_first_batch(lm, 32, 8, 32, batch_size=8)
    opt.optimize()
    assert opt.driver_state["Loss"] < init_loss - 0.3


@shard_map_skip
def test_interleaved_needs_enough_microbatches(devices8):
    """M < S is schedule-infeasible (a round-v activation would need to
    re-enter stage 0 before it arrives) — fail fast, not silently."""
    mesh = make_mesh([4], ["pipe"], devices8[:4])
    lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                num_layers=8, num_heads=2, max_len=8,
                                n_microbatches=2, mesh=mesh,
                                pp_schedule="interleaved", pp_rounds=2)
    lm.ensure_initialized()
    with pytest.raises(AssertionError, match="microbatches"):
        jax.eval_shape(
            lambda p: lm.forward_fn(p, jnp.zeros((8, 8), jnp.int32)),
            lm.get_parameters())


def _grads_vs_dense(mesh, model_kw, rules_kw, devices8, atol=2e-4):
    """Shared harness: PipelinedTransformerLM grads on a composed mesh
    must equal its own dense-scan twin on identical params/batch."""
    from bigdl_tpu.parallel import shard_params
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(3)
    lm = PipelinedTransformerLM(vocab_size=32, hidden_size=16,
                                num_layers=4, num_heads=2, max_len=16,
                                n_microbatches=2, mesh=mesh, **model_kw)
    lm.ensure_initialized()
    host_p = jax.tree.map(np.asarray, lm.get_parameters())
    p = shard_params(lm.get_parameters(), mesh,
                     lm.sharding_rules(**rules_kw))
    dense = PipelinedTransformerLM(
        vocab_size=32, hidden_size=16, num_layers=4, num_heads=2,
        max_len=16, n_microbatches=2, mesh=None,
        **{k: v for k, v in model_kw.items() if k != "ring_axis"})
    crit = nn.SequenceCrossEntropyCriterion()
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 32, (8, 16)).astype(np.int32)
    tgts = rs.randint(0, 32, (8, 16)).astype(np.int32)

    def loss(model, pp):
        out, st = model.apply(pp, model.initial_state(), toks)
        base = crit.apply(out, tgts)
        if model.moe_experts:
            base = base + 0.01 * model.aux_loss(st)
        return base

    gp = jax.jit(jax.grad(lambda pp: loss(lm, pp)))(p)
    gd = jax.grad(lambda pp: loss(dense, pp))(host_p)
    for a, b in zip(jax.tree.leaves(jax.tree.map(np.asarray, gp)),
                    jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=atol)


@shard_map_skip
def test_pp_composes_with_ring_sp(devices8):
    """SP inside the pipeline: ring attention runs its manual
    collectives within each stage (seq axis manual alongside pipe) —
    the SP∦PP gap closed."""
    mesh = make_mesh([2, 2, 2], ["data", "pipe", "seq"], devices8)
    _grads_vs_dense(mesh, {"ring_axis": "seq"}, {}, devices8)


@shard_map_skip
def test_pp_composes_with_ulysses_sp(devices8):
    mesh = make_mesh([2, 2, 2], ["data", "pipe", "seq"], devices8)
    _grads_vs_dense(mesh, {"ring_axis": "seq", "sp_impl": "ulysses"},
                    {}, devices8)


@shard_map_skip
def test_pp_composes_with_moe_ep(devices8):
    """MoE inside the pipeline: stacked routed experts GSPMD-sharded
    over the model axis, the load-balance aux threaded through the
    pipeline ring — bit-comparable to the dense microbatch-looped
    fallback."""
    mesh = make_mesh([2, 2, 2], ["data", "pipe", "model"], devices8)
    _grads_vs_dense(mesh, {"moe_experts": 2},
                    {"model_axis": "model", "expert_axis": "model"},
                    devices8)


@shard_map_skip
def test_full_product_pp_sp_ep(devices8):
    """DP x PP x SP x EP constructible in ONE model on one mesh."""
    mesh = make_mesh([2, 2, 2], ["data", "pipe", "seq"], devices8)
    _grads_vs_dense(mesh, {"ring_axis": "seq", "moe_experts": 2},
                    {"expert_axis": "seq"}, devices8)


@shard_map_skip
def test_interleaved_composes_with_moe_ep(devices8):
    """The interleaved schedule's aux threading (valid-mask + psum/m
    over V rounds) must ALSO equal the dense microbatch-looped aux —
    the two-process composed test's oracle runs the same interleaved
    code, so only this dense cross-check can catch aux-math bugs."""
    mesh = make_mesh([2, 2, 2], ["data", "pipe", "model"], devices8)
    _grads_vs_dense(mesh, {"moe_experts": 2,
                           "pp_schedule": "interleaved", "pp_rounds": 2},
                    {"model_axis": "model", "expert_axis": "model"},
                    devices8)
