"""Windowed step driver (Optimizer.set_steps_per_sync): K fused train
steps per host sync must be OBSERVABLY identical to the per-step loop —
seeded K=1 vs K∈{4,8} runs produce the same final params/losses on both
the host-feed and device-feed paths, windows flush at every
validation/checkpoint/epoch boundary, loss-dependent triggers force
per-step fallback, and K-step mode compiles exactly one program per
(K, shape) pair. Plus the window plumbing itself: trigger dependency
metadata/peek, ``stack_windows``, and the prefetch stager's clean exit
when the consumer abandons the iterator mid-stream."""
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.device_dataset import DeviceCachedArrayDataSet
from bigdl_tpu.dataset.prefetch import (batch_signature, device_prefetch,
                                        stack_windows)
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import (LocalOptimizer, SGD, Loss, every_epoch,
                             max_iteration, min_loss, several_iteration)
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils.random import RandomGenerator


# ---------------------------------------------------------------- helpers

def _toy_xy(n=96, d=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d).astype(np.float32) * 3
    X = np.stack([centers[i % classes]
                  + rng.randn(d).astype(np.float32) * 0.5
                  for i in range(n)])
    y = np.array([i % classes + 1 for i in range(n)], np.float32)
    return X, y


def _mlp():
    return nn.Sequential().add(nn.Linear(8, 16)).add(nn.Tanh()) \
        .add(nn.Linear(16, 3)).add(nn.LogSoftMax())


def _host_ds(n=96, batch=32, seed=0):
    X, y = _toy_xy(n, seed=seed)
    return DataSet.array([Sample(X[i], y[i]) for i in range(n)]) \
        .transform(SampleToMiniBatch(batch))


def _img_model():
    return nn.Sequential().add(nn.Reshape([64])).add(nn.Linear(64, 3)) \
        .add(nn.LogSoftMax())


def _device_ds(n=64, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 255, (n, 1, 8, 8), np.uint8)
    labels = (rng.randint(0, 3, n) + 1).astype(np.float32)
    return DeviceCachedArrayDataSet(imgs, labels, batch, crop=(8, 8),
                                    flip=True, mean=(0.0,), std=(255.0,))


def _params_of(model):
    import jax
    return [np.asarray(l)
            for l in jax.tree_util.tree_leaves(model.get_parameters())]


def _run_host(k, iters=12, end_when=None):
    RandomGenerator.set_seed(11)
    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion(),
                         batch_size=32)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(end_when or max_iteration(iters))
    opt.set_steps_per_sync(k)
    model = opt.optimize()
    return _params_of(model), opt


def _run_device(k, iters=10, n=64):
    RandomGenerator.set_seed(23)
    opt = LocalOptimizer(_img_model(), _device_ds(n=n),
                         nn.ClassNLLCriterion(), batch_size=16)
    opt.set_optim_method(SGD(learning_rate=0.05))
    opt.set_end_when(max_iteration(iters))
    opt.set_steps_per_sync(k)
    model = opt.optimize()
    return _params_of(model), opt


# ---------------------------------------------- K=1 vs K>1 equivalence

@pytest.mark.parametrize("k", [4, 8])
def test_host_feed_windowed_matches_per_step(k):
    p1, o1 = _run_host(1)
    pk, ok = _run_host(k)
    assert o1.driver_state["neval"] == ok.driver_state["neval"]
    assert o1.driver_state["epoch"] == ok.driver_state["epoch"]
    assert np.isclose(o1.driver_state["Loss"], ok.driver_state["Loss"],
                      rtol=1e-5, atol=1e-7)
    for a, b in zip(p1, pk):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("k", [4, 8])
def test_device_feed_windowed_matches_per_step(k):
    p1, o1 = _run_device(1)
    pk, ok = _run_device(k)
    assert o1.driver_state["neval"] == ok.driver_state["neval"]
    assert o1.driver_state["epoch"] == ok.driver_state["epoch"]
    for a, b in zip(p1, pk):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_windowed_loss_sequence_matches_per_step():
    """Every per-step Loss the summary would see, not just the final
    one: the replay must hand triggers/summaries the true sequence."""
    seen = {}
    for k in (1, 8):
        RandomGenerator.set_seed(31)
        opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(max_iteration(9))
        opt.set_steps_per_sync(k)

        class Spy:
            def __init__(self):
                self.rows = []

            def add_scalar(self, tag, value, step):
                if tag == "Loss":
                    self.rows.append((step, value))

            def add_histogram(self, *a):
                pass

        spy = Spy()
        opt.set_train_summary(spy)
        opt.optimize()
        seen[k] = spy.rows
    assert len(seen[1]) == len(seen[8]) == 9
    for (s1, l1), (s8, l8) in zip(seen[1], seen[8]):
        assert s1 == s8
        assert np.isclose(l1, l8, rtol=1e-5, atol=1e-7)


# ------------------------------------------------------- window planning

def _plan(opt, k, state, bsz, ds_size, end_when, shard=None):
    return opt._plan_window(k, state, bsz, ds_size, end_when,
                            shard_size=shard)


def test_window_flushes_at_validation_boundary():
    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion())
    opt.validation_trigger = several_iteration(3)
    st = {"epoch": 1, "neval": 1, "recordsProcessedThisEpoch": 0}
    # post-step-2 state has neval=3 -> trigger fires -> window is 2
    assert _plan(opt, 8, st, 8, 10**6, max_iteration(100)) == 2
    st["neval"] = 3
    assert _plan(opt, 8, st, 8, 10**6, max_iteration(100)) == 3


def test_window_flushes_at_checkpoint_and_end_boundaries():
    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion())
    opt.checkpoint_trigger = several_iteration(5)
    st = {"epoch": 1, "neval": 1, "recordsProcessedThisEpoch": 0}
    assert _plan(opt, 8, st, 8, 10**6, max_iteration(100)) == 4
    opt.checkpoint_trigger = None
    assert _plan(opt, 8, st, 8, 10**6, max_iteration(6)) == 6
    assert _plan(opt, 4, st, 8, 10**6, max_iteration(100)) == 4


def test_window_flushes_at_epoch_rollover_and_shard_boundary():
    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion())
    st = {"epoch": 1, "neval": 1, "recordsProcessedThisEpoch": 0}
    # 96-record epoch, batch 32: the 3rd step completes the epoch
    assert _plan(opt, 8, st, 32, 96, max_iteration(100)) == 3
    # shard of 64 records, batch 16: rotation due after step 4
    assert _plan(opt, 8, st, 16, 10**6, max_iteration(100), shard=64) == 4


def test_every_epoch_peek_does_not_mutate():
    t = every_epoch()
    assert not t({"epoch": 1})          # latches the baseline
    assert t.peek({"epoch": 2})         # preview: would fire
    assert t.peek({"epoch": 2})         # ... and again: no mutation
    assert t({"epoch": 2})              # the real call still fires once
    assert not t({"epoch": 2})


def test_trigger_dependency_metadata():
    assert several_iteration(5).depends_on == {"neval"}
    assert min_loss(0.1).depends_on == {"Loss"}
    assert not min_loss(0.1).plannable()
    assert several_iteration(5).plannable()
    both = several_iteration(5).or_(every_epoch())
    assert both.depends_on == {"neval", "epoch"}
    assert both.plannable()
    unknown = Trigger(lambda s: False)
    assert unknown.depends_on is None and not unknown.plannable()
    assert several_iteration(5).and_(unknown).depends_on is None


# ----------------------------------------------------- per-step fallback

def test_loss_dependent_end_trigger_forces_per_step():
    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion())
    k, why = opt._window_limit(8, min_loss(0.01), False)
    assert k == 1 and "Loss" in why


def test_unknown_trigger_forces_per_step():
    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion())
    opt.validation_trigger = Trigger(lambda s: s.get("neval", 1) % 7 == 0)
    k, why = opt._window_limit(8, max_iteration(10), False)
    assert k == 1 and "undeclared" in why


def test_parameter_histogram_summary_forces_per_step():
    class HistSummary:
        def add_scalar(self, *a):
            pass

        def add_histogram(self, *a):
            pass

        def get_summary_trigger(self, name):
            return several_iteration(5) if name == "Parameters" else None

    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion())
    opt.set_train_summary(HistSummary())
    k, why = opt._window_limit(8, max_iteration(10), False)
    assert k == 1 and "Parameters" in why


def test_plateau_schedule_forces_per_step():
    from bigdl_tpu.optim.optim_method import Plateau
    opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion())
    opt.set_optim_method(SGD(learning_rate=0.1,
                             learning_rate_schedule=Plateau()))
    k, why = opt._window_limit(8, max_iteration(10), False)
    assert k == 1 and "Plateau" in why


def test_fallback_run_still_trains():
    # a K=8 ask with a min_loss end trigger must run (per-step) and stop
    p, opt = _run_host(8, end_when=min_loss(0.05).or_(max_iteration(40)))
    assert opt.driver_state["neval"] > 1


# --------------------------------------- boundary-equivalence end-to-end

def test_validation_fires_at_identical_steps_and_scores():
    rows = {}
    for k in (1, 8):
        RandomGenerator.set_seed(17)
        opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(max_iteration(9))
        opt.set_validation(several_iteration(3), _host_ds(seed=1),
                           [Loss(nn.ClassNLLCriterion())])
        opt.set_steps_per_sync(k)
        calls = []
        orig = opt._validate

        def spy(params, mstate, ev, _o=orig, _c=calls, _opt=opt):
            _c.append(_opt.driver_state["neval"])
            return _o(params, mstate, ev)

        opt._validate = spy
        opt.optimize()
        rows[k] = (calls, opt.driver_state.get("score"))
    assert rows[1][0] == rows[8][0] == [3, 6, 9]
    assert np.isclose(rows[1][1], rows[8][1], rtol=1e-5)


def test_actual_batch_sizes_guard_trigger_boundaries():
    """Optimizer configured with batch_size=32 but the dataset yields
    64-row batches: plan simulation (configured size) under-counts
    records, so the gather must re-peek triggers with ACTUAL sizes — a
    records-dependent trigger still fires at the per-step loop's step."""
    rows = {}
    for k in (1, 8):
        RandomGenerator.set_seed(37)
        opt = LocalOptimizer(_mlp(), _host_ds(n=192, batch=64),
                             nn.ClassNLLCriterion(),
                             batch_size=32)  # mismatched on purpose
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(max_iteration(4))
        trig = Trigger(
            lambda s: s.get("recordsProcessedThisEpoch", 0) >= 64,
            depends_on=frozenset({"recordsProcessedThisEpoch"}))
        opt.set_validation(trig, _host_ds(seed=1),
                           [Loss(nn.ClassNLLCriterion())])
        opt.set_steps_per_sync(k)
        calls = []
        orig = opt._validate

        def spy(params, mstate, ev, _o=orig, _c=calls, _opt=opt):
            _c.append(_opt.driver_state["neval"])
            return _o(params, mstate, ev)

        opt._validate = spy
        opt.optimize()
        rows[k] = calls
    assert rows[1] == rows[8]
    assert rows[1]  # the trigger really fired


def test_checkpoints_written_at_identical_steps(tmp_path):
    import os
    dirs = {}
    for k in (1, 8):
        path = str(tmp_path / f"ck{k}")
        RandomGenerator.set_seed(19)
        opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(max_iteration(8))
        opt.set_checkpoint(path, several_iteration(4))
        opt.set_steps_per_sync(k)
        opt.optimize()
        dirs[k] = sorted(os.listdir(path))
    assert dirs[1] == dirs[8]
    assert dirs[1]  # something was actually written


def test_rotating_feed_windowed_matches_per_step():
    """Windows over a RotatingDeviceDataSet flush at shard boundaries
    (the slot arrays are window-invariant scan arguments), so K=8 runs
    in shard-sized windows and still matches the per-step run."""
    from bigdl_tpu.dataset import RotatingDeviceDataSet, ShardRotator

    m_per = 16  # shard size; batch 8 -> windows capped at 2 steps
    protos = np.random.RandomState(42).randn(4, 3, 8, 8)

    def provider(i):
        r = np.random.RandomState(50 + i)
        xs = np.clip(protos[i % 4] * 40 + 128
                     + r.randn(m_per, 3, 8, 8) * 10, 0, 255)
        return xs.astype(np.uint8), np.full(m_per, float(i % 4 + 1),
                                            np.float32)

    def run(k):
        RandomGenerator.set_seed(29)
        rot = ShardRotator(provider, 4, 8, crop=(8, 8), flip=False,
                           mean=(128,) * 3, std=(64,) * 3,
                           chunk_bytes=8 * 3 * 8 * 8,
                           shuffle_shards=False)
        ds = RotatingDeviceDataSet(rot)
        model = (nn.Sequential().add(nn.Reshape((3 * 8 * 8,)))
                 .add(nn.Linear(3 * 8 * 8, 4)).add(nn.LogSoftMax()))
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                             batch_size=8)
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(max_iteration(9))
        opt.set_steps_per_sync(k)
        trained = opt.optimize()
        return _params_of(trained), opt

    p1, o1 = run(1)
    p8, o8 = run(8)
    assert o1.dataset._consumed_shards == o8.dataset._consumed_shards
    for a, b in zip(p1, p8):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- compile counter

def _count_compiles(fn):
    from jax._src import compiler
    orig = compiler.backend_compile
    calls = []

    def counting(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    compiler.backend_compile = counting
    try:
        fn()
    finally:
        compiler.backend_compile = orig
    return len(calls)


def test_windowed_mode_compiles_one_program_per_k_shape():
    # warm every eager-op/helper cache with an identical run, then
    # count: steady K=4 traffic (8 steps = 2 full windows) is exactly
    # ONE compiled program; K=8 over 12 steps on a 16-step epoch
    # (windows of 8 then 4 at the end boundary) is exactly two — one
    # per (K, shape) pair
    _run_device(4, iters=8)
    assert _count_compiles(lambda: _run_device(4, iters=8)) == 1
    _run_device(8, iters=12, n=256)
    assert _count_compiles(lambda: _run_device(8, iters=12, n=256)) == 2


def test_windowed_phase_sums_match_metrics_to_the_digit():
    """K>1 records ONE data_wait/compute pair per window (amortized
    granularity) — but the trace's phase SUMS must still equal the
    Metrics sums exactly, so tools.diagnose's invariant holds."""
    import bigdl_tpu.telemetry as telemetry
    telemetry.enable()
    try:
        telemetry.tracer().clear()
        RandomGenerator.set_seed(41)
        opt = LocalOptimizer(_mlp(), _host_ds(), nn.ClassNLLCriterion(),
                             batch_size=32)
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(max_iteration(8))
        opt.set_steps_per_sync(8)
        opt.optimize()
        spans = {"optimizer/data_wait": 0.0, "optimizer/compute": 0.0}
        counts = {"optimizer/data_wait": 0, "optimizer/compute": 0}
        for rec in list(telemetry.tracer().spans()):
            if rec.name in spans:
                spans[rec.name] += rec.dur
                counts[rec.name] += 1
        assert counts["optimizer/compute"] >= 1
        # windows, not steps: 8 fused steps -> far fewer records than 8
        assert counts["optimizer/compute"] < 8
        assert np.isclose(spans["optimizer/data_wait"],
                          sum(opt.metrics.values["data time"]), atol=1e-12)
        assert np.isclose(spans["optimizer/compute"],
                          sum(opt.metrics.values["computing time"]),
                          atol=1e-12)
    finally:
        telemetry.disable()


# ------------------------------------------------------- stack_windows

def _mb(i, b=4, d=3):
    x = np.full((b, d), i, np.float32)
    y = np.full((b,), i, np.float32)
    return MiniBatch(x, y)


def test_stack_windows_groups_and_tails():
    out = list(stack_windows(iter([_mb(i) for i in range(7)]), 3))
    assert [b.input.shape for b in out] == [(3, 4, 3), (3, 4, 3),
                                            (1, 4, 3)]
    np.testing.assert_array_equal(out[0].input[1], _mb(1).input)
    np.testing.assert_array_equal(out[2].target[0], _mb(6).target)


def test_stack_windows_flushes_on_shape_change():
    batches = [_mb(0), _mb(1), _mb(2, b=2), _mb(3, b=2), _mb(4)]
    out = list(stack_windows(iter(batches), 4))
    assert [b.input.shape for b in out] == [(2, 4, 3), (2, 2, 3),
                                            (1, 4, 3)]


def test_stack_minibatches_rejects_mixed_none_targets_either_order():
    from bigdl_tpu.dataset import stack_minibatches
    with_t = _mb(0)
    without_t = MiniBatch(_mb(1).input, None)
    for pair in ([with_t, without_t], [without_t, with_t]):
        with pytest.raises(ValueError, match="mix None"):
            stack_minibatches(pair)


def test_device_resident_batches_fall_back_to_per_step():
    """A pipeline yielding device-resident MiniBatches must not be
    host-stacked (hidden device->host round-trip per batch): the
    window gather detects jax.Array leaves and runs per-step."""
    import jax.numpy as jnp
    from bigdl_tpu.optim.optimizer import _window_stackable
    host = _mb(0)
    dev = MiniBatch(jnp.asarray(host.input), jnp.asarray(host.target))
    assert _window_stackable(host)
    assert not _window_stackable(dev)


def test_stack_windows_multi_input_and_signature():
    a = MiniBatch([np.zeros((2, 3), np.float32),
                   np.zeros((2,), np.int32)], np.ones((2,), np.float32))
    b = MiniBatch([np.ones((2, 3), np.float32),
                   np.ones((2,), np.int32)], np.zeros((2,), np.float32))
    assert batch_signature(a) == batch_signature(b)
    (w,) = stack_windows(iter([a, b]), 2)
    assert isinstance(w.input, list)
    assert w.input[0].shape == (2, 2, 3) and w.input[1].shape == (2, 2)
    assert stack_windows(iter([]), 3) is not None  # generator, no blowup
    with pytest.raises(ValueError):
        list(stack_windows(iter([a]), 0))


# ------------------------------------------- prefetch abandoned-consumer

def _slow_batches(n=100):
    for i in range(n):
        yield _mb(i)


def test_device_prefetch_close_midstream_joins_stager():
    before = set(threading.enumerate())
    it = device_prefetch(_slow_batches(), size=2)
    next(it)  # consume one, leave the stager blocked on a full queue
    time.sleep(0.2)  # let the stager fill the queue and park on put()
    it.close()  # GeneratorExit -> stop event -> drain -> join
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = set(threading.enumerate()) - before
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"stager thread leaked: {leaked}"


def test_device_prefetch_normal_exhaustion_still_clean():
    before = set(threading.enumerate())
    out = list(device_prefetch(iter([_mb(i) for i in range(5)]), size=2))
    assert len(out) == 5
    time.sleep(0.1)
    assert set(threading.enumerate()) <= before


def test_device_prefetch_error_still_propagates():
    def boom():
        yield _mb(0)
        raise RuntimeError("upstream died")

    it = device_prefetch(boom(), size=2)
    next(it)
    with pytest.raises(RuntimeError, match="upstream died"):
        next(it)
