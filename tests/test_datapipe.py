"""Streaming data plane (bigdl_tpu.datapipe): shard/cursor resume
round-trips, seeded windowed-shuffle determinism, sequence-packing
correctness (segment masks BIT-EXACT vs per-sequence unpacked
forwards), K=1 vs K=8 windowed equivalence through a streaming source,
and the prefetch-abandonment no-leak regression over staged pipelines."""
import os
import threading
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import datapipe as dp
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.models import TransformerLM
from bigdl_tpu.optim import SGD, LocalOptimizer, max_iteration
from bigdl_tpu.optim.trigger import several_iteration
from bigdl_tpu.utils.random import RandomGenerator


# ------------------------------------------------------------- helpers

def _write_shards(tmp_path, n_shards=3, lines_per=5):
    paths = []
    for s in range(n_shards):
        p = tmp_path / f"shard-{s}.txt"
        p.write_text("".join(f"s{s}r{i}\n" for i in range(lines_per)))
        paths.append(str(p))
    return paths


def _docs(n=40, lo=4, hi=24, vocab=50, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def _tiny_lm(vocab=50, seed=3):
    RandomGenerator.set_seed(seed)
    m = TransformerLM(vocab_size=vocab, hidden_size=16, num_layers=2,
                      num_heads=2, max_len=64).evaluate()
    m.ensure_initialized()
    return m


# ------------------------------------------------- readers & cursors

def test_text_reader_streams_all_shards(tmp_path):
    r = dp.TextLineReader(_write_shards(tmp_path), shuffle_shards=False)
    got = list(r.read_epoch())
    assert got == [f"s{s}r{i}" for s in range(3) for i in range(5)]
    assert r.epoch == 1  # cursor advanced to the next epoch


def test_reader_cursor_resume_roundtrip(tmp_path):
    paths = _write_shards(tmp_path, n_shards=4, lines_per=7)
    ref = dp.TextLineReader(paths, seed=11)
    stream = ref.read(loop=True)
    head = [next(stream) for _ in range(9)]  # partway into some shard
    snap = ref.state()
    want = [next(stream) for _ in range(30)]  # crosses an epoch boundary

    fresh = dp.TextLineReader(paths, seed=11).restore(snap)
    it = fresh.read(loop=True)
    got = [next(it) for _ in range(30)]
    assert got == want
    assert len(set(head)) == 9


def test_reader_state_is_json_plain(tmp_path):
    import json
    r = dp.TextLineReader(_write_shards(tmp_path))
    next(r.read(loop=True))
    assert json.loads(json.dumps(r.state())) == r.state()


def test_reader_epoch_shard_order_reshuffles_deterministically(tmp_path):
    paths = _write_shards(tmp_path, n_shards=6, lines_per=1)
    a = dp.TextLineReader(paths, seed=5)
    e0 = list(a.read_epoch())
    e1 = list(a.read_epoch())
    assert sorted(e0) == sorted(e1)
    assert e0 != e1  # per-epoch shard-order permutation
    b = dp.TextLineReader(paths, seed=5)
    assert list(b.read_epoch()) == e0  # seeded: replayable
    assert list(b.read_epoch()) == e1


def test_reader_multihost_shard_split(tmp_path):
    paths = _write_shards(tmp_path, n_shards=4, lines_per=3)
    parts = [
        set(dp.TextLineReader(paths, process_index=i, process_count=2,
                              shuffle_shards=False).read_epoch())
        for i in range(2)]
    assert parts[0] | parts[1] == \
        {f"s{s}r{i}" for s in range(4) for i in range(3)}
    assert not parts[0] & parts[1]


def test_array_reader_counts_and_samples():
    feats = np.arange(20, dtype=np.float32).reshape(10, 2)
    labels = np.arange(10, dtype=np.float32)
    r = dp.ArrayRecordReader(feats, labels, shard_size=3,
                             shuffle_shards=False)
    assert r.num_records() == 10
    recs = list(r.read_epoch())
    assert len(recs) == 10
    np.testing.assert_array_equal(recs[4].feature(), feats[4])
    assert recs[4].label() == labels[4]


def test_datapipe_read_faultpoint_fires(tmp_path):
    from bigdl_tpu import faults
    r = dp.TextLineReader(_write_shards(tmp_path, 1, 5),
                          shuffle_shards=False)
    faults.arm(faults.parse_schedule("datapipe/read=nth:3,raise:OSError"))
    try:
        with pytest.raises(OSError):
            list(r.read_epoch())
    finally:
        faults.disarm()


# ------------------------------------------------- windowed shuffle

def test_shuffle_seeded_determinism():
    recs = list(range(200))
    a = list(dp.WindowShuffle(32, seed=7)(iter(recs), epoch=0))
    b = list(dp.WindowShuffle(32, seed=7)(iter(recs), epoch=0))
    c = list(dp.WindowShuffle(32, seed=8)(iter(recs), epoch=0))
    assert a == b                       # same seed: bit-identical order
    assert sorted(a) == recs            # a true permutation
    assert a != c                       # different seed: different order
    assert a != recs                    # actually shuffled


def test_shuffle_reseeds_per_epoch():
    recs = list(range(100))
    st = dp.WindowShuffle(25, seed=3)
    e0 = list(st(iter(recs), epoch=0))
    e1 = list(st(iter(recs), epoch=1))
    assert e0 != e1
    # epoch N is reproducible WITHOUT replaying earlier epochs
    assert list(dp.WindowShuffle(25, seed=3)(iter(recs), epoch=1)) == e1


def test_shuffle_bounded_displacement():
    # a record can only move ~buffer_size forward: streaming, not global
    buf = 10
    out = list(dp.WindowShuffle(buf, seed=1)(iter(range(1000)), epoch=0))
    for pos, v in enumerate(out):
        assert pos >= v - buf


# ---------------------------------------------------------- packing

def test_pack_documents_layout_and_targets():
    docs = [np.arange(1, 6, dtype=np.int32),      # x len 4
            np.arange(10, 14, dtype=np.int32),    # x len 3
            np.arange(20, 30, dtype=np.int32)]    # x len 9
    toks, segs, pos, tgt = dp.pack_documents(docs, 8)
    assert toks.shape == segs.shape == pos.shape == tgt.shape
    assert toks.shape[1] == 8
    # doc 1: x = [1..4], y = [2..5], segment 1, positions 0..3
    np.testing.assert_array_equal(toks[0, :4], [1, 2, 3, 4])
    np.testing.assert_array_equal(tgt[0, :4], [2, 3, 4, 5])
    np.testing.assert_array_equal(segs[0, :4], [1, 1, 1, 1])
    np.testing.assert_array_equal(pos[0, :4], [0, 1, 2, 3])
    # doc 2 packs into the same row, new segment id, positions reset
    np.testing.assert_array_equal(toks[0, 4:7], [10, 11, 12])
    np.testing.assert_array_equal(segs[0, 4:7], [2, 2, 2])
    np.testing.assert_array_equal(pos[0, 4:7], [0, 1, 2])
    # pad slot: segment 0, target ignored
    assert segs[0, 7] == 0 and tgt[0, 7] == -1
    # no target ever crosses a document boundary
    for r in range(len(toks)):
        for j in range(8):
            if tgt[r, j] != -1:
                assert segs[r, j] != 0


def test_padding_efficiency_math():
    assert dp.padding_efficiency([4, 8], 8) == pytest.approx(0.75)
    assert dp.padding_efficiency([], 8) == 1.0
    # PTB-like regime: short ragged documents, a long slab — packing
    # must clear 0.9 where pad-to-max wastes most of the batch
    docs = _docs(300, seed=2)
    lens = [len(d) - 1 for d in docs]
    toks, segs, _, _ = dp.pack_documents(docs, 128)
    packed_eff = float((segs > 0).mean())
    assert packed_eff > 0.9 > dp.padding_efficiency(lens, 128)


def test_packed_forward_bit_exact_vs_unpacked():
    """THE segment-mask correctness assert: every document's logits in
    a packed slab are BIT-IDENTICAL to running that document alone —
    both as a padded row (same slab width) and as an unpadded [1, L]
    forward. Any cross-document attention leak, positional-embedding
    offset, or mask slip breaks bitwise equality."""
    m = _tiny_lm()
    p, st = m.get_parameters(), m.get_state()
    docs = _docs(7, lo=4, hi=10, seed=1)
    S = 16
    toks, segs, pos, _ = dp.pack_documents(docs, S)
    packed = np.asarray(m.apply(p, st, [toks, segs, pos],
                                training=False)[0])
    # walk the slabs segment by segment and compare per document
    checked = 0
    for r in range(len(toks)):
        for sid in range(1, int(segs[r].max()) + 1):
            at = np.flatnonzero(segs[r] == sid)
            x = toks[r, at]
            # padded single-document row (same width S)
            t0 = np.zeros((1, S), np.int32)
            s0 = np.zeros((1, S), np.int32)
            p0 = np.zeros((1, S), np.int32)
            n = len(at)
            t0[0, :n], s0[0, :n] = x, 1
            p0[0, :n] = np.arange(n)
            ref = np.asarray(m.apply(p, st, [t0, s0, p0],
                                     training=False)[0])
            assert np.array_equal(packed[r, at], ref[0, :n])
            # truly unpacked [1, L] forward
            ref2 = np.asarray(m.apply(p, st, x[None].astype(np.int32),
                                      training=False)[0])
            assert np.array_equal(packed[r, at], ref2[0])
            checked += 1
    assert checked >= 7


def test_packed_forward_differs_without_segment_mask():
    """Control for the bit-exact assert: the SAME packed tokens with a
    single all-ones segment plane (mask off) must NOT reproduce the
    per-document forwards — otherwise the exactness test proves
    nothing."""
    m = _tiny_lm()
    p, st = m.get_parameters(), m.get_state()
    docs = _docs(6, lo=6, hi=10, seed=4)
    toks, segs, pos, _ = dp.pack_documents(docs, 16)
    masked = np.asarray(m.apply(p, st, [toks, segs, pos],
                                training=False)[0])
    unmasked = np.asarray(m.apply(
        p, st, [toks, np.ones_like(segs), pos], training=False)[0])
    # second-and-later segments see forged history without the mask
    later = segs > 1
    assert later.any()
    assert not np.allclose(masked[later], unmasked[later], atol=1e-4)


def test_bucket_batcher_layout_and_efficiency():
    docs = [np.arange(1, 5, dtype=np.int32),     # x len 3 -> bucket 4
            np.arange(1, 10, dtype=np.int32),    # x len 8 -> bucket 8
            np.arange(1, 4, dtype=np.int32),     # x len 2 -> bucket 4
            np.arange(1, 30, dtype=np.int32)]    # x len 8 (truncated)
    b = dp.LengthBucketBatcher([4, 8], batch_size=2)
    out = list(b(iter(docs), epoch=0))
    assert len(out) == 2
    widths = sorted(mb.input[0].shape[1] for mb in out)
    assert widths == [4, 8]
    for mb in out:
        toks, segs, pos = mb.input
        assert mb.target.shape == toks.shape
        assert ((segs == 0) == (mb.target == -1)).all()
    assert 0 < b.efficiency <= 1.0


def test_criterion_ignore_index_masks_positions():
    import jax.numpy as jnp
    crit = nn.SequenceCrossEntropyCriterion(ignore_index=-1)
    ref = nn.SequenceCrossEntropyCriterion()
    logits = np.random.RandomState(0).randn(2, 4, 7).astype(np.float32)
    t_full = np.array([[1, 2, 3, 4], [5, 6, 0, 1]], np.int32)
    # masking the second row's tail == scoring only the kept positions
    t_mask = t_full.copy()
    t_mask[1, 2:] = -1
    got = float(crit.apply(jnp.asarray(logits), jnp.asarray(t_mask)))
    kept = np.concatenate([logits[0], logits[1, :2]])[None]
    want = float(ref.apply(jnp.asarray(kept),
                           jnp.asarray(np.concatenate(
                               [t_full[0], t_full[1, :2]])[None])))
    assert got == pytest.approx(want, rel=1e-6)


# ------------------------------------------------- pipeline plumbing

def _token_pipeline(seed=7, n=60, vocab=50):
    docs = _docs(n, vocab=vocab, seed=9)

    class DocReader(dp.ShardedReader):
        def _open(self, shard):
            lo, hi = shard
            return iter(docs[lo:hi])

        def _shard_len(self, shard):
            return shard[1] - shard[0]

    shards = [(i, min(i + 10, n)) for i in range(0, n, 10)]
    return dp.Pipeline(DocReader(shards, seed=seed)) \
        .shuffle(buffer_size=16, seed=seed).pack(seq_len=32, batch_rows=4)


def test_pipeline_stream_bit_identical_across_runs():
    a = [mb for _, mb in zip(range(8), _token_pipeline().iterate(True))]
    b = [mb for _, mb in zip(range(8), _token_pipeline().iterate(True))]
    for x, y in zip(a, b):
        for pa, pb in zip(x.input, y.input):
            np.testing.assert_array_equal(pa, pb)
        np.testing.assert_array_equal(x.target, y.target)


def test_pipeline_as_dataset_counts_rows():
    pipe = _token_pipeline()
    ds = pipe.as_dataset(batch_size=4)
    n = sum(mb.size() for mb in _token_pipeline().iterate(False))
    assert ds.size() == n
    assert ds.batch_size == 4
    assert ds.continuous_stream


def test_pipeline_state_roundtrip_restores_stream():
    pipe = _token_pipeline()
    it = pipe.iterate(loop=True)
    for _ in range(3):
        next(it)
    snap = pipe.state()
    # NOTE the contract: restore rewinds to the READER cursor, i.e. the
    # epoch position after the last fully-consumed epoch batch; at
    # epoch boundaries this is exact
    fresh = _token_pipeline().restore(snap)
    assert fresh.state() == snap


def test_staged_windows_layout():
    pipe = _token_pipeline()
    it = pipe.staged(k=2, loop=True)
    try:
        mb = next(it)
        toks = np.asarray(mb.input[0])
        assert toks.shape[:2] == (2, 4)  # [K, B, S]
        assert np.asarray(mb.target).shape[:2] == (2, 4)
    finally:
        it.close()


def test_staged_pipeline_abandonment_leaks_no_threads():
    """PR-4 regression, re-aimed at the datapipe: abandoning a staged
    pipeline mid-epoch must stop the prefetch stager (stop event ->
    drain -> join), not leave a daemon parked on a full queue."""
    before = set(threading.enumerate())
    it = _token_pipeline().staged(k=2, loop=True)
    next(it)
    time.sleep(0.2)  # let the stager park on a full queue
    it.close()
    deadline = time.time() + 5.0
    leaked = set()
    while time.time() < deadline:
        leaked = set(threading.enumerate()) - before
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"stager thread leaked: {leaked}"


# ------------------------------- optimizer integration & K-equivalence

def _sample_pipeline(seed, n=96, batch=16):
    rng = np.random.RandomState(41)
    X = rng.randn(n, 8).astype(np.float32)
    y = (np.arange(n) % 3 + 1).astype(np.float32)
    return dp.Pipeline(dp.ArrayRecordReader(X, y, shard_size=24,
                                            seed=seed)) \
        .shuffle(buffer_size=32, seed=seed) \
        .batch(batch, drop_remainder=True)


def _mlp():
    return nn.Sequential().add(nn.Linear(8, 16)).add(nn.Tanh()) \
        .add(nn.Linear(16, 3)).add(nn.LogSoftMax())


def _run_stream_opt(k, iters=12, checkpoint=None, trigger=None):
    RandomGenerator.set_seed(17)
    ds = _sample_pipeline(seed=5).as_dataset(batch_size=16)
    opt = LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion(),
                         batch_size=16)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(iters))
    opt.set_steps_per_sync(k)
    if checkpoint:
        opt.set_checkpoint(checkpoint, trigger or several_iteration(4))
    model = opt.optimize()
    import jax
    params = [np.asarray(l) for l in
              jax.tree_util.tree_leaves(model.get_parameters())]
    return params, opt


@pytest.mark.parametrize("k", [8])
def test_streaming_source_k1_vs_k8_equivalence(k):
    """The windowed-equivalence harness over the STREAMING source: the
    pipeline's seeded shuffle + cursor make the batch stream identical
    whatever K, so fused windows and per-step sync converge to the
    same params (the PR-4 guarantee extended through the data plane)."""
    p1, o1 = _run_stream_opt(1)
    pk, ok = _run_stream_opt(k)
    assert o1.driver_state["neval"] == ok.driver_state["neval"]
    assert o1.driver_state["epoch"] == ok.driver_state["epoch"]
    for a, b in zip(p1, pk):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_optimizer_checkpoints_and_restores_pipeline_cursor(tmp_path):
    import json
    ck = str(tmp_path / "ck")
    _, opt = _run_stream_opt(1, iters=9, checkpoint=ck)
    latest = None
    from bigdl_tpu.utils.serialization import find_latest_checkpoint
    latest = find_latest_checkpoint(ck)
    assert latest is not None
    with open(os.path.join(latest, "host_state.json")) as f:
        host = json.load(f)
    cursor = host["driver_state"].get("datapipe")
    assert cursor is not None
    assert set(cursor) == {"epoch", "spos", "offset"}

    # a fresh optimizer resuming from this checkpoint must restore the
    # cursor into its OWN pipeline before building the data iterator
    RandomGenerator.set_seed(17)
    ds2 = _sample_pipeline(seed=5).as_dataset(batch_size=16)
    opt2 = LocalOptimizer(_mlp(), ds2, nn.ClassNLLCriterion(),
                          batch_size=16)
    opt2.set_optim_method(SGD(learning_rate=0.1))
    opt2.set_end_when(max_iteration(10))
    opt2.set_checkpoint(ck, several_iteration(100))
    opt2.optimize()
    assert opt2.driver_state["neval"] == 11  # resumed, not restarted
    assert "datapipe" not in opt2.driver_state
    assert ds2.pipeline_state() != {"epoch": 0, "spos": 0, "offset": 0}


def test_as_dataset_uses_cheap_count_for_count_preserving_stages():
    rng = np.random.RandomState(1)
    X = rng.randn(30, 4).astype(np.float32)
    y = np.ones(30, np.float32)
    pipe = dp.Pipeline(dp.ArrayRecordReader(X, y, shard_size=10)) \
        .map(lambda s: s).shuffle(buffer_size=8, seed=1)
    # map/shuffle preserve cardinality: the reader's num_records() must
    # answer without a cold epoch scan
    pipe.count_epoch_records = None  # a scan would now TypeError
    ds = pipe.as_dataset()
    assert ds.size() == 30


def test_eval_iteration_is_repeatable_and_cursor_free():
    """data(train=False) must honor the AbstractDataSet eval contract:
    identical stream on every call, and NO side effect on the training
    cursor (a validation trigger mid-training must not eat an epoch)."""
    pipe = _sample_pipeline(seed=5)
    ds = pipe.as_dataset(batch_size=16)
    before = ds.pipeline_state()
    a = [np.asarray(mb.input) for mb in ds.data(train=False)]
    b = [np.asarray(mb.input) for mb in ds.data(train=False)]
    assert len(a) == len(b) > 0
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert ds.pipeline_state() == before


def test_as_dataset_batch_stage_uses_cheap_count():
    rng = np.random.RandomState(1)
    X = rng.randn(30, 4).astype(np.float32)
    y = np.ones(30, np.float32)
    pipe = dp.Pipeline(dp.ArrayRecordReader(X, y, shard_size=10)) \
        .shuffle(buffer_size=8, seed=1).batch(7)  # non-dropping
    pipe.count_epoch_records = None  # a scan would now TypeError
    assert pipe.as_dataset().size() == 30


def test_transformed_pipeline_dataset_still_checkpoints_cursor(tmp_path):
    """`pipe.as_dataset().transform(...)` must not silently lose cursor
    checkpointing: the optimizer walks the wrapper's .base chain."""
    import json
    from bigdl_tpu.dataset.transformer import Lambda
    from bigdl_tpu.utils.serialization import find_latest_checkpoint
    ck = str(tmp_path / "ck")
    RandomGenerator.set_seed(17)
    inner = _sample_pipeline(seed=5).as_dataset(batch_size=16)
    wrapped = inner.transform(Lambda(lambda mb: mb))
    opt = LocalOptimizer(_mlp(), wrapped, nn.ClassNLLCriterion(),
                         batch_size=16)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(6))
    opt.set_checkpoint(ck, several_iteration(3))
    opt.optimize()
    latest = find_latest_checkpoint(ck)
    with open(os.path.join(latest, "host_state.json")) as f:
        host = json.load(f)
    assert host["driver_state"].get("datapipe") is not None


# ------------------------------------------------------------ telemetry

def test_padding_efficiency_gauge_lands_in_registry():
    import bigdl_tpu.telemetry as telemetry
    docs = _docs(30, seed=6)
    dp.pack_documents(docs, 32)
    snap = telemetry.registry().snapshot()
    names = {row["name"] for row in snap}
    assert "data/packing/padding_efficiency" in names
    row = next(r for r in snap
               if r["name"] == "data/packing/padding_efficiency")
    assert 0.5 < row["series"][0]["value"] <= 1.0


def test_diagnose_feed_summary_ingests_datapipe_gauges():
    import bigdl_tpu.telemetry as telemetry
    from bigdl_tpu.tools.diagnose import feed_summary
    docs = _docs(30, seed=6)
    dp.pack_documents(docs, 32)
    list(dp.WindowShuffle(8, seed=1)(iter(range(20)), epoch=0))
    feed = feed_summary(telemetry.registry().snapshot())
    assert "padding_efficiency" in feed
    assert "shuffle_buffer_depth" in feed
