"""Device-level observability tests (ISSUE 10): program profile
registry (XLA cost/memory analysis -> FLOPs/HBM/MFU gauges, the
scan-body caveat in ONE place, ceiling MFU golden-unchanged),
per-request trace propagation (queue-wait + prefill + per-token decode
spans on a linked track, asserted on exported JSON), the crash flight
recorder (WorkerDied and fatal-optimizer bundles that
``diagnose --postmortem`` ingests; disarmed = one flag check), the
bench regression sentinel (checked-in BENCH_r01–r05 passes, a
synthetic 20% drop fails, unknown schema refused), and the exporter
edge cases the new series exercise."""
import glob
import json
import os
import time

import numpy as np
import pytest

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import flight, programs

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _observability_off():
    """Every test starts and ends with tracing, profiling and the
    flight recorder disabled (cumulative registries are read via
    deltas or private instances)."""
    telemetry.disable()
    telemetry.tracer().clear()
    programs.disable()
    flight.disarm()
    yield
    telemetry.disable()
    telemetry.tracer().clear()
    programs.disable()
    flight.disarm()


def _lenet_step():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step

    model = LeNet5(10).set_name("LeNet5").training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.05)
    params = model.get_parameters()
    step = build_train_step(model, nn.ClassNLLCriterion(), optim)
    return model, step, (params, optim.init_state(params),
                         model.get_state())


# ------------------------------------------------- program registry

class TestProgramRegistry:
    def test_resolve_per_item_flops_is_the_one_scan_caveat_home(self):
        """The scan-body-counted-once disambiguation: body-once wins
        when closer to the estimate, body x K wins when IT is closer,
        and neither-within-4x falls back to the estimate outright."""
        # 8 items/call, scan of 4: per-item candidates are 100 (body
        # once) and 25 (body counted x4)
        f = programs.resolve_per_item_flops
        assert f(800.0, 8) == 100.0                      # no estimate
        assert f(800.0, 8, 4, per_item_estimate=90.0) == 100.0
        assert f(800.0, 8, 4, per_item_estimate=26.0) == 25.0
        # estimate 4x+ away from both candidates: trust the estimate
        assert f(800.0, 8, 4, per_item_estimate=5.0) == 5.0

    def test_ceiling_mfu_fields_golden_unchanged(self):
        """ceiling.py's reported MFU must be byte-identical after the
        dedupe — replicate the pre-refactor math here and compare."""
        import math

        from bigdl_tpu.tools import ceiling as C

        def legacy(rate, per_item_flops, per_chunk, batch, scan, peak):
            if per_chunk is not None and per_chunk > 0:
                per_item = per_chunk / batch
                if per_item_flops:
                    cands = (per_item, per_chunk / (batch * scan))
                    per_item = min(cands, key=lambda c: abs(
                        math.log(c / per_item_flops)))
                    if not 0.25 < per_item / per_item_flops < 4.0:
                        per_item = per_item_flops
                tfs = per_item * rate / 1e12
            elif per_item_flops:
                tfs = per_item_flops * rate / 1e12
            else:
                return {}
            return {"achieved_tfs": round(tfs, 2),
                    "mfu_vs_peak": round(tfs / peak, 3),
                    "peak_tfs": peak}

        old_flops, old_b, old_s = C._FLOPS["per_chunk"], C.BATCH, C.SCAN
        try:
            C.BATCH, C.SCAN = 256, 8
            for per_chunk, est in ((6.2e15, None), (6.2e15, 2.4e10),
                                   (6.2e15, 3.1e12), (6.2e15, 1.0),
                                   (None, 2.4e10), (None, None),
                                   (0.0, 5e9)):
                C._FLOPS["per_chunk"] = per_chunk
                got = C.mfu_fields(2500.0, est)
                want = legacy(2500.0, est, per_chunk, 256, 8,
                              C.DEVICE_TFS)
                assert got == want, (per_chunk, est, got, want)
        finally:
            C._FLOPS["per_chunk"] = old_flops
            C.BATCH, C.SCAN = old_b, old_s

    def test_lenet_train_step_reports_nonzero_flops_hbm_mfu(self):
        """Acceptance: a compiled LeNet train step reports non-zero
        FLOPs, HBM bytes and (after a measured rate) MFU gauges."""
        import jax

        programs.enable()
        model, step, (params, opt_state, mstate) = _lenet_step()
        x = np.random.rand(8, 1, 28, 28).astype(np.float32)
        y = (np.random.randint(0, 10, 8) + 1).astype(np.float32)
        p2, o2, m2, loss = step(params, opt_state, mstate,
                                jax.random.PRNGKey(0), 0.05, x, y)
        assert np.isfinite(float(loss))

        from bigdl_tpu.optim.optimizer import train_program_name
        name = train_program_name(model)
        prof = programs.registry().get(name)
        assert prof is not None and prof.kind == "train"
        assert prof.flops > 0 and prof.hbm_bytes > 0
        assert prof.compile_s > 0 and prof.items_per_call == 8

        programs.record_rate(name, 10_000.0)
        assert prof.mfu is not None and prof.mfu > 0
        labels = {"program": name}
        r = telemetry.registry()
        assert r.gauge("train/program/flops").value(**labels) > 0
        assert r.gauge("train/program/hbm_bytes").value(**labels) > 0
        assert r.gauge("train/program/mfu").value(**labels) > 0

        # the profiled step keeps computing: a second call reuses the
        # compiled program and matches a fresh unprofiled step's shape
        p3, o3, m3, loss2 = step(p2, o2, m2, jax.random.PRNGKey(1),
                                 0.05, x, y)
        assert np.isfinite(float(loss2))
        assert len(programs.registry().profiles()) >= 1

    def test_serving_bucket_reports_nonzero_flops_hbm_mfu(self):
        """Acceptance: one serving bucket through the CompileCache
        registers a serving/program/* profile with non-zero FLOPs,
        HBM bytes and (auto-rated) MFU."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.serving.compile_cache import CompileCache

        programs.enable()
        model = nn.Sequential().add(nn.Reshape((28 * 28,))) \
            .add(nn.Linear(28 * 28, 10))
        model.ensure_initialized()
        cache = CompileCache()
        step = cache.step_for(("obs-lenet", 1), model)
        x = np.random.rand(8, 1, 28, 28).astype(np.float32)
        out = step(model.get_parameters(), model.get_state(), x)
        assert np.asarray(out).shape == (8, 10)
        assert cache.compile_count(("obs-lenet", 1)) == 1

        prof = programs.registry().get("obs-lenet/1")
        assert prof is not None and prof.kind == "serving"
        assert prof.flops > 0 and prof.hbm_bytes > 0
        # auto_rate: the synchronous serving call recorded a rate
        assert prof.mfu is not None and prof.mfu >= 0
        labels = {"program": "obs-lenet/1"}
        r = telemetry.registry()
        assert r.gauge("serving/program/flops").value(**labels) > 0
        assert r.gauge("serving/program/hbm_bytes").value(**labels) > 0
        # second call: cached program, no recompile
        step(model.get_parameters(), model.get_state(), x)
        assert cache.compile_count(("obs-lenet", 1)) == 1

    def test_disabled_profiling_is_passthrough(self):
        """Profiling off (the default): build sites return the raw jit
        wrapper (AOT consumers keep .lower) and register nothing."""
        assert not programs.enabled()
        before = {p.name for p in programs.registry().profiles()}
        import jax

        model, step, (params, opt_state, mstate) = _lenet_step()
        assert hasattr(step, "lower")
        assert not isinstance(step, programs._ProfiledProgram)
        x = np.random.rand(4, 1, 28, 28).astype(np.float32)
        y = (np.random.randint(0, 10, 4) + 1).astype(np.float32)
        step(params, opt_state, mstate, jax.random.PRNGKey(0), 0.05,
             x, y)
        after = {p.name for p in programs.registry().profiles()}
        assert after == before

    def test_profiled_step_transparent_under_outer_trace(self):
        """A profiled step scanned inside an outer jit must pass
        tracers through untouched (the OUTER program is the compiled
        artifact)."""
        import functools

        import jax
        from jax import lax

        programs.enable()
        model, step, carry = _lenet_step()
        x = np.random.rand(4, 1, 28, 28).astype(np.float32)
        y = (np.random.randint(0, 10, 4) + 1).astype(np.float32)

        def body(c, key):
            p, o, m = c
            p, o, m, loss = step(p, o, m, key, 0.05, x, y)
            return (p, o, m), loss

        @functools.partial(jax.jit, donate_argnums=(0,))
        def chunk(c, keys):
            return lax.scan(body, c, keys)

        _, losses = chunk(carry, jax.random.split(jax.random.PRNGKey(1),
                                                  3))
        assert np.isfinite(np.asarray(losses)).all()


# ---------------------------------------------------- request tracing

def _tiny_generation_service(slots=2, max_len=16):
    from bigdl_tpu.generation import GenerationConfig, GenerationService
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(3)
    model = TransformerLM(vocab_size=64, hidden_size=32, num_layers=1,
                          num_heads=2, max_len=max_len).evaluate()
    model.ensure_initialized()
    svc = GenerationService(config=GenerationConfig(
        slots=slots, max_len=max_len, prefill_rows=slots))
    svc.load("lm", model)
    return svc


class TestRequestTracing:
    def test_generation_trace_one_request_linked_track(self, tmp_path):
        """Acceptance: for one trace_id the exported Chrome trace
        carries queue-wait + prefill + >= max_tokens decode spans on
        ONE (virtual) track, flow-linked to the decode thread —
        asserted on the exported JSON, not internals."""
        telemetry.enable()
        svc = _tiny_generation_service()
        try:
            max_new = 4
            streams = [svc.generate("lm", np.array([1, 2, 3]),
                                    max_new_tokens=max_new)
                       for _ in range(3)]
            for s in streams:
                s.result()
            trace_id = streams[0].trace_id
            assert trace_id
            path = str(tmp_path / "gen_trace.json")
            telemetry.export_chrome_trace(path)
        finally:
            svc.shutdown()
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        mine = [e for e in events if e.get("ph") == "X"
                and (e.get("args") or {}).get("trace_id") == trace_id]
        names = [e["name"] for e in mine]
        assert names.count("serving/request/queue_wait") >= 1
        assert names.count("serving/request/prefill") >= 1
        # one span per token landed (the first rides the prefill
        # program): >= max_tokens decode spans
        assert names.count("serving/request/decode") >= max_new
        # ... all on ONE track, which is not any OS thread's track
        tids = {e["tid"] for e in mine}
        assert len(tids) == 1
        track = tids.pop()
        thread_tids = {e["tid"] for e in events if e.get("ph") == "X"
                       and e["name"] in ("serving/prefill",
                                         "serving/decode")}
        assert track not in thread_tids
        # the track is labelled with the trace id and flow-linked
        assert any(e.get("ph") == "M"
                   and e["args"]["name"] == f"req {trace_id}"
                   for e in events)
        flows = [e for e in events if e.get("ph") in ("s", "f")
                 and e.get("id") == trace_id]
        assert {"s", "f"} <= {e["ph"] for e in flows}

    def test_generation_trace_decode_cadence_ordered(self, tmp_path):
        """Per-token decode spans carry the token index and advance in
        time — the per-token cadence a TTFT investigation reads."""
        telemetry.enable()
        svc = _tiny_generation_service()
        try:
            stream = svc.generate("lm", np.array([5, 6]),
                                  max_new_tokens=3)
            stream.result()
            trace_id = stream.trace_id
            events = telemetry.tracer().chrome_trace_events()
        finally:
            svc.shutdown()
        decodes = [e for e in events if e.get("ph") == "X"
                   and e["name"] == "serving/request/decode"
                   and (e.get("args") or {}).get("trace_id") == trace_id]
        toks = [e["args"]["token"] for e in decodes]
        assert toks == sorted(toks) and toks[0] == 0
        ts = [e["ts"] for e in decodes]
        assert ts == sorted(ts)

    def test_microbatcher_trace_id_on_future_and_track(self):
        """MicroBatcher.submit assigns a trace_id carried to the
        response future; with tracing on the request's queue wait and
        batch membership land on its track."""
        from bigdl_tpu.serving.batcher import MicroBatcher
        from bigdl_tpu.serving.compile_cache import BucketLadder

        telemetry.enable()
        mb = MicroBatcher(lambda x: x, BucketLadder(4), max_wait_ms=1.0,
                          name="obs")
        try:
            fut = mb.submit(np.ones((1, 2), np.float32))
            np.testing.assert_array_equal(
                fut.result(timeout=5), np.ones((1, 2), np.float32))
            assert fut.trace_id.startswith("obs/req-")
            time.sleep(0.05)
            events = telemetry.tracer().chrome_trace_events()
        finally:
            mb.shutdown(drain=False)
        mine = [e for e in events if e.get("ph") == "X"
                and (e.get("args") or {}).get("trace_id") == fut.trace_id]
        names = {e["name"] for e in mine}
        assert "serving/request/queue_wait" in names
        assert "serving/request/batch" in names
        batch_ev = next(e for e in mine
                        if e["name"] == "serving/request/batch")
        assert batch_ev["args"]["bucket"] >= batch_ev["args"]["rows"]

    def test_virtual_track_table_is_bounded(self):
        """Request trace_ids arrive at traffic rate: the name->tid
        track table must evict (oldest first), never grow without
        bound — and metadata rows for evicted tracks age out of the
        export."""
        from bigdl_tpu.telemetry import SpanTracer

        tr = SpanTracer(capacity=16)
        cap = tr._MAX_TRACKS
        tids = [tr.track(f"req r-{i}") for i in range(cap + 100)]
        assert len(set(tids)) == cap + 100  # no tid reuse
        assert len(tr._tracks) == cap
        # the oldest 100 evicted, newest retained and stable
        assert tr.track(f"req r-{cap + 99}") == tids[-1]
        assert "req r-0" not in tr._tracks
        meta_names = {e["args"]["name"]
                      for e in tr.chrome_trace_events()
                      if e["ph"] == "M"}
        assert f"req r-{cap + 99}" in meta_names
        assert "req r-0" not in meta_names

    def test_tracing_disabled_records_no_request_spans(self):
        """Disabled tracing: trace_ids still assigned (cheap), but the
        ring stays empty — the <5us disabled-overhead contract in
        test_telemetry covers the span() fast path itself."""
        from bigdl_tpu.serving.batcher import MicroBatcher
        from bigdl_tpu.serving.compile_cache import BucketLadder

        assert not telemetry.enabled()
        mb = MicroBatcher(lambda x: x, BucketLadder(4), max_wait_ms=1.0,
                          name="quiet")
        try:
            fut = mb.submit(np.ones((1, 2), np.float32))
            fut.result(timeout=5)
            assert fut.trace_id
        finally:
            mb.shutdown(drain=False)
        assert len(telemetry.tracer()) == 0


# --------------------------------------------------- flight recorder

class TestFlightRecorder:
    def test_disarmed_note_is_one_flag_check(self):
        """The telemetry.span discipline: a disarmed note() must cost
        a flag check, nothing else (budget generous for CI noise)."""
        assert not flight.armed()
        n = 50_000
        t0 = time.perf_counter()
        for i in range(n):
            flight.note("fault", point="x")
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"{per_call * 1e6:.2f}us disarmed note"

    def test_worker_died_dumps_bundle_diagnose_ingests(self, tmp_path):
        """Acceptance: an injected serving dispatch death produces a
        bundle `diagnose --postmortem` ingests (exit 0)."""
        from bigdl_tpu import faults
        from bigdl_tpu.serving.batcher import MicroBatcher, WorkerDied
        from bigdl_tpu.serving.compile_cache import BucketLadder
        from bigdl_tpu.tools.diagnose import main as diagnose_main

        flight.arm(str(tmp_path))
        mb = MicroBatcher(lambda x: x, BucketLadder(4), max_wait_ms=1.0,
                          name="doomed")
        try:
            with faults.armed("serving/take_batch=nth:1,raise"):
                fut = mb.submit(np.ones((1, 2), np.float32))
                with pytest.raises(WorkerDied):
                    fut.result(timeout=5)
            deadline = time.monotonic() + 5
            while not glob.glob(str(tmp_path / "postmortem-*")) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            mb.shutdown(drain=False)
        (bundle,) = glob.glob(str(tmp_path / "postmortem-*"))
        for name in ("MANIFEST.json", "events.jsonl", "trace.json",
                     "metrics.json", "programs.json"):
            assert os.path.exists(os.path.join(bundle, name)), name
        with open(os.path.join(bundle, "MANIFEST.json")) as f:
            manifest = json.load(f)
        assert manifest["reason"] == "serving/dispatch"
        assert manifest["error"]["type"] == "InjectedFault"
        assert diagnose_main(["--postmortem", bundle]) == 0
        assert diagnose_main(["--postmortem", bundle, "--json"]) == 0

    def test_fatal_optimizer_error_dumps_bundle(self, tmp_path):
        """Acceptance: a fatal classified Optimizer error (TypeError —
        structural, never retried) dumps a bundle diagnose ingests."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu import faults
        from bigdl_tpu.dataset import (DataSet, Sample,
                                       SampleToMiniBatch)
        from bigdl_tpu.models import LeNet5
        from bigdl_tpu.optim import SGD, LocalOptimizer, max_iteration
        from bigdl_tpu.tools.diagnose import main as diagnose_main

        flight.arm(str(tmp_path))
        rng = np.random.RandomState(0)
        x = rng.rand(16, 1, 28, 28).astype(np.float32)
        y = (rng.randint(0, 10, 16) + 1).astype(np.float32)
        ds = DataSet.array([Sample(x[i], y[i]) for i in range(16)]) \
            .transform(SampleToMiniBatch(8))
        opt = LocalOptimizer(LeNet5(10), ds, nn.ClassNLLCriterion(),
                             batch_size=8)
        opt.set_optim_method(SGD(learning_rate=0.05))
        opt.set_end_when(max_iteration(4))
        with faults.armed("train/step=nth:1,raise:TypeError"):
            with pytest.raises(TypeError):
                opt.optimize()
        (bundle,) = glob.glob(str(tmp_path / "postmortem-*"))
        with open(os.path.join(bundle, "MANIFEST.json")) as f:
            manifest = json.load(f)
        assert manifest["reason"] == "train/optimizer"
        assert manifest["error"]["type"] == "TypeError"
        # the ring captured the injected fault leading up to the death
        with open(os.path.join(bundle, "events.jsonl")) as f:
            kinds = [json.loads(ln)["kind"] for ln in f if ln.strip()]
        assert "fault" in kinds and "fatal" in kinds
        assert diagnose_main(["--postmortem", bundle]) == 0

    def test_postmortem_refuses_foreign_dir(self, tmp_path):
        from bigdl_tpu.tools.diagnose import main as diagnose_main

        assert diagnose_main(["--postmortem", str(tmp_path)]) == 2
        (tmp_path / "MANIFEST.json").write_text('{"format": "other"}')
        assert diagnose_main(["--postmortem", str(tmp_path)]) == 2

    def test_dump_cap_bounds_disk(self, tmp_path):
        import bigdl_tpu.telemetry.flight as fl

        flight.arm(str(tmp_path))
        old_seq = fl._SEQ[0]
        try:
            fl._SEQ[0] = fl._MAX_DUMPS
            assert flight.dump("cap-test") is None
        finally:
            fl._SEQ[0] = old_seq


# ------------------------------------------------ regression sentinel

class TestRegressionSentinel:
    def _trajectory(self):
        return sorted(glob.glob(os.path.join(_ROOT, "BENCH_r*.json")))

    def test_checked_in_trajectory_passes(self):
        """Acceptance: the banked BENCH_r01–r05 trajectory exits 0."""
        from bigdl_tpu.tools.regress import main

        paths = self._trajectory()
        assert len(paths) >= 5
        assert main(paths) == 0

    def test_synthetic_20pct_drop_fails(self, tmp_path):
        """Acceptance: a 20% throughput drop exits 1."""
        from bigdl_tpu.tools.regress import main

        paths = self._trajectory()
        with open(paths[-1]) as f:
            parsed = json.load(f)["parsed"]
        bad = dict(parsed, value=parsed["value"] * 0.8,
                   vs_baseline=parsed["vs_baseline"] * 0.8)
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(bad))
        assert main(paths + ["--candidate", str(cand)]) == 1

    def test_latency_direction_is_lower_is_better(self, tmp_path):
        """*_ms latencies regress UP: a 50% TTFT increase exits 1, a
        50% decrease passes."""
        from bigdl_tpu.tools.regress import main

        base = {"schema_version": 2, "value": 100.0,
                "generation_ttft_ms_p50": 10.0}
        pts = []
        for i in range(3):
            p = tmp_path / f"t{i}.json"
            p.write_text(json.dumps(base))
            pts.append(str(p))
        slow = tmp_path / "slow.json"
        slow.write_text(json.dumps(
            dict(base, generation_ttft_ms_p50=15.0)))
        fast = tmp_path / "fast.json"
        fast.write_text(json.dumps(
            dict(base, generation_ttft_ms_p50=5.0)))
        assert main(pts + ["--candidate", str(slow)]) == 1
        assert main(pts + ["--candidate", str(fast)]) == 0

    def test_new_metric_never_fails_the_build(self, tmp_path):
        from bigdl_tpu.tools.regress import main

        pts = []
        for i in range(3):
            p = tmp_path / f"t{i}.json"
            p.write_text(json.dumps({"value": 100.0}))
            pts.append(str(p))
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(
            {"value": 99.0, "brand_new_tokens_per_sec": 1.0}))
        assert main(pts + ["--candidate", str(cand)]) == 0

    def test_unknown_schema_version_refused(self, tmp_path, capsys):
        """Acceptance satellite: unknown schema_version exits 2 with a
        clear message."""
        from bigdl_tpu.tools.regress import main

        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps({"schema_version": 99, "value": 1}))
        with pytest.raises(SystemExit) as exc:
            main(self._trajectory() + ["--candidate", str(cand)])
        assert exc.value.code == 2
        assert "schema_version" in capsys.readouterr().err

    def test_key_direction_rules(self):
        from bigdl_tpu.tools.regress import classify_key

        assert classify_key("resnet50_imgs_per_sec") == "higher"
        assert classify_key("value") == "higher"
        assert classify_key("programs_resnet50_train_mfu") == "higher"
        assert classify_key("generation_ttft_ms_p99") == "lower"
        assert classify_key("programs_resnet50_train_hbm_bytes") \
            == "lower"
        assert classify_key("zero_stage2_opt_state_bytes_per_chip") \
            == "lower"
        assert classify_key("generation_compiles") == "lower"
        assert classify_key("steps_per_sync") is None
        assert classify_key("unit") is None


# --------------------------------------------- exporter edge cases

class TestExporterEdgeCases:
    def test_prometheus_program_label_slashes_quotes_roundtrip(self):
        """Program-name labels carry slashes and may carry quotes or
        backslashes (registry keys are arbitrary) — the text
        exposition escaping must round-trip them exactly."""
        from bigdl_tpu.telemetry import (parse_prometheus_text,
                                         prometheus_text)

        r = telemetry.MetricsRegistry()
        g = r.gauge("serving/program/hbm_bytes", "d")
        gnarly = ['lm/v1/prefill/64', 'model "quoted"/v2',
                  'back\\slash/step', 'multi\nline/decode/8']
        for i, name in enumerate(gnarly):
            g.set(float(i + 1), program=name)
        text = prometheus_text(r.snapshot())
        parsed = parse_prometheus_text(text)
        for i, name in enumerate(gnarly):
            key = ("serving_program_hbm_bytes", (("program", name),))
            assert parsed[key] == float(i + 1), name

    def test_jsonl_roundtrip_of_program_profile_gauges(self, tmp_path):
        """A registered profile's gauges survive the JSONL snapshot
        round-trip with label and value intact."""
        from bigdl_tpu.telemetry import JsonlExporter, read_jsonl

        r = telemetry.MetricsRegistry()
        reg = programs.ProgramRegistry(metrics=r)
        reg.register("rt/model/step", "train",
                     analysis={"flops": 1.5e9, "bytes_accessed": 3e8,
                               "hbm_bytes": 2.5e8},
                     compile_s=1.25, items_per_call=32)
        reg.record_rate("rt/model/step", 1000.0)
        path = str(tmp_path / "m.jsonl")
        JsonlExporter(r, path).export(step=1)
        (rec,) = read_jsonl(path)
        by_name = {row["name"]: row for row in rec["metrics"]}
        flops = by_name["train/program/flops"]["series"]
        assert flops[0]["labels"] == {"program": "rt/model/step"}
        assert flops[0]["value"] == 1.5e9
        assert by_name["train/program/mfu"]["series"][0]["value"] > 0
        assert by_name["train/program/compile_s"]["series"][0][
            "value"] == 1.25

    def test_flight_bundle_metrics_json_is_snapshot_shaped(
            self, tmp_path):
        """diagnose ingestion contract: the bundle's metrics.json rows
        are registry-snapshot rows (name/kind/series)."""
        flight.arm(str(tmp_path))
        flight.note("fault", point="x")
        bundle = flight.dump("contract-test")
        assert bundle is not None
        with open(os.path.join(bundle, "metrics.json")) as f:
            snaps = json.load(f)
        for rows in snaps.values():
            for row in rows:
                assert {"name", "kind", "series"} <= set(row)
