"""Gradient clipping (Optimizer.scala setConstantGradientClipping /
setGradientClippingByl2Norm — the reference's stabilizer applied to the
aggregated gradients before the update, DistriOptimizer's
parameterProcessers)."""
import numpy as np

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration
from bigdl_tpu.optim.optimizer import build_train_step
from bigdl_tpu.utils.random import RandomGenerator


def _setup(scale=100.0):
    RandomGenerator.set_seed(5)
    model = nn.Sequential().add(nn.Linear(4, 3)).training()
    model.ensure_initialized()
    crit = nn.MSECriterion()
    optim = SGD(learning_rate=1.0)
    params = model.get_parameters()
    x = jnp.asarray(np.full((2, 4), scale, np.float32))
    y = jnp.zeros((2, 3), jnp.float32)
    return model, crit, optim, params, x, y


def _grads_via_update(model, crit, optim, params, x, y, clip):
    """Recover the applied gradient from a lr-1 plain-SGD update."""
    host_p = jax.tree.map(np.asarray, params)  # step donates its inputs
    step = build_train_step(model, crit, optim, gradient_clip=clip)
    opt_state = optim.init_state(host_p)
    new_p, _, _, _ = step(jax.tree.map(jnp.asarray, host_p), opt_state,
                          model.get_state(), jax.random.PRNGKey(0),
                          1.0, x, y)
    return jax.tree.map(lambda a, b: np.asarray(b) - np.asarray(a),
                        jax.tree.map(np.asarray, new_p), host_p)


def test_l2_norm_clipping_bounds_the_global_norm():
    model, crit, optim, params, x, y = _setup()
    g_raw = _grads_via_update(model, crit, optim, params, x, y, None)
    raw_norm = float(np.sqrt(sum(
        np.sum(np.square(g)) for g in jax.tree.leaves(g_raw))))
    assert raw_norm > 5.0  # the test is vacuous otherwise

    g_clip = _grads_via_update(model, crit, optim, params, x, y,
                               ("l2norm", 5.0))
    clip_norm = float(np.sqrt(sum(
        np.sum(np.square(g)) for g in jax.tree.leaves(g_clip))))
    np.testing.assert_allclose(clip_norm, 5.0, rtol=1e-4)
    # DIRECTION preserved: clipped = raw * (5/raw_norm)
    for a, b in zip(jax.tree.leaves(g_clip), jax.tree.leaves(g_raw)):
        np.testing.assert_allclose(a, b * (5.0 / raw_norm), rtol=1e-4)


def test_l2_norm_clipping_is_noop_below_threshold():
    model, crit, optim, params, x, y = _setup(scale=0.001)
    g_raw = _grads_via_update(model, crit, optim, params, x, y, None)
    g_clip = _grads_via_update(model, crit, optim, params, x, y,
                               ("l2norm", 5.0))
    for a, b in zip(jax.tree.leaves(g_clip), jax.tree.leaves(g_raw)):
        np.testing.assert_allclose(a, b, rtol=1e-5)


def test_constant_clipping_bounds_every_element():
    model, crit, optim, params, x, y = _setup()
    g = _grads_via_update(model, crit, optim, params, x, y,
                          ("constant", -0.1, 0.1))
    for leaf in jax.tree.leaves(g):
        assert float(np.max(leaf)) <= 0.1 + 1e-6
        assert float(np.min(leaf)) >= -0.1 - 1e-6


def test_fluent_surface_reaches_the_step():
    """set_gradient_clipping_by_l2_norm on the Optimizer keeps an
    lr-1.0 run on exploding data finite (it diverges unclipped)."""
    RandomGenerator.set_seed(7)
    rng = np.random.RandomState(0)
    xs = (rng.randn(32, 6) * 50).astype(np.float32)
    ys = (rng.randn(32, 1) * 50).astype(np.float32)
    ds = DataSet.array([Sample(xs[i], ys[i]) for i in range(32)]) \
        .transform(SampleToMiniBatch(8))

    def run(clip):
        RandomGenerator.set_seed(7)
        model = (nn.Sequential().add(nn.Linear(6, 8)).add(nn.Tanh())
                 .add(nn.Linear(8, 1)))
        opt = LocalOptimizer(model, ds, nn.MSECriterion(), batch_size=8)
        opt.set_optim_method(SGD(learning_rate=1.0))
        if clip:
            opt.set_gradient_clipping_by_l2_norm(1.0)
        opt.set_end_when(max_iteration(20))
        opt.optimize()
        return opt.driver_state["Loss"]

    unclipped = run(False)
    assert not np.isfinite(unclipped) or unclipped > 1e4
    assert np.isfinite(run(True))


def test_fluent_set_model_and_set_state():
    """Optimizer.scala:230/:240 — swap the model and seed the driver
    state before optimize()."""
    RandomGenerator.set_seed(9)
    rng = np.random.RandomState(1)
    xs = rng.randn(16, 4).astype(np.float32)
    ys = (rng.randint(0, 2, 16) + 1).astype(np.float32)
    ds = DataSet.array([Sample(xs[i], ys[i]) for i in range(16)]) \
        .transform(SampleToMiniBatch(8))
    placeholder = nn.Sequential().add(nn.Linear(4, 2))
    real = (nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax()))
    opt = (LocalOptimizer(placeholder, ds, nn.ClassNLLCriterion(),
                          batch_size=8)
           .set_model(real)
           .set_state({"epoch": 3})
           .set_end_when(max_iteration(2)))
    opt.optimize()
    assert opt.model is real
    assert opt.driver_state["epoch"] >= 3  # seeded, not reset


def test_constant_clipping_rejects_inverted_range():
    import pytest
    opt = LocalOptimizer(nn.Sequential().add(nn.Linear(2, 2)),
                         DataSet.array([Sample(np.zeros(2, np.float32),
                                               1.0)]),
                         nn.MSECriterion(), batch_size=1)
    with pytest.raises(ValueError, match="min <= max"):
        opt.set_constant_gradient_clipping(0.1, -0.1)


def test_set_state_reaches_epoch_lr_schedules():
    """A seeded epoch must drive epoch-based schedules from step one —
    not after the first rollover (the resume use case)."""
    from bigdl_tpu.optim import EpochStep

    RandomGenerator.set_seed(11)
    rng = np.random.RandomState(2)
    xs = rng.randn(16, 4).astype(np.float32)
    ys = rng.randn(16, 1).astype(np.float32)
    ds = DataSet.array([Sample(xs[i], ys[i]) for i in range(16)]) \
        .transform(SampleToMiniBatch(8))
    model = nn.Sequential().add(nn.Linear(4, 1))
    opt = (LocalOptimizer(model, ds, nn.MSECriterion(), batch_size=8)
           .set_state({"epoch": 26})
           .set_end_when(max_iteration(2)))
    # EpochStep(25, 0.5): epoch 26 -> lr * 0.5
    opt.set_optim_method(SGD(learning_rate=0.4,
                             learning_rate_schedule=EpochStep(25, 0.5)))
    opt.optimize()
    np.testing.assert_allclose(opt.driver_state["LearningRate"], 0.2,
                               rtol=1e-6)
