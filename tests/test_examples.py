"""Runnable examples (reference example/ tree, SURVEY §2.4:
textclassification, loadmodel ModelValidator, udfpredictor)."""
import os
import sys

# examples/ is a plain folder at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_text_classification_example_converges():
    from examples.text_classification import main
    state = main(["--synthetic", "200", "--classes", "2", "-e", "6",
                  "-b", "32", "--vocabSize", "200"])
    assert state["score"] > 0.8  # separable synthetic corpus


def test_load_model_example_bigdl_synthetic(tmp_path):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.serialization import save_module
    from examples.load_model import main

    m = (nn.Sequential().add(nn.Reshape((3 * 16 * 16,)))
         .add(nn.Linear(3 * 16 * 16, 10)).add(nn.LogSoftMax()))
    m.ensure_initialized()
    save_module(str(tmp_path / "m"), m)
    results = main(["--model-type", "bigdl", "--model",
                    str(tmp_path / "m"), "--synthetic", "32",
                    "--classes", "10", "--size", "16", "-b", "16"])
    assert "Top1Accuracy" in results


def test_udf_predictor_demo():
    from examples.udf_predictor import main
    preds = main(["--demo"])
    assert isinstance(preds, list) and len(preds) == 8
    assert set(preds).issubset({1, 2})


def test_tree_lstm_sentiment_example():
    from examples.tree_lstm_sentiment import main
    acc = main(["--trees", "120"])
    assert acc > 0.8  # majority-polarity sentiment is learnable


def test_image_classification_example(capsys):
    """example/imageclassification ImagePredictor.scala — load model,
    predict a folder (synthetic stand-in), print name -> class."""
    from examples.image_classification import main
    out = main(["--synthetic", "6", "--classNum", "10", "-b", "4"])
    assert len(out) == 6
    assert all(1 <= p <= 10 for _, p in out)
    assert "synthetic_0.jpg:" in capsys.readouterr().out


def test_image_classification_example_real_images(tmp_path):
    """Folder scan + decode + center-crop path with real (tiny) JPEGs."""
    import numpy as np
    from PIL import Image
    for i in range(3):
        arr = np.random.RandomState(i).randint(
            0, 255, (300, 260, 3), np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.jpg")
    from examples.image_classification import main
    out = main(["-f", str(tmp_path), "--classNum", "10", "-b", "2"])
    assert len(out) == 3


def test_ml_pipeline_example():
    """example/MLPipeline DLClassifierLeNet — estimator-API training."""
    from examples.ml_pipeline import main
    acc = main(["--synthetic", "128", "-e", "6", "-b", "32"])
    assert acc > 0.9


def test_tensorflow_interop_example_demo():
    """example/tensorflow Load.scala path: a graph frozen by REAL TF
    imports and agrees numerically."""
    import pytest
    pytest.importorskip("tensorflow")
    from examples.tensorflow_interop import cmd_demo
    assert cmd_demo() < 1e-4


def test_tensorflow_interop_example_save(tmp_path):
    """Save.scala path: exported GraphDef parses back in real TF."""
    import pytest
    tf = pytest.importorskip("tensorflow")
    from examples.tensorflow_interop import cmd_save
    p = str(tmp_path / "m.pb")
    cmd_save(p)
    gd = tf.compat.v1.GraphDef()
    with open(p, "rb") as f:
        gd.ParseFromString(f.read())
    assert any(n.name == "input" for n in gd.node)


def test_language_model_example_beats_uniform():
    """example/languagemodel PTBWordLM: stacked-LSTM LM with per-epoch
    HELD-OUT validation (a fresh continuation of the stream). Per-token
    perplexity must land far below uniform (50) and near the noise
    floor (~2.0; measured 3.5)."""
    import numpy as np

    from examples.language_model import main
    state = main(["--synthetic", "3000", "-e", "15", "--hiddenSize",
                  "64", "--numSteps", "8", "-b", "8"])
    assert np.exp(state["score"]) < 10.0


def test_wide_and_deep_example_sparse_feed():
    from examples.wide_and_deep import main
    acc = main(["-n", "512", "--wideDim", "100", "-e", "3", "-b", "32"])
    assert acc > 0.8, acc


def test_miswired_model_example():
    """analysis example: the pre-flight diagnostic names the exact layer
    path; the raw error it replaces names no layer at all."""
    from examples.miswired_model import main
    out = main([])
    assert "`sequential[7]/mnist_head`" in out["preflight"]
    assert "dot_general" in out["raw"]
    assert "mnist_head" not in out["raw"]  # the UX gap being closed


def test_online_serving_example(tmp_path):
    """serving example: warm start, batched traffic, int8 hot-swap,
    metrics export — the runnable face of docs/serving.md."""
    from examples.online_serving import main
    metrics = main(["--requests", "24", "--batch-size", "8",
                    "--log-dir", str(tmp_path)])
    assert metrics["request_count"] >= 24
    assert metrics["errors"] == 0 and metrics["timed_out"] == 0
    from bigdl_tpu.visualization import FileReader
    import os
    d = os.path.join(str(tmp_path), "serving_example", "serving")
    vals = FileReader.read_scalar(d, "serving/mnist/request_count")
    assert vals and vals[-1][1] >= 24


def test_telemetry_tour_example(tmp_path):
    """telemetry example: one instrumented train+serve run exported as
    Chrome trace + TensorBoard + Prometheus + JSONL — the runnable face
    of docs/telemetry.md."""
    import json
    from bigdl_tpu import telemetry
    from examples.telemetry_tour import main
    try:
        out = main(["--steps", "3", "--out-dir", str(tmp_path)])
    finally:
        telemetry.disable()
    trace = json.load(open(out["trace"]))
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    assert "optimizer/compute" in names and "serving/batch" in names
    parsed = telemetry.parse_prometheus_text(open(out["prometheus"]).read())
    assert any(k[0] == "serving_batcher_requests" for k in parsed)
    recs = telemetry.read_jsonl(out["jsonl"])
    assert recs and recs[-1]["meta"]["tool"] == "telemetry_tour"
    assert any(r["name"] == "optimizer/compute" for r in out["spans"])
