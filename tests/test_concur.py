"""The static concurrency analyzer (analysis.concur): one positive +
suppressed fixture pair per rule, golden seeded-mutant shapes for the
review-record bug classes (the MicroBatcher unlocked-worker shape, the
PR-14 PrefixCache pin-leak with the doomed verdict read outside the
lock), and targeted regressions for the dogfood fixes (FleetStream
re-route dedup, BatcherStats consistent snapshots, metrics registry
get-or-create vs snapshot)."""
import threading

import numpy as np
import pytest

from bigdl_tpu.analysis.concur import analyze_source, available_concur_rules

HEADER = """\
import queue
import signal
import subprocess
import threading
import time
"""


def run(body, rules=None):
    return analyze_source(HEADER + body, "fixture.py", rules=rules)


def names(findings, active_only=True):
    return [f.rule for f in findings
            if not (active_only and f.suppressed)]


# --------------------------------------------------------- fixture pairs
# one (positive, suppressed) source pair per rule: the positive MUST
# fire, the suppressed twin MUST be muted (and stay recorded)

CASES = {
    "unguarded-shared-state": (
        """
class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = []
        self._thread = threading.Thread(target=self._run, daemon=True)

    def submit(self, job):
        with self._lock:
            self._jobs.append(job)

    def _run(self):
        while True:
            job = self._jobs.pop()
""",
        """
class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = []
        self._thread = threading.Thread(target=self._run, daemon=True)

    def submit(self, job):
        with self._lock:
            self._jobs.append(job)

    def _run(self):
        while True:
            # single-consumer queue: only this thread pops
            # bigdl: disable=unguarded-shared-state
            job = self._jobs.pop()
""",
    ),
    "torn-invariant-write": (
        """
class Cursor:
    def __init__(self):
        self._lock = threading.Lock()
        self._spos = 0
        self._offset = 0
        self._thread = threading.Thread(target=self._advance,
                                        daemon=True)

    def seek(self, spos, offset):
        with self._lock:
            self._spos = spos
            self._offset = offset

    def _advance(self):
        self._spos = self._spos + 1
""",
        """
class Cursor:
    def __init__(self):
        self._lock = threading.Lock()
        self._spos = 0
        self._offset = 0
        self._thread = threading.Thread(target=self._advance,
                                        daemon=True)

    def seek(self, spos, offset):
        with self._lock:
            self._spos = spos
            self._offset = offset

    def _advance(self):
        # offset is reset by the same statement's reader contract
        # bigdl: disable=torn-invariant-write,unguarded-shared-state
        self._spos = self._spos + 1
""",
    ),
    "lock-order-cycle": (
        """
class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def fwd(self):
        with self._a:
            with self._b:
                pass

    def rev(self):
        with self._b:
            with self._a:
                pass
""",
        """
class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def fwd(self):
        with self._a:
            # rev() is only ever called at single-threaded shutdown
            # bigdl: disable=lock-order-cycle
            with self._b:
                pass

    def rev(self):
        with self._b:
            with self._a:
                pass
""",
    ),
    "blocking-under-lock": (
        """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def drain(self):
        with self._lock:
            item = self._q.get()
            return item
""",
        """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def drain(self):
        with self._lock:
            # producer never blocks on this lock; queue is pre-filled
            # bigdl: disable=blocking-under-lock
            item = self._q.get()
            return item
""",
    ),
    "signal-handler-impure": (
        """
class Handler:
    def __init__(self):
        self._lock = threading.Lock()
        self._hits = 0

    def install(self):
        signal.signal(signal.SIGTERM, self._on_signal)

    def _on_signal(self, signum, frame):
        with self._lock:
            self._hits = self._hits + 1
""",
        """
class Handler:
    def __init__(self):
        self._lock = threading.Lock()
        self._hits = 0

    def install(self):
        signal.signal(signal.SIGTERM, self._on_signal)

    def _on_signal(self, signum, frame):
        # uninstalled before any other thread takes this lock
        # bigdl: disable=signal-handler-impure
        with self._lock:
            self._hits = self._hits + 1
""",
    ),
}


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_fires_on_positive(rule):
    positive, _ = CASES[rule]
    assert rule in names(run(positive)), \
        f"{rule} did not fire:\n" + "\n".join(
            f.format() for f in run(positive))


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_suppressed_twin_is_muted(rule):
    _, suppressed = CASES[rule]
    findings = run(suppressed)
    assert rule not in names(findings)
    # the suppressed finding is retained for audit, not dropped
    assert rule in names(findings, active_only=False)


def test_every_rule_has_a_fixture_pair():
    assert sorted(CASES) == [r.name for r in available_concur_rules()]


# ------------------------------------------------------- seeded mutants
# golden shapes from the review record: each mutant reintroduces a bug
# the analyzer must catch; its fixed twin must be silent (zero false
# positives on the pair)

MUTANT_BATCHER = """
class MiniBatcher:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = []
        self._stopping = False
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def submit(self, row):
        with self._cond:
            self._queue.append(row)
            self._cond.notify()

    def shutdown(self):
        with self._cond:
            self._stopping = True
            self._cond.notify_all()

    def _loop(self):
        while not self._stopping:
            batch = list(self._queue)
            self._queue.clear()
"""

FIXED_BATCHER = """
class MiniBatcher:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = []
        self._stopping = False
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def submit(self, row):
        with self._cond:
            self._queue.append(row)
            self._cond.notify()

    def shutdown(self):
        with self._cond:
            self._stopping = True
            self._cond.notify_all()

    def _loop(self):
        while True:
            with self._cond:
                if self._stopping:
                    return
                batch = list(self._queue)
                self._queue.clear()
"""


def test_mutant_batcher_unlocked_worker_caught():
    """The pre-PR-5 shape: the dispatch worker reads/mutates the queue
    and the stop flag outside the condition."""
    findings = [f for f in run(MUTANT_BATCHER)
                if f.rule == "unguarded-shared-state" and not f.suppressed]
    flagged = {m for f in findings
               for m in ("_stopping", "_queue") if m in f.message}
    assert flagged == {"_stopping", "_queue"}, \
        "\n".join(f.format() for f in run(MUTANT_BATCHER))


def test_fixed_batcher_is_clean():
    assert names(run(FIXED_BATCHER)) == []


MUTANT_PREFIX = """
class MiniPrefixCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def insert(self, key, entry):
        with self._lock:
            self._entries[key] = entry

    def drop_version(self, version):
        with self._lock:
            for k in list(self._entries):
                if k[0] == version:
                    del self._entries[k]

    def _dispatch_loop(self):
        while True:
            entry = self._entries.get(("v", 0))
            if entry is not None and not entry.doomed:
                entry.refs += 1
"""

FIXED_PREFIX = """
class MiniPrefixCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def insert(self, key, entry):
        with self._lock:
            self._entries[key] = entry

    def drop_version(self, version):
        with self._lock:
            for k in list(self._entries):
                if k[0] == version:
                    del self._entries[k]

    def _dispatch_loop(self):
        while True:
            with self._lock:
                entry = self._entries.get(("v", 0))
                if entry is not None and not entry.doomed:
                    entry.refs += 1
"""


def test_mutant_prefix_pin_leak_caught():
    """The PR-14 review shape reintroduced: the doomed verdict is read
    outside the lock from a worker-entry method, racing
    ``drop_version``'s doom-and-sweep."""
    findings = run(MUTANT_PREFIX)
    hits = [f for f in findings
            if f.rule == "unguarded-shared-state" and not f.suppressed
            and "_entries" in f.message]
    assert hits, "\n".join(f.format() for f in findings)


def test_fixed_prefix_is_clean():
    assert names(run(FIXED_PREFIX)) == []


# ------------------------------------------------ analyzer edge contracts

def test_cond_wait_on_held_condition_is_exempt():
    """``cond.wait()`` on the condition this region holds RELEASES the
    lock — the idiomatic worker wait loop must not be flagged."""
    src = """
class Loop:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = []
        self._thread = threading.Thread(target=self._run, daemon=True)

    def submit(self, x):
        with self._cond:
            self._queue.append(x)
            self._cond.notify()

    def _run(self):
        with self._cond:
            while not self._queue:
                self._cond.wait(timeout=0.1)
            self._queue.clear()
"""
    assert names(run(src)) == []


def test_locked_suffix_methods_follow_the_convention():
    """``*_locked`` methods run with the caller holding the lock: their
    writes infer guardedness, their accesses are exempt, and blocking
    calls inside them are still flagged."""
    src = """
class Conventional:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _take_locked(self):
        out = list(self._items)
        self._items = []
        time.sleep(0.5)
        return out

    def _run(self):
        with self._lock:
            batch = self._take_locked()
"""
    got = names(run(src))
    assert "unguarded-shared-state" not in got
    assert "blocking-under-lock" in got  # the sleep under the held lock


def test_init_writes_are_happens_before_exempt():
    src = """
class Simple:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "new"
        self._thread = threading.Thread(target=self._run, daemon=True)

    def set_state(self, s):
        with self._lock:
            self._state = s

    def state(self):
        return self._state
"""
    # state() is NOT thread-escaping, __init__ is exempt: clean
    assert names(run(src)) == []


def test_lock_cycle_message_carries_both_witness_paths():
    findings = [f for f in run(CASES["lock-order-cycle"][0])
                if f.rule == "lock-order-cycle"]
    assert len(findings) == 1
    msg = findings[0].message
    assert "Pair._a -> Pair._b" in msg and "Pair._b -> Pair._a" in msg
    assert msg.count("fixture.py:") == 2


def test_flag_only_signal_handler_is_clean():
    """The PR 12 GraceHandler contract: an Event.set()-only handler
    passes."""
    src = """
class Grace:
    def __init__(self):
        self._event = threading.Event()

    def install(self):
        signal.signal(signal.SIGTERM, self._on_signal)

    def _on_signal(self, signum, frame):
        self._event.set()
"""
    assert names(run(src)) == []


# ------------------------------------------- dogfood-fix regressions

def test_fleet_stream_concurrent_delivery_dedups_exactly():
    """The re-route window: the new replica's driver and the
    death-callback's attach-replay deliver the same token indices
    concurrently; every token must land exactly once, in order."""
    from bigdl_tpu.fleet.router import FleetStream
    stream = FleetStream(None, np.array([1, 2, 3], np.int32),
                         {"max_new_tokens": 0}, retries=0,
                         trace_id="test/req-1")
    n = 400
    start = threading.Barrier(4)

    def deliver():
        start.wait()
        for i in range(n):
            stream.on_token(i, 1000 + i)

    threads = [threading.Thread(target=deliver) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert stream.tokens() == [1000 + i for i in range(n)]


def test_fleet_stream_out_of_order_replay_buffers():
    from bigdl_tpu.fleet.router import FleetStream
    stream = FleetStream(None, np.array([1], np.int32),
                         {"max_new_tokens": 0}, retries=0,
                         trace_id="test/req-2")
    stream.on_token(2, 12)  # attach-replay racing ahead
    stream.on_token(0, 10)
    stream.on_token(1, 11)  # fills the gap; pending 2 drains after it
    assert stream.tokens() == [10, 11, 12]


def test_batcher_stats_snapshot_is_consistent_under_writers():
    """Derived ratios must come from ONE locked view: on_batch writes
    four counters under ``stats.lock``; a torn read would break the
    per-batch arithmetic invariants below."""
    from bigdl_tpu.serving.batcher import BatcherStats
    stats = BatcherStats(model="snap-test")
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            stats.on_batch(1, 2)  # 1 real row padded to bucket 2

    threads = [threading.Thread(target=writer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            st = stats.snapshot()
            assert st["batched_rows"] == st["batches"]
            assert st["padded_rows"] == st["batches"]
            assert abs(st["fill_sum"] - 0.5 * st["batches"]) < 1e-6
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)


def test_metrics_registry_get_or_create_vs_snapshot():
    """The audited contract: instrument creation and snapshot share the
    registry lock; concurrent create+inc against snapshot never tears
    a row or raises."""
    from bigdl_tpu.telemetry import MetricsRegistry
    r = MetricsRegistry()
    n_threads, n_each = 4, 50
    start = threading.Barrier(n_threads + 1)

    def creator(tid):
        start.wait()
        for i in range(n_each):
            r.counter(f"load/worker{tid}/c{i}").inc()

    threads = [threading.Thread(target=creator, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    start.wait()
    for _ in range(50):
        for row in r.snapshot():
            for series in row["series"]:
                assert series.get("value", 0) >= 0
    for t in threads:
        t.join()
    final = {row["name"] for row in r.snapshot()}
    assert len(final) == n_threads * n_each
