"""Chaos soak (python -m bigdl_tpu.tools.chaos): the tier-1 smoke runs
the full in-process soak on the tiny workload — transient step faults,
serving dispatch failure, worker-thread death, corrupt-checkpoint
fallback — asserting bit-identical recovery, zero hangs, and exact
fault/recovery reconciliation. The slow half adds the subprocess
SIGKILL legs (mid-training and mid-checkpoint-write)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from bigdl_tpu.tools.chaos import main, run_soak

SMOKE_SCHEDULE = ("train/step=nth:2,raise:RuntimeError;"
                  "serving/dispatch=nth:2,raise:RuntimeError;"
                  "serving/take_batch=nth:3,raise:RuntimeError;"
                  "serving/decode=nth:3,raise:RuntimeError")


def test_chaos_smoke_soak_in_process(tmp_path):
    report = run_soak(model="tiny", steps=8, leg_a=4, ckpt_every=2,
                      batch_size=8, seed=42, schedule=SMOKE_SCHEDULE,
                      workdir=str(tmp_path))
    assert report["passed"], report["violations"]
    assert report["bit_identical"] is True
    assert report["burst"]["hung"] == 0
    assert report["gen_burst"]["hung"] == 0, \
        "a generation token stream never resolved"
    assert report["quarantined"], "corrupt checkpoint never quarantined"
    # counter-for-counter reconciliation across every armed fault kind
    assert report["injected"] == {"train/step": 1,
                                  "serving/dispatch": 1,
                                  "serving/take_batch": 1,
                                  "serving/decode": 1}
    for point, n in report["injected"].items():
        assert report["recovered"][point] == n, (point, report)


def test_chaos_cli_usage_errors():
    assert main(["--leg-a", "20", "--steps", "10"]) == 2
    assert main(["--kill-at", "9", "--leg-a", "4", "--steps", "8"]) == 2


def _worker(args, timeout=300):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.chaos", "--worker",
         "--model", "tiny", "--batch-size", "8", "--seed", "42", *args],
        capture_output=True, text=True, timeout=timeout, env=env)


@pytest.mark.slow
def test_resume_exactness_after_midtraining_sigkill(tmp_path):
    """The satellite contract: a seeded run SIGKILLed mid-training at
    step k (train/step faultpoint), relaunched and resumed from its
    checkpoint, must land bit-identically — final params array-equal
    and final loss float-equal — on an uninterrupted seeded run."""
    ck_kill = tmp_path / "ck_kill"
    ck_ref = tmp_path / "ck_ref"
    p_kill = tmp_path / "killed.npz"
    p_ref = tmp_path / "ref.npz"

    r = _worker(["--steps", "8", "--ckpt-every", "2",
                 "--ckpt-dir", str(ck_kill),
                 "--schedule", "train/step=match:neval=5,sigkill"])
    assert r.returncode == -9, (r.returncode, r.stderr[-500:])
    assert (ck_kill / "checkpoint.4").exists()

    r2 = _worker(["--steps", "8", "--ckpt-every", "2",
                  "--ckpt-dir", str(ck_kill),
                  "--save-params", str(p_kill)])
    assert r2.returncode == 0, (r2.returncode, r2.stderr[-500:])
    res2 = json.loads(r2.stdout.strip().splitlines()[-1])

    r3 = _worker(["--steps", "8", "--ckpt-every", "2",
                  "--ckpt-dir", str(ck_ref),
                  "--save-params", str(p_ref)])
    assert r3.returncode == 0, (r3.returncode, r3.stderr[-500:])
    res3 = json.loads(r3.stdout.strip().splitlines()[-1])

    assert res2["loss"] == res3["loss"]  # exact float, not approx
    with np.load(p_kill) as a, np.load(p_ref) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            assert a[k].dtype == b[k].dtype
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.slow
def test_full_soak_cli_with_sigkill_leg(tmp_path):
    """The acceptance soak: >= 4 distinct fault kinds (mid-checkpoint
    SIGKILL, corrupt npz, transient step failures, serving dispatch
    failure + worker death) through the real CLI; exit 0 == every
    invariant held."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.chaos", "--model",
         "tiny", "--steps", "12", "--leg-a", "6", "--ckpt-every", "2",
         "--kill-at", "4", "--workdir", str(tmp_path), "--json"],
        capture_output=True, text=True, timeout=560, env=env)
    assert r.returncode == 0, (r.returncode, r.stdout[-800:],
                               r.stderr[-500:])
    report = json.loads(r.stdout)
    assert report["passed"] and report["bit_identical"]
    assert report["kill"] == {"injected_sigkills": 1, "resumes": 1}
    assert report["burst"]["hung"] == 0


def test_hostkill_skips_cleanly_without_multiprocess_cpu():
    """The --hostkill leg is capability-probed: a runtime whose CPU
    backend cannot execute cross-process collectives reports a precise
    ``skipped`` reason (exit 0), never a crash."""
    from bigdl_tpu.elastic.capability import multiprocess_cpu
    from bigdl_tpu.tools.chaos import run_hostkill
    ok, reason = multiprocess_cpu()
    if ok:
        pytest.skip("runtime HAS multiprocess CPU collectives; the "
                    "skip path is not reachable here")
    report = run_hostkill(nproc=2, relaunch_nproc=2)
    assert report["passed"] and report["skipped"] == reason


@pytest.mark.slow
def test_hostkill_leg_single_process_gang(tmp_path):
    """The host-kill acceptance leg in its runtime-independent form:
    a tools.launch gang is SIGKILLed WHOLE-HOST mid-window after an
    async elastic checkpoint commits, then relaunched onto a different
    device count — the resumed run must load only COMMITTED state
    (a torn in-flight write is never visible) and land on the
    uninterrupted reference within the documented tolerance, with the
    one injected host kill reconciled against exactly one relaunch."""
    from bigdl_tpu.tools.chaos import run_hostkill
    report = run_hostkill(model="tiny", steps=12, ckpt_every=2,
                          nproc=1, cpu_devices=4, relaunch_nproc=1,
                          relaunch_cpu_devices=2,
                          workdir=str(tmp_path))
    assert report["passed"], report["violations"]
    assert report["injected"] == {"hostkill": 1}
    assert report["recovered"] == {"relaunch": 1}
    assert all(kind == "killed" for _, kind, _ in report["gang_a"]), \
        report["gang_a"]
    assert report["params_max_err"] <= 1e-5


@pytest.mark.slow
def test_hostkill_leg_multiprocess_gang(tmp_path):
    """The full multi-process form: a 2-process gang (the 'host')
    SIGKILLed mid-window, relaunched at world size 1. Runs wherever
    the CPU backend executes cross-process collectives; elsewhere the
    capability probe skips with the auditable reason."""
    from _capability import require_multiprocess_cpu
    require_multiprocess_cpu()
    from bigdl_tpu.tools.chaos import run_hostkill
    report = run_hostkill(model="tiny", steps=12, ckpt_every=2,
                          nproc=2, cpu_devices=2, relaunch_nproc=1,
                          relaunch_cpu_devices=4,
                          workdir=str(tmp_path))
    assert report["passed"], report["violations"]
    assert report["injected"] == {"hostkill": 1}
    assert report["recovered"] == {"relaunch": 1}


@pytest.mark.slow
def test_async_torn_commit_sigkill_invisible_then_resumes(tmp_path):
    """Satellite contract for the elastic writer: SIGKILL injected
    between the last part write and the manifest fsync (the
    ckpt/write_manifest faultpoint, now fired from the BACKGROUND
    writer thread) must leave the staging dir invisible to
    find_latest_checkpoint and quarantinable by verify_checkpoint —
    and the relaunched run resumes from the previous committed
    checkpoint to the uninterrupted run's exact params."""
    from bigdl_tpu.elastic import is_torn_commit
    from bigdl_tpu.utils.serialization import (CheckpointCorrupt,
                                               find_latest_checkpoint,
                                               verify_checkpoint)
    ck = tmp_path / "ck"
    ck_ref = tmp_path / "ck_ref"
    p_res = tmp_path / "resumed.npz"
    p_ref = tmp_path / "ref.npz"

    r = _worker(["--steps", "8", "--ckpt-every", "2", "--async-ckpt",
                 "--ckpt-dir", str(ck),
                 "--schedule", "ckpt/write_manifest=match:neval=4,sigkill"])
    assert r.returncode == -9, (r.returncode, r.stderr[-500:])
    staging = [n for n in os.listdir(ck) if ".staging-" in n]
    assert staging, "torn async commit left no staging dir"
    torn = str(ck / staging[0])
    assert is_torn_commit(torn)
    assert find_latest_checkpoint(str(ck)) == str(ck / "checkpoint.2")
    with pytest.raises(CheckpointCorrupt):
        verify_checkpoint(torn)

    r2 = _worker(["--steps", "8", "--ckpt-every", "2", "--async-ckpt",
                  "--ckpt-dir", str(ck), "--save-params", str(p_res)])
    assert r2.returncode == 0, (r2.returncode, r2.stderr[-500:])
    r3 = _worker(["--steps", "8", "--ckpt-every", "2", "--async-ckpt",
                  "--ckpt-dir", str(ck_ref), "--save-params", str(p_ref)])
    assert r3.returncode == 0, (r3.returncode, r3.stderr[-500:])
    with np.load(p_res) as a, np.load(p_ref) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_chaos_fleet_leg_in_process():
    """The --fleet leg at smoke scale: one replica killed mid-burst by
    the seeded schedule; every stream resolves typed or re-routed,
    surviving greedy outputs stay bit-identical to the pre-chaos
    reference, and injected kills reconcile counter-for-counter with
    the router's evictions."""
    from bigdl_tpu.tools.chaos import run_fleet

    report = run_fleet(replicas=3, requests=12, threads=3, max_new=4,
                       seed=42)
    assert report["passed"], report["violations"]
    assert report["burst"]["hung"] == 0
    assert report["bit_identical"] is True
    assert report["injected"]["fleet/replica"] >= 1
    assert report["recovered"]["evictions"] == \
        report["injected"]["fleet/replica"]
    assert "dead" in report["states"].values()
    # observability plane: the seeded death surfaced as a typed SLO
    # breach over the MERGED fleet snapshot, and the artifacts exist
    assert report["slo_breach_detected"] is True
    assert "evictions" in report["slo"]["breached"]
    assert os.path.exists(report["artifacts"]["trace"])
    assert os.path.exists(report["artifacts"]["slo"])


@pytest.mark.slow
def test_chaos_fleet_cli():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.chaos", "--fleet",
         "--fleet-requests", "12", "--json"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    report = json.loads(r.stdout[r.stdout.index("{"):])
    assert report["passed"] is True
    assert report["recovered"]["evictions"] == \
        report["injected"]["fleet/replica"]
