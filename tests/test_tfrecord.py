"""TFRecord + tf.Example codec (reference: utils/tf/TFRecordIterator.scala,
nn/ops/ParseExample) validated against the reference's own
mnist_train.tfrecord fixture and real TF parsing."""
import os

import numpy as np
import pytest

from bigdl_tpu.utils.tfrecord import (encode_example, example_dataset,
                                      parse_example, read_tfrecord,
                                      write_tfrecord)

FIXTURE = ("/root/reference/spark/dl/src/test/resources/tf/"
           "mnist_train.tfrecord")

needs_fixture = pytest.mark.skipif(not os.path.exists(FIXTURE),
                                   reason="reference fixture absent")


@needs_fixture
def test_reads_reference_mnist_fixture():
    recs = list(read_tfrecord(FIXTURE))
    assert len(recs) == 10
    ex = parse_example(recs[0])
    assert ex["image/format"] == b"png"
    assert int(ex["image/width"][0]) == 28
    assert int(ex["image/height"][0]) == 28
    assert 0 <= int(ex["image/class/label"][0]) <= 10
    # the embedded PNG decodes to a 28x28 grayscale image
    from bigdl_tpu.dataset.imagenet import decode_image
    img = decode_image(ex["image/encoded"])
    assert img.shape[:2] == (28, 28)


@needs_fixture
def test_parse_matches_real_tensorflow():
    tf = pytest.importorskip("tensorflow")
    recs = list(read_tfrecord(FIXTURE))
    for rec in recs[:3]:
        ours = parse_example(rec)
        theirs = tf.train.Example.FromString(rec)
        fmap = theirs.features.feature
        assert set(ours) == set(fmap)
        assert ours["image/encoded"] == fmap["image/encoded"].bytes_list \
            .value[0]
        assert int(ours["image/class/label"][0]) == \
            fmap["image/class/label"].int64_list.value[0]


def test_tfrecord_roundtrip_and_crc(tmp_path):
    p = str(tmp_path / "x.tfrecord")
    recs = [b"hello", b"", b"world" * 100]
    write_tfrecord(p, recs)
    assert list(read_tfrecord(p)) == recs
    # corrupt a payload byte -> crc failure
    data = bytearray(open(p, "rb").read())
    data[-6] ^= 0xFF
    open(p, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="crc"):
        list(read_tfrecord(p))


def test_example_roundtrip():
    feats = {"img": np.arange(6, dtype=np.float32),
             "label": np.asarray([3], np.int64),
             "name": b"abc"}
    back = parse_example(encode_example(feats))
    np.testing.assert_allclose(back["img"], feats["img"])
    assert int(back["label"][0]) == 3
    assert back["name"] == b"abc"


def test_example_roundtrip_vs_tf():
    tf = pytest.importorskip("tensorflow")
    feats = {"x": np.asarray([1.5, -2.0], np.float32),
             "y": np.asarray([7, 8, 9], np.int64)}
    data = encode_example(feats)
    theirs = tf.train.Example.FromString(data)
    np.testing.assert_allclose(
        list(theirs.features.feature["x"].float_list.value), feats["x"])
    assert list(theirs.features.feature["y"].int64_list.value) == [7, 8, 9]


@needs_fixture
def test_example_dataset_trains(tmp_path):
    """End-to-end: the reference fixture -> arrays -> a training step."""
    recs = list(read_tfrecord(FIXTURE))
    from bigdl_tpu.dataset.imagenet import decode_image

    # repack with raw pixels so example_dataset's frombuffer path is used
    out = []
    for rec in recs:
        ex = parse_example(rec)
        img = decode_image(ex["image/encoded"])[:, :, 0]
        out.append(encode_example({
            "image/raw": img.astype(np.uint8).tobytes(),
            "label": np.asarray([int(ex["image/class/label"][0]) + 1],
                                np.int64)}))
    p = str(tmp_path / "mnist.tfrecord")
    write_tfrecord(p, out)
    X, y = example_dataset(p, shape=(1, 28, 28))
    assert X.shape == (10, 1, 28, 28) and y.shape == (10,)
    assert y.min() >= 1

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    ds = DataSet.array([Sample(X[i] / 255.0, y[i]) for i in range(10)]) \
        .transform(SampleToMiniBatch(5))
    model = (nn.Sequential().add(nn.Reshape((784,)))
             .add(nn.Linear(784, 11)).add(nn.LogSoftMax()))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=5)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(max_iteration(10))
    opt.optimize()
    assert np.isfinite(opt.driver_state["Loss"])
