"""Shape/dtype checker: golden layer-path diagnostics, the zero-compile
guarantee, and the Optimizer / ModelRegistry pre-flight wiring."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.analysis import ShapeCheckError, check_module, spec


# ------------------------------------------------- golden-message tests

def test_miswired_sequential_names_exact_layer_path():
    m = (nn.Sequential()
         .add(nn.Linear(16, 32))
         .add(nn.ReLU())
         .add(nn.Linear(64, 10).set_name("head")))
    with pytest.raises(ShapeCheckError) as ei:
        m.check(spec(("b", 16)))
    msg = str(ei.value)
    # the exact offending layer path, not the container or a sibling
    assert "`sequential[2]/head`" in msg
    assert "Linear" in msg
    assert "(32,) and (64,)" in msg  # the underlying dot_general mismatch


def test_ragged_concat_names_branch_and_inner_layer():
    m = nn.Concat(
        2,
        nn.Linear(8, 4),
        nn.Sequential().add(nn.Linear(8, 6)).add(nn.Linear(5, 6)))
    report = check_module(m, spec(("b", 8)))
    assert not report.ok
    [d] = report.errors
    assert d.path == "concat[1]/sequential[1]/linear"
    assert d.layer == "Linear"


def test_dtype_mismatch_float_params_int_input():
    m = nn.Sequential().add(nn.Linear(8, 4).set_name("proj"))
    report = check_module(m, spec(("b", 8), jnp.int32))
    assert not report.ok
    [d] = report.errors
    assert d.path == "sequential[0]/proj"
    assert "dtype mismatch" in d.message
    assert "integer input" in d.message


def test_embedding_accepts_integer_input():
    m = nn.Sequential().add(nn.LookupTable(100, 16)).add(nn.Linear(16, 4))
    report = check_module(m, spec(("b", 7), jnp.int32))
    assert report.ok and report.symbolic


def test_miswired_graph_names_node():
    from bigdl_tpu.nn.graph import Graph, Input
    inp = Input()()
    h = nn.Linear(10, 4).set_name("enc")(inp)
    out = nn.Linear(8, 2).set_name("dec")(h)  # expects 8, gets 4
    g = Graph(inp, out)
    report = check_module(g, spec(("b", 10)))
    assert not report.ok
    [d] = report.errors
    assert d.path == "graph/dec"


def test_good_model_reports_symbolic_output_shape():
    m = nn.Sequential().add(nn.Linear(16, 32)).add(nn.Linear(32, 10))
    report = m.check(spec(("b", 16)))
    assert report.ok and report.symbolic
    assert tuple(str(d) for d in report.output.shape)[-1] == "10"
    assert "b" in str(report.output.shape[0])


def test_multi_input_spec_table():
    m = nn.ParallelTable(nn.Linear(4, 2), nn.Linear(6, 2))
    report = check_module(
        m, [spec(("b", 4)), spec(("b", 6))])
    assert report.ok
    bad = check_module(m, [spec(("b", 4)), spec(("b", 5))])
    assert not bad.ok
    assert bad.errors[0].path == "paralleltable[1]/linear"


def test_two_tuple_of_specs_is_multi_input_not_one_spec():
    """A TUPLE of exactly two spec() results must parse as two inputs
    (regression: the (shape, dtype) pair branch used to swallow it)."""
    m = nn.ParallelTable(nn.Linear(4, 2), nn.Linear(6, 2))
    report = check_module(m, (spec(("b", 4)), spec(("b", 6))))
    assert report.ok
    # and an explicit dtype class (not np.dtype instance) still works
    report = check_module(
        nn.Sequential().add(nn.Linear(4, 2)), (("b", 4), jnp.float32))
    assert report.ok


# --------------------------------------------------- zero-compile guard

def test_check_triggers_no_xla_compilation():
    """Module.check rejects a mis-wired model (and accepts ResNet-50)
    without compiling anything — asserted via a backend_compile counter."""
    from jax._src import compiler
    calls = []
    orig = compiler.backend_compile

    def counting(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    good = nn.Sequential().add(nn.Linear(16, 32)).add(nn.Linear(32, 10))
    bad = nn.Sequential().add(nn.Linear(16, 32)).add(nn.Linear(7, 10))
    from bigdl_tpu.models import ResNet
    rn = ResNet(100, depth=20, dataset="CIFAR10")

    compiler.backend_compile = counting
    try:
        assert good.check(spec(("b", 16))).ok
        assert not check_module(bad, spec(("b", 16))).ok
        assert rn.check(spec(("b", 3, 32, 32)), training=True).ok
    finally:
        compiler.backend_compile = orig
    assert calls == [], f"check compiled {len(calls)} XLA programs"


def test_check_leaves_module_usable():
    """The apply-interception is fully undone: eager forward still works
    and params adopt as usual after a failed check."""
    m = nn.Sequential().add(nn.Linear(4, 3)).add(nn.Linear(9, 2))
    with pytest.raises(ShapeCheckError):
        m.check(spec(("b", 4)))
    assert "apply" not in m.__dict__
    assert all("apply" not in c.__dict__ for c in m.modules)
    ok = nn.Sequential().add(nn.Linear(4, 3))
    ok.check(spec(("b", 4)))
    out = ok.forward(np.ones((2, 4), np.float32))
    assert out.shape == (2, 3)


# ------------------------------------------------------ pre-flight hooks

def test_optimizer_preflight_rejects_before_training():
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim.optimizer import LocalOptimizer

    bad = (nn.Sequential().add(nn.Reshape((16,)))
           .add(nn.Linear(16, 8)).add(nn.Linear(4, 2).set_name("clf")))
    samples = [Sample(np.ones((4, 4), np.float32), np.float32(1.0))
               for _ in range(8)]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(4))
    opt = LocalOptimizer(bad, ds, nn.CrossEntropyCriterion(), batch_size=4)
    opt.set_preflight_spec(spec(("b", 4, 4)))
    with pytest.raises(ShapeCheckError) as ei:
        opt.optimize()
    assert "`sequential[2]/clf`" in str(ei.value)
    # without the spec the check is opt-in: config error surfaces later
    assert bad._params is None  # preflight failed before any init


def test_registry_preflight_rejects_and_stages_nothing():
    from bigdl_tpu.serving import ModelRegistry

    reg = ModelRegistry()
    bad = nn.Sequential().add(nn.Linear(8, 4)).add(nn.Linear(5, 2))
    with pytest.raises(ShapeCheckError):
        reg.load("clf", bad, input_spec=spec(("b", 8)))
    assert reg.names() == []  # nothing staged, nothing resolvable

    good = nn.Sequential().add(nn.Linear(8, 4)).add(nn.Linear(4, 2))
    s = reg.load("clf", good, input_spec=spec(("b", 8)))
    assert reg.current("clf") is s


def test_registry_preflight_checks_live_module_via_detached_clone():
    """A user-passed live module is checked through a topology clone —
    the interception never shadows `apply` on the caller's instances."""
    from unittest.mock import patch

    from bigdl_tpu.analysis import shapecheck
    from bigdl_tpu.serving import ModelRegistry

    good = nn.Sequential().add(nn.Linear(8, 4)).add(nn.Linear(4, 2))
    touched = []
    orig = shapecheck._Interceptor.__init__

    def spying(self, root):
        touched.append(root)
        orig(self, root)

    with patch.object(shapecheck._Interceptor, "__init__", spying):
        ModelRegistry().load("clf", good, input_spec=spec(("b", 8)))
    assert touched and all(t is not good for t in touched)
    # ... while a registry-private quantized rewrite is checked directly
    q_reg = ModelRegistry()
    touched.clear()
    with patch.object(shapecheck._Interceptor, "__init__", spying):
        q_reg.load("q", good, input_spec=spec(("b", 8)), quantize=True)
    assert touched and all(t is not good for t in touched)


def test_bare_shape_tuple_and_struct_specs():
    m = nn.Sequential().add(nn.Linear(8, 2))
    assert check_module(m, (4, 8)).ok  # bare concrete shape, float32
    assert check_module(
        m, jax.ShapeDtypeStruct((4, 8), jnp.float32)).ok
