"""ImageNet-scale input pipeline tests (reference: dataset/DataSet.scala:408
ImageFolder, :470-552 SeqFileFolder, dataset/image/MTLabeledBGRImgToBatch).
"""
import os

import numpy as np
import pytest

from bigdl_tpu.dataset import (
    ImageFolderDataSet, ImageRecordWriter, MiniBatch, decode_image,
    device_prefetch, list_image_folder, read_image_records,
    write_image_record_shards)


def _make_folder(root, classes=("ant", "bee"), per_class=6, size=(40, 48)):
    from PIL import Image
    rng = np.random.RandomState(0)
    for cls in classes:
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 255, (size[0], size[1], 3), np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i:03d}.jpg"))
    return root


def test_list_image_folder_sorted_one_based_labels(tmp_path):
    _make_folder(str(tmp_path), classes=("zebra", "ant"), per_class=2)
    paths, labels, classes = list_image_folder(str(tmp_path))
    assert classes == ["ant", "zebra"]  # sorted (DataSet.scala:425)
    assert labels.min() == 1.0 and labels.max() == 2.0
    assert len(paths) == 4
    # all 'ant' files come first with label 1
    assert all("ant" in p for p in paths[:2])


def test_decode_image_shorter_side_scale(tmp_path):
    _make_folder(str(tmp_path), classes=("a",), per_class=1, size=(40, 80))
    paths, _, _ = list_image_folder(str(tmp_path))
    img = decode_image(paths[0], scale=32)
    assert img.shape[0] == 32 and img.shape[1] == 64  # aspect preserved
    assert img.dtype == np.uint8


def test_image_folder_dataset_train_and_eval(tmp_path):
    _make_folder(str(tmp_path))
    ds = ImageFolderDataSet(str(tmp_path), batch_size=4, crop=24, scale=32,
                            num_threads=2, prefetch=2, seed=3)
    try:
        it = ds.data(train=True)
        for _ in range(3):
            b = next(it)
            assert isinstance(b, MiniBatch)
            assert b.input.shape == (4, 3, 24, 24)
            assert b.input.dtype == np.float32
            assert set(np.asarray(b.target)).issubset({1.0, 2.0})
        # eval: deterministic full sweep, center crop
        evs = list(ds.data(train=False))
        n = sum(len(np.asarray(b.target)) for b in evs)
        assert n == ds.size() == 12
        evs2 = list(ds.data(train=False))
        np.testing.assert_array_equal(evs[0].input, evs2[0].input)
    finally:
        ds.close()


def test_image_folder_dataset_process_sharding(tmp_path):
    _make_folder(str(tmp_path), per_class=4)
    ds0 = ImageFolderDataSet(str(tmp_path), batch_size=2, crop=24, scale=32,
                             num_threads=1, process_index=0, process_count=2)
    ds1 = ImageFolderDataSet(str(tmp_path), batch_size=2, crop=24, scale=32,
                             num_threads=1, process_index=1, process_count=2)
    try:
        assert ds0.size() == ds1.size() == 8      # global size
        assert ds0.local_size() == ds1.local_size() == 4
    finally:
        ds0.close()
        ds1.close()


def test_record_shards_roundtrip(tmp_path):
    folder = tmp_path / "imgs"
    folder.mkdir()
    _make_folder(str(folder), per_class=3)
    shards = write_image_record_shards(str(folder), str(tmp_path / "rec"),
                                       num_shards=2)
    assert len(shards) == 2
    recs = [r for s in shards for r in read_image_records(s)]
    assert len(recs) == 6
    data, label, name = recs[0]
    img = decode_image(data)
    assert img.ndim == 3 and img.shape[2] == 3
    assert label in (1.0, 2.0) and name.endswith(".jpg")
    # dataset can feed straight from shards (SeqFileFolder path)
    ds = ImageFolderDataSet(record_shards=shards, batch_size=3, crop=24,
                            scale=32, num_threads=1)
    try:
        b = next(ds.data(train=True))
        assert b.input.shape == (3, 3, 24, 24)
    finally:
        ds.close()


def test_record_crc_detects_corruption(tmp_path):
    folder = tmp_path / "imgs"
    folder.mkdir()
    _make_folder(str(folder), per_class=1, classes=("a",))
    shards = write_image_record_shards(str(folder), str(tmp_path / "rec"),
                                       num_shards=1)
    data = bytearray(open(shards[0], "rb").read())
    data[-1] ^= 0xFF  # flip a payload byte
    open(shards[0], "wb").write(bytes(data))
    with pytest.raises(ValueError, match="crc"):
        list(read_image_records(shards[0]))


def test_device_prefetch_preserves_order_and_content(tmp_path):
    batches = [MiniBatch(np.full((2, 3), i, np.float32),
                         np.full((2,), i, np.float32)) for i in range(5)]
    out = list(device_prefetch(iter(batches), size=2))
    assert len(out) == 5
    for i, b in enumerate(out):
        np.testing.assert_allclose(np.asarray(b.input), i)
        np.testing.assert_allclose(np.asarray(b.target), i)


def test_device_prefetch_sharded_batch_dim(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices())
    if devs.size < 2:
        pytest.skip("needs >=2 devices")
    mesh = Mesh(devs.reshape(-1), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    n = devs.size
    batches = [MiniBatch(np.ones((2 * n, 3), np.float32),
                         np.ones((2 * n,), np.float32))]
    out = list(device_prefetch(iter(batches), sharding=sharding))
    assert out[0].input.sharding.is_equivalent_to(sharding, ndim=2)


def test_distri_optimizer_trains_from_image_folder(tmp_path):
    """Multi-device DP training fed by the ImageFolder JPEG pipeline —
    the reference's DistriOptimizer-over-SeqFileFolder shape on an
    8-virtual-device mesh."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import DistriOptimizer, SGD, max_iteration
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init()
    assert Engine.device_count() == 8

    # two clearly-separable classes (dark vs bright)
    from PIL import Image
    rng = np.random.RandomState(0)
    for ci, cls in enumerate(["dark", "bright"]):
        d = os.path.join(str(tmp_path), cls)
        os.makedirs(d)
        for i in range(12):
            base = np.full((32, 32, 3), 50 + 150 * ci, np.uint8)
            arr = base + rng.randint(0, 30, base.shape).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.jpg"))

    ds = ImageFolderDataSet(str(tmp_path), batch_size=16, crop=24,
                            scale=28, mean=(128,) * 3, std=(64,) * 3,
                            num_threads=2, prefetch=2, seed=5)
    try:
        model = (nn.Sequential()
                 .add(nn.Reshape((3 * 24 * 24,)))
                 .add(nn.Linear(3 * 24 * 24, 2))
                 .add(nn.LogSoftMax()))
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              batch_size=16)
        opt.set_optim_method(SGD(learning_rate=0.05))
        opt.set_end_when(max_iteration(25))
        opt.optimize()
        assert opt.driver_state["Loss"] < 0.2
    finally:
        ds.close()


def test_augmenter_color_jitter_lighting(tmp_path):
    """ColorJitter.scala / Lighting.scala analogues: train-time flags
    perturb pixels; eval output stays deterministic and untouched."""
    from bigdl_tpu.dataset.imagenet import _Augmenter

    _make_folder(str(tmp_path), classes=("a",), per_class=1)
    paths, _, _ = list_image_folder(str(tmp_path))
    plain = _Augmenter(24, 32, True, (0, 0, 0), (1, 1, 1))
    jit = _Augmenter(24, 32, True, (0, 0, 0), (1, 1, 1),
                     color_jitter=True, lighting=True)
    a = plain(paths[0], np.random.RandomState(7))
    b = jit(paths[0], np.random.RandomState(7))  # same crop/flip draws
    assert a.shape == b.shape == (3, 24, 24)
    assert not np.allclose(a, b)          # photometric noise applied
    assert np.abs(a - b).mean() < 128.0   # ... but bounded
    # eval ignores the flags entirely
    ev = _Augmenter(24, 32, False, (0, 0, 0), (1, 1, 1),
                    color_jitter=True, lighting=True)
    ev_plain = _Augmenter(24, 32, False, (0, 0, 0), (1, 1, 1))
    np.testing.assert_array_equal(ev(paths[0], np.random.RandomState(0)),
                                  ev_plain(paths[0],
                                           np.random.RandomState(1)))


def test_threaded_eval_order_matches_items(tmp_path):
    """Eval decode runs on a thread pool but must keep the sorted file
    order and exact per-epoch coverage (MTLabeledBGRImgToBatch is used
    for val too in the reference)."""
    _make_folder(str(tmp_path), classes=("a", "b", "c"), per_class=5)
    ds = ImageFolderDataSet(str(tmp_path), batch_size=4, crop=24, scale=32,
                            num_threads=4, prefetch=2)
    try:
        batches = list(ds.data(train=False))
        lbls = np.concatenate([np.asarray(b.target) for b in batches])
        # sorted class order -> labels are non-decreasing 1,1,..2,..3
        np.testing.assert_array_equal(lbls, np.sort(lbls))
        assert len(lbls) == 15
        again = list(ds.data(train=False))
        for b1, b2 in zip(batches, again):
            np.testing.assert_array_equal(b1.input, b2.input)
    finally:
        ds.close()


def test_image_folder_dataset_jitter_flags_train(tmp_path):
    _make_folder(str(tmp_path))
    ds = ImageFolderDataSet(str(tmp_path), batch_size=4, crop=24, scale=32,
                            num_threads=2, color_jitter=True, lighting=True)
    try:
        b = next(ds.data(train=True))
        assert b.input.shape == (4, 3, 24, 24)
        assert np.isfinite(np.asarray(b.input)).all()
    finally:
        ds.close()


def test_seqfile_generator_cli(tmp_path):
    """ImageNetSeqFileGenerator.scala analogue: folder -> shards that
    ImageFolderDataSet(record_shards=) reads back."""
    from bigdl_tpu.tools.imagenet_seqfile_generator import main

    _make_folder(str(tmp_path / "imgs"))
    out = tmp_path / "shards"
    shards = main(["-f", str(tmp_path / "imgs"), "-o", str(out), "-p", "3"])
    assert len(shards) == 3
    ds = ImageFolderDataSet(record_shards=shards, batch_size=4, crop=24,
                            scale=32, num_threads=1)
    try:
        assert ds.size() == 12
        b = next(ds.data(train=True))
        assert b.input.shape == (4, 3, 24, 24)
    finally:
        ds.close()
