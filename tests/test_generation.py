"""Generation serving (bigdl_tpu.generation): bucketed KV-cache decode
with continuous batching. Pins the subsystem's load-bearing claims —
greedy decode from the cache is token-bit-identical to full-sequence
re-forward at every step, K length-buckets compile at most 2K programs
(asserted via the compile counter, warmup covers them all), slot
alloc/free never double-assigns, admission under a full cache queues
rather than drops, deadlines and loop deaths fail streams TYPED, and
registry hot-swap under live decode finishes old-version slots on the
old snapshot."""
import time

import numpy as np
import pytest

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.generation import (GenerationConfig, GenerationService,
                                  KVCache, SamplingParams, Sampler,
                                  SlotAllocator, TokenStream)
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.serving import DeadlineExceeded, QueueFull, WorkerDied
from bigdl_tpu.utils.random import RandomGenerator


def _model(vocab=50, hidden=32, layers=2, heads=4, max_len=32, seed=42):
    RandomGenerator.set_seed(seed)
    m = TransformerLM(vocab_size=vocab, hidden_size=hidden,
                      num_layers=layers, num_heads=heads,
                      max_len=max_len).evaluate()
    m.ensure_initialized()
    return m


def _service(model=None, **cfg):
    defaults = dict(slots=4, max_len=16, length_buckets=(16,),
                    prefill_rows=2)
    defaults.update(cfg)
    svc = GenerationService(config=GenerationConfig(**defaults))
    svc.load("lm", model if model is not None else _model())
    return svc


def _greedy_reference(model, prompt, n, pad_to=16):
    """Full-sequence greedy re-forward, one token at a time (padded to
    one fixed length so the reference compiles once; trailing pad
    tokens cannot reach position len-1 under the causal mask)."""
    import jax

    @jax.jit
    def fwd(p, s, t):
        logits, _ = model.apply(p, s, t, training=False)
        return logits

    params, state = model.get_parameters(), model.get_state()
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        padded = np.zeros((1, pad_to), np.int32)
        padded[0, :len(toks)] = toks
        logits = np.asarray(fwd(params, state, padded))
        nxt = int(np.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
    return out


# ---------------------------------------------------------------- slots

def test_slot_allocator_never_double_assigns():
    rng = np.random.RandomState(0)
    alloc = SlotAllocator(5)
    held = set()
    for _ in range(500):
        if held and (rng.rand() < 0.5 or not alloc.free_count):
            s = held.pop()
            alloc.free(s)
        elif alloc.free_count:
            s = alloc.alloc()
            assert s not in held, "slot handed out twice"
            held.add(s)
        assert held == set(alloc.live)
        assert len(held) + alloc.free_count == 5
    with pytest.raises(RuntimeError):
        alloc.free(99)  # freeing a non-live slot is an accounting bug
    for s in sorted(held):
        alloc.free(s)
    for _ in range(5):
        alloc.alloc()
    with pytest.raises(RuntimeError):
        alloc.alloc()  # full cache never over-allocates


def test_kv_cache_geometry_and_occupancy():
    m = _model()
    kv = KVCache.for_model(m, slots=4, max_len=16)
    assert kv.k.shape == (2, 4, 4, 16, 8)  # [L, slots, H, T, D]
    assert kv.v.shape == kv.k.shape
    assert kv.lengths.tolist() == [0, 0, 0, 0]
    assert kv.occupancy() == 0.0
    kv.allocator.alloc()
    assert kv.occupancy() == pytest.approx(0.25)
    with pytest.raises(ValueError):
        KVCache.for_model(m, slots=4, max_len=64)  # > model.max_len


# ------------------------------------------------- decode exactness

def test_greedy_decode_bit_identical_to_full_reforward_every_step():
    """The acceptance invariant: greedy decode from the KV cache
    yields the SAME token as a full-sequence re-forward at every
    single step."""
    model = _model()
    svc = _service(model)
    try:
        prompt = np.array([3, 7, 1, 4, 9], np.int32)
        out = svc.generate("lm", prompt, max_new_tokens=8).result(60)
        assert list(out) == _greedy_reference(model, prompt, 8)
        # a second, differently-shaped prompt through the same programs
        prompt2 = np.array([11, 2], np.int32)
        out2 = svc.generate("lm", prompt2, max_new_tokens=5).result(60)
        assert list(out2) == _greedy_reference(model, prompt2, 5)
    finally:
        svc.shutdown()


def test_prefill_logits_bitwise_and_decode_logits_tight():
    """Engine-level exactness: prefill logits are BITWISE equal to the
    padded full-sequence forward (same program shape), and decode-step
    logits agree to float32 reduction order (the single-query GEMM is
    a different — smaller — program by design)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.generation.engine import DecodeEngine
    from bigdl_tpu.serving.compile_cache import BucketLadder, CompileCache
    from bigdl_tpu.serving.registry import ModelRegistry

    model = _model()
    sv = ModelRegistry().load("m", model)
    eng = DecodeEngine(CompileCache(), BucketLadder(16, (16,)),
                       slots=4, prefill_rows=2)
    kv = KVCache.for_model(model, 4, 16)
    prompt = np.array([3, 7, 1, 4, 9], np.int32)
    logits, _ = eng.prefill(sv, kv, [prompt], [0])

    @jax.jit
    def fwd(p, s, t):
        out, _ = model.apply(p, s, t, training=False)
        return out

    toks = list(prompt)
    for step in range(5):
        padded = np.zeros((1, 16), np.int32)
        padded[0, :len(toks)] = toks
        full = np.asarray(fwd(sv.params, sv.state,
                              jnp.asarray(padded)))[0, len(toks) - 1]
        if step == 0:  # prefill: identical program shape => bitwise
            assert np.array_equal(full, logits[0])
        np.testing.assert_allclose(logits[0], full, atol=1e-5, rtol=0)
        nxt = int(np.argmax(logits[0]))
        assert nxt == int(np.argmax(full))
        toks.append(nxt)
        tokens = np.zeros(4, np.int32)
        tokens[0] = nxt
        positions = np.zeros(4, np.int32)
        positions[0] = kv.lengths[0]
        active = np.zeros(4, bool)
        active[0] = True
        out, _ = eng.decode(sv, kv, tokens, positions, active)
        kv.lengths[0] += 1
        logits = out[:1]
    # anchor against the UNPADDED exact-length re-forward too: the
    # greedy token agrees there as well (one eager forward)
    exact, _ = model.apply(sv.params, sv.state,
                           jnp.asarray([toks]), training=False)
    exact = np.asarray(exact)[0, len(toks) - 1]
    np.testing.assert_allclose(logits[0], exact, atol=1e-5, rtol=0)
    assert int(np.argmax(logits[0])) == int(np.argmax(exact))


# ------------------------------------------------- the compile bound

def test_k_buckets_compile_at_most_2k_under_generation_burst():
    """K length-buckets => at most 2K compiled programs, warmup covers
    every pair, and a ragged burst afterwards compiles NOTHING new —
    asserted via the compile counter, not trusted."""
    buckets = (4, 8, 16)  # K = 3
    svc = _service(length_buckets=buckets, slots=3, prefill_rows=2)
    try:
        warm = svc.compile_count("lm")
        assert warm <= 2 * len(buckets)
        rng = np.random.RandomState(3)
        streams = [svc.generate("lm",
                                rng.randint(1, 50, rng.randint(1, 12)),
                                max_new_tokens=int(rng.randint(1, 6)))
                   for _ in range(12)]
        for s in streams:
            s.result(timeout=60)
        assert svc.compile_count("lm") == warm, \
            "a generation burst after warmup must never compile"
        assert svc.compile_count("lm") <= 2 * len(buckets)
    finally:
        svc.shutdown()


def test_warmup_counts_pairs_and_is_idempotent():
    model = _model()
    svc = _service(model, length_buckets=(8, 16))
    try:
        assert svc.compile_count("lm") == 4  # 2 rungs x (prefill+decode)
        assert svc.warmup("lm") == 0  # everything already compiled
    finally:
        svc.shutdown()


# ------------------------------------- continuous-batching invariants

def test_admission_under_full_cache_queues_rather_than_drops():
    """More requests than slots: every one completes — the full cache
    QUEUES admissions into freed slots, step by step."""
    svc = _service(slots=2, prefill_rows=2, max_queue=64)
    try:
        rng = np.random.RandomState(0)
        streams = [svc.generate("lm", rng.randint(1, 50, 4),
                                max_new_tokens=4) for _ in range(10)]
        outs = [s.result(timeout=60) for s in streams]
        assert all(len(o) == 4 for o in outs)
        m = svc.metrics("lm")
        assert m["request_count"] == 10 and m["finished"] == 10
        assert m["rejected"] == 0
    finally:
        svc.shutdown()


def test_queue_full_rejects_typed_at_the_admission_bound():
    svc = _service(slots=1, prefill_rows=1, max_queue=1)
    try:
        with faults.armed("serving/decode=delay:30,times:1000"):
            a = svc.generate("lm", [1, 2, 3], max_new_tokens=8)
            time.sleep(0.15)  # a occupies the only slot
            b = svc.generate("lm", [4, 5], max_new_tokens=2)
            with pytest.raises(QueueFull):
                svc.generate("lm", [6], max_new_tokens=2)
            assert svc.metrics("lm")["rejected"] == 1
            a.result(timeout=60)
            b.result(timeout=60)
    finally:
        svc.shutdown()


def test_deadline_expired_generation_evicts_with_typed_error():
    """A deadline that passes mid-generation evicts the slot and fails
    the stream with DeadlineExceeded (partial tokens retained); a
    deadline that passes in the queue fails the same way."""
    svc = _service(slots=1, prefill_rows=1, max_queue=8)
    try:
        with faults.armed("serving/decode=delay:40,times:1000"):
            s = svc.generate("lm", [1, 2, 3], max_new_tokens=16,
                             timeout_ms=150)
            q = svc.generate("lm", [4, 5], max_new_tokens=16,
                             timeout_ms=60)  # expires while queued
            with pytest.raises(DeadlineExceeded):
                s.result(timeout=60)
            assert 1 <= len(s.tokens()) < 16  # partial progress kept
            with pytest.raises(DeadlineExceeded):
                q.result(timeout=60)
        assert svc.metrics("lm")["timed_out"] == 2
        # the expired slots were freed: the loop keeps serving
        assert len(svc.generate("lm", [7, 8],
                                max_new_tokens=3).result(60)) == 3
    finally:
        svc.shutdown()


def test_hot_swap_under_live_decode_finishes_old_version_slots():
    """Swap while slots decode: the in-flight generation finishes on
    the snapshot it prefilled with (v1 greedy reference), the next
    admission decodes the new version (v2 reference)."""
    m1 = _model(seed=42)
    m2 = _model(seed=7)
    svc = _service(m1, slots=2, prefill_rows=1)
    try:
        prompt = np.array([3, 7, 1], np.int32)
        with faults.armed("serving/decode=delay:25,times:1000"):
            live = svc.generate("lm", prompt, max_new_tokens=8)
            live.first(timeout=30)  # admitted: it occupies a v1 slot
            svc.load("lm", m2)      # hot-swap under live decode
            after = svc.generate("lm", prompt, max_new_tokens=8)
            v1_out = live.result(timeout=60)
            v2_out = after.result(timeout=60)
        assert list(v1_out) == _greedy_reference(m1, prompt, 8)
        assert list(v2_out) == _greedy_reference(m2, prompt, 8)
        # the drained v1 group released its cache: no live slots remain
        assert svc.metrics("lm")["live_slots"] == 0
    finally:
        svc.shutdown()


def test_decode_fault_fails_streams_typed_and_loop_restarts():
    """PR-5 supervision semantics on the decode loop: an injected
    serving/decode fault fails every in-flight stream with a typed
    WorkerDied (never a hang), and the restarted loop keeps serving."""
    svc = _service(slots=2, prefill_rows=2)
    try:
        with faults.armed("serving/decode=nth:2,raise:RuntimeError"):
            a = svc.generate("lm", [1, 2, 3], max_new_tokens=8)
            b = svc.generate("lm", [4, 5], max_new_tokens=8)
            for s in (a, b):
                with pytest.raises(WorkerDied):
                    s.result(timeout=60)
        m = svc.metrics("lm")
        assert m["worker_restarts"] == 1
        # restarted: the same name serves again, correctly
        out = svc.generate("lm", [1, 2, 3], max_new_tokens=4).result(60)
        assert len(out) == 4
    finally:
        svc.shutdown()


# ------------------------------------------------- sampling + streams

def test_seeded_sampling_deterministic_and_topk1_is_greedy():
    svc = _service()
    try:
        prompt = [3, 7, 1]
        greedy = svc.generate("lm", prompt, max_new_tokens=6).result(60)
        t1 = svc.generate("lm", prompt, max_new_tokens=6,
                          temperature=0.7, top_k=1, seed=9).result(60)
        assert np.array_equal(t1, greedy), \
            "top_k=1 sampling must reduce to greedy"
        a = svc.generate("lm", prompt, max_new_tokens=6,
                         temperature=0.9, top_k=5, seed=11).result(60)
        b = svc.generate("lm", prompt, max_new_tokens=6,
                         temperature=0.9, top_k=5, seed=11).result(60)
        assert np.array_equal(a, b), "same seed => same stream"
    finally:
        svc.shutdown()


def test_sampler_validation_and_distribution_support():
    with pytest.raises(ValueError):
        SamplingParams(top_k=0).validate()
    s = Sampler(SamplingParams(temperature=1.0, top_k=2, seed=3))
    logits = np.array([0.0, 5.0, 4.0, -1.0], np.float32)
    draws = {s.sample(logits) for _ in range(64)}
    assert draws <= {1, 2}, "top-k must restrict the support"


def test_eos_token_evicts_the_slot():
    model = _model()
    probe = _service(model)
    try:
        first = int(probe.generate("lm", [3, 7, 1],
                                   max_new_tokens=1).result(60)[0])
    finally:
        probe.shutdown()
    svc = _service(model, eos_token=first)
    try:
        s = svc.generate("lm", [3, 7, 1], max_new_tokens=8)
        out = s.result(timeout=60)
        assert s.finish_reason == "eos"
        assert list(out) == [first]  # the EOS token is included
        assert svc.metrics("lm")["live_slots"] == 0
    finally:
        svc.shutdown()


def test_token_stream_iteration_futures_and_ttft():
    svc = _service()
    try:
        s = svc.generate("lm", [2, 4], max_new_tokens=4)
        f1 = s.token_future(1)
        f9 = s.token_future(9)  # beyond the generation
        toks = list(s)
        assert toks == list(s.result(60))
        assert len(toks) == 4
        assert s.first() == toks[0]
        assert f1.result(timeout=10) == toks[1]
        assert f9.result(timeout=10) is None  # finished earlier: None
        assert s.ttft_ms is not None and s.ttft_ms >= 0.0
        assert s.finish_reason == "max_tokens"
    finally:
        svc.shutdown()


def test_prompt_validation_and_max_new_cap():
    svc = _service()  # max_len = 16
    try:
        with pytest.raises(ValueError):
            svc.generate("lm", [])
        with pytest.raises(ValueError):
            svc.generate("lm", list(range(1, 17)))  # no room to decode
        s = svc.generate("lm", list(range(1, 13)),
                         max_new_tokens=100)  # capped to 16 - 12
        assert len(s.result(timeout=60)) == 4
    finally:
        svc.shutdown()


# ----------------------------------------------- telemetry + lifecycle

def test_generation_telemetry_spans_and_gauges():
    telemetry.tracer().clear()
    telemetry.enable()
    try:
        svc = _service()
        svc.generate("lm", [1, 2, 3], max_new_tokens=3).result(60)
        svc.shutdown()
        names = {rec.name for rec in telemetry.tracer().spans()}
        assert "serving/prefill" in names and "serving/decode" in names
        m = svc.metrics("lm")
        assert m["tokens"] == 3
        assert 0.0 < m["padding_efficiency"] <= 1.0
        assert "ttft_ms_p50" in m and "token_ms_p99" in m
        assert telemetry.audit_names(svc.metrics_registry) == []
    finally:
        telemetry.disable()
        telemetry.tracer().clear()


def test_unload_releases_generation_programs():
    svc = _service()
    try:
        assert svc.cache.compile_count() > 0
        svc.generate("lm", [1, 2], max_new_tokens=2).result(60)
        svc.unload("lm")
        assert svc.cache.compile_count() == 0, \
            "unload must release every compiled generation program"
        with pytest.raises(KeyError):
            svc.generate("lm", [1, 2])
    finally:
        svc.shutdown()


def test_prefill_failure_fails_admitted_streams_typed_not_hang():
    """Regression: a prefill that raises AFTER its requests were
    popped from the queue (admitted, slots allocated) must fail those
    streams typed — never strand them pending forever."""
    svc = _service()
    try:
        real_prefill = svc.engine.prefill
        boom = {"armed": True}

        def failing_prefill(*a, **kw):
            if boom.pop("armed", False):
                raise RuntimeError("injected prefill failure")
            return real_prefill(*a, **kw)

        svc.engine.prefill = failing_prefill
        s = svc.generate("lm", [1, 2, 3], max_new_tokens=3)
        with pytest.raises(WorkerDied):
            s.result(timeout=30)
        # the restarted loop serves the next request normally
        assert len(svc.generate("lm", [1, 2, 3],
                                max_new_tokens=3).result(60)) == 3
    finally:
        svc.shutdown()


def test_load_warmup_cache_is_adopted_by_the_serving_group():
    """The load-time warmup buffers ARE the serving cache — one
    full-size K/V allocation per version, not warmup + serving
    copies."""
    svc = _service()
    try:
        sv2 = svc.load("lm", _model(seed=9))  # v2, warmed + activated
        assert sv2.key in svc._warm_caches
        warmed = svc._warm_caches[sv2.key]
        svc.generate("lm", [1, 2], max_new_tokens=2).result(60)
        assert sv2.key not in svc._warm_caches  # handed to the loop
        assert warmed.allocator.free_count == warmed.slots  # and usable
    finally:
        svc.shutdown()


def test_shutdown_without_drain_fails_streams_typed():
    svc = _service(slots=1, prefill_rows=1)
    try:
        with faults.armed("serving/decode=delay:30,times:1000"):
            s = svc.generate("lm", [1, 2, 3], max_new_tokens=16)
            s.first(timeout=30)
            q = svc.generate("lm", [4, 5], max_new_tokens=4)
            svc.shutdown(drain=False)
            for stream in (s, q):
                with pytest.raises(RuntimeError):
                    stream.result(timeout=30)
    finally:
        svc.shutdown()


def test_shared_registry_with_inference_service():
    """GenerationService(svc) rides an InferenceService's registry:
    one load, scored AND generated."""
    from bigdl_tpu.serving import InferenceService, ServingConfig

    model = _model()
    inf = InferenceService(config=ServingConfig(max_batch_size=4))
    inf.registry.load("lm", model)
    gen = GenerationService(inf, config=GenerationConfig(
        slots=2, max_len=16, length_buckets=(16,), prefill_rows=1))
    try:
        out = gen.generate("lm", [3, 7, 1], max_new_tokens=3).result(60)
        assert list(out) == _greedy_reference(model, [3, 7, 1], 3)
        assert gen.registry is inf.registry
        assert gen.metrics_registry is inf.metrics_registry
    finally:
        gen.shutdown()
        inf.shutdown()


def test_full_sequence_path_unchanged_by_cache_support():
    """The no-cache forward is byte-identical before/after this PR's
    signature change: cache kwargs default to the legacy path."""
    import jax.numpy as jnp
    model = _model()
    params, state = model.get_parameters(), model.get_state()
    toks = jnp.asarray([[3, 7, 1, 4]])
    a, _ = model.apply(params, state, toks, training=False)
    b, _ = model.apply(params, state, toks, training=False,
                       cache=None, positions=None)
    assert np.array_equal(np.asarray(a), np.asarray(b))


def test_online_generation_example():
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from examples.online_generation import main
    metrics = main(["--requests", "5", "--max-new", "6", "--slots", "2",
                    "--max-len", "32", "--buckets", "16,32"])
    assert metrics["finished"] >= 7  # burst + sampled + swap checks
    assert metrics["compile_count"] <= 2 * 2 * 2  # 2K per version
