"""The compiled-program self-gate: `python -m bigdl_tpu.tools.check
--programs` lowers the package's representative program suite (train/
eval steps, the K=8 window, the ZeRO-2 mesh step, the bf16-policy step,
the generation prefill/decode pair) and every static HLO check passes —
tier-1 keeps the package's own programs clean forever, the way
test_lint_self.py keeps the source clean."""
import json
import os
import subprocess
import sys

import pytest

import bigdl_tpu

PKG_DIR = os.path.dirname(os.path.abspath(bigdl_tpu.__file__))
REPO = os.path.dirname(PKG_DIR)


@pytest.fixture(scope="module")
def suite():
    """ONE enumeration + check run shared by the in-process tests (the
    CLI test pays its own in a subprocess, as users do)."""
    from bigdl_tpu.analysis.programs import verify_programs
    return verify_programs()


def test_verify_programs_self_gate(suite):
    """In-process acceptance: the whole enumerated suite is clean, and
    the suite actually covers the contract surface (window, ZeRO mesh
    step, bf16 policy leg, serving prefill/decode pair)."""
    findings, specs, notes = suite
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(f.format() for f in active)
    names = {s.name for s in specs}
    assert "train/mlp/window@k8" in names
    assert "train/transformer_lm/step@bf16" in names
    assert "serving/transformer_lm/prefill/16" in names
    assert "serving/transformer_lm/decode/16" in names
    # the fleet speculative-verify rung rides the same enumeration
    # hook: donation + HBM checks cover it like prefill/decode
    assert "serving/transformer_lm/verify/16" in names
    # conftest forces 8 virtual devices, so the mesh leg must be there
    assert "train/mlp/zero2/step" in names, notes
    # the seq-parallel window leg additionally needs jax.shard_map —
    # on builds without it the skip is announced, never silent
    from bigdl_tpu.elastic.capability import shard_map_available
    if shard_map_available():
        assert "train/transformer_lm/seq_parallel/window@k2" in names, \
            notes
        assert notes == []
    else:
        assert [n for n in notes
                if "seq-parallel window leg skipped" not in n] == []
    # every donated program's contract was non-trivial
    donated = [s for s in specs if s.donated > 0]
    assert len(donated) >= 6
    window = next(s for s in specs if s.name == "train/mlp/window@k8")
    assert window.companion is not None and window.scan_length == 8


def test_check_cli_programs_json_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "--programs",
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)["programs"]
    assert payload["findings"] == []
    assert "train/lenet5/step" in payload["programs"]


def test_check_cli_unknown_rule_exits_two():
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "--programs",
         "--rules", "no-such-check"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 2
    assert "no-such-check" in proc.stderr


def test_check_cli_list_rules_is_unified():
    """--list-rules is ONE catalogue: AST lint rules and HLO program
    checks share the --rules namespace."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0
    for name in ("donation-dropped", "entry-collective",
                 "precision-leak", "hbm-over-budget",
                 "scan-dispatch-ratio", "replicated-large-operand",
                 "use-after-donate", "host-sync"):
        assert name in proc.stdout, name
    assert "[hlo]" in proc.stdout and "[lint]" in proc.stdout
    for name in ("unguarded-shared-state", "torn-invariant-write",
                 "lock-order-cycle", "blocking-under-lock",
                 "signal-handler-impure"):
        assert name in proc.stdout, name
    assert "[concur]" in proc.stdout


def test_concur_self_gate_in_process():
    """The package self-analyzes clean under the concurrency analyzer:
    every thread-escaping access of a lock-guarded attribute is locked,
    the package-wide lock-order graph is acyclic, no held-lock region
    blocks, and the preempt signal handler stays flag-only."""
    from bigdl_tpu.analysis.concur import analyze_paths
    findings = analyze_paths([PKG_DIR])
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(f.format() for f in active)


def test_check_cli_concurrency_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "--concurrency",
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)["concur"]
    assert [f for f in payload if not f.get("suppressed")] == []


def test_check_cli_concur_rule_subset():
    """--rules with a concur rule name routes to the concurrency pass
    alone (no lint/shape/program passes run)."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.check", "--concurrency",
         "--rules", "lock-order-cycle", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["concur"] == []


def test_rule_subset_restricts_checks(suite):
    """A --rules-style subset runs only the named check over the
    suite (and still comes back clean on the package's programs)."""
    from bigdl_tpu.analysis.hlo import run_checks
    _, specs, _ = suite
    findings = run_checks(specs, checks=["donation-dropped"])
    assert [f for f in findings if not f.suppressed] == []
    assert len(specs) >= 8
