"""TF export tests: our model -> frozen GraphDef -> executed by REAL
TensorFlow, outputs compared (reference model: TensorflowSaverSpec)."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.tf_saver import save_tf_graph


def _run_tf(pb_path, names, x):
    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(open(pb_path, "rb").read())
    with tf.Graph().as_default() as graph:
        tf.import_graph_def(gd, name="")
        with tf.compat.v1.Session(graph=graph) as sess:
            return sess.run(
                graph.get_tensor_by_name(names["output"] + ":0"),
                {graph.get_tensor_by_name(names["input"] + ":0"): x})


def test_export_mlp(tmp_path):
    model = (nn.Sequential().add(nn.Linear(6, 12)).add(nn.ReLU())
             .add(nn.Linear(12, 3)).add(nn.SoftMax())).evaluate()
    x = np.random.randn(4, 6).astype(np.float32)
    ours = np.asarray(model.forward(x))
    p = str(tmp_path / "mlp.pb")
    names = save_tf_graph(p, model)
    theirs = _run_tf(p, names, x)
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_export_convnet(tmp_path):
    model = (nn.Sequential()
             .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(2, 2, 2, 2))
             .add(nn.Reshape((8 * 4 * 4,)))
             .add(nn.Linear(8 * 4 * 4, 5))
             .add(nn.LogSoftMax())).evaluate()
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    ours = np.asarray(model.forward(x))
    p = str(tmp_path / "conv.pb")
    names = save_tf_graph(p, model)
    theirs = _run_tf(p, names, x)
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


def test_export_import_roundtrip(tmp_path):
    """Export with tf_saver then re-import with tf_loader — full circle."""
    from bigdl_tpu.utils.tf_loader import load_tf_graph
    model = (nn.Sequential().add(nn.Linear(5, 7)).add(nn.Tanh())
             .add(nn.Linear(7, 2))).evaluate()
    x = np.random.randn(3, 5).astype(np.float32)
    ours = np.asarray(model.forward(x))
    p = str(tmp_path / "rt.pb")
    names = save_tf_graph(p, model)
    back = load_tf_graph(p, inputs=[names["input"]],
                         outputs=[names["output"]]).evaluate()
    np.testing.assert_allclose(ours, np.asarray(back.forward(x)), atol=1e-5)


def test_export_unsupported_raises(tmp_path):
    model = nn.Sequential().add(nn.LookupTable(10, 4))
    with pytest.raises(ValueError, match="unsupported module"):
        save_tf_graph(str(tmp_path / "x.pb"), model)


def test_export_grouped_conv_raises(tmp_path):
    model = nn.Sequential().add(nn.SpatialConvolution(4, 4, 3, 3, n_group=2))
    with pytest.raises(ValueError, match="grouped convolution"):
        save_tf_graph(str(tmp_path / "g.pb"), model)


def test_export_ceil_pool_raises(tmp_path):
    model = nn.Sequential().add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    with pytest.raises(ValueError, match="ceil-mode"):
        save_tf_graph(str(tmp_path / "c.pb"), model)


def test_export_padded_maxpool_negative_values(tmp_path):
    """MaxPool padding must not clamp negative activations to 0."""
    model = (nn.Sequential().add(nn.SpatialMaxPooling(2, 2, 2, 2, 1, 1))
             .evaluate())
    x = -np.abs(np.random.randn(1, 2, 4, 4)).astype(np.float32) - 1.0
    ours = np.asarray(model.forward(x))
    p = str(tmp_path / "mp.pb")
    names = save_tf_graph(p, model)
    theirs = _run_tf(p, names, x)
    np.testing.assert_allclose(ours, theirs, atol=1e-6)
