"""Int8 quantized inference tests (reference test model: nn/quantized specs
+ bigquant correctness — quantized output close to float, rewrite preserves
untouched layers)."""
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.ops.quant import (int8_matmul, quantize_symmetric,
                                 quantized_linear)


def test_quantize_symmetric_roundtrip():
    w = np.random.randn(8, 32).astype(np.float32)
    q, scale = quantize_symmetric(w, axis=0)
    assert q.dtype == jnp.int8
    deq = np.asarray(q, np.float32) * np.asarray(scale)
    assert np.abs(deq - w).max() <= np.abs(w).max() / 127.0 + 1e-6


def test_int8_matmul_exact():
    a = np.random.randint(-127, 128, (4, 16), dtype=np.int8)
    b = np.random.randint(-127, 128, (8, 16), dtype=np.int8)
    out = np.asarray(int8_matmul(jnp.asarray(a), jnp.asarray(b)))
    ref = a.astype(np.int64) @ b.astype(np.int64).T
    np.testing.assert_array_equal(out, ref.astype(np.int32))


def test_quantized_linear_close_to_float():
    x = np.random.randn(16, 64).astype(np.float32)
    w = np.random.randn(32, 64).astype(np.float32) * 0.2
    b = np.random.randn(32).astype(np.float32)
    q, scale = quantize_symmetric(w, axis=0)
    out = np.asarray(quantized_linear(jnp.asarray(x), q, scale.reshape(-1),
                                      jnp.asarray(b)))
    ref = x @ w.T + b
    # int8 quantization error bound: ~1-2% relative
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.05


def test_quantize_model_sequential():
    model = (nn.Sequential()
             .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.ReLU())
             .add(nn.Reshape((8 * 8 * 8,)))
             .add(nn.Linear(8 * 8 * 8, 10))
             .add(nn.LogSoftMax()))
    x = np.random.randn(4, 3, 8, 8).astype(np.float32)
    model.evaluate()
    ref = np.asarray(model.forward(x))
    qmodel = model.quantize()
    assert isinstance(qmodel[0], nn.QuantizedSpatialConvolution)
    assert isinstance(qmodel[3], nn.QuantizedLinear)
    out = np.asarray(qmodel.forward(x))
    assert out.shape == ref.shape
    # top-1 predictions agree on almost all samples
    assert (out.argmax(1) == ref.argmax(1)).mean() >= 0.75
    # original model untouched: still float, same outputs
    ref2 = np.asarray(model.forward(x))
    np.testing.assert_allclose(ref, ref2, atol=1e-6)


def test_quantize_graph_model():
    from bigdl_tpu.models.lenet import LeNet5_graph
    model = LeNet5_graph(10).evaluate()
    x = np.random.randn(2, 1, 28, 28).astype(np.float32)
    ref = np.asarray(model.forward(x))
    qmodel = model.quantize()
    out = np.asarray(qmodel.forward(x))
    assert out.shape == ref.shape
    assert np.abs(out - ref).max() < 0.5  # logsoftmax outputs, loose bound
    # original graph still float
    assert np.allclose(np.asarray(model.forward(x)), ref, atol=1e-6)


def test_quantized_preserves_batchnorm_stats():
    model = (nn.Sequential()
             .add(nn.Linear(8, 8))
             .add(nn.BatchNormalization(8))
             .add(nn.Linear(8, 4)))
    model.training()
    for _ in range(3):
        model.forward(np.random.randn(16, 8).astype(np.float32) * 3 + 1)
    model.evaluate()
    x = np.random.randn(4, 8).astype(np.float32)
    ref = np.asarray(model.forward(x))
    q = model.quantize()
    out = np.asarray(q.forward(x))
    # BN running stats carried over -> outputs stay close
    assert np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9) < 0.1


def test_quantized_model_serializes(tmp_path):
    from bigdl_tpu.utils.serialization import load_module, save_module
    model = nn.Sequential().add(nn.Linear(16, 8)).evaluate()
    x = np.random.randn(2, 16).astype(np.float32)
    q = model.quantize()
    ref = np.asarray(q.forward(x))
    save_module(str(tmp_path / "q"), q)
    loaded = load_module(str(tmp_path / "q")).evaluate()
    np.testing.assert_allclose(ref, np.asarray(loaded.forward(x)), atol=1e-5)
