"""Sparse layer tests (reference model: SparseLinearSpec/SparseJoinTableSpec
— sparse forward equals dense forward on the same data)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

import bigdl_tpu.nn as nn


def _sparse_input(b=4, n=32, density=0.1, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(b, n).astype(np.float32)
    x[rng.rand(b, n) > density] = 0.0
    return x


def test_sparse_linear_matches_dense():
    x = _sparse_input()
    m = nn.SparseLinear(32, 8)
    dense_out = np.asarray(m.forward(x))
    sp = jsparse.BCOO.fromdense(jnp.asarray(x))
    sparse_out = np.asarray(m.forward(sp))
    np.testing.assert_allclose(sparse_out, dense_out, atol=1e-5)


def test_sparse_linear_grad():
    x = jsparse.BCOO.fromdense(jnp.asarray(_sparse_input()))
    m = nn.SparseLinear(32, 8)
    m.ensure_initialized()
    p = m.get_parameters()

    def loss(p):
        return m.forward_fn(p, x).sum()

    g = jax.grad(loss)(p)
    assert np.isfinite(np.asarray(g["weight"])).all()
    assert g["weight"].shape == (8, 32)


def test_dense_to_sparse_and_join():
    a = _sparse_input(2, 8, seed=1)
    b = _sparse_input(2, 6, seed=2)
    d2s = nn.DenseToSparse()
    sa = d2s.forward(a)
    assert isinstance(sa, jsparse.BCOO)
    join = nn.SparseJoinTable(2)
    out = join.forward([a, b])
    ref = np.concatenate([a, b], axis=1)
    np.testing.assert_allclose(np.asarray(out.todense()), ref, atol=1e-6)


def test_wide_and_deep_style_model():
    """Sparse wide path + dense deep path joined (the reference's use case
    for sparse tensors)."""
    xs_wide = jsparse.BCOO.fromdense(jnp.asarray(_sparse_input(4, 100, 0.05)))
    xs_deep = np.random.randn(4, 10).astype(np.float32)
    wide = nn.SparseLinear(100, 4)
    deep = nn.Sequential().add(nn.Linear(10, 16)).add(nn.ReLU()).add(
        nn.Linear(16, 4))
    w_out = np.asarray(wide.forward(xs_wide))
    d_out = np.asarray(deep.forward(xs_deep))
    logits = w_out + d_out
    assert logits.shape == (4, 4)
    assert np.isfinite(logits).all()


def test_sparse_sample_to_minibatch_batches_coo():
    """SampleToMiniBatch on SparseFeature samples produces the static-
    shape SparseMiniBatch analogue (MiniBatch.scala:587): nnz padded to
    the batch max with zero values, dense view == stacked dense."""
    from bigdl_tpu.dataset import (DataSet, HostBatchedCOO, Sample,
                                   SampleToMiniBatch, SparseFeature)

    rng = np.random.RandomState(0)
    dense = rng.rand(6, 12) * (rng.rand(6, 12) < 0.3)
    samples = [Sample(SparseFeature.from_dense(dense[i]), float(i % 2 + 1))
               for i in range(6)]
    mbs = list(DataSet.array(samples)
               .transform(SampleToMiniBatch(3)).data(train=False))
    assert len(mbs) == 2
    for j, mb in enumerate(mbs):
        wide = mb.get_input()
        assert isinstance(wide, HostBatchedCOO)
        assert wide.values.shape == wide.indices.shape[:2]
        np.testing.assert_allclose(wide.to_dense(),
                                   dense[3 * j:3 * j + 3], atol=1e-6)
        assert mb.size() == 3


def test_sparse_feed_trains_through_optimizer():
    """The last §2 gap closed: a dataset of sparse Samples feeds the
    Optimizer end to end and SparseLinear learns (dataset path
    Transformer.scala:309 -> MiniBatch.scala:587 -> SparseLinear)."""
    from bigdl_tpu.dataset import (DataSet, Sample, SampleToMiniBatch,
                                   SparseFeature)
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    rng = np.random.RandomState(3)
    dim = 64
    samples = []
    for _ in range(128):
        hot = rng.choice(dim, size=3, replace=False)
        label = 1.0 if (hot < dim // 2).sum() >= 2 else 2.0
        samples.append(Sample(
            SparseFeature(hot[:, None], np.ones(3, np.float32), (dim,)),
            label))
    ds = DataSet.array(samples).transform(SampleToMiniBatch(32))
    model = nn.Sequential().add(nn.SparseLinear(dim, 2)) \
        .add(nn.LogSoftMax())
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=32)
    opt.set_optim_method(SGD(learning_rate=1.0))
    opt.set_end_when(max_iteration(48))
    opt.optimize()
    # init loss is ln(2)=0.693; well below it proves the sparse feed
    # carries gradient (margin-loss tail converges slowly by nature)
    assert opt.driver_state["Loss"] < 0.3, opt.driver_state["Loss"]


def test_sparse_feed_matches_dense_feed():
    """Sparse COO feed computes the SAME training losses as the dense
    feed on identical data + init (zero-padding must be a no-op)."""
    from bigdl_tpu.dataset import (DataSet, Sample, SampleToMiniBatch,
                                   SparseFeature)
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration
    from bigdl_tpu.utils.random import RandomGenerator

    rng = np.random.RandomState(5)
    dense = (rng.rand(64, 20) * (rng.rand(64, 20) < 0.2)) \
        .astype(np.float32)
    lbls = rng.randint(1, 3, 64).astype(np.float32)

    losses = {}
    for kind in ("sparse", "dense"):
        if kind == "sparse":
            ss = [Sample(SparseFeature.from_dense(dense[i]), lbls[i])
                  for i in range(64)]
        else:
            ss = [Sample(dense[i], lbls[i]) for i in range(64)]
        ds = DataSet.array(ss).transform(SampleToMiniBatch(16))
        RandomGenerator.set_seed(7)
        model = nn.Sequential().add(nn.SparseLinear(20, 2)) \
            .add(nn.LogSoftMax())
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                             batch_size=16)
        opt.set_optim_method(SGD(learning_rate=0.5))
        opt.set_end_when(max_iteration(6))
        opt.optimize()
        losses[kind] = opt.driver_state["Loss"]
    np.testing.assert_allclose(losses["sparse"], losses["dense"],
                               atol=1e-5)


def test_sparse_feed_on_mesh():
    """Sparse batches shard their leaves over the data axis like any
    dense input (DistriOptimizer + SparseMiniBatch)."""
    import jax

    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")
    from jax.sharding import Mesh

    from bigdl_tpu.dataset import (DataSet, Sample, SampleToMiniBatch,
                                   SparseFeature)
    from bigdl_tpu.optim import SGD, max_iteration
    from bigdl_tpu.optim.optimizer import Optimizer

    rng = np.random.RandomState(9)
    samples = []
    for _ in range(64):
        hot = rng.choice(32, size=2, replace=False)
        samples.append(Sample(
            SparseFeature(hot[:, None], np.ones(2, np.float32), (32,)),
            float(hot[0] % 2 + 1)))
    ds = DataSet.array(samples).transform(SampleToMiniBatch(16))
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    model = nn.Sequential().add(nn.SparseLinear(32, 2)) \
        .add(nn.LogSoftMax())
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=16,
                    mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(max_iteration(4))
    opt.optimize()
    assert np.isfinite(opt.driver_state["Loss"])


def test_sparse_minibatch_slice_and_predictor():
    """MiniBatch.slice works on sparse payloads, and the stock
    Predictor/Evaluator consume sparse datasets directly."""
    from bigdl_tpu.dataset import (DataSet, Sample, SampleToMiniBatch,
                                   SparseFeature, samples_to_minibatch)
    from bigdl_tpu.optim import Evaluator, Top1Accuracy
    from bigdl_tpu.optim.predictor import LocalPredictor

    rng = np.random.RandomState(11)
    dense = (rng.rand(8, 10) * (rng.rand(8, 10) < 0.4)).astype(np.float32)
    samples = [Sample(SparseFeature.from_dense(dense[i]),
                      float(i % 2 + 1)) for i in range(8)]
    mb = samples_to_minibatch(samples)
    sub = mb.slice(3, 2)  # 1-based offset
    np.testing.assert_allclose(sub.get_input().to_dense(), dense[2:4],
                               atol=1e-6)

    model = nn.Sequential().add(nn.SparseLinear(10, 2)) \
        .add(nn.LogSoftMax())
    ds = DataSet.array(samples).transform(SampleToMiniBatch(4))
    preds = LocalPredictor(model).predict_class(ds, batch_size=4)
    assert len(preds) == 8 and all(p in (1, 2) for p in preds)
    res = Evaluator(model).test(ds, [Top1Accuracy()], batch_size=4)
    acc, count = res["Top1Accuracy"].result()
    assert count == 8 and 0.0 <= acc <= 1.0
