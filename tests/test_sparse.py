"""Sparse layer tests (reference model: SparseLinearSpec/SparseJoinTableSpec
— sparse forward equals dense forward on the same data)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

import bigdl_tpu.nn as nn


def _sparse_input(b=4, n=32, density=0.1, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(b, n).astype(np.float32)
    x[rng.rand(b, n) > density] = 0.0
    return x


def test_sparse_linear_matches_dense():
    x = _sparse_input()
    m = nn.SparseLinear(32, 8)
    dense_out = np.asarray(m.forward(x))
    sp = jsparse.BCOO.fromdense(jnp.asarray(x))
    sparse_out = np.asarray(m.forward(sp))
    np.testing.assert_allclose(sparse_out, dense_out, atol=1e-5)


def test_sparse_linear_grad():
    x = jsparse.BCOO.fromdense(jnp.asarray(_sparse_input()))
    m = nn.SparseLinear(32, 8)
    m.ensure_initialized()
    p = m.get_parameters()

    def loss(p):
        return m.forward_fn(p, x).sum()

    g = jax.grad(loss)(p)
    assert np.isfinite(np.asarray(g["weight"])).all()
    assert g["weight"].shape == (8, 32)


def test_dense_to_sparse_and_join():
    a = _sparse_input(2, 8, seed=1)
    b = _sparse_input(2, 6, seed=2)
    d2s = nn.DenseToSparse()
    sa = d2s.forward(a)
    assert isinstance(sa, jsparse.BCOO)
    join = nn.SparseJoinTable(2)
    out = join.forward([a, b])
    ref = np.concatenate([a, b], axis=1)
    np.testing.assert_allclose(np.asarray(out.todense()), ref, atol=1e-6)


def test_wide_and_deep_style_model():
    """Sparse wide path + dense deep path joined (the reference's use case
    for sparse tensors)."""
    xs_wide = jsparse.BCOO.fromdense(jnp.asarray(_sparse_input(4, 100, 0.05)))
    xs_deep = np.random.randn(4, 10).astype(np.float32)
    wide = nn.SparseLinear(100, 4)
    deep = nn.Sequential().add(nn.Linear(10, 16)).add(nn.ReLU()).add(
        nn.Linear(16, 4))
    w_out = np.asarray(wide.forward(xs_wide))
    d_out = np.asarray(deep.forward(xs_deep))
    logits = w_out + d_out
    assert logits.shape == (4, 4)
    assert np.isfinite(logits).all()
