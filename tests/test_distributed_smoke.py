"""Two-process jax.distributed smoke test (the analogue of the
reference's multi-node Engine semantics check, Engine.scala:93-106 /
DistriOptimizerSpec.scala:41 Engine.init(4,4,true)).

Spawns two real OS processes that rendezvous through
``Engine.init_distributed``, run one cross-process psum, and take one
data-parallel SGD step that must equal the sequential update. Skips
gracefully when the runtime lacks cross-process CPU collectives.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_engine_psum_and_dp_step():
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(port), str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.skip("distributed rendezvous timed out on this runtime")

    results = []
    for p, (out, err) in zip(procs, outs):
        if p.returncode != 0:
            pytest.fail(f"worker crashed (rc={p.returncode}):\n{err[-2000:]}")
        line = [l for l in out.strip().splitlines()
                if l.startswith("{")][-1]
        results.append(json.loads(line))

    if any("skip" in r for r in results):
        pytest.skip(f"no cross-process CPU collectives: {results}")

    for r in results:
        assert r["ok"] and r["psum"] == 3.0
    # both processes computed the identical replicated weight
    assert results[0]["w1"] == results[1]["w1"]
