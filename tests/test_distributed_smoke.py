"""Two-process jax.distributed smoke test (the analogue of the
reference's multi-node Engine semantics check, Engine.scala:93-106 /
DistriOptimizerSpec.scala:41 Engine.init(4,4,true)).

Spawns two real OS processes that rendezvous through
``Engine.init_distributed``, run one cross-process psum, and take one
data-parallel SGD step that must equal the sequential update. Skips
gracefully when the runtime lacks cross-process CPU collectives.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

from _capability import require_multiprocess_cpu

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(mode=None, extra_args=(), timeout=300):
    """Spawn the two-process worker in ``mode`` and return the parsed
    per-worker JSON results. Skips when the runtime lacks cross-process
    collectives or rendezvous/compile time out; a timeout AFTER a
    worker completed training steps (its STEP_OK marker) is a mid-run
    collective deadlock and FAILS with both workers' output (a hung
    collective must not read as an environment skip)."""
    # one probed, cached, auditable reason instead of 12 crash-shaped
    # failures on runtimes whose CPU backend cannot EXECUTE
    # cross-process collectives (rendezvous alone is not the capability)
    require_multiprocess_cpu()
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    argv_tail = ([mode] if mode else []) + [str(a) for a in extra_args]
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(port), str(i)] + argv_tail,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    try:
        outs = [p.communicate(timeout=timeout) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        tails = [p.communicate() for p in procs]
        if any("STEP_OK" in t[0] for t in tails):
            # at least one worker got PAST compilation and completed
            # training steps, then the gang hung — a real collective
            # deadlock, not environment slowness (slow compile on a
            # loaded host prints RENDEZVOUS_OK but no STEP_OK and
            # still skips)
            dump = "\n".join(
                f"--- worker {i} stdout ---\n{t[0][-2000:]}\n"
                f"--- worker {i} stderr ---\n{t[1][-2000:]}"
                for i, t in enumerate(tails))
            pytest.fail("workers trained past compile but then hung — "
                        f"collective deadlock:\n{dump}")
        pytest.skip("distributed rendezvous/compile timed out on this "
                    "runtime")

    results = []
    for p, (out, err) in zip(procs, outs):
        if p.returncode != 0:
            pytest.fail(f"worker crashed (rc={p.returncode}):\n{err[-2000:]}")
        lines = [l for l in out.strip().splitlines() if l.startswith("{")]
        if not lines:
            pytest.fail(f"worker produced no JSON:\n{out[-2000:]}")
        # Gloo/absl sometimes appends its own log text to the same
        # stdout line — parse the leading JSON object, ignore the tail
        results.append(json.JSONDecoder().raw_decode(lines[-1])[0])
    if any("skip" in r for r in results):
        pytest.skip(f"no cross-process CPU collectives: {results}")
    return results


def test_two_process_engine_psum_and_dp_step():
    results = _run_workers(timeout=240)
    for r in results:
        assert r["ok"] and r["psum"] == 3.0
    # both processes computed the identical replicated weight
    assert results[0]["w1"] == results[1]["w1"]


def test_two_process_distri_optimizer_matches_single_process():
    """The real DistriOptimizer over a mesh spanning two OS processes
    (4 virtual devices each) must produce the same training losses as a
    single-process 8-device run on the identical global batches — the
    reference's RefDistriOptimizer oracle lifted to true multi-host
    (DistriOptimizerSpec.scala:233-249 + Engine.init(4,4,true)); the
    workers run ZeRO-1 sharded optimizer state, the reference runs
    replicated — the match proves both equivalences at once."""
    import numpy as np

    results = _run_workers("optimizer")

    # single-process reference on the same global batches: global batch
    # i is concat(proc0 batch i, proc1 batch i), so order the samples as
    # interleaved blocks of 8
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import DistriOptimizer, SGD, max_iteration
    from bigdl_tpu.utils.random import RandomGenerator

    rng = np.random.RandomState(7)
    xs = rng.randn(64, 10).astype(np.float32)
    ys = (rng.randint(0, 3, 64) + 1).astype(np.float32)
    order = []
    for i in range(4):
        order += list(range(i * 8, i * 8 + 8))
        order += list(range(32 + i * 8, 32 + i * 8 + 8))
    samples = [Sample(xs[i], ys[i]) for i in order]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(16))
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("data",))
    RandomGenerator.set_seed(42)
    model = (nn.Sequential().add(nn.Linear(10, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          batch_size=16, mesh=mesh)
    # replicated opt state here vs ZeRO-1 in the workers: the loss match
    # additionally proves sharded-state equivalence across hosts
    opt.set_optim_method(SGD(learning_rate=0.2, momentum=0.9))
    opt.set_end_when(max_iteration(4))
    opt.optimize()
    ref_loss = opt.driver_state["Loss"]

    for r in results:
        assert r["ok"] and r["neval"] == 5
        np.testing.assert_allclose(r["last_loss"], ref_loss, atol=1e-5)
        # validation ran on the global mesh (local-shard scoring,
        # reduced across processes)
        assert r["score"] is not None and 0.0 <= r["score"] <= 1.0
    # the cross-process reduce makes every host report the GLOBAL score
    assert results[0]["score"] == results[1]["score"]


def test_launcher_spawns_rendezvoused_workers(tmp_path):
    """tools/launch (the spark-submit role): two workers get the env
    contract, rendezvous through Engine.init_distributed() with NO
    arguments, and both report the global topology."""
    worker = tmp_path / "worker.py"
    worker.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "try:\n"
        "    jax.extend.backend.clear_backends()\n"
        "except Exception:\n"
        "    pass\n"
        "from bigdl_tpu.utils.engine import Engine\n"
        "Engine.init_distributed(initialization_timeout=60)\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "assert len(jax.devices()) == 4\n"
        "print('WORKER_OK', jax.process_index())\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.tools.launch", "--nproc", "2",
         "--cpu-devices", "2", str(worker)],
        capture_output=True, text=True, timeout=240, env=env)
    if r.returncode != 0 and "UNAVAILABLE" in r.stdout:
        pytest.skip("no cross-process rendezvous on this runtime")
    assert r.returncode == 0, r.stdout[-2000:]
    assert "[0] WORKER_OK 0" in r.stdout
    assert "[1] WORKER_OK 1" in r.stdout


def test_two_process_imagefolder_reader_sharding(tmp_path):
    """The full multi-host input story: one image folder, each process
    reading its shard (process_index/process_count), feeding the global
    DistriOptimizer batch — Spark partition locality's role end to end."""
    import numpy as np
    from PIL import Image

    rng = np.random.RandomState(0)
    for cls in ("a", "b"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(8):
            Image.fromarray(rng.randint(0, 255, (20, 20, 3), np.uint8)) \
                .save(d / f"{i}.jpg")

    results = _run_workers("imagefolder", extra_args=(tmp_path,))
    for r in results:
        assert r["ok"] and np.isfinite(r["last_loss"])
    # synchronous DP: both processes observed the same global loss
    assert abs(results[0]["last_loss"] - results[1]["last_loss"]) < 1e-6


def test_two_process_shard_rotation_on_spanning_mesh():
    """Rotating HBM slots sharded across BOTH processes: per-process
    shard providers, global piece assembly, argument-rebind swaps —
    the pod-scale rotating-cache composition end to end."""
    results = _run_workers("rotate")
    for r in results:
        assert r["ok"] and r["means"] == [8.5, 108.5, 208.5]


def _run_launcher(tmp_env, ckpt, kill_at, max_restarts, crash_ckpt_at=0):
    """Launch the 2-process fault-tolerance worker gang. Two full gang
    bring-ups (Gloo rendezvous + compiles) can pass 10 minutes on a
    loaded CI host; skip rather than fail on timeout, like the sibling
    rendezvous tests."""
    require_multiprocess_cpu()
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_faulttol_worker.py")
    args = [sys.executable, "-m", "bigdl_tpu.tools.launch",
            "--nproc", "2", "--cpu-devices", "4",
            "--max-restarts", str(max_restarts),
            worker, str(ckpt), str(kill_at)]
    if crash_ckpt_at:
        args.append(str(crash_ckpt_at))
    try:
        return subprocess.run(args, capture_output=True, text=True,
                              timeout=900, env=tmp_env)
    except subprocess.TimeoutExpired:
        pytest.skip("gang bring-up timed out on this runtime")


def _launcher_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _final_losses(out):
    res = [json.loads(l.split("] ", 1)[1])
           for l in out.strip().splitlines()
           if l.startswith("[") and '"ok"' in l]
    assert len(res) == 2, out[-2000:]
    return sorted((r["pid"], r["final_loss"]) for r in res)


def test_kill_worker_mid_training_resumes_to_same_loss(tmp_path):
    """The reference's signature resilience feature at true multi-process
    scale (DistriOptimizer.scala:789-855 retry + ExceptionTest-scripted
    failure): SIGKILL one of two workers mid-training; the launcher
    gang-restarts, workers resume from their latest (shared,
    single-writer) checkpoint, and the job finishes with the SAME final
    loss as an uninterrupted run."""
    env = _launcher_env()

    r_plain = _run_launcher(env, tmp_path / "a", 0, 0)
    if r_plain.returncode != 0 and "UNAVAILABLE" in r_plain.stdout:
        pytest.skip("no cross-process rendezvous on this runtime")
    assert r_plain.returncode == 0, r_plain.stdout[-3000:]

    r_killed = _run_launcher(env, tmp_path / "b", 6, 2)
    assert r_killed.returncode == 0, r_killed.stdout[-3000:]
    assert "gang restart 1/2" in r_killed.stdout, \
        "the scripted kill never triggered a restart"

    la, lb = _final_losses(r_plain.stdout), _final_losses(r_killed.stdout)
    # resumed run reports attempt 1 in its surviving incarnation
    assert any(json.loads(l.split("] ", 1)[1])["attempt"] == 1
               for l in r_killed.stdout.strip().splitlines()
               if l.startswith("[") and '"ok"' in l)
    for (pa, va), (pb, vb) in zip(la, lb):
        assert pa == pb and abs(va - vb) < 1e-6, (la, lb)


def test_kill_during_checkpoint_write_resumes_from_intact(tmp_path):
    """The failure mode the resilience feature exists to survive: the
    WRITER process is SIGKILLed MID-checkpoint-write (tree files
    written, MANIFEST not), leaving a torn staging dir. The restarted
    gang must skip the torn write, resume from the previous INTACT
    checkpoint, and still finish with the uninterrupted run's final
    loss."""
    env = _launcher_env()

    r_plain = _run_launcher(env, tmp_path / "a", 0, 0)
    if r_plain.returncode != 0 and "UNAVAILABLE" in r_plain.stdout:
        pytest.skip("no cross-process rendezvous on this runtime")
    assert r_plain.returncode == 0, r_plain.stdout[-3000:]

    # several_iteration(2) checkpoints at neval 2,4,6,8 — die inside
    # the neval-6 write; resume must come from checkpoint.4
    r_torn = _run_launcher(env, tmp_path / "b", 0, 2, crash_ckpt_at=6)
    assert r_torn.returncode == 0, r_torn.stdout[-3000:]
    assert "gang restart 1/2" in r_torn.stdout, \
        "the scripted mid-write kill never triggered a restart"

    la, lb = _final_losses(r_plain.stdout), _final_losses(r_torn.stdout)
    for (pa, va), (pb, vb) in zip(la, lb):
        assert pa == pb and abs(va - vb) < 1e-6, (la, lb)


def test_two_process_tensor_parallel_matches_single_process():
    """Megatron TP whose model axis SPANS two OS processes: the
    column/row-parallel collectives cross the real inter-process
    transport, and training must match a single-process 4-device run
    of the identical batches (the multi-host form of the dryrun's
    dp x tp part — beyond-DP parallelism at true multi-host)."""
    import numpy as np

    results = _run_workers("tp")

    # single-process oracle: the SHARED case definition on local
    # devices (hyperparameters cannot drift from the workers')
    import jax

    import _distributed_worker as W

    ref_loss = W.run_parallel_case("tp", jax.devices()[:4])["Loss"]

    for r in results:
        assert r["ok"] and r["neval"] == 5
        np.testing.assert_allclose(r["last_loss"], ref_loss, atol=1e-5)


def test_two_process_pipeline_parallel_matches_single_process():
    """GPipe PP whose pipe axis SPANS two OS processes: the ppermute
    activation ring crosses the inter-process transport every
    microbatch hop, and training must match a single-process run of
    the identical batches."""
    import numpy as np

    results = _run_workers("pp")

    import jax

    import _distributed_worker as W

    ref_loss = W.run_parallel_case("pp", jax.devices()[:4])["Loss"]

    for r in results:
        assert r["ok"] and r["neval"] == 5
        np.testing.assert_allclose(r["last_loss"], ref_loss, atol=1e-5)


def test_two_process_expert_parallel_matches_single_process():
    """MoE expert parallelism whose EXPERT axis SPANS two OS processes:
    the routed-dispatch collectives (stacked-expert einsums sharded over
    the model axis) cross the real inter-process transport, and training
    — including the load-balance aux loss joining the objective — must
    match a single-process run of the identical batches."""
    import numpy as np

    results = _run_workers("ep")

    import jax

    import _distributed_worker as W

    ref_loss = W.run_parallel_case("ep", jax.devices()[:2])["Loss"]

    for r in results:
        assert r["ok"] and r["neval"] == 5
        np.testing.assert_allclose(r["last_loss"], ref_loss, atol=1e-5)


@pytest.mark.parametrize("kind", ["composed", "composed_gpipe"])
def test_two_process_composed_mesh_matches_single_process(kind):
    """The COMPOSED product across a real OS-process boundary: a
    (data × pipe × model) spanning mesh trains a PipelinedTransformerLM
    with MoE experts — the data axis spans the two processes (each
    feeds its half, sharded-batch regime) while the pipe ring and the
    megatron/EP collectives run under the same jitted step; losses must
    match a single-process 8-device run of the identical global batches
    (DistriOptimizer.scala:728's one-call contract, now for the full
    DP×TP×PP×EP composition at true multi-host). Parametrized over
    BOTH pipeline schedules: "composed" additionally drives the
    interleaved virtual-stage waiting-room queue across the
    transport."""
    import numpy as np

    results = _run_workers(kind, timeout=420)

    import jax

    import _distributed_worker as W

    ref_loss = W.run_parallel_case(kind, jax.devices()[:8])["Loss"]

    for r in results:
        assert r["ok"] and r["neval"] == 5
        np.testing.assert_allclose(r["last_loss"], ref_loss, atol=1e-5)


def test_two_process_predict_and_evaluate_match_single_process():
    """Distributed inference at true multi-host (the reference's
    distributed Predictor/Evaluator, Predictor.scala:35,
    Evaluator.scala:37): each process feeds ITS dataset shard over the
    spanning data mesh and must get back exactly its rows' predictions;
    the evaluator's cross-process reduction makes both report the same
    GLOBAL accuracy — all equal to a single-process oracle."""
    import numpy as np

    results = _run_workers("predict")

    import jax

    import _distributed_worker as W

    ref_preds, ref_score, ref_n = W.run_predict_case(None,
                                                     jax.devices()[:8])

    assert ref_n == 32
    for r in results:
        assert r["ok"] and r["n"] == 32
        assert abs(r["score"] - ref_score) < 1e-6
        lo = r["pid"] * 16
        np.testing.assert_allclose(np.array(r["preds"]),
                                   ref_preds[lo:lo + 16], atol=1e-5)
    assert results[0]["score"] == results[1]["score"]


def test_two_process_sparse_feed_matches_single_process():
    """SparseMiniBatch at TRUE multi-host: fixed-nnz COO batches from
    two OS processes assemble into global BCOOs sharded over the
    spanning data axis, and training matches a single-process run of
    the identical global batches (the multi-host half of the sparse
    feed — the fixed-nnz requirement exists exactly for this)."""
    import numpy as np

    results = _run_workers("sparse")

    import jax

    import _distributed_worker as W

    ref_loss = W.run_sparse_case(None, jax.devices()[:8])["Loss"]

    for r in results:
        assert r["ok"] and r["neval"] == 5
        np.testing.assert_allclose(r["last_loss"], ref_loss, atol=1e-5)
