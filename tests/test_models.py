"""Model zoo forward-shape tests (reference model specs in test/.../models)."""
import numpy as np
import pytest

import bigdl_tpu.nn as nn


def test_lenet_forward_shape():
    from bigdl_tpu.models import LeNet5
    m = LeNet5(10)
    x = np.random.rand(4, 28, 28).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (4, 10)
    # log-softmax rows sum to 1 in prob space
    np.testing.assert_allclose(np.exp(out).sum(-1), np.ones(4), rtol=1e-4)


def test_lenet_graph_matches_sequential():
    from bigdl_tpu.models.lenet import LeNet5, LeNet5_graph
    from bigdl_tpu.utils.random import RandomGenerator
    x = np.random.rand(2, 28, 28).astype(np.float32)
    RandomGenerator.set_seed(7)
    seq = LeNet5(10)
    out_seq = np.asarray(seq.forward(x))
    RandomGenerator.set_seed(7)
    g = LeNet5_graph(10)
    out_g = np.asarray(g.forward(x))
    assert out_seq.shape == out_g.shape == (2, 10)


def test_vgg_cifar_forward():
    from bigdl_tpu.models import VggForCifar10
    m = VggForCifar10(10, has_dropout=False).evaluate()
    x = np.random.rand(2, 3, 32, 32).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (2, 10)


def test_resnet20_cifar_forward():
    from bigdl_tpu.models import ResNet
    m = ResNet(10, depth=20, dataset="CIFAR10").evaluate()
    x = np.random.rand(2, 3, 32, 32).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (2, 10)


def test_resnet18_imagenet_forward():
    from bigdl_tpu.models import ResNet
    m = ResNet(1000, depth=18, dataset="ImageNet").evaluate()
    x = np.random.rand(1, 3, 224, 224).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (1, 1000)


@pytest.mark.slow
def test_resnet50_imagenet_forward():
    from bigdl_tpu.models import ResNet
    m = ResNet(1000, depth=50, dataset="ImageNet").evaluate()
    x = np.random.rand(1, 3, 224, 224).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (1, 1000)


def test_inception_v1_noaux_forward():
    from bigdl_tpu.models import Inception_v1_NoAuxClassifier
    m = Inception_v1_NoAuxClassifier(1000, has_dropout=False).evaluate()
    x = np.random.rand(1, 3, 224, 224).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (1, 1000)


def test_simple_rnn_forward():
    from bigdl_tpu.models import SimpleRNN
    m = SimpleRNN(input_size=8, hidden_size=16, output_size=5)
    x = np.random.rand(3, 7, 8).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (3, 7, 5)


def test_ptb_model_forward():
    from bigdl_tpu.models import PTBModel
    m = PTBModel(input_size=50, hidden_size=32, output_size=50,
                 num_layers=2).evaluate()
    x = (np.random.randint(1, 51, size=(4, 10))).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (4, 10, 50)


def test_autoencoder_forward():
    from bigdl_tpu.models import Autoencoder
    m = Autoencoder(32)
    x = np.random.rand(5, 28, 28).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (5, 784)


def test_graph_multi_input_output():
    inp1 = nn.Input()()
    inp2 = nn.Input()()
    h1 = nn.Linear(4, 8)(inp1)
    h2 = nn.Linear(6, 8)(inp2)
    merged = nn.CAddTable()(h1, h2)
    out1 = nn.Linear(8, 3)(merged)
    out2 = nn.ReLU()(merged)
    g = nn.Graph([inp1, inp2], [out1, out2])
    from bigdl_tpu.utils.table import T
    x1 = np.random.rand(2, 4).astype(np.float32)
    x2 = np.random.rand(2, 6).astype(np.float32)
    out = g.forward(T(x1, x2))
    assert np.asarray(out[1]).shape == (2, 3)
    assert np.asarray(out[2]).shape == (2, 8)


def test_resnet_conv_bias_dropped_and_cancelled_by_bn():
    """Convs feeding BN carry no bias by default (fb.resnet noBias;
    +7.7% measured step throughput on v5e) because BN's mean subtraction
    cancels any per-channel constant — proven here numerically — while
    conv_bias=True restores the reference's exact parameter set
    (ResNet.scala:36)."""
    import jax
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.resnet import ResNet

    lean = ResNet(10, depth=20, dataset="CIFAR10")
    lean.ensure_initialized()
    full = ResNet(10, depth=20, dataset="CIFAR10", conv_bias=True)
    full.ensure_initialized()
    n_lean = len(jax.tree_util.tree_leaves(lean.get_parameters()))
    n_full = len(jax.tree_util.tree_leaves(full.get_parameters()))
    assert n_full - n_lean == 21  # one bias per conv restored

    # numeric proof: conv+BN output is invariant to the conv bias
    conv = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1)
    bn_l = nn.SpatialBatchNormalization(8)
    m = nn.Sequential().add(conv).add(bn_l).training()
    m.ensure_initialized()
    params = m.get_parameters()
    x = np.random.RandomState(0).randn(4, 3, 8, 8).astype(np.float32)
    y0 = np.asarray(m.forward(x))
    params["0"]["bias"] = params["0"]["bias"] + 3.7  # any constant shift
    m.set_parameters(params)
    y1 = np.asarray(m.forward(x))
    np.testing.assert_allclose(y0, y1, atol=2e-4)


def test_inception_v2_noaux_forward():
    from bigdl_tpu.models import Inception_v2_NoAuxClassifier
    m = Inception_v2_NoAuxClassifier(1000).evaluate()
    x = np.random.rand(1, 3, 224, 224).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (1, 1000)
    assert np.isfinite(out).all()


def test_inception_v2_full_three_heads():
    """Full BN-GoogLeNet concats [main, aux2, aux1] on the class dim
    (Inception_v2.scala:275-364)."""
    from bigdl_tpu.models import Inception_v2
    m = Inception_v2(7).evaluate()
    x = np.random.rand(1, 3, 224, 224).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (1, 21)
    # each head is a LogSoftMax distribution over 7 classes
    for h in range(3):
        np.testing.assert_allclose(
            np.exp(out[0, h * 7:(h + 1) * 7]).sum(), 1.0, atol=1e-4)


def test_alexnet_forward_shapes():
    """AlexNet.scala:84 (original, LRN + 2-group convs) and :23 (OWT)."""
    from bigdl_tpu.models import AlexNet, AlexNet_OWT
    m = AlexNet(50, has_dropout=False).evaluate()
    out = np.asarray(m.forward(
        np.random.rand(2, 3, 227, 227).astype(np.float32)))
    assert out.shape == (2, 50)
    m2 = AlexNet_OWT(50, has_dropout=False).evaluate()
    out2 = np.asarray(m2.forward(
        np.random.rand(2, 3, 224, 224).astype(np.float32)))
    assert out2.shape == (2, 50)
    np.testing.assert_allclose(np.exp(out2).sum(-1), 1.0, atol=1e-4)


def test_alexnet_owt_trains():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import AlexNet_OWT
    from bigdl_tpu.optim import LocalOptimizer, SGD, max_iteration

    rng = np.random.RandomState(0)
    xs = rng.rand(16, 3, 224, 224).astype(np.float32)
    ys = rng.randint(1, 6, 16).astype(np.float32)
    ds = DataSet.array([Sample(xs[i], ys[i]) for i in range(16)]) \
        .transform(SampleToMiniBatch(8))
    m = AlexNet_OWT(5, has_dropout=False)
    opt = LocalOptimizer(m, ds, nn.ClassNLLCriterion(), batch_size=8)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(max_iteration(3))
    opt.optimize()
    assert np.isfinite(opt.driver_state["Loss"])


def test_perf_tool_knows_new_models():
    from bigdl_tpu.tools.perf import build_model
    m, shape, classes = build_model("alexnetowt", 10)
    assert shape == (3, 224, 224) and classes == 10
    m, shape, _ = build_model("alexnet", 10)
    assert shape == (3, 227, 227)
    m, shape, _ = build_model("inception_v2", 10)
    assert shape == (3, 224, 224)
