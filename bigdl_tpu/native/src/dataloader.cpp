// Native data pipeline — the TPU build's counterpart of the reference's
// multi-threaded batch building (dataset/image/MTLabeledBGRImgToBatch.scala
// + the MKL-native preprocessing the JVM leaned on).
//
// Provides:
//  - idx (MNIST) and CIFAR-10 binary decoding into float arrays
//  - a multi-threaded augmenting batch loader: random crop + horizontal
//    flip + per-channel normalize, producing NCHW float32 batches into a
//    ring of prefetch buffers while the accelerator computes.
// Exported with C linkage for ctypes.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ------------------------------------------------------------- decoders

// Parse an idx file (MNIST): returns 0 on success; fills dims (up to 4).
// data_out receives float32 values (bytes scaled 1:1, no normalization).
int bigdl_parse_idx(const uint8_t* buf, int64_t len, float* data_out,
                    int64_t out_capacity, int32_t* dims_out,
                    int32_t* ndim_out) {
  if (len < 4) return -1;
  if (buf[0] != 0 || buf[1] != 0) return -2;
  int dtype = buf[2];
  int ndim = buf[3];
  if (ndim > 4) return -3;
  int64_t off = 4;
  int64_t total = 1;
  for (int i = 0; i < ndim; ++i) {
    if (off + 4 > len) return -4;
    int32_t d = (buf[off] << 24) | (buf[off + 1] << 16) |
                (buf[off + 2] << 8) | buf[off + 3];
    dims_out[i] = d;
    total *= d;
    off += 4;
  }
  *ndim_out = ndim;
  if (dtype != 0x08) return -5;  // unsigned byte only
  if (total > out_capacity) return -6;
  if (off + total > len) return -7;
  for (int64_t i = 0; i < total; ++i)
    data_out[i] = static_cast<float>(buf[off + i]);
  return 0;
}

// CIFAR-10 binary format: records of [label u8][3072 u8 RGB planes].
// Fills labels (1-based, reference convention) and CHW float images.
int bigdl_parse_cifar(const uint8_t* buf, int64_t len, float* images_out,
                      float* labels_out, int64_t max_records) {
  const int64_t rec = 1 + 3 * 32 * 32;
  int64_t n = len / rec;
  if (n > max_records) n = max_records;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* r = buf + i * rec;
    labels_out[i] = static_cast<float>(r[0]) + 1.0f;
    const uint8_t* px = r + 1;
    float* dst = images_out + i * 3 * 32 * 32;
    for (int64_t j = 0; j < 3 * 32 * 32; ++j)
      dst[j] = static_cast<float>(px[j]);
  }
  return static_cast<int>(n);
}

// ------------------------------------------------ augmenting batch loader

struct Loader {
  const float* images;   // [n, c, h, w] source (borrowed)
  const float* labels;   // [n]
  int64_t n;
  int c, h, w;           // source geometry
  int crop_h, crop_w;    // output geometry
  int pad;               // zero-pad before crop (CIFAR style)
  int batch;
  bool flip, train;
  float mean[8], std_[8];
  uint64_t seed;

  std::vector<std::vector<float>> img_bufs;
  std::vector<std::vector<float>> lbl_bufs;
  std::queue<int> ready;
  std::queue<int> free_bufs;
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::atomic<bool> stop{false};
  std::atomic<int64_t> cursor{0};

  void worker(int tid) {
    std::mt19937_64 rng(seed + tid);
    const int64_t out_px = int64_t(c) * crop_h * crop_w;
    while (!stop.load()) {
      int buf_idx;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] { return stop.load() || !free_bufs.empty(); });
        if (stop.load()) return;
        buf_idx = free_bufs.front();
        free_bufs.pop();
      }
      float* out = img_bufs[buf_idx].data();
      float* lbl = lbl_bufs[buf_idx].data();
      for (int b = 0; b < batch; ++b) {
        int64_t idx;
        if (train) {
          idx = static_cast<int64_t>(rng() % uint64_t(n));
        } else {
          idx = cursor.fetch_add(1) % n;
        }
        lbl[b] = labels[idx];
        const float* src = images + idx * int64_t(c) * h * w;
        int off_y = 0, off_x = 0;
        bool do_flip = false;
        if (train) {
          off_y = int(rng() % uint64_t(h + 2 * pad - crop_h + 1)) - pad;
          off_x = int(rng() % uint64_t(w + 2 * pad - crop_w + 1)) - pad;
          do_flip = flip && (rng() & 1);
        } else {
          off_y = (h - crop_h) / 2;
          off_x = (w - crop_w) / 2;
        }
        float* dst = out + b * out_px;
        for (int ch = 0; ch < c; ++ch) {
          const float m = mean[ch], s = std_[ch];
          for (int y = 0; y < crop_h; ++y) {
            int sy = y + off_y;
            for (int x = 0; x < crop_w; ++x) {
              int sx = do_flip ? (crop_w - 1 - x) + off_x : x + off_x;
              float v = 0.0f;
              if (sy >= 0 && sy < h && sx >= 0 && sx < w)
                v = src[(int64_t(ch) * h + sy) * w + sx];
              dst[(int64_t(ch) * crop_h + y) * crop_w + x] = (v - m) / s;
            }
          }
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push(buf_idx);
      }
      cv_ready.notify_one();
    }
  }
};

void* bigdl_loader_create(const float* images, const float* labels,
                          int64_t n, int c, int h, int w, int crop_h,
                          int crop_w, int pad, int batch, int flip,
                          int train, const float* mean, const float* std_,
                          int num_threads, int prefetch, uint64_t seed) {
  if (n <= 0 || c <= 0 || c > 8 || batch <= 0 || prefetch <= 0 ||
      num_threads <= 0)
    return nullptr;
  auto* L = new Loader();
  L->images = images;
  L->labels = labels;
  L->n = n;
  L->c = c; L->h = h; L->w = w;
  L->crop_h = crop_h; L->crop_w = crop_w;
  L->pad = pad;
  L->batch = batch;
  L->flip = flip != 0;
  L->train = train != 0;
  for (int i = 0; i < c && i < 8; ++i) {
    L->mean[i] = mean ? mean[i] : 0.0f;
    L->std_[i] = (std_ && std_[i] != 0.0f) ? std_[i] : 1.0f;
  }
  L->seed = seed;
  const int64_t out_px = int64_t(c) * crop_h * crop_w;
  for (int i = 0; i < prefetch; ++i) {
    L->img_bufs.emplace_back(size_t(batch) * out_px);
    L->lbl_bufs.emplace_back(size_t(batch));
    L->free_bufs.push(i);
  }
  for (int t = 0; t < num_threads; ++t)
    L->workers.emplace_back(&Loader::worker, L, t);
  return L;
}

// Copies the next ready batch into out_images/out_labels. Blocks until one
// is available. Returns the batch size.
int bigdl_loader_next(void* handle, float* out_images, float* out_labels) {
  auto* L = static_cast<Loader*>(handle);
  int buf_idx;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_ready.wait(lk, [&] { return !L->ready.empty(); });
    buf_idx = L->ready.front();
    L->ready.pop();
  }
  std::memcpy(out_images, L->img_bufs[buf_idx].data(),
              L->img_bufs[buf_idx].size() * sizeof(float));
  std::memcpy(out_labels, L->lbl_bufs[buf_idx].data(),
              L->lbl_bufs[buf_idx].size() * sizeof(float));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_bufs.push(buf_idx);
  }
  L->cv_free.notify_one();
  return L->batch;
}

void bigdl_loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
