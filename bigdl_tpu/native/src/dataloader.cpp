// Native data pipeline — the TPU build's counterpart of the reference's
// multi-threaded batch building (dataset/image/MTLabeledBGRImgToBatch.scala
// + the MKL-native preprocessing the JVM leaned on).
//
// Provides:
//  - idx (MNIST) and CIFAR-10 binary decoding into float arrays
//  - a multi-threaded augmenting batch loader: random crop + horizontal
//    flip + per-channel normalize, producing NCHW float32 batches into a
//    ring of prefetch buffers while the accelerator computes.
// Exported with C linkage for ctypes.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// ------------------------------------------------------------- decoders

// Parse an idx file (MNIST): returns 0 on success; fills dims (up to 4).
// data_out receives float32 values (bytes scaled 1:1, no normalization).
int bigdl_parse_idx(const uint8_t* buf, int64_t len, float* data_out,
                    int64_t out_capacity, int32_t* dims_out,
                    int32_t* ndim_out) {
  if (len < 4) return -1;
  if (buf[0] != 0 || buf[1] != 0) return -2;
  int dtype = buf[2];
  int ndim = buf[3];
  if (ndim > 4) return -3;
  int64_t off = 4;
  int64_t total = 1;
  for (int i = 0; i < ndim; ++i) {
    if (off + 4 > len) return -4;
    int32_t d = (buf[off] << 24) | (buf[off + 1] << 16) |
                (buf[off + 2] << 8) | buf[off + 3];
    dims_out[i] = d;
    total *= d;
    off += 4;
  }
  *ndim_out = ndim;
  if (dtype != 0x08) return -5;  // unsigned byte only
  if (total > out_capacity) return -6;
  if (off + total > len) return -7;
  for (int64_t i = 0; i < total; ++i)
    data_out[i] = static_cast<float>(buf[off + i]);
  return 0;
}

// CIFAR-10 binary format: records of [label u8][3072 u8 RGB planes].
// Fills labels (1-based, reference convention) and CHW float images.
int bigdl_parse_cifar(const uint8_t* buf, int64_t len, float* images_out,
                      float* labels_out, int64_t max_records) {
  const int64_t rec = 1 + 3 * 32 * 32;
  int64_t n = len / rec;
  if (n > max_records) n = max_records;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* r = buf + i * rec;
    labels_out[i] = static_cast<float>(r[0]) + 1.0f;
    const uint8_t* px = r + 1;
    float* dst = images_out + i * 3 * 32 * 32;
    for (int64_t j = 0; j < 3 * 32 * 32; ++j)
      dst[j] = static_cast<float>(px[j]);
  }
  return static_cast<int>(n);
}

// ------------------------------------------------ augmenting batch loader
// The loader is templated on the pixel type; the float instantiation
// normalizes during the copy (the classic MTLabeledBGRImgToBatch shape),
// the uint8 instantiation copies raw crops so the batch crosses the
// host->device link at 1/4 the float32 bytes and (x - mean) / std runs on
// device, where XLA fuses it into the first conv.

}  // extern "C" (reopened below; the template can't have C linkage)

template <typename Tpix>
struct LoaderT {
  const Tpix* images;    // [n, c, h, w] source (borrowed)
  const float* labels;   // [n]
  int64_t n;
  int c, h, w;           // source geometry
  int crop_h, crop_w;    // output geometry
  int pad;               // zero-pad before crop (CIFAR style)
  int batch;
  bool flip, train;
  bool normalize;        // only meaningful for Tpix=float
  float mean[8], std_[8];
  uint64_t seed;

  std::vector<std::vector<Tpix>> img_bufs;
  std::vector<std::vector<float>> lbl_bufs;
  std::queue<int> ready;
  std::queue<int> free_bufs;
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free, cv_drained;
  int next_waiters = 0;  // guarded by mu; consumers inside next()
  std::atomic<bool> stop{false};
  std::atomic<int64_t> cursor{0};

  static LoaderT* create(const Tpix* images, const float* labels, int64_t n,
                         int c, int h, int w, int crop_h, int crop_w,
                         int pad, int batch, int flip, int train,
                         const float* mean, const float* std_,
                         bool normalize, int num_threads, int prefetch,
                         uint64_t seed) {
    if (n <= 0 || c <= 0 || c > 8 || batch <= 0 || prefetch <= 0 ||
        num_threads <= 0)
      return nullptr;
    // A crop larger than the padded source would make the random-offset
    // modulus non-positive (wild uint64 offsets -> silently zeroed
    // batches).
    if (crop_h <= 0 || crop_w <= 0 || pad < 0 || crop_h > h + 2 * pad ||
        crop_w > w + 2 * pad)
      return nullptr;
    auto* L = new LoaderT();
    L->images = images;
    L->labels = labels;
    L->n = n;
    L->c = c; L->h = h; L->w = w;
    L->crop_h = crop_h; L->crop_w = crop_w;
    L->pad = pad;
    L->batch = batch;
    L->flip = flip != 0;
    L->train = train != 0;
    L->normalize = normalize;
    for (int i = 0; i < c && i < 8; ++i) {
      L->mean[i] = mean ? mean[i] : 0.0f;
      L->std_[i] = (std_ && std_[i] != 0.0f) ? std_[i] : 1.0f;
    }
    L->seed = seed;
    const int64_t out_px = int64_t(c) * crop_h * crop_w;
    for (int i = 0; i < prefetch; ++i) {
      L->img_bufs.emplace_back(size_t(batch) * out_px);
      L->lbl_bufs.emplace_back(size_t(batch));
      L->free_bufs.push(i);
    }
    for (int t = 0; t < num_threads; ++t)
      L->workers.emplace_back(&LoaderT::worker, L, t);
    return L;
  }

  void worker(int tid) {
    std::mt19937_64 rng(seed + tid);
    const int64_t out_px = int64_t(c) * crop_h * crop_w;
    while (!stop.load()) {
      int buf_idx;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] { return stop.load() || !free_bufs.empty(); });
        if (stop.load()) return;
        buf_idx = free_bufs.front();
        free_bufs.pop();
      }
      Tpix* out = img_bufs[buf_idx].data();
      float* lbl = lbl_bufs[buf_idx].data();
      for (int b = 0; b < batch; ++b) {
        int64_t idx;
        if (train) {
          idx = static_cast<int64_t>(rng() % uint64_t(n));
        } else {
          idx = cursor.fetch_add(1) % n;
        }
        lbl[b] = labels[idx];
        const Tpix* src = images + idx * int64_t(c) * h * w;
        int off_y, off_x;
        bool do_flip = false;
        if (train) {
          off_y = int(rng() % uint64_t(h + 2 * pad - crop_h + 1)) - pad;
          off_x = int(rng() % uint64_t(w + 2 * pad - crop_w + 1)) - pad;
          do_flip = flip && (rng() & 1);
        } else {
          off_y = (h - crop_h) / 2;
          off_x = (w - crop_w) / 2;
        }
        Tpix* dst = out + b * out_px;
        const bool interior = off_y >= 0 && off_x >= 0 &&
                              off_y + crop_h <= h && off_x + crop_w <= w;
        for (int ch = 0; ch < c; ++ch) {
          const float m = mean[ch], s = std_[ch];
          for (int y = 0; y < crop_h; ++y) {
            int sy = y + off_y;
            Tpix* drow = dst + (int64_t(ch) * crop_h + y) * crop_w;
            if (!normalize && interior && !do_flip) {
              std::memcpy(drow, src + (int64_t(ch) * h + sy) * w + off_x,
                          size_t(crop_w) * sizeof(Tpix));
              continue;
            }
            for (int x = 0; x < crop_w; ++x) {
              int sx = do_flip ? (crop_w - 1 - x) + off_x : x + off_x;
              float v = 0.0f;
              if (sy >= 0 && sy < h && sx >= 0 && sx < w)
                v = float(src[(int64_t(ch) * h + sy) * w + sx]);
              drow[x] = normalize ? Tpix((v - m) / s) : Tpix(v);
            }
          }
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push(buf_idx);
      }
      cv_ready.notify_one();
    }
  }

  // Copies the next ready batch into out_images/out_labels. Blocks until
  // one is available. Returns the batch size, or 0 if the loader is
  // stopping.
  int next(Tpix* out_images, float* out_labels) {
    int buf_idx;
    {
      std::unique_lock<std::mutex> lk(mu);
      ++next_waiters;
      cv_ready.wait(lk, [&] { return stop.load() || !ready.empty(); });
      if (ready.empty()) {  // stopping with nothing buffered
        if (--next_waiters == 0) cv_drained.notify_all();
        return 0;
      }
      buf_idx = ready.front();
      ready.pop();
    }
    std::memcpy(out_images, img_bufs[buf_idx].data(),
                img_bufs[buf_idx].size() * sizeof(Tpix));
    std::memcpy(out_labels, lbl_bufs[buf_idx].data(),
                lbl_bufs[buf_idx].size() * sizeof(float));
    {
      std::lock_guard<std::mutex> lk(mu);
      free_bufs.push(buf_idx);
    }
    cv_free.notify_one();
    const int result = batch;
    {
      // Decrementing the waiter count is the LAST touch of this object:
      // once it hits zero, destroy() may delete `this` as soon as the
      // notify is delivered and the lock released.
      std::lock_guard<std::mutex> lk(mu);
      if (--next_waiters == 0) cv_drained.notify_all();
    }
    return result;
  }

  void destroy() {
    {
      // stop must flip under mu: a thread between its predicate check and
      // blocking would otherwise miss the only notify and sleep forever
      std::lock_guard<std::mutex> lk(mu);
      stop.store(true);
    }
    cv_free.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers) t.join();
    {
      // A consumer may still be inside next() (e.g. __del__ racing a
      // data() generator at interpreter shutdown); deleting the mutex and
      // condvars out from under it would be a use-after-free.
      std::unique_lock<std::mutex> lk(mu);
      cv_drained.wait(lk, [&] { return next_waiters == 0; });
    }
    delete this;
  }
};

extern "C" {

void* bigdl_loader_create(const float* images, const float* labels,
                          int64_t n, int c, int h, int w, int crop_h,
                          int crop_w, int pad, int batch, int flip,
                          int train, const float* mean, const float* std_,
                          int num_threads, int prefetch, uint64_t seed) {
  return LoaderT<float>::create(images, labels, n, c, h, w, crop_h, crop_w,
                                pad, batch, flip, train, mean, std_,
                                /*normalize=*/true, num_threads, prefetch,
                                seed);
}

int bigdl_loader_next(void* handle, float* out_images, float* out_labels) {
  return static_cast<LoaderT<float>*>(handle)->next(out_images, out_labels);
}

void bigdl_loader_destroy(void* handle) {
  static_cast<LoaderT<float>*>(handle)->destroy();
}

void* bigdl_loader_u8_create(const uint8_t* images, const float* labels,
                             int64_t n, int c, int h, int w, int crop_h,
                             int crop_w, int pad, int batch, int flip,
                             int train, int num_threads, int prefetch,
                             uint64_t seed) {
  return LoaderT<uint8_t>::create(images, labels, n, c, h, w, crop_h,
                                  crop_w, pad, batch, flip, train,
                                  /*mean=*/nullptr, /*std=*/nullptr,
                                  /*normalize=*/false, num_threads,
                                  prefetch, seed);
}

int bigdl_loader_u8_next(void* handle, uint8_t* out_images,
                         float* out_labels) {
  return static_cast<LoaderT<uint8_t>*>(handle)->next(out_images,
                                                      out_labels);
}

void bigdl_loader_u8_destroy(void* handle) {
  static_cast<LoaderT<uint8_t>*>(handle)->destroy();
}

}  // extern "C"
