"""Native C++ runtime bindings (ctypes — no pybind11 in this image).

The reference offloads its hot host-side paths to native code (MKL JNI,
BigQuant, netty CRC); here the TPU compute is XLA/pallas and the native
layer covers the HOST side: CRC32C for the event writer and a
multi-threaded augmenting data loader that keeps the input pipeline off
the Python GIL. Builds lazily with `make` on first import; every entry
point has a pure-Python fallback so the framework works without a
compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

# fallback for close() on partially-constructed loaders (init raised
# before _lock existed)
_NULL_LOCK = threading.Lock()

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libbigdl_native.so")
_lib: Optional[ctypes.CDLL] = None


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def load_library(build: bool = True) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable.

    ``build=False`` only dlopens an existing .so — used by hot paths that
    must not block on a compile."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and (not build or not _build()):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.bigdl_crc32c.restype = ctypes.c_uint32
    lib.bigdl_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                 ctypes.c_uint32]
    lib.bigdl_parse_idx.restype = ctypes.c_int
    lib.bigdl_parse_cifar.restype = ctypes.c_int
    lib.bigdl_loader_create.restype = ctypes.c_void_p
    lib.bigdl_loader_next.restype = ctypes.c_int
    lib.bigdl_loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_void_p]
    lib.bigdl_loader_destroy.argtypes = [ctypes.c_void_p]
    lib.bigdl_loader_u8_create.restype = ctypes.c_void_p
    lib.bigdl_loader_u8_next.restype = ctypes.c_int
    lib.bigdl_loader_u8_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_void_p]
    lib.bigdl_loader_u8_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def native_crc32c(data: bytes, crc: int = 0) -> int:
    lib = load_library()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return lib.bigdl_crc32c(data, len(data), crc)


def native_available() -> bool:
    return load_library() is not None


def parse_idx(data: bytes) -> np.ndarray:
    """Parse an MNIST idx buffer natively; raises if unavailable."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("native library unavailable")
    cap = len(data)  # one float per byte max
    out = np.empty(cap, np.float32)
    dims = np.zeros(4, np.int32)
    ndim = ctypes.c_int32(0)
    rc = lib.bigdl_parse_idx(
        data, ctypes.c_int64(len(data)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(cap),
        dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.byref(ndim))
    if rc != 0:
        raise ValueError(f"idx parse failed (code {rc})")
    shape = tuple(int(d) for d in dims[:ndim.value])
    return out[:int(np.prod(shape))].reshape(shape)


def parse_cifar(data: bytes, max_records: int = 1 << 30):
    lib = load_library()
    if lib is None:
        raise RuntimeError("native library unavailable")
    rec = 1 + 3 * 32 * 32
    n = min(len(data) // rec, max_records)
    imgs = np.empty((n, 3, 32, 32), np.float32)
    lbls = np.empty((n,), np.float32)
    got = lib.bigdl_parse_cifar(
        data, ctypes.c_int64(len(data)),
        imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        lbls.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int64(n))
    return imgs[:got], lbls[:got]


class NativeBatchLoader:
    """Threaded augmenting loader over an in-memory [N,C,H,W] dataset
    (the MTLabeledBGRImgToBatch analogue). Yields (images, labels) float32
    batches: random pad-crop + h-flip + normalize in C++ threads."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, *, crop: Optional[tuple] = None,
                 pad: int = 0, flip: bool = True, train: bool = True,
                 mean=None, std=None, num_threads: int = 4,
                 prefetch: int = 4, seed: int = 0):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.images = np.ascontiguousarray(images, np.float32)
        self.labels = np.ascontiguousarray(labels, np.float32)
        n, c, h, w = self.images.shape
        if n <= 0:
            raise ValueError("NativeBatchLoader needs a non-empty dataset")
        if len(self.labels) < n:
            raise ValueError(
                f"labels ({len(self.labels)}) shorter than images ({n}) "
                "— C++ workers index labels[0:n)")
        if c > 8:
            raise ValueError("NativeBatchLoader supports at most 8 "
                             "channels (mean/std are fixed-size in C++)")
        ch, cw = crop or (h, w)
        self.batch_size = batch_size
        self.out_shape = (batch_size, c, ch, cw)
        mean = np.asarray(mean if mean is not None else [0.0] * c,
                          np.float32)
        std = np.asarray(std if std is not None else [1.0] * c, np.float32)
        self._handle = lib.bigdl_loader_create(
            self.images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self.labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(n), c, h, w, ch, cw, pad, batch_size,
            int(flip), int(train),
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            num_threads, prefetch, ctypes.c_uint64(seed))
        if not self._handle:
            raise ValueError("bigdl_loader_create rejected the config")
        self._lock = threading.Lock()  # serializes next_batch vs close

    def next_batch(self):
        imgs = np.empty(self.out_shape, np.float32)
        lbls = np.empty((self.batch_size,), np.float32)
        with self._lock:
            if not self._handle:
                raise RuntimeError("loader is closed")
            got = self._lib.bigdl_loader_next(
                self._handle,
                imgs.ctypes.data_as(ctypes.c_void_p),
                lbls.ctypes.data_as(ctypes.c_void_p))
        if got == 0:  # loader is stopping; the buffers are uninitialized
            raise RuntimeError("loader stopped")
        return imgs, lbls

    def __iter__(self):
        while True:
            yield self.next_batch()

    def close(self):
        with getattr(self, "_lock", _NULL_LOCK):
            if getattr(self, "_handle", None):
                self._lib.bigdl_loader_destroy(self._handle)
                self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeBatchLoaderU8:
    """uint8 variant of NativeBatchLoader: crop+flip only, NO normalize.

    Batches cross the host->device link at 1/4 the float32 bytes (the link
    is the feed bottleneck on tunneled TPUs); do ``(x - mean) / std`` on
    device, where XLA fuses it into the first conv.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, *, crop: Optional[tuple] = None,
                 pad: int = 0, flip: bool = True, train: bool = True,
                 num_threads: int = 4, prefetch: int = 4, seed: int = 0):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.images = np.ascontiguousarray(images, np.uint8)
        self.labels = np.ascontiguousarray(labels, np.float32)
        n, c, h, w = self.images.shape
        if n <= 0:
            raise ValueError("NativeBatchLoaderU8 needs a non-empty dataset")
        if len(self.labels) < n:
            raise ValueError(
                f"labels ({len(self.labels)}) shorter than images ({n}) "
                "— C++ workers index labels[0:n)")
        ch, cw = crop or (h, w)
        self.batch_size = batch_size
        self.out_shape = (batch_size, c, ch, cw)
        self._handle = lib.bigdl_loader_u8_create(
            self.images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self.labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(n), c, h, w, ch, cw, pad, batch_size,
            int(flip), int(train), num_threads, prefetch,
            ctypes.c_uint64(seed))
        if not self._handle:
            raise ValueError("bigdl_loader_u8_create rejected the config")
        self._lock = threading.Lock()  # serializes next_batch vs close

    def next_batch(self):
        imgs = np.empty(self.out_shape, np.uint8)
        lbls = np.empty((self.batch_size,), np.float32)
        with self._lock:
            if not self._handle:
                raise RuntimeError("loader is closed")
            got = self._lib.bigdl_loader_u8_next(
                self._handle,
                imgs.ctypes.data_as(ctypes.c_void_p),
                lbls.ctypes.data_as(ctypes.c_void_p))
        if got == 0:  # loader is stopping; the buffers are uninitialized
            raise RuntimeError("loader stopped")
        return imgs, lbls

    def __iter__(self):
        while True:
            yield self.next_batch()

    def close(self):
        with getattr(self, "_lock", _NULL_LOCK):
            if getattr(self, "_handle", None):
                self._lib.bigdl_loader_u8_destroy(self._handle)
                self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
