"""The precision policy: four dtypes that define a training/serving
regime.

``param_dtype`` is what the weights are stored in at rest;
``compute_dtype`` is what forward/backward matmuls run in (the MXU's
bf16 sweet spot); ``output_dtype`` is what the model hands the loss;
``accum_dtype`` is where reductions and the weight update accumulate —
pinned to f32 in every preset, because that is the part low-precision
training cannot cheapen without diverging (norm statistics, softmax,
the loss, and the optimizer's master-copy update are the sanctioned f32
islands).

The policy is *declarative*: ``build_train_step`` reads it once and
compiles the casts into the step, so switching ``f32`` ->
``bf16_mixed`` is one ``Optimizer.set_precision`` call, not a model
rewrite. When ``param_dtype`` is lower than ``accum_dtype`` the
optimizer keeps an f32 **master copy** of the weights in its state tree
(the classic mixed-precision recipe, and the reference's
FP16CompressedTensor idea taken to its conclusion): gradients arrive in
compute dtype, the update runs on the f32 master, and the served
params are the master cast down.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

# Reserved optimizer-state keys. The dunder namespace guarantees a real
# OptimMethod buffer can never collide: the loss-scaler state and the
# f32 master params ride the SAME opt-state tree as the moments, so
# they are donated into the scan carry, sharded by ZeRO's spec engine,
# and checkpointed/resumed with zero extra plumbing.
SCALER_KEY = "__bigdl_loss_scale__"
MASTER_KEY = "__bigdl_master_params__"

_LOW_PRECISION = (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16))


def cast_floating(tree, dtype):
    """Cast every floating leaf of ``tree`` to ``dtype``; integer/bool
    leaves (labels, step counters, int8 weights) pass through."""
    dtype = jnp.dtype(dtype)
    return jax.tree.map(
        lambda a: a.astype(dtype)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        and a.dtype != dtype else a, tree)


def matmul_accum_dtype(operand_dtype):
    """The ``preferred_element_type`` a layer should request for a
    matmul over ``operand_dtype`` operands: f32 for bf16/f16 inputs (the
    MXU accumulates in f32 natively — asking for it costs nothing and
    keeps long contractions exact), the operand dtype otherwise."""
    if jnp.dtype(operand_dtype) in _LOW_PRECISION:
        return jnp.float32
    return jnp.dtype(operand_dtype)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Declarative mixed-precision regime (module docstring has the
    semantics of the four dtypes).

    Presets: :meth:`f32` (everything f32 — the no-op policy),
    :meth:`bf16_mixed` (f32 params, bf16 compute — the TPU default win:
    bf16's 8 exponent bits need no loss scaling), :meth:`f16_mixed`
    (f32 master params, f16 compute, dynamic loss scaling on). The
    serving-side int8 path is not a training policy — it goes through
    ``ModelRegistry.load(quantize=True, calibration=...)``.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32
    accum_dtype: jnp.dtype = jnp.float32
    #: None = decide from compute_dtype (f16 scales, bf16/f32 do not)
    loss_scaling: Optional[bool] = None
    #: None = decide from param_dtype (below accum -> keep an f32
    #: master). False trains DIRECTLY on low-precision params — the
    #: pre-policy Engine behavior ``from_engine`` preserves bitwise.
    master_weights: Optional[bool] = None

    def __post_init__(self):
        for f in ("param_dtype", "compute_dtype", "output_dtype",
                  "accum_dtype"):
            object.__setattr__(self, f, jnp.dtype(getattr(self, f)))
        if self.accum_dtype != jnp.dtype(jnp.float32):
            raise ValueError(
                "accum_dtype must stay float32: reductions, norm stats "
                "and the master-copy update are the f32 islands that "
                "keep low-precision training convergent")

    # ---- presets ---------------------------------------------------------
    @classmethod
    def f32(cls) -> "PrecisionPolicy":
        """Everything float32 — the exact pre-policy behavior."""
        return cls()

    @classmethod
    def bf16_mixed(cls) -> "PrecisionPolicy":
        """f32 params at rest, bf16 forward/backward, f32 accumulation.
        bf16 shares f32's exponent range, so no loss scaling."""
        return cls(compute_dtype=jnp.bfloat16)

    @classmethod
    def f16_mixed(cls) -> "PrecisionPolicy":
        """f16 params at rest + f32 master copy, f16 compute, dynamic
        loss scaling (f16's 5 exponent bits underflow small gradients
        without it)."""
        return cls(param_dtype=jnp.float16, compute_dtype=jnp.float16,
                   loss_scaling=True)

    @classmethod
    def named(cls, name: str) -> "PrecisionPolicy":
        """Preset by name: ``"f32"`` | ``"bf16_mixed"`` | ``"f16_mixed"``."""
        try:
            return {"f32": cls.f32, "bf16_mixed": cls.bf16_mixed,
                    "f16_mixed": cls.f16_mixed}[name]()
        except KeyError:
            raise ValueError(
                f"unknown precision preset {name!r}; pick one of "
                "f32 | bf16_mixed | f16_mixed") from None

    @classmethod
    def from_engine(cls) -> "PrecisionPolicy":
        """The policy ``Engine.set_default_dtype``/``set_compute_dtype``
        imply — the pre-policy configuration surface, kept working so
        existing recipes change behavior not one bit. That surface had
        no loss scaler and no master copy (a low-precision default
        dtype trained directly on the low-precision params), so both
        are pinned OFF here; the presets are the opt-in for the full
        mixed-precision recipe."""
        from bigdl_tpu.utils.engine import Engine
        return cls(param_dtype=Engine.default_dtype(),
                   compute_dtype=Engine.compute_dtype(),
                   output_dtype=Engine.default_dtype(),
                   loss_scaling=False, master_weights=False)

    # ---- derived properties ----------------------------------------------
    @property
    def is_noop(self) -> bool:
        """True when the policy changes nothing vs plain f32 training."""
        return (self.param_dtype == self.compute_dtype
                == self.output_dtype and not self.needs_loss_scaling
                and not self.needs_master)

    @property
    def needs_master(self) -> bool:
        """Params stored below accum precision -> the optimizer keeps an
        f32 master copy in its state tree (``MASTER_KEY``). Explicit
        ``master_weights`` wins (``from_engine`` pins it False: the
        legacy path updates low-precision params directly)."""
        if self.master_weights is not None:
            return self.master_weights
        return self.param_dtype != self.accum_dtype

    @property
    def needs_loss_scaling(self) -> bool:
        """Explicit ``loss_scaling`` wins; otherwise f16 compute scales."""
        if self.loss_scaling is not None:
            return self.loss_scaling
        return self.compute_dtype == jnp.dtype(jnp.float16)

    @property
    def name(self) -> str:
        """The preset name when this policy matches one, else "custom"."""
        for n in ("f32", "bf16_mixed", "f16_mixed"):
            if self == PrecisionPolicy.named(n):
                return n
        return "custom"

    # ---- casting ---------------------------------------------------------
    def cast_to_compute(self, tree):
        """Cast-on-entry: floating leaves -> ``compute_dtype``."""
        return cast_floating(tree, self.compute_dtype)

    def cast_output(self, tree):
        """Cast-on-exit: floating leaves -> ``output_dtype`` (what the
        loss consumes — its log/exp run in f32)."""
        return cast_floating(tree, self.output_dtype)

    def cast_to_param(self, tree):
        """Floating leaves -> ``param_dtype`` (the at-rest weights)."""
        return cast_floating(tree, self.param_dtype)

    def cast_to_accum(self, tree):
        """Floating leaves -> ``accum_dtype`` (gradients entering the
        update, after any unscaling)."""
        return cast_floating(tree, self.accum_dtype)

    def apply_module(self, module, params, state, x, *, training=False,
                     rng=None):
        """``module.apply`` under this policy: params and inputs cast to
        ``compute_dtype`` on entry, the output cast to ``output_dtype``
        on exit — the one cast boundary every consumer (train step,
        eval step, shape checker) shares. Layer-internal f32 islands
        (norm stats, softmax) are the layers' own responsibility."""
        out, new_state = module.apply(self.cast_to_compute(params), state,
                                      self.cast_to_compute(x),
                                      training=training, rng=rng)
        return self.cast_output(out), new_state

    def describe(self) -> str:
        """One-line human form for logs/diagnose."""
        return (f"{self.name}(param={self.param_dtype.name}, "
                f"compute={self.compute_dtype.name}, "
                f"output={self.output_dtype.name}, "
                f"accum={self.accum_dtype.name}, "
                f"loss_scaling={self.needs_loss_scaling})")
