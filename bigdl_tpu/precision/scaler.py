"""Dynamic loss scaling for fp16-style training.

f16's 5 exponent bits underflow gradients around 6e-8, so the loss is
multiplied by a large scale before ``jax.grad`` (shifting the whole
gradient distribution into range) and the gradients divided back in f32
before the update. The scale adapts online with the classic overflow
state machine:

- **non-finite gradients** (overflow): the step is SKIPPED (params and
  optimizer state keep their previous values), the scale halves
  (``backoff_factor``), and the growth counter resets.
- **finite gradients**: the update applies; after ``growth_interval``
  consecutive finite steps the scale doubles (``growth_factor``) and
  the counter resets.

The state is a tiny jittable pytree ``{scale, good_steps, skipped}``
that lives in the optimizer-state tree under ``precision.SCALER_KEY``,
so it rides the donated ``lax.scan`` carry of
``Optimizer.set_steps_per_sync(K)`` — a window that overflows at step 3
backs off INSIDE the scan and step 4 already retries at the halved
scale, bit-identically to the per-step loop. ``skipped`` counts
cumulative skipped steps for the ``train/precision/skipped_steps``
gauge.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DynamicLossScaler:
    """Config of the overflow state machine (module docstring). The
    mutable part is the state pytree from :meth:`init_state`; every
    method is pure/jittable."""

    init_scale: float = 2.0 ** 15
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 200
    min_scale: float = 1.0
    max_scale: float = 2.0 ** 24

    def __post_init__(self):
        if not (self.growth_factor > 1.0 and 0.0 < self.backoff_factor
                < 1.0 and self.growth_interval >= 1
                and self.min_scale > 0.0):
            raise ValueError(
                "DynamicLossScaler needs growth_factor > 1, "
                "0 < backoff_factor < 1, growth_interval >= 1 and "
                "min_scale > 0")

    def init_state(self):
        """Fresh scaler state: ``{scale, good_steps, skipped}``."""
        return {"scale": jnp.float32(self.init_scale),
                "good_steps": jnp.int32(0),
                "skipped": jnp.int32(0)}

    def scale_loss(self, loss, state):
        """The loss actually differentiated: ``loss * scale`` (cast to
        the loss's own dtype so f16 compute stays f16)."""
        return loss * state["scale"].astype(loss.dtype)

    def unscale(self, grads, state):
        """Divide the scale back out — call AFTER casting gradients to
        accum dtype, so the division is exact f32."""
        inv = 1.0 / state["scale"]
        return jax.tree.map(lambda g: g * inv.astype(g.dtype), grads)

    @staticmethod
    def all_finite(grads):
        """Scalar bool: every gradient element is finite. The overflow
        probe the skip-step decision keys on."""
        leaves = [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)
                  if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)]
        if not leaves:
            return jnp.bool_(True)
        return jnp.stack(leaves).all()

    def next_state(self, state, finite):
        """One state-machine transition (module docstring has the
        rules); ``finite`` is :meth:`all_finite`'s scalar."""
        good = state["good_steps"] + 1
        grow = good >= self.growth_interval
        grown = jnp.minimum(state["scale"] * self.growth_factor,
                            self.max_scale)
        backed = jnp.maximum(state["scale"] * self.backoff_factor,
                             self.min_scale)
        scale = jnp.where(finite, jnp.where(grow, grown, state["scale"]),
                          backed)
        good_steps = jnp.where(finite, jnp.where(grow, 0, good), 0)
        skipped = state["skipped"] + jnp.where(finite, 0, 1)
        return {"scale": scale.astype(jnp.float32),
                "good_steps": good_steps.astype(jnp.int32),
                "skipped": skipped.astype(jnp.int32)}
