"""Mixed-precision as a *policy* — bf16/fp16 training with dynamic loss
scaling and calibrated int8 serving, end to end.

The source system shipped a native int8 inference engine (BigQuant) and
an fp16 gradient-compression path (FP16CompressedTensor.scala); here
precision is one declarative object instead of scattered one-offs:

- :class:`PrecisionPolicy` — the four dtypes that define a regime
  (``param``/``compute``/``output``/``accum``) with presets ``f32``,
  ``bf16_mixed`` and ``f16_mixed``; threaded through ``Module.apply``
  (cast-on-entry / cast-on-exit at the step boundary, norm stats /
  softmax / loss pinned to f32 accumulation inside the layers) and
  ``Optimizer.set_precision`` (f32 master-copy update, low-precision
  gradients reduce-scattered in compute dtype under ZeRO).
- :class:`DynamicLossScaler` — the fp16 overflow state machine; its
  state rides the donated scan carry so ``set_steps_per_sync(K)`` stays
  bit-consistent across K.
- :mod:`~bigdl_tpu.precision.calibrate` — the ONE scale-estimation path
  for int8: weight scales and activation-calibration scales both derive
  from ``ops/quant``'s symmetric max-abs rule.
- :class:`AccuracyGate` — calibrated int8 serving loads refuse the swap
  when the quantized model's accuracy delta exceeds the bound
  (``serving/precision/accuracy_delta``).

See ``docs/precision.md`` for the policy table and interaction rules
with ``steps_per_sync``/ZeRO/TP.
"""
from bigdl_tpu.precision.calibrate import (calibrate_weight,
                                           collect_activation_scales,
                                           scale_from_amax)
from bigdl_tpu.precision.gate import AccuracyGate, AccuracyGateError
from bigdl_tpu.precision.policy import (MASTER_KEY, SCALER_KEY,
                                        PrecisionPolicy, cast_floating,
                                        matmul_accum_dtype)
from bigdl_tpu.precision.scaler import DynamicLossScaler

__all__ = [
    "AccuracyGate", "AccuracyGateError", "DynamicLossScaler",
    "MASTER_KEY", "PrecisionPolicy", "SCALER_KEY", "calibrate_weight",
    "cast_floating", "collect_activation_scales", "matmul_accum_dtype",
    "scale_from_amax",
]
