"""Accuracy-delta gate for quantized serving loads.

``ModelRegistry.load(quantize=True, calibration=..., accuracy_gate=
AccuracyGate(...))`` evaluates the candidate (quantized) model against
the float reference on held-out batches BEFORE anything is staged: if
the accuracy delta exceeds the configured bound the load raises
:class:`AccuracyGateError` and the registry is untouched — no version
registered, no program compiled, no traffic can resolve it. The
measured delta lands in the ``serving/precision/accuracy_delta`` gauge
either way, so dashboards see near-misses too.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

import bigdl_tpu.telemetry as telemetry

_ACC_DELTA = telemetry.gauge(
    "serving/precision/accuracy_delta",
    "accuracy delta (reference minus candidate) measured by the last "
    "quantized-load gate evaluation, by model label")


class AccuracyGateError(ValueError):
    """A quantized load's accuracy delta exceeded the gate bound; the
    candidate was refused before staging."""


@dataclasses.dataclass
class AccuracyGate:
    """Eval-batch gate for quantized loads.

    ``inputs`` — held-out eval rows ``[N, features...]``.
    ``targets`` — optional 1-based class labels ``[N]``; with targets
    the metric is top-1 accuracy of each model and the delta is
    ``acc_reference - acc_candidate``; without targets the metric is
    top-1 AGREEMENT with the reference (delta = disagreement rate) —
    no labels needed, which is the common serving case.
    ``max_delta`` — the refusal bound (default 2 points).
    ``batch_size`` — evaluation chunking (eager forwards).
    """

    inputs: np.ndarray
    targets: Optional[np.ndarray] = None
    max_delta: float = 0.02
    batch_size: int = 64

    @staticmethod
    def _top1(model, params, state, x) -> np.ndarray:
        out = np.asarray(model.apply(params, state, x,
                                     training=False)[0])
        return np.argmax(out.reshape(out.shape[0], -1), axis=1)

    def evaluate(self, reference, candidate) -> float:
        """The accuracy delta of ``candidate`` vs ``reference`` on the
        gate's eval rows (positive = the candidate is worse)."""
        x = np.asarray(self.inputs)
        # one module-tree walk per model, not one per eval chunk
        ref_ps = (reference.get_parameters(), reference.get_state())
        cand_ps = (candidate.get_parameters(), candidate.get_state())
        ref_hits = cand_hits = agree = 0
        for start in range(0, x.shape[0], self.batch_size):
            chunk = x[start:start + self.batch_size]
            ref = self._top1(reference, *ref_ps, chunk)
            cand = self._top1(candidate, *cand_ps, chunk)
            if self.targets is not None:
                t = np.asarray(self.targets).reshape(-1)[
                    start:start + chunk.shape[0]].astype(np.int64) - 1
                ref_hits += int((ref == t).sum())
                cand_hits += int((cand == t).sum())
            else:
                agree += int((ref == cand).sum())
        n = x.shape[0]
        if self.targets is not None:
            return (ref_hits - cand_hits) / n
        return 1.0 - agree / n

    def check(self, reference, candidate, *, label: str = "") -> float:
        """Evaluate, record the gauge, and raise
        :class:`AccuracyGateError` when the delta exceeds
        ``max_delta``. Returns the delta on success."""
        delta = self.evaluate(reference, candidate)
        _ACC_DELTA.set(delta, **({"model": label} if label else {}))
        if delta > self.max_delta:
            raise AccuracyGateError(
                f"quantized model refused: accuracy delta {delta:.4f} "
                f"exceeds the gate bound {self.max_delta:.4f}"
                + (f" for {label!r}" if label else "")
                + " (recalibrate with representative batches, or raise "
                  "the bound if the regression is acceptable)")
        return delta
