"""Int8 calibration — the ONE scale-estimation path.

Every int8 scale in the system derives from the same symmetric max-abs
rule (``ops/quant.scale_from_amax``: ``scale = max(|x|) / 127``, the
BigQuant scheme):

- **weight scales** — :func:`calibrate_weight` (per-output-channel,
  exactly ``ops/quant.quantize_symmetric``).
- **activation scales** — :func:`collect_activation_scales` runs
  calibration batches through the FLOAT model once, recording the
  running max-abs of every quantizable layer's input; the resulting
  per-layer scale is baked into the quantized twin, replacing the
  per-batch dynamic estimate. Static scales are both cheaper (no amax
  reduce + divide per request on the hot path) and the thing an
  accuracy gate can actually certify — a dynamic scale changes with
  every batch, so "calibrated accuracy" would be meaningless.

``tools/int8_sweep`` and ``ModelRegistry.load(quantize=True,
calibration=...)`` both go through here.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np

from bigdl_tpu.ops.quant import quantize_symmetric, scale_from_amax

__all__ = ["calibrate_weight", "calibrate_activation",
           "collect_activation_scales", "scale_from_amax"]


def calibrate_weight(w, axis: int = 0):
    """Per-channel symmetric int8 weight quantization along ``axis``
    (delegates to the one ``ops/quant`` path). Returns ``(q, scale)``."""
    return quantize_symmetric(w, axis=axis)


def calibrate_activation(x, axis: int = 0):
    """DYNAMIC per-batch activation quantization along ``axis`` — the
    same symmetric max-abs rule as everything else here, applied to one
    observed batch instead of a calibration sweep. Returns
    ``(q, scale)``.

    This is the estimate :func:`collect_activation_scales` exists to
    replace on serving hot paths (static scales are cheaper and
    certifiable); it remains the right call for one-off measurement
    sweeps (``tools/int8_sweep``) where each batch IS the entire
    distribution being measured."""
    return quantize_symmetric(x, axis=axis)


def _quantizable(m) -> bool:
    from bigdl_tpu.nn.conv import SpatialConvolution
    from bigdl_tpu.nn.linear import Linear
    return isinstance(m, Linear) or (
        isinstance(m, SpatialConvolution) and m.n_group == 1)


def _walk(m, out):
    from bigdl_tpu.nn.container import Container
    from bigdl_tpu.nn.graph import Graph
    if _quantizable(m):
        out.append(m)
    if isinstance(m, Graph):
        for n in m.exec_order:
            _walk(n.element, out)
    elif isinstance(m, Container):
        for c in m.modules:
            _walk(c, out)
    else:
        for v in vars(m).values():
            from bigdl_tpu.nn.module import Module
            if isinstance(v, Module):
                _walk(v, out)
            elif isinstance(v, (list, tuple)):
                for e in v:
                    if isinstance(e, Module):
                        _walk(e, out)


def collect_activation_scales(model,
                              batches: Iterable) -> Dict[int, float]:
    """Run ``batches`` through the float ``model`` (inference mode) and
    return ``{id(module): activation_scale}`` for every quantizable
    layer (Linear, ungrouped SpatialConvolution) — the per-tensor
    symmetric scale of the layer's OBSERVED input range, via the shared
    max-abs rule.

    Interception mirrors ``analysis/shapecheck``: each target module's
    bound ``apply`` is temporarily shadowed with a recording wrapper and
    restored afterwards; the model itself is never mutated beyond the
    transient wrapper. Keys are module identities so
    ``nn/quantized.quantize`` can look its conversion targets up while
    rebuilding the tree.
    """
    from bigdl_tpu.utils.random import RandomGenerator

    targets: list = []
    _walk(model, targets)
    if not targets:
        raise ValueError(
            "model has no quantizable layers (Linear / ungrouped "
            "SpatialConvolution); nothing to calibrate")
    amax: Dict[int, float] = {}

    def wrap(m):
        orig = type(m).apply.__get__(m)

        def recording(params, state, input, *, training=False, rng=None):
            x = np.asarray(input)
            peak = float(np.max(np.abs(x))) if x.size else 0.0
            amax[id(m)] = max(amax.get(id(m), 0.0), peak)
            return orig(params, state, input, training=training, rng=rng)

        m.__dict__["apply"] = recording

    model.ensure_initialized()
    params, state = model.get_parameters(), model.get_state()
    for m in targets:
        wrap(m)
    try:
        saw_batch = False
        for batch in batches:
            saw_batch = True
            model.apply(params, state, np.asarray(batch),
                        training=False, rng=RandomGenerator.next_key())
    finally:
        for m in targets:
            m.__dict__.pop("apply", None)
    if not saw_batch:
        raise ValueError("calibration needs at least one batch")
    return {mid: float(np.asarray(scale_from_amax(peak)))
            for mid, peak in amax.items()}


def maybe_collect(model, calibration: Optional[Iterable]):
    """``collect_activation_scales`` when ``calibration`` is given,
    else None — the registry/quantize entry point's one-liner."""
    if calibration is None:
        return None
    return collect_activation_scales(model, calibration)
