"""InferenceService — the serving façade.

``InferenceService(registry, config)`` wires the three serving pieces
together per model name: requests enter a :class:`MicroBatcher`, batches
resolve ONE :class:`Servable` snapshot from the :class:`ModelRegistry`
(hot-swap atomicity), and run through the :class:`CompileCache`'s
bucket-padded jitted forward. Everything runs on plain threads + queues
(``JAX_PLATFORMS=cpu`` works end to end; on TPU the same code path jits
onto the chips).

Metrics: per-model request/rejection/timeout counts, queue depth,
batch-fill ratio, and latency percentiles (via
``utils.profiling.percentile_summary``), exportable as TensorBoard
scalars through the existing ``visualization.summary`` writers —
serving observability lands next to training curves.
"""
from __future__ import annotations

import threading
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.serving.batcher import MicroBatcher
from bigdl_tpu.serving.breaker import CircuitBreaker, Degraded
from bigdl_tpu.serving.compile_cache import BucketLadder, CompileCache
from bigdl_tpu.serving.registry import ModelRegistry, Servable


@dataclass
class ServingConfig:
    """Tuning surface (see docs/serving.md for the trade-offs).

    ``max_wait_ms`` trades tail latency for batch fill: a full batch
    dispatches immediately, an underfilled one waits at most this long
    for stragglers. ``buckets`` overrides the powers-of-two ladder
    (its max then bounds the batch size). ``breaker_failures``
    consecutive dispatch failures open a per-model circuit breaker
    (submits fast-reject with :class:`Degraded` until a cooldown
    half-opens it; 0 disables)."""
    max_batch_size: int = 32
    max_wait_ms: float = 2.0
    max_queue: int = 256
    timeout_ms: Optional[float] = None
    buckets: Optional[Sequence[int]] = None
    breaker_failures: int = 8
    breaker_cooldown_ms: float = 1000.0


class InferenceService:
    """The serving façade: ``predict(name, x)`` (sync + async-future
    forms) over a hot-swappable multi-model registry, with per-model
    micro-batching, bucket-padded compiled forwards, and exportable
    serving metrics (module docstring has the wiring)."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 config: Optional[ServingConfig] = None,
                 metrics_registry=None):
        self.registry = registry or ModelRegistry()
        self.config = config or ServingConfig()
        self.ladder = BucketLadder(self.config.max_batch_size,
                                   self.config.buckets)
        # every serving instrument (batcher admission, compile cache,
        # latency reservoirs) reports through ONE telemetry registry,
        # private to this service by default so concurrent services /
        # tests never mix counts; pass telemetry.registry() to land the
        # series in the process-wide pane instead
        self.metrics_registry = metrics_registry \
            if metrics_registry is not None else telemetry.MetricsRegistry()
        self.cache = CompileCache(metrics=self.metrics_registry)
        # guards _batchers + _shut_down: batcher creation must be
        # once-per-name (a MicroBatcher owns a dispatch thread) and
        # must not race shutdown's iteration
        self._lock = threading.Lock()
        self._batchers: Dict[str, MicroBatcher] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._c_shed = self.metrics_registry.counter(
            "serving/service/shed",
            "requests fast-rejected by an open circuit breaker")
        self._shut_down = False

    # ------------------------------------------------------- lifecycle
    def load(self, name: str, model=None, *, path: Optional[str] = None,
             version: Optional[int] = None, quantize: bool = False,
             calibration=None, accuracy_gate=None,
             activate: bool = True,
             warmup_shape: Optional[Sequence[int]] = None,
             warmup_dtype=np.float32) -> Servable:
        """Registry load + (optionally) eager per-bucket compile.

        Pass ``warmup_shape`` (per-sample feature shape, no batch dim)
        to pre-compile every ladder rung before the version takes
        traffic — the version is registered inactive, warmed, and only
        THEN swapped in, so a hot-swap under live traffic never serves
        a cold bucket (and the first real request never eats a
        compile). ``calibration``/``accuracy_gate`` ride through to
        ``ModelRegistry.load`` for quantized loads: calibrated int8
        weights stage through this cache's warmed programs ONLY after
        the accuracy gate passes — a refused candidate compiles
        nothing and the old version keeps serving."""
        servable = self.registry.load(name, model, path=path,
                                      version=version, quantize=quantize,
                                      calibration=calibration,
                                      accuracy_gate=accuracy_gate,
                                      activate=False)
        if warmup_shape is not None:
            self.cache.warmup(servable.key, servable.model,
                              servable.params, servable.state,
                              warmup_shape, self.ladder, warmup_dtype)
        if activate:
            self.registry.swap(name, servable.version)
        return servable

    def warmup(self, name: str, feature_shape: Sequence[int],
               dtype=np.float32) -> int:
        """Pre-compile every bucket for the CURRENT version of
        ``name``; returns how many programs that compiled."""
        s = self.registry.current(name)
        return self.cache.warmup(s.key, s.model, s.params, s.state,
                                 feature_shape, self.ladder, dtype)

    def swap(self, name: str, version: int) -> Servable:
        """Atomic hot-swap: already-dispatched batches finish on the
        snapshot they resolved; every later batch serves ``version``."""
        return self.registry.swap(name, version)

    def unload(self, name: str, version: Optional[int] = None) -> None:
        """Unload a version (or a whole name, draining its batcher)
        and release its compiled programs."""
        if version is None:
            with self._lock:
                b = self._batchers.pop(name, None)
                # drop the breaker with the batcher: a reloaded name
                # must not inherit a stale open circuit
                self._breakers.pop(name, None)
            if b is not None:
                b.shutdown(drain=True)
        for key in self.registry.unload(name, version):
            self.cache.drop(key)

    def shutdown(self, drain: bool = True) -> None:
        """Stop admission on every batcher; with ``drain`` flush queued
        requests first."""
        with self._lock:
            self._shut_down = True
            batchers = list(self._batchers.values())
        for b in batchers:
            b.shutdown(drain=drain)

    # --------------------------------------------------------- predict
    def _batcher(self, name: str) -> MicroBatcher:
        with self._lock:
            b = self._batchers.get(name)
            if b is None:
                if self._shut_down:
                    raise RuntimeError("InferenceService is shut down")
                self.registry.current(name)  # fail fast on unknown names
                breaker = CircuitBreaker(
                    self.config.breaker_failures,
                    self.config.breaker_cooldown_ms)
                self._breakers[name] = breaker

                def run_batch(x, name=name, breaker=breaker):
                    # ONE registry read per batch: the snapshot can't
                    # change under a batch mid-forward (swap atomicity).
                    # Outcomes feed the breaker; the faultpoint is the
                    # chaos harness's dispatch-failure site.
                    try:
                        faults.point("serving/dispatch", model=name,
                                     rows=int(x.shape[0]))
                        s = self.registry.current(name)
                        step = self.cache.step_for(s.key, s.model)
                        out = np.asarray(step(s.params, s.state, x))
                    except Exception:
                        breaker.on_failure()
                        raise
                    breaker.on_success()
                    return out

                b = MicroBatcher(run_batch, self.ladder,
                                 max_wait_ms=self.config.max_wait_ms,
                                 max_queue=self.config.max_queue,
                                 name=name,
                                 metrics=self.metrics_registry)
                self._batchers[name] = b
        return b

    def _submit(self, name: str, x,
                timeout_ms: Optional[float]) -> Future:
        """Breaker-gated admission: an open circuit fast-rejects with
        :class:`Degraded` (counted into ``serving/service/shed``)
        instead of queueing work the dispatch path will fail anyway."""
        b = self._batcher(name)
        breaker = self._breakers.get(name)
        if breaker is not None and not breaker.allow():
            self._c_shed.inc(model=name)
            raise Degraded(
                f"{name}: circuit open after "
                f"{breaker.failures} consecutive dispatch failures; "
                f"retry after {breaker.cooldown_s * 1000:.0f}ms")
        return b.submit(x, self._timeout(timeout_ms))

    def predict_async(self, name: str, x,
                      timeout_ms: Optional[float] = None) -> Future:
        """One SAMPLE in -> Future of one prediction row."""
        x = np.asarray(x)
        fut = self._submit(name, x[None], timeout_ms)
        out: Future = Future()
        fut.add_done_callback(lambda f: _chain(f, out, lambda o: o[0]))
        return out

    def predict(self, name: str, x,
                timeout_ms: Optional[float] = None):
        """Sync single-sample predict (blocks on the micro-batch)."""
        return self.predict_async(name, x, timeout_ms).result()

    def predict_batch_async(self, name: str, x,
                            timeout_ms: Optional[float] = None) -> Future:
        """(rows, features...) in -> Future of (rows, ...) predictions
        — the rows ride one micro-batch together."""
        return self._submit(name, np.asarray(x), timeout_ms)

    def predict_batch(self, name: str, x,
                      timeout_ms: Optional[float] = None):
        return self.predict_batch_async(name, x, timeout_ms).result()

    def _timeout(self, timeout_ms: Optional[float]) -> Optional[float]:
        return timeout_ms if timeout_ms is not None \
            else self.config.timeout_ms

    # --------------------------------------------------------- metrics
    def compile_count(self, name: str,
                      version: Optional[int] = None) -> int:
        """Programs compiled for ``name`` (one version, or all)."""
        if version is not None:
            return self.cache.compile_count((name, version))
        return sum(self.cache.compile_count((name, v))
                   for v in self.registry.versions(name))

    def metrics(self, name: str) -> Dict[str, float]:
        """Point-in-time serving stats for one model name.

        The values are read from this service's telemetry registry
        (``self.metrics_registry`` — the same series the
        TensorBoard/Prometheus/JSONL exporters render); the key shapes
        predate the registry and stay byte-compatible."""
        from bigdl_tpu.utils.profiling import percentile_summary
        with self._lock:
            b = self._batchers.get(name)
        out: Dict[str, float] = {
            "request_count": 0, "rows": 0, "rejected": 0, "timed_out": 0,
            "errors": 0, "batch_count": 0, "batch_fill": 0.0,
            "padded_row_ratio": 0.0, "queue_depth": 0,
            "shed": 0, "worker_restarts": 0, "failed_batches": 0,
        }
        if b is not None:
            # one locked multi-counter view: the derived ratios below
            # must not mix counters from different instants
            st = b.stats.snapshot()
            lat = st["latencies_ms"]
            out.update(
                request_count=st["requests"], rows=st["rows"],
                rejected=st["rejected"], timed_out=st["timed_out"],
                errors=st["errors"], batch_count=st["batches"],
                worker_restarts=st["worker_restarts"],
                failed_batches=st["failed_batches"],
                batch_fill=(st["fill_sum"] / st["batches"]
                            if st["batches"] else 0.0),
                padded_row_ratio=(
                    st["padded_rows"] /
                    (st["batched_rows"] + st["padded_rows"])
                    if st["batched_rows"] + st["padded_rows"] else 0.0))
            out["queue_depth"] = b.queue_depth()
            out["shed"] = int(self._c_shed.value(model=name))
            for k, v in percentile_summary(lat, (50, 99)).items():
                out[f"latency_ms_{k}"] = v
        out["compile_count"] = self.compile_count(name)
        return out

    def breaker_state(self, name: str) -> str:
        """The model's circuit-breaker state (``"closed"`` when no
        breaker exists yet — no traffic has created the batcher)."""
        with self._lock:
            breaker = self._breakers.get(name)
        return breaker.state if breaker is not None else "closed"

    def export_metrics(self, summary, step: int) -> None:
        """Write every model's metrics as ``serving/<name>/<metric>``
        scalars through a ``visualization.summary.Summary`` writer —
        the same TensorBoard path training curves use. The values are
        the registry-backed :meth:`metrics` rows (tag shapes
        unchanged); for the raw instrument series use
        ``telemetry.TensorBoardExporter(self.metrics_registry, ...)``
        or ``telemetry.write_prometheus`` on the same registry."""
        for name in self.registry.names():
            for metric, value in self.metrics(name).items():
                summary.add_scalar(f"serving/{name}/{metric}",
                                   float(value), step)


def _chain(src: Future, dst: Future, fn) -> None:
    """Propagate src's outcome into dst through fn (row-slice views)."""
    if src.cancelled():
        dst.cancel()
        return
    e = src.exception()
    if e is not None:
        dst.set_exception(e)
    else:
        dst.set_result(fn(src.result()))
