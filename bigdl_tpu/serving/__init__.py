"""Online inference: dynamic micro-batching, shape-bucketed compile
cache, multi-model registry (docs/serving.md).

The offline ``optim.Predictor`` sweeps a dataset; this package turns
any Module (float, loaded, or int8-quantized) into a request-level
service::

    from bigdl_tpu.serving import InferenceService, ServingConfig

    svc = InferenceService(config=ServingConfig(max_batch_size=16,
                                                max_wait_ms=2.0))
    svc.load("mnist", model, warmup_shape=(28 * 28,))
    y = svc.predict("mnist", x)            # sync, one sample
    fut = svc.predict_async("mnist", x)    # future form
    svc.load("mnist", new_model)           # hot-swap v2 behind the name
"""
from bigdl_tpu.serving.batcher import (DeadlineExceeded, MicroBatcher,
                                       QueueFull, WorkerDied)
from bigdl_tpu.serving.breaker import CircuitBreaker, Degraded
from bigdl_tpu.serving.compile_cache import BucketLadder, CompileCache
from bigdl_tpu.serving.registry import ModelRegistry, Servable
from bigdl_tpu.serving.service import InferenceService, ServingConfig

__all__ = [
    "BucketLadder", "CircuitBreaker", "CompileCache", "DeadlineExceeded",
    "Degraded", "InferenceService", "MicroBatcher", "ModelRegistry",
    "QueueFull", "Servable", "ServingConfig", "WorkerDied",
]
