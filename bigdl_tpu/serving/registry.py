"""Multi-model registry: named, versioned servables with atomic hot-swap.

A **servable** is an immutable snapshot of everything a forward needs —
the module tree plus its params/state captured at load time. Snapshots
make hot-swap trivially atomic: ``current()`` returns one object, a
swap republishes the name→servable pointer under the registry lock, and
any batch already dispatched keeps the snapshot it resolved — in-flight
requests finish on the old version, later batches see only the new one,
and no response can mix versions (one batch, one snapshot).

Models arrive as live :class:`~bigdl_tpu.nn.module.Module` trees, as
``utils/serialization.save_module`` directories (``path=``), or through
the ``nn/quantized`` int8 rewrite (``quantize=True``) — a quantized
model serves identically (it is just another Module snapshot).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from bigdl_tpu import faults


class Servable:
    """One immutable (model, params, state) snapshot behind a
    (name, version)."""

    __slots__ = ("name", "version", "model", "params", "state")

    def __init__(self, name: str, version: int, model, params, state):
        self.name = name
        self.version = version
        self.model = model
        self.params = params
        self.state = state

    @property
    def key(self):
        """Compile-cache key: programs are never shared across
        versions (their param shapes/dtypes may differ)."""
        return (self.name, self.version)

    def __repr__(self) -> str:
        return (f"Servable({self.name!r} v{self.version} "
                f"{type(self.model).__name__})")


class _Entry:
    def __init__(self):
        self.versions: Dict[int, Servable] = {}
        self.current: Optional[Servable] = None


class ModelRegistry:
    """Named models, each with versions and one *current* pointer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, _Entry] = {}

    # ---------------------------------------------------------- load
    def load(self, name: str, model=None, *, path: Optional[str] = None,
             version: Optional[int] = None, quantize: bool = False,
             calibration=None, accuracy_gate=None,
             activate: bool = True, input_spec=None) -> Servable:
        """Register a model version under ``name``.

        Exactly one of ``model`` (a Module) or ``path`` (a
        ``save_module`` directory) must be given; ``quantize=True``
        rewrites it through the int8 path first. The new version
        becomes current when ``activate`` (the default) — an atomic
        hot-swap if the name already serves traffic. With
        ``activate=False`` the version is STAGED only, even for a
        fresh name (that is what lets a caller warm it up before any
        traffic can resolve it): ``swap`` makes it current.

        ``calibration`` (an iterable of activation batches, quantize
        loads only) runs the FLOAT model once over the batches and
        bakes per-layer static activation scales into the int8 twin
        (``precision/calibrate.py`` — one scale-estimation path).
        ``accuracy_gate`` (a ``precision.AccuracyGate``) evaluates the
        quantized candidate against the float reference BEFORE
        registration: a delta above the gate bound raises
        ``AccuracyGateError`` and stages nothing — the previous
        version keeps serving, exactly like a failed swap.

        ``input_spec`` (``analysis.spec`` / shape tuple / list of them)
        opts into a pre-flight shape check: the servable-to-be is walked
        under ``jax.eval_shape`` and a mis-wired model is rejected with a
        layer-path diagnostic BEFORE it can be registered — nothing is
        staged, no traffic can resolve it, and no compile is spent on it.
        """
        if (model is None) == (path is None):
            raise ValueError("pass exactly one of model= or path=")
        if (calibration is not None or accuracy_gate is not None) \
                and not quantize:
            raise ValueError(
                "calibration=/accuracy_gate= only apply to quantize=True "
                "loads (they calibrate and certify the int8 rewrite)")
        user_live_module = path is None
        if path is not None:
            from bigdl_tpu.utils.serialization import load_module
            model = load_module(path)
            model.evaluate()  # fresh instance: the registry owns it
        model.ensure_initialized()
        if quantize:
            from bigdl_tpu.nn.quantized import quantize as _quantize
            from bigdl_tpu.precision.calibrate import maybe_collect
            float_reference = model
            scales = maybe_collect(model, calibration)
            # a rewrite, original untouched
            model = _quantize(model, act_scales=scales)
            model.evaluate()
            user_live_module = False
            if accuracy_gate is not None:
                # raises AccuracyGateError above the bound — before any
                # registration, so no traffic can ever resolve a
                # candidate that failed its accuracy budget; the delta
                # lands in serving/precision/accuracy_delta either way
                accuracy_gate.check(float_reference, model, label=name)
        if input_spec is not None:
            # checks the model that will actually SERVE (post-quantize
            # rewrite), in inference mode; raises ShapeCheckError.
            # Module.check temporarily intercepts every submodule's
            # `apply`, so a USER-PASSED live module (which may be
            # training eagerly in another thread — see the comment
            # below) is checked through a detached topology clone when
            # the class supports the spec roundtrip; registry-private
            # instances (path loads, quantize rewrites) check directly.
            target = model
            if user_live_module:
                try:
                    from bigdl_tpu.utils.module_serializer import (
                        from_spec, to_spec)
                    target = from_spec(to_spec(model))
                except Exception:
                    pass  # unregistered custom class: check in place
            target.check(input_spec, training=False)
        # a user-passed live module is NOT flipped to eval mode (it may
        # still be training eagerly elsewhere) — the serving step runs
        # apply(training=False) regardless, so serving stays inert
        servable = None
        with self._lock:
            entry = self._models.setdefault(name, _Entry())
            if version is None:
                version = max(entry.versions, default=0) + 1
            if version in entry.versions:
                raise ValueError(f"{name} v{version} already loaded "
                                 "(unload it first or pick a new version)")
            servable = Servable(name, version, model,
                                model.get_parameters(), model.get_state())
            entry.versions[version] = servable
            if activate:
                entry.current = servable
        return servable

    # ------------------------------------------------------ resolve
    def current(self, name: str) -> Servable:
        """The servable behind ``name`` right now (one atomic read —
        callers hold the returned snapshot for a whole batch)."""
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(f"no model loaded under {name!r}")
            if entry.current is None:
                raise KeyError(
                    f"no ACTIVE version under {name!r} (versions "
                    f"{sorted(entry.versions)} are staged; swap one in)")
            return entry.current

    def swap(self, name: str, version: int) -> Servable:
        """Atomically repoint ``name`` at an already-loaded version."""
        # hot-swap failure site: a chaos schedule raising here must
        # leave the OLD version serving (the repoint below is the only
        # mutation, so an injected failure is atomic by construction)
        faults.point("serving/swap", name=name, version=version)
        with self._lock:
            entry = self._models.get(name)
            if entry is None or version not in entry.versions:
                raise KeyError(f"{name!r} has no loaded v{version}")
            entry.current = entry.versions[version]
            return entry.current

    def unload(self, name: str, version: Optional[int] = None) -> List:
        """Drop one version (or the whole name). Refuses to drop the
        version currently serving unless the whole name goes — swap
        first. Returns the dropped servables' compile-cache keys."""
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(f"no model loaded under {name!r}")
            if version is None:
                dropped = list(entry.versions.values())
                del self._models[name]
            else:
                if version not in entry.versions:
                    raise KeyError(f"{name!r} has no loaded v{version}")
                if entry.current is not None and \
                        entry.current.version == version:
                    raise ValueError(
                        f"{name} v{version} is the current servable; "
                        "swap to another version before unloading it")
                dropped = [entry.versions.pop(version)]
            return [s.key for s in dropped]

    # ------------------------------------------------------- introspect
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def versions(self, name: str) -> List[int]:
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(f"no model loaded under {name!r}")
            return sorted(entry.versions)

    def describe(self, name: str) -> Dict:
        """Stable-name status: current version + all loaded versions."""
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(f"no model loaded under {name!r}")
            return {
                "name": name,
                "current_version": (entry.current.version
                                    if entry.current else None),
                "versions": sorted(entry.versions),
                "model_types": {v: type(s.model).__name__
                                for v, s in entry.versions.items()},
            }
