"""Dynamic micro-batcher: the request→batch coalescing core of serving.

Callers submit single requests (or small row-batches) and get a Future;
a dispatch thread coalesces queued requests up to ``max_batch_size``
rows or until the oldest request has waited ``max_wait_ms``, right-pads
the coalesced rows to the nearest ``BucketLadder`` rung (the
``optim.predictor.pad_rows`` idiom — repeat the last real row), runs ONE
forward via the injected ``run_batch`` callable, and scatters per-request
row slices back to the futures. A full batch dispatches immediately —
``max_wait_ms`` is the latency bound for underfilled batches, not a tax
on busy traffic.

Admission control (the production-serving table stakes the offline
Predictor never needed):

- bounded queue depth — ``submit`` raises :class:`QueueFull` at once
  instead of buffering unboundedly;
- per-request deadlines — a request that waits past its budget fails
  with :class:`DeadlineExceeded` (and the batch window never waits
  beyond the earliest queued deadline);
- graceful drain — ``shutdown(drain=True)`` stops admission, flushes
  everything queued, then joins the dispatch thread.

The batcher is model-agnostic (``run_batch`` is any padded-rows →
padded-rows callable), which is also what lets tests drive it with a
slow pure-python runner to exercise the rejection/timeout paths.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.serving.compile_cache import BucketLadder


class QueueFull(RuntimeError):
    """Admission control: the request queue is at max_queue depth."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before a batch could serve it."""


class WorkerDied(RuntimeError):
    """The batcher's dispatch thread died outside the per-batch error
    handling (a bug or injected fault in the batching machinery
    itself, not the model). Every pending future fails with this —
    typed, promptly — instead of hanging forever, and the supervisor
    restarts the loop so the batcher keeps serving."""


class _Request:
    __slots__ = ("x", "n_rows", "future", "deadline", "t_enqueue",
                 "trace_id")

    def __init__(self, x: np.ndarray, deadline: Optional[float],
                 trace_id: str = ""):
        self.x = x
        self.n_rows = x.shape[0]
        self.future: Future = Future()
        self.deadline = deadline
        self.t_enqueue = time.monotonic()
        #: per-request trace id, assigned at submit and carried through
        #: queue -> batch -> response (``future.trace_id``); when span
        #: tracing is on, the Chrome-trace export renders this
        #: request's queue wait and the batch it rode on its own track
        self.trace_id = trace_id


class BatcherStats:
    """Batcher counters, routed through a telemetry
    :class:`~bigdl_tpu.telemetry.MetricsRegistry` (series are labelled
    ``model=<name>``, so one service's batchers share instruments and
    every exporter sees them).

    The pre-telemetry attribute surface (``requests``, ``timed_out``,
    ``latencies_ms``, ... and the public ``lock``) is preserved as
    read-only views — ``InferenceService.metrics()`` and existing
    callers read the exact same shapes as before."""

    def __init__(self, reservoir: int = 2048, registry=None,
                 model: str = "model"):
        self.lock = threading.Lock()
        r = registry if registry is not None \
            else telemetry.MetricsRegistry()
        self.registry = r
        self._labels = {"model": model}
        self._c_requests = r.counter(
            "serving/batcher/requests", "requests admitted")
        self._c_rows = r.counter(
            "serving/batcher/rows", "request rows admitted")
        self._c_rejected = r.counter(
            "serving/batcher/rejected",
            "requests rejected at admission (QueueFull)")
        self._c_timed_out = r.counter(
            "serving/batcher/timed_out",
            "requests failed past their deadline (deadline misses)")
        self._c_errors = r.counter(
            "serving/batcher/errors", "requests failed by a batch error")
        self._c_failed_batches = r.counter(
            "serving/batcher/failed_batches",
            "batches whose dispatch raised (one per failed dispatch)")
        self._c_worker_restarts = r.counter(
            "serving/batcher/worker_restarts",
            "dispatch-thread deaths survived by supervision")
        self._c_worker_failed = r.counter(
            "serving/batcher/worker_failed",
            "requests failed with WorkerDied by a thread death")
        self._c_batches = r.counter(
            "serving/batcher/batches", "batches dispatched")
        self._c_batched_rows = r.counter(
            "serving/batcher/batched_rows",
            "real rows dispatched in batches")
        self._c_padded_rows = r.counter(
            "serving/batcher/padded_rows",
            "pad rows added to reach bucket rungs")
        self._c_fill_sum = r.counter(
            "serving/batcher/fill_sum", "sum of per-batch fill ratios")
        self._h_latency = r.histogram(
            "serving/batcher/latency_ms",
            "request latency enqueue -> result (ms)",
            reservoir_size=reservoir)
        self._h_queue_wait = r.histogram(
            "serving/batcher/queue_wait_ms",
            "request wait enqueue -> batch dispatch (ms)",
            reservoir_size=reservoir)
        self._h_batch_rows = r.histogram(
            "serving/batcher/batch_rows",
            "real rows per dispatched batch", reservoir_size=reservoir)
        self._g_depth = r.gauge(
            "serving/batcher/queue_depth", "requests waiting in queue")

    # -- writers (called by MicroBatcher only) ---------------------------
    def on_reject(self) -> None:
        """Count one QueueFull admission rejection."""
        with self.lock:
            self._c_rejected.inc(**self._labels)

    def on_submit(self, rows: int) -> None:
        """Count one admitted request of ``rows`` rows."""
        with self.lock:
            self._c_requests.inc(**self._labels)
            self._c_rows.inc(rows, **self._labels)

    def on_timeout(self) -> None:
        """Count one deadline miss."""
        with self.lock:
            self._c_timed_out.inc(**self._labels)

    def on_error(self, n_requests: int) -> None:
        """Count ``n_requests`` failed by one batch error."""
        with self.lock:
            self._c_errors.inc(n_requests, **self._labels)
            self._c_failed_batches.inc(**self._labels)

    def on_worker_death(self, n_requests: int) -> None:
        """Count one dispatch-thread death that failed ``n_requests``
        pending requests with WorkerDied."""
        with self.lock:
            self._c_worker_restarts.inc(**self._labels)
            self._c_worker_failed.inc(n_requests, **self._labels)

    def on_batch(self, rows: int, bucket: int) -> None:
        """Count one dispatched batch of ``rows`` real rows padded to
        ``bucket``."""
        with self.lock:
            self._c_batches.inc(**self._labels)
            self._c_batched_rows.inc(rows, **self._labels)
            self._c_padded_rows.inc(bucket - rows, **self._labels)
            self._c_fill_sum.inc(rows / bucket, **self._labels)
            self._h_batch_rows.observe(rows, **self._labels)

    def on_latency(self, ms: float) -> None:
        """Record one request's enqueue->result latency."""
        self._h_latency.observe(ms, **self._labels)

    def on_queue_wait(self, ms: float) -> None:
        """Record one request's enqueue->dispatch wait."""
        self._h_queue_wait.observe(ms, **self._labels)

    def on_depth(self, depth: int) -> None:
        """Publish the current queue depth."""
        self._g_depth.set(depth, **self._labels)

    # -- consistent multi-counter reads ----------------------------------
    def snapshot(self) -> Dict[str, object]:
        """One consistent view of the whole counter family, read under
        ``self.lock``. The bare properties below are each internally
        consistent (their instrument lock suffices) but can tear ACROSS
        counters — a writer like :meth:`on_batch` may land between two
        property reads, so derived ratios (``fill_sum / batches``,
        padded-row ratio) must come from here."""
        with self.lock:
            return {
                "requests": self.requests, "rows": self.rows,
                "rejected": self.rejected, "timed_out": self.timed_out,
                "errors": self.errors,
                "failed_batches": self.failed_batches,
                "worker_restarts": self.worker_restarts,
                "worker_failed": self.worker_failed,
                "batches": self.batches,
                "batched_rows": self.batched_rows,
                "padded_rows": self.padded_rows,
                "fill_sum": self.fill_sum,
                "latencies_ms": list(self.latencies_ms),
            }

    # -- legacy read surface ---------------------------------------------
    def _count(self, c) -> int:
        return int(c.value(**self._labels))

    @property
    def requests(self) -> int:
        """Requests admitted."""
        return self._count(self._c_requests)

    @property
    def rows(self) -> int:
        """Request rows admitted."""
        return self._count(self._c_rows)

    @property
    def rejected(self) -> int:
        """Requests rejected at admission."""
        return self._count(self._c_rejected)

    @property
    def timed_out(self) -> int:
        """Requests failed past their deadline."""
        return self._count(self._c_timed_out)

    @property
    def errors(self) -> int:
        """Requests failed by a batch error."""
        return self._count(self._c_errors)

    @property
    def failed_batches(self) -> int:
        """Batches whose dispatch raised."""
        return self._count(self._c_failed_batches)

    @property
    def worker_restarts(self) -> int:
        """Dispatch-thread deaths survived by supervision."""
        return self._count(self._c_worker_restarts)

    @property
    def worker_failed(self) -> int:
        """Requests failed with WorkerDied."""
        return self._count(self._c_worker_failed)

    @property
    def batches(self) -> int:
        """Batches dispatched."""
        return self._count(self._c_batches)

    @property
    def batched_rows(self) -> int:
        """Real rows dispatched."""
        return self._count(self._c_batched_rows)

    @property
    def padded_rows(self) -> int:
        """Pad rows added."""
        return self._count(self._c_padded_rows)

    @property
    def fill_sum(self) -> float:
        """Sum of per-batch fill ratios."""
        return self._c_fill_sum.value(**self._labels)

    @property
    def latencies_ms(self) -> List[float]:
        """The bounded latency reservoir (ms, oldest first)."""
        return self._h_latency.samples(**self._labels)


class MicroBatcher:
    """Queue + dispatch thread coalescing requests into bucket-padded
    batches for one ``run_batch`` callable (module docstring has the
    batching window and admission-control rules)."""

    def __init__(self, run_batch: Callable[[np.ndarray], np.ndarray],
                 ladder: BucketLadder, *, max_wait_ms: float = 2.0,
                 max_queue: int = 256, name: str = "model",
                 metrics=None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._run_batch = run_batch
        self._ladder = ladder
        self._max_wait = max_wait_ms / 1000.0
        self._max_queue = max_queue
        self._name = name
        # ``metrics``: the telemetry MetricsRegistry to report through
        # (an InferenceService passes its own so concurrent services
        # don't mix counts); default is a private registry
        self.stats = BatcherStats(registry=metrics, model=name)
        #: (feature_shape, dtype) CONFIRMED by the first successful
        #: dispatch; requests coalesce into ONE ndarray, so a mismatch
        #: must be rejected at admission (its whole batch would fail
        #: on concatenate, or silently upcast and double-compile).
        #: Until confirmed, submits are checked against what's queued —
        #: a malformed lone first request fails its own forward without
        #: permanently bricking the name.
        self._sig = None
        self._seq = itertools.count(1)  # trace_id suffixes
        self._queue: Deque[_Request] = deque()
        self._cond = threading.Condition()
        self._stopping = False
        #: requests popped from the queue but not yet resolved by
        #: _dispatch — the supervisor fails THESE too on a worker
        #: death (a crash between take and dispatch must not strand
        #: popped futures). Worker-thread-only state.
        self._inflight: List[_Request] = []
        self._thread = threading.Thread(
            target=self._supervised, name=f"serving-batcher-{name}",
            daemon=True)
        self._thread.start()

    @property
    def max_batch_size(self) -> int:
        return self._ladder.max_batch_size

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # -------------------------------------------------------- submit
    def submit(self, x: np.ndarray,
               timeout_ms: Optional[float] = None) -> Future:
        """Enqueue a (rows, features...) request; returns its Future.

        Raises :class:`QueueFull` immediately when the queue is at
        depth (explicit rejection beats unbounded buffering), and
        ValueError for requests wider than one batch (split upstream)
        or whose feature shape/dtype differs from the batcher's
        established signature (one malformed request must never fail
        the well-formed requests it would have been batched with).
        """
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"request needs >= 1 rows, got shape {x.shape}")
        if x.shape[0] > self.max_batch_size:
            raise ValueError(
                f"request of {x.shape[0]} rows exceeds max_batch_size="
                f"{self.max_batch_size}; split it upstream")
        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms is not None else None)
        req = _Request(x, deadline,
                       f"{self._name}/req-{next(self._seq)}")
        req.future.trace_id = req.trace_id  # response carries the id
        sig = (x.shape[1:], x.dtype)
        with self._cond:
            if self._stopping:
                raise RuntimeError(f"batcher {self._name!r} is shut down")
            ref = self._sig or (
                (self._queue[-1].x.shape[1:], self._queue[-1].x.dtype)
                if self._queue else None)
            if ref is not None and sig != ref:
                raise ValueError(
                    f"{self._name}: request feature shape/dtype "
                    f"{sig[0]}/{sig[1]} does not match this model's "
                    f"established {ref[0]}/{ref[1]} — one "
                    "micro-batched service serves one input signature")
            if len(self._queue) >= self._max_queue:
                self.stats.on_reject()
                raise QueueFull(
                    f"{self._name}: queue at max depth {self._max_queue}")
            self._queue.append(req)
            self.stats.on_submit(req.n_rows)
            self.stats.on_depth(len(self._queue))
            self._cond.notify_all()
        return req.future

    # ------------------------------------------------------ dispatch
    def _queued_rows_locked(self) -> int:
        rows, cap = 0, self.max_batch_size
        for r in self._queue:
            if rows + r.n_rows > cap:
                break
            rows += r.n_rows
        return rows

    def _window_end_locked(self, now: float) -> float:
        """The moment this batch must dispatch: the head request's
        max_wait budget, tightened by the earliest queued deadline."""
        end = self._queue[0].t_enqueue + self._max_wait
        for r in self._queue:
            if r.deadline is not None:
                end = min(end, r.deadline)
        return end

    def _take_batch_locked(self, window_open: float):
        """Pop expired requests (failing their futures) and then up to
        max_batch_size rows of live ones.

        "Expired" means the deadline passed BEFORE this batching round
        opened — i.e. the batcher was busy elsewhere while the budget
        ran out. A deadline the window itself closed on is SERVED: the
        window end is tightened to the earliest queued deadline exactly
        so that request dispatches as its budget expires, rather than
        being failed by the wakeup meant to serve it (a request with
        timeout_ms <= max_wait_ms must still work on an idle server).
        """
        batch = self._inflight  # crash-visible to the supervisor
        rows, cap = 0, self.max_batch_size
        while self._queue:
            r = self._queue[0]
            if r.deadline is not None and r.deadline < window_open:
                self._queue.popleft()
                self.stats.on_timeout()
                r.future.set_exception(DeadlineExceeded(
                    f"{self._name}: request waited past its deadline"))
                continue
            if rows + r.n_rows > cap:
                break
            self._queue.popleft()
            batch.append(r)
            rows += r.n_rows
        # the batching-machinery death site (requests are popped but
        # not yet dispatched — exactly where an unsupervised loop
        # would strand futures forever)
        faults.point("serving/take_batch", model=self._name, rows=rows)
        return batch, rows

    def _supervised(self) -> None:
        """Run ``_loop``, surviving its death: a crash OUTSIDE
        ``_dispatch``'s per-batch error handling (the batching
        machinery itself) fails every pending future — queued AND
        popped-but-undispatched — with a typed :class:`WorkerDied`
        instead of leaving them pending forever, then restarts the
        loop so the batcher keeps serving."""
        while True:
            try:
                self._loop()
                return  # clean shutdown
            except BaseException as e:  # noqa: BLE001 — supervision
                with self._cond:
                    died = list(self._inflight) + list(self._queue)
                    self._inflight = []
                    self._queue.clear()
                    restart = not self._stopping
                    self.stats.on_worker_death(len(died))
                    self.stats.on_depth(0)
                    self._cond.notify_all()
                # post-mortem bundle BEFORE failing futures: the
                # flight recorder's whole reason to exist is this path
                from bigdl_tpu.telemetry import flight
                flight.on_fatal("serving/dispatch", e,
                                metrics=self.stats.registry)
                err = WorkerDied(
                    f"batcher {self._name!r} dispatch worker died: "
                    f"{type(e).__name__}: {e}")
                err.__cause__ = e
                for r in died:
                    # in-flight requests may already be resolved (a
                    # crash in post-dispatch bookkeeping) or racing a
                    # caller's cancel — failing THOSE would raise
                    # InvalidStateError and kill the supervisor itself
                    try:
                        if not r.future.done():
                            r.future.set_exception(err)
                    except Exception:
                        pass  # resolved/cancelled in the race window
                if not restart:
                    return

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if not self._queue and self._stopping:
                    return
                # hold the window open for stragglers until the batch
                # fills, the head request's wait budget ends, or drain
                window_open = time.monotonic()
                while not self._stopping:
                    now = time.monotonic()
                    if self._queued_rows_locked() >= self.max_batch_size:
                        break
                    remaining = self._window_end_locked(now) - now
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch, rows = self._take_batch_locked(window_open)
                self.stats.on_depth(len(self._queue))
            if batch:
                self._dispatch(batch, rows)
            with self._cond:
                # cleared under the lock: the supervisor's crash-path
                # rebind of _inflight must never race this one
                self._inflight = []

    def _request_tracks(self, batch: List[_Request], t_dispatch: float,
                        t_done: float, rows: int, bucket: int) -> None:
        """Per-request trace spans on each request's virtual track:
        its queue wait and the batch it rode (flow-linked back to this
        dispatch thread's ``serving/batch`` span)."""
        tr = telemetry.tracer()
        for r in batch:
            tid = tr.track(f"req {r.trace_id}")
            args = {"trace_id": r.trace_id, "model": self._name}
            tr.record_span("serving/request/queue_wait", r.t_enqueue,
                           t_dispatch - r.t_enqueue, tid=tid, args=args)
            tr.record_span("serving/request/batch", t_dispatch,
                           t_done - t_dispatch, tid=tid,
                           args=dict(args, rows=rows, bucket=bucket),
                           flow=r.trace_id)

    def _dispatch(self, batch: List[_Request], rows: int) -> None:
        bucket = self._ladder.bucket_for(rows)
        from bigdl_tpu.optim.predictor import pad_rows
        t_dispatch = time.monotonic()
        for r in batch:
            self.stats.on_queue_wait((t_dispatch - r.t_enqueue) * 1000.0)
        x = np.concatenate([r.x for r in batch], axis=0) \
            if len(batch) > 1 else batch[0].x
        try:
            with telemetry.span("serving/batch", model=self._name,
                                rows=rows, bucket=bucket):
                out = np.asarray(self._run_batch(pad_rows(x, bucket)))
            if out.shape[:1] != (bucket,):
                # a row-reducing model would otherwise scatter empty/
                # truncated slices into futures that "succeed"
                raise ValueError(
                    f"{self._name}: run_batch returned shape {out.shape} "
                    f"for a {bucket}-row padded batch; serving requires "
                    "one output row per input row")
        except Exception as e:  # noqa: BLE001 — failures go to futures
            self.stats.on_error(len(batch))
            for r in batch:
                if not r.future.cancelled():
                    r.future.set_exception(e)
            return
        with self._cond:
            if self._sig is None:
                # confirmed by a successful forward: from here on the
                # name serves exactly this signature
                self._sig = (x.shape[1:], x.dtype)
        t_done = time.monotonic()
        if telemetry.enabled():
            self._request_tracks(batch, t_dispatch, t_done, rows, bucket)
        self.stats.on_batch(rows, bucket)
        for r in batch:
            self.stats.on_latency((t_done - r.t_enqueue) * 1000.0)
        off = 0
        for r in batch:
            if not r.future.cancelled():
                # pad rows live PAST every request slice: they can
                # never leak into a scattered result
                r.future.set_result(out[off:off + r.n_rows])
            off += r.n_rows

    # ------------------------------------------------------ shutdown
    def shutdown(self, drain: bool = True) -> None:
        """Stop admission; with ``drain`` serve everything queued, else
        fail queued requests; then join the dispatch thread."""
        with self._cond:
            if self._stopping:
                self._cond.notify_all()
            self._stopping = True
            if not drain:
                while self._queue:
                    r = self._queue.popleft()
                    r.future.set_exception(
                        RuntimeError(f"batcher {self._name!r} shut down"))
            self._cond.notify_all()
        self._thread.join()
