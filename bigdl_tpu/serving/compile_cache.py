"""Shape-bucketed compile cache for online inference.

The XLA-centric lesson (TensorFlow paper §4.4, and BigDL's own fixed
``batch_size`` padding in ``optim/predictor.py``): every distinct input
shape is a fresh compilation. Offline sweeps dodge this with ONE padded
batch size; an online service sees ragged request sizes, so it pads each
micro-batch up to the nearest rung of a small **bucket ladder** — with K
buckets, at most K programs ever compile per (model, dtype), no matter
how many request sizes arrive.

``CompileCache`` holds one jitted eval step per servable (built by
``optim.predictor.make_eval_step`` — the same jitted forward the offline
Predictor runs) and counts compilations via the step's trace hook, so
tests can assert the bound instead of trusting it. ``warmup`` eagerly
compiles every rung so the first real request never eats a compile.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import bigdl_tpu.telemetry as telemetry


class BucketLadder:
    """Sorted batch-size rungs; requests pad up to the nearest rung.

    Default ladder is powers of two up to ``max_batch_size`` (with
    ``max_batch_size`` itself as the top rung), e.g. 32 -> [1, 2, 4, 8,
    16, 32]; pass ``buckets`` for a custom ladder (deduped, sorted; its
    max becomes the effective max batch size).
    """

    def __init__(self, max_batch_size: int,
                 buckets: Optional[Sequence[int]] = None):
        if buckets is not None:
            rungs = sorted(set(int(b) for b in buckets))
            if not rungs or rungs[0] < 1:
                raise ValueError(f"buckets must be positive ints, got "
                                 f"{list(buckets)}")
        else:
            if max_batch_size < 1:
                raise ValueError(
                    f"max_batch_size must be >= 1, got {max_batch_size}")
            rungs, b = [], 1
            while b < max_batch_size:
                rungs.append(b)
                b *= 2
            rungs.append(max_batch_size)
        self._rungs: List[int] = rungs

    @property
    def max_batch_size(self) -> int:
        return self._rungs[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n (the padded size a batch of n rows runs
        at)."""
        if n < 1:
            raise ValueError(f"batch of {n} rows")
        for b in self._rungs:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} rows exceeds the ladder's max "
            f"{self.max_batch_size}")

    def __iter__(self) -> Iterator[int]:
        return iter(self._rungs)

    def __len__(self) -> int:
        return len(self._rungs)

    def __repr__(self) -> str:
        return f"BucketLadder({self._rungs})"


class CompileCache:
    """Per-servable jitted eval steps + a compile counter.

    Keys are opaque hashables — the registry uses ``(name, version)`` —
    so two versions of a model never share programs and ``drop`` at
    unload releases them. Within one key, jax.jit's own aval cache
    provides the per-(bucket, dtype) specialization; the counter
    increments exactly once per trace (= per compiled program), which is
    the quantity the acceptance tests bound.
    """

    def __init__(self, metrics=None):
        self._lock = threading.Lock()
        self._steps: Dict = {}
        self._compiles: Dict[Tuple, int] = {}
        # telemetry registry to report hit/miss/compile-duration
        # through (an InferenceService passes its own); the cache works
        # identically without one
        r = metrics if metrics is not None else telemetry.MetricsRegistry()
        self._m_hits = r.counter(
            "serving/compile_cache/hits",
            "step executions served by an already-compiled program")
        self._m_misses = r.counter(
            "serving/compile_cache/misses",
            "step executions that paid an XLA compile")
        self._m_compile_s = r.histogram(
            "serving/compile_cache/compile_s",
            "seconds per compiling execution (trace+compile+first run)")

    @staticmethod
    def _model_label(key) -> str:
        # registry keys are (name, version); fall back to str(key)
        if isinstance(key, tuple) and key and isinstance(key[0], str):
            return key[0]
        return str(key)

    @staticmethod
    def _program_name(key) -> str:
        # registry keys are (name, version[, kind, bucket]) tuples:
        # render as a slash path ("lenet/1/prefill/64") — the program
        # label every */program/* gauge series carries
        if isinstance(key, tuple):
            return "/".join(str(p) for p in key)
        return str(key)

    def program_for(self, key, build, profile_items=None):
        """The (cached) self-counting program for ``key``; built on
        first use by ``build(on_trace) -> jitted callable``, where
        ``on_trace`` must be invoked from inside the traced function
        body — i.e. exactly once per XLA compilation. The returned
        callable times itself: an execution that triggered a trace
        counts as a cache miss (its wall-clock lands in the
        ``serving/compile_cache/compile_s`` histogram), every other
        execution as a hit.

        ``step_for`` (the eval forward every servable gets) and the
        generation engine's per-bucket prefill/decode program pairs
        (:mod:`bigdl_tpu.generation`) both build through here, so ONE
        counter bounds every kind of program a servable compiles.

        With program profiling on (``telemetry.programs.enable()``),
        each compiled program additionally registers its cost/memory
        profile under ``serving/program/*``; ``profile_items(args,
        kwargs)`` counts the rows/tokens one call processes so measured
        rates become MFU gauges."""
        with self._lock:
            prog = self._steps.get(key)
            if prog is not None:
                return prog

        label = self._model_label(key)
        # compiles already charged to the miss series; the delta against
        # _compiles attributes each trace to exactly ONE executing call
        # (two requests racing the first compile must not both count a
        # miss — the series would contradict compile_count)
        counted = [0]

        def on_trace(key=key):
            with self._lock:
                self._compiles[key] = self._compiles.get(key, 0) + 1

        jitted = build(on_trace)
        from bigdl_tpu.telemetry import programs as _programs
        jitted = _programs.maybe_wrap_jitted(
            self._program_name(key), "serving", jitted,
            items_for=profile_items,
            auto_rate=profile_items is not None)

        def prog(*args, **kwargs):
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            dt = time.perf_counter() - t0
            with self._lock:
                total = self._compiles.get(key, 0)
                fresh = total - counted[0]
                counted[0] = total
            if fresh > 0:  # this call (or one it raced) compiled
                self._m_misses.inc(fresh, model=label)
                self._m_compile_s.observe(dt, model=label)
            else:
                self._m_hits.inc(model=label)
            return out

        with self._lock:
            # two racing builders: keep the first registered program so
            # the trace counter stays tied to the cached callable
            cached = self._steps.setdefault(key, prog)
        return cached

    def step_for(self, key, model):
        """The (cached) jitted eval step for ``key`` — ``program_for``
        over ``optim.predictor.make_eval_step`` (hit/miss timing and
        the per-key compile counter included)."""
        from bigdl_tpu.optim.predictor import make_eval_step

        return self.program_for(
            key, lambda on_trace: make_eval_step(model, on_trace=on_trace),
            # (params, state, x): the padded batch's rows are the items
            profile_items=lambda args, kwargs: args[2].shape[0])

    @staticmethod
    def abstract_step(model):
        """Program-enumeration hook for the static verifier: the raw
        jitted eval step :meth:`step_for` would compile for ``model``
        — built outside the cache (no counters, nothing cached or
        executed), ready for ``.lower(params, state, x)`` over
        ``jax.ShapeDtypeStruct`` trees."""
        from bigdl_tpu.optim.predictor import make_eval_step

        return make_eval_step(model)

    def compile_count(self, key=None) -> int:
        """Compilations so far — for ``key``, or in total when None."""
        with self._lock:
            if key is not None:
                return self._compiles.get(key, 0)
            return sum(self._compiles.values())

    def drop(self, key) -> None:
        """Release the compiled programs of an unloaded servable."""
        with self._lock:
            self._steps.pop(key, None)
            self._compiles.pop(key, None)

    def warmup(self, key, model, params, state,
               feature_shape: Sequence[int], ladder: BucketLadder,
               dtype=np.float32) -> int:
        """Eagerly compile every ladder rung for ``key`` (zeros input of
        shape ``(bucket,) + feature_shape``) so no real request ever
        pays a compile. Returns the number of programs compiled by this
        call (rungs already cached cost nothing)."""
        import jax

        step = self.step_for(key, model)
        before = self.compile_count(key)
        for b in ladder:
            x = np.zeros((b,) + tuple(feature_shape), dtype)
            # deliberately synchronous: warmup exists to GATE on the
            # compile of every ladder bucket before serving starts
            jax.block_until_ready(step(params, state, x))  # bigdl: disable=sync-in-loop
        return self.compile_count(key) - before
