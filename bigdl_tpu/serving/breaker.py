"""Circuit breaker: shed load fast when the backend is failing.

When a model's dispatch path fails repeatedly (a bad weight push, a
wedged device, a dependency outage), continuing to queue requests just
converts every caller's latency budget into a slow failure. The
breaker turns ``K`` *consecutive* dispatch failures into fast
rejection (:class:`Degraded` raised at submit time — the caller learns
in microseconds, queue depth stays available for models that work),
then **half-opens** after a cooldown: one probe request is admitted,
and its outcome closes the circuit (success) or re-opens it for
another cooldown (failure). The classic states:

- ``closed``  — normal service; failures count, any success resets.
- ``open``    — shedding; every ``allow()`` is False until the
  cooldown elapses.
- ``half-open`` — exactly one probe in flight; its outcome decides.

``InferenceService`` wires one breaker per model name around the
batcher's ``run_batch`` (see docs/robustness.md); shed requests count
into the ``serving/service/shed`` telemetry series.
"""
from __future__ import annotations

import threading
import time
from typing import Callable


class Degraded(RuntimeError):
    """Fast-reject: the model's circuit breaker is open after repeated
    consecutive dispatch failures; retry after its cooldown."""


class CircuitBreaker:
    """Consecutive-failure circuit breaker (module docstring has the
    state machine). ``failures <= 0`` disables the breaker — every
    ``allow()`` is True and outcomes are ignored. Thread-safe: submit
    paths call :meth:`allow`, the dispatch thread reports
    :meth:`on_success`/:meth:`on_failure`."""

    def __init__(self, failures: int = 8, cooldown_ms: float = 1000.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failures = int(failures)
        self.cooldown_s = float(cooldown_ms) / 1000.0
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_at = 0.0

    @property
    def state(self) -> str:
        """``"closed"``, ``"open"`` or ``"half-open"`` (reading an
        elapsed cooldown does not itself transition — the next
        ``allow()`` does)."""
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """Whether one request may proceed now. In ``open``, flips to
        ``half-open`` once the cooldown has elapsed and admits exactly
        ONE probe; further requests shed until the probe resolves — or
        until a cooldown passes with no outcome (a probe can die
        before reaching dispatch: queue-full rejection, deadline
        expiry, a worker death clearing the queue), in which case a
        fresh probe is admitted rather than shedding forever."""
        if self.failures <= 0:
            return True
        with self._lock:
            now = self._clock()
            if self._state == "closed":
                return True
            if self._state == "open":
                if now - self._opened_at < self.cooldown_s:
                    return False
                self._state = "half-open"
                return self._claim_probe(now)
            # half-open: one probe at a time, re-armed if the probe
            # vanished without reporting an outcome
            return self._claim_probe(now)

    def _claim_probe(self, now: float) -> bool:
        """Single-flight claim of THE half-open probe slot (caller
        holds the lock). A held slot only counts as vanished once
        STRICTLY more than a cooldown passes with no outcome — ``<=``
        matters: with a zero (or coarse) cooldown, two submits racing
        the same clock reading would otherwise both claim and
        half-open would admit two concurrent probes."""
        if self._probing and now - self._probe_at <= self.cooldown_s:
            return False
        self._probing = True
        self._probe_at = now
        return True

    def on_success(self) -> None:
        """A dispatch succeeded: reset to ``closed``."""
        if self.failures <= 0:
            return
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._probing = False

    def on_failure(self) -> None:
        """A dispatch failed: count it; ``K`` consecutive failures (or
        a failed half-open probe) open the circuit for a cooldown."""
        if self.failures <= 0:
            return
        with self._lock:
            self._consecutive += 1
            if self._state == "half-open" \
                    or self._consecutive >= self.failures:
                self._state = "open"
                self._opened_at = self._clock()
                self._probing = False
